// CSR row-block container.
//
// Counterpart of reference include/dmlc/data.h:174-236 (RowBlock CSR batch)
// and src/data/row_block.h (owning growable container with Save/Load).
// Layout decisions for the TPU bridge (see dmlc_core_tpu/tpu/):
//   - offsets are uint64 (row starts into index/value arrays)
//   - labels/weights/values are float32, qid uint64, field uint32
//   - IndexType is uint32 by default (device-friendly; gathers/scatters on
//     TPU want int32) with a uint64 instantiation for >4B-feature corpora.
// The arrays are exactly the buffers handed zero-copy to numpy/JAX via the
// C ABI (capi.cc) — no AoS Row objects on the hot path.
#ifndef DCT_ROWBLOCK_H_
#define DCT_ROWBLOCK_H_

#include <algorithm>
#include <vector>

#include "serializer.h"

namespace dct {

template <typename IndexType>
struct RowBlockContainer {
  // offset[i]..offset[i+1] delimit row i in index/value; offset[0] == 0
  std::vector<uint64_t> offset{0};
  std::vector<float> label;
  std::vector<float> weight;   // empty = uniform weights
  std::vector<uint64_t> qid;   // empty = absent
  std::vector<uint32_t> field; // empty = absent (libfm only)
  std::vector<IndexType> index;
  std::vector<float> value;    // empty = implicit 1.0 (binary features)
  // typed csv values (reference csv_parser.h DType float32/int32/int64):
  // exactly one of value/value_i32/value_i64 is populated per value_dtype
  std::vector<int32_t> value_i32;
  std::vector<int64_t> value_i64;
  int32_t value_dtype = 0;  // 0=float32, 1=int32, 2=int64
  uint64_t max_index = 0;
  uint32_t max_field = 0;

  size_t Size() const { return label.size(); }
  size_t ValueCount() const {
    return value.size() + value_i32.size() + value_i64.size();
  }

  void Clear() {
    offset.assign(1, 0);
    label.clear();
    weight.clear();
    qid.clear();
    field.clear();
    index.clear();
    value.clear();
    value_i32.clear();
    value_i64.clear();
    value_dtype = 0;
    max_index = 0;
    max_field = 0;
  }

  void UpdateMax() {
    for (IndexType v : index) max_index = std::max<uint64_t>(max_index, v);
    for (uint32_t v : field) max_field = std::max(max_field, v);
  }

  size_t MemCostBytes() const {
    return offset.size() * 8 + label.size() * 4 + weight.size() * 4 +
           qid.size() * 8 + field.size() * 4 +
           index.size() * sizeof(IndexType) + value.size() * 4 +
           value_i32.size() * 4 + value_i64.size() * 8;
  }

  // Append all rows of another container (reference row_block.h Push).
  void Append(const RowBlockContainer& other) {
    // dtype reconciliation up front, before any mutation: adopt the other
    // side's dtype only when it actually carries typed values
    DCT_CHECK(value_dtype == other.value_dtype || ValueCount() == 0 ||
              other.ValueCount() == 0)
        << "cannot append row blocks of different value dtypes";
    if (other.value_dtype != 0 && other.ValueCount() != 0) {
      value_dtype = other.value_dtype;
    }
    size_t base = index.size();
    for (size_t i = 1; i < other.offset.size(); ++i) {
      offset.push_back(other.offset[i] + base);
    }
    label.insert(label.end(), other.label.begin(), other.label.end());
    weight.insert(weight.end(), other.weight.begin(), other.weight.end());
    qid.insert(qid.end(), other.qid.begin(), other.qid.end());
    field.insert(field.end(), other.field.begin(), other.field.end());
    index.insert(index.end(), other.index.begin(), other.index.end());
    value.insert(value.end(), other.value.begin(), other.value.end());
    value_i32.insert(value_i32.end(), other.value_i32.begin(),
                     other.value_i32.end());
    value_i64.insert(value_i64.end(), other.value_i64.begin(),
                     other.value_i64.end());
    max_index = std::max(max_index, other.max_index);
    max_field = std::max(max_field, other.max_field);
  }

  // Binary save/load in the shared cross-language wire format
  // (dmlc_core_tpu/serializer.py reads this; reference row_block.h:189-215).
  void Save(Stream* s) const {
    serial::WriteVec(s, offset);
    serial::WriteVec(s, label);
    serial::WriteVec(s, weight);
    serial::WriteVec(s, qid);
    serial::WriteVec(s, field);
    serial::WriteVec(s, index);
    serial::WriteVec(s, value);
    serial::WriteVec(s, value_i32);
    serial::WriteVec(s, value_i64);
    serial::WritePOD<int32_t>(s, value_dtype);
    serial::WritePOD<uint64_t>(s, max_index);
    serial::WritePOD<uint32_t>(s, max_field);
  }

  // Append-deserialize another container's wire image onto this one —
  // Load + Append fused without the intermediate container copy (the rec
  // binary ingest hot path, parser.cc RecParser::ParseBlock). Returns
  // false when the stream is exhausted before the first field.
  bool LoadAppend(Stream* s) {
    // a prior Load() of a corrupt n=0 image can leave offset empty; the
    // rebase below reads offset.back(), so re-establish the invariant
    if (offset.empty()) offset.assign(1, 0);
    uint64_t n;
    if (s->Read(&n, 8) != 8) return false;
    if (!serial::NativeIsLE()) n = serial::ByteSwap(n);
    DCT_CHECK(n <= s->BytesRemaining() / 8)
        << "corrupt row-block image: offset count " << n
        << " exceeds the remaining payload";
    // Offsets: the wire image carries n absolute offsets starting with a 0;
    // appended rows rebase onto the current nnz tail and the leading 0 is
    // dropped. Read all n into the grown tail, then shift-rebase in place
    // (forward shift reads slot i+1 before iteration i+1 overwrites it).
    const uint64_t nnz_base = offset.back();
    if (n != 0) {
      const size_t old = offset.size();
      offset.resize(old + n - 1);
      s->ReadExact(offset.data() + old, (n - 1) * 8);
      uint64_t last;
      s->ReadExact(&last, 8);
      if (!serial::NativeIsLE()) {
        for (size_t i = old; i < offset.size(); ++i) {
          offset[i] = serial::ByteSwap(offset[i]);
        }
        last = serial::ByteSwap(last);
      }
      for (size_t i = old; i + 1 < offset.size(); ++i) {
        offset[i] = offset[i + 1] + nnz_base;
      }
      if (offset.size() > old) {
        offset.back() = last + nnz_base;
      }
    }
    const size_t pre_values = ValueCount();
    serial::ReadVecAppend(s, &label);
    serial::ReadVecAppend(s, &weight);
    serial::ReadVecAppend(s, &qid);
    serial::ReadVecAppend(s, &field);
    serial::ReadVecAppend(s, &index);
    uint64_t added = serial::ReadVecAppend(s, &value);
    added += serial::ReadVecAppend(s, &value_i32);
    added += serial::ReadVecAppend(s, &value_i64);
    const int32_t dt = serial::ReadPOD<int32_t>(s);
    // same dtype reconciliation as Append: adopt the incoming dtype only
    // when this container had no values yet and the image carries some
    DCT_CHECK(value_dtype == dt || pre_values == 0 || added == 0)
        << "cannot append row blocks of different value dtypes";
    if (dt != 0 && added != 0) value_dtype = dt;
    max_index = std::max(max_index, serial::ReadPOD<uint64_t>(s));
    max_field = std::max(max_field, serial::ReadPOD<uint32_t>(s));
    return true;
  }

  bool Load(Stream* s) {
    // probe end-of-stream via the first vector length
    uint64_t n;
    if (s->Read(&n, 8) != 8) return false;
    if (!serial::NativeIsLE()) n = serial::ByteSwap(n);
    DCT_CHECK(n <= s->BytesRemaining() / 8)
        << "corrupt row-block image: offset count " << n
        << " exceeds the remaining payload";
    offset.resize(n);
    if (n != 0) {
      s->ReadExact(offset.data(), n * 8);
      if (!serial::NativeIsLE()) {
        for (auto& v : offset) v = serial::ByteSwap(v);
      }
    }
    serial::ReadVec(s, &label);
    serial::ReadVec(s, &weight);
    serial::ReadVec(s, &qid);
    serial::ReadVec(s, &field);
    serial::ReadVec(s, &index);
    serial::ReadVec(s, &value);
    serial::ReadVec(s, &value_i32);
    serial::ReadVec(s, &value_i64);
    value_dtype = serial::ReadPOD<int32_t>(s);
    max_index = serial::ReadPOD<uint64_t>(s);
    max_field = serial::ReadPOD<uint32_t>(s);
    return true;
  }
};

// Borrowed, layout-free view of one CSR row block — the zero-copy unit the
// shard cache's mmap replay serves (shard_cache.h) and the shape the C ABI
// (dct_rowblock_t) exposes. Pointers reference memory owned by the producer
// (a container's vectors, or an mmap'd cache shard) and stay valid until
// the producer's next Next* call at minimum.
template <typename IndexType>
struct RowBlockView {
  uint64_t num_rows = 0;
  uint64_t nnz = 0;
  const uint64_t* offset = nullptr;  // num_rows + 1
  const float* label = nullptr;      // num_rows
  const float* weight = nullptr;     // num_rows or null
  const uint64_t* qid = nullptr;     // num_rows or null
  const uint32_t* field = nullptr;   // nnz or null
  const IndexType* index = nullptr;  // nnz
  const float* value = nullptr;      // nnz or null (implicit 1.0)
  const int32_t* value_i32 = nullptr;
  const int64_t* value_i64 = nullptr;
  int32_t value_dtype = 0;
  uint64_t max_index = 0;
  uint32_t max_field = 0;

  void FromContainer(const RowBlockContainer<IndexType>& b) {
    num_rows = b.Size();
    nnz = b.index.size();
    offset = b.offset.data();
    label = b.label.data();
    weight = b.weight.empty() ? nullptr : b.weight.data();
    qid = b.qid.empty() ? nullptr : b.qid.data();
    field = b.field.empty() ? nullptr : b.field.data();
    index = b.index.data();
    value = b.value.empty() ? nullptr : b.value.data();
    value_i32 = b.value_i32.empty() ? nullptr : b.value_i32.data();
    value_i64 = b.value_i64.empty() ? nullptr : b.value_i64.data();
    value_dtype = b.value_dtype;
    max_index = b.max_index;
    max_field = b.max_field;
  }

  // Materialize into an owned container (bulk assigns — memcpy speed).
  void ToContainer(RowBlockContainer<IndexType>* out) const {
    out->offset.assign(offset, offset + num_rows + 1);
    out->label.assign(label, label + num_rows);
    if (weight != nullptr) {
      out->weight.assign(weight, weight + num_rows);
    } else {
      out->weight.clear();
    }
    if (qid != nullptr) {
      out->qid.assign(qid, qid + num_rows);
    } else {
      out->qid.clear();
    }
    if (field != nullptr) {
      out->field.assign(field, field + nnz);
    } else {
      out->field.clear();
    }
    out->index.assign(index, index + nnz);
    if (value != nullptr) {
      out->value.assign(value, value + nnz);
    } else {
      out->value.clear();
    }
    if (value_i32 != nullptr) {
      out->value_i32.assign(value_i32, value_i32 + nnz);
    } else {
      out->value_i32.clear();
    }
    if (value_i64 != nullptr) {
      out->value_i64.assign(value_i64, value_i64 + nnz);
    } else {
      out->value_i64.clear();
    }
    out->value_dtype = value_dtype;
    out->max_index = max_index;
    out->max_field = max_field;
  }
};

}  // namespace dct

#endif  // DCT_ROWBLOCK_H_
