// Azure Blob Storage filesystem over the Blob service REST API.
//
// Counterpart of reference src/io/azure_filesys.{h,cc}, which is a partial
// stub: only ListDirectory is implemented (against the wastorage SDK) and
// Open/OpenForRead return NULL (azure_filesys.h:22-32). This implementation
// exceeds that surface: SharedKey-signed List Blobs, ranged blob reads with
// reconnect-at-offset retry, and block-blob writes (Put Blob for small
// objects, Put Block + Put Block List for large ones). Same URI form
// (azure://container/path) and env credentials (AZURE_STORAGE_ACCOUNT /
// AZURE_STORAGE_ACCESS_KEY, reference azure_filesys.cc:31-39). Transport is
// the built-in http client, so it targets http endpoints (Azurite-style
// emulators, gateways) — like the S3 client (s3_filesys.h).
#ifndef DCT_AZURE_FILESYS_H_
#define DCT_AZURE_FILESYS_H_

#include <map>
#include <string>
#include <vector>

#include "filesys.h"
#include "retry.h"

namespace dct {

struct AzureConfig {
  std::string account;
  std::string key_base64;     // SharedKey account key (base64)
  std::string endpoint_host;  // empty => <account>.blob.core.windows.net
  int endpoint_port = 80;
  // "https" routes through the local TLS helper (DCT_TLS_PROXY, http.h
  // ResolveHttpRoute). The no-endpoint default is https against the real
  // <account>.blob.core.windows.net — Azure enforces secure transfer.
  std::string scheme = "http";
  // Shared resilience policy (retry.h): DMLC_IO_* globals overridden by
  // AZURE_MAX_RETRY / AZURE_RETRY_SLEEP_MS / AZURE_BACKOFF_* /
  // AZURE_DEADLINE_MS (checked parsing).
  io::RetryPolicy retry;

  // AZURE_STORAGE_ACCOUNT / AZURE_STORAGE_ACCESS_KEY (reference
  // azure_filesys.cc:31-39) + AZURE_ENDPOINT ("host[:port]" or
  // "http(s)://host[:port]") for emulators/gateways.
  static AzureConfig FromEnv();
};

class AzureFileSystem : public FileSystem {
 public:
  explicit AzureFileSystem(const AzureConfig& config) : config_(config) {}
  static AzureFileSystem* GetInstance();

  FileInfo GetPathInfo(const URI& path) override;
  void ListDirectory(const URI& path, std::vector<FileInfo>* out) override;
  Stream* Open(const URI& path, const char* mode,
               bool allow_null = false) override;
  SeekStream* OpenForRead(const URI& path, bool allow_null = false) override;

  const AzureConfig& config() const { return config_; }

 private:
  // GetPathInfo under an explicit resilience policy — OpenForRead routes
  // its per-open `?io_*=` overrides through here so the open-time probe
  // honors the caller's budget, not just the env default.
  FileInfo PathInfoUnderPolicy(const URI& path,
                               const io::RetryPolicy& policy);

  AzureConfig config_;
};

namespace azure {

// SharedKey authorization (exposed for tests): returns the Authorization
// header value and fills x-ms-date / x-ms-version into headers.
std::string BuildSharedKey(const AzureConfig& cfg, const std::string& method,
                           const std::string& resource_path,
                           const std::map<std::string, std::string>& query,
                           std::map<std::string, std::string>* headers,
                           size_t content_length);

}  // namespace azure

}  // namespace dct

#endif  // DCT_AZURE_FILESYS_H_
