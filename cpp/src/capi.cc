// C ABI for ctypes binding (dmlc_core_tpu/io/native.py).
//
// The reference exposes C++ headers directly; a TPU-native rebuild needs a
// stable C surface instead because the Python/JAX layer binds via ctypes
// (pybind11 is not part of the toolchain — see repo README). Conventions:
//   - every call returns 0 on success, -1 on error; dct_last_error() returns
//     the thread-local message
//   - handles are opaque pointers; *_free releases
//   - blob/rowblock pointers remain valid until the next call on the same
//     handle (matching reference DataIter Value() semantics, data.h:55-66)
//
// MACHINE-CHECKED PARITY (scripts/analyze.py Pass 4, doc/analysis.md):
// every extern-"C" function below is diffed against the ctypes table in
// dmlc_core_tpu/io/native.py (explicit restype, arity, pointer-ness,
// scalar widths), and every `typedef struct` is diffed field-by-field
// against its ctypes Structure mirror AND proven byte-identical by a
// compile-time sizeof/offsetof probe. Adding a function or struct field
// here without updating the binding fails `make analyze` — keep
// declarations in the plain shapes the extractor parses (one `dct_*`
// definition per `extern "C"` symbol, `typedef struct { ... } name;`).
#include <cstring>
#include <string>

#include "batcher.h"
#include "bf16.h"
#include "csr_rec.h"
#include "dense_rec.h"
#include "filesys.h"
#include "fs_fault.h"
#include "hdfs_filesys.h"
#include "http.h"
#include "input_split.h"
#include "parser.h"
#include "recordio.h"
#include "retry.h"
#include "rowblock.h"
#include "stream.h"
#include "telemetry.h"

namespace {
thread_local std::string g_last_error;

template <typename F>
int Guard(F&& fn) {
  try {
    fn();
    return 0;
  } catch (const std::exception& e) {
    g_last_error = e.what();
    return -1;
  } catch (...) {
    g_last_error = "unknown C++ exception";
    return -1;
  }
}
}  // namespace



typedef struct {
  uint64_t num_rows;
  uint64_t nnz;
  const uint64_t* offset;  // num_rows + 1
  const float* label;      // num_rows
  const float* weight;     // num_rows or NULL
  const uint64_t* qid;     // num_rows or NULL
  const uint32_t* field;   // nnz or NULL
  const void* index;       // nnz entries, dtype per index_is_64
  const float* value;      // nnz or NULL (implicit 1.0)
  uint64_t max_index;
  uint32_t max_field;
  int32_t index_is_64;
  // typed csv values (value_dtype 0=f32/1=i32/2=i64); for non-zero dtypes
  // `value` is NULL and the matching typed pointer holds nnz entries
  const int32_t* value_i32;
  const int64_t* value_i64;
  int32_t value_dtype;
} dct_rowblock_t;

namespace {
struct ParserHandle {
  dct::Parser<uint32_t>* p32 = nullptr;
  dct::Parser<uint64_t>* p64 = nullptr;

  ~ParserHandle() {
    delete p32;
    delete p64;
  }

  // dct_rowblock_t is exactly the RowBlockView shape: the parser's view
  // lane (Parser::NextBlockView) fills it with NO intermediate container —
  // on a shard-cache replay the pointers go straight into the mmap.
  template <typename T>
  static void FillView(const dct::RowBlockView<T>& v, dct_rowblock_t* out) {
    out->num_rows = v.num_rows;
    out->nnz = v.nnz;
    out->offset = v.offset;
    out->label = v.label;
    out->weight = v.weight;
    out->qid = v.qid;
    out->field = v.field;
    out->index = v.index;
    out->value = v.value;
    out->max_index = v.max_index;
    out->max_field = v.max_field;
    out->index_is_64 = sizeof(T) == 8 ? 1 : 0;
    out->value_i32 = v.value_i32;
    out->value_i64 = v.value_i64;
    out->value_dtype = v.value_dtype;
  }
};
}  // namespace

extern "C" {

const char* dct_last_error() { return g_last_error.c_str(); }

// Rotate the WebHDFS delegation token at runtime (long-running jobs renew
// Hadoop tokens mid-flight); empty string reverts to user.name auth.
int dct_webhdfs_set_delegation_token(const char* token) {
  return Guard([&] {
    dct::WebHdfsFileSystem::GetInstance()->set_delegation_token(
        token == nullptr ? "" : token);
  });
}

// Inject/rotate the verbatim Authorization header for WebHDFS (the SPNEGO
// hook: an external kinit-based helper supplies "Negotiate <token>");
// empty string reverts to user.name / delegation auth.
int dct_webhdfs_set_auth_header(const char* header) {
  return Guard([&] {
    dct::WebHdfsFileSystem::GetInstance()->set_auth_header(
        header == nullptr ? "" : header);
  });
}

// Publish the TLS-terminating helper's "host:port" address to the native
// https router (http.h SetTlsProxyOverride). The binding calls this instead
// of mutating DCT_TLS_PROXY: setenv after native request threads exist
// races their getenv (glibc UB). Empty/NULL clears back to the env fallback.
int dct_set_tls_proxy(const char* addr) {
  return Guard(
      [&] { dct::SetTlsProxyOverride(addr == nullptr ? "" : addr); });
}

// --------------------------------------------------------------- telemetry --
// The unified telemetry plane (cpp/src/telemetry.h). dct_telemetry_snapshot
// returns the versioned JSON document (schema doc/observability.md; caller
// frees with dct_str_free) that dmlc_core_tpu.telemetry.snapshot() merges
// and the tracker's /metrics scrape serves — one snapshot, three surfaces.
int dct_telemetry_snapshot(char** out) {
  return Guard([&] {
    // touch the io-stats singleton so its counters are registered even in
    // processes that have not issued a remote request yet: the snapshot's
    // metric SET must be stable, not dependent on call order
    dct::io::GlobalIoStats();
    const std::string s = dct::telemetry::SnapshotJson();
    char* buf = new char[s.size() + 1];
    std::memcpy(buf, s.c_str(), s.size() + 1);
    *out = buf;
  });
}

// Zero every registered metric (owned and adopted-external alike) and
// drop the buffered span ring — one reset restores the whole plane.
int dct_telemetry_reset() {
  return Guard([&] {
    dct::telemetry::Reset();
    dct::telemetry::TraceReset();
  });
}

// Runtime override of the DMLC_TELEMETRY gate for timed spans (counters
// keep counting either way — they are cheaper than the branch).
int dct_telemetry_enable(int on) {
  return Guard([&] { dct::telemetry::SetEnabled(on != 0); });
}

// The native span-ring trace document (telemetry.h TraceJson; schema
// doc/observability.md "Distributed tracing"). Steady-clock timestamps
// plus the per-process (wall, steady) anchor pair — the Python half
// (telemetry.trace_json / the tracker's /trace) merges it onto the
// wall clock. Caller frees with dct_str_free.
int dct_trace_snapshot(char** out) {
  return Guard([&] {
    const std::string s = dct::telemetry::TraceJson();
    char* buf = new char[s.size() + 1];
    std::memcpy(buf, s.c_str(), s.size() + 1);
    *out = buf;
  });
}

// Drop every buffered span and restart the trace sequence.
int dct_trace_reset() {
  return Guard([&] { dct::telemetry::TraceReset(); });
}

// Best-effort native flight-recorder dump (telemetry.h FlightDump):
// writes trace + metrics to $DMLC_TRACE_DUMP when set. Returns 0 with
// *written = 1 only when a dump file actually landed.
int dct_flight_dump(const char* reason, int* written) {
  return Guard([&] {
    *written = dct::telemetry::FlightDump(reason) ? 1 : 0;
  });
}

// ----------------------------------------------------------- io resilience --
// Mirror of dct::io::IoStats (retry.h) — process-global remote-I/O
// resilience counters, surfaced in Python as io_stats() (alongside the
// PR-1 dct_parser_pipeline_stats).
typedef struct {
  uint64_t requests;          // HTTP requests sent
  uint64_t retries;           // backoff sleeps taken
  uint64_t backoff_ms_total;  // total milliseconds slept in backoff
  uint64_t timeouts;          // per-attempt timeout expiries
  uint64_t faults_injected;   // DMLC_IO_FAULT_PLAN firings
  uint64_t giveups;           // retry loops that exhausted their budget
  uint64_t deadline_exhausted;  // giveups caused by the deadline
} dct_io_retry_stats_t;

int dct_io_retry_stats(dct_io_retry_stats_t* out) {
  return Guard([&] {
    const dct::io::IoStats& st = dct::io::GlobalIoStats();
    out->requests = st.requests.load(std::memory_order_relaxed);
    out->retries = st.retries.load(std::memory_order_relaxed);
    out->backoff_ms_total =
        st.backoff_ms_total.load(std::memory_order_relaxed);
    out->timeouts = st.timeouts.load(std::memory_order_relaxed);
    out->faults_injected =
        st.faults_injected.load(std::memory_order_relaxed);
    out->giveups = st.giveups.load(std::memory_order_relaxed);
    out->deadline_exhausted =
        st.deadline_exhausted.load(std::memory_order_relaxed);
  });
}

int dct_io_stats_reset() {
  return Guard([&] { dct::io::ResetIoStats(); });
}

// Install/replace the deterministic fault-injection plan evaluated inside
// the native HTTP client (retry.h grammar, e.g.
// "reset:every=3;stall:every=5,ms=80;5xx:every=7,status=503"); empty/NULL
// clears. The explicit setter is the race-free alternative to mutating
// DMLC_IO_FAULT_PLAN after native request threads exist (same rule as
// dct_set_tls_proxy).
int dct_io_set_fault_plan(const char* plan) {
  return Guard(
      [&] { dct::io::SetFaultPlan(plan == nullptr ? "" : plan); });
}

// Override the per-attempt socket timeout (connect/recv/send bound,
// milliseconds); <=0 reverts to DMLC_IO_TIMEOUT_MS / the 60 s default.
int dct_io_set_timeout_ms(int ms) {
  return Guard([&] { dct::io::SetIoTimeoutMs(ms); });
}

// Install/replace the LOCAL-filesystem fault plan (fs_fault.h grammar,
// e.g. "write:fault=enospc,every=3;rename:fault=torn_rename,p=0.5") —
// evaluated inside the local stream/shard-cache syscall wrappers, below
// every mock. Empty/NULL clears; an explicit clear beats
// DMLC_FS_FAULT_PLAN (same race-free-setter rule as the io plan).
int dct_fs_set_fault_plan(const char* plan) {
  return Guard(
      [&] { dct::fsio::SetFsFaultPlan(plan == nullptr ? "" : plan); });
}

// ---------------------------------------------------------------- streams --
typedef void* dct_stream_t;

int dct_stream_create(const char* uri, const char* mode, dct_stream_t* out) {
  return Guard([&] { *out = dct::Stream::Create(uri, mode); });
}

int dct_stream_read(dct_stream_t h, void* buf, size_t size, size_t* nread) {
  return Guard(
      [&] { *nread = static_cast<dct::Stream*>(h)->Read(buf, size); });
}

int dct_stream_write(dct_stream_t h, const void* buf, size_t size) {
  return Guard([&] { static_cast<dct::Stream*>(h)->Write(buf, size); });
}

int dct_stream_free(dct_stream_t h) {
  // Finish() first so buffered-write failures reach the caller; the
  // destructor's own Finish is a no-op afterwards (finished_ latch), so the
  // handle is freed even on error.
  auto* s = static_cast<dct::Stream*>(h);
  if (s == nullptr) return 0;
  int rc = Guard([&] { s->Finish(); });
  delete s;
  return rc;
}

// ------------------------------------------------------------- filesystem --
// Lists to a newline-separated "path\tsize\ttype" string (caller frees with
// dct_str_free).
int dct_fs_list(const char* uri, int recursive, char** out) {
  return Guard([&] {
    dct::URI u(uri);
    dct::FileSystem* fs = dct::FileSystem::GetInstance(u);
    std::vector<dct::FileInfo> infos;
    if (recursive) {
      fs->ListDirectoryRecursive(u, &infos);
    } else {
      fs->ListDirectory(u, &infos);
    }
    std::string s;
    for (const auto& info : infos) {
      s += info.path.Str();
      s += '\t';
      s += std::to_string(info.size);
      s += '\t';
      s += info.type == dct::FileType::kDirectory ? 'd' : 'f';
      s += '\n';
    }
    char* buf = new char[s.size() + 1];
    std::memcpy(buf, s.c_str(), s.size() + 1);
    *out = buf;
  });
}

int dct_fs_path_info(const char* uri, size_t* size, int* is_dir) {
  return Guard([&] {
    dct::URI u(uri);
    dct::FileInfo info = dct::FileSystem::GetInstance(u)->GetPathInfo(u);
    *size = info.size;
    *is_dir = info.type == dct::FileType::kDirectory ? 1 : 0;
  });
}

int dct_str_free(char* s) {
  delete[] s;
  return 0;
}

// ------------------------------------------------------------ input split --
typedef void* dct_split_t;

int dct_split_create(const char* uri, unsigned part, unsigned nsplit,
                     const char* type, int threaded, dct_split_t* out) {
  return Guard([&] {
    *out = dct::InputSplit::Create(uri, part, nsplit, type, "", false, 0, 256,
                                   false, threaded != 0);
  });
}

// full-option factory: indexed recordio, shuffle, caching, coarse shuffle
int dct_split_create_ex(const char* uri, const char* index_uri, unsigned part,
                        unsigned nsplit, const char* type, int threaded,
                        int shuffle, int seed, size_t batch_size,
                        const char* cache_file, unsigned shuffle_parts,
                        int recurse, dct_split_t* out) {
  return Guard([&] {
    *out = dct::InputSplit::Create(
        uri, part, nsplit, type, index_uri == nullptr ? "" : index_uri,
        shuffle != 0, seed, batch_size, recurse != 0, threaded != 0,
        cache_file == nullptr ? "" : cache_file, shuffle_parts);
  });
}

int dct_split_next_record(dct_split_t h, const void** data, size_t* size,
                          int* has) {
  return Guard([&] {
    dct::InputSplit::Blob blob;
    *has = static_cast<dct::InputSplit*>(h)->NextRecord(&blob) ? 1 : 0;
    *data = blob.dptr;
    *size = blob.size;
  });
}

int dct_split_next_chunk(dct_split_t h, const void** data, size_t* size,
                         int* has) {
  return Guard([&] {
    dct::InputSplit::Blob blob;
    *has = static_cast<dct::InputSplit*>(h)->NextChunk(&blob) ? 1 : 0;
    *data = blob.dptr;
    *size = blob.size;
  });
}

int dct_split_before_first(dct_split_t h) {
  return Guard([&] { static_cast<dct::InputSplit*>(h)->BeforeFirst(); });
}

int dct_split_reset_partition(dct_split_t h, unsigned part, unsigned nsplit) {
  return Guard(
      [&] { static_cast<dct::InputSplit*>(h)->ResetPartition(part, nsplit); });
}

int dct_split_total_size(dct_split_t h, size_t* out) {
  return Guard(
      [&] { *out = static_cast<dct::InputSplit*>(h)->GetTotalSize(); });
}

int dct_split_hint_chunk_size(dct_split_t h, size_t bytes) {
  return Guard(
      [&] { static_cast<dct::InputSplit*>(h)->HintChunkSize(bytes); });
}

int dct_split_free(dct_split_t h) {
  return Guard([&] { delete static_cast<dct::InputSplit*>(h); });
}

// --------------------------------------------------------------- recordio --
typedef void* dct_recordio_writer_t;
typedef void* dct_recordio_reader_t;

namespace {
struct WriterHandle {
  dct::Stream* stream;
  dct::RecordIOWriter* writer;
};
struct ReaderHandle {
  dct::Stream* stream;
  dct::RecordIOReader* reader;
  std::string buf;
};
}  // namespace

int dct_recordio_writer_create(const char* uri, dct_recordio_writer_t* out) {
  return Guard([&] {
    auto* h = new WriterHandle();
    h->stream = dct::Stream::Create(uri, "w");
    h->writer = new dct::RecordIOWriter(h->stream);
    *out = h;
  });
}

int dct_recordio_write(dct_recordio_writer_t h, const void* data,
                       size_t size) {
  return Guard([&] {
    static_cast<WriterHandle*>(h)->writer->WriteRecord(data, size);
  });
}

int dct_recordio_writer_free(dct_recordio_writer_t h) {
  return Guard([&] {
    auto* wh = static_cast<WriterHandle*>(h);
    delete wh->writer;
    delete wh->stream;
    delete wh;
  });
}

int dct_recordio_reader_create(const char* uri, dct_recordio_reader_t* out) {
  return Guard([&] {
    auto* h = new ReaderHandle();
    h->stream = dct::Stream::Create(uri, "r");
    h->reader = new dct::RecordIOReader(h->stream);
    *out = h;
  });
}

int dct_recordio_read(dct_recordio_reader_t h, const void** data, size_t* size,
                      int* has) {
  return Guard([&] {
    auto* rh = static_cast<ReaderHandle*>(h);
    *has = rh->reader->NextRecord(&rh->buf) ? 1 : 0;
    *data = rh->buf.data();
    *size = rh->buf.size();
  });
}

int dct_recordio_reader_free(dct_recordio_reader_t h) {
  return Guard([&] {
    auto* rh = static_cast<ReaderHandle*>(h);
    delete rh->reader;
    delete rh->stream;
    delete rh;
  });
}

// ----------------------------------------------------------------- parser --
typedef void* dct_parser_t;




// chunks_in_flight bounds the threaded pipeline's outstanding chunks
// (0 = auto-size to the worker count; parser.cc DefaultChunksInFlight).
// cache_dir/cache_mode (NULL/"" = URI sugar + env only) opt into the
// transcoding shard cache (cpp/src/shard_cache.h, doc/caching.md):
// cache_dir names the shard directory, cache_mode is never|auto|refresh.
int dct_parser_create_ex(const char* uri, unsigned part, unsigned npart,
                         const char* format, int nthread, int threaded,
                         int index64, int chunks_in_flight,
                         const char* cache_dir, const char* cache_mode,
                         dct_parser_t* out) {
  return Guard([&] {
    const std::string cdir = cache_dir == nullptr ? "" : cache_dir;
    const std::string cmode = cache_mode == nullptr ? "" : cache_mode;
    auto* h = new ParserHandle();
    if (index64 != 0) {
      h->p64 = dct::Parser<uint64_t>::Create(uri, part, npart, format, nthread,
                                             threaded != 0, chunks_in_flight,
                                             cdir, cmode);
    } else {
      h->p32 = dct::Parser<uint32_t>::Create(uri, part, npart, format, nthread,
                                             threaded != 0, chunks_in_flight,
                                             cdir, cmode);
    }
    *out = h;
  });
}

int dct_parser_create(const char* uri, unsigned part, unsigned npart,
                      const char* format, int nthread, int threaded,
                      int index64, dct_parser_t* out) {
  return dct_parser_create_ex(uri, part, npart, format, nthread, threaded,
                              index64, 0, nullptr, nullptr, out);
}

int dct_parser_next_block(dct_parser_t h, dct_rowblock_t* out, int* has) {
  return Guard([&] {
    auto* ph = static_cast<ParserHandle*>(h);
    // the view lane: pointers into the producer's storage (a container's
    // vectors, or the shard cache's mmap — zero copies either way),
    // valid until the next call on this handle
    if (ph->p64 != nullptr) {
      dct::RowBlockView<uint64_t> v;
      *has = ph->p64->NextBlockView(&v) ? 1 : 0;
      if (*has) ParserHandle::FillView(v, out);
    } else {
      dct::RowBlockView<uint32_t> v;
      *has = ph->p32->NextBlockView(&v) ? 1 : 0;
      if (*has) ParserHandle::FillView(v, out);
    }
  });
}

int dct_parser_before_first(dct_parser_t h) {
  return Guard([&] {
    auto* ph = static_cast<ParserHandle*>(h);
    if (ph->p64 != nullptr) {
      ph->p64->BeforeFirst();
    } else {
      ph->p32->BeforeFirst();
    }
  });
}

int dct_parser_bytes_read(dct_parser_t h, size_t* out) {
  return Guard([&] {
    auto* ph = static_cast<ParserHandle*>(h);
    *out = ph->p64 != nullptr ? ph->p64->BytesRead() : ph->p32->BytesRead();
  });
}

// Mirror of dct::ParsePipelineStats (parser.h) — occupancy/stall counters
// of the multi-chunk parse pipeline, for bench/ops introspection.
// APPEND-ONLY contract: the struct is caller-allocated and versionless
// (the in-tree ctypes mirror in dmlc_core_tpu/io/native.py ships in
// lockstep with this .so); new fields go at the END only, and out-of-tree
// consumers must rebuild against the matching header.
typedef struct {
  uint64_t chunks_read;
  uint64_t blocks_delivered;
  uint64_t reader_waits;
  uint64_t worker_waits;
  uint64_t consumer_waits;
  uint64_t inflight_now;
  uint64_t inflight_peak;
  uint64_t inflight_sum;
  uint64_t capacity;
  uint64_t workers;
  uint64_t simd_tier;  // structural-scan lane: 0 scalar, 1 swar, 2 sse2,
                       // 3 avx2 (simd_scan.h SimdTier)
} dct_parse_pipeline_stats_t;

// *has = 0 when the handle carries no pipeline (threaded=0 parsers).
int dct_parser_pipeline_stats(dct_parser_t h, dct_parse_pipeline_stats_t* out,
                              int* has) {
  return Guard([&] {
    auto* ph = static_cast<ParserHandle*>(h);
    dct::ParsePipelineStats s;
    const bool ok = ph->p64 != nullptr ? ph->p64->GetPipelineStats(&s)
                                       : ph->p32->GetPipelineStats(&s);
    *has = ok ? 1 : 0;
    if (ok) {
      out->chunks_read = s.chunks_read;
      out->blocks_delivered = s.blocks_delivered;
      out->reader_waits = s.reader_waits;
      out->worker_waits = s.worker_waits;
      out->consumer_waits = s.consumer_waits;
      out->inflight_now = s.inflight_now;
      out->inflight_peak = s.inflight_peak;
      out->inflight_sum = s.inflight_sum;
      out->capacity = s.capacity;
      out->workers = s.workers;
      out->simd_tier = s.simd_tier;
    }
  });
}

// Pin the shuffle permutation the next before_first samples; *supported = 0
// when nothing in the chain shuffles (resume is order-safe regardless).
int dct_parser_set_epoch(dct_parser_t h, unsigned epoch, int32_t* supported) {
  return Guard([&] {
    auto* ph = static_cast<ParserHandle*>(h);
    const bool ok = ph->p64 != nullptr ? ph->p64->SetShuffleEpoch(epoch)
                                       : ph->p32->SetShuffleEpoch(epoch);
    *supported = ok ? 1 : 0;
  });
}

int dct_parser_free(dct_parser_t h) {
  return Guard([&] { delete static_cast<ParserHandle*>(h); });
}

// Render the native parser-format registry as markdown (name, description,
// argument tables from each format's reflection params) — the doc lane's
// source of truth (scripts/gendoc.py; reference doc/parameter.md documents
// the same surface by hand).
int dct_parser_formats_doc(char** out) {
  return Guard([&] {
    auto* reg = dct::Registry<dct::ParserFactoryReg<uint32_t>>::Get();
    std::string s;
    for (const std::string& name : reg->ListAllNames()) {
      const auto* e = reg->Find(name);
      s += "## format `" + e->name + "`\n\n" + e->description + "\n\n";
      if (!e->arguments.empty()) {
        s += "| argument | type | description |\n|---|---|---|\n";
        for (const auto& a : e->arguments) {
          s += "| `" + a.name + "` | " + a.type_info_str + " | " +
               a.description + " |\n";
        }
        s += "\n";
      }
    }
    char* buf = new char[s.size() + 1];
    std::memcpy(buf, s.c_str(), s.size() + 1);
    *out = buf;
  });
}

// ---------------------------------------------------------------- batcher --
// Native static-shape batch assembly (batcher.h): Python asks for the next
// batch's shape via next_meta, allocates numpy arrays, and fill_* writes
// them in one GIL-free pass.
typedef void* dct_batcher_t;

int dct_batcher_create(const char* uri, unsigned part, unsigned npart,
                       const char* format, int nthread, int threaded,
                       uint64_t batch_rows, uint32_t num_shards,
                       uint64_t min_nnz_bucket, dct_batcher_t* out) {
  return Guard([&] {
    auto* p = dct::Parser<uint32_t>::Create(uri, part, npart, format, nthread,
                                            threaded != 0);
    *out = new dct::PaddedBatcher(p, batch_rows, num_shards, min_nnz_bucket);
  });
}

int dct_batcher_next_meta(dct_batcher_t h, uint64_t* take, uint64_t* bucket,
                          uint64_t* max_index, int* has_qid, int* has_field,
                          int* has) {
  return Guard([&] {
    *has = static_cast<dct::PaddedBatcher*>(h)->NextMeta(
               take, bucket, max_index, has_qid, has_field)
               ? 1
               : 0;
  });
}

// qid/field may be NULL to skip (reference RowBlock carries both,
// data.h:174-236; here they continue into the device layout)
int dct_batcher_fill_csr(dct_batcher_t h, int32_t* row, int32_t* col,
                         float* val, float* label, float* weight,
                         int32_t* nrows, int32_t* qid, int32_t* field) {
  return Guard([&] {
    static_cast<dct::PaddedBatcher*>(h)->FillCSR(row, col, val, label, weight,
                                                 nrows, qid, field);
  });
}

// x_dtype: 0 = float32, 1 = bfloat16 (uint16 storage) — bf16 emission halves
// host fill and host->HBM transfer bytes for the dense (MXU) layout
int dct_batcher_fill_dense(dct_batcher_t h, void* x, int32_t x_dtype,
                           uint64_t num_features, float* label, float* weight,
                           int32_t* nrows, int32_t* qid) {
  return Guard([&] {
    static_cast<dct::PaddedBatcher*>(h)->FillDense(x, x_dtype, num_features,
                                                   label, weight, nrows, qid);
  });
}

// Fused shard-major fill (batcher.h FillPacked): big [D, kb, bucket] int32,
// aux [D, ka, R] int32, optional separate bf16 val plane [D, bucket] when
// val_dtype == 1 (val may be NULL for val_dtype == 0). One pass writes the
// transfer packs the device lane ships as-is.
int dct_batcher_fill_packed(dct_batcher_t h, int32_t* big, int32_t kb,
                            void* val, int32_t val_dtype, int32_t* aux,
                            int32_t ka, int32_t* nrows) {
  return Guard([&] {
    static_cast<dct::PaddedBatcher*>(h)->FillPacked(big, kb, val, val_dtype,
                                                    aux, ka, nrows);
  });
}

int dct_batcher_fill_dense_packed(dct_batcher_t h, void* x, int32_t x_dtype,
                                  uint64_t num_features, int32_t* aux,
                                  int32_t ka, int32_t* nrows) {
  return Guard([&] {
    static_cast<dct::PaddedBatcher*>(h)->FillDensePacked(
        x, x_dtype, num_features, aux, ka, nrows);
  });
}

int dct_batcher_before_first(dct_batcher_t h) {
  return Guard([&] { static_cast<dct::PaddedBatcher*>(h)->BeforeFirst(); });
}

int dct_batcher_set_epoch(dct_batcher_t h, unsigned epoch,
                          int32_t* supported) {
  return Guard([&] {
    *supported =
        static_cast<dct::PaddedBatcher*>(h)->SetShuffleEpoch(epoch) ? 1 : 0;
  });
}

int dct_batcher_bytes_read(dct_batcher_t h, size_t* out) {
  return Guard(
      [&] { *out = static_cast<dct::PaddedBatcher*>(h)->BytesRead(); });
}

int dct_batcher_free(dct_batcher_t h) {
  return Guard([&] { delete static_cast<dct::PaddedBatcher*>(h); });
}

// -------------------------------------------------------------- dense rec --
// Zero-parse dense ingest (dense_rec.h): records carry [rows, F] matrices
// in device layout, so fill is record framing + bulk memcpy.
typedef void* dct_denserec_t;

int dct_denserec_create(const char* uri, unsigned part, unsigned npart,
                        uint64_t batch_rows, uint32_t num_shards,
                        dct_denserec_t* out) {
  return Guard([&] {
    *out = new dct::DenseRecBatcher(uri, part, npart, batch_rows, num_shards);
  });
}

int dct_denserec_meta(dct_denserec_t h, uint64_t* num_features,
                      int32_t* x_dtype, int32_t* has_weight) {
  return Guard([&] {
    int dt = 0, hw = 0;
    static_cast<dct::DenseRecBatcher*>(h)->Meta(num_features, &dt, &hw);
    *x_dtype = dt;
    *has_weight = hw;
  });
}

int dct_denserec_fill(dct_denserec_t h, void* x, int32_t out_dtype,
                      uint64_t x_features, float* label, float* weight,
                      int32_t* nrows, uint64_t* take) {
  return Guard([&] {
    *take = static_cast<dct::DenseRecBatcher*>(h)->Fill(
        x, out_dtype, x_features, label, weight, nrows);
  });
}

int dct_denserec_fill_packed(dct_denserec_t h, void* x, int32_t out_dtype,
                             uint64_t x_features, int32_t* aux, int32_t ka,
                             int32_t* nrows, uint64_t* take) {
  return Guard([&] {
    *take = static_cast<dct::DenseRecBatcher*>(h)->FillPacked(
        x, out_dtype, x_features, aux, ka, nrows);
  });
}

int dct_denserec_before_first(dct_denserec_t h) {
  return Guard([&] { static_cast<dct::DenseRecBatcher*>(h)->BeforeFirst(); });
}

int dct_denserec_set_epoch(dct_denserec_t h, unsigned epoch,
                           int32_t* supported) {
  return Guard([&] {
    *supported =
        static_cast<dct::DenseRecBatcher*>(h)->SetShuffleEpoch(epoch) ? 1 : 0;
  });
}

int dct_denserec_bytes_read(dct_denserec_t h, size_t* out) {
  return Guard(
      [&] { *out = static_cast<dct::DenseRecBatcher*>(h)->BytesRead(); });
}

int dct_denserec_free(dct_denserec_t h) {
  return Guard([&] { delete static_cast<dct::DenseRecBatcher*>(h); });
}

// ---------------------------------------------------------------- csr rec --
// Zero-rearrangement CSR ingest (csr_rec.h): records carry col/val/row-len
// planes in device batch layout; fill is bulk memcpy + run-length row ids.
typedef void* dct_csrrec_t;

int dct_csrrec_create(const char* uri, unsigned part, unsigned npart,
                      uint64_t batch_rows, uint32_t num_shards,
                      uint64_t min_nnz_bucket, dct_csrrec_t* out) {
  return Guard([&] {
    *out = new dct::CsrRecBatcher(uri, part, npart, batch_rows, num_shards,
                                  min_nnz_bucket);
  });
}

int dct_csrrec_meta(dct_csrrec_t h, uint64_t* bucket, int32_t* has_weight,
                    int32_t* has_qid, int32_t* has_field) {
  return Guard([&] {
    int hw = 0, hq = 0, hf = 0;
    static_cast<dct::CsrRecBatcher*>(h)->Meta(bucket, &hw, &hq, &hf);
    *has_weight = hw;
    *has_qid = hq;
    *has_field = hf;
  });
}

int dct_csrrec_fill(dct_csrrec_t h, int32_t* row, int32_t* col, float* val,
                    int32_t* field, float* label, float* weight,
                    int32_t* qid, int32_t* nrows, uint64_t* take) {
  return Guard([&] {
    *take = static_cast<dct::CsrRecBatcher*>(h)->Fill(
        row, col, val, field, label, weight, qid, nrows);
  });
}

int dct_csrrec_fill_packed(dct_csrrec_t h, int32_t* big, int32_t kb,
                           int32_t* aux, int32_t ka, int32_t* nrows,
                           uint64_t* take) {
  return Guard([&] {
    *take = static_cast<dct::CsrRecBatcher*>(h)->FillPacked(big, kb, aux, ka,
                                                            nrows);
  });
}

int dct_csrrec_before_first(dct_csrrec_t h) {
  return Guard([&] { static_cast<dct::CsrRecBatcher*>(h)->BeforeFirst(); });
}

int dct_csrrec_set_epoch(dct_csrrec_t h, unsigned epoch,
                         int32_t* supported) {
  return Guard([&] {
    *supported =
        static_cast<dct::CsrRecBatcher*>(h)->SetShuffleEpoch(epoch) ? 1 : 0;
  });
}

int dct_csrrec_bytes_read(dct_csrrec_t h, size_t* out) {
  return Guard(
      [&] { *out = static_cast<dct::CsrRecBatcher*>(h)->BytesRead(); });
}

int dct_csrrec_free(dct_csrrec_t h) {
  return Guard([&] { delete static_cast<dct::CsrRecBatcher*>(h); });
}

// ------------------------------------------------------------------- bf16 --
// Bulk bf16 conversion hooks (bf16.h): the parity surface the Python tests
// fuzz against ml_dtypes.bfloat16 — the SAME inlines the batch fills use,
// so a rounding drift there fails the parity test here.

int dct_bf16_convert(const float* src, uint16_t* dst, uint64_t n) {
  return Guard([&] {
    for (uint64_t i = 0; i < n; ++i) dst[i] = dct::Bf16FromFloat(src[i]);
  });
}

int dct_bf16_upcast(const uint16_t* src, float* dst, uint64_t n) {
  return Guard([&] {
    for (uint64_t i = 0; i < n; ++i) dst[i] = dct::Bf16ToFloat(src[i]);
  });
}

}  // extern "C"
