// Little-endian binary serialization over Stream.
//
// Counterpart of reference include/dmlc/serializer.h + endian.h: PODs are
// written fixed-width little-endian on disk regardless of host order
// (the reference's DMLC_IO_NO_ENDIAN_SWAP scheme, endian.h:39-51); vectors
// and strings are uint64 length + payload. The wire format is shared with
// dmlc_core_tpu/serializer.py so containers round-trip across languages.
#ifndef DCT_SERIALIZER_H_
#define DCT_SERIALIZER_H_

#include <cstring>
#include <string>
#include <type_traits>
#include <vector>

#include "stream.h"

namespace dct {

namespace serial {

inline bool NativeIsLE() {
  const uint32_t probe = 1;
  return *reinterpret_cast<const uint8_t*>(&probe) == 1;
}

template <typename T>
inline T ByteSwap(T v) {
  T out;
  auto* src = reinterpret_cast<const uint8_t*>(&v);
  auto* dst = reinterpret_cast<uint8_t*>(&out);
  for (size_t i = 0; i < sizeof(T); ++i) dst[i] = src[sizeof(T) - 1 - i];
  return out;
}

// Host-value <-> on-disk (LE) conversion, parameterized on host order so the
// big-endian branch is directly unit-testable on an LE machine (the
// reference validates its equivalent under s390x QEMU, test_script.sh:60-65;
// here the branch itself is exercised with golden BE fixtures instead —
// cpp/test/test_core.cc TestEndianGoldenBytes).
template <typename T>
inline T ToDisk(T v, bool host_is_le) {
  return host_is_le ? v : ByteSwap(v);
}

// LE<->host conversion is symmetric; FromDisk aliases ToDisk so call sites
// read directionally while one body carries the logic.
template <typename T>
inline T FromDisk(T v, bool host_is_le) {
  return ToDisk(v, host_is_le);
}

template <typename T>
inline void WritePOD(Stream* s, T v) {
  static_assert(std::is_arithmetic_v<T>);
  v = ToDisk(v, NativeIsLE());
  s->Write(&v, sizeof(T));
}

template <typename T>
inline T ReadPOD(Stream* s) {
  static_assert(std::is_arithmetic_v<T>);
  T v;
  s->ReadExact(&v, sizeof(T));
  return FromDisk(v, NativeIsLE());
}

template <typename T>
inline void WriteVec(Stream* s, const std::vector<T>& v) {
  WritePOD<uint64_t>(s, v.size());
  if (v.empty()) return;
  if (NativeIsLE() || sizeof(T) == 1) {
    s->Write(v.data(), v.size() * sizeof(T));
  } else {
    for (const T& e : v) WritePOD(s, e);
  }
}

// Append-read: deserialize a vector onto the tail of *v (no intermediate
// copy — the zero-copy discipline of the rec ingest lane, parser.cc
// RecParser). Returns the number of elements appended. The length prefix
// is validated against the stream's remaining bytes BEFORE the resize: a
// corrupt length must raise, not allocate gigabytes (bounded streams
// only; unbounded streams report SIZE_MAX and fail at ReadExact).
template <typename T>
inline uint64_t ReadVecAppend(Stream* s, std::vector<T>* v) {
  uint64_t n = ReadPOD<uint64_t>(s);
  if (n == 0) return 0;
  DCT_CHECK(n <= s->BytesRemaining() / sizeof(T))
      << "corrupt stream: vector length " << n << " exceeds the "
      << s->BytesRemaining() << " remaining bytes";
  size_t old = v->size();
  v->resize(old + n);
  if (NativeIsLE() || sizeof(T) == 1) {
    s->ReadExact(v->data() + old, n * sizeof(T));
  } else {
    for (uint64_t i = 0; i < n; ++i) (*v)[old + i] = ReadPOD<T>(s);
  }
  return n;
}

template <typename T>
inline void ReadVec(Stream* s, std::vector<T>* v) {
  v->clear();
  ReadVecAppend(s, v);
}

inline void WriteStr(Stream* s, const std::string& str) {
  WritePOD<uint64_t>(s, str.size());
  s->Write(str.data(), str.size());
}

inline std::string ReadStr(Stream* s) {
  uint64_t n = ReadPOD<uint64_t>(s);
  std::string str(n, '\0');
  if (n != 0) s->ReadExact(&str[0], n);
  return str;
}

}  // namespace serial
}  // namespace dct

#endif  // DCT_SERIALIZER_H_
