// Zero-rearrangement CSR ingest: RecordIO records that store col/val/
// row-length planes in (near-)final device batch layout.
//
// The "rec" lane (parser.cc RecParser) deserializes RowBlockContainers and
// re-batches them through PaddedBatcher — two full passes over the bytes
// (LoadAppend memcpy, then FillCSR copy + segment expansion). This lane is
// the CSR continuation of the dense_rec idea (dense_rec.h): the converter
// (dmlc_core_tpu/io/convert.py rows_to_csr_recordio) lays the data out so
// ingest is ONE pass — bulk memcpy of col/val spans straight into the
// packed batch planes plus a run-length expansion of row ids. Reference
// analog: RecordIOChunkReader zero-copy sub-partitioning
// (/root/reference/include/dmlc/recordio.h:166) — taken one step further
// by also fixing the layout on disk.
//
// Record payload (all little-endian):
//   u32 magic 'DRC1'   u32 flags (bit0 weight, bit1 qid, bit2 field)
//   u32 rows           u32 nwin
//   u64 nnz            u32 max_col   u32 reserved
//   u64 win_max[nwin]  // GLOBAL: max nnz over any 2^i consecutive rows
//   u32 row_len[rows]
//   f32 label[rows]    [f32 weight[rows]]  [i32 qid[rows]]
//   u32 col[nnz]       f32 val[nnz]        [u32 field[nnz]]
//
// The win_max table (stamped into every record, so any byte-range
// partition sees it) bounds the nnz of any R consecutive rows — the
// per-shard bucket becomes a STATIC property of (file, batch_rows,
// num_shards), computed once at Meta(): one compiled XLA shape per epoch
// and no per-batch meta round-trip.
#ifndef DCT_CSR_REC_H_
#define DCT_CSR_REC_H_

#include <cstdint>
#include <memory>
#include <string>

#include "input_split.h"

namespace dct {

constexpr uint32_t kCsrRecMagic = 0x44524331;  // 'DRC1'

class CsrRecBatcher {
 public:
  // batch_rows must divide by num_shards (device-axis reshape contract).
  CsrRecBatcher(const std::string& uri, unsigned part, unsigned npart,
                uint64_t batch_rows, uint32_t num_shards,
                uint64_t min_nnz_bucket);

  // Static batch shape, valid before any Fill: bucket is the per-shard
  // nnz capacity (pow2 of the window bound, floored at min_nnz_bucket).
  void Meta(uint64_t* bucket, int* has_weight, int* has_qid, int* has_field);

  // Fill one batch into caller planes (PaddedBatcher::FillCSR layout):
  // row/col/val[/field] are [num_shards, bucket], label/weight[/qid] are
  // [batch_rows], nrows is [num_shards]. Padding: segment id R, col/val/
  // field 0, weight 0, qid -1. Returns the true row count; 0 at end.
  uint64_t Fill(int32_t* row, int32_t* col, float* val, int32_t* field,
                float* label, float* weight, int32_t* qid, int32_t* nrows);

  // Fused shard-major fill (PaddedBatcher::FillPacked layout, f32 values
  // in-pack since the record stores f32): big is [num_shards, kb, bucket]
  // int32 (row, col, val bits, [field]), aux is [num_shards, ka, R] int32
  // (label bits, weight bits, [qid], nrows plane). kb must be
  // 3 + has_field, ka must be 3 + has_qid. Returns the true row count;
  // 0 at end.
  uint64_t FillPacked(int32_t* big, int32_t kb, int32_t* aux, int32_t ka,
                      int32_t* nrows);

  void BeforeFirst();
  size_t BytesRead() const { return bytes_read_; }
  bool SetShuffleEpoch(unsigned epoch) {
    return split_->SetShuffleEpoch(epoch);
  }

 private:
  // Shard-0 plane bases + per-shard element strides; Fill (stride = one
  // plane) and FillPacked (stride = all of a shard's planes) are the same
  // walk over different addressing. Spans never cross shard boundaries
  // (the fill loop clamps to R*(d+1)), so `base + d*stride + local` is
  // safe for both.
  struct Targets {
    int32_t* row;
    int32_t* col;
    float* val;
    int32_t* field;        // null to skip
    uint64_t nnz_stride;   // per-shard stride of the nnz planes (elements)
    float* label;
    float* weight;
    int32_t* qid;          // null to skip
    int32_t* nrows_plane;  // null for the legacy split-plane layout
    uint64_t row_stride;   // per-shard stride of the row-wise planes
  };
  uint64_t FillImpl(const Targets& t, int32_t* nrows);
  bool AdvanceRecord();  // load + validate the next record; false at end
  void Peek();           // ensure the first record's header is parsed

  std::unique_ptr<InputSplit> split_;
  const uint64_t batch_rows_;
  const uint32_t num_shards_;
  const uint64_t min_bucket_;

  // current record view (valid until the next NextRecord on split_)
  const char* row_len_ = nullptr;
  const char* labels_ = nullptr;
  const char* weights_ = nullptr;
  const char* qids_ = nullptr;
  const char* cols_ = nullptr;
  const char* vals_ = nullptr;
  const char* fields_ = nullptr;
  uint64_t rec_rows_ = 0;
  uint64_t rec_nnz_ = 0;
  uint64_t row_in_rec_ = 0;
  uint64_t nnz_in_rec_ = 0;  // nnz consumed from this record

  // pinned static shape (first record wins; later mismatches throw)
  int has_weight_ = -1;
  int has_qid_ = -1;
  int has_field_ = -1;
  uint64_t bucket_ = 0;

  bool have_record_ = false;
  bool eof_ = false;
  size_t bytes_read_ = 0;
};

}  // namespace dct

#endif  // DCT_CSR_REC_H_
