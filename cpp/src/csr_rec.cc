#include "csr_rec.h"

#include <algorithm>
#include <cstring>

#include "base.h"
#include "recordio.h"
#include "serializer.h"
#include "stream.h"

namespace dct {

namespace {

using recordio::CopyWords32LE;
using recordio::LoadU64LE;

uint32_t LoadRowLen(const char* row_len, uint64_t i) {
  return recordio::LoadWordLE(row_len + i * 4);
}

}  // namespace

CsrRecBatcher::CsrRecBatcher(const std::string& uri, unsigned part,
                             unsigned npart, uint64_t batch_rows,
                             uint32_t num_shards, uint64_t min_nnz_bucket)
    : batch_rows_(batch_rows),
      num_shards_(num_shards),
      min_bucket_(std::max<uint64_t>(min_nnz_bucket, 1)) {
  DCT_CHECK(num_shards_ > 0) << "num_shards must be positive";
  DCT_CHECK(batch_rows_ > 0 && batch_rows_ % num_shards_ == 0)
      << "batch_rows=" << batch_rows_ << " must divide by shards="
      << num_shards_;
  URISpec spec(uri, part, npart);
  // shuffling is additionally unsound here: the window-table bucket
  // bounds CONSECUTIVE rows, and a coarse shuffle would compose batches
  // from two windows' tails
  spec.RejectUnknownArgs("csr rec lane", {"format"});
  // already-binary lanes keep the legacy `#<path>` chunk cache; the
  // `#cachefile=<dir>` shard cache re-encodes parsed row blocks and
  // would be a silent no-op here (URI sugar must error, not no-op)
  DCT_CHECK(spec.cache_dir.empty())
      << "the csr rec lane takes the legacy `#<path>` chunk cache, not a "
         "`#cachefile=<dir>` shard-cache directory (the data is already "
         "binary)";
  split_.reset(InputSplit::Create(spec.uri, part, npart, "recordio", "",
                                  false, 0, 256, false, /*threaded=*/true,
                                  spec.cache_file));
}

bool CsrRecBatcher::AdvanceRecord() {
  InputSplit::Blob b;
  if (!split_->NextRecord(&b)) {
    eof_ = true;
    have_record_ = false;
    return false;
  }
  bytes_read_ += b.size;
  DCT_CHECK(b.size >= 32) << "csr rec record too short for its header";
  const char* p = static_cast<const char*>(b.dptr);
  DCT_CHECK(recordio::LoadWordLE(p) == kCsrRecMagic)
      << "not a csr-plane record (bad payload magic); .crec files are "
         "written by rows_to_csr_recordio (dmlc_core_tpu/io/convert.py)";
  const uint32_t flags = recordio::LoadWordLE(p + 4);
  const uint64_t rows = recordio::LoadWordLE(p + 8);
  const uint32_t nwin = recordio::LoadWordLE(p + 12);
  const uint64_t nnz = LoadU64LE(p + 16);
  const uint32_t max_col = recordio::LoadWordLE(p + 24);
  // RecordIO records are < 2^29 bytes; bounding the dims keeps the `need`
  // arithmetic overflow-free under fuzzed headers (dense_rec.cc rule)
  DCT_CHECK(rows <= (1u << 30) && nnz <= (1ull << 34) && nwin <= 64)
      << "corrupt csr rec header: rows=" << rows << " nnz=" << nnz
      << " nwin=" << nwin;
  DCT_CHECK(max_col <= 0x7fffffffu)
      << "csr rec feature index " << max_col
      << " exceeds the int32 device layout";
  const int hw = static_cast<int>(flags & 1u);
  const int hq = static_cast<int>((flags >> 1) & 1u);
  const int hf = static_cast<int>((flags >> 2) & 1u);
  // the window table must fit the blob BEFORE any table read: a truncated
  // record with a large claimed nwin would otherwise read past the end
  DCT_CHECK(nwin >= 1 && b.size >= 32 + 8ull * nwin)
      << "csr rec record truncated inside its window table";
  if (has_weight_ < 0) {
    has_weight_ = hw;
    has_qid_ = hq;
    has_field_ = hf;
    // the per-shard nnz capacity: any R consecutive rows carry at most
    // win_max[ceil_log2(R)] nonzeros (the converter's GLOBAL sliding
    // bound), so one pow2 bucket serves every batch of the epoch
    const uint64_t R = batch_rows_ / num_shards_;
    uint32_t wi = 0;
    while ((1ull << wi) < R && wi + 1 < nwin) ++wi;
    const uint64_t bound = LoadU64LE(p + 32 + 8 * wi);
    // same sanity bound as nnz: a flipped high bit in the table must die
    // here, not drive the pow2 loop into overflow or a multi-GB alloc
    DCT_CHECK(bound <= (1ull << 34))
        << "corrupt csr rec window table: bound " << bound;
    uint64_t bkt = min_bucket_;
    while (bkt < bound) bkt <<= 1;
    bucket_ = bkt;
  } else {
    DCT_CHECK(hw == has_weight_ && hq == has_qid_ && hf == has_field_)
        << "csr rec record flag drift: got w/q/f=" << hw << hq << hf
        << ", pinned " << has_weight_ << has_qid_ << has_field_;
  }
  const char* tab_end = p + 32 + 8 * static_cast<uint64_t>(nwin);
  const uint64_t need = 32 + 8ull * nwin + rows * 4 /*row_len*/ +
                        rows * 4 /*label*/ + (hw ? rows * 4 : 0) +
                        (hq ? rows * 4 : 0) + nnz * 4 /*col*/ +
                        nnz * 4 /*val*/ + (hf ? nnz * 4 : 0);
  DCT_CHECK(b.size >= need)
      << "truncated csr rec record: " << b.size << " bytes, need " << need;
  row_len_ = tab_end;
  labels_ = row_len_ + rows * 4;
  weights_ = hw ? labels_ + rows * 4 : nullptr;
  qids_ = hq ? (hw ? weights_ : labels_) + rows * 4 : nullptr;
  const char* after_rowwise =
      (hq ? qids_ : (hw ? weights_ : labels_)) + rows * 4;
  cols_ = after_rowwise;
  vals_ = cols_ + nnz * 4;
  fields_ = hf ? vals_ + nnz * 4 : nullptr;
  rec_rows_ = rows;
  rec_nnz_ = nnz;
  row_in_rec_ = 0;
  nnz_in_rec_ = 0;
  have_record_ = true;
  return true;
}

void CsrRecBatcher::Peek() {
  if (has_weight_ < 0 && !eof_) {
    AdvanceRecord();
  }
}

void CsrRecBatcher::Meta(uint64_t* bucket, int* has_weight, int* has_qid,
                         int* has_field) {
  Peek();
  DCT_CHECK(has_weight_ >= 0)
      << "csr rec source is empty; cannot determine the batch shape";
  *bucket = bucket_;
  *has_weight = has_weight_;
  *has_qid = has_qid_;
  *has_field = has_field_;
}

uint64_t CsrRecBatcher::Fill(int32_t* row, int32_t* col, float* val,
                             int32_t* field, float* label, float* weight,
                             int32_t* qid, int32_t* nrows) {
  Peek();
  DCT_CHECK(has_field_ <= 0 || field != nullptr)
      << "csr rec file carries field ids but no field plane was passed";
  DCT_CHECK(has_qid_ <= 0 || qid != nullptr)
      << "csr rec file carries qid but no qid plane was passed";
  const uint64_t R = batch_rows_ / num_shards_;
  Targets t;
  t.row = row;
  t.col = col;
  t.val = val;
  t.field = field;
  t.nnz_stride = bucket_;
  t.label = label;
  t.weight = weight;
  t.qid = qid;
  t.nrows_plane = nullptr;
  t.row_stride = R;
  return FillImpl(t, nrows);
}

uint64_t CsrRecBatcher::FillPacked(int32_t* big, int32_t kb, int32_t* aux,
                                   int32_t ka, int32_t* nrows) {
  Peek();
  DCT_CHECK(has_weight_ >= 0)
      << "csr rec source is empty; cannot determine the batch shape";
  const int32_t want_kb = 3 + (has_field_ == 1 ? 1 : 0);
  DCT_CHECK(kb == want_kb)
      << "packed big has " << kb << " planes but the file needs " << want_kb;
  const int32_t want_ka = 3 + (has_qid_ == 1 ? 1 : 0);
  DCT_CHECK(ka == want_ka)
      << "packed aux has " << ka << " planes but the file needs " << want_ka;
  const uint64_t R = batch_rows_ / num_shards_;
  const uint64_t B = bucket_;
  Targets t;
  t.row = big;
  t.col = big + B;
  t.val = reinterpret_cast<float*>(big + 2 * B);
  t.field = has_field_ == 1 ? big + 3 * B : nullptr;
  t.nnz_stride = static_cast<uint64_t>(kb) * B;
  t.label = reinterpret_cast<float*>(aux);
  t.weight = reinterpret_cast<float*>(aux + R);
  t.qid = has_qid_ == 1 ? aux + 2 * R : nullptr;
  t.nrows_plane = aux + static_cast<uint64_t>(ka - 1) * R;
  t.row_stride = static_cast<uint64_t>(ka) * R;
  return FillImpl(t, nrows);
}

uint64_t CsrRecBatcher::FillImpl(const Targets& t, int32_t* nrows) {
  const uint64_t R = batch_rows_ / num_shards_;
  const uint64_t B = bucket_;
  uint64_t filled = 0;                   // rows placed into this batch
  uint64_t shard_written = 0;            // nnz in the current shard's plane
  while (filled < batch_rows_) {
    if (!have_record_ || row_in_rec_ >= rec_rows_) {
      if (eof_ || !AdvanceRecord()) break;
      if (rec_rows_ == 0) continue;  // empty record: skip
    }
    const uint32_t d = static_cast<uint32_t>(filled / R);
    if (filled % R == 0) shard_written = 0;
    // rows until the shard boundary, batch end, or record end
    const uint64_t n = std::min({R * (d + 1) - filled,
                                 batch_rows_ - filled,
                                 rec_rows_ - row_in_rec_});
    // single pass over the span's row lengths: expand local segment ids
    // and count the span's nnz
    int32_t* rowd = t.row + static_cast<uint64_t>(d) * t.nnz_stride;
    uint64_t span_nnz = 0;
    const uint64_t local0 = filled - static_cast<uint64_t>(d) * R;
    for (uint64_t i = 0; i < n; ++i) {
      const uint32_t l = LoadRowLen(row_len_, row_in_rec_ + i);
      DCT_CHECK(shard_written + span_nnz + l <= B)
          << "csr rec shard nnz exceeds the file's window bound (corrupt "
             "row_len or window table)";
      const int32_t local = static_cast<int32_t>(local0 + i);
      for (uint32_t k = 0; k < l; ++k) {
        rowd[shard_written + span_nnz + k] = local;
      }
      span_nnz += l;
    }
    DCT_CHECK(nnz_in_rec_ + span_nnz <= rec_nnz_)
        << "csr rec row lengths overrun the record's nnz";
    // bulk copies: the span's col/val[/field] are contiguous on disk
    CopyWords32LE(t.col + static_cast<uint64_t>(d) * t.nnz_stride +
                      shard_written,
                  cols_ + nnz_in_rec_ * 4, span_nnz);
    CopyWords32LE(t.val + static_cast<uint64_t>(d) * t.nnz_stride +
                      shard_written,
                  vals_ + nnz_in_rec_ * 4, span_nnz);
    if (t.field != nullptr) {
      int32_t* fieldw = t.field + static_cast<uint64_t>(d) * t.nnz_stride +
                        shard_written;
      if (fields_ != nullptr) {
        CopyWords32LE(fieldw, fields_ + nnz_in_rec_ * 4, span_nnz);
      } else {
        std::memset(fieldw, 0, span_nnz * 4);
      }
    }
    const uint64_t roff = static_cast<uint64_t>(d) * t.row_stride + local0;
    CopyWords32LE(t.label + roff, labels_ + row_in_rec_ * 4, n);
    if (weights_ != nullptr) {
      CopyWords32LE(t.weight + roff, weights_ + row_in_rec_ * 4, n);
    } else {
      for (uint64_t i = 0; i < n; ++i) t.weight[roff + i] = 1.0f;
    }
    if (t.qid != nullptr) {
      if (qids_ != nullptr) {
        CopyWords32LE(t.qid + roff, qids_ + row_in_rec_ * 4, n);
      } else {
        for (uint64_t i = 0; i < n; ++i) t.qid[roff + i] = -1;
      }
    }
    shard_written += span_nnz;
    nnz_in_rec_ += span_nnz;
    row_in_rec_ += n;
    filled += n;
    // pad the shard's plane tail when the shard completes (or data ends)
    if (filled % R == 0 || filled == batch_rows_) {
      for (uint64_t k = shard_written; k < B; ++k) {
        rowd[k] = static_cast<int32_t>(R);  // sacrificial segment
      }
      const uint64_t off = static_cast<uint64_t>(d) * t.nnz_stride +
                           shard_written;
      std::memset(t.col + off, 0, (B - shard_written) * 4);
      std::memset(t.val + off, 0, (B - shard_written) * 4);
      if (t.field != nullptr) {
        std::memset(t.field + off, 0, (B - shard_written) * 4);
      }
    }
  }
  if (filled == 0) return 0;
  // data ended mid-shard: the loop's pad-on-complete never ran for it
  if (filled % R != 0) {
    const uint32_t d = static_cast<uint32_t>(filled / R);
    int32_t* rowd = t.row + static_cast<uint64_t>(d) * t.nnz_stride;
    for (uint64_t k = shard_written; k < B; ++k) {
      rowd[k] = static_cast<int32_t>(R);
    }
    const uint64_t off = static_cast<uint64_t>(d) * t.nnz_stride +
                         shard_written;
    std::memset(t.col + off, 0, (B - shard_written) * 4);
    std::memset(t.val + off, 0, (B - shard_written) * 4);
    if (t.field != nullptr) {
      std::memset(t.field + off, 0, (B - shard_written) * 4);
    }
  }
  // pad wholly-empty shards and the row-wise tails
  const uint32_t first_empty =
      static_cast<uint32_t>((filled + R - 1) / R);
  for (uint32_t d = first_empty; d < num_shards_; ++d) {
    int32_t* rowd = t.row + static_cast<uint64_t>(d) * t.nnz_stride;
    for (uint64_t k = 0; k < B; ++k) rowd[k] = static_cast<int32_t>(R);
    const uint64_t off = static_cast<uint64_t>(d) * t.nnz_stride;
    std::memset(t.col + off, 0, B * 4);
    std::memset(t.val + off, 0, B * 4);
    if (t.field != nullptr) {
      std::memset(t.field + off, 0, B * 4);
    }
  }
  for (uint32_t d = 0; d < num_shards_; ++d) {
    const int64_t left = static_cast<int64_t>(filled) - d * R;
    const uint64_t count = static_cast<uint64_t>(
        std::max<int64_t>(0, std::min<int64_t>(left, R)));
    const uint64_t roff = static_cast<uint64_t>(d) * t.row_stride;
    if (count < R) {  // padding rows: weight 0 drops them from the loss
      std::memset(t.label + roff + count, 0, (R - count) * 4);
      std::memset(t.weight + roff + count, 0, (R - count) * 4);
      if (t.qid != nullptr) {
        for (uint64_t i = count; i < R; ++i) t.qid[roff + i] = -1;
      }
    }
    if (t.nrows_plane != nullptr) {
      int32_t* nplane = t.nrows_plane + roff;
      std::memset(nplane, 0, R * 4);
      nplane[0] = static_cast<int32_t>(count);
    }
    nrows[d] = static_cast<int32_t>(count);
  }
  return filled;
}

void CsrRecBatcher::BeforeFirst() {
  split_->BeforeFirst();
  eof_ = false;
  have_record_ = false;
  row_in_rec_ = 0;
  nnz_in_rec_ = 0;
  rec_rows_ = 0;
  rec_nnz_ = 0;
  // flags/bucket deliberately survive: device shapes stay static across
  // epochs (dense_rec.cc rule)
}

}  // namespace dct
