// Concurrent ranged-read engine implementation (see range_reader.h).
#include "range_reader.h"

#include <algorithm>
#include <cstring>

#include "http.h"

namespace dct {
namespace io {

namespace {

constexpr int64_t kRangeBytesLo = 4 << 10;    // 4 KiB floor (tests shrink)
constexpr int64_t kRangeBytesHi = 1 << 30;    // 1 GiB ceiling

// Registry pointers resolved once per process (telemetry.h rule).
telemetry::Counter* IssuedCounter() {
  static telemetry::Counter* c =
      telemetry::GetCounter("io_range_issued_total");
  return c;
}
telemetry::Counter* RetriedCounter() {
  static telemetry::Counter* c =
      telemetry::GetCounter("io_range_retried_total");
  return c;
}
telemetry::Counter* DegradedCounter() {
  static telemetry::Counter* c =
      telemetry::GetCounter("io_range_degraded_200_total");
  return c;
}
telemetry::Gauge* SchedBytesGauge() {
  static telemetry::Gauge* g = telemetry::GetGauge("io_range_sched_bytes");
  return g;
}
telemetry::Gauge* SchedConcurrencyGauge() {
  static telemetry::Gauge* g =
      telemetry::GetGauge("io_range_sched_concurrency");
  return g;
}

// Seed the first range size from the backend's live connect/ttfb
// histograms (PR 5): size ranges so transfer ~4x the observed per-request
// setup cost at a conservative ~64 MB/s per connection — bytes =
// 4 * setup_us * 64 B/us. With no prior traffic, start at the floor and
// let AIMD grow.
size_t SeedRangeBytes(const RangeConfig& cfg, const std::string& backend) {
  const telemetry::IoHists* h = telemetry::IoHistsFor(backend);
  uint64_t setup_us = 0;
  if (h->connect_us->count() > 0) {
    setup_us += h->connect_us->sum() / h->connect_us->count();
  }
  if (h->ttfb_us->count() > 0) {
    setup_us += h->ttfb_us->sum() / h->ttfb_us->count();
  }
  size_t seed = cfg.min_bytes;
  if (setup_us > 0) seed = static_cast<size_t>(setup_us) * 256;
  return std::min(cfg.max_bytes, std::max(cfg.min_bytes, seed));
}

RangeConfig Normalized(RangeConfig c) {
  if (c.max_bytes < c.min_bytes) c.max_bytes = c.min_bytes;
  if (c.max_concurrency < 1) c.max_concurrency = 1;
  return c;
}

}  // namespace

// ---------------------------------------------------------------- config --
RangeConfig RangeConfig::FromEnv() {
  RangeConfig c;
  c.enabled = CheckedEnvInt("DMLC_IO_RANGE", 1, 0, 1) != 0;
  c.min_bytes = static_cast<size_t>(
      CheckedEnvInt("DMLC_IO_RANGE_MIN_BYTES",
                    static_cast<int64_t>(c.min_bytes), kRangeBytesLo,
                    kRangeBytesHi));
  c.max_bytes = static_cast<size_t>(
      CheckedEnvInt("DMLC_IO_RANGE_MAX_BYTES",
                    static_cast<int64_t>(c.max_bytes), kRangeBytesLo,
                    kRangeBytesHi));
  c.max_concurrency = static_cast<int>(
      CheckedEnvInt("DMLC_IO_RANGE_CONCURRENCY", c.max_concurrency, 1, 64));
  return Normalized(c);
}

bool RangeConfig::ApplyUriArg(const std::string& key,
                              const std::string& value) {
  if (key == "io_range") {
    enabled = CheckedInt("uri arg io_range", value, 0, 1) != 0;
  } else if (key == "io_range_min_bytes") {
    min_bytes = static_cast<size_t>(CheckedInt(
        "uri arg io_range_min_bytes", value, kRangeBytesLo, kRangeBytesHi));
    if (max_bytes < min_bytes) max_bytes = min_bytes;
  } else if (key == "io_range_max_bytes") {
    max_bytes = static_cast<size_t>(CheckedInt(
        "uri arg io_range_max_bytes", value, kRangeBytesLo, kRangeBytesHi));
    if (min_bytes > max_bytes) min_bytes = max_bytes;
  } else if (key == "io_range_concurrency") {
    max_concurrency = static_cast<int>(
        CheckedInt("uri arg io_range_concurrency", value, 1, 64));
  } else {
    return false;
  }
  return true;
}

void ExtractUriIoArgs(std::string* path, RetryPolicy* policy,
                      int* timeout_ms_override, RangeConfig* rcfg) {
  // one tokenizer for every io_* knob family: the retry walk offers each
  // key it does not consume to the range config (unknown typos still
  // error there with the full knob list)
  ExtractUriRetryArgs(path, policy, timeout_ms_override,
                      [rcfg](const std::string& key, const std::string& val) {
                        return rcfg != nullptr && rcfg->ApplyUriArg(key, val);
                      });
}

// ----------------------------------------------------------------- reader --
RangeReader::RangeReader(const char* backend, size_t file_size,
                         std::unique_ptr<RangeFetcher> fetcher,
                         std::function<SeekStream*()> sequential_factory,
                         const RangeConfig& cfg, const RetryPolicy& policy,
                         int timeout_ms_override)
    : backend_(backend),
      file_size_(file_size),
      fetcher_(std::move(fetcher)),
      seq_factory_(std::move(sequential_factory)),
      cfg_(Normalized(cfg)),
      policy_(policy),
      timeout_ms_override_(timeout_ms_override),
      hists_(telemetry::RangeHistsFor(backend_)) {
  // fair-share clamp: a telemetry-seeded size must still leave one range
  // per allowed worker in this object, or the seed itself caps the
  // parallelism it exists to enable (AIMD can still grow past it later);
  // floored at min_bytes for objects too small to split that finely
  size_t seed = SeedRangeBytes(cfg_, backend_);
  const size_t fair =
      file_size_ / static_cast<size_t>(cfg_.max_concurrency);
  if (seed > fair) seed = std::max(cfg_.min_bytes, fair);
  // lock-ok: pre-spawn init — no worker thread exists until the first Read
  range_bytes_ = seed;
  // concurrency starts at the configured cap — the operator's stated
  // connection budget; a slow ramp-up would be paid again on EVERY shard
  // reopen. AIMD then runs downhill-first: repeated per-range retries
  // (the congestion signal) halve it, head-of-line waits recover it.
  // lock-ok: pre-spawn init — no worker thread exists until the first Read
  concurrency_ = cfg_.max_concurrency;
}

RangeReader::~RangeReader() {
  {
    std::lock_guard<std::mutex> lk(mu_);
    shutdown_.store(true);
  }
  cv_work_.notify_all();
  cv_data_.notify_all();
  for (std::thread& t : workers_) {
    if (t.joinable()) t.join();
  }
}

bool RangeReader::ShouldExitLocked() const DMLC_REQUIRES(mu_) {
  return shutdown_.load() || degraded_ || error_ != nullptr;
}

size_t RangeReader::CarveEndLocked() const DMLC_REQUIRES(mu_) {
  return std::min(file_size_, bound_);
}

bool RangeReader::WantWorkLocked(int id) const DMLC_REQUIRES(mu_) {
  if (id >= concurrency_) return false;
  if (issue_next_ >= CarveEndLocked()) return false;
  // readahead window from the consumer position bounds buffered + in-
  // flight bytes; the +2 keeps the pipe full while the head is drained
  const size_t window =
      range_bytes_ * static_cast<size_t>(concurrency_ + 2);
  return issue_next_ - pos_ < window;
}

bool RangeReader::HeadReadyLocked() const DMLC_REQUIRES(mu_) {
  auto it = landed_.upper_bound(pos_);
  if (it == landed_.begin()) return false;
  --it;
  return pos_ < it->first + it->second.size;
}

void RangeReader::TrimConsumedLocked() DMLC_REQUIRES(mu_) {
  // segments wholly before the consumer position only exist after a
  // forward seek skipped them: discarded prefetch, counted as waste
  while (!landed_.empty()) {
    auto it = landed_.begin();
    if (it->first + it->second.size <= pos_) {
      wasted_bytes_ += it->second.size;
      landed_.erase(it);
    } else {
      break;
    }
  }
}

void RangeReader::StartWorkersLocked() DMLC_REQUIRES(mu_) {
  started_ = true;
  issue_next_ = pos_;
  SchedBytesGauge()->Set(static_cast<int64_t>(range_bytes_));
  SchedConcurrencyGauge()->Set(concurrency_);
  // never spawn more threads than the remaining bytes can yield ranges at
  // the minimum size — a small shard under a big concurrency cap must not
  // pay for a dozen parked threads per open (if the read bound widens
  // later, parallelism is merely capped at the spawned count, still
  // correct)
  const size_t end = CarveEndLocked();
  const size_t remaining = end - std::min(pos_, end);
  const size_t yield =
      std::max<size_t>((remaining + cfg_.min_bytes - 1) / cfg_.min_bytes, 1);
  const int n = static_cast<int>(std::min<size_t>(
      static_cast<size_t>(cfg_.max_concurrency), yield));
  workers_.reserve(static_cast<size_t>(n));
  for (int i = 0; i < n; ++i) {
    workers_.emplace_back([this, i] { WorkerLoop(i); });
  }
}

void RangeReader::AdaptAfterRangeLocked(
    size_t len, uint64_t elapsed_us, int retries) DMLC_REQUIRES(mu_) {
  if (retries > 0) {
    // multiplicative decrease: a flaky link loses less work per retry on
    // smaller ranges; 2+ retries on one range also halves concurrency
    range_bytes_ = std::max(cfg_.min_bytes, range_bytes_ / 2);
    if (retries >= 2 && concurrency_ > 1) {
      concurrency_ = std::max(1, concurrency_ / 2);
      SchedConcurrencyGauge()->Set(concurrency_);
    }
  } else if (len >= range_bytes_) {
    // additive increase while per-range goodput holds up: bigger ranges
    // keep amortizing the per-request setup cost until transfer dominates
    // (only full-size ranges inform growth — the EOF tail is smaller)
    const double gp = static_cast<double>(len) /
                      static_cast<double>(std::max<uint64_t>(elapsed_us, 1));
    if (ewma_goodput_ <= 0.0 || gp >= ewma_goodput_ * 0.75) {
      range_bytes_ = std::min(cfg_.max_bytes, range_bytes_ + cfg_.min_bytes);
    }
    ewma_goodput_ =
        ewma_goodput_ <= 0.0 ? gp : 0.7 * ewma_goodput_ + 0.3 * gp;
  }
  SchedBytesGauge()->Set(static_cast<int64_t>(range_bytes_));
}

void RangeReader::WorkerLoop(int id) {
  // a per-open ?io_timeout_ms= must bind this worker's socket ops exactly
  // like it binds the sequential lane (thread-local override, retry.h)
  ScopedIoTimeout scoped_timeout(timeout_ms_override_);
  std::unique_lock<std::mutex> lk(mu_);
  while (true) {
    cv_work_.wait(lk, [this, id] {
      return ShouldExitLocked() || WantWorkLocked(id);
    });
    if (ShouldExitLocked()) return;
    const uint64_t gen = generation_;
    const size_t off = issue_next_;
    const size_t len = std::min(range_bytes_, CarveEndLocked() - off);
    issue_next_ += len;
    inflight_bytes_ += len;
    lk.unlock();

    IssuedCounter()->Add(1);
    Segment seg;
    seg.data.reset(new char[len]);  // default-init: the fetch fills it
    seg.size = len;
    int retries = 0;
    bool degraded_fetch = false;
    std::exception_ptr err;
    const uint64_t t0 = telemetry::NowUs();
    // fresh controller whenever an attempt delivered bytes: the policy
    // budget bounds a stretch of ZERO progress, exactly like the
    // sequential lane (one controller per Read call, where any landed
    // bytes mean the next call starts a fresh budget) — without this, a
    // server that truncates every response burns the whole ladder on a
    // range that is in fact converging
    auto ctl = std::make_unique<RetryController>(policy_);
    size_t got = 0;  // retries resume WITHIN the range (offset+got)
    while (true) {
      size_t step = 0;
      try {
        FetchStatus st =
            fetcher_->Fetch(off + got, len - got, seg.data.get() + got,
                            &step);
        got += step;
        degraded_fetch = st == FetchStatus::kDegraded;
        break;
      } catch (const PermanentNetworkError&) {
        err = std::current_exception();  // backoff cannot fix a typo'd host
        break;
      } catch (const HttpStatusError& e) {
        got += step;
        if (step > 0) ctl = std::make_unique<RetryController>(policy_);
        if (!RetryableHttpStatus(e.status) || shutdown_.load() ||
            !ctl->BackoffOrGiveUp(&shutdown_)) {
          err = std::current_exception();
          break;
        }
        ++retries;
      } catch (const Error&) {
        got += step;
        if (step > 0) ctl = std::make_unique<RetryController>(policy_);
        if (shutdown_.load() || !ctl->BackoffOrGiveUp(&shutdown_)) {
          err = std::current_exception();
          break;
        }
        ++retries;
      }
    }
    const uint64_t elapsed_us = telemetry::NowUs() - t0;
    if (retries > 0) RetriedCounter()->Add(static_cast<uint64_t>(retries));
    if (err == nullptr && !degraded_fetch) {
      telemetry::EmitSpan("range.fetch", t0, elapsed_us, len);
    }

    lk.lock();
    range_retries_ += static_cast<uint64_t>(retries);
    if (gen != generation_) {
      // a Seek restarted the carve plan while this fetch was in flight:
      // the bytes are stale — drop them (inflight accounting was reset)
      wasted_bytes_ += len;
      continue;
    }
    inflight_bytes_ -= len;
    if (err != nullptr) {
      if (shutdown_.load()) return;  // dtor-driven abandon, not an error
      if (error_ == nullptr) error_ = err;
      cv_data_.notify_all();
      cv_work_.notify_all();
      return;
    }
    if (degraded_fetch) {
      // the origin ignored Range: hand the stream to the sequential lane
      // (which resumes-at-offset under 200 with its tightened budget);
      // counted once per stream, not once per racing worker
      if (!degraded_) DegradedCounter()->Add(1);
      degraded_ = true;
      cv_data_.notify_all();
      cv_work_.notify_all();
      return;
    }
    if (!degraded_ && !shutdown_.load()) {
      hists_->bytes->Observe(len);
      ++ranges_fetched_;
      landed_[off] = std::move(seg);
      AdaptAfterRangeLocked(len, elapsed_us, retries);
      cv_data_.notify_all();
    }
  }
}

size_t RangeReader::Read(void* ptr, size_t size) {
  if (seq_ != nullptr) return seq_->Read(ptr, size);
  char* out = static_cast<char*>(ptr);
  size_t copied = 0;
  bool go_sequential = false;
  size_t seq_pos = 0;
  {
    std::unique_lock<std::mutex> lk(mu_);
    if (size == 0 || pos_ >= file_size_) return 0;
    if (!started_) StartWorkersLocked();
    while (copied < size && pos_ < file_size_) {
      if (pos_ >= bound_) {
        // the consumer crossed the hint after all: resume carving
        bound_ = static_cast<size_t>(-1);
        cv_work_.notify_all();
      }
      TrimConsumedLocked();
      if (HeadReadyLocked()) {
        auto it = landed_.upper_bound(pos_);
        --it;
        const size_t seg_off = pos_ - it->first;
        const size_t avail = it->second.size - seg_off;
        const size_t n = std::min(size - copied, avail);
        std::memcpy(out + copied, it->second.data.get() + seg_off, n);
        copied += n;
        pos_ += n;
        useful_bytes_ += n;
        if (n == avail) {
          landed_.erase(it);
          cv_work_.notify_all();  // window advanced
        }
        continue;
      }
      if (copied > 0) break;  // serve what landed; short reads are legal
      if (error_ != nullptr) std::rethrow_exception(error_);
      if (degraded_) {
        go_sequential = true;
        seq_pos = pos_;
        break;
      }
      // head-of-line wait: the network is behind the consumer — additive
      // concurrency increase, one step per wait episode
      if (concurrency_ < cfg_.max_concurrency) {
        ++concurrency_;
        SchedConcurrencyGauge()->Set(concurrency_);
        cv_work_.notify_all();
      }
      telemetry::ScopedTimerUs wait_span(hists_->wait_us);
      cv_data_.wait(lk, [this] {
        return shutdown_.load() || error_ != nullptr || degraded_ ||
               HeadReadyLocked();
      });
      if (shutdown_.load()) return copied;
    }
  }
  if (go_sequential) {
    SwitchToSequential(seq_pos);
    return seq_->Read(out, size);
  }
  return copied;
}

size_t RangeReader::Write(const void*, size_t) {
  throw Error(backend_ + " ranged read stream is read-only");
}

void RangeReader::Seek(size_t pos) {
  if (seq_ != nullptr) {
    seq_->Seek(pos);
    return;
  }
  std::lock_guard<std::mutex> lk(mu_);
  if (pos >= bound_) bound_ = static_cast<size_t>(-1);  // hint outlived
  if (pos == pos_) return;
  if (!started_) {
    // the open-then-seek-to-partition-start dance: nothing fetched yet
    pos_ = pos;
    issue_next_ = pos;
    return;
  }
  // Only FORWARD seeks within the carve plan keep it: every claim from
  // pos_ to issue_next_ has either landed or will land, so coverage is
  // contiguous. A backward seek always restarts — a landed segment below
  // pos_ does NOT prove the bytes after it are still coming (a forward
  // seek may have trimmed mid segments as waste while a lower in-flight
  // range landed late; serving from that island would hang the consumer
  // at its end, waiting for a range nobody will ever re-carve).
  if (pos >= pos_ && pos <= issue_next_) {
    pos_ = pos;
    cv_work_.notify_all();
    return;
  }
  // discontinuity: restart the carve plan at the new position; landed and
  // in-flight prefetch is stale (in-flight drops on landing via the
  // generation check)
  ++generation_;
  for (const auto& kv : landed_) wasted_bytes_ += kv.second.size;
  wasted_bytes_ += inflight_bytes_;
  landed_.clear();
  inflight_bytes_ = 0;
  issue_next_ = pos;
  pos_ = pos;
  ++discontinuities_;
  // a seek-thrashing consumer (record-indexed shuffles) turns readahead
  // into pure waste: once discarded prefetch outweighs delivered bytes,
  // hand the stream to the sequential lane for good
  if (discontinuities_ >= 8 && wasted_bytes_ > useful_bytes_) {
    degraded_ = true;
    cv_data_.notify_all();
  }
  cv_work_.notify_all();
}

size_t RangeReader::Tell() {
  if (seq_ != nullptr) return seq_->Tell();
  std::lock_guard<std::mutex> lk(mu_);
  return pos_;
}

void RangeReader::HintReadBound(size_t end) {
  if (seq_ != nullptr) return;  // plain streams ignore the hint
  std::lock_guard<std::mutex> lk(mu_);
  bound_ = end;
  // a tighter bound stops future claims (in-flight ones land harmlessly);
  // a wider one opens the carve plan back up
  cv_work_.notify_all();
}

void RangeReader::SwitchToSequential(size_t pos) {
  seq_.reset(seq_factory_());
  seq_->Seek(pos);
  std::lock_guard<std::mutex> lk(mu_);
  landed_.clear();  // free prefetch memory; workers are exiting
}

RangeReader::Stats RangeReader::stats() {
  std::lock_guard<std::mutex> lk(mu_);
  Stats s;
  s.ranges_fetched = ranges_fetched_;
  s.range_retries = range_retries_;
  s.discontinuities = discontinuities_;
  s.range_bytes = range_bytes_;
  s.concurrency = concurrency_;
  s.degraded = degraded_ || seq_ != nullptr;
  return s;
}

SeekStream* NewRangedOrSequential(
    const char* backend, size_t file_size,
    std::unique_ptr<RangeFetcher> fetcher,
    std::function<SeekStream*()> sequential_factory, const RangeConfig& cfg,
    const RetryPolicy& policy, int timeout_ms_override) {
  if (!cfg.enabled || cfg.max_concurrency <= 1 ||
      file_size < cfg.min_bytes * 2) {
    // too small to split (or switched off): the sequential lane is strictly
    // better — no scheduler, no extra connections
    return sequential_factory();
  }
  return new RangeReader(backend, file_size, std::move(fetcher),
                         std::move(sequential_factory), cfg, policy,
                         timeout_ms_override);
}

}  // namespace io
}  // namespace dct
