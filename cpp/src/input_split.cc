// InputSplit implementation. See input_split.h for the contract; the
// partition-edge rules mirror reference src/io/input_split_base.cc:30-64
// (aligned tiling + same-rule record-head fixup at both edges) and the
// chunking mirrors :221-258 (overflow carry of the partial trailing record).
#include "input_split.h"

#include "fs_fault.h"

#include <algorithm>
#include <cerrno>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <random>

#include "numparse.h"
#include "recordio.h"

namespace dct {

namespace {

// Match a trailing-'*' glob or exact name.
bool GlobMatch(const std::string& pattern, const std::string& name) {
  size_t star = pattern.find('*');
  if (star == std::string::npos) return pattern == name;
  // prefix*suffix
  std::string prefix = pattern.substr(0, star);
  std::string suffix = pattern.substr(star + 1);
  if (name.size() < prefix.size() + suffix.size()) return false;
  return name.compare(0, prefix.size(), prefix) == 0 &&
         name.compare(name.size() - suffix.size(), suffix.size(), suffix) == 0;
}

std::string BaseName(const std::string& path) {
  size_t slash = path.find_last_of('/');
  return slash == std::string::npos ? path : path.substr(slash + 1);
}

}  // namespace

// --------------------------------------------------------------------------
// Expand ';'-separated URIs; directories list their contents; a '*' in the
// last path component globs within its directory
// (reference input_split_base.cc:96-147 InitInputFileInfo).
std::vector<FileInfo> ExpandFileList(const std::string& uri,
                                     bool recurse_directories) {
  std::vector<FileInfo> files_;
  for (const std::string& piece : StrSplit(uri, ';')) {
    if (piece.empty()) continue;
    URI u(piece);
    FileSystem* fs = FileSystem::GetInstance(u);
    std::string base = BaseName(u.path);
    if (base.find('*') != std::string::npos) {
      URI dir = u;
      size_t slash = u.path.find_last_of('/');
      dir.path = slash == std::string::npos ? "." : u.path.substr(0, slash);
      std::vector<FileInfo> listing;
      fs->ListDirectory(dir, &listing);
      std::sort(listing.begin(), listing.end(),
                [](const FileInfo& a, const FileInfo& b) {
                  return a.path.path < b.path.path;
                });
      for (const FileInfo& info : listing) {
        if (info.type == FileType::kFile && info.size != 0 &&
            GlobMatch(base, BaseName(info.path.path))) {
          files_.push_back(info);
        }
      }
      continue;
    }
    FileInfo info = fs->GetPathInfo(u);
    if (info.type == FileType::kDirectory) {
      std::vector<FileInfo> listing;
      if (recurse_directories) {
        fs->ListDirectoryRecursive(info.path, &listing);
      } else {
        fs->ListDirectory(info.path, &listing);
      }
      std::sort(listing.begin(), listing.end(),
                [](const FileInfo& a, const FileInfo& b) {
                  return a.path.path < b.path.path;
                });
      for (const FileInfo& f : listing) {
        std::string name = BaseName(f.path.path);
        if (f.type == FileType::kFile && f.size != 0 && !name.empty() &&
            name[0] != '.' && name[0] != '_') {
          files_.push_back(f);
        }
      }
    } else if (info.size != 0) {
      files_.push_back(info);
    }
  }
  DCT_CHECK(!files_.empty()) << "no non-empty input files match uri: " << uri;
  return files_;
}

namespace {
// Default read-chunk size, env-tunable (DCT_CHUNK_SIZE_KB). Chunk size
// trades per-chunk overhead against how finely prefetch/parse/consume
// overlap and how quickly the recycled-buffer pools warm up. 2 MB beats
// the earlier 8 MB by ~11% e2e on the 1-core bench host (A/B-interleaved,
// cpp/test/bench_pipeline.cc): a chunk plus its parsed CSR output stays
// cache-resident and short files see the recycle pools warm after the
// first few chunks instead of never.
size_t DefaultChunkSize() {
  const char* v = std::getenv("DCT_CHUNK_SIZE_KB");
  if (v != nullptr && *v != '\0') {
    char* end = nullptr;
    errno = 0;
    long kb = std::strtol(v, &end, 10);
    // bounded like parse_uarg: [64 KB, 1 GB]; anything else (junk,
    // overflow, tiny) falls back to the default instead of wrapping
    // through the shift into an absurd resize
    if (errno == 0 && end != v && *end == '\0' && kb >= 64 &&
        kb <= (1L << 20)) {
      return static_cast<size_t>(kb) << 10;
    }
  }
  return size_t(2) << 20;
}
}  // namespace

ByteSplit::ByteSplit(const std::string& uri, unsigned align_bytes,
                     bool is_text, bool recurse_directories)
    : chunk_size_(DefaultChunkSize()),
      align_bytes_(align_bytes),
      is_text_(is_text) {
  files_ = ExpandFileList(uri, recurse_directories);
  file_start_.resize(files_.size());
  size_t acc = 0;
  for (size_t i = 0; i < files_.size(); ++i) {
    file_start_[i] = acc;
    acc += files_[i].size;
  }
  total_size_ = acc;
}

void ByteSplit::ResetPartition(unsigned rank, unsigned nsplit) {
  DCT_CHECK_LT(rank, nsplit) << "part index out of range";
  rank_ = rank;
  nsplit_ = nsplit;
  size_t nstep = (total_size_ + nsplit - 1) / nsplit;
  nstep = (nstep + align_bytes_ - 1) / align_bytes_ * align_bytes_;
  size_t raw_begin = std::min(total_size_, nstep * rank);
  size_t raw_end = std::min(total_size_, nstep * (rank + 1));
  begin_ = GlobalBoundaryFixup(raw_begin);
  end_ = GlobalBoundaryFixup(raw_end);
  BeforeFirst();
}

size_t ByteSplit::GlobalBoundaryFixup(size_t ofs) {
  if (ofs == 0 || ofs >= total_size_) return std::min(ofs, total_size_);
  // file containing ofs
  size_t k =
      std::upper_bound(file_start_.begin(), file_start_.end(), ofs) -
      file_start_.begin() - 1;
  if (ofs == file_start_[k]) return ofs;  // a file start is a record head
  size_t local = ofs - file_start_[k];
  std::unique_ptr<SeekStream> s(
      FileSystem::GetInstance(files_[k].path)->OpenForRead(files_[k].path));
  s->Seek(local);
  // boundary probe: usually scans at most one record — no point letting a
  // readahead stream prefetch a whole window for it (the hint re-extends
  // automatically in the rare longer scan)
  s->HintReadBound(std::min(local + (64 << 10), files_[k].size));
  size_t consumed = SeekRecordHead(s.get(), local, files_[k].size);
  return std::min(file_start_[k] + local + consumed,
                  file_start_[k] + files_[k].size);
}

void ByteSplit::BeforeFirst() {
  // position the read cursor at begin_
  size_t k = files_.empty()
                 ? 0
                 : static_cast<size_t>(
                       std::upper_bound(file_start_.begin(), file_start_.end(),
                                        begin_) -
                       file_start_.begin()) -
                       1;
  if (begin_ >= total_size_ && !files_.empty()) k = files_.size() - 1;
  file_idx_ = k;
  local_pos_ = begin_ - file_start_[k];
  cur_stream_.reset();
  prev_byte_ = '\n';
  pending_newline_ = false;
  overflow_.clear();
  chunk_.clear();
  cursor_ = 0;
  exhausted_ = false;
}

size_t ByteSplit::ReadSpan(char* buf, size_t want) {
  size_t got = 0;
  while (got < want) {
    if (pending_newline_) {
      buf[got++] = '\n';
      pending_newline_ = false;
      continue;
    }
    size_t global = file_start_[file_idx_] + local_pos_;
    if (global >= end_) break;
    if (local_pos_ >= files_[file_idx_].size) {
      // advance to next file; inject newline between text files when the
      // previous file did not end with one (NOEOL rule,
      // reference input_split_base.cc:195-199, dmlc PRs 385/452)
      cur_stream_.reset();
      bool more = file_idx_ + 1 < files_.size() &&
                  file_start_[file_idx_ + 1] < end_;
      if (is_text_ && prev_byte_ != '\n' && more) pending_newline_ = true;
      if (!more) break;
      ++file_idx_;
      local_pos_ = 0;
      prev_byte_ = '\n';
      continue;
    }
    if (cur_stream_ == nullptr) {
      cur_stream_.reset(FileSystem::GetInstance(files_[file_idx_].path)
                            ->OpenForRead(files_[file_idx_].path));
      cur_stream_->Seek(local_pos_);
      // this partition never reads past end_ in this file: a readahead
      // stream must not prefetch a window past the partition edge
      cur_stream_->HintReadBound(std::min(
          files_[file_idx_].size, end_ - file_start_[file_idx_]));
    }
    size_t to_read = std::min(
        {want - got, files_[file_idx_].size - local_pos_, end_ - global});
    size_t n = cur_stream_->Read(buf + got, to_read);
    DCT_CHECK_GT(n, size_t(0))
        << "file " << files_[file_idx_].path.Str()
        << " shorter than listed size";
    local_pos_ += n;
    got += n;
    prev_byte_ = buf[got - 1];
  }
  return got;
}

bool ByteSplit::FillChunkBuffer(std::vector<char>* buf) {
  if (exhausted_ && overflow_.empty()) return false;
  buf->clear();
  buf->swap(overflow_);  // carried partial record heads the new chunk
  size_t target = buf->size() + chunk_size_;
  while (true) {
    size_t old = buf->size();
    buf->resize(target);
    size_t n = ReadSpan(buf->data() + old, target - old);
    buf->resize(old + n);
    if (n < target - old) exhausted_ = true;
    if (buf->empty()) return false;
    if (exhausted_) {
      // partition end is a record head: everything left is whole records
      break;
    }
    size_t boundary = FindLastRecordHead(buf->data(),
                                         buf->data() + buf->size());
    if (boundary == 0) {
      // no record boundary in sight: grow the chunk
      // (reference input_split_base.cc Chunk::Append)
      target = buf->size() + chunk_size_;
      continue;
    }
    overflow_.assign(buf->begin() + boundary, buf->end());
    buf->resize(boundary);
    break;
  }
  return true;
}

bool ByteSplit::NextChunk(Blob* out) {
  if (!FillChunkBuffer(&chunk_)) return false;
  out->dptr = chunk_.data();
  out->size = chunk_.size();
  cursor_ = chunk_.size();  // chunk handed out wholesale
  return true;
}

bool ByteSplit::NextRecord(Blob* out) {
  while (true) {
    if (cursor_ < chunk_.size() &&
        ExtractRecordAt(chunk_.data(), chunk_.size(), &cursor_, out)) {
      return true;
    }
    if (!FillChunkBuffer(&chunk_)) return false;
    cursor_ = 0;
  }
}

// --------------------------------------------------------------------------
LineSplit::LineSplit(const std::string& uri, unsigned part, unsigned nsplit,
                     bool recurse_directories)
    : ByteSplit(uri, /*align_bytes=*/1, /*is_text=*/true,
                recurse_directories) {
  ResetPartition(part, nsplit);
}

size_t LineSplit::SeekRecordHead(SeekStream* s, size_t local_pos,
                                 size_t file_size) {
  // consume bytes until just past the next '\n'; EOF counts as a head
  char buf[1024];
  size_t consumed = 0;
  while (true) {
    size_t n = s->Read(buf, sizeof(buf));
    if (n == 0) return consumed;  // NOEOL tail: boundary at file end
    const char* nl = static_cast<const char*>(std::memchr(buf, '\n', n));
    if (nl != nullptr) {
      return consumed + static_cast<size_t>(nl - buf) + 1;
    }
    consumed += n;
  }
}

size_t LineSplit::FindLastRecordHead(const char* begin, const char* end) {
  for (const char* p = end; p != begin;) {
    --p;
    if (*p == '\n') return static_cast<size_t>(p - begin) + 1;
  }
  return 0;
}

bool LineSplit::ExtractRecordAt(char* data, size_t valid, size_t* cursor,
                                Blob* out) {
  if (*cursor >= valid) return false;
  char* line = data + *cursor;
  size_t remain = valid - *cursor;
  char* nl = static_cast<char*>(std::memchr(line, '\n', remain));
  size_t len = (nl == nullptr) ? remain : static_cast<size_t>(nl - line);
  *cursor += len + (nl == nullptr ? 0 : 1);
  if (len > 0 && line[len - 1] == '\r') --len;  // CRLF
  out->dptr = line;
  out->size = len;
  return true;
}

// --------------------------------------------------------------------------
SingleFileSplit::SingleFileSplit(const std::string& uri) : uri_(uri) {
  stream_.reset(Stream::Create(uri, "r"));
}

void SingleFileSplit::BeforeFirst() {
  DCT_CHECK(uri_ != "stdin" || (valid_ == 0 && exhausted_ == false))
      << "stdin cannot be rewound";
  if (uri_ != "stdin") stream_.reset(Stream::Create(uri_, "r"));
  chunk_.clear();
  valid_ = cursor_ = 0;
  exhausted_ = false;
}

void SingleFileSplit::ResetPartition(unsigned rank, unsigned nsplit) {
  DCT_CHECK(rank == 0 && nsplit == 1)
      << "SingleFileSplit (stdin / single pipe) cannot be partitioned";
  BeforeFirst();
}

size_t SingleFileSplit::GetTotalSize() {
  if (uri_ == "stdin") return 0;  // unknowable on a pipe
  URI u(uri_);
  return FileSystem::GetInstance(u)->GetPathInfo(u).size;
}

bool SingleFileSplit::FillChunk() {
  if (exhausted_) return false;
  // carry bytes past `valid_` (a partial trailing line) to the front
  chunk_.erase(chunk_.begin(), chunk_.begin() + valid_);
  cursor_ = 0;
  size_t have = chunk_.size();
  chunk_.resize(have + chunk_size_);
  size_t n = stream_->Read(chunk_.data() + have, chunk_size_);
  chunk_.resize(have + n);
  if (n < chunk_size_) {
    exhausted_ = true;
    if (!chunk_.empty() && chunk_.back() != '\n') chunk_.push_back('\n');
    valid_ = chunk_.size();
    return valid_ != 0;
  }
  // grow byte-by-byte until the chunk ends on a line boundary
  while (!chunk_.empty() && chunk_.back() != '\n') {
    char c;
    if (stream_->Read(&c, 1) != 1) {
      exhausted_ = true;
      chunk_.push_back('\n');
      break;
    }
    chunk_.push_back(c);
  }
  valid_ = chunk_.size();
  return valid_ != 0;
}

bool SingleFileSplit::NextRecord(Blob* out) {
  while (true) {
    if (cursor_ < valid_) {
      char* line = chunk_.data() + cursor_;
      char* nl = static_cast<char*>(
          std::memchr(line, '\n', valid_ - cursor_));
      size_t len = (nl == nullptr) ? valid_ - cursor_
                                   : static_cast<size_t>(nl - line);
      cursor_ += len + (nl == nullptr ? 0 : 1);
      if (len > 0 && line[len - 1] == '\r') --len;  // CRLF
      out->dptr = line;
      out->size = len;
      return true;
    }
    if (!FillChunk()) return false;
  }
}

bool SingleFileSplit::NextChunk(Blob* out) {
  if (cursor_ >= valid_ && !FillChunk()) return false;
  out->dptr = chunk_.data() + cursor_;
  out->size = valid_ - cursor_;
  cursor_ = valid_;
  return true;
}

// --------------------------------------------------------------------------
RecordIOSplit::RecordIOSplit(const std::string& uri, unsigned part,
                             unsigned nsplit, bool recurse_directories)
    : ByteSplit(uri, /*align_bytes=*/4, /*is_text=*/false,
                recurse_directories) {
  ResetPartition(part, nsplit);
}

size_t RecordIOSplit::SeekRecordHead(SeekStream* s, size_t local_pos,
                                     size_t file_size) {
  // scan forward from the next 4-aligned offset for magic + cflag in {0,1}
  size_t aligned = recordio::AlignUp4(local_pos);
  if (aligned + 8 > file_size) return file_size - local_pos;
  s->Seek(aligned);
  std::vector<char> buf(size_t(1) << 16);
  size_t have = 0;       // valid bytes in buf
  size_t base = aligned;  // absolute file offset of buf[0] (4-aligned)
  while (true) {
    size_t n = s->Read(buf.data() + have, buf.size() - have);
    have += n;
    for (size_t i = 0; i + 8 <= have; i += 4) {
      if (recordio::IsRecordHead(buf.data() + i)) {
        return base + i - local_pos;
      }
    }
    if (n == 0) return file_size - local_pos;  // no head: file end
    // retain the unverified tail (first aligned i with i + 8 > have)
    size_t first_unchecked = have >= 8 ? recordio::AlignUp4(have - 7) : 0;
    size_t keep = have - first_unchecked;
    std::memmove(buf.data(), buf.data() + first_unchecked, keep);
    base += first_unchecked;
    have = keep;
  }
}

size_t RecordIOSplit::FindLastRecordHead(const char* begin, const char* end) {
  size_t size = static_cast<size_t>(end - begin) & ~size_t(3);
  for (size_t ofs = size >= 8 ? size - 8 : 0;; ofs -= 4) {
    if (ofs == 0) return 0;
    if (recordio::IsRecordHead(begin + ofs)) return ofs;
    if (ofs < 4) return 0;
  }
}

// Shared recordio frame extraction (multi-part reassembly into *assembled).
bool ExtractRecordIOFrame(char* data, size_t valid, size_t* cursor,
                          InputSplit::Blob* out, std::string* assembled) {
  if (*cursor + 8 > valid) {
    *cursor = valid;
    return false;
  }
  std::string& assembled_ = *assembled;
  assembled_.clear();
  bool multipart = false;
  while (true) {
    DCT_CHECK_LE(*cursor + 8, valid) << "truncated recordio chunk";
    uint32_t magic = recordio::LoadWordLE(data + *cursor);
    DCT_CHECK_EQ(magic, recordio::kMagic) << "bad recordio magic in chunk";
    uint32_t lrec = recordio::LoadWordLE(data + *cursor + 4);
    uint32_t cflag = recordio::HeaderFlag(lrec);
    uint32_t len = recordio::HeaderLen(lrec);
    size_t payload = *cursor + 8;
    DCT_CHECK_LE(payload + recordio::AlignUp4(len), valid)
        << "recordio record overruns chunk";
    *cursor = payload + recordio::AlignUp4(len);
    if (cflag == 0) {
      DCT_CHECK(!multipart) << "unexpected cflag=0 inside multi-part record";
      out->dptr = data + payload;
      out->size = len;
      return true;
    }
    if (cflag == 1) {
      DCT_CHECK(!multipart) << "unexpected cflag=1 inside multi-part record";
      multipart = true;
      assembled_.assign(data + payload, len);
    } else {
      DCT_CHECK(multipart) << "continuation part without a head";
      char magic_bytes[4];
      uint32_t m = recordio::kMagic;
      if (!serial::NativeIsLE()) m = serial::ByteSwap(m);
      std::memcpy(magic_bytes, &m, 4);
      assembled_.append(magic_bytes, 4);
      assembled_.append(data + payload, len);
      if (cflag == 3) {
        out->dptr = assembled_.data();
        out->size = assembled_.size();
        return true;
      }
      DCT_CHECK_EQ(cflag, 2u) << "invalid recordio cflag";
    }
  }
}

bool RecordIOSplit::ExtractRecordAt(char* data, size_t valid, size_t* cursor,
                                    Blob* out) {
  return ExtractRecordIOFrame(data, valid, cursor, out, &assembled_);
}

// --------------------------------------------------------------------------
// IndexedRecordIOSplit
IndexedRecordIOSplit::IndexedRecordIOSplit(
    const std::string& uri, const std::string& index_uri, unsigned part,
    unsigned nsplit, size_t batch_size, bool shuffle, int seed,
    bool recurse_directories)
    : batch_size_(std::max<size_t>(batch_size, 1)),
      shuffle_(shuffle),
      seed_(seed) {
  files_ = ExpandFileList(uri, recurse_directories);
  file_start_.resize(files_.size());
  size_t acc = 0;
  for (size_t i = 0; i < files_.size(); ++i) {
    file_start_[i] = acc;
    acc += files_[i].size;
  }
  total_size_ = acc;
  // index file: text `record_index byte_offset` pairs; offsets sorted and
  // differenced into (offset, size) records
  // (reference indexed_recordio_split.cc:43-62)
  std::vector<FileInfo> idx_files = ExpandFileList(index_uri, false);
  DCT_CHECK_EQ(idx_files.size(), size_t(1))
      << "indexed_recordio supports exactly one index file";
  std::unique_ptr<SeekStream> fi(
      FileSystem::GetInstance(idx_files[0].path)
          ->OpenForRead(idx_files[0].path));
  std::string text(idx_files[0].size, '\0');
  fi->ReadExact(&text[0], text.size());
  std::vector<size_t> offsets;
  const char* p = text.data();
  const char* end = p + text.size();
  while (p < end) {
    uint64_t idx_v, ofs_v;
    while (p < end && (*p == ' ' || *p == '\n' || *p == '\r' || *p == '\t'))
      ++p;
    if (p >= end) break;
    const char* q;
    DCT_CHECK(ParseNum<uint64_t>(p, end, &q, &idx_v)) << "bad index file";
    p = q;
    while (p < end && (*p == ' ' || *p == '\t')) ++p;
    DCT_CHECK(ParseNum<uint64_t>(p, end, &q, &ofs_v)) << "bad index file";
    p = q;
    offsets.push_back(ofs_v);
  }
  DCT_CHECK(!offsets.empty()) << "empty index file " << index_uri;
  std::sort(offsets.begin(), offsets.end());
  for (size_t j = 0; j + 1 < offsets.size(); ++j) {
    index_.emplace_back(offsets[j], offsets[j + 1] - offsets[j]);
  }
  index_.emplace_back(offsets.back(), total_size_ - offsets.back());
  ResetPartition(part, nsplit);
}

void IndexedRecordIOSplit::ResetPartition(unsigned rank, unsigned nsplit) {
  DCT_CHECK_LT(rank, nsplit) << "part index out of range";
  // partition BY RECORD COUNT, not bytes
  // (reference indexed_recordio_split.cc:12-41)
  size_t n = index_.size();
  size_t step = (n + nsplit - 1) / nsplit;
  lo_ = std::min(n, step * rank);
  hi_ = std::min(n, step * (rank + 1));
  epoch_ = 0;
  BeforeFirst();
}

void IndexedRecordIOSplit::BeforeFirst() {
  order_.resize(hi_ - lo_);
  for (size_t i = 0; i < order_.size(); ++i) order_[i] = lo_ + i;
  if (shuffle_) {
    // fresh permutation every epoch (reference BeforeFirst reshuffle,
    // kRandMagic = 111)
    std::mt19937 rng(111 + seed_ + static_cast<int>(epoch_));
    std::shuffle(order_.begin(), order_.end(), rng);
    ++epoch_;
  }
  next_rec_ = 0;
  chunk_.clear();
  cursor_ = 0;
}

void IndexedRecordIOSplit::ReadSpanAt(size_t global_ofs, char* dst,
                                      size_t size) {
  size_t k =
      std::upper_bound(file_start_.begin(), file_start_.end(), global_ofs) -
      file_start_.begin() - 1;
  size_t local = global_ofs - file_start_[k];
  while (size != 0) {
    DCT_CHECK_LT(k, files_.size()) << "record extends past data";
    if (open_file_ != k || open_stream_ == nullptr) {
      open_stream_.reset(FileSystem::GetInstance(files_[k].path)
                             ->OpenForRead(files_[k].path));
      open_file_ = k;
    }
    open_stream_->Seek(local);
    size_t take = std::min(size, files_[k].size - local);
    // record-exact span: prefetching past it would be discarded by the
    // next (possibly shuffled) seek anyway
    open_stream_->HintReadBound(local + take);
    open_stream_->ReadExact(dst, take);
    dst += take;
    size -= take;
    ++k;
    local = 0;
  }
}

bool IndexedRecordIOSplit::FillChunkBuffer(std::vector<char>* buf) {
  if (next_rec_ >= order_.size()) return false;
  buf->clear();
  size_t end_rec = std::min(order_.size(), next_rec_ + batch_size_);
  for (; next_rec_ < end_rec; ++next_rec_) {
    const auto& rec = index_[order_[next_rec_]];
    size_t old = buf->size();
    buf->resize(old + rec.second);
    ReadSpanAt(rec.first, buf->data() + old, rec.second);
  }
  return true;
}

bool IndexedRecordIOSplit::ExtractRecordAt(char* data, size_t valid,
                                           size_t* cursor, Blob* out) {
  return ExtractRecordIOFrame(data, valid, cursor, out, &assembled_);
}

bool IndexedRecordIOSplit::NextChunk(Blob* out) {
  if (!FillChunkBuffer(&chunk_)) return false;
  out->dptr = chunk_.data();
  out->size = chunk_.size();
  cursor_ = chunk_.size();
  return true;
}

bool IndexedRecordIOSplit::NextRecord(Blob* out) {
  while (true) {
    if (cursor_ < chunk_.size() &&
        ExtractRecordAt(chunk_.data(), chunk_.size(), &cursor_, out)) {
      return true;
    }
    if (!FillChunkBuffer(&chunk_)) return false;
    cursor_ = 0;
  }
}

// --------------------------------------------------------------------------
// CachedSplit
namespace {
constexpr uint64_t kCacheMagic = 0x44435443414348; // "DCTCACH"

uint64_t FingerprintHash(const std::string& s) {
  uint64_t h = 1469598103934665603ull;  // FNV-1a
  for (unsigned char c : s) {
    h ^= c;
    h *= 1099511628211ull;
  }
  return h;
}

void WriteU64(Stream* s, uint64_t v) {
  if (!serial::NativeIsLE()) v = serial::ByteSwap(v);
  s->Write(&v, 8);
}

bool ReadU64(Stream* s, uint64_t* v) {
  if (s->Read(v, 8) != 8) return false;
  if (!serial::NativeIsLE()) *v = serial::ByteSwap(*v);
  return true;
}
}  // namespace

CachedSplit::CachedSplit(InputSplit* base, RecordChunkSource* base_src,
                         const std::string& cache_file,
                         const std::string& fingerprint)
    : base_(base),
      base_src_(base_src),
      cache_file_(cache_file),
      fingerprint_(FingerprintHash(fingerprint)) {
  // a completed cache from an earlier run is replayed only when its header
  // matches this (uri, part, nsplit) — a stale cache for another partition
  // must not silently serve the wrong shard
  std::unique_ptr<SeekStream> probe(
      SeekStream::CreateForRead(cache_file_, /*allow_null=*/true));
  if (probe != nullptr) {
    uint64_t magic = 0, fp = 0;
    if (ReadU64(probe.get(), &magic) && magic == kCacheMagic &&
        ReadU64(probe.get(), &fp) && fp == fingerprint_) {
      cache_reader_ = std::move(probe);
      replaying_ = true;
    } else {
      std::remove(cache_file_.c_str());  // stale or foreign cache
    }
  }
}

CachedSplit::~CachedSplit() = default;

void CachedSplit::FinalizeCache() {
  // publish ONLY a complete first pass; a partial .tmp would silently
  // truncate the dataset for every later epoch and process
  if (cache_writer_ == nullptr) return;
  cache_writer_.reset();
  std::string tmp = cache_file_ + ".tmp";
  if (!write_complete_) {
    std::remove(tmp.c_str());
    return;
  }
  // injectable publish (fs_fault.h): a failed/torn rename surfaces as a
  // structured error with errno instead of a bare check string. The
  // DESTINATION is removed first: a torn half-copy keeps the 16-byte
  // magic+fingerprint probe valid, so leaving it would wedge every later
  // epoch/process mid-replay — deleting it makes the failure a clean
  // first-pass re-parse instead (the shard cache gets this from
  // manifest-last publishing; this single-file format has no manifest).
  if (fsio::Rename(tmp.c_str(), cache_file_.c_str()) != 0) {
    const int err = errno != 0 ? errno : EIO;
    std::remove(cache_file_.c_str());
    std::remove(tmp.c_str());
    throw fsio::FsError(fsio::FsOp::kRename, cache_file_, err);
  }
}

bool CachedSplit::FillChunkBuffer(std::vector<char>* buf) {
  if (replaying_) {
    uint64_t size;
    size_t n = cache_reader_->Read(&size, 8);
    if (n == 0) return false;
    DCT_CHECK_EQ(n, size_t(8))
        << "corrupt chunk cache (truncated header): " << cache_file_;
    if (!serial::NativeIsLE()) size = serial::ByteSwap(size);
    buf->resize(size);
    cache_reader_->ReadExact(buf->data(), size);
    return true;
  }
  if (!base_src_->FillChunkBuffer(buf)) {
    write_complete_ = true;
    FinalizeCache();
    return false;
  }
  if (cache_writer_ == nullptr) {
    cache_writer_.reset(Stream::Create(cache_file_ + ".tmp", "w"));
    WriteU64(cache_writer_.get(), kCacheMagic);
    WriteU64(cache_writer_.get(), fingerprint_);
  }
  uint64_t size = buf->size();
  if (!serial::NativeIsLE()) size = serial::ByteSwap(size);
  cache_writer_->Write(&size, 8);
  cache_writer_->Write(buf->data(), buf->size());
  return true;
}

bool CachedSplit::ExtractRecordAt(char* data, size_t valid, size_t* cursor,
                                  Blob* out) {
  return base_src_->ExtractRecordAt(data, valid, cursor, out);
}

void CachedSplit::BeforeFirst() {
  FinalizeCache();  // publishes only when the first pass completed
  write_complete_ = false;
  std::unique_ptr<SeekStream> probe(
      SeekStream::CreateForRead(cache_file_, /*allow_null=*/true));
  uint64_t magic = 0, fp = 0;
  if (probe != nullptr && ReadU64(probe.get(), &magic) &&
      magic == kCacheMagic && ReadU64(probe.get(), &fp) &&
      fp == fingerprint_) {
    cache_reader_ = std::move(probe);
    replaying_ = true;
  } else {
    replaying_ = false;
    cache_reader_.reset();
    base_->BeforeFirst();
  }
  chunk_.clear();
  cursor_ = 0;
}

bool CachedSplit::NextChunk(Blob* out) {
  if (!FillChunkBuffer(&chunk_)) return false;
  out->dptr = chunk_.data();
  out->size = chunk_.size();
  cursor_ = chunk_.size();
  return true;
}

bool CachedSplit::NextRecord(Blob* out) {
  while (true) {
    if (cursor_ < chunk_.size() &&
        ExtractRecordAt(chunk_.data(), chunk_.size(), &cursor_, out)) {
      return true;
    }
    if (!FillChunkBuffer(&chunk_)) return false;
    cursor_ = 0;
  }
}

void CachedSplit::ResetPartition(unsigned rank, unsigned nsplit) {
  // the cache is partition-specific; drop it and start over
  cache_writer_.reset();
  cache_reader_.reset();
  std::remove((cache_file_ + ".tmp").c_str());
  std::remove(cache_file_.c_str());
  replaying_ = false;
  write_complete_ = false;
  base_->ResetPartition(rank, nsplit);
  chunk_.clear();
  cursor_ = 0;
}

// --------------------------------------------------------------------------
// ShuffleSplit
ShuffleSplit::ShuffleSplit(InputSplit* base, unsigned part, unsigned nsplit,
                           unsigned num_shuffle_parts, int seed)
    : base_(base),
      part_(part),
      nsplit_(nsplit),
      num_shuffle_parts_(std::max(num_shuffle_parts, 1u)),
      seed_(seed) {
  BeforeFirst();
}

void ShuffleSplit::BeforeFirst() {
  order_.resize(num_shuffle_parts_);
  for (unsigned i = 0; i < num_shuffle_parts_; ++i) order_[i] = i;
  if (num_shuffle_parts_ > 1) {
    std::mt19937 rng(111 + seed_ + static_cast<int>(part_) * 997 +
                     static_cast<int>(epoch_));
    std::shuffle(order_.begin(), order_.end(), rng);
    ++epoch_;
    cur_ = 0;
    base_->ResetPartition(part_ * num_shuffle_parts_ + order_[0],
                          nsplit_ * num_shuffle_parts_);
  } else {
    base_->BeforeFirst();
  }
}

bool ShuffleSplit::AdvanceSubPart() {
  if (num_shuffle_parts_ <= 1 || cur_ + 1 >= num_shuffle_parts_) return false;
  ++cur_;
  base_->ResetPartition(part_ * num_shuffle_parts_ + order_[cur_],
                        nsplit_ * num_shuffle_parts_);
  return true;
}

bool ShuffleSplit::NextRecord(Blob* out) {
  while (!base_->NextRecord(out)) {
    if (!AdvanceSubPart()) return false;
  }
  return true;
}

bool ShuffleSplit::NextChunk(Blob* out) {
  while (!base_->NextChunk(out)) {
    if (!AdvanceSubPart()) return false;
  }
  return true;
}

void ShuffleSplit::ResetPartition(unsigned rank, unsigned nsplit) {
  part_ = rank;
  nsplit_ = nsplit;
  epoch_ = 0;
  BeforeFirst();
}

// --------------------------------------------------------------------------
PrefetchSplit::PrefetchSplit(InputSplit* base, RecordChunkSource* src,
                             size_t capacity)
    : base_(base), src_(src), pipe_(capacity) {}

PrefetchSplit::~PrefetchSplit() {
  if (current_ != nullptr) pipe_.Recycle(&current_);
  pipe_.Shutdown();
}

void PrefetchSplit::EnsureStarted() {
  if (started_) return;
  pipe_.Init(
      [this](Cell** cell) {
        if (*cell == nullptr) *cell = new Cell();
        (*cell)->cursor = 0;
        return src_->FillChunkBuffer(&(*cell)->data);
      },
      [this] { src_->SourceBeforeFirst(); });
  started_ = true;
}

void PrefetchSplit::BeforeFirst() {
  if (current_ != nullptr) pipe_.Recycle(&current_);
  if (started_) {
    pipe_.BeforeFirst();
  } else {
    // the pipeline starts producing from the source's CURRENT state
    // (PipelineIter::Init does not rewind), so an unstarted BeforeFirst
    // must walk the source chain synchronously — shuffled splits resample
    // their permutation here, which a pinned SetShuffleEpoch relies on
    src_->SourceBeforeFirst();
  }
}

bool PrefetchSplit::NextChunk(Blob* out) {
  EnsureStarted();
  if (current_ != nullptr) pipe_.Recycle(&current_);
  if (!pipe_.Next(&current_)) return false;
  out->dptr = current_->data.data();
  out->size = current_->data.size();
  current_->cursor = current_->data.size();
  return true;
}

bool PrefetchSplit::NextRecord(Blob* out) {
  EnsureStarted();
  while (true) {
    if (current_ != nullptr &&
        src_->ExtractRecordAt(current_->data.data(), current_->data.size(),
                              &current_->cursor, out)) {
      return true;
    }
    if (current_ != nullptr) pipe_.Recycle(&current_);
    if (!pipe_.Next(&current_)) return false;
  }
}

void PrefetchSplit::ResetPartition(unsigned rank, unsigned nsplit) {
  if (current_ != nullptr) pipe_.Recycle(&current_);
  pipe_.Shutdown();
  started_ = false;
  base_->ResetPartition(rank, nsplit);
}

InputSplit* InputSplit::Create(const std::string& uri, unsigned part,
                               unsigned nsplit, const std::string& type,
                               const std::string& index_uri, bool shuffle,
                               int seed, size_t batch_size,
                               bool recurse_directories, bool threaded,
                               const std::string& cache_file,
                               unsigned shuffle_parts) {
  DCT_CHECK(shuffle == false || type == "indexed_recordio")
      << "record shuffle requires type=indexed_recordio "
         "(use shuffle_parts for coarse shuffling)";
  DCT_CHECK(cache_file.empty() || shuffle_parts <= 1)
      << "cache_file cannot be combined with shuffle_parts: sub-part resets "
         "would invalidate the cache every epoch";
  if (uri == "stdin") {
    // single-pipe fallback (reference src/io.cc:94-96): no partitioning,
    // no cache, no prefetch wrapper
    DCT_CHECK(type == "text") << "stdin input must be type=text";
    DCT_CHECK(part == 0 && nsplit == 1) << "stdin cannot be partitioned";
    DCT_CHECK(cache_file.empty() && shuffle_parts <= 1)
        << "stdin cannot be cached or shuffled (it cannot be rewound)";
    return new SingleFileSplit(uri);
  }
  InputSplit* split;
  RecordChunkSource* src;
  if (type == "text") {
    auto* b = new LineSplit(uri, part, nsplit, recurse_directories);
    split = b;
    src = b;
  } else if (type == "recordio") {
    auto* b = new RecordIOSplit(uri, part, nsplit, recurse_directories);
    split = b;
    src = b;
  } else if (type == "indexed_recordio") {
    DCT_CHECK(!index_uri.empty())
        << "indexed_recordio requires an index uri";
    auto* b = new IndexedRecordIOSplit(uri, index_uri, part, nsplit,
                                       batch_size, shuffle, seed,
                                       recurse_directories);
    split = b;
    src = b;
  } else {
    throw Error("unknown input split type: " + type);
  }
  if (!cache_file.empty()) {
    // per-part cache naming for raw (non-URISpec) callers, matching the
    // URISpec `.splitN.partK` convention (reference uri_spec.h:42-57)
    std::string cf = cache_file;
    if (nsplit != 1 && cf.find(".split") == std::string::npos) {
      cf += ".split" + std::to_string(nsplit) + ".part" +
            std::to_string(part);
    }
    std::string fingerprint = uri + "|" + std::to_string(part) + "|" +
                              std::to_string(nsplit) + "|" + type;
    auto* c = new CachedSplit(split, src, cf, fingerprint);
    split = c;
    src = c;
  }
  if (threaded) {
    split = new PrefetchSplit(split, src, 2);
  }
  if (shuffle_parts > 1) {
    split = new ShuffleSplit(split, part, nsplit, shuffle_parts, seed);
  }
  return split;
}

}  // namespace dct
