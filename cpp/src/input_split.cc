// InputSplit implementation. See input_split.h for the contract; the
// partition-edge rules mirror reference src/io/input_split_base.cc:30-64
// (aligned tiling + same-rule record-head fixup at both edges) and the
// chunking mirrors :221-258 (overflow carry of the partial trailing record).
#include "input_split.h"

#include <algorithm>
#include <cstring>

#include "recordio.h"

namespace dct {

namespace {

// Match a trailing-'*' glob or exact name.
bool GlobMatch(const std::string& pattern, const std::string& name) {
  size_t star = pattern.find('*');
  if (star == std::string::npos) return pattern == name;
  // prefix*suffix
  std::string prefix = pattern.substr(0, star);
  std::string suffix = pattern.substr(star + 1);
  if (name.size() < prefix.size() + suffix.size()) return false;
  return name.compare(0, prefix.size(), prefix) == 0 &&
         name.compare(name.size() - suffix.size(), suffix.size(), suffix) == 0;
}

std::string BaseName(const std::string& path) {
  size_t slash = path.find_last_of('/');
  return slash == std::string::npos ? path : path.substr(slash + 1);
}

}  // namespace

// --------------------------------------------------------------------------
ByteSplit::ByteSplit(const std::string& uri, unsigned align_bytes,
                     bool is_text, bool recurse_directories)
    : chunk_size_(size_t(8) << 20),
      align_bytes_(align_bytes),
      is_text_(is_text) {
  // Expand ';'-separated URIs; directories list their contents; a '*' in the
  // last path component globs within its directory
  // (reference input_split_base.cc:96-147 InitInputFileInfo).
  for (const std::string& piece : StrSplit(uri, ';')) {
    if (piece.empty()) continue;
    URI u(piece);
    FileSystem* fs = FileSystem::GetInstance(u);
    std::string base = BaseName(u.path);
    if (base.find('*') != std::string::npos) {
      URI dir = u;
      size_t slash = u.path.find_last_of('/');
      dir.path = slash == std::string::npos ? "." : u.path.substr(0, slash);
      std::vector<FileInfo> listing;
      fs->ListDirectory(dir, &listing);
      std::sort(listing.begin(), listing.end(),
                [](const FileInfo& a, const FileInfo& b) {
                  return a.path.path < b.path.path;
                });
      for (const FileInfo& info : listing) {
        if (info.type == FileType::kFile && info.size != 0 &&
            GlobMatch(base, BaseName(info.path.path))) {
          files_.push_back(info);
        }
      }
      continue;
    }
    FileInfo info = fs->GetPathInfo(u);
    if (info.type == FileType::kDirectory) {
      std::vector<FileInfo> listing;
      if (recurse_directories) {
        fs->ListDirectoryRecursive(info.path, &listing);
      } else {
        fs->ListDirectory(info.path, &listing);
      }
      std::sort(listing.begin(), listing.end(),
                [](const FileInfo& a, const FileInfo& b) {
                  return a.path.path < b.path.path;
                });
      for (const FileInfo& f : listing) {
        std::string name = BaseName(f.path.path);
        if (f.type == FileType::kFile && f.size != 0 && !name.empty() &&
            name[0] != '.' && name[0] != '_') {
          files_.push_back(f);
        }
      }
    } else if (info.size != 0) {
      files_.push_back(info);
    }
  }
  DCT_CHECK(!files_.empty()) << "no non-empty input files match uri: " << uri;
  file_start_.resize(files_.size());
  size_t acc = 0;
  for (size_t i = 0; i < files_.size(); ++i) {
    file_start_[i] = acc;
    acc += files_[i].size;
  }
  total_size_ = acc;
}

void ByteSplit::ResetPartition(unsigned rank, unsigned nsplit) {
  DCT_CHECK_LT(rank, nsplit) << "part index out of range";
  rank_ = rank;
  nsplit_ = nsplit;
  size_t nstep = (total_size_ + nsplit - 1) / nsplit;
  nstep = (nstep + align_bytes_ - 1) / align_bytes_ * align_bytes_;
  size_t raw_begin = std::min(total_size_, nstep * rank);
  size_t raw_end = std::min(total_size_, nstep * (rank + 1));
  begin_ = GlobalBoundaryFixup(raw_begin);
  end_ = GlobalBoundaryFixup(raw_end);
  BeforeFirst();
}

size_t ByteSplit::GlobalBoundaryFixup(size_t ofs) {
  if (ofs == 0 || ofs >= total_size_) return std::min(ofs, total_size_);
  // file containing ofs
  size_t k =
      std::upper_bound(file_start_.begin(), file_start_.end(), ofs) -
      file_start_.begin() - 1;
  if (ofs == file_start_[k]) return ofs;  // a file start is a record head
  size_t local = ofs - file_start_[k];
  std::unique_ptr<SeekStream> s(
      FileSystem::GetInstance(files_[k].path)->OpenForRead(files_[k].path));
  s->Seek(local);
  size_t consumed = SeekRecordHead(s.get(), local, files_[k].size);
  return std::min(file_start_[k] + local + consumed,
                  file_start_[k] + files_[k].size);
}

void ByteSplit::BeforeFirst() {
  // position the read cursor at begin_
  size_t k = files_.empty()
                 ? 0
                 : static_cast<size_t>(
                       std::upper_bound(file_start_.begin(), file_start_.end(),
                                        begin_) -
                       file_start_.begin()) -
                       1;
  if (begin_ >= total_size_ && !files_.empty()) k = files_.size() - 1;
  file_idx_ = k;
  local_pos_ = begin_ - file_start_[k];
  cur_stream_.reset();
  prev_byte_ = '\n';
  pending_newline_ = false;
  overflow_.clear();
  chunk_.clear();
  cursor_ = 0;
  exhausted_ = false;
}

size_t ByteSplit::ReadSpan(char* buf, size_t want) {
  size_t got = 0;
  while (got < want) {
    if (pending_newline_) {
      buf[got++] = '\n';
      pending_newline_ = false;
      continue;
    }
    size_t global = file_start_[file_idx_] + local_pos_;
    if (global >= end_) break;
    if (local_pos_ >= files_[file_idx_].size) {
      // advance to next file; inject newline between text files when the
      // previous file did not end with one (NOEOL rule,
      // reference input_split_base.cc:195-199, dmlc PRs 385/452)
      cur_stream_.reset();
      bool more = file_idx_ + 1 < files_.size() &&
                  file_start_[file_idx_ + 1] < end_;
      if (is_text_ && prev_byte_ != '\n' && more) pending_newline_ = true;
      if (!more) break;
      ++file_idx_;
      local_pos_ = 0;
      prev_byte_ = '\n';
      continue;
    }
    if (cur_stream_ == nullptr) {
      cur_stream_.reset(FileSystem::GetInstance(files_[file_idx_].path)
                            ->OpenForRead(files_[file_idx_].path));
      cur_stream_->Seek(local_pos_);
    }
    size_t to_read = std::min(
        {want - got, files_[file_idx_].size - local_pos_, end_ - global});
    size_t n = cur_stream_->Read(buf + got, to_read);
    DCT_CHECK_GT(n, size_t(0))
        << "file " << files_[file_idx_].path.Str()
        << " shorter than listed size";
    local_pos_ += n;
    got += n;
    prev_byte_ = buf[got - 1];
  }
  return got;
}

bool ByteSplit::FillChunkBuffer(std::vector<char>* buf) {
  if (exhausted_ && overflow_.empty()) return false;
  buf->clear();
  buf->swap(overflow_);  // carried partial record heads the new chunk
  size_t target = buf->size() + chunk_size_;
  while (true) {
    size_t old = buf->size();
    buf->resize(target);
    size_t n = ReadSpan(buf->data() + old, target - old);
    buf->resize(old + n);
    if (n < target - old) exhausted_ = true;
    if (buf->empty()) return false;
    if (exhausted_) {
      // partition end is a record head: everything left is whole records
      break;
    }
    size_t boundary = FindLastRecordHead(buf->data(),
                                         buf->data() + buf->size());
    if (boundary == 0) {
      // no record boundary in sight: grow the chunk
      // (reference input_split_base.cc Chunk::Append)
      target = buf->size() + chunk_size_;
      continue;
    }
    overflow_.assign(buf->begin() + boundary, buf->end());
    buf->resize(boundary);
    break;
  }
  return true;
}

bool ByteSplit::NextChunk(Blob* out) {
  if (!FillChunkBuffer(&chunk_)) return false;
  out->dptr = chunk_.data();
  out->size = chunk_.size();
  cursor_ = chunk_.size();  // chunk handed out wholesale
  return true;
}

bool ByteSplit::NextRecord(Blob* out) {
  while (true) {
    if (cursor_ < chunk_.size() &&
        ExtractRecordAt(chunk_.data(), chunk_.size(), &cursor_, out)) {
      return true;
    }
    if (!FillChunkBuffer(&chunk_)) return false;
    cursor_ = 0;
  }
}

// --------------------------------------------------------------------------
LineSplit::LineSplit(const std::string& uri, unsigned part, unsigned nsplit,
                     bool recurse_directories)
    : ByteSplit(uri, /*align_bytes=*/1, /*is_text=*/true,
                recurse_directories) {
  ResetPartition(part, nsplit);
}

size_t LineSplit::SeekRecordHead(SeekStream* s, size_t local_pos,
                                 size_t file_size) {
  // consume bytes until just past the next '\n'; EOF counts as a head
  char buf[1024];
  size_t consumed = 0;
  while (true) {
    size_t n = s->Read(buf, sizeof(buf));
    if (n == 0) return consumed;  // NOEOL tail: boundary at file end
    const char* nl = static_cast<const char*>(std::memchr(buf, '\n', n));
    if (nl != nullptr) {
      return consumed + static_cast<size_t>(nl - buf) + 1;
    }
    consumed += n;
  }
}

size_t LineSplit::FindLastRecordHead(const char* begin, const char* end) {
  for (const char* p = end; p != begin;) {
    --p;
    if (*p == '\n') return static_cast<size_t>(p - begin) + 1;
  }
  return 0;
}

bool LineSplit::ExtractRecordAt(char* data, size_t valid, size_t* cursor,
                                Blob* out) {
  if (*cursor >= valid) return false;
  char* line = data + *cursor;
  size_t remain = valid - *cursor;
  char* nl = static_cast<char*>(std::memchr(line, '\n', remain));
  size_t len = (nl == nullptr) ? remain : static_cast<size_t>(nl - line);
  *cursor += len + (nl == nullptr ? 0 : 1);
  if (len > 0 && line[len - 1] == '\r') --len;  // CRLF
  out->dptr = line;
  out->size = len;
  return true;
}

// --------------------------------------------------------------------------
RecordIOSplit::RecordIOSplit(const std::string& uri, unsigned part,
                             unsigned nsplit, bool recurse_directories)
    : ByteSplit(uri, /*align_bytes=*/4, /*is_text=*/false,
                recurse_directories) {
  ResetPartition(part, nsplit);
}

size_t RecordIOSplit::SeekRecordHead(SeekStream* s, size_t local_pos,
                                     size_t file_size) {
  // scan forward from the next 4-aligned offset for magic + cflag in {0,1}
  size_t aligned = recordio::AlignUp4(local_pos);
  if (aligned + 8 > file_size) return file_size - local_pos;
  s->Seek(aligned);
  std::vector<char> buf(size_t(1) << 16);
  size_t have = 0;       // valid bytes in buf
  size_t base = aligned;  // absolute file offset of buf[0] (4-aligned)
  while (true) {
    size_t n = s->Read(buf.data() + have, buf.size() - have);
    have += n;
    for (size_t i = 0; i + 8 <= have; i += 4) {
      if (recordio::IsRecordHead(buf.data() + i)) {
        return base + i - local_pos;
      }
    }
    if (n == 0) return file_size - local_pos;  // no head: file end
    // retain the unverified tail (first aligned i with i + 8 > have)
    size_t first_unchecked = have >= 8 ? recordio::AlignUp4(have - 7) : 0;
    size_t keep = have - first_unchecked;
    std::memmove(buf.data(), buf.data() + first_unchecked, keep);
    base += first_unchecked;
    have = keep;
  }
}

size_t RecordIOSplit::FindLastRecordHead(const char* begin, const char* end) {
  size_t size = static_cast<size_t>(end - begin) & ~size_t(3);
  for (size_t ofs = size >= 8 ? size - 8 : 0;; ofs -= 4) {
    if (ofs == 0) return 0;
    if (recordio::IsRecordHead(begin + ofs)) return ofs;
    if (ofs < 4) return 0;
  }
}

bool RecordIOSplit::ExtractRecordAt(char* data, size_t valid, size_t* cursor,
                                    Blob* out) {
  if (*cursor + 8 > valid) {
    *cursor = valid;
    return false;
  }
  assembled_.clear();
  bool multipart = false;
  while (true) {
    DCT_CHECK_LE(*cursor + 8, valid) << "truncated recordio chunk";
    uint32_t magic = recordio::LoadWordLE(data + *cursor);
    DCT_CHECK_EQ(magic, recordio::kMagic) << "bad recordio magic in chunk";
    uint32_t lrec = recordio::LoadWordLE(data + *cursor + 4);
    uint32_t cflag = recordio::HeaderFlag(lrec);
    uint32_t len = recordio::HeaderLen(lrec);
    size_t payload = *cursor + 8;
    DCT_CHECK_LE(payload + recordio::AlignUp4(len), valid)
        << "recordio record overruns chunk";
    *cursor = payload + recordio::AlignUp4(len);
    if (cflag == 0) {
      DCT_CHECK(!multipart) << "unexpected cflag=0 inside multi-part record";
      out->dptr = data + payload;
      out->size = len;
      return true;
    }
    if (cflag == 1) {
      DCT_CHECK(!multipart) << "unexpected cflag=1 inside multi-part record";
      multipart = true;
      assembled_.assign(data + payload, len);
    } else {
      DCT_CHECK(multipart) << "continuation part without a head";
      char magic_bytes[4];
      uint32_t m = recordio::kMagic;
      if (!serial::NativeIsLE()) m = serial::ByteSwap(m);
      std::memcpy(magic_bytes, &m, 4);
      assembled_.append(magic_bytes, 4);
      assembled_.append(data + payload, len);
      if (cflag == 3) {
        out->dptr = assembled_.data();
        out->size = assembled_.size();
        return true;
      }
      DCT_CHECK_EQ(cflag, 2u) << "invalid recordio cflag";
    }
  }
}

// --------------------------------------------------------------------------
PrefetchSplit::PrefetchSplit(ByteSplit* base, size_t capacity)
    : base_(base), pipe_(capacity) {}

PrefetchSplit::~PrefetchSplit() {
  if (current_ != nullptr) pipe_.Recycle(&current_);
  pipe_.Shutdown();
}

void PrefetchSplit::EnsureStarted() {
  if (started_) return;
  pipe_.Init(
      [this](Cell** cell) {
        if (*cell == nullptr) *cell = new Cell();
        (*cell)->cursor = 0;
        return base_->FillChunkBuffer(&(*cell)->data);
      },
      [this] { base_->BeforeFirst(); });
  started_ = true;
}

void PrefetchSplit::BeforeFirst() {
  if (current_ != nullptr) pipe_.Recycle(&current_);
  if (started_) pipe_.BeforeFirst();
}

bool PrefetchSplit::NextChunk(Blob* out) {
  EnsureStarted();
  if (current_ != nullptr) pipe_.Recycle(&current_);
  if (!pipe_.Next(&current_)) return false;
  out->dptr = current_->data.data();
  out->size = current_->data.size();
  current_->cursor = current_->data.size();
  return true;
}

bool PrefetchSplit::NextRecord(Blob* out) {
  EnsureStarted();
  while (true) {
    if (current_ != nullptr &&
        base_->ExtractRecordAt(current_->data.data(), current_->data.size(),
                               &current_->cursor, out)) {
      return true;
    }
    if (current_ != nullptr) pipe_.Recycle(&current_);
    if (!pipe_.Next(&current_)) return false;
  }
}

void PrefetchSplit::ResetPartition(unsigned rank, unsigned nsplit) {
  if (current_ != nullptr) pipe_.Recycle(&current_);
  pipe_.Shutdown();
  started_ = false;
  base_->ResetPartition(rank, nsplit);
}

InputSplit* InputSplit::Create(const std::string& uri, unsigned part,
                               unsigned nsplit, const std::string& type,
                               const std::string& index_uri, bool shuffle,
                               int seed, size_t batch_size,
                               bool recurse_directories, bool threaded,
                               const std::string& cache_file) {
  DCT_CHECK(index_uri.empty() && !shuffle && cache_file.empty())
      << "indexed/shuffled/cached input splits are not implemented yet "
         "(type=" << type << ")";
  (void)seed;
  (void)batch_size;
  ByteSplit* split = nullptr;
  if (type == "text") {
    split = new LineSplit(uri, part, nsplit, recurse_directories);
  } else if (type == "recordio") {
    split = new RecordIOSplit(uri, part, nsplit, recurse_directories);
  } else {
    throw Error("unknown input split type: " + type);
  }
  if (threaded) {
    return new PrefetchSplit(split, 2);
  }
  return split;
}

}  // namespace dct
