// Self-contained SHA-256 + HMAC-SHA256 (FIPS 180-4 / RFC 2104).
//
// The reference links OpenSSL for its S3 SIG4 signing
// (src/io/s3_filesys.cc:231-319); this build environment has no OpenSSL
// headers, so the two primitives SIG4 needs are implemented here directly
// from the spec. Correctness is cross-checked against Python hashlib in
// tests/test_s3.py.
#ifndef DCT_SHA256_H_
#define DCT_SHA256_H_

#include <cstdint>
#include <cstring>
#include <string>

namespace dct {
namespace crypto {

class SHA256 {
 public:
  SHA256() { Reset(); }

  void Reset() {
    h_[0] = 0x6a09e667u; h_[1] = 0xbb67ae85u;
    h_[2] = 0x3c6ef372u; h_[3] = 0xa54ff53au;
    h_[4] = 0x510e527fu; h_[5] = 0x9b05688cu;
    h_[6] = 0x1f83d9abu; h_[7] = 0x5be0cd19u;
    total_ = 0;
    buf_len_ = 0;
  }

  void Update(const void* data, size_t len) {
    const uint8_t* p = static_cast<const uint8_t*>(data);
    total_ += len;
    while (len != 0) {
      size_t take = 64 - buf_len_;
      if (take > len) take = len;
      std::memcpy(buf_ + buf_len_, p, take);
      buf_len_ += take;
      p += take;
      len -= take;
      if (buf_len_ == 64) {
        Compress(buf_);
        buf_len_ = 0;
      }
    }
  }

  // 32-byte digest
  void Final(uint8_t out[32]) {
    uint64_t bit_len = total_ * 8;
    uint8_t pad = 0x80;
    Update(&pad, 1);
    uint8_t zero = 0;
    while (buf_len_ != 56) Update(&zero, 1);
    // appending the length must not recount into total_
    uint8_t len_be[8];
    for (int i = 0; i < 8; ++i) {
      len_be[i] = static_cast<uint8_t>(bit_len >> (56 - 8 * i));
    }
    std::memcpy(buf_ + 56, len_be, 8);
    Compress(buf_);
    for (int i = 0; i < 8; ++i) {
      out[4 * i] = static_cast<uint8_t>(h_[i] >> 24);
      out[4 * i + 1] = static_cast<uint8_t>(h_[i] >> 16);
      out[4 * i + 2] = static_cast<uint8_t>(h_[i] >> 8);
      out[4 * i + 3] = static_cast<uint8_t>(h_[i]);
    }
  }

 private:
  static uint32_t Rotr(uint32_t x, int n) {
    return (x >> n) | (x << (32 - n));
  }

  void Compress(const uint8_t block[64]) {
    static const uint32_t K[64] = {
        0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b,
        0x59f111f1, 0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01,
        0x243185be, 0x550c7dc3, 0x72be5d74, 0x80deb1fe, 0x9bdc06a7,
        0xc19bf174, 0xe49b69c1, 0xefbe4786, 0x0fc19dc6, 0x240ca1cc,
        0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da, 0x983e5152,
        0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
        0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc,
        0x53380d13, 0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85,
        0xa2bfe8a1, 0xa81a664b, 0xc24b8b70, 0xc76c51a3, 0xd192e819,
        0xd6990624, 0xf40e3585, 0x106aa070, 0x19a4c116, 0x1e376c08,
        0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a, 0x5b9cca4f,
        0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
        0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};
    uint32_t w[64];
    for (int i = 0; i < 16; ++i) {
      w[i] = (uint32_t(block[4 * i]) << 24) |
             (uint32_t(block[4 * i + 1]) << 16) |
             (uint32_t(block[4 * i + 2]) << 8) | uint32_t(block[4 * i + 3]);
    }
    for (int i = 16; i < 64; ++i) {
      uint32_t s0 = Rotr(w[i - 15], 7) ^ Rotr(w[i - 15], 18) ^
                    (w[i - 15] >> 3);
      uint32_t s1 = Rotr(w[i - 2], 17) ^ Rotr(w[i - 2], 19) ^
                    (w[i - 2] >> 10);
      w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    uint32_t a = h_[0], b = h_[1], c = h_[2], d = h_[3];
    uint32_t e = h_[4], f = h_[5], g = h_[6], h = h_[7];
    for (int i = 0; i < 64; ++i) {
      uint32_t S1 = Rotr(e, 6) ^ Rotr(e, 11) ^ Rotr(e, 25);
      uint32_t ch = (e & f) ^ (~e & g);
      uint32_t t1 = h + S1 + ch + K[i] + w[i];
      uint32_t S0 = Rotr(a, 2) ^ Rotr(a, 13) ^ Rotr(a, 22);
      uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
      uint32_t t2 = S0 + maj;
      h = g; g = f; f = e; e = d + t1;
      d = c; c = b; b = a; a = t1 + t2;
    }
    h_[0] += a; h_[1] += b; h_[2] += c; h_[3] += d;
    h_[4] += e; h_[5] += f; h_[6] += g; h_[7] += h;
  }

  uint32_t h_[8];
  uint8_t buf_[64];
  size_t buf_len_ = 0;
  uint64_t total_ = 0;
};

inline std::string Sha256Hex(const std::string& data) {
  SHA256 s;
  s.Update(data.data(), data.size());
  uint8_t digest[32];
  s.Final(digest);
  static const char* hex = "0123456789abcdef";
  std::string out(64, '0');
  for (int i = 0; i < 32; ++i) {
    out[2 * i] = hex[digest[i] >> 4];
    out[2 * i + 1] = hex[digest[i] & 0xF];
  }
  return out;
}

inline std::string HmacSha256(const std::string& key, const std::string& msg) {
  // RFC 2104 with B=64
  std::string k = key;
  if (k.size() > 64) {
    SHA256 s;
    s.Update(k.data(), k.size());
    uint8_t d[32];
    s.Final(d);
    k.assign(reinterpret_cast<char*>(d), 32);
  }
  k.resize(64, '\0');
  std::string ipad(64, '\x36'), opad(64, '\x5c');
  for (int i = 0; i < 64; ++i) {
    ipad[i] ^= k[i];
    opad[i] ^= k[i];
  }
  SHA256 inner;
  inner.Update(ipad.data(), 64);
  inner.Update(msg.data(), msg.size());
  uint8_t id[32];
  inner.Final(id);
  SHA256 outer;
  outer.Update(opad.data(), 64);
  outer.Update(id, 32);
  uint8_t od[32];
  outer.Final(od);
  return std::string(reinterpret_cast<char*>(od), 32);
}

inline std::string HexEncode(const std::string& raw) {
  static const char* hex = "0123456789abcdef";
  std::string out;
  out.reserve(raw.size() * 2);
  for (unsigned char c : raw) {
    out.push_back(hex[c >> 4]);
    out.push_back(hex[c & 0xF]);
  }
  return out;
}

// RFC 4648 base64 (Azure SharedKey uses base64 account keys/signatures).
inline std::string Base64Encode(const std::string& raw) {
  static const char* tbl =
      "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789+/";
  std::string out;
  out.reserve((raw.size() + 2) / 3 * 4);
  size_t i = 0;
  while (i + 3 <= raw.size()) {
    uint32_t v = (static_cast<uint8_t>(raw[i]) << 16) |
                 (static_cast<uint8_t>(raw[i + 1]) << 8) |
                 static_cast<uint8_t>(raw[i + 2]);
    out.push_back(tbl[(v >> 18) & 63]);
    out.push_back(tbl[(v >> 12) & 63]);
    out.push_back(tbl[(v >> 6) & 63]);
    out.push_back(tbl[v & 63]);
    i += 3;
  }
  size_t rem = raw.size() - i;
  if (rem == 1) {
    uint32_t v = static_cast<uint8_t>(raw[i]) << 16;
    out.push_back(tbl[(v >> 18) & 63]);
    out.push_back(tbl[(v >> 12) & 63]);
    out += "==";
  } else if (rem == 2) {
    uint32_t v = (static_cast<uint8_t>(raw[i]) << 16) |
                 (static_cast<uint8_t>(raw[i + 1]) << 8);
    out.push_back(tbl[(v >> 18) & 63]);
    out.push_back(tbl[(v >> 12) & 63]);
    out.push_back(tbl[(v >> 6) & 63]);
    out += "=";
  }
  return out;
}

inline std::string Base64Decode(const std::string& enc) {
  auto val = [](char c) -> int {
    if (c >= 'A' && c <= 'Z') return c - 'A';
    if (c >= 'a' && c <= 'z') return c - 'a' + 26;
    if (c >= '0' && c <= '9') return c - '0' + 52;
    if (c == '+') return 62;
    if (c == '/') return 63;
    return -1;  // padding or invalid
  };
  std::string out;
  out.reserve(enc.size() / 4 * 3);
  uint32_t acc = 0;
  int bits = 0;
  for (char c : enc) {
    int v = val(c);
    if (v < 0) continue;  // skip '=', whitespace
    acc = (acc << 6) | static_cast<uint32_t>(v);
    bits += 6;
    if (bits >= 8) {
      bits -= 8;
      out.push_back(static_cast<char>((acc >> bits) & 0xFF));
    }
  }
  return out;
}

}  // namespace crypto
}  // namespace dct

#endif  // DCT_SHA256_H_
