// Reflection-based parameter structs.
//
// Counterpart of reference include/dmlc/parameter.h (1153 L): plain C++
// structs gain keyword initialization, validation (range / enum), default
// handling, docstring generation, dict export, and JSON save/load through a
// once-built per-type ParamManager (reference __MANAGER__, parameter.h:
// 248-257,311-319). The macro surface is kept — DCT_DECLARE_PARAMETER /
// DCT_DECLARE_FIELD / alias / range / enum — because downstream code keys on
// that idiom; the implementation is C++17 (std::function setters bound to
// member offsets, from_chars parsing via numparse.h) rather than the
// reference's hand-rolled type lattice.
#ifndef DCT_PARAMETER_H_
#define DCT_PARAMETER_H_

#include <cstdlib>
#include <limits>
#include <map>
#include <memory>
#include <sstream>
#include <string>
#include <utility>
#include <vector>

#include "base.h"
#include "json.h"
#include "numparse.h"

namespace dct {

// Field metadata surfaced by __FIELDS__ / docstrings and the registry
// (reference ParamFieldInfo, parameter.h:85-100).
struct ParamFieldInfo {
  std::string name;
  std::string type;            // e.g. "int", "float", "string"
  std::string type_info_str;   // type + default/range/enum rendering
  std::string description;
};

// Init matching policy (reference parameter.h:77-84).
enum class ParamInitOption {
  kAllowUnknown,  // ignore unknown keys
  kAllMatch,      // every key must match a declared field
  kAllowHidden,   // unknown keys allowed only when prefixed with '_'
};

class ParamError : public Error {
 public:
  using Error::Error;
};

namespace param {

template <typename T>
inline const char* TypeName();
template <> inline const char* TypeName<int>() { return "int"; }
template <> inline const char* TypeName<unsigned>() { return "unsigned"; }
template <> inline const char* TypeName<int64_t>() { return "int64"; }
template <> inline const char* TypeName<uint64_t>() { return "uint64"; }
template <> inline const char* TypeName<float>() { return "float"; }
template <> inline const char* TypeName<double>() { return "double"; }
template <> inline const char* TypeName<bool>() { return "boolean"; }
template <> inline const char* TypeName<std::string>() { return "string"; }

class FieldAccessEntry {
 public:
  virtual ~FieldAccessEntry() = default;
  virtual void Set(void* head, const std::string& value) const = 0;
  virtual std::string GetStringValue(const void* head) const = 0;
  virtual void SetDefault(void* head) const = 0;
  bool has_default() const { return has_default_; }
  const std::string& key() const { return key_; }
  virtual ParamFieldInfo GetFieldInfo() const = 0;

 protected:
  friend class ParamManager;
  std::string key_;
  std::string description_;
  bool has_default_ = false;
};

template <typename T>
class FieldEntry : public FieldAccessEntry {
 public:
  // -- chainable declaration surface (reference FieldEntry, parameter.h
  //    :775-880) --
  FieldEntry& set_default(const T& v) {
    default_ = v;
    has_default_ = true;
    return *this;
  }
  FieldEntry& describe(const std::string& d) {
    description_ = d;
    return *this;
  }
  FieldEntry& set_range(T lo, T hi) {
    lo_ = lo;
    hi_ = hi;
    has_range_ = true;
    return *this;
  }
  FieldEntry& set_lower_bound(T lo) {
    lo_ = lo;
    has_range_ = true;
    return *this;
  }
  // string aliases for values (reference add_enum, int fields)
  FieldEntry& add_enum(const std::string& name, T v) {
    enum_.emplace_back(name, v);
    return *this;
  }

  void Set(void* head, const std::string& value) const override {
    T* ref = reinterpret_cast<T*>(static_cast<char*>(head) + offset_);
    T parsed{};
    if (!ParseValue(value, &parsed)) {
      throw ParamError("parameter " + key_ + ": cannot parse value \"" +
                       value + "\" as " + TypeName<T>());
    }
    if (has_range_ && (parsed < lo_ || parsed > hi_)) {
      std::ostringstream os;
      os << "parameter " << key_ << ": value " << value
         << " out of range " << RangeString();
      throw ParamError(os.str());
    }
    *ref = parsed;
  }

  std::string GetStringValue(const void* head) const override {
    const T& v = *reinterpret_cast<const T*>(
        static_cast<const char*>(head) + offset_);
    for (const auto& kv : enum_) {
      if (kv.second == v) return kv.first;
    }
    return ToString(v);
  }

  void SetDefault(void* head) const override {
    *reinterpret_cast<T*>(static_cast<char*>(head) + offset_) = default_;
  }

  ParamFieldInfo GetFieldInfo() const override {
    ParamFieldInfo info;
    info.name = key_;
    info.type = TypeName<T>();
    std::ostringstream os;
    os << info.type;
    if (!enum_.empty()) {
      os << ", {";
      for (size_t i = 0; i < enum_.size(); ++i) {
        os << (i ? ", " : "") << '\'' << enum_[i].first << '\'';
      }
      os << '}';
    } else if (has_range_) {
      os << ", " << RangeString();
    }
    if (has_default_) {
      os << ", default=" << ToString(default_);
    } else {
      os << ", required";
    }
    info.type_info_str = os.str();
    info.description = description_;
    return info;
  }

 private:
  friend class ParamManager;

  bool ParseValue(const std::string& s, T* out) const {
    for (const auto& kv : enum_) {
      if (kv.first == s) {
        *out = kv.second;
        return true;
      }
    }
    if constexpr (std::is_same_v<T, std::string>) {
      *out = s;
      return true;
    } else if constexpr (std::is_same_v<T, bool>) {
      if (s == "true" || s == "True" || s == "1") { *out = true; return true; }
      if (s == "false" || s == "False" || s == "0") {
        *out = false;
        return true;
      }
      return false;
    } else {
      const char* p = s.data();
      const char* end = p + s.size();
      const char* q = p;
      T v{};
      if (!ParseNum(p, end, &q, &v) || q != end) return false;
      *out = v;
      return true;
    }
  }

  static std::string ToString(const T& v) {
    if constexpr (std::is_same_v<T, std::string>) {
      return v;
    } else if constexpr (std::is_same_v<T, bool>) {
      return v ? "true" : "false";
    } else {
      std::ostringstream os;
      if constexpr (std::is_floating_point_v<T>) {
        // full round-trip precision: __DICT__/JSON Save→Load must not
        // perturb float fields
        os.precision(std::numeric_limits<T>::max_digits10);
      }
      os << v;
      return os.str();
    }
  }

  std::string RangeString() const {
    std::ostringstream os;
    os << "[" << ToString(lo_) << ", ";
    if (hi_ == std::numeric_limits<T>::max()) {
      os << "inf";
    } else {
      os << ToString(hi_);
    }
    os << "]";
    return os.str();
  }

  size_t offset_ = 0;
  T default_{};
  T lo_{};
  T hi_ = std::numeric_limits<T>::max();
  bool has_range_ = false;
  std::vector<std::pair<std::string, T>> enum_;
};

class ParamManager {
 public:
  template <typename T>
  FieldEntry<T>& Declare(void* head, const std::string& key, T& ref) {
    auto entry = std::make_unique<FieldEntry<T>>();
    entry->key_ = key;
    entry->offset_ = reinterpret_cast<char*>(&ref) -
                     reinterpret_cast<char*>(head);
    FieldEntry<T>* raw = entry.get();
    fmap_[key] = raw;
    entries_.push_back(std::move(entry));
    return *raw;
  }

  // alias → canonical key (reference DMLC_DECLARE_ALIAS, parameter.h:330)
  void AddAlias(const std::string& field, const std::string& alias) {
    auto it = fmap_.find(field);
    DCT_CHECK(it != fmap_.end()) << "alias of undeclared field " << field;
    fmap_[alias] = it->second;
  }

  void set_name(const std::string& name) { name_ = name; }
  const std::string& name() const { return name_; }

  // Initialize fields of *head from kwargs; returns keys that matched no
  // field (empty unless kAllowUnknown/kAllowHidden). Missing fields take
  // defaults; missing required fields throw listing the docstring
  // (reference RunInit, parameter.h:429-482).
  std::vector<std::pair<std::string, std::string>> RunInit(
      void* head, const std::map<std::string, std::string>& kwargs,
      ParamInitOption option) const {
    std::vector<std::pair<std::string, std::string>> unknown;
    std::map<std::string, bool> set_flags;
    for (const auto& kv : kwargs) {
      auto it = fmap_.find(kv.first);
      if (it == fmap_.end()) {
        switch (option) {
          case ParamInitOption::kAllMatch:
            throw ParamError("unknown parameter " + kv.first + " for " +
                             name_ + "\n" + DocString());
          case ParamInitOption::kAllowHidden:
            if (kv.first.empty() || kv.first[0] != '_') {
              throw ParamError("unknown parameter " + kv.first + " for " +
                               name_ + "\n" + DocString());
            }
            [[fallthrough]];
          case ParamInitOption::kAllowUnknown:
            unknown.push_back(kv);
            continue;
        }
      }
      it->second->Set(head, kv.second);
      set_flags[it->second->key()] = true;
    }
    for (const auto& e : entries_) {
      if (set_flags.count(e->key())) continue;
      if (e->has_default()) {
        e->SetDefault(head);
      } else {
        throw ParamError("required parameter " + e->key() + " of " + name_ +
                         " is not set\n" + DocString());
      }
    }
    return unknown;
  }

  std::vector<ParamFieldInfo> GetFieldInfo() const {
    std::vector<ParamFieldInfo> out;
    for (const auto& e : entries_) out.push_back(e->GetFieldInfo());
    return out;
  }

  std::map<std::string, std::string> GetDict(const void* head) const {
    std::map<std::string, std::string> out;
    for (const auto& e : entries_) {
      out[e->key()] = e->GetStringValue(head);
    }
    return out;
  }

  // reference PrintDocString (parameter.h:541)
  std::string DocString() const {
    std::ostringstream os;
    for (const auto& e : entries_) {
      ParamFieldInfo info = e->GetFieldInfo();
      os << info.name << " : " << info.type_info_str << "\n";
      if (!info.description.empty()) {
        os << "    " << info.description << "\n";
      }
    }
    return os.str();
  }

 private:
  std::string name_;
  std::vector<std::unique_ptr<FieldAccessEntry>> entries_;
  std::map<std::string, FieldAccessEntry*> fmap_;  // includes aliases
};

// Builds the manager once per PType by running __DECLARE__ on a scratch
// instance (field offsets are recorded relative to it) — reference
// ParamManagerSingleton, parameter.h:248-257.
template <typename PType>
struct ParamManagerSingleton {
  ParamManager manager;
  explicit ParamManagerSingleton(const std::string& param_name) {
    PType param;
    manager.set_name(param_name);
    param.__DECLARE__(&manager, &param);
  }
};

}  // namespace param

// CRTP base (reference Parameter<PType>, parameter.h:140-223).
template <typename PType>
struct Parameter {
  // Initialize from kwargs; throws ParamError on parse/range/missing
  // violations. Returns unmatched keys under kAllowUnknown/kAllowHidden.
  std::vector<std::pair<std::string, std::string>> Init(
      const std::map<std::string, std::string>& kwargs,
      ParamInitOption option = ParamInitOption::kAllowUnknown) {
    return PType::__MANAGER__()->RunInit(static_cast<PType*>(this), kwargs,
                                         option);
  }

  std::map<std::string, std::string> __DICT__() const {
    return PType::__MANAGER__()->GetDict(static_cast<const PType*>(this));
  }

  static std::vector<ParamFieldInfo> __FIELDS__() {
    return PType::__MANAGER__()->GetFieldInfo();
  }

  static std::string __DOC__() {
    return PType::__MANAGER__()->DocString();
  }

  // JSON save/load as a {"key": "value"} object (reference parameter.h
  // :211-223).
  void Save(JSONWriter* writer) const {
    writer->Write(__DICT__());
  }

  void Load(JSONReader* reader) {
    std::map<std::string, std::string> kwargs;
    reader->Read(&kwargs);
    Init(kwargs, ParamInitOption::kAllMatch);
  }
};

// Environment access with typed defaults (reference GetEnv/SetEnv,
// parameter.h:50-61,1122+).
template <typename T>
inline T GetEnv(const char* key, T default_value) {
  const char* v = std::getenv(key);
  if (v == nullptr || *v == '\0') return default_value;
  if constexpr (std::is_same_v<T, std::string>) {
    return std::string(v);
  } else if constexpr (std::is_same_v<T, bool>) {
    std::string s(v);
    return s == "1" || s == "true" || s == "True";
  } else {
    const char* end = v + std::char_traits<char>::length(v);
    const char* q = v;
    T out{};
    if (!ParseNum(v, end, &q, &out) || q != end) return default_value;
    return out;
  }
}

inline void SetEnv(const char* key, const std::string& value) {
  ::setenv(key, value.c_str(), 1);
}

#define DCT_DECLARE_PARAMETER(PType)                                      \
  static dct::param::ParamManager* __MANAGER__() {                        \
    static dct::param::ParamManagerSingleton<PType> inst(#PType);         \
    return &inst.manager;                                                 \
  }                                                                       \
  void __DECLARE__(dct::param::ParamManager* mgr_, PType* self_)

#define DCT_DECLARE_FIELD(FieldName) \
  mgr_->Declare(self_, #FieldName, self_->FieldName)

#define DCT_DECLARE_ALIAS(FieldName, AliasName) \
  mgr_->AddAlias(#FieldName, #AliasName)

}  // namespace dct

#endif  // DCT_PARAMETER_H_
