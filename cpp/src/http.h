// Minimal HTTP/1.1 client over POSIX sockets.
//
// The reference drives S3 through libcurl (src/io/s3_filesys.cc:498-650
// curl multi + select loops); this environment has no libcurl, so the small
// subset S3 needs is implemented directly: one request per connection
// (Connection: close), Content-Length and chunked responses, streaming body
// reads. Plain http only — TLS is out of scope for the built-in client
// (S3-compatible stores and the test harness speak http; see s3_filesys.h).
#ifndef DCT_HTTP_H_
#define DCT_HTTP_H_

#include <map>
#include <string>
#include <vector>

#include "base.h"

namespace dct {

struct HttpResponse {
  int status = 0;
  std::map<std::string, std::string> headers;  // lower-cased keys
  std::string body;
};

// An HTTP error response with its status carried as data, so callers can
// classify (404 probe, retryability) without parsing the message text.
class HttpStatusError : public Error {
 public:
  HttpStatusError(const std::string& what, int status_code)
      : Error(what), status(status_code) {}
  int status;
};

// Retry can help: transport-level timeouts/throttling and server errors.
// Other 4xx are definitive and must fail fast.
inline bool RetryableHttpStatus(int status) {
  return status == 408 || status == 429 || status >= 500;
}

class HttpConnection {
 public:
  HttpConnection(const std::string& host, int port);
  ~HttpConnection();
  HttpConnection(const HttpConnection&) = delete;
  HttpConnection& operator=(const HttpConnection&) = delete;

  // Send a full request (path may include the query string).
  void SendRequest(const std::string& method, const std::string& path,
                   const std::map<std::string, std::string>& headers,
                   const std::string& body);

  // Read status line + headers; body is then streamed with ReadBody.
  void ReadResponseHead(HttpResponse* out);
  // Stream up to `size` body bytes; 0 at end of body.
  size_t ReadBody(void* buf, size_t size);
  // Convenience: read the entire remaining body into out->body.
  void ReadFullBody(HttpResponse* out);

 private:
  size_t RawRead(void* buf, size_t size);
  bool ReadLine(std::string* line);

  int fd_ = -1;
  std::string default_host_header_;  // injected when caller sets no Host
  std::string rbuf_;          // buffered unread bytes
  size_t rpos_ = 0;
  int64_t body_remaining_ = -1;  // -1: read-to-close
  bool chunked_ = false;
  int64_t chunk_remaining_ = 0;
  bool body_done_ = false;
};

// One-shot request helper.
HttpResponse HttpRequest(const std::string& host, int port,
                         const std::string& method, const std::string& path,
                         const std::map<std::string, std::string>& headers,
                         const std::string& body);

// "host", "host:port", or "[v6literal]:port" -> (host, port). A bare IPv6
// literal (more than one ':' and no brackets) is never split; the bracketed
// form carries the port after the closing ']'.
void SplitHostPort(const std::string& s, std::string* host, int* port,
                   int default_port);

}  // namespace dct

#endif  // DCT_HTTP_H_
