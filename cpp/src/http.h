// Minimal HTTP/1.1 client over POSIX sockets.
//
// The reference drives S3 through libcurl (src/io/s3_filesys.cc:498-650
// curl multi + select loops); this environment has no libcurl, so the small
// subset S3 needs is implemented directly: one request per connection
// (Connection: close), Content-Length and chunked responses, streaming body
// reads. The socket client itself is plain http; https origins are reached
// through the local TLS-terminating helper via HttpRoute (below) — TLS
// terminates in the helper process (python -m dmlc_core_tpu.io.tls_proxy,
// stdlib ssl), not in this client.
#ifndef DCT_HTTP_H_
#define DCT_HTTP_H_

#include <map>
#include <string>
#include <vector>

#include "base.h"

namespace dct {

namespace telemetry {
struct IoHists;  // per-backend io latency histograms (telemetry.h)
}  // namespace telemetry

struct HttpResponse {
  int status = 0;
  std::map<std::string, std::string> headers;  // lower-cased keys
  std::string body;
};

// An HTTP error response with its status carried as data, so callers can
// classify (404 probe, retryability) without parsing the message text.
class HttpStatusError : public Error {
 public:
  HttpStatusError(const std::string& what, int status_code)
      : Error(what), status(status_code) {}
  int status;
};

// Retry can help: transport-level timeouts/throttling and server errors.
// Other 4xx are definitive and must fail fast.
inline bool RetryableHttpStatus(int status) {
  return status == 408 || status == 429 || status >= 500;
}

// A network failure retrying cannot fix — DNS says the name does not
// exist (typo'd endpoint config). Retry ladders rethrow it immediately
// instead of backing off through their whole budget. Transient resolver
// failures (EAI_AGAIN) stay plain Error and retry.
class PermanentNetworkError : public Error {
 public:
  explicit PermanentNetworkError(const std::string& what) : Error(what) {}
};

// Where a request for an origin actually connects, and how the request
// path is phrased. Direct plain-http origins connect straight through with
// origin-form paths. https origins are reached via the local
// TLS-terminating helper (python -m dmlc_core_tpu.io.tls_proxy), selected
// by DCT_TLS_PROXY=host:port: the client connects to the helper and sends
// ABSOLUTE-form requests ("GET https://origin/path"), the helper opens TLS
// to the origin and relays — the reference gets the same capability from
// libcurl+OpenSSL inside its S3 client (s3_filesys.cc curl handles).
struct HttpRoute {
  std::string connect_host;
  int connect_port = 0;
  std::string path_prefix;  // "" direct; "https://host[:port]" via helper
  std::string host_header;  // origin Host (survives the helper unchanged)
  // telemetry label for the backend issuing requests along this route
  // ("s3"/"azure"/"webhdfs"/"http"); selects the io_{connect,ttfb,recv}_us
  // histogram set (telemetry.h IoHistsFor)
  std::string backend = "http";
};

// Resolve (scheme, host, port) to a route. Throws for https origins when
// no TLS helper is published (the built-in socket client is plain-HTTP).
// `backend` tags the route's telemetry label (HttpRoute::backend).
HttpRoute ResolveHttpRoute(const std::string& scheme, const std::string& host,
                           int port, const std::string& backend = "http");

// Publish the TLS helper address ("host:port"; empty clears) explicitly —
// the race-free alternative to mutating DCT_TLS_PROXY after native threads
// exist (C ABI: dct_set_tls_proxy). The override wins over the env var.
void SetTlsProxyOverride(const std::string& addr);
// Current helper address: the override, else DCT_TLS_PROXY, else "".
std::string TlsProxyAddress();

// "host" or "host:port", omitting the scheme's default port. Signing
// clients (S3 SIG4) MUST build their signed Host with this same formula —
// it is also what ResolveHttpRoute puts on the wire.
std::string DefaultHostHeader(const std::string& scheme,
                              const std::string& host, int port);

// Strip a leading "http://"/"https://" from *s in place; returns the
// scheme, or "" when *s carries none. Throws on any other scheme. Shared
// by the endpoint-env parsers (S3_ENDPOINT / AZURE_ENDPOINT /
// WEBHDFS_NAMENODE).
std::string StripUrlScheme(std::string* s);

class HttpConnection {
 public:
  HttpConnection(const std::string& host, int port);
  // Connect along a resolved route (possibly via the TLS helper; requests
  // then use absolute-form paths and the origin's Host header).
  explicit HttpConnection(const HttpRoute& route);
  ~HttpConnection();
  HttpConnection(const HttpConnection&) = delete;
  HttpConnection& operator=(const HttpConnection&) = delete;

  // Send a full request (path may include the query string).
  void SendRequest(const std::string& method, const std::string& path,
                   const std::map<std::string, std::string>& headers,
                   const std::string& body);

  // Read status line + headers; body is then streamed with ReadBody.
  void ReadResponseHead(HttpResponse* out);
  // Stream up to `size` body bytes; 0 at end of body.
  size_t ReadBody(void* buf, size_t size);
  // Convenience: read the entire remaining body into out->body.
  void ReadFullBody(HttpResponse* out);

 private:
  size_t RawRead(void* buf, size_t size);
  bool ReadLine(std::string* line);

  int fd_ = -1;
  std::string default_host_header_;  // injected when caller sets no Host
  std::string path_prefix_;  // absolute-form prefix when routed via helper
  std::string rbuf_;          // buffered unread bytes
  size_t rpos_ = 0;
  int64_t body_remaining_ = -1;  // -1: read-to-close
  bool chunked_ = false;
  int64_t chunk_remaining_ = 0;
  bool body_done_ = false;
  // per-backend latency histograms (telemetry.h): connect is observed by
  // the ctor, ttfb by the first ReadResponseHead line, recv per ReadBody
  const telemetry::IoHists* io_hists_ = nullptr;
  uint64_t request_sent_us_ = 0;  // end of SendRequest (ttfb anchor)
  bool ttfb_observed_ = false;
};

// One-shot request helper.
HttpResponse HttpRequest(const std::string& host, int port,
                         const std::string& method, const std::string& path,
                         const std::map<std::string, std::string>& headers,
                         const std::string& body);
HttpResponse HttpRequest(const HttpRoute& route, const std::string& method,
                         const std::string& path,
                         const std::map<std::string, std::string>& headers,
                         const std::string& body);

// "host", "host:port", or "[v6literal]:port" -> (host, port). A bare IPv6
// literal (more than one ':' and no brackets) is never split; the bracketed
// form carries the port after the closing ']'.
void SplitHostPort(const std::string& s, std::string* host, int* port,
                   int default_port);

}  // namespace dct

#endif  // DCT_HTTP_H_
