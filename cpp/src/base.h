// dmlc_core_tpu native core — diagnostics and common definitions.
//
// TPU-native counterpart of reference include/dmlc/base.h + logging.h:
// the CHECK macro family throws dct::Error (the reference's throw-on-fatal
// configuration, logging.h:202-212, base.h:21). No glog backend; errors cross
// the C ABI as thread-local message strings (see capi.cc).
#ifndef DCT_BASE_H_
#define DCT_BASE_H_

#include <cstddef>
#include <cstdint>
#include <sstream>
#include <stdexcept>
#include <string>

namespace dct {

class Error : public std::runtime_error {
 public:
  explicit Error(const std::string& what) : std::runtime_error(what) {}
};

namespace detail {
// Collects a message via operator<< and throws on destruction-by-value.
class CheckFailStream {
 public:
  CheckFailStream(const char* expr, const char* file, int line) {
    os_ << file << ":" << line << ": check failed: `" << expr << "` ";
  }
  template <typename T>
  CheckFailStream& operator<<(const T& v) {
    os_ << v;
    return *this;
  }
  [[noreturn]] void Fire() const { throw Error(os_.str()); }

 private:
  std::ostringstream os_;
};

struct CheckFire {
  [[noreturn]] void operator&(const CheckFailStream& s) { s.Fire(); }
};
}  // namespace detail

}  // namespace dct

#define DCT_CHECK(cond)                                       \
  if (!(cond))                                                \
  ::dct::detail::CheckFire() &                                \
      ::dct::detail::CheckFailStream(#cond, __FILE__, __LINE__)

#define DCT_CHECK_BINARY(a, b, op) DCT_CHECK((a)op(b))                     \
      << "(" << (a) << " vs " << (b) << ") "
#define DCT_CHECK_EQ(a, b) DCT_CHECK_BINARY(a, b, ==)
#define DCT_CHECK_NE(a, b) DCT_CHECK_BINARY(a, b, !=)
#define DCT_CHECK_LT(a, b) DCT_CHECK_BINARY(a, b, <)
#define DCT_CHECK_LE(a, b) DCT_CHECK_BINARY(a, b, <=)
#define DCT_CHECK_GT(a, b) DCT_CHECK_BINARY(a, b, >)
#define DCT_CHECK_GE(a, b) DCT_CHECK_BINARY(a, b, >=)

#endif  // DCT_BASE_H_
