// dmlc_core_tpu native core — diagnostics and common definitions.
//
// TPU-native counterpart of reference include/dmlc/base.h + logging.h:
// the CHECK macro family throws dct::Error (the reference's throw-on-fatal
// configuration, logging.h:202-212, base.h:21). No glog backend; errors cross
// the C ABI as thread-local message strings (see capi.cc).
#ifndef DCT_BASE_H_
#define DCT_BASE_H_

#include <cstddef>
#include <cstdint>
#include <sstream>
#include <stdexcept>
#include <string>

namespace dct {

class Error : public std::runtime_error {
 public:
  explicit Error(const std::string& what) : std::runtime_error(what) {}
};

namespace detail {
// Collects a message via operator<< and throws on destruction-by-value.
class CheckFailStream {
 public:
  CheckFailStream(const char* expr, const char* file, int line) {
    os_ << file << ":" << line << ": check failed: `" << expr << "` ";
  }
  template <typename T>
  CheckFailStream& operator<<(const T& v) {
    os_ << v;
    return *this;
  }
  [[noreturn]] void Fire() const { throw Error(os_.str()); }

 private:
  std::ostringstream os_;
};

struct CheckFire {
  [[noreturn]] void operator&(const CheckFailStream& s) { s.Fire(); }
};
}  // namespace detail

}  // namespace dct

#define DCT_CHECK(cond)                                       \
  if (!(cond))                                                \
  ::dct::detail::CheckFire() &                                \
      ::dct::detail::CheckFailStream(#cond, __FILE__, __LINE__)

#define DCT_CHECK_BINARY(a, b, op) DCT_CHECK((a)op(b))                     \
      << "(" << (a) << " vs " << (b) << ") "
#define DCT_CHECK_EQ(a, b) DCT_CHECK_BINARY(a, b, ==)
#define DCT_CHECK_NE(a, b) DCT_CHECK_BINARY(a, b, !=)
#define DCT_CHECK_LT(a, b) DCT_CHECK_BINARY(a, b, <)
#define DCT_CHECK_LE(a, b) DCT_CHECK_BINARY(a, b, <=)
#define DCT_CHECK_GT(a, b) DCT_CHECK_BINARY(a, b, >)
#define DCT_CHECK_GE(a, b) DCT_CHECK_BINARY(a, b, >=)

// ---------------------------------------------------------------------------
// Thread-safety capability annotations (doc/analysis.md).
//
// Under clang these expand to the thread-safety-analysis attributes, so a
// `clang -Wthread-safety` build checks them natively; under gcc (this
// image's compiler) they expand to nothing and the structural checker in
// scripts/analyze.py enforces the same contract: every member declared
// DMLC_GUARDED_BY(m) may only be touched inside a lock_guard/unique_lock/
// scoped_lock scope of `m`, or inside a function declared DMLC_REQUIRES(m).
// Audited exceptions (single-threaded teardown, pre-spawn init) carry a
// `// lock-ok: <reason>` comment on the touching line.
//
//   std::mutex mu_;
//   std::deque<Task*> q_ DMLC_GUARDED_BY(mu_);
//   void DrainLocked() DMLC_REQUIRES(mu_);   // caller holds mu_
//   void Publish() DMLC_EXCLUDES(mu_);       // caller must NOT hold mu_
#if defined(__clang__) && defined(__has_attribute)
#if __has_attribute(guarded_by)
#define DMLC_THREAD_ANNOTATION(x) __attribute__((x))
#endif
#endif
#ifndef DMLC_THREAD_ANNOTATION
#define DMLC_THREAD_ANNOTATION(x)  // no-op under gcc; analyze.py checks
#endif
#define DMLC_GUARDED_BY(m) DMLC_THREAD_ANNOTATION(guarded_by(m))
#define DMLC_REQUIRES(m) DMLC_THREAD_ANNOTATION(requires_capability(m))
#define DMLC_EXCLUDES(m) DMLC_THREAD_ANNOTATION(locks_excluded(m))

#endif  // DCT_BASE_H_
