// Fixed-size memory pooling utilities.
//
// Counterpart of reference include/dmlc/memory.h (MemoryPool page+free-list
// allocator, ThreadlocalAllocator) and include/dmlc/thread_local.h
// (ThreadLocalStore). The reference targets pre-C++11 thread_local
// portability; here C++17 `thread_local` is a given so ThreadLocalStore is
// a thin function-local singleton, and the pool keeps the same design:
// pages of N objects carved sequentially, frees pushed on an intrusive
// free list, everything released when the pool dies.
#ifndef DCT_MEMORY_H_
#define DCT_MEMORY_H_

#include <cstddef>
#include <memory>
#include <type_traits>
#include <vector>

#include "base.h"

namespace dct {

// Thread-local singleton of T (reference thread_local.h:35 ThreadLocalStore).
template <typename T>
class ThreadLocalStore {
 public:
  static T* Get() {
    static thread_local T inst;
    return &inst;
  }
};

// Pool of fixed-size, fixed-alignment allocations (reference memory.h:24-78
// MemoryPool): O(1) allocate/deallocate, no per-object malloc.
template <size_t kSize, size_t kAlign>
class MemoryPool {
 public:
  MemoryPool() {
    static_assert(kAlign % alignof(FreeNode) == 0,
                  "alignment must fit the free-list node");
    curr_page_.reset(new Page());
  }

  void* allocate() {
    if (head_ != nullptr) {
      FreeNode* ret = head_;
      head_ = head_->next;
      return ret;
    }
    if (page_pos_ < kPageLen) {
      return &curr_page_->data[page_pos_++];
    }
    full_pages_.push_back(std::move(curr_page_));
    curr_page_.reset(new Page());
    page_pos_ = 1;
    return &curr_page_->data[0];
  }

  void deallocate(void* p) {
    FreeNode* node = static_cast<FreeNode*>(p);
    node->next = head_;
    head_ = node;
  }

 private:
  // ~4 MB pages, at least one object each
  static constexpr size_t kPageLen =
      (1 << 22) / kSize > 0 ? (1 << 22) / kSize : 1;
  struct Page {
    typename std::aligned_storage<kSize, kAlign>::type data[kPageLen];
  };
  struct FreeNode {
    FreeNode* next = nullptr;
  };

  FreeNode* head_ = nullptr;
  std::unique_ptr<Page> curr_page_;
  size_t page_pos_ = 0;
  std::vector<std::unique_ptr<Page>> full_pages_;
};

// STL-compatible single-object allocator backed by a thread-local pool
// (reference memory.h:80-144 ThreadlocalAllocator): for containers like
// std::list/std::map whose nodes never cross threads.
template <typename T>
class ThreadlocalAllocator {
 public:
  using pointer = T*;
  using const_pointer = const T*;
  using value_type = T;

  ThreadlocalAllocator() = default;
  template <typename U>
  ThreadlocalAllocator(const ThreadlocalAllocator<U>&) {}  // NOLINT

  T* allocate(size_t n) {
    DCT_CHECK_EQ(n, size_t(1))
        << "ThreadlocalAllocator serves single-object nodes only";
    using Store = ThreadLocalStore<MemoryPool<sizeof(T), alignof(T)>>;
    return static_cast<T*>(Store::Get()->allocate());
  }

  void deallocate(T* p, size_t n) {
    DCT_CHECK_EQ(n, size_t(1));
    using Store = ThreadLocalStore<MemoryPool<sizeof(T), alignof(T)>>;
    Store::Get()->deallocate(p);
  }

  template <typename U>
  bool operator==(const ThreadlocalAllocator<U>&) const {
    return true;
  }
  template <typename U>
  bool operator!=(const ThreadlocalAllocator<U>&) const {
    return false;
  }
};

}  // namespace dct

#endif  // DCT_MEMORY_H_
