// Gated remote filesystem schemes.
//
// Reference parity notes:
// - HDFS now has a real implementation over WebHDFS (hdfs_filesys.cc).
// - Azure (reference src/io/azure_filesys.{h,cc}) is a partial stub in the
//   reference itself: only ListDirectory is implemented and Open/OpenForRead
//   return NULL (azure_filesys.h:26-32). Matching surface here, explicit.
#include "filesys.h"

namespace dct {
namespace {

FileSystem* Unavailable(const char* scheme, const char* detail) {
  throw Error(std::string(scheme) +
              ":// filesystem is not built into this binary: " + detail);
}

struct RemoteStubRegistrar {
  RemoteStubRegistrar() {
    FileSystem::RegisterScheme("azure", [](const URI&) {
      return Unavailable("azure",
                         "the reference implementation is itself a partial "
                         "stub (azure_filesys.h:26-32); use s3:// against "
                         "an S3-compatible gateway");
    });
  }
} remote_stub_registrar;

}  // namespace
}  // namespace dct
