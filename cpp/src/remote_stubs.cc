// Gated remote filesystem schemes.
//
// Reference parity notes:
// - HDFS (reference src/io/hdfs_filesys.{h,cc}) binds libhdfs via JNI and is
//   enabled by a build flag (reference CMakeLists.txt:71-83). libhdfs is not
//   part of this toolchain, so the scheme registers an informative error;
//   the URI surface (hdfs:// and viewfs://) is reserved and dispatched.
// - Azure (reference src/io/azure_filesys.{h,cc}) is a partial stub in the
//   reference itself: only ListDirectory is implemented and Open/OpenForRead
//   return NULL (azure_filesys.h:26-32). Matching surface here, explicit.
#include "filesys.h"

namespace dct {
namespace {

FileSystem* Unavailable(const char* scheme, const char* detail) {
  throw Error(std::string(scheme) +
              ":// filesystem is not built into this binary: " + detail);
}

struct RemoteStubRegistrar {
  RemoteStubRegistrar() {
    FileSystem::RegisterScheme("hdfs", [](const URI&) {
      return Unavailable("hdfs",
                         "requires libhdfs (reference gates it behind a "
                         "build flag too, CMakeLists.txt:71-83); stage data "
                         "through s3:// or file:// instead");
    });
    FileSystem::RegisterScheme("viewfs", [](const URI&) {
      return Unavailable("viewfs", "requires libhdfs (see hdfs://)");
    });
    FileSystem::RegisterScheme("azure", [](const URI&) {
      return Unavailable("azure",
                         "the reference implementation is itself a partial "
                         "stub (azure_filesys.h:26-32); use s3:// against "
                         "an S3-compatible gateway");
    });
  }
} remote_stub_registrar;

}  // namespace
}  // namespace dct
