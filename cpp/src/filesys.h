// Filesystem interface with URI-scheme dispatch.
//
// Counterpart of reference include/dmlc/io.h:582-631 (FileSystem) and
// src/io.cc:30-71 (protocol dispatch singleton table). LocalFileSystem
// mirrors src/io/local_filesys.{h,cc}: stdio-backed streams, stat/dirent
// listing, stdin/stdout passthrough. Remote filesystems register themselves
// into the same dispatch table (s3 in s3_filesys.cc).
#ifndef DCT_FILESYS_H_
#define DCT_FILESYS_H_

#include <functional>
#include <string>
#include <vector>

#include "stream.h"

namespace dct {

enum class FileType { kFile, kDirectory };

struct FileInfo {
  URI path;
  size_t size = 0;
  FileType type = FileType::kFile;
};

class FileSystem {
 public:
  virtual ~FileSystem() = default;
  virtual FileInfo GetPathInfo(const URI& path) = 0;
  virtual void ListDirectory(const URI& path, std::vector<FileInfo>* out) = 0;
  virtual Stream* Open(const URI& path, const char* mode,
                       bool allow_null = false) = 0;
  virtual SeekStream* OpenForRead(const URI& path, bool allow_null = false) = 0;

  // BFS recursive listing (reference src/io/filesys.cc:9-25).
  void ListDirectoryRecursive(const URI& path, std::vector<FileInfo>* out);

  // Scheme dispatch: ""/"file" -> local, registered schemes otherwise
  // (reference src/io.cc:30-71).
  static FileSystem* GetInstance(const URI& uri);
  // Register a scheme -> singleton-factory (returns borrowed pointer).
  static void RegisterScheme(const std::string& scheme,
                             std::function<FileSystem*(const URI&)> factory);
};

// Scoped mkdtemp-style temporary directory with recursive delete on
// destruction; refuses to traverse symlinks while deleting (counterpart of
// reference include/dmlc/filesystem.h:54 TemporaryDirectory +
// src/io/filesys.cc:29-58).
class TemporaryDirectory {
 public:
  explicit TemporaryDirectory(bool verbose = false);
  ~TemporaryDirectory();
  TemporaryDirectory(const TemporaryDirectory&) = delete;
  TemporaryDirectory& operator=(const TemporaryDirectory&) = delete;

  const std::string& path() const { return path_; }

 private:
  static void RecursiveDelete(const std::string& path);
  std::string path_;
  bool verbose_;
};

class LocalFileSystem : public FileSystem {
 public:
  static LocalFileSystem* GetInstance();
  FileInfo GetPathInfo(const URI& path) override;
  void ListDirectory(const URI& path, std::vector<FileInfo>* out) override;
  Stream* Open(const URI& path, const char* mode,
               bool allow_null = false) override;
  SeekStream* OpenForRead(const URI& path, bool allow_null = false) override;

 private:
  LocalFileSystem() = default;
};

}  // namespace dct

#endif  // DCT_FILESYS_H_
