// Parse-once, serve-many: the transcoding binary shard cache.
//
// Counterpart of reference src/io/cached_input_split.h taken one layer up
// the stack (ROADMAP "Parse-once, serve-many"): instead of caching raw
// record CHUNKS (the split-level CachedSplit, input_split.h) or re-loading
// serialized containers through a stream (DiskCacheParser, parser.h), the
// first pass through any text source tees the DECODED row blocks into a
// binary shard file laid out for mmap — every array 8-byte aligned in
// final plane order (the csr_rec/dense_rec discipline of fixing the device
// layout on disk, extended to full row-block fidelity so cache-vs-text
// byte-identity holds for every format). Later epochs mmap the shard and
// serve RowBlockView pointers straight into the mapping: zero copies on
// the C-ABI lane, one bulk memcpy on the container lanes — either way the
// text tokenizer never runs again.
//
// Shard file layout (`<key>.p<part>.n<npart>.dshard`, little-endian):
//   header (80 B): u64 magic  u32 version  u32 index_is_64
//                  u64 blocks u64 rows     u64 nnz
//                  u8 key_digest[32] (SHA-256 of the manifest key text)
//                  u8 pad[8]
//   per block:     u32 block magic 'DSB1'   u32 flags (bit0 weight,
//                  bit1 qid, bit2 field; bits 8..9 value_dtype;
//                  bit10 has_value)
//                  u64 rows   u64 nnz   u64 max_index
//                  u32 max_field   u32 reserved
//                  then the arrays, each padded to 8-byte alignment:
//                  offset[rows+1] u64, label[rows] f32, [weight f32],
//                  [qid u64], [field u32], index[nnz] u32|u64,
//                  [value f32 | value_i32 | value_i64]
//
// Manifest (`<stem>.manifest`, plain `k=v` lines) is written ONLY after
// the shard file has been fsync'd and atomically renamed into place, so a
// crash mid-transcode leaves no manifest and the next open re-transcodes
// instead of serving a truncated dataset. Validation on open re-derives
// the key text (URI + split params + parser args + format version),
// compares its SHA-256 against both the manifest and the shard header,
// and checks the recorded byte size — any mismatch (changed parser args,
// partial write, foreign file) is a MISS, never an error: the text lane
// is always the fallback. Writers stage under `.tmp.<pid>` names, so
// concurrent transcoders of the same unit never corrupt each other (last
// publish wins; both are byte-identical by construction).
//
// Telemetry (doc/observability.md): cache_hits_total / cache_misses_total
// / cache_transcodes_total counters, cache_read_us / cache_write_us
// per-block histograms.
#ifndef DCT_SHARD_CACHE_H_
#define DCT_SHARD_CACHE_H_

#include <functional>
#include <map>
#include <memory>
#include <string>
#include <vector>

#include "parser.h"
#include "rowblock.h"

namespace dct {

constexpr uint64_t kShardCacheMagic = 0x0A31445241485344ull;  // "DSHARD1\n"
constexpr uint32_t kShardCacheVersion = 1;
constexpr uint32_t kShardBlockMagic = 0x31425344;  // 'DSB1'

// ------------------------------------------------------------------ config --
// never: cache layer disabled; auto: replay when valid, else transcode;
// refresh: force one re-transcode, then replay.
enum class ShardCacheMode { kNever = 0, kAuto = 1, kRefresh = 2 };

struct ShardCacheConfig {
  std::string dir;  // empty = disabled
  ShardCacheMode mode = ShardCacheMode::kAuto;
  bool explicit_opt_in = false;  // URI sugar / API arg (vs env-only)

  bool enabled() const {
    return !dir.empty() && mode != ShardCacheMode::kNever;
  }

  // Layered resolution: explicit args > URI sugar (#cachefile=<dir>,
  // ?cache=) > env (DMLC_DATA_CACHE_DIR, DMLC_DATA_CACHE). Throws Error on
  // an unknown mode word (a typo'd knob must not silently disable the
  // cache — the checked-env rule, retry.h CheckedEnvInt).
  static ShardCacheConfig Resolve(const std::string& uri_cache_dir,
                                  const std::string& uri_cache_mode,
                                  const std::string& arg_cache_dir,
                                  const std::string& arg_cache_mode);
};

// Parse one of never|auto|refresh ("" = dflt). Throws on anything else.
ShardCacheMode ParseShardCacheMode(const std::string& what,
                                   const std::string& text,
                                   ShardCacheMode dflt);

// Deterministic manifest key text for one cache unit. `args` is the
// parser's URI-arg map minus the cache knobs themselves (they select the
// cache, they do not change the parsed bytes).
std::string ShardCacheKeyText(const std::string& uri, unsigned part,
                              unsigned npart, const std::string& format,
                              bool index64,
                              const std::map<std::string, std::string>& args);

// `<dir>/<sha16>.p<part>.n<npart>` — the shard/manifest filename stem.
std::string ShardCacheStem(const std::string& dir, const std::string& key,
                           unsigned part, unsigned npart);

// -------------------------------------------------------------- writer -----
// Appends row blocks to `<stem>.dshard.tmp.<pid>`; Finalize() fsyncs,
// atomically renames the shard into place, then publishes the manifest
// (same temp+fsync+rename dance). Abandon() (or destruction without
// Finalize) deletes the temp — a partial transcode is never visible.
class ShardCacheWriterImpl;

template <typename IndexType>
class ShardCacheWriter {
 public:
  ShardCacheWriter(const std::string& stem, const std::string& key_text);
  ~ShardCacheWriter();

  void Append(const RowBlockContainer<IndexType>& b);
  void Finalize();
  void Abandon();
  // Like Abandon, but the partial temp is kept under `.quarantined` (the
  // I/O-fault landing — doc/robustness.md "Local durability"); the
  // age-based sweep at writer construction reaps it later.
  void Quarantine();
  uint64_t blocks() const;

 private:
  std::unique_ptr<ShardCacheWriterImpl> impl_;
};

// -------------------------------------------------------------- reader -----
// mmap-backed zero-copy replay. TryOpen returns nullptr on any validation
// miss (absent/stale/corrupt manifest or shard). Views point into the
// mapping and stay valid for the reader's lifetime.
class MmapShardReaderImpl;

template <typename IndexType>
class MmapShardReader {
 public:
  static MmapShardReader* TryOpen(const std::string& stem,
                                  const std::string& key_text);
  ~MmapShardReader();

  bool NextView(RowBlockView<IndexType>* out);
  void BeforeFirst();
  uint64_t blocks() const;
  size_t bytes_consumed() const;  // mapped bytes walked so far
  size_t total_bytes() const;

 private:
  MmapShardReader();
  std::unique_ptr<MmapShardReaderImpl> impl_;
};

// ------------------------------------------------------- parser wrapper ----
// The cache layer of Parser::Create: on construction (mode=auto) a valid
// shard makes the whole epoch an mmap replay and the base parser chain —
// including any remote filesystem open — is NEVER built; otherwise the
// base is built lazily from `factory`, every block it parses is teed into
// the writer, and the completed pass publishes the shard so the NEXT
// BeforeFirst flips to replay.
template <typename IndexType>
class ShardCacheParser : public Parser<IndexType> {
 public:
  using BaseFactory = std::function<Parser<IndexType>*()>;

  ShardCacheParser(BaseFactory factory, const ShardCacheConfig& cfg,
                   const std::string& stem, const std::string& key_text);
  ~ShardCacheParser() override;

  void BeforeFirst() override;
  const RowBlockContainer<IndexType>* NextBlock() override;
  bool NextBlockMove(RowBlockContainer<IndexType>* out) override;
  bool NextBlockView(RowBlockView<IndexType>* out) override;
  size_t BytesRead() const override;
  bool SetShuffleEpoch(unsigned epoch) override {
    // unreachable in practice: Create forbids shuffle + caching
    return base_ != nullptr && base_->SetShuffleEpoch(epoch);
  }
  bool GetPipelineStats(ParsePipelineStats* out) const override {
    // meaningful during the transcode epoch; replay bypasses the parse
    // pipeline entirely (same contract as DiskCacheParser)
    return base_ != nullptr && base_->GetPipelineStats(out);
  }

  bool replaying() const { return reader_ != nullptr; }

 private:
  Parser<IndexType>* EnsureBase();
  void FinishTranscode();  // publish a completed pass
  // A pull that threw may have dropped blocks the consumer will skip
  // over (RowBlockIter on_error="skip" keeps pulling): the pass can no
  // longer prove completeness, so it must never publish — abandon the
  // temp and stop teeing until the next BeforeFirst re-tees from the
  // start. Also the landing for a failed tee itself (disk full): the
  // cache degrades to "no cache", it never breaks the text lane.
  // `quarantine` keeps the partial temp under `.quarantined` (the cache
  // I/O-fault path) instead of deleting it (the parse-error path).
  void PoisonTranscode(bool quarantine = false);
  const RowBlockContainer<IndexType>* PullBase();  // NextBlock + poison
  void TeeBlock(const RowBlockContainer<IndexType>& b);

  BaseFactory factory_;
  ShardCacheConfig cfg_;
  std::string stem_;
  std::string key_text_;
  std::unique_ptr<Parser<IndexType>> base_;
  std::unique_ptr<MmapShardReader<IndexType>> reader_;
  std::unique_ptr<ShardCacheWriter<IndexType>> writer_;
  RowBlockContainer<IndexType> scratch_;  // NextBlock materialization
  bool write_complete_ = false;
  bool refresh_pending_ = false;  // mode=refresh: one forced re-transcode
  bool iterated_ = false;  // any Next* since the last lane decision
};

}  // namespace dct

#endif  // DCT_SHARD_CACHE_H_
