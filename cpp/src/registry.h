// Name → factory-entry singleton registries.
//
// Counterpart of reference include/dmlc/registry.h (310 L): a per-EntryType
// global map with __REGISTER__/Find/ListAllNames, and FunctionRegEntryBase
// carrying description + typed argument metadata (ParamFieldInfo). The
// reference's DMLC_REGISTRY_FILE_TAG/LINK_TAG static-link rescue machinery
// is dropped: this core always builds as one shared object, so registration
// order is a non-problem.
#ifndef DCT_REGISTRY_H_
#define DCT_REGISTRY_H_

#include <map>
#include <memory>
#include <string>
#include <vector>

#include "base.h"
#include "parameter.h"

namespace dct {

template <typename EntryType>
class Registry {
 public:
  static Registry* Get() {
    static Registry inst;
    return &inst;
  }

  // Register (or fetch for further chaining) the entry under `name`
  // (reference __REGISTER__, registry.h:78).
  EntryType& __REGISTER__(const std::string& name) {
    auto it = entries_.find(name);
    DCT_CHECK(it == entries_.end())
        << "registry entry " << name << " already registered";
    auto e = std::make_unique<EntryType>();
    e->name = name;
    EntryType* raw = e.get();
    entries_[name] = std::move(e);
    names_.push_back(name);
    return *raw;
  }

  EntryType& __REGISTER_OR_GET__(const std::string& name) {
    auto it = entries_.find(name);
    if (it != entries_.end()) return *it->second;
    return __REGISTER__(name);
  }

  // reference Registry::Find (registry.h:48-56) — nullptr when absent.
  EntryType* Find(const std::string& name) const {
    auto it = entries_.find(name);
    return it == entries_.end() ? nullptr : it->second.get();
  }

  std::vector<std::string> ListAllNames() const { return names_; }

 private:
  Registry() = default;
  std::map<std::string, std::unique_ptr<EntryType>> entries_;
  std::vector<std::string> names_;  // registration order
};

// Common base for function-style registry entries (reference
// FunctionRegEntryBase, registry.h:150-226).
template <typename EntryType, typename FunctionType>
struct FunctionRegEntryBase {
  std::string name;
  std::string description;
  std::vector<ParamFieldInfo> arguments;
  FunctionType body;
  std::string return_type;

  EntryType& set_body(FunctionType f) {
    body = f;
    return Self();
  }
  EntryType& describe(const std::string& d) {
    description = d;
    return Self();
  }
  EntryType& add_argument(const std::string& aname, const std::string& type,
                          const std::string& desc) {
    ParamFieldInfo info;
    info.name = aname;
    info.type = type;
    info.type_info_str = type;
    info.description = desc;
    arguments.push_back(info);
    return Self();
  }
  EntryType& add_arguments(const std::vector<ParamFieldInfo>& args) {
    arguments.insert(arguments.end(), args.begin(), args.end());
    return Self();
  }
  EntryType& set_return_type(const std::string& t) {
    return_type = t;
    return Self();
  }

 protected:
  EntryType& Self() { return *static_cast<EntryType*>(this); }
};

// Static-registration helper (reference DMLC_REGISTRY_REGISTER):
//   DCT_REGISTRY_REGISTER(ParserFactoryReg, parser, libsvm).set_body(...);
#define DCT_REGISTRY_REGISTER(EntryType, TypeName, Name)                  \
  static EntryType& __make_##TypeName##_##Name##__ [[maybe_unused]] =     \
      ::dct::Registry<EntryType>::Get()->__REGISTER__(#Name)

}  // namespace dct

#endif  // DCT_REGISTRY_H_
