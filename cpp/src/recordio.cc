// RecordIO implementation. Format spec: see recordio.h (byte-compatible with
// reference include/dmlc/recordio.h; implementation is original).
#include "recordio.h"

#include <algorithm>
#include <vector>

namespace dct {

using recordio::AlignUp4;
using recordio::EncodeHeader;
using recordio::HeaderFlag;
using recordio::HeaderLen;
using recordio::IsRecordHead;
using recordio::kMagic;
using recordio::LoadWordLE;

namespace {

inline void WriteWordLE(Stream* s, uint32_t w) {
  if (!serial::NativeIsLE()) w = serial::ByteSwap(w);
  s->Write(&w, 4);
}

// Find the next 4-aligned offset in [from, len) where the payload contains
// the magic pattern; len is truncated to aligned length. Returns len if none.
inline size_t NextEmbeddedMagic(const char* data, size_t from, size_t len) {
  char magic_bytes[4];
  uint32_t m = kMagic;
  if (!serial::NativeIsLE()) m = serial::ByteSwap(m);
  std::memcpy(magic_bytes, &m, 4);
  size_t aligned_len = len & ~size_t(3);
  for (size_t i = from; i + 4 <= aligned_len; i += 4) {
    if (std::memcmp(data + i, magic_bytes, 4) == 0) return i;
  }
  return len;
}

}  // namespace

void RecordIOWriter::WriteRecord(const void* buf, size_t size) {
  DCT_CHECK_LT(size, size_t(1) << 29) << "RecordIO record must be < 2^29 B";
  const char* data = static_cast<const char*>(buf);
  // Split payload at embedded aligned magics. Each split elides the magic
  // itself (readers re-insert it between parts).
  size_t part_begin = 0;
  bool is_first = true;
  while (true) {
    size_t cut = NextEmbeddedMagic(data, part_begin, size);
    bool is_last = (cut == size);
    uint32_t part_len = static_cast<uint32_t>(cut - part_begin);
    uint32_t cflag;
    if (is_first && is_last) {
      cflag = 0;
    } else if (is_first) {
      cflag = 1;
    } else if (is_last) {
      cflag = 3;
    } else {
      cflag = 2;
    }
    WriteWordLE(stream_, kMagic);
    WriteWordLE(stream_, EncodeHeader(cflag, part_len));
    if (part_len != 0) stream_->Write(data + part_begin, part_len);
    if (is_last) {
      size_t pad = AlignUp4(part_len) - part_len;
      if (pad != 0) {
        const char zeros[4] = {0, 0, 0, 0};
        stream_->Write(zeros, pad);
      }
      break;
    }
    ++escape_count_;
    part_begin = cut + 4;  // skip the elided magic
    is_first = false;
  }
}

bool RecordIOReader::NextRecord(std::string* out) {
  if (eof_) return false;
  out->clear();
  while (true) {
    // header fill loop: Stream::Read may legally return short (buffered/
    // ranged remote streams at a chunk boundary) — only got==0 at a
    // record boundary is EOF; got==0 mid-header is a torn file
    char header[8];
    size_t hfill = 0;
    while (hfill < 8) {
      size_t n = stream_->Read(header + hfill, 8 - hfill);
      if (n == 0) break;
      hfill += n;
    }
    if (hfill == 0 && out->empty()) {
      eof_ = true;
      return false;
    }
    // structured corruption errors: a torn file (crash mid-append, short
    // write) must name WHERE the stream broke, not just that it did —
    // the operator's first question is "how much survived"
    DCT_CHECK_EQ(hfill, size_t(8))
        << "truncated recordio header after record " << records_
        << " at byte offset " << bytes_in_;
    DCT_CHECK_EQ(LoadWordLE(header), kMagic)
        << "bad recordio magic after record " << records_
        << " at byte offset " << bytes_in_;
    uint32_t lrec = LoadWordLE(header + 4);
    uint32_t cflag = HeaderFlag(lrec);
    uint32_t len = HeaderLen(lrec);
    size_t padded = AlignUp4(len);
    size_t old = out->size();
    out->resize(old + padded);
    size_t filled = 0;
    while (filled < padded) {
      size_t got = stream_->Read(&(*out)[old + filled], padded - filled);
      DCT_CHECK_GT(got, size_t(0))
          << "truncated recordio payload (" << (padded - filled)
          << " of " << padded << " bytes missing) after record "
          << records_ << " at byte offset " << bytes_in_;
      filled += got;
    }
    bytes_in_ += 8 + padded;
    out->resize(old + len);  // drop pad
    if (cflag == 0 || cflag == 3) {
      ++records_;
      return true;
    }
    // re-insert the elided magic between parts
    char magic_bytes[4];
    uint32_t m = kMagic;
    if (!serial::NativeIsLE()) m = serial::ByteSwap(m);
    std::memcpy(magic_bytes, &m, 4);
    out->append(magic_bytes, 4);
  }
}

const char* FindRecordHead(const char* base, const char* begin,
                           const char* end) {
  // scan 4-aligned offsets relative to base
  size_t ofs = AlignUp4(static_cast<size_t>(begin - base));
  size_t limit = static_cast<size_t>(end - base);
  for (; ofs + 8 <= limit; ofs += 4) {
    if (IsRecordHead(base + ofs)) return base + ofs;
  }
  return end;
}

RecordIOChunkReader::RecordIOChunkReader(const char* begin, const char* end,
                                         unsigned part_index,
                                         unsigned num_parts) {
  size_t size = static_cast<size_t>(end - begin);
  size_t step = AlignUp4((size + num_parts - 1) / num_parts);
  size_t lo = std::min(size, step * part_index);
  size_t hi = std::min(size, step * (part_index + 1));
  cur_ = FindRecordHead(begin, begin + lo, end);
  end_ = FindRecordHead(begin, begin + hi, end);
}

bool RecordIOChunkReader::NextRecord(Blob* out) {
  if (cur_ >= end_) return false;
  DCT_CHECK_EQ(LoadWordLE(cur_), kMagic) << "bad recordio chunk";
  uint32_t lrec = LoadWordLE(cur_ + 4);
  uint32_t cflag = HeaderFlag(lrec);
  uint32_t len = HeaderLen(lrec);
  if (cflag == 0) {
    out->dptr = cur_ + 8;
    out->size = len;
    cur_ += 8 + AlignUp4(len);
    DCT_CHECK_LE(cur_, end_) << "recordio record overruns chunk";
    return true;
  }
  DCT_CHECK_EQ(cflag, 1u) << "multi-part record must start with cflag=1";
  assembled_.clear();
  while (true) {
    DCT_CHECK_LE(cur_ + 8, end_) << "truncated multi-part record";
    DCT_CHECK_EQ(LoadWordLE(cur_), kMagic) << "bad recordio chunk";
    lrec = LoadWordLE(cur_ + 4);
    cflag = HeaderFlag(lrec);
    len = HeaderLen(lrec);
    assembled_.append(cur_ + 8, len);
    cur_ += 8 + AlignUp4(len);
    DCT_CHECK_LE(cur_, end_) << "recordio record overruns chunk";
    if (cflag == 3) break;
    char magic_bytes[4];
    uint32_t m = kMagic;
    if (!serial::NativeIsLE()) m = serial::ByteSwap(m);
    std::memcpy(magic_bytes, &m, 4);
    assembled_.append(magic_bytes, 4);
  }
  out->dptr = assembled_.data();
  out->size = assembled_.size();
  return true;
}

}  // namespace dct
