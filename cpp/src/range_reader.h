// Concurrent ranged-read engine for remote SeekStreams.
//
// Every remote backend here (s3/azure/http(s)/webhdfs) serves ranged GETs,
// but the sequential readers consume one connection per split — so a single
// connection's latency-bandwidth product caps ingest no matter how fast the
// parse pipeline runs. RangeReader splits one logical stream into N
// in-flight ranged fetches that land out of order and are handed to the
// consumer strictly IN order (head-of-line delivery): the bytes a caller
// sees are byte-identical to the sequential lane by construction.
//
// Design rules:
//   - Each range fetch is an IDEMPOTENT one-shot riding the shared
//     RetryPolicy (retry.h): a reset/stall/5xx retries only that range,
//     never restarts the stream, and a non-retryable status fails the
//     stream exactly like the sequential lane would.
//   - An adaptive scheduler picks range size and concurrency per stream:
//     seeded from the live per-backend io_{connect,ttfb}_us telemetry
//     (PR 5), then AIMD on observed per-range goodput — additive range
//     growth while setup cost still shows, multiplicative shrink when a
//     range had to retry; concurrency ramps up on head-of-line waits and
//     halves when a range needed 2+ retries.
//   - Servers that ignore Range and answer 200 degrade cleanly: the reader
//     falls back to the backend's sequential stream (which already knows
//     how to resume-at-offset under a 200, including its tightened retry
//     budget), sought to the current position. Seek-thrashing consumers
//     (indexed shuffles) degrade the same way once prefetch waste
//     outweighs delivered bytes.
//   - DMLC_IO_RANGE=0 is the kill switch; DMLC_IO_RANGE_{MIN,MAX}_BYTES and
//     DMLC_IO_RANGE_CONCURRENCY clamp the scheduler (checked parses), and
//     `?io_range*=` URI args override per open (stream opens only — the
//     parser lane configures through env, same rule as the retry knobs).
#ifndef DCT_RANGE_READER_H_
#define DCT_RANGE_READER_H_

#include <atomic>
#include <condition_variable>
#include <functional>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "retry.h"
#include "stream.h"
#include "telemetry.h"

namespace dct {
namespace io {

// ---------------------------------------------------------------- config --
struct RangeConfig {
  bool enabled = true;            // DMLC_IO_RANGE=0 falls back to sequential
  size_t min_bytes = 256 << 10;   // DMLC_IO_RANGE_MIN_BYTES
  size_t max_bytes = 4 << 20;     // DMLC_IO_RANGE_MAX_BYTES
  int max_concurrency = 4;        // DMLC_IO_RANGE_CONCURRENCY

  // Defaults <- DMLC_IO_RANGE* env (checked parses; re-read per open so
  // tests and operators can reshape between streams).
  static RangeConfig FromEnv();

  // Consume one `io_range*` URI arg (io_range, io_range_min_bytes,
  // io_range_max_bytes, io_range_concurrency). Returns false when the key
  // is not a range knob. Throws on non-numeric values.
  bool ApplyUriArg(const std::string& key, const std::string& value);
};

// Strip ALL per-open `io_*` URI args from `path`: range knobs into *rcfg,
// then the retry/timeout family via ExtractUriRetryArgs. The one entry
// point every remote OpenForRead calls.
void ExtractUriIoArgs(std::string* path, RetryPolicy* policy,
                      int* timeout_ms_override, RangeConfig* rcfg);

// ---------------------------------------------------------------- fetcher --
enum class FetchStatus {
  kOk,        // buf holds exactly the requested bytes
  kDegraded,  // origin ignored Range (200 full-body): fall back sequential
};

// One idempotent ranged GET per call: fetch exactly [offset, offset+len)
// into buf on a FRESH connection. Implementations throw HttpStatusError /
// TimeoutError / Error on failure (retryability is the caller's decision,
// same classification as the sequential lane) and return kDegraded when
// the origin ignored the Range request. `*progress` (never null) must
// count the bytes already landed in buf when an exception cuts the
// transfer mid-body: the caller's retry resumes from offset+progress —
// the ranged twin of reconnect-at-offset — so truncation faults always
// converge instead of refetching a range from scratch forever.
class RangeFetcher {
 public:
  virtual ~RangeFetcher() = default;
  virtual FetchStatus Fetch(size_t offset, size_t len, char* buf,
                            size_t* progress) = 0;
};

// ----------------------------------------------------------------- reader --
class RangeReader : public SeekStream {
 public:
  // `sequential_factory` builds the backend's plain reconnect-at-offset
  // stream — the degrade target (and must inherit that lane's 200-resume
  // budget rule). `policy` is copied; per-range RetryControllers reference
  // the copy.
  RangeReader(const char* backend, size_t file_size,
              std::unique_ptr<RangeFetcher> fetcher,
              std::function<SeekStream*()> sequential_factory,
              const RangeConfig& cfg, const RetryPolicy& policy,
              int timeout_ms_override);
  ~RangeReader() override;

  size_t Read(void* ptr, size_t size) override;
  size_t Write(const void*, size_t) override;
  void Seek(size_t pos) override;
  size_t Tell() override;
  // Stop carving at `end` (partitioned splits end mid-object); a read or
  // seek reaching `end` clears the hint and carving resumes.
  void HintReadBound(size_t end) override;

  // Scheduler introspection for tests (test_core --range).
  struct Stats {
    uint64_t ranges_fetched = 0;
    uint64_t range_retries = 0;
    uint64_t discontinuities = 0;
    size_t range_bytes = 0;
    int concurrency = 0;
    bool degraded = false;
  };
  Stats stats();

 private:
  struct Segment {
    // raw buffer, NOT std::string: a string resize would zero-fill every
    // range buffer just for the fetch to overwrite it
    std::unique_ptr<char[]> data;
    size_t size = 0;
  };

  void StartWorkersLocked() DMLC_REQUIRES(mu_);
  void WorkerLoop(int id);
  bool ShouldExitLocked() const DMLC_REQUIRES(mu_);
  bool WantWorkLocked(int id) const DMLC_REQUIRES(mu_);
  size_t CarveEndLocked() const DMLC_REQUIRES(mu_);
  bool HeadReadyLocked() const DMLC_REQUIRES(mu_);
  void TrimConsumedLocked() DMLC_REQUIRES(mu_);
  void AdaptAfterRangeLocked(size_t len, uint64_t elapsed_us,
                             int retries) DMLC_REQUIRES(mu_);
  // consumer-side: build the sequential fallback at the current position
  // (called outside mu_ — the factory may do network I/O)
  void SwitchToSequential(size_t pos);

  const std::string backend_;
  const size_t file_size_;
  std::unique_ptr<RangeFetcher> fetcher_;
  std::function<SeekStream*()> seq_factory_;
  const RangeConfig cfg_;
  const RetryPolicy policy_;  // stable: per-range controllers reference it
  const int timeout_ms_override_;
  const telemetry::RangeHists* hists_;

  // Degraded lane: all calls delegate here once set (consumer thread only;
  // set before any further reads, never cleared).
  std::unique_ptr<SeekStream> seq_;

  std::mutex mu_;
  std::condition_variable cv_work_;  // workers: credit / window / shutdown
  std::condition_variable cv_data_;  // consumer: head segment / error

  // -- scheduler state ------------------------------------------------------
  std::map<size_t, Segment> landed_ DMLC_GUARDED_BY(mu_);  // by start offset
  size_t issue_next_ DMLC_GUARDED_BY(mu_) = 0;  // next offset to carve
  // HintReadBound: carve no further (cleared when the consumer crosses it)
  size_t bound_ DMLC_GUARDED_BY(mu_) = static_cast<size_t>(-1);
  size_t inflight_bytes_ DMLC_GUARDED_BY(mu_) = 0;
  size_t pos_ DMLC_GUARDED_BY(mu_) = 0;         // consumer position
  uint64_t generation_ DMLC_GUARDED_BY(mu_) = 0;  // bumped on plan restarts
  size_t range_bytes_ DMLC_GUARDED_BY(mu_);     // current range size
  int concurrency_ DMLC_GUARDED_BY(mu_);        // current worker credit
  double ewma_goodput_ DMLC_GUARDED_BY(mu_) = 0.0;  // bytes/us, smoothed
  bool degraded_ DMLC_GUARDED_BY(mu_) = false;
  bool started_ DMLC_GUARDED_BY(mu_) = false;
  std::exception_ptr error_ DMLC_GUARDED_BY(mu_);
  // atomic, not guarded: workers poll it between retry attempts AND it is
  // handed to BackoffOrGiveUp as the abort flag, so destruction cuts even
  // a late-ladder multi-second backoff sleep short (~100 ms granularity)
  std::atomic<bool> shutdown_{false};
  uint64_t ranges_fetched_ DMLC_GUARDED_BY(mu_) = 0;
  uint64_t range_retries_ DMLC_GUARDED_BY(mu_) = 0;
  uint64_t discontinuities_ DMLC_GUARDED_BY(mu_) = 0;
  uint64_t wasted_bytes_ DMLC_GUARDED_BY(mu_) = 0;
  uint64_t useful_bytes_ DMLC_GUARDED_BY(mu_) = 0;

  std::vector<std::thread> workers_;  // filled under mu_; joined post-
                                      // shutdown in the dtor
};

// Open-time decision: a RangeReader when the ranged lane is enabled and the
// object is big enough to split (>= 2 min-size ranges and more than one
// worker allowed), else the sequential stream directly.
SeekStream* NewRangedOrSequential(
    const char* backend, size_t file_size,
    std::unique_ptr<RangeFetcher> fetcher,
    std::function<SeekStream*()> sequential_factory, const RangeConfig& cfg,
    const RetryPolicy& policy, int timeout_ms_override);

}  // namespace io
}  // namespace dct

#endif  // DCT_RANGE_READER_H_
