// S3 filesystem: AWS Signature V4 client.
//
// Counterpart of reference src/io/s3_filesys.{h,cc} (1412 L): SIG4 request
// signing (reference CalculateSig4Sign/SignSig4 :231-319), ranged-GET read
// streams with automatic reconnect/retry on short reads (:498-650, <=50
// retries at 100 ms), multipart-upload write streams (:978-1016), ListObjects
// XML paging, and the S3_* -> AWS_* env credential chain (:1150-1214).
// Differences from the reference: the transport is the built-in POSIX-socket
// HTTP client (no libcurl/OpenSSL in this toolchain — see http.h/sha256.h).
// Custom http endpoints (S3-compatible stores, test harnesses) connect
// directly; https endpoints — including the no-endpoint default, real
// TLS-only AWS — route through the local TLS-terminating helper
// (DCT_TLS_PROXY, http.h ResolveHttpRoute, io/tls_proxy.py).
#ifndef DCT_S3_FILESYS_H_
#define DCT_S3_FILESYS_H_

#include <string>
#include <vector>

#include "filesys.h"
#include "retry.h"

namespace dct {

struct S3Config {
  std::string access_key;
  std::string secret_key;
  std::string session_token;  // optional
  std::string region = "us-east-1";
  std::string endpoint_host;  // empty => <bucket>.s3.<region>.amazonaws.com
  int endpoint_port = 80;
  // "http" for custom plain endpoints; "https" routes through the local
  // TLS-terminating helper (DCT_TLS_PROXY, http.h ResolveHttpRoute). The
  // no-endpoint AWS default is https — the real service is TLS-only.
  std::string scheme = "http";
  bool path_style = false;    // true for custom endpoints (bucket in path)
  // Shared resilience policy (retry.h): DMLC_IO_* globals overridden by
  // S3_MAX_RETRY / S3_RETRY_SLEEP_MS (legacy, checked parsing now) /
  // S3_BACKOFF_BASE_MS / S3_BACKOFF_CAP_MS / S3_DEADLINE_MS.
  io::RetryPolicy retry;

  // Environment chain: S3_* falling back to AWS_* (reference
  // s3_filesys.cc:1150-1214). S3_ENDPOINT accepts "host:port" or
  // "http(s)://host[:port]".
  static S3Config FromEnv();
};

class S3FileSystem : public FileSystem {
 public:
  explicit S3FileSystem(const S3Config& config) : config_(config) {}
  static S3FileSystem* GetInstance();

  FileInfo GetPathInfo(const URI& path) override;
  void ListDirectory(const URI& path, std::vector<FileInfo>* out) override;
  Stream* Open(const URI& path, const char* mode,
               bool allow_null = false) override;
  SeekStream* OpenForRead(const URI& path, bool allow_null = false) override;

  const S3Config& config() const { return config_; }

 private:
  // GetPathInfo under an explicit resilience policy — OpenForRead routes
  // its per-open `?io_*=` overrides through here so the open-time probe
  // honors the caller's budget, not just the env default.
  FileInfo PathInfoUnderPolicy(const URI& path,
                               const io::RetryPolicy& policy);

  S3Config config_;
};

namespace s3 {

// --- SIG4 building blocks (exposed for tests) ------------------------------
// RFC 3986 percent-encoding; keep_slash for canonical URIs.
std::string UriEncode(const std::string& s, bool keep_slash);

struct SignedRequest {
  std::string method;
  std::string canonical_path;  // starts with '/'
  // sorted key -> value (already-encoded values not expected; raw)
  std::vector<std::pair<std::string, std::string>> query;
  std::string host_header;
  std::string payload_hash;  // hex sha256 or UNSIGNED-PAYLOAD
  std::string amz_date;      // yyyymmddThhmmssZ
};

// Returns the Authorization header value; fills extra_headers with
// x-amz-date / x-amz-content-sha256 (+ session token when present).
std::string BuildAuthorization(
    const S3Config& cfg, const SignedRequest& req,
    std::map<std::string, std::string>* extra_headers);

// Current UTC timestamp in SIG4 basic format.
std::string AmzDateNow();

// Minimal forward-only XML field scanner (reference XMLIter,
// s3_filesys.cc:26-70): extracts the text of successive <tag>...</tag>.
bool XmlNextField(const std::string& xml, size_t* pos,
                  const std::string& tag, std::string* out);

// Decode XML character entities (&amp; &lt; &gt; &quot; &apos; &#NN;
// &#xNN;) — object names come back entity-escaped in list XML.
std::string XmlUnescape(const std::string& s);

}  // namespace s3

}  // namespace dct

#endif  // DCT_S3_FILESYS_H_
