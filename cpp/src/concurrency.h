// Concurrency primitives and thread lifecycle management.
//
// Counterpart of reference include/dmlc/concurrency.h (Spinlock
// :25-57, ConcurrentBlockingQueue :61-250 with FIFO/priority modes and
// SignalForKill) and include/dmlc/thread_group.h (ManualEvent :32-73,
// ThreadGroup named-thread lifecycle, TimerThread periodic timer).
// Redesigned on C++17: std::atomic_flag spin, one mutex + two CVs per queue,
// shared_ptr-owned threads with a shutdown-request flag instead of the
// reference's 800-line hierarchy.
#ifndef DCT_CONCURRENCY_H_
#define DCT_CONCURRENCY_H_

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <deque>
#include <functional>
#include <map>
#include <memory>
#include <mutex>
#include <queue>
#include <string>
#include <thread>
#include <utility>
#include <vector>

#include "base.h"

namespace dct {

// Test-and-set spinlock (reference concurrency.h:25-57).
class Spinlock {
 public:
  void lock() noexcept {
    while (flag_.test_and_set(std::memory_order_acquire)) {
    }
  }
  void unlock() noexcept { flag_.clear(std::memory_order_release); }

 private:
  std::atomic_flag flag_ = ATOMIC_FLAG_INIT;
};

enum class QueueType { kFIFO, kPriority };

// Blocking MPMC queue with a kill switch (reference concurrency.h:61-250).
// Pop returns false only after SignalForKill; priority mode pops the
// largest element first (Push takes an explicit priority).
template <typename T, QueueType kType = QueueType::kFIFO>
class ConcurrentBlockingQueue {
 public:
  void Push(T value, int priority = 0) {
    {
      std::lock_guard<std::mutex> lock(mu_);
      if (kType == QueueType::kFIFO) {
        fifo_.push_back(std::move(value));
      } else {
        heap_.push({priority, seq_++, std::move(value)});
      }
    }
    cv_.notify_one();
  }

  // Blocks until an element or kill signal; false means killed+empty.
  bool Pop(T* out) {
    std::unique_lock<std::mutex> lock(mu_);
    cv_.wait(lock, [this] { return Size() != 0 || killed_; });
    if (Size() == 0) return false;
    if (kType == QueueType::kFIFO) {
      *out = std::move(fifo_.front());
      fifo_.pop_front();
    } else {
      *out = std::move(const_cast<Entry&>(heap_.top()).value);
      heap_.pop();
    }
    return true;
  }

  // Wake every blocked popper; subsequent pops drain then return false.
  void SignalForKill() {
    {
      std::lock_guard<std::mutex> lock(mu_);
      killed_ = true;
    }
    cv_.notify_all();
  }

  size_t size() {
    std::lock_guard<std::mutex> lock(mu_);
    return Size();
  }

 private:
  struct Entry {
    int priority;
    uint64_t seq;  // FIFO among equal priorities
    T value;
    bool operator<(const Entry& o) const {
      if (priority != o.priority) return priority < o.priority;
      return seq > o.seq;
    }
  };
  size_t Size() const DMLC_REQUIRES(mu_) {
    return kType == QueueType::kFIFO ? fifo_.size() : heap_.size();
  }

  mutable std::mutex mu_;
  std::condition_variable cv_;
  std::deque<T> fifo_ DMLC_GUARDED_BY(mu_);
  std::priority_queue<Entry> heap_ DMLC_GUARDED_BY(mu_);
  uint64_t seq_ DMLC_GUARDED_BY(mu_) = 0;
  bool killed_ DMLC_GUARDED_BY(mu_) = false;
};

// Manually-reset event gate (reference thread_group.h:32-73).
class ManualEvent {
 public:
  void signal() {
    {
      std::lock_guard<std::mutex> lock(mu_);
      set_ = true;
    }
    cv_.notify_all();
  }
  void reset() {
    std::lock_guard<std::mutex> lock(mu_);
    set_ = false;
  }
  void wait() {
    std::unique_lock<std::mutex> lock(mu_);
    cv_.wait(lock, [this] { return set_; });
  }
  template <typename Rep, typename Period>
  bool wait_for(std::chrono::duration<Rep, Period> d) {
    std::unique_lock<std::mutex> lock(mu_);
    // system_clock deadline on purpose: the steady-clock wait_for of
    // libstdc++ 10 lowers to pthread_cond_clockwait, which the matching
    // TSan runtime does not intercept — it then misses the unlock inside
    // the wait and reports a bogus "double lock of a mutex" on this gate.
    // The system-clock path (pthread_cond_timedwait) is instrumented. A
    // wall-clock jump at worst stretches one poll of a shutdown gate.
    return cv_.wait_until(lock, std::chrono::system_clock::now() + d,
                          [this] { return set_; });
  }

 private:
  std::mutex mu_;
  std::condition_variable cv_;
  bool set_ DMLC_GUARDED_BY(mu_) = false;
};

// Named-thread lifecycle manager (reference thread_group.h ThreadGroup):
// launched threads receive a shutdown-request flag they should poll or wait
// on; JoinAll requests shutdown and joins everything.
class ThreadGroup {
 public:
  class Thread {
   public:
    Thread(std::string name, ThreadGroup* owner)
        : name_(std::move(name)), owner_(owner) {}

    const std::string& name() const { return name_; }
    bool shutdown_requested() const {
      return shutdown_.load(std::memory_order_acquire);
    }
    void request_shutdown() {
      shutdown_.store(true, std::memory_order_release);
      event_.signal();
    }
    // gate a worker loop: true -> shutdown was requested during the wait
    template <typename Rep, typename Period>
    bool wait_shutdown_for(std::chrono::duration<Rep, Period> d) {
      event_.wait_for(d);
      return shutdown_requested();
    }

   private:
    friend class ThreadGroup;
    std::string name_;
    ThreadGroup* owner_;
    std::atomic<bool> shutdown_{false};
    ManualEvent event_;
    std::thread impl_;
  };

  ~ThreadGroup() { JoinAll(); }

  // Launch fn(thread*) under `name`; names must be unique while running.
  std::shared_ptr<Thread> Start(const std::string& name,
                                std::function<void(Thread*)> fn) {
    auto t = std::make_shared<Thread>(name, this);
    // publish and launch under one lock so JoinAll never observes a
    // registered Thread whose impl_ is still being move-assigned
    std::lock_guard<std::mutex> lock(mu_);
    DCT_CHECK(threads_.count(name) == 0)
        << "ThreadGroup: duplicate thread name `" << name << "`";
    t->impl_ = std::thread([t, fn = std::move(fn)] { fn(t.get()); });
    threads_[name] = t;
    return t;
  }

  // Periodic timer thread (reference thread_group.h TimerThread): runs fn
  // every `period` until shutdown; returns its handle.
  template <typename Rep, typename Period>
  std::shared_ptr<Thread> StartTimer(const std::string& name,
                                     std::chrono::duration<Rep, Period> period,
                                     std::function<void()> fn) {
    return Start(name, [period, fn = std::move(fn)](Thread* self) {
      while (!self->wait_shutdown_for(period)) fn();
    });
  }

  std::shared_ptr<Thread> Get(const std::string& name) {
    std::lock_guard<std::mutex> lock(mu_);
    auto it = threads_.find(name);
    return it == threads_.end() ? nullptr : it->second;
  }

  size_t size() {
    std::lock_guard<std::mutex> lock(mu_);
    return threads_.size();
  }

  void JoinAll() {
    std::map<std::string, std::shared_ptr<Thread>> taken;
    {
      std::lock_guard<std::mutex> lock(mu_);
      taken.swap(threads_);
    }
    for (auto& [name, t] : taken) t->request_shutdown();
    for (auto& [name, t] : taken) {
      if (t->impl_.joinable()) t->impl_.join();
    }
  }

 private:
  std::mutex mu_;
  std::map<std::string, std::shared_ptr<Thread>> threads_
      DMLC_GUARDED_BY(mu_);
};

}  // namespace dct

#endif  // DCT_CONCURRENCY_H_
