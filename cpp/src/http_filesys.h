// Read-only filesystem over plain HTTP.
//
// The reference routes `http://`/`https://` URIs to its curl-backed S3
// reader so public objects can be read with ranged GETs
// (/root/reference/src/io.cc:53). Here the plain-HTTP client (http.h)
// backs a dedicated read-only filesystem instead: ranged GET streams with
// reconnect-at-offset retries (http_stream.h, the same loop the S3 path
// uses), HEAD-based path info, and graceful degradation to
// skip-the-prefix when a server ignores Range (Python's http.server,
// for one, serves 200/full-body).
//
// `https://` registers too, but the built-in client is plain-HTTP only
// (http.h rationale: no TLS stack in-image) — opening an https URI
// throws a clear error pointing at an http:// or S3-endpoint route.
#ifndef DCT_HTTP_FILESYS_H_
#define DCT_HTTP_FILESYS_H_

#include <vector>

#include "filesys.h"

namespace dct {

class HttpFileSystem : public FileSystem {
 public:
  static HttpFileSystem* GetInstance();

  FileInfo GetPathInfo(const URI& path) override;
  void ListDirectory(const URI& path, std::vector<FileInfo>* out) override;
  Stream* Open(const URI& path, const char* mode,
               bool allow_null = false) override;
  SeekStream* OpenForRead(const URI& path, bool allow_null = false) override;

 private:
  HttpFileSystem() = default;
};

}  // namespace dct

#endif  // DCT_HTTP_FILESYS_H_
