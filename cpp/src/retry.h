// Unified remote-I/O resilience layer shared by every HTTP-backed
// filesystem (s3/azure/webhdfs/http).
//
// The reference's only failure story is a fixed 50 x 100 ms retry loop in
// the S3 read path (s3_filesys.cc:522-546) and sockets with no timeout at
// all — a stalled remote peer hangs the parse pipeline forever. This layer
// replaces that with:
//   - RetryPolicy: exponential backoff with DECORRELATED JITTER
//     (sleep = min(cap, uniform(base, prev*3)) — the AWS architecture-blog
//     variant that both spreads thundering herds and keeps a short first
//     retry), a per-attempt socket timeout, and an overall per-operation
//     deadline budget. Configured once via DMLC_IO_MAX_RETRY /
//     DMLC_IO_BACKOFF_BASE_MS / DMLC_IO_BACKOFF_CAP_MS /
//     DMLC_IO_DEADLINE_MS / DMLC_IO_TIMEOUT_MS; per-backend env names
//     (S3_MAX_RETRY, WEBHDFS_RETRY_SLEEP_MS, ...) stay as overrides, and
//     per-open `?io_*=` URI query args override both.
//   - RetryController: the runtime loop state (attempt count, previous
//     sleep, deadline clock) a retry site drives via BackoffOrGiveUp().
//   - IoStats: process-global atomic counters (requests, retries, timeouts,
//     injected faults, deadline exhaustions) surfaced through the C ABI
//     (dct_io_retry_stats) into Python io_stats().
//   - Fault injection: DMLC_IO_FAULT_PLAN / dct_io_set_fault_plan installs
//     a deterministic plan ("reset:every=3;stall:every=5,ms=80;5xx:every=7")
//     evaluated inside the native HTTP client — BELOW every mock — so the
//     chaos suites prove the real retry machinery, not the test harness.
//   - CheckedEnvInt: the shared validated config parser (replaces the raw
//     atoi on S3_MAX_RETRY et al., which silently turned typos into
//     0-retry or garbage configs).
#ifndef DCT_RETRY_H_
#define DCT_RETRY_H_

#include <atomic>
#include <chrono>
#include <cstdint>
#include <functional>
#include <random>
#include <string>

#include "base.h"

namespace dct {

// A per-attempt timeout expiry (socket connect/recv/send, or an injected
// stall). Distinct from Error so the stats layer can classify, but callers
// that only catch Error keep working — timeouts are retryable transport
// errors like any other drop.
class TimeoutError : public Error {
 public:
  explicit TimeoutError(const std::string& what) : Error(what) {}
};

namespace io {

// ---------------------------------------------------------------- config --
// Validated integer env read: returns `dflt` when unset; throws on
// non-numeric text (a typo'd retry config must not silently become 0
// retries); clamps into [lo, hi]. The shared replacement for the raw
// atoi() reads the backends used to do.
int64_t CheckedEnvInt(const char* name, int64_t dflt, int64_t lo, int64_t hi);

// Parse a decimal integer out of a URI-arg/env value. Throws Error naming
// `what` on empty/non-numeric text; clamps into [lo, hi].
int64_t CheckedInt(const std::string& what, const std::string& text,
                   int64_t lo, int64_t hi);

struct RetryPolicy {
  int max_retry = 50;        // retries after the first attempt
  int backoff_base_ms = 100; // first sleep; legacy *_RETRY_SLEEP_MS maps here
  int backoff_cap_ms = 10000;    // jittered sleeps never exceed this
  // Per-operation wall-clock budget (one Read call's reconnect loop, one
  // write request); 0 = unbounded. The default bounds worst-case
  // time-to-failure: 50 capped jittered sleeps alone would admit ~8 min
  // of backoff against a persistently sick endpoint, where the legacy
  // constant loop failed in 5 s.
  int64_t deadline_ms = 120000;
  int64_t jitter_seed = -1;      // >=0 pins the jitter RNG (tests)

  // Layered construction: defaults <- DMLC_IO_* <- <prefix>_* overrides.
  // `prefix` is the backend's env namespace ("S3", "AZURE", "WEBHDFS",
  // "DCT_HTTP"); reads <prefix>_MAX_RETRY, <prefix>_RETRY_SLEEP_MS
  // (legacy alias for the backoff base), <prefix>_BACKOFF_BASE_MS,
  // <prefix>_BACKOFF_CAP_MS, <prefix>_DEADLINE_MS — all through
  // CheckedEnvInt.
  static RetryPolicy FromEnv(const char* prefix);

  // Consume one `io_*` URI query arg (io_max_retry, io_backoff_base_ms,
  // io_backoff_cap_ms, io_deadline_ms, io_timeout_ms). Returns false when
  // the key is not a retry knob (caller leaves it in the URI). Throws on
  // non-numeric values.
  bool ApplyUriArg(const std::string& key, const std::string& value);
};

// Strip `io_*` retry args from the query segment of `path` in place,
// applying them to `policy` (and the per-open socket timeout override via
// io_timeout_ms -> policy handling in the stream). Non-io_* args and paths
// without a query are left untouched; the '?' is dropped when the query
// empties. Backends call this at Open/OpenForRead entry so the remaining
// path is the real object key. `extra_arg` lets another io_* knob family
// (the range knobs, range_reader.h) ride the SAME tokenizer: it is
// offered every io_* key the retry family does not consume; returning
// false falls through to the unknown-knob error.
using UriArgConsumer =
    std::function<bool(const std::string& key, const std::string& value)>;
void ExtractUriRetryArgs(std::string* path, RetryPolicy* policy,
                         int* timeout_ms_override,
                         const UriArgConsumer& extra_arg = nullptr);

// --------------------------------------------------------------- runtime --
// Holds a REFERENCE to its policy (which must outlive it): Connect()
// implementations may tighten the policy mid-loop (the http reader cuts
// max_retry to 2 once it learns the server ignores Range) and the change
// must bind the in-flight loop, not just the next one.
class RetryController {
 public:
  explicit RetryController(const RetryPolicy& policy);

  // Call after a retryable failure. Sleeps the next jittered backoff and
  // returns true, or returns false (recording the giveup) when the retry
  // count or the deadline budget is exhausted — the caller then rethrows.
  // `abort` (optional) is polled during the sleep (~100 ms granularity):
  // when it flips, the sleep is cut short and false is returned WITHOUT
  // counting a giveup — a shutting-down owner must not wait out a whole
  // late-ladder backoff (range_reader.h worker teardown).
  bool BackoffOrGiveUp(const std::atomic<bool>* abort = nullptr);

  int attempts() const { return attempts_; }
  int64_t elapsed_ms() const;

 private:
  const RetryPolicy& policy_;
  std::chrono::steady_clock::time_point start_;
  int attempts_ = 0;
  int64_t prev_sleep_ms_;
  // seeded lazily on the first backoff: a controller is built per Read()
  // call / per one-shot request, and on the healthy hot path the RNG
  // (random_device open + mt19937_64 state init) would be pure overhead
  bool rng_ready_ = false;
  std::mt19937_64 rng_;
};

// ----------------------------------------------------------------- stats --
// Process-global counters; plain atomics so request threads never contend
// on a lock. Snapshot through the C ABI (dct_io_retry_stats).
struct IoStats {
  std::atomic<uint64_t> requests{0};         // HTTP requests sent
  std::atomic<uint64_t> retries{0};          // backoff sleeps taken
  std::atomic<uint64_t> backoff_ms_total{0}; // total time slept in backoff
  std::atomic<uint64_t> timeouts{0};         // per-attempt timeout expiries
  std::atomic<uint64_t> faults_injected{0};  // DMLC_IO_FAULT_PLAN firings
  std::atomic<uint64_t> giveups{0};          // retry loops that gave up
  std::atomic<uint64_t> deadline_exhausted{0};  // giveups due to deadline
};

IoStats& GlobalIoStats();
void ResetIoStats();

// --------------------------------------------------------- fault injection --
// Install a fault plan ("" clears). Grammar, ';'-separated rules:
//   <kind>:every=N[,ms=M][,status=S]
// kinds: reset (transport drop), stall (sleep M ms — default 50 — then
// time out), 5xx (HTTP status S — default 503 — carried as
// HttpStatusError). Each rule keeps its own atomic request counter and
// fires on every Nth request it observes, so multi-threaded runs stay
// deterministic in COUNT (which request draws the fault races, the total
// does not). Throws Error on bad grammar.
void SetFaultPlan(const std::string& plan);

// Evaluate the installed plan for one outgoing request (also counts the
// request). May throw Error / TimeoutError / an HTTP-status error built by
// `status_thrower` (the http layer passes a lambda that throws its
// HttpStatusError so this header stays independent of http.h).
using StatusThrower = void (*)(const std::string& what, int status);
void MaybeInjectFault(StatusThrower status_thrower);

// Lazily installs DMLC_IO_FAULT_PLAN from the env on first use (explicit
// SetFaultPlan wins; called by the http client).
void EnsureFaultPlanFromEnv();

// --------------------------------------------------------------- timeouts --
// Per-attempt socket-operation timeout (connect/recv/send), milliseconds.
// Order of precedence: explicit SetIoTimeoutMs override (C ABI, race-free
// like SetTlsProxyOverride) > DMLC_IO_TIMEOUT_MS > 60000. A hung peer now
// surfaces as a retryable TimeoutError within this bound instead of
// blocking forever.
int IoTimeoutMs();
void SetIoTimeoutMs(int ms);  // <=0 clears back to env/default

// RAII thread-local timeout override for the current thread's socket ops —
// how a per-open `?io_timeout_ms=` URI arg applies to exactly the stream
// that asked for it (socket ops run on the calling thread), without racing
// other threads' global setting. ms <= 0 is a no-op.
class ScopedIoTimeout {
 public:
  explicit ScopedIoTimeout(int ms);
  ~ScopedIoTimeout();
  ScopedIoTimeout(const ScopedIoTimeout&) = delete;
  ScopedIoTimeout& operator=(const ScopedIoTimeout&) = delete;

 private:
  int saved_;
};

}  // namespace io
}  // namespace dct

#endif  // DCT_RETRY_H_
