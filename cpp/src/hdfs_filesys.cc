// WebHDFS filesystem implementation (see hdfs_filesys.h for provenance).
#include "hdfs_filesys.h"

#include <unistd.h>

#include <cstdlib>
#include <memory>
#include <sstream>

#include "http.h"
#include "http_stream.h"
#include "json.h"
#include "range_reader.h"
#include "s3_filesys.h"  // s3::UriEncode (RFC 3986 percent-encoding)

namespace dct {
namespace webhdfs {

HttpUrl ParseHttpUrl(const std::string& url) {
  HttpUrl out;
  size_t scheme = url.find("://");
  DCT_CHECK(scheme != std::string::npos) << "not a url: " << url;
  out.scheme = url.substr(0, scheme);
  DCT_CHECK(out.scheme == "http" || out.scheme == "https")
      << "webhdfs redirect must be an http(s) url, got " << url;
  size_t body = scheme + 3;
  size_t slash = url.find('/', body);
  std::string hostport =
      slash == std::string::npos ? url.substr(body)
                                 : url.substr(body, slash - body);
  out.path_query = slash == std::string::npos ? "/" : url.substr(slash);
  SplitHostPort(hostport, &out.host, &out.port,
                out.scheme == "https" ? 443 : 80);
  return out;
}

namespace {

struct Target {
  std::string host;
  int port;
  std::string scheme = "http";
};

// Resolve namenode from URI host ("hdfs://host:port/...") falling back to
// the configured default (reference hdfs_filesys GetInstance namenode arg).
Target ResolveTarget(const WebHdfsConfig& cfg, const URI& uri) {
  Target t{cfg.namenode_host, cfg.namenode_port, cfg.scheme};
  if (!uri.host.empty()) {
    SplitHostPort(uri.host, &t.host, &t.port, cfg.namenode_port);
  }
  DCT_CHECK(!t.host.empty())
      << "hdfs uri has no host and WEBHDFS_NAMENODE is unset: " << uri.Str();
  return t;
}

// /webhdfs/v1<path>?op=<OP>&delegation=<t>|user.name=<u>&<extra...>
std::string OpPath(const WebHdfsConfig& cfg, const std::string& path,
                   const std::string& op, const std::string& extra) {
  std::string p = path.empty() ? "/" : path;
  std::string out = "/webhdfs/v1" + s3::UriEncode(p, true) + "?op=" + op;
  if (!cfg.delegation_token.empty()) {
    // token auth: user.name must NOT accompany delegation (WebHDFS spec)
    out += "&delegation=" + s3::UriEncode(cfg.delegation_token, false);
  } else if (!cfg.auth_header.empty()) {
    // header auth (SPNEGO/Knox): identity comes from the credential;
    // user.name must not override it
  } else if (!cfg.user.empty()) {
    out += "&user.name=" + s3::UriEncode(cfg.user, false);
  }
  if (!extra.empty()) out += "&" + extra;
  return out;
}

// Per-request headers: the verbatim Authorization credential when set
// (SPNEGO "Negotiate ...", Knox "Basic ..." — the auth hook; datanode
// redirects carry it too, matching curl --negotiate behavior).
std::map<std::string, std::string> AuthHeaders(const WebHdfsConfig& cfg) {
  std::map<std::string, std::string> h;
  if (!cfg.auth_header.empty()) h["authorization"] = cfg.auth_header;
  return h;
}

// One FileStatus JSON object -> FileInfo (caller fixes .path for listings).
void ReadFileStatus(JSONReader* reader, FileInfo* info,
                    std::string* path_suffix) {
  std::string key;
  reader->BeginObject();
  while (reader->NextObjectItem(&key)) {
    if (key == "length") {
      double v = 0;
      reader->ReadNumber(&v);
      info->size = static_cast<size_t>(v);
    } else if (key == "type") {
      std::string t;
      reader->ReadString(&t);
      info->type = t == "DIRECTORY" ? FileType::kDirectory : FileType::kFile;
    } else if (key == "pathSuffix") {
      reader->ReadString(path_suffix);
    } else {
      reader->SkipValue();
    }
  }
}

// Raise a readable, status-typed error from a non-2xx WebHDFS response
// (RemoteException JSON body when present).
void CheckStatus(const HttpResponse& resp, int expect, const char* what,
                 const URI& uri) {
  if (resp.status == expect) return;
  throw HttpStatusError(std::string("webhdfs ") + what + " " + uri.Str() +
                            " failed with status " +
                            std::to_string(resp.status) + ": " + resp.body,
                        resp.status);
}

// ---------------------------------------------------------------- reading --
// Ranged reader: each (re)connect issues OPEN with the current offset; the
// namenode 307-redirects to a datanode which streams the rest of the file
// (libhdfs hdfsSeek maps to the offset= parameter; reconnect-at-offset
// retry scaffolding shared via RetryingHttpReadStream).
class WebHdfsReadStream : public RetryingHttpReadStream {
 public:
  WebHdfsReadStream(const WebHdfsConfig& cfg, const Target& target,
                    const URI& uri, size_t file_size,
                    const io::RetryPolicy& policy, int timeout_ms)
      : RetryingHttpReadStream("webhdfs", file_size, policy, timeout_ms),
        cfg_(cfg), target_(target), uri_(uri) {}

 private:
  void Connect() override {
    std::string path =
        OpPath(cfg_, uri_.path, "OPEN", "offset=" + std::to_string(pos_));
    std::string host = target_.host;
    int port = target_.port;
    std::string scheme = target_.scheme;
    // follow namenode -> datanode redirects (bounded; gateways may serve
    // the body directly with 200)
    for (int hop = 0; hop < 5; ++hop) {
      conn_.reset(new HttpConnection(ResolveHttpRoute(scheme, host, port, "webhdfs")));
      conn_->SendRequest("GET", path, AuthHeaders(cfg_), "");
      HttpResponse head;
      conn_->ReadResponseHead(&head);
      if (head.status == 200 || head.status == 206) return;
      if (head.status == 307 || head.status == 302) {
        auto it = head.headers.find("location");
        DCT_CHECK(it != head.headers.end())
            << "webhdfs redirect without Location header";
        conn_->ReadFullBody(&head);  // drain before reconnecting
        webhdfs::HttpUrl next = webhdfs::ParseHttpUrl(it->second);
        host = next.host;
        port = next.port;
        scheme = next.scheme;
        path = next.path_query;
        continue;
      }
      conn_->ReadFullBody(&head);
      int status = head.status;
      conn_.reset();
      throw HttpStatusError("webhdfs OPEN " + uri_.Str() +
                                " failed with status " +
                                std::to_string(status) + ": " + head.body,
                            status);
    }
    throw Error("webhdfs OPEN " + uri_.Str() + ": too many redirects");
  }

  WebHdfsConfig cfg_;
  Target target_;
  URI uri_;
};

// One idempotent bounded read per call (range_reader.h): OPEN with
// `offset=` AND `length=` (the WebHDFS spelling of a ranged GET), following
// the namenode -> datanode redirect dance per fetch. Gateways that honor
// offset but ignore length just stream long — the surplus is abandoned
// with the connection; a body that ends short of `length` is a transport
// error the per-range retry absorbs. (There is no 200-degrade here:
// `offset=` is core WebHDFS API, honored wherever the sequential lane
// works at all.)
class WebHdfsRangeFetcher : public io::RangeFetcher {
 public:
  WebHdfsRangeFetcher(const WebHdfsConfig& cfg, const Target& target,
                      const URI& uri)
      : cfg_(cfg), target_(target), uri_(uri) {}

  io::FetchStatus Fetch(size_t off, size_t len, char* buf,
                        size_t* progress) override {
    std::string path = OpPath(cfg_, uri_.path, "OPEN",
                              "offset=" + std::to_string(off) +
                                  "&length=" + std::to_string(len));
    std::string host = target_.host;
    int port = target_.port;
    std::string scheme = target_.scheme;
    for (int hop = 0; hop < 5; ++hop) {
      HttpConnection conn(ResolveHttpRoute(scheme, host, port, "webhdfs"));
      conn.SendRequest("GET", path, AuthHeaders(cfg_), "");
      HttpResponse head;
      conn.ReadResponseHead(&head);
      if (head.status == 200 || head.status == 206) {
        ReadRangeBody(&conn, buf, len, "webhdfs", uri_.Str(), progress);
        return io::FetchStatus::kOk;
      }
      if (head.status == 307 || head.status == 302) {
        auto it = head.headers.find("location");
        DCT_CHECK(it != head.headers.end())
            << "webhdfs redirect without Location header";
        conn.ReadFullBody(&head);  // drain before reconnecting
        webhdfs::HttpUrl next = webhdfs::ParseHttpUrl(it->second);
        host = next.host;
        port = next.port;
        scheme = next.scheme;
        path = next.path_query;
        continue;
      }
      conn.ReadFullBody(&head);
      throw HttpStatusError("webhdfs ranged OPEN " + uri_.Str() +
                                " failed with status " +
                                std::to_string(head.status) + ": " +
                                head.body,
                            head.status);
    }
    throw Error("webhdfs ranged OPEN " + uri_.Str() +
                ": too many redirects");
  }

 private:
  WebHdfsConfig cfg_;
  Target target_;
  URI uri_;
};

// ---------------------------------------------------------------- writing --
// Buffered writer: first flush CREATEs the file (overwrite), later flushes
// APPEND; both follow the namenode's redirect to a datanode. The libhdfs
// write path the reference wraps is likewise create-then-stream. Mode "a"
// starts in APPEND when the file already exists (`append_to_existing`).
class WebHdfsWriteStream : public Stream {
 public:
  static constexpr size_t kFlushSize = 8 << 20;

  WebHdfsWriteStream(const WebHdfsConfig& cfg, const Target& target,
                     const URI& uri, bool append_to_existing = false)
      : cfg_(cfg), target_(target), uri_(uri),
        created_(append_to_existing) {}

  ~WebHdfsWriteStream() override {
    try {
      Finish();
    } catch (...) {
      // destructor must not throw; errors surface on explicit Finish
    }
  }

  size_t Read(void*, size_t) override {
    throw Error("WebHdfsWriteStream is write-only");
  }

  size_t Write(const void* ptr, size_t size) override {
    buffer_.append(static_cast<const char*>(ptr), size);
    while (buffer_.size() >= kFlushSize) Flush(kFlushSize);
    return size;
  }

  void Finish() override {
    if (finished_) return;
    finished_ = true;
    if (!buffer_.empty() || !created_) Flush(buffer_.size());
  }

 private:
  void Flush(size_t size) {
    std::string part;
    if (size == buffer_.size()) {
      part.swap(buffer_);
    } else {
      part = buffer_.substr(0, size);
      buffer_.erase(0, size);
    }
    const char* method = created_ ? "POST" : "PUT";
    std::string op_extra = created_ ? std::string("APPEND")
                                    : std::string("CREATE");
    std::string extra = created_ ? "" : "overwrite=true";
    std::string path = OpPath(cfg_, uri_.path, op_extra, extra);
    // step 1: namenode; expect redirect to a datanode (send no body, per
    // the WebHDFS two-step protocol)
    HttpResponse head = HttpRequest(
        ResolveHttpRoute(target_.scheme, target_.host, target_.port, "webhdfs"), method,
        path, AuthHeaders(cfg_), "");
    if (head.status == 307 || head.status == 302) {
      auto it = head.headers.find("location");
      DCT_CHECK(it != head.headers.end())
          << "webhdfs redirect without Location header";
      webhdfs::HttpUrl next = webhdfs::ParseHttpUrl(it->second);
      head = HttpRequest(ResolveHttpRoute(next.scheme, next.host, next.port, "webhdfs"),
                         method, next.path_query, AuthHeaders(cfg_), part);
    } else if (head.status >= 200 && head.status < 300 && !part.empty()) {
      // One-step gateway (HttpFS style): the empty step-1 request was
      // accepted directly, so the payload was never transmitted. Re-send
      // with the body: CREATE&overwrite=true is idempotent and the empty
      // APPEND appended nothing, so exactly one copy of `part` lands.
      head = HttpRequest(
          ResolveHttpRoute(target_.scheme, target_.host, target_.port, "webhdfs"),
          method, path, AuthHeaders(cfg_), part);
    }
    CheckStatus(head, created_ ? 200 : 201,
                created_ ? "APPEND" : "CREATE", uri_);
    created_ = true;
  }

  WebHdfsConfig cfg_;
  Target target_;
  URI uri_;
  std::string buffer_;
  bool created_ = false;
  bool finished_ = false;
};

}  // namespace
}  // namespace webhdfs

// ----------------------------------------------------------------- config --
WebHdfsConfig WebHdfsConfig::FromEnv() {
  WebHdfsConfig cfg;
  const char* nn = std::getenv("WEBHDFS_NAMENODE");
  if (nn != nullptr && *nn != '\0') {
    std::string s = nn;
    std::string sch = StripUrlScheme(&s);
    if (!sch.empty()) {
      cfg.scheme = sch;
      if (sch == "https") cfg.namenode_port = 9871;  // secure REST default
    }
    SplitHostPort(s, &cfg.namenode_host, &cfg.namenode_port,
                           cfg.namenode_port);
  }
  const char* user = std::getenv("HADOOP_USER_NAME");
  if (user == nullptr || *user == '\0') user = std::getenv("USER");
  if (user != nullptr) cfg.user = user;
  const char* tok = std::getenv("WEBHDFS_DELEGATION_TOKEN");
  if (tok != nullptr && *tok != '\0') cfg.delegation_token = tok;
  const char* ah = std::getenv("WEBHDFS_AUTH_HEADER");
  if (ah != nullptr && *ah != '\0') cfg.auth_header = ah;
  cfg.retry = io::RetryPolicy::FromEnv("WEBHDFS");
  return cfg;
}

WebHdfsFileSystem* WebHdfsFileSystem::GetInstance() {
  static WebHdfsFileSystem inst(WebHdfsConfig::FromEnv());
  return &inst;
}

FileInfo WebHdfsFileSystem::GetPathInfo(const URI& path) {
  return PathInfoUnderPolicy(path, config_copy().retry);
}

FileInfo WebHdfsFileSystem::PathInfoUnderPolicy(
    const URI& path, const io::RetryPolicy& policy) {
  const WebHdfsConfig cfg = config_copy();
  webhdfs::Target t = webhdfs::ResolveTarget(cfg, path);
  std::string p = webhdfs::OpPath(cfg, path.path, "GETFILESTATUS", "");
  // metadata ops ride the shared resilience policy (idempotent GET)
  HttpResponse resp = RetryingHttpRequest(
      ResolveHttpRoute(t.scheme, t.host, t.port, "webhdfs"), "GET", p,
      webhdfs::AuthHeaders(cfg), "", policy);
  webhdfs::CheckStatus(resp, 200, "GETFILESTATUS", path);
  FileInfo info;
  info.path = path;
  std::istringstream body(resp.body);
  JSONReader reader(&body);
  std::string key, suffix;
  reader.BeginObject();
  while (reader.NextObjectItem(&key)) {
    if (key == "FileStatus") {
      webhdfs::ReadFileStatus(&reader, &info, &suffix);
    } else {
      reader.SkipValue();
    }
  }
  return info;
}

void WebHdfsFileSystem::ListDirectory(const URI& path,
                                      std::vector<FileInfo>* out) {
  const WebHdfsConfig cfg = config_copy();
  webhdfs::Target t = webhdfs::ResolveTarget(cfg, path);
  std::string p = webhdfs::OpPath(cfg, path.path, "LISTSTATUS", "");
  HttpResponse resp = RetryingHttpRequest(
      ResolveHttpRoute(t.scheme, t.host, t.port, "webhdfs"), "GET", p,
      webhdfs::AuthHeaders(cfg), "", cfg.retry);
  webhdfs::CheckStatus(resp, 200, "LISTSTATUS", path);
  std::string dir = path.path.empty() ? "/" : path.path;
  if (dir.back() != '/') dir += '/';
  std::istringstream body(resp.body);
  JSONReader reader(&body);
  std::string key;
  reader.BeginObject();
  while (reader.NextObjectItem(&key)) {
    if (key != "FileStatuses") {
      reader.SkipValue();
      continue;
    }
    reader.BeginObject();
    while (reader.NextObjectItem(&key)) {
      if (key != "FileStatus") {
        reader.SkipValue();
        continue;
      }
      reader.BeginArray();
      while (reader.NextArrayItem()) {
        FileInfo info;
        std::string suffix;
        webhdfs::ReadFileStatus(&reader, &info, &suffix);
        info.path = path;
        // LISTSTATUS of a *file* returns one entry with empty pathSuffix
        // meaning the path itself — no trailing slash in that case
        info.path.path = suffix.empty()
                             ? (path.path.empty() ? "/" : path.path)
                             : dir + suffix;
        out->push_back(info);
      }
    }
  }
}

SeekStream* WebHdfsFileSystem::OpenForRead(const URI& path, bool allow_null) {
  URI clean = path;
  const WebHdfsConfig cfg = config_copy();
  io::RetryPolicy policy = cfg.retry;
  io::RangeConfig rcfg = io::RangeConfig::FromEnv();
  int timeout_ms = 0;
  io::ExtractUriIoArgs(&clean.path, &policy, &timeout_ms, &rcfg);
  // bind the open-time metadata probe to the per-open timeout as well
  io::ScopedIoTimeout scoped_timeout(timeout_ms);
  try {
    FileInfo info = PathInfoUnderPolicy(clean, policy);
    DCT_CHECK(info.type == FileType::kFile)
        << "cannot open hdfs directory for read: " << clean.Str();
    webhdfs::Target t = webhdfs::ResolveTarget(cfg, clean);
    const size_t size = info.size;
    return io::NewRangedOrSequential(
        "webhdfs", size,
        std::make_unique<webhdfs::WebHdfsRangeFetcher>(cfg, t, clean),
        [cfg, t, clean, size, policy, timeout_ms]() -> SeekStream* {
          return new webhdfs::WebHdfsReadStream(cfg, t, clean, size, policy,
                                                timeout_ms);
        },
        rcfg, policy, timeout_ms);
  } catch (const Error&) {
    if (allow_null) return nullptr;
    throw;
  }
}

Stream* WebHdfsFileSystem::Open(const URI& path, const char* mode,
                                bool allow_null) {
  std::string m = mode;
  if (m.find('r') != std::string::npos) return OpenForRead(path, allow_null);
  bool append = m.find('a') != std::string::npos;
  DCT_CHECK(m.find('w') != std::string::npos || append)
      << "hdfs supports modes r|w|a, got " << mode;
  const WebHdfsConfig cfg = config_copy();
  webhdfs::Target t = webhdfs::ResolveTarget(cfg, path);
  if (append) {
    // append to an existing file; fall back to CREATE only when the
    // namenode definitively says 404 — any other failure must propagate,
    // or a transient error would turn append into a destructive overwrite
    bool exists = true;
    try {
      exists = GetPathInfo(path).type == FileType::kFile;
    } catch (const HttpStatusError& e) {
      if (e.status != 404) throw;
      exists = false;
    }
    return new webhdfs::WebHdfsWriteStream(cfg, t, path, exists);
  }
  return new webhdfs::WebHdfsWriteStream(cfg, t, path);
}

namespace {
// hdfs:// and viewfs:// dispatch (reference src/io.cc:38-52 routes both to
// HDFSFileSystem; viewfs resolution is the namenode's job over WebHDFS).
struct WebHdfsRegistrar {
  WebHdfsRegistrar() {
    FileSystem::RegisterScheme("hdfs", [](const URI&) -> FileSystem* {
      return WebHdfsFileSystem::GetInstance();
    });
    FileSystem::RegisterScheme("viewfs", [](const URI&) -> FileSystem* {
      return WebHdfsFileSystem::GetInstance();
    });
  }
} webhdfs_registrar;
}  // namespace

}  // namespace dct
