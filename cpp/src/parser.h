// Multithreaded text parsers producing CSR row blocks.
//
// Counterpart of reference src/data/parser.h (ParserImpl + ThreadedParser),
// src/data/text_parser.h (chunk → N worker threads, each parsing a
// line-aligned slice), and the format parsers libsvm_parser.h /
// csv_parser.h / libfm_parser.h. Parse semantics (comment/blank skipping,
// label[:weight], qid:, 0/1-based indexing heuristic, CSV missing values,
// NOEOL/BOM/CRLF handling) match the reference; the worker fan-out is
// restructured: slices are tiled forward to line heads and each worker fills
// its own RowBlockContainer which is exposed zero-copy through the C ABI.
#ifndef DCT_PARSER_H_
#define DCT_PARSER_H_

#include <atomic>
#include <condition_variable>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "input_split.h"
#include "registry.h"
#include "rowblock.h"
#include "simd_scan.h"

namespace dct {

template <typename IndexType>
class TextParserBase;

// Occupancy/stall counters for the multi-chunk parse pipeline
// (PipelinedParser below), exposed through the C ABI
// (dct_parser_pipeline_stats) so the bench harness can see which stage
// binds: a starved reader (reader_waits low, consumer_waits high) means
// parse-bound; a full queue (reader_waits high) means consume-bound.
struct ParsePipelineStats {
  uint64_t chunks_read = 0;      // chunks admitted by the reader stage
  uint64_t blocks_delivered = 0; // row blocks handed to the consumer
  uint64_t reader_waits = 0;     // reader blocked on the in-flight bound
  uint64_t worker_waits = 0;     // worker slept with no claimable slice
  uint64_t consumer_waits = 0;   // consumer slept on the head-of-line chunk
  uint64_t inflight_now = 0;     // chunks currently outstanding
  uint64_t inflight_peak = 0;
  uint64_t inflight_sum = 0;     // summed at each admit; avg = sum/chunks
  uint64_t capacity = 0;         // configured chunks-in-flight bound
  uint64_t workers = 0;          // parse worker thread count
  uint64_t simd_tier = 0;        // structural-scan lane (simd_scan.h
                                 // SimdTier: 0 scalar, 1 swar, 2 sse2,
                                 // 3 avx2)
};

// Parser factory registry entry (reference ParserFactoryReg +
// DMLC_REGISTER_DATA_PARSER, data.h:330-358): formats resolve by name
// through Registry<ParserFactoryReg<I>> so downstream code can register
// additional native formats.
template <typename IndexType>
struct ParserFactoryReg
    : public FunctionRegEntryBase<
          ParserFactoryReg<IndexType>,
          std::function<TextParserBase<IndexType>*(
              InputSplit*, const std::map<std::string, std::string>&, int)>> {
};

template <typename IndexType>
class Parser {
 public:
  virtual ~Parser() = default;
  virtual void BeforeFirst() = 0;
  // Produce the next block of rows; nullptr at end of data. The returned
  // container stays valid until the next call.
  virtual const RowBlockContainer<IndexType>* NextBlock() = 0;
  // Move the next block into *out; false at end of data. Swap semantics
  // where the implementation allows it (out's old buffers return to the
  // producer's recycled cells, so capacity is never lost) — the zero-copy
  // hand-off the padded batcher rides (reference parser.h:95-109 keeps
  // the same discipline with its shared data_ vector). Base: copy.
  virtual bool NextBlockMove(RowBlockContainer<IndexType>* out) {
    const RowBlockContainer<IndexType>* b = NextBlock();
    if (b == nullptr) return false;
    *out = *b;
    return true;
  }
  // Borrowed view of the next block; false at end of data. The default
  // aliases NextBlock()'s container (valid until the next call, like the
  // C-ABI contract). The shard cache's mmap replay (shard_cache.h)
  // overrides this to serve pointers straight into the mapping — the
  // zero-copy lane dct_parser_next_block rides.
  virtual bool NextBlockView(RowBlockView<IndexType>* out) {
    const RowBlockContainer<IndexType>* b = NextBlock();
    if (b == nullptr) return false;
    out->FromContainer(*b);
    return true;
  }
  virtual size_t BytesRead() const = 0;
  // Pin the shuffle permutation the next BeforeFirst samples (mid-epoch
  // resume across restarts; InputSplit::SetShuffleEpoch). False when the
  // underlying split chain does not shuffle.
  virtual bool SetShuffleEpoch(unsigned epoch) {
    (void)epoch;
    return false;
  }
  // Pipeline occupancy counters; false when this parser chain carries no
  // multi-chunk pipeline (threaded=false). Wrappers forward to their base.
  virtual bool GetPipelineStats(ParsePipelineStats* out) const {
    (void)out;
    return false;
  }

  // Factory (reference src/data.cc:62-85 CreateParser_): format is
  // "libsvm" | "csv" | "libfm" | "auto" (resolved from ?format= URI arg).
  // `threaded` pipelines parsing against consumption (PipelinedParser).
  // `chunks_in_flight` bounds the pipeline's outstanding chunks (0 = auto;
  // also settable per-URI via `?chunks_in_flight=K`). Caching sugar
  // (reference uri_spec.h:42-57, src/data.cc:97-103): a legacy `#<path>`
  // fragment enables the DiskCacheParser single-file row-block cache;
  // `#cachefile=<dir>` (or `cache_dir` here / DMLC_DATA_CACHE_DIR) enables
  // the manifest-keyed transcoding shard cache with mmap zero-copy replay
  // (shard_cache.h, doc/caching.md). `cache_mode` / `?cache=` /
  // DMLC_DATA_CACHE is never|auto|refresh.
  static Parser* Create(const std::string& uri, unsigned part, unsigned npart,
                        const std::string& format, int nthread = 0,
                        bool threaded = true, int chunks_in_flight = 0,
                        const std::string& cache_dir = "",
                        const std::string& cache_mode = "");
};

// --------------------------------------------------------------------------
// Chunk-parallel text parser base.
template <typename IndexType>
class TextParserBase : public Parser<IndexType> {
 public:
  TextParserBase(InputSplit* source, int nthread);
  ~TextParserBase() override;

  void BeforeFirst() override;
  const RowBlockContainer<IndexType>* NextBlock() override;
  bool NextBlockMove(RowBlockContainer<IndexType>* out) override;
  size_t BytesRead() const override {
    return bytes_read_.load(std::memory_order_relaxed);
  }
  bool SetShuffleEpoch(unsigned epoch) override {
    return source_->SetShuffleEpoch(epoch);
  }

  // Parse [begin, end) — whole lines — into *out. Public for testing.
  virtual void ParseBlock(const char* begin, const char* end,
                          RowBlockContainer<IndexType>* out) = 0;

  // Fill `blocks` (resized to the worker count) from the next chunk;
  // returns false at end of data. The synchronous (threaded=false) path:
  // barrier fan-out over the persistent pool, one chunk per round.
  bool FillBlocks(std::vector<RowBlockContainer<IndexType>>* blocks);

  // -- multi-chunk pipeline hooks (PipelinedParser stages) -----------------
  // Copy the next chunk into *buf (the source Blob is only valid until the
  // following NextChunk, so in-flight chunks need owned bytes); false at
  // end of data. Counts toward BytesRead.
  bool ReadChunk(std::vector<char>* buf);
  // Tile [begin, end) into `nslice` unit-aligned slices: cuts has
  // nslice + 1 monotone entries, cut i at the first parse-unit head at or
  // after i*size/nslice (the same tiling FillBlocks uses, so pipelined
  // output block boundaries match the barrier path exactly).
  void TileCuts(const char* begin, const char* end, int nslice,
                std::vector<const char*>* cuts);
  // Slice count for a chunk of `size` bytes: nthread, or 1 for chunks too
  // small to amortize the fan-out.
  int SlicesFor(size_t size) const {
    return size < (size_t(1) << 16) ? 1 : nthread_;
  }
  int num_threads() const { return nthread_; }
  // Structural-scan lane this parser decodes with (simd_scan.h SimdTier;
  // resolved from DMLC_PARSE_SIMD + CPUID at construction, reported
  // through ParsePipelineStats). The rec binary lane never consults it.
  int simd_tier() const { return simd_tier_; }

 protected:
  // Worker-tiling resync: the first parse-unit head at/after `hint` in
  // [base, end). Text formats resync at line heads (default); binary
  // formats override (RecParser resyncs at RecordIO magics — the reference
  // splits text by BackFindEndLine and recordio by magic scan,
  // src/recordio.cc FindNextRecordIOHead).
  virtual const char* FindUnitBoundary(const char* base, const char* hint,
                                       const char* end);

  std::unique_ptr<InputSplit> source_;
  int nthread_;
  SimdTier simd_tier_ = kSimdScalar;
  // read from the consumer thread while the pipeline reader fills
  std::atomic<size_t> bytes_read_{0};
  // direct chunk-producer view of source_ when its top layer exposes one
  // (ReadChunk fast lane); probed once, lazily
  RecordChunkSource* chunk_source_ = nullptr;
  bool chunk_source_probed_ = false;

 private:
  // Persistent worker pool for the chunk fan-out: spawning fresh
  // std::threads per chunk costs ~100 us each, which 2 MB chunks turn
  // into a measurable tax (the reference fans out via OpenMP's persistent
  // team, text_parser.h:60-84 — this is the same economics without omp).
  // Workers parse slices 1..n-1 of the current round; slice 0 runs on the
  // calling thread. Round state is handed over under pool_mu_.
  void EnsurePool(int workers);
  void WorkerLoop(int i);

  std::vector<std::thread> pool_;
  std::mutex pool_mu_;
  std::condition_variable pool_cv_, done_cv_;
  uint64_t pool_generation_ DMLC_GUARDED_BY(pool_mu_) = 0;
  int pool_done_ DMLC_GUARDED_BY(pool_mu_) = 0;
  int pool_active_ DMLC_GUARDED_BY(pool_mu_) = 0;
  bool pool_stop_ DMLC_GUARDED_BY(pool_mu_) = false;
  const std::vector<const char*>* round_cuts_
      DMLC_GUARDED_BY(pool_mu_) = nullptr;
  std::vector<RowBlockContainer<IndexType>>* round_blocks_
      DMLC_GUARDED_BY(pool_mu_) = nullptr;
  std::vector<std::exception_ptr>* round_errors_
      DMLC_GUARDED_BY(pool_mu_) = nullptr;

  std::vector<RowBlockContainer<IndexType>> blocks_;
  size_t block_idx_ = 0;
  size_t block_count_ = 0;
};

// libsvm: `label[:weight] [qid:n] index[:value]...`, '#' comments
// (reference src/data/libsvm_parser.h:87-169). Two decode lanes sharing
// ONE tokenizer template: ParseBlockScalar instantiates it with the
// byte-loop numeric primitives, ParseBlockSimd with the fused SWAR field
// decoders plus the stage-1 reserve-hint scan (simd_scan.h); outputs are
// byte-identical by construction (tests/test_parse_simd.py pins it over
// adversarial corpora, DMLC_PARSE_SIMD=0 forces the scalar lane).
template <typename IndexType>
class LibSVMParser : public TextParserBase<IndexType> {
 public:
  LibSVMParser(InputSplit* source,
               const std::map<std::string, std::string>& args, int nthread);
  void ParseBlock(const char* begin, const char* end,
                  RowBlockContainer<IndexType>* out) override;

 private:
  void ParseBlockScalar(const char* begin, const char* end,
                        RowBlockContainer<IndexType>* out);
  void ParseBlockSimd(const char* begin, const char* end,
                      RowBlockContainer<IndexType>* out);
  int indexing_mode_;  // >0: 1-based, 0: 0-based, <0: heuristic
};

// csv: dense rows, explicit column index per value, label/weight columns,
// single-char delimiter, missing values skipped
// (reference src/data/csv_parser.h:24-147).
template <typename IndexType>
class CSVParser : public TextParserBase<IndexType> {
 public:
  CSVParser(InputSplit* source, const std::map<std::string, std::string>& args,
            int nthread);
  void ParseBlock(const char* begin, const char* end,
                  RowBlockContainer<IndexType>* out) override;

 private:
  void ParseBlockScalar(const char* begin, const char* end,
                        RowBlockContainer<IndexType>* out);
  void ParseBlockSimd(const char* begin, const char* end,
                      RowBlockContainer<IndexType>* out);
  int label_column_ = -1;
  int weight_column_ = -1;
  char delimiter_ = ',';
  int value_dtype_ = 0;  // 0=float32, 1=int32, 2=int64
};

// libfm: `label[:weight] field:feature:value...`
// (reference src/data/libfm_parser.h:24-144).
template <typename IndexType>
class LibFMParser : public TextParserBase<IndexType> {
 public:
  LibFMParser(InputSplit* source,
              const std::map<std::string, std::string>& args, int nthread);
  void ParseBlock(const char* begin, const char* end,
                  RowBlockContainer<IndexType>* out) override;

 private:
  void ParseBlockScalar(const char* begin, const char* end,
                        RowBlockContainer<IndexType>* out);
  void ParseBlockSimd(const char* begin, const char* end,
                      RowBlockContainer<IndexType>* out);
  int indexing_mode_;
};

// rec: binary ingest — RecordIO records whose payloads are serialized
// RowBlockContainers (8-byte header: 'DRB1' magic + flags, then the
// rowblock.h wire format). Deserialization is bulk memcpy, so this lane
// can feed the host->HBM path at rates text parsing cannot (the binary
// counterpart of the reference's pre-parsed .rec datasets; chunk-parallel
// via RecordIOChunkReader, reference recordio.h:166). Written by
// dmlc_core_tpu/io/convert.py rows_to_recordio.
template <typename IndexType>
class RecParser : public TextParserBase<IndexType> {
 public:
  RecParser(InputSplit* source, const std::map<std::string, std::string>& args,
            int nthread);
  void ParseBlock(const char* begin, const char* end,
                  RowBlockContainer<IndexType>* out) override;

 protected:
  const char* FindUnitBoundary(const char* base, const char* hint,
                               const char* end) override;
};

// --------------------------------------------------------------------------
// Disk row-block cache (reference src/data/disk_row_iter.h): the first
// epoch serves parsed blocks while appending their binary serialization to
// a cache file; later epochs replay the cache (skipping text parsing and
// the original filesystem entirely), prefetched on a pipeline thread.
template <typename IndexType>
class DiskCacheParser : public Parser<IndexType> {
 public:
  // takes ownership of base; fingerprint identifies (uri, part, npart)
  DiskCacheParser(Parser<IndexType>* base, const std::string& cache_file,
                  const std::string& fingerprint);
  ~DiskCacheParser() override;

  void BeforeFirst() override;
  const RowBlockContainer<IndexType>* NextBlock() override;
  bool NextBlockMove(RowBlockContainer<IndexType>* out) override;
  size_t BytesRead() const override { return base_->BytesRead(); }
  bool SetShuffleEpoch(unsigned epoch) override {
    // unreachable in practice: Create forbids shuffle + #cachefile
    return base_->SetShuffleEpoch(epoch);
  }
  bool GetPipelineStats(ParsePipelineStats* out) const override {
    // meaningful during the write-through epoch; replay bypasses the parse
    // pipeline (counters then freeze at their epoch-1 values)
    return base_->GetPipelineStats(out);
  }

 private:
  void FinalizeCache();
  bool TryOpenCache();
  void StartReplayPipeline();
  void EnsureWriter();  // open the .tmp cache + header on first write

  std::unique_ptr<Parser<IndexType>> base_;
  std::string cache_file_;
  uint64_t fingerprint_ = 0;
  std::unique_ptr<Stream> writer_;
  std::unique_ptr<SeekStream> reader_;
  bool replaying_ = false;
  bool write_complete_ = false;
  // replay is prefetched on a pipeline thread (reference DiskRowIter's
  // ThreadedIter, disk_row_iter.h:96-108)
  PipelineIter<RowBlockContainer<IndexType>> replay_pipe_{4};
  RowBlockContainer<IndexType>* replay_cell_ = nullptr;
  bool replay_started_ = false;
};

// --------------------------------------------------------------------------
// Multi-chunk in-flight parse pipeline — the threaded=true wrapper.
//
// The predecessor (ThreadedParser, reference src/data/parser.h:70-126)
// pipelined exactly ONE chunk against consumption and fanned each chunk out
// behind a barrier (FillBlocks), so the producer thread serialized the
// InputSplit read against the straggler slice of every round and added
// workers mostly waited (BENCH_r05 thread_scaling: +2% at 4 threads).
// Here the stages are decoupled:
//
//   reader thread ──> bounded in-flight chunk queue ──> worker pool
//                        (≤ chunks_in_flight)        (claim (chunk, slice))
//                                  │
//                        ordered head-of-line reassembly ──> consumer
//
// - The reader keeps up to `chunks_in_flight` chunks outstanding, copying
//   each InputSplit::NextChunk blob into an owned, recycled buffer and
//   pre-tiling it into nthread unit-aligned slices (TileCuts — identical
//   tiling to the barrier path, so output blocks are byte-identical to
//   nthread=1 concatenation).
// - Workers claim (chunk, slice) work items oldest-chunk-first; slices of
//   chunk N+1 parse while a straggler of chunk N is still running — no
//   barrier anywhere.
// - The consumer drains chunks strictly in input order (head-of-line wait
//   on the oldest chunk), preserving deterministic output; consumed chunk
//   tasks recycle their buffers through a free list so the zero-copy C-ABI
//   hand-off and NextBlockMove swap semantics keep their capacity-reuse
//   discipline.
// Exceptions from any stage surface at the consumer in input order
// (reference OMPException rethrow semantics).
template <typename IndexType>
class PipelinedParser : public Parser<IndexType> {
 public:
  // takes ownership of base; chunks_in_flight <= 0 picks a default sized
  // to the worker count
  explicit PipelinedParser(TextParserBase<IndexType>* base,
                           int chunks_in_flight = 0);
  ~PipelinedParser() override;

  void BeforeFirst() override;
  const RowBlockContainer<IndexType>* NextBlock() override;
  bool NextBlockMove(RowBlockContainer<IndexType>* out) override;
  size_t BytesRead() const override { return base_->BytesRead(); }
  bool SetShuffleEpoch(unsigned epoch) override {
    return base_->SetShuffleEpoch(epoch);
  }
  bool GetPipelineStats(ParsePipelineStats* out) const override;

 private:
  // One chunk in flight: owned bytes, slice cuts, per-slice output blocks
  // and errors. Buffers (data + blocks) survive recycling, so steady state
  // allocates nothing.
  struct ChunkTask {
    std::vector<char> data;
    std::vector<const char*> cuts;  // nslice + 1 monotone boundaries
    std::vector<RowBlockContainer<IndexType>> blocks;
    std::vector<std::exception_ptr> errors;
    int nslice = 0;
    // next_slice/remaining are guarded by the owning parser's mu_ —
    // documented, not DMLC_GUARDED_BY: clang's thread-safety analysis
    // cannot name another object's member from a nested struct
    int next_slice = 0;  // next unclaimed slice
    int remaining = 0;   // unparsed slices; 0 = complete
    size_t next_serve = 0;  // consumer cursor over blocks[0..nslice)
  };

  void Start();        // spawn reader + workers (lazy, on first NextBlock)
  void StopThreads();  // join all stages, reclaim in-flight tasks
  void ReaderLoop();
  void WorkerLoop();
  RowBlockContainer<IndexType>* NextMutable();  // shared walk for both Next*
  void RecycleCurrent();

  std::unique_ptr<TextParserBase<IndexType>> base_;
  size_t capacity_;
  int nworker_;

  mutable std::mutex mu_;             // mutable: locked by const stats reads
  std::condition_variable space_cv_;  // reader waits for in-flight room
  std::condition_variable work_cv_;   // workers wait for claimable slices
  std::condition_variable done_cv_;   // consumer waits on head-of-line
  // admitted chunks, input order
  std::deque<ChunkTask*> inflight_ DMLC_GUARDED_BY(mu_);
  // prefix of inflight_ with free slices
  std::deque<ChunkTask*> claim_ DMLC_GUARDED_BY(mu_);
  std::vector<ChunkTask*> free_ DMLC_GUARDED_BY(mu_);  // recycled tasks
  bool stop_ DMLC_GUARDED_BY(mu_) = false;
  bool eof_ DMLC_GUARDED_BY(mu_) = false;
  std::exception_ptr reader_error_ DMLC_GUARDED_BY(mu_);
  bool failed_ = false;  // consumer saw an error; restart is forbidden
  bool started_ = false;
  std::thread reader_;
  std::vector<std::thread> workers_;

  ChunkTask* current_ = nullptr;  // chunk being served to the consumer

  // stats: relaxed atomics — written by stage threads, read via the C ABI
  std::atomic<uint64_t> chunks_read_{0}, blocks_delivered_{0},
      reader_waits_{0}, worker_waits_{0}, consumer_waits_{0},
      inflight_peak_{0}, inflight_sum_{0};
};

}  // namespace dct

#endif  // DCT_PARSER_H_
