// Multithreaded text parsers producing CSR row blocks.
//
// Counterpart of reference src/data/parser.h (ParserImpl + ThreadedParser),
// src/data/text_parser.h (chunk → N worker threads, each parsing a
// line-aligned slice), and the format parsers libsvm_parser.h /
// csv_parser.h / libfm_parser.h. Parse semantics (comment/blank skipping,
// label[:weight], qid:, 0/1-based indexing heuristic, CSV missing values,
// NOEOL/BOM/CRLF handling) match the reference; the worker fan-out is
// restructured: slices are tiled forward to line heads and each worker fills
// its own RowBlockContainer which is exposed zero-copy through the C ABI.
#ifndef DCT_PARSER_H_
#define DCT_PARSER_H_

#include <atomic>
#include <condition_variable>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "input_split.h"
#include "registry.h"
#include "rowblock.h"

namespace dct {

template <typename IndexType>
class TextParserBase;

// Parser factory registry entry (reference ParserFactoryReg +
// DMLC_REGISTER_DATA_PARSER, data.h:330-358): formats resolve by name
// through Registry<ParserFactoryReg<I>> so downstream code can register
// additional native formats.
template <typename IndexType>
struct ParserFactoryReg
    : public FunctionRegEntryBase<
          ParserFactoryReg<IndexType>,
          std::function<TextParserBase<IndexType>*(
              InputSplit*, const std::map<std::string, std::string>&, int)>> {
};

template <typename IndexType>
class Parser {
 public:
  virtual ~Parser() = default;
  virtual void BeforeFirst() = 0;
  // Produce the next block of rows; nullptr at end of data. The returned
  // container stays valid until the next call.
  virtual const RowBlockContainer<IndexType>* NextBlock() = 0;
  // Move the next block into *out; false at end of data. Swap semantics
  // where the implementation allows it (out's old buffers return to the
  // producer's recycled cells, so capacity is never lost) — the zero-copy
  // hand-off the padded batcher rides (reference parser.h:95-109 keeps
  // the same discipline with its shared data_ vector). Base: copy.
  virtual bool NextBlockMove(RowBlockContainer<IndexType>* out) {
    const RowBlockContainer<IndexType>* b = NextBlock();
    if (b == nullptr) return false;
    *out = *b;
    return true;
  }
  virtual size_t BytesRead() const = 0;
  // Pin the shuffle permutation the next BeforeFirst samples (mid-epoch
  // resume across restarts; InputSplit::SetShuffleEpoch). False when the
  // underlying split chain does not shuffle.
  virtual bool SetShuffleEpoch(unsigned epoch) {
    (void)epoch;
    return false;
  }

  // Factory (reference src/data.cc:62-85 CreateParser_): format is
  // "libsvm" | "csv" | "libfm" | "auto" (resolved from ?format= URI arg).
  // `threaded` pipelines parsing against consumption (ThreadedParser).
  // `#cachefile` URI sugar enables DiskCacheParser row-block caching
  // (reference uri_spec.h:42-57, src/data.cc:97-103).
  static Parser* Create(const std::string& uri, unsigned part, unsigned npart,
                        const std::string& format, int nthread = 0,
                        bool threaded = true);
};

// --------------------------------------------------------------------------
// Chunk-parallel text parser base.
template <typename IndexType>
class TextParserBase : public Parser<IndexType> {
 public:
  TextParserBase(InputSplit* source, int nthread);
  ~TextParserBase() override;

  void BeforeFirst() override;
  const RowBlockContainer<IndexType>* NextBlock() override;
  bool NextBlockMove(RowBlockContainer<IndexType>* out) override;
  size_t BytesRead() const override {
    return bytes_read_.load(std::memory_order_relaxed);
  }
  bool SetShuffleEpoch(unsigned epoch) override {
    return source_->SetShuffleEpoch(epoch);
  }

  // Parse [begin, end) — whole lines — into *out. Public for testing.
  virtual void ParseBlock(const char* begin, const char* end,
                          RowBlockContainer<IndexType>* out) = 0;

  // Fill `blocks` (resized to the worker count) from the next chunk;
  // returns false at end of data. Used by the ThreadedParser producer.
  bool FillBlocks(std::vector<RowBlockContainer<IndexType>>* blocks);

 protected:
  // Worker-tiling resync: the first parse-unit head at/after `hint` in
  // [base, end). Text formats resync at line heads (default); binary
  // formats override (RecParser resyncs at RecordIO magics — the reference
  // splits text by BackFindEndLine and recordio by magic scan,
  // src/recordio.cc FindNextRecordIOHead).
  virtual const char* FindUnitBoundary(const char* base, const char* hint,
                                       const char* end);

  std::unique_ptr<InputSplit> source_;
  int nthread_;
  // read from the consumer thread while the ThreadedParser producer fills
  std::atomic<size_t> bytes_read_{0};

 private:
  // Persistent worker pool for the chunk fan-out: spawning fresh
  // std::threads per chunk costs ~100 us each, which 2 MB chunks turn
  // into a measurable tax (the reference fans out via OpenMP's persistent
  // team, text_parser.h:60-84 — this is the same economics without omp).
  // Workers parse slices 1..n-1 of the current round; slice 0 runs on the
  // calling thread. Round state is handed over under pool_mu_.
  void EnsurePool(int workers);
  void WorkerLoop(int i);

  std::vector<std::thread> pool_;
  std::mutex pool_mu_;
  std::condition_variable pool_cv_, done_cv_;
  uint64_t pool_generation_ = 0;
  int pool_done_ = 0;
  int pool_active_ = 0;
  bool pool_stop_ = false;
  const std::vector<const char*>* round_cuts_ = nullptr;
  std::vector<RowBlockContainer<IndexType>>* round_blocks_ = nullptr;
  std::vector<std::exception_ptr>* round_errors_ = nullptr;

  std::vector<RowBlockContainer<IndexType>> blocks_;
  size_t block_idx_ = 0;
  size_t block_count_ = 0;
};

// libsvm: `label[:weight] [qid:n] index[:value]...`, '#' comments
// (reference src/data/libsvm_parser.h:87-169).
template <typename IndexType>
class LibSVMParser : public TextParserBase<IndexType> {
 public:
  LibSVMParser(InputSplit* source,
               const std::map<std::string, std::string>& args, int nthread);
  void ParseBlock(const char* begin, const char* end,
                  RowBlockContainer<IndexType>* out) override;

 private:
  int indexing_mode_;  // >0: 1-based, 0: 0-based, <0: heuristic
};

// csv: dense rows, explicit column index per value, label/weight columns,
// single-char delimiter, missing values skipped
// (reference src/data/csv_parser.h:24-147).
template <typename IndexType>
class CSVParser : public TextParserBase<IndexType> {
 public:
  CSVParser(InputSplit* source, const std::map<std::string, std::string>& args,
            int nthread);
  void ParseBlock(const char* begin, const char* end,
                  RowBlockContainer<IndexType>* out) override;

 private:
  int label_column_ = -1;
  int weight_column_ = -1;
  char delimiter_ = ',';
  int value_dtype_ = 0;  // 0=float32, 1=int32, 2=int64
};

// libfm: `label[:weight] field:feature:value...`
// (reference src/data/libfm_parser.h:24-144).
template <typename IndexType>
class LibFMParser : public TextParserBase<IndexType> {
 public:
  LibFMParser(InputSplit* source,
              const std::map<std::string, std::string>& args, int nthread);
  void ParseBlock(const char* begin, const char* end,
                  RowBlockContainer<IndexType>* out) override;

 private:
  int indexing_mode_;
};

// rec: binary ingest — RecordIO records whose payloads are serialized
// RowBlockContainers (8-byte header: 'DRB1' magic + flags, then the
// rowblock.h wire format). Deserialization is bulk memcpy, so this lane
// can feed the host->HBM path at rates text parsing cannot (the binary
// counterpart of the reference's pre-parsed .rec datasets; chunk-parallel
// via RecordIOChunkReader, reference recordio.h:166). Written by
// dmlc_core_tpu/io/convert.py rows_to_recordio.
template <typename IndexType>
class RecParser : public TextParserBase<IndexType> {
 public:
  RecParser(InputSplit* source, const std::map<std::string, std::string>& args,
            int nthread);
  void ParseBlock(const char* begin, const char* end,
                  RowBlockContainer<IndexType>* out) override;

 protected:
  const char* FindUnitBoundary(const char* base, const char* hint,
                               const char* end) override;
};

// --------------------------------------------------------------------------
// Disk row-block cache (reference src/data/disk_row_iter.h): the first
// epoch serves parsed blocks while appending their binary serialization to
// a cache file; later epochs replay the cache (skipping text parsing and
// the original filesystem entirely), prefetched on a pipeline thread.
template <typename IndexType>
class DiskCacheParser : public Parser<IndexType> {
 public:
  // takes ownership of base; fingerprint identifies (uri, part, npart)
  DiskCacheParser(Parser<IndexType>* base, const std::string& cache_file,
                  const std::string& fingerprint);
  ~DiskCacheParser() override;

  void BeforeFirst() override;
  const RowBlockContainer<IndexType>* NextBlock() override;
  bool NextBlockMove(RowBlockContainer<IndexType>* out) override;
  size_t BytesRead() const override { return base_->BytesRead(); }
  bool SetShuffleEpoch(unsigned epoch) override {
    // unreachable in practice: Create forbids shuffle + #cachefile
    return base_->SetShuffleEpoch(epoch);
  }

 private:
  void FinalizeCache();
  bool TryOpenCache();
  void StartReplayPipeline();
  void EnsureWriter();  // open the .tmp cache + header on first write

  std::unique_ptr<Parser<IndexType>> base_;
  std::string cache_file_;
  uint64_t fingerprint_ = 0;
  std::unique_ptr<Stream> writer_;
  std::unique_ptr<SeekStream> reader_;
  bool replaying_ = false;
  bool write_complete_ = false;
  // replay is prefetched on a pipeline thread (reference DiskRowIter's
  // ThreadedIter, disk_row_iter.h:96-108)
  PipelineIter<RowBlockContainer<IndexType>> replay_pipe_{4};
  RowBlockContainer<IndexType>* replay_cell_ = nullptr;
  bool replay_started_ = false;
};

// --------------------------------------------------------------------------
// Pipelined wrapper: parsing runs on a producer thread while the consumer
// drains blocks (reference src/data/parser.h:70-126, capacity 8).
template <typename IndexType>
class ThreadedParser : public Parser<IndexType> {
 public:
  explicit ThreadedParser(TextParserBase<IndexType>* base, size_t capacity = 8);
  ~ThreadedParser() override;

  void BeforeFirst() override;
  const RowBlockContainer<IndexType>* NextBlock() override;
  bool NextBlockMove(RowBlockContainer<IndexType>* out) override;
  size_t BytesRead() const override { return base_->BytesRead(); }
  bool SetShuffleEpoch(unsigned epoch) override {
    return base_->SetShuffleEpoch(epoch);
  }

 private:
  struct Cell {
    std::vector<RowBlockContainer<IndexType>> blocks;
    size_t next = 0;
  };
  RowBlockContainer<IndexType>* NextMutable();  // shared walk for both Next*
  std::unique_ptr<TextParserBase<IndexType>> base_;
  PipelineIter<Cell> pipe_;
  Cell* current_ = nullptr;
  bool started_ = false;
  void EnsureStarted();
};

}  // namespace dct

#endif  // DCT_PARSER_H_
