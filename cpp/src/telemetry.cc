// Telemetry registry implementation (see telemetry.h).
#include "telemetry.h"

#include "base.h"

#include <unistd.h>

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <mutex>
#include <sstream>
#include <vector>

namespace dct {
namespace telemetry {

namespace {

std::atomic<int> g_enabled{-1};  // -1: unresolved (read env on first use)

struct CounterEntry {
  std::string name;
  std::map<std::string, std::string> labels;
  Counter owned;
  std::atomic<uint64_t>* external = nullptr;  // wins over `owned` when set
  uint64_t value() const {
    return external != nullptr
               ? external->load(std::memory_order_relaxed)
               : owned.value();
  }
  void Zero() {
    if (external != nullptr) {
      external->store(0, std::memory_order_relaxed);
    } else {
      owned.Zero();
    }
  }
};

struct GaugeEntry {
  std::string name;
  Gauge gauge;
};

struct HistEntry {
  std::string name;
  std::map<std::string, std::string> labels;
  Hist hist;
};

// Entries live in deques for pointer stability and are never removed; the
// mutex guards registration and the snapshot/reset walks only.
struct Registry {
  std::mutex mu;
  std::deque<CounterEntry> counters DMLC_GUARDED_BY(mu);
  std::deque<GaugeEntry> gauges DMLC_GUARDED_BY(mu);
  std::deque<HistEntry> hists DMLC_GUARDED_BY(mu);
};

Registry& Reg() {
  static Registry* r = new Registry();  // leaked: outlive every static dtor
  return *r;
}

void EscapeJson(const std::string& s, std::string* out) {
  for (char c : s) {
    switch (c) {
      case '"': *out += "\\\""; break;
      case '\\': *out += "\\\\"; break;
      case '\n': *out += "\\n"; break;
      case '\r': *out += "\\r"; break;
      case '\t': *out += "\\t"; break;
      default:
        if (static_cast<unsigned char>(c) < 0x20) {
          char buf[8];
          std::snprintf(buf, sizeof(buf), "\\u%04x", c);
          *out += buf;
        } else {
          *out += c;
        }
    }
  }
}

void AppendNameLabels(const std::string& name,
                      const std::map<std::string, std::string>& labels,
                      std::string* out) {
  *out += "\"name\":\"";
  EscapeJson(name, out);
  *out += "\",\"labels\":{";
  bool first = true;
  for (const auto& kv : labels) {
    if (!first) *out += ',';
    first = false;
    *out += '"';
    EscapeJson(kv.first, out);
    *out += "\":\"";
    EscapeJson(kv.second, out);
    *out += '"';
  }
  *out += '}';
}

}  // namespace

bool Enabled() {
  int v = g_enabled.load(std::memory_order_relaxed);
  if (v < 0) {
    const char* env = std::getenv("DMLC_TELEMETRY");
    v = (env != nullptr &&
         (std::strcmp(env, "0") == 0 || std::strcmp(env, "off") == 0))
            ? 0
            : 1;
    g_enabled.store(v, std::memory_order_relaxed);
  }
  return v != 0;
}

void SetEnabled(bool on) {
  g_enabled.store(on ? 1 : 0, std::memory_order_relaxed);
}

Counter* GetCounter(const std::string& name) {
  return GetCounter(name, {});
}

Counter* GetCounter(const std::string& name,
                    const std::map<std::string, std::string>& labels) {
  Registry& r = Reg();
  std::lock_guard<std::mutex> lk(r.mu);
  for (auto& e : r.counters) {
    // an externally-backed entry still hands out its owned counter: adds
    // to it are shadowed in the snapshot (external wins), never a crash
    if (e.name == name && e.labels == labels) return &e.owned;
  }
  r.counters.emplace_back();
  r.counters.back().name = name;
  r.counters.back().labels = labels;
  return &r.counters.back().owned;
}

void RegisterExternalCounter(const std::string& name,
                             std::atomic<uint64_t>* v) {
  Registry& r = Reg();
  std::lock_guard<std::mutex> lk(r.mu);
  for (auto& e : r.counters) {
    if (e.name == name && e.labels.empty()) {
      e.external = v;
      return;
    }
  }
  r.counters.emplace_back();
  r.counters.back().name = name;
  r.counters.back().external = v;
}

Gauge* GetGauge(const std::string& name) {
  Registry& r = Reg();
  std::lock_guard<std::mutex> lk(r.mu);
  for (auto& e : r.gauges) {
    if (e.name == name) return &e.gauge;
  }
  r.gauges.emplace_back();
  r.gauges.back().name = name;
  return &r.gauges.back().gauge;
}

Hist* GetHist(const std::string& name,
              const std::map<std::string, std::string>& labels) {
  Registry& r = Reg();
  std::lock_guard<std::mutex> lk(r.mu);
  for (auto& e : r.hists) {
    if (e.name == name && e.labels == labels) return &e.hist;
  }
  r.hists.emplace_back();
  r.hists.back().name = name;
  r.hists.back().labels = labels;
  return &r.hists.back().hist;
}

const IoHists* IoHistsFor(const std::string& backend) {
  // small leaked cache: one IoHists per backend, resolved under its own
  // mutex (called once per HttpConnection, never per byte)
  static std::mutex* mu = new std::mutex();
  static std::map<std::string, IoHists>* cache =
      new std::map<std::string, IoHists>();
  std::lock_guard<std::mutex> lk(*mu);
  auto it = cache->find(backend);
  if (it != cache->end()) return &it->second;
  std::map<std::string, std::string> labels{{"backend", backend}};
  IoHists h;
  h.connect_us = GetHist("io_connect_us", labels);
  h.ttfb_us = GetHist("io_ttfb_us", labels);
  h.recv_us = GetHist("io_recv_us", labels);
  return &((*cache)[backend] = h);
}

const RangeHists* RangeHistsFor(const std::string& backend) {
  // same shape as IoHistsFor: one leaked per-backend cache, resolved once
  // per RangeReader construction (never per range)
  static std::mutex* mu = new std::mutex();
  static std::map<std::string, RangeHists>* cache =
      new std::map<std::string, RangeHists>();
  std::lock_guard<std::mutex> lk(*mu);
  auto it = cache->find(backend);
  if (it != cache->end()) return &it->second;
  std::map<std::string, std::string> labels{{"backend", backend}};
  RangeHists h;
  h.bytes = GetHist("io_range_bytes", labels);
  h.wait_us = GetHist("io_range_wait_us", labels);
  return &((*cache)[backend] = h);
}

namespace {

// One (wall, steady) clock pair sampled back to back: the per-process
// anchor every snapshot/trace/dump carries so steady-clock timelines can
// be merged across processes (ranks) without drift.
void AppendAnchor(std::string* out) {
  const uint64_t wall_us = static_cast<uint64_t>(
      std::chrono::duration_cast<std::chrono::microseconds>(
          std::chrono::system_clock::now().time_since_epoch())
          .count());
  const uint64_t steady_us = NowUs();
  *out += "{\"wall_us\":";
  *out += std::to_string(wall_us);
  *out += ",\"steady_us\":";
  *out += std::to_string(steady_us);
  *out += '}';
}

}  // namespace

std::string SnapshotJson() {
  Registry& r = Reg();
  std::string out;
  out.reserve(4096);
  out += "{\"version\":";
  out += std::to_string(kSnapshotVersion);
  out += ",\"enabled\":";
  out += Enabled() ? "true" : "false";
  out += ",\"anchor\":";
  AppendAnchor(&out);
  out += ",\"counters\":[";
  {
    std::lock_guard<std::mutex> lk(r.mu);
    bool first = true;
    for (const auto& e : r.counters) {
      if (!first) out += ',';
      first = false;
      out += '{';
      AppendNameLabels(e.name, e.labels, &out);
      out += ",\"value\":";
      out += std::to_string(e.value());
      out += '}';
    }
    out += "],\"gauges\":[";
    first = true;
    for (const auto& e : r.gauges) {
      if (!first) out += ',';
      first = false;
      out += '{';
      AppendNameLabels(e.name, {}, &out);
      out += ",\"value\":";
      out += std::to_string(e.gauge.value());
      out += '}';
    }
    out += "],\"histograms\":[";
    first = true;
    for (const auto& e : r.hists) {
      if (!first) out += ',';
      first = false;
      out += '{';
      AppendNameLabels(e.name, e.labels, &out);
      out += ",\"count\":";
      out += std::to_string(e.hist.count());
      out += ",\"sum\":";
      out += std::to_string(e.hist.sum());
      out += ",\"buckets\":[";
      for (int i = 0; i <= kHistBuckets; ++i) {
        if (i) out += ',';
        out += std::to_string(e.hist.bucket(i));
      }
      out += "]}";
    }
  }
  out += "]}";
  return out;
}

void Reset() {
  Registry& r = Reg();
  std::lock_guard<std::mutex> lk(r.mu);
  for (auto& e : r.counters) e.Zero();
  for (auto& e : r.gauges) e.gauge.Zero();
  for (auto& e : r.hists) e.hist.Zero();
}

// ------------------------------------------------------------- span ring --
namespace {

// Every field is an atomic so a snapshot racing a writer reads a torn
// RECORD at worst, never undefined behavior; the per-slot seq (published
// last with release, checked before and after the field reads) rejects
// torn records. Slots are overwritten in claim order — the ring holds the
// most recent kSpanRingSize spans.
struct SpanSlot {
  std::atomic<uint64_t> seq{0};  // claim index + 1; 0 = never written
  std::atomic<const char*> name{nullptr};
  std::atomic<uint64_t> span_id{0};
  std::atomic<uint64_t> parent{0};
  std::atomic<uint64_t> start_us{0};
  std::atomic<uint64_t> dur_us{0};
  std::atomic<uint64_t> arg{0};
  std::atomic<uint32_t> tid{0};
};

struct SpanRing {
  std::atomic<uint64_t> cursor{0};     // total spans ever claimed
  std::atomic<uint64_t> next_span{1};  // span-id allocator (0 = no parent)
  std::atomic<uint32_t> next_tid{0};   // small per-thread lane ids
  SpanSlot slots[kSpanRingSize];
};

SpanRing& Ring() {
  static SpanRing* r = new SpanRing();  // leaked: outlive static dtors
  return *r;
}

uint32_t ThreadLane() {
  thread_local uint32_t lane =
      Ring().next_tid.fetch_add(1, std::memory_order_relaxed) + 1;
  return lane;
}

// the thread's currently open TraceSpan (parent of the next nested one)
thread_local uint64_t tls_open_span = 0;

void EmitSpanRecord(const char* name, uint64_t start_us, uint64_t dur_us,
                    uint64_t span_id, uint64_t parent, uint64_t arg) {
  SpanRing& r = Ring();
  const uint64_t idx = r.cursor.fetch_add(1, std::memory_order_relaxed);
  if (idx >= kSpanRingSize) {
    // this claim overwrites the record kSpanRingSize behind it — a wrap
    // must be countable, not silent (labeled per half: the Python ring
    // publishes its own spans_dropped_total{half="python"})
    static Counter* dropped =
        GetCounter("spans_dropped_total", {{"half", "native"}});
    dropped->Add(1);
  }
  SpanSlot& s = r.slots[idx & (kSpanRingSize - 1)];
  // Seqlock write protocol (Boehm, "Can seqlocks get along with
  // programming language memory models"): invalidate, RELEASE FENCE,
  // field stores, release publish. The fence — not a release store of
  // seq, which only orders PRIOR writes — is what guarantees a reader
  // that observed any NEW field value will also observe seq==0 (or the
  // final publish) at its re-check, so a torn old/new record can never
  // pass both seq checks even on weakly-ordered hardware.
  s.seq.store(0, std::memory_order_relaxed);
  std::atomic_thread_fence(std::memory_order_release);
  s.name.store(name, std::memory_order_relaxed);
  s.span_id.store(span_id, std::memory_order_relaxed);
  s.parent.store(parent, std::memory_order_relaxed);
  s.start_us.store(start_us, std::memory_order_relaxed);
  s.dur_us.store(dur_us, std::memory_order_relaxed);
  s.arg.store(arg, std::memory_order_relaxed);
  s.tid.store(ThreadLane(), std::memory_order_relaxed);
  s.seq.store(idx + 1, std::memory_order_release);
}

}  // namespace

void EmitSpan(const char* name, uint64_t start_us, uint64_t dur_us,
              uint64_t arg) {
  if (!Enabled()) return;
  SpanRing& r = Ring();
  EmitSpanRecord(name, start_us, dur_us,
                 r.next_span.fetch_add(1, std::memory_order_relaxed),
                 tls_open_span, arg);
}

TraceSpan::TraceSpan(const char* name)
    : name_(name), active_(Enabled()) {
  if (!active_) return;
  span_id_ = Ring().next_span.fetch_add(1, std::memory_order_relaxed);
  parent_ = tls_open_span;
  tls_open_span = span_id_;
  start_ = NowUs();
}

TraceSpan::~TraceSpan() {
  if (!active_) return;
  tls_open_span = parent_;
  EmitSpanRecord(name_, start_, NowUs() - start_, span_id_, parent_, arg_);
}

std::string TraceJson() {
  SpanRing& r = Ring();
  const uint64_t cur = r.cursor.load(std::memory_order_acquire);
  const uint64_t window = cur < kSpanRingSize ? cur : kSpanRingSize;
  std::string out;
  out.reserve(256 + window * 96);
  out += "{\"version\":1,\"pid\":";
  out += std::to_string(static_cast<uint64_t>(::getpid()));
  out += ",\"anchor\":";
  AppendAnchor(&out);
  out += ",\"emitted\":";
  out += std::to_string(cur);
  out += ",\"dropped\":";
  out += std::to_string(cur - window);
  out += ",\"spans\":[";
  bool first = true;
  for (uint64_t idx = cur - window; idx < cur; ++idx) {
    SpanSlot& s = r.slots[idx & (kSpanRingSize - 1)];
    const uint64_t seq = s.seq.load(std::memory_order_acquire);
    if (seq != idx + 1) continue;  // torn or already overwritten: skip
    const char* name = s.name.load(std::memory_order_relaxed);
    const uint64_t span_id = s.span_id.load(std::memory_order_relaxed);
    const uint64_t parent = s.parent.load(std::memory_order_relaxed);
    const uint64_t start_us = s.start_us.load(std::memory_order_relaxed);
    const uint64_t dur_us = s.dur_us.load(std::memory_order_relaxed);
    const uint64_t arg = s.arg.load(std::memory_order_relaxed);
    const uint32_t tid = s.tid.load(std::memory_order_relaxed);
    // Seqlock read re-check: the acquire FENCE pairs with the writer's
    // release fence — if any field load above saw a new-record value,
    // the re-check is guaranteed to see seq==0 or the new publish and
    // reject; an unchanged seq proves every field read was consistent.
    std::atomic_thread_fence(std::memory_order_acquire);
    if (s.seq.load(std::memory_order_relaxed) != idx + 1 ||
        name == nullptr) {
      continue;
    }
    if (!first) out += ',';
    first = false;
    out += "{\"name\":\"";
    EscapeJson(name, &out);
    out += "\",\"id\":";
    out += std::to_string(span_id);
    out += ",\"parent\":";
    out += std::to_string(parent);
    out += ",\"tid\":";
    out += std::to_string(tid);
    out += ",\"ts\":";
    out += std::to_string(start_us);
    out += ",\"dur\":";
    out += std::to_string(dur_us);
    out += ",\"arg\":";
    out += std::to_string(arg);
    out += '}';
  }
  out += "]}";
  return out;
}

void TraceReset() {
  SpanRing& r = Ring();
  // clear the slot seqs FIRST: a stale seq matching a post-reset claim
  // index would let TraceJson stitch an old record into the new window
  for (auto& s : r.slots) s.seq.store(0, std::memory_order_relaxed);
  r.cursor.store(0, std::memory_order_release);
}

bool FlightDump(const char* reason) {
  const char* dir = std::getenv("DMLC_TRACE_DUMP");
  if (dir == nullptr || dir[0] == '\0') return false;
  static std::atomic<uint32_t> n{0};
  const uint32_t id = n.fetch_add(1, std::memory_order_relaxed);
  std::string path = std::string(dir) + "/flight_native_" +
                     std::to_string(static_cast<uint64_t>(::getpid())) +
                     "_" + std::to_string(id) + ".json";
  std::string doc;
  doc += "{\"reason\":\"";
  EscapeJson(reason == nullptr ? "" : reason, &doc);
  doc += "\",\"anchor\":";
  AppendAnchor(&doc);
  doc += ",\"trace\":";
  doc += TraceJson();
  doc += ",\"metrics\":";
  doc += SnapshotJson();
  doc += "}\n";
  // plain stdio, errors swallowed: the dump is a best-effort postmortem
  // and must never mask (or re-enter, via the fault plane) the failure
  // being recorded
  std::FILE* f = std::fopen(path.c_str(), "w");
  if (f == nullptr) return false;
  const bool ok = std::fwrite(doc.data(), 1, doc.size(), f) == doc.size();
  std::fclose(f);
  return ok;
}

}  // namespace telemetry
}  // namespace dct
