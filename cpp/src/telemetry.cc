// Telemetry registry implementation (see telemetry.h).
#include "telemetry.h"

#include "base.h"

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <mutex>
#include <sstream>
#include <vector>

namespace dct {
namespace telemetry {

namespace {

std::atomic<int> g_enabled{-1};  // -1: unresolved (read env on first use)

struct CounterEntry {
  std::string name;
  std::map<std::string, std::string> labels;
  Counter owned;
  std::atomic<uint64_t>* external = nullptr;  // wins over `owned` when set
  uint64_t value() const {
    return external != nullptr
               ? external->load(std::memory_order_relaxed)
               : owned.value();
  }
  void Zero() {
    if (external != nullptr) {
      external->store(0, std::memory_order_relaxed);
    } else {
      owned.Zero();
    }
  }
};

struct GaugeEntry {
  std::string name;
  Gauge gauge;
};

struct HistEntry {
  std::string name;
  std::map<std::string, std::string> labels;
  Hist hist;
};

// Entries live in deques for pointer stability and are never removed; the
// mutex guards registration and the snapshot/reset walks only.
struct Registry {
  std::mutex mu;
  std::deque<CounterEntry> counters DMLC_GUARDED_BY(mu);
  std::deque<GaugeEntry> gauges DMLC_GUARDED_BY(mu);
  std::deque<HistEntry> hists DMLC_GUARDED_BY(mu);
};

Registry& Reg() {
  static Registry* r = new Registry();  // leaked: outlive every static dtor
  return *r;
}

void EscapeJson(const std::string& s, std::string* out) {
  for (char c : s) {
    switch (c) {
      case '"': *out += "\\\""; break;
      case '\\': *out += "\\\\"; break;
      case '\n': *out += "\\n"; break;
      case '\r': *out += "\\r"; break;
      case '\t': *out += "\\t"; break;
      default:
        if (static_cast<unsigned char>(c) < 0x20) {
          char buf[8];
          std::snprintf(buf, sizeof(buf), "\\u%04x", c);
          *out += buf;
        } else {
          *out += c;
        }
    }
  }
}

void AppendNameLabels(const std::string& name,
                      const std::map<std::string, std::string>& labels,
                      std::string* out) {
  *out += "\"name\":\"";
  EscapeJson(name, out);
  *out += "\",\"labels\":{";
  bool first = true;
  for (const auto& kv : labels) {
    if (!first) *out += ',';
    first = false;
    *out += '"';
    EscapeJson(kv.first, out);
    *out += "\":\"";
    EscapeJson(kv.second, out);
    *out += '"';
  }
  *out += '}';
}

}  // namespace

bool Enabled() {
  int v = g_enabled.load(std::memory_order_relaxed);
  if (v < 0) {
    const char* env = std::getenv("DMLC_TELEMETRY");
    v = (env != nullptr &&
         (std::strcmp(env, "0") == 0 || std::strcmp(env, "off") == 0))
            ? 0
            : 1;
    g_enabled.store(v, std::memory_order_relaxed);
  }
  return v != 0;
}

void SetEnabled(bool on) {
  g_enabled.store(on ? 1 : 0, std::memory_order_relaxed);
}

Counter* GetCounter(const std::string& name) {
  return GetCounter(name, {});
}

Counter* GetCounter(const std::string& name,
                    const std::map<std::string, std::string>& labels) {
  Registry& r = Reg();
  std::lock_guard<std::mutex> lk(r.mu);
  for (auto& e : r.counters) {
    // an externally-backed entry still hands out its owned counter: adds
    // to it are shadowed in the snapshot (external wins), never a crash
    if (e.name == name && e.labels == labels) return &e.owned;
  }
  r.counters.emplace_back();
  r.counters.back().name = name;
  r.counters.back().labels = labels;
  return &r.counters.back().owned;
}

void RegisterExternalCounter(const std::string& name,
                             std::atomic<uint64_t>* v) {
  Registry& r = Reg();
  std::lock_guard<std::mutex> lk(r.mu);
  for (auto& e : r.counters) {
    if (e.name == name && e.labels.empty()) {
      e.external = v;
      return;
    }
  }
  r.counters.emplace_back();
  r.counters.back().name = name;
  r.counters.back().external = v;
}

Gauge* GetGauge(const std::string& name) {
  Registry& r = Reg();
  std::lock_guard<std::mutex> lk(r.mu);
  for (auto& e : r.gauges) {
    if (e.name == name) return &e.gauge;
  }
  r.gauges.emplace_back();
  r.gauges.back().name = name;
  return &r.gauges.back().gauge;
}

Hist* GetHist(const std::string& name,
              const std::map<std::string, std::string>& labels) {
  Registry& r = Reg();
  std::lock_guard<std::mutex> lk(r.mu);
  for (auto& e : r.hists) {
    if (e.name == name && e.labels == labels) return &e.hist;
  }
  r.hists.emplace_back();
  r.hists.back().name = name;
  r.hists.back().labels = labels;
  return &r.hists.back().hist;
}

const IoHists* IoHistsFor(const std::string& backend) {
  // small leaked cache: one IoHists per backend, resolved under its own
  // mutex (called once per HttpConnection, never per byte)
  static std::mutex* mu = new std::mutex();
  static std::map<std::string, IoHists>* cache =
      new std::map<std::string, IoHists>();
  std::lock_guard<std::mutex> lk(*mu);
  auto it = cache->find(backend);
  if (it != cache->end()) return &it->second;
  std::map<std::string, std::string> labels{{"backend", backend}};
  IoHists h;
  h.connect_us = GetHist("io_connect_us", labels);
  h.ttfb_us = GetHist("io_ttfb_us", labels);
  h.recv_us = GetHist("io_recv_us", labels);
  return &((*cache)[backend] = h);
}

const RangeHists* RangeHistsFor(const std::string& backend) {
  // same shape as IoHistsFor: one leaked per-backend cache, resolved once
  // per RangeReader construction (never per range)
  static std::mutex* mu = new std::mutex();
  static std::map<std::string, RangeHists>* cache =
      new std::map<std::string, RangeHists>();
  std::lock_guard<std::mutex> lk(*mu);
  auto it = cache->find(backend);
  if (it != cache->end()) return &it->second;
  std::map<std::string, std::string> labels{{"backend", backend}};
  RangeHists h;
  h.bytes = GetHist("io_range_bytes", labels);
  h.wait_us = GetHist("io_range_wait_us", labels);
  return &((*cache)[backend] = h);
}

std::string SnapshotJson() {
  Registry& r = Reg();
  std::string out;
  out.reserve(4096);
  out += "{\"version\":";
  out += std::to_string(kSnapshotVersion);
  out += ",\"enabled\":";
  out += Enabled() ? "true" : "false";
  out += ",\"counters\":[";
  {
    std::lock_guard<std::mutex> lk(r.mu);
    bool first = true;
    for (const auto& e : r.counters) {
      if (!first) out += ',';
      first = false;
      out += '{';
      AppendNameLabels(e.name, e.labels, &out);
      out += ",\"value\":";
      out += std::to_string(e.value());
      out += '}';
    }
    out += "],\"gauges\":[";
    first = true;
    for (const auto& e : r.gauges) {
      if (!first) out += ',';
      first = false;
      out += '{';
      AppendNameLabels(e.name, {}, &out);
      out += ",\"value\":";
      out += std::to_string(e.gauge.value());
      out += '}';
    }
    out += "],\"histograms\":[";
    first = true;
    for (const auto& e : r.hists) {
      if (!first) out += ',';
      first = false;
      out += '{';
      AppendNameLabels(e.name, e.labels, &out);
      out += ",\"count\":";
      out += std::to_string(e.hist.count());
      out += ",\"sum\":";
      out += std::to_string(e.hist.sum());
      out += ",\"buckets\":[";
      for (int i = 0; i <= kHistBuckets; ++i) {
        if (i) out += ',';
        out += std::to_string(e.hist.bucket(i));
      }
      out += "]}";
    }
  }
  out += "]}";
  return out;
}

void Reset() {
  Registry& r = Reg();
  std::lock_guard<std::mutex> lk(r.mu);
  for (auto& e : r.counters) e.Zero();
  for (auto& e : r.gauges) e.gauge.Zero();
  for (auto& e : r.hists) e.hist.Zero();
}

}  // namespace telemetry
}  // namespace dct
