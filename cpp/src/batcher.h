// Native padded-batch assembly for the TPU device bridge.
//
// The reference pipeline ends at host CSR views (RowBlockIter, reference
// include/dmlc/data.h:267); the TPU-native pipeline must emit *static-shape*
// batches (fixed rows per batch, power-of-two nnz buckets) so XLA compiles a
// bounded set of programs (SURVEY §7 hard part 1, "ragged → device").
//
// This module does that reshaping in C++ on the parser side of the ctypes
// boundary: Python asks for the next batch's metadata (row count, nnz
// bucket), allocates numpy arrays of exactly that shape, and the Fill* call
// writes them in one pass — no per-block numpy concatenation, padding, or
// fancy indexing on the (GIL-holding) Python thread.
//
// Zero-copy discipline (reference src/data/parser.h:95-109): parsed blocks
// are MOVED from the parser (Parser::NextBlockMove swap hand-off) into a
// deque and consumed through a (block, row) cursor — the only host copy of
// the parsed data is the final write into the caller's batch buffers.
// Normalization (implicit 1.0 values, default weights, typed csv values,
// qid/field sentinels) happens during that single write.
//
// Layouts match dmlc_core_tpu/tpu/device_iter.py:
//   CSR:   row/col/val [D, bucket]; per-nonzero local row segment ids with a
//          sacrificial padding segment id == R; label/weight [D*R] with
//          weight 0 marking padding rows; nrows [D].
//   Dense: x [D*R, F] zero-filled then scattered (the MXU on-ramp for
//          low-dimensional data, e.g. HIGGS's 28 columns), float32 or bf16.
#ifndef DCT_BATCHER_H_
#define DCT_BATCHER_H_

#include <cstdint>
#include <deque>
#include <memory>
#include <vector>

#include "parser.h"

namespace dct {

class PaddedBatcher {
 public:
  // Takes ownership of parser. batch_rows must divide by num_shards.
  PaddedBatcher(Parser<uint32_t>* parser, uint64_t batch_rows,
                uint32_t num_shards, uint64_t min_nnz_bucket);

  // Stage the next batch. Returns false at end of data. On success:
  //   *take      true (unpadded) row count, <= batch_rows
  //   *bucket    per-shard nnz capacity (next pow2 of max shard nnz)
  //   *max_index running max feature id (drives the dense/csr auto choice)
  //   *has_qid   1 when any parsed block carried query/group ids
  //   *has_field 1 when any parsed block carried per-nonzero field ids
  bool NextMeta(uint64_t* take, uint64_t* bucket, uint64_t* max_index,
                int* has_qid, int* has_field);

  // Consume the staged batch into caller buffers (shapes per header
  // comment). qid is [batch_rows] int32 group ids (-1 on padding rows and
  // rows from qid-less blocks — the sentinel can't collide with a real
  // qid:0) and field is [D, bucket] int32 per-nonzero field ids (0 on
  // padding nonzeros); either may be null to skip (reference data.h:174-236
  // carries both on RowBlock — this is their device-layout continuation).
  void FillCSR(int32_t* row, int32_t* col, float* val, float* label,
               float* weight, int32_t* nrows, int32_t* qid = nullptr,
               int32_t* field = nullptr);
  // x is [batch_rows, num_features], zeroed here before scatter. x_dtype
  // selects the element store: 0 = float32, 1 = bfloat16 (uint16 storage,
  // round-to-nearest-even) — the MXU-native dtype; emitting bf16 here halves
  // both the host fill bytes and the host->HBM transfer bytes and removes
  // the numpy astype copy from the Python side. Field ids have no dense
  // representation; use the CSR layout for field-aware models.
  void FillDense(void* x, int x_dtype, uint64_t num_features, float* label,
                 float* weight, int32_t* nrows, int32_t* qid = nullptr);

  // Fused packed-batch fill: ONE pass writes the shard-major transfer
  // packs the device lane ships as-is, so Python never touches a plane.
  //   big [D, kb, bucket] int32  per shard: row, col, [val f32 bits when
  //                              val_dtype==0], [field]
  //   val [D, bucket] uint16     bf16 values, only when val_dtype==1 (the
  //                              separate leaf keeps the pack int32-pure)
  //   aux [D, ka, R] int32       per shard: label bits, weight bits,
  //                              [qid], nrows plane ([d, last, 0] = shard
  //                              d's true row count)
  // kb/ka pin the caller's plane layout (kb = 2 + (val_dtype==0)
  // + has_field, ka = 3 + has_qid — validated here); nrows [D] is the
  // host-side copy of the per-shard counts. Writing straight into the
  // caller's recyclable 64-byte-aligned staging buffers is what makes the
  // downstream device_put zero-copy (device_iter.py `_device_put`).
  void FillPacked(int32_t* big, int32_t kb, void* val, int32_t val_dtype,
                  int32_t* aux, int32_t ka, int32_t* nrows);
  // Dense twin: x as FillDense, label/weight/qid/nrows fused into the
  // shard-major aux pack.
  void FillDensePacked(void* x, int x_dtype, uint64_t num_features,
                       int32_t* aux, int32_t ka, int32_t* nrows);

  void BeforeFirst();
  size_t BytesRead() const { return parser_->BytesRead(); }
  // Pin the shuffle permutation the next BeforeFirst samples (mid-epoch
  // resume; Parser::SetShuffleEpoch). False when nothing shuffles.
  bool SetShuffleEpoch(unsigned epoch) {
    return parser_->SetShuffleEpoch(epoch);
  }

 private:
  // pending parsed blocks in arrival order; the front is partially
  // consumed up to row_in_front_
  using Block = RowBlockContainer<uint32_t>;

  void Accumulate();           // move parser blocks in until a batch pends
  // Visit the staged batch's rows as (block, row range) segments:
  // fn(block, r0, r1, out_row) covers block-local rows [r0, r1) landing at
  // batch rows [out_row, out_row + (r1-r0)).
  template <typename Fn>
  void ForEachRowRange(uint64_t skip, uint64_t count, Fn&& fn) const;
  template <typename T>
  void FillDenseT(T* x, uint64_t num_features);  // zero + scatter, typed
  void FillQid(int32_t* qid);  // staged qid column (or the -1 sentinel)
  void FillRowArrays(float* label, float* weight, int32_t* nrows);
  // One shard's nonzero planes (row segment ids, cols, fields) with the
  // value store abstracted out: copy_vals(block, p0, written, n) writes n
  // normalized values, pad_vals(written) zeroes [written, bucket_). Shared
  // by FillCSR (f32 planes) and FillPacked (f32-in-big or separate bf16).
  template <typename CopyVals, typename PadVals>
  void FillShardNnz(uint32_t d, int32_t* rowd, int32_t* cold,
                    int32_t* fieldd, CopyVals&& copy_vals,
                    PadVals&& pad_vals);
  // Shard-major row-wise planes of the packed layout: label/weight bits,
  // optional qid, and the nrows plane, plus the host-side nrows[D] copy.
  void FillRowWisePacked(int32_t* aux, int32_t ka, int32_t* nrows);
  void Consume();              // pop the staged rows off the deque
  // nnz of block-local rows [r0, r1)
  static uint64_t RowRangeNnz(const Block& b, uint64_t r0, uint64_t r1) {
    return b.offset[r1] - b.offset[r0];
  }
  // value of nonzero k of `b` under dtype/implicit-1.0 normalization
  static float ValueAt(const Block& b, uint64_t k) {
    if (b.value_dtype == 1) return static_cast<float>(b.value_i32[k]);
    if (b.value_dtype == 2) return static_cast<float>(b.value_i64[k]);
    return b.value.empty() ? 1.0f : b.value[k];
  }

  std::unique_ptr<Parser<uint32_t>> parser_;
  const uint64_t batch_rows_;
  const uint32_t num_shards_;
  const uint64_t min_bucket_;

  std::deque<Block> blocks_;
  // consumed blocks parked here (cleared, capacity kept) and fed back as
  // NextBlockMove out-arguments, so the swap hand-off really does recycle
  // buffer capacity end-to-end instead of reallocating per chunk
  std::vector<Block> spares_;
  uint64_t row_in_front_ = 0;  // consumed rows of blocks_.front()
  uint64_t avail_rows_ = 0;    // unconsumed rows across the deque
  bool done_ = false;
  bool have_qid_ = false;
  bool have_field_ = false;
  uint64_t max_index_ = 0;

  // staged by NextMeta for the following Fill* call
  uint64_t take_ = 0;
  uint64_t bucket_ = 0;
  bool staged_ = false;
};

}  // namespace dct

#endif  // DCT_BATCHER_H_
