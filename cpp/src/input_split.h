// Record-aligned distributed input splitting.
//
// Counterpart of reference include/dmlc/io.h:155-302 (InputSplit) and
// src/io/input_split_base.{h,cc} / line_split / recordio_split /
// indexed_recordio_split / threaded_input_split / cached_input_split /
// single_file_split. The distributed-read contract (SURVEY §3.2, reference
// input_split_base.cc:30-64): the byte space of the expanded file list is
// tiled into num_parts aligned ranges, and both edges of each range are moved
// forward to the next record head with the *same* rule — so every record
// belongs to exactly one part and the union of parts covers the dataset.
//
// Architecture here differs from the reference: one ByteSplit base owns a
// (file cursor, chunk buffer, overflow carry) state machine, and format
// policy objects supply three hooks: SeekRecordHead (stream resync),
// FindLastRecordHead (chunk-tail truncation), and record extraction.
#ifndef DCT_INPUT_SPLIT_H_
#define DCT_INPUT_SPLIT_H_

#include <atomic>
#include <memory>
#include <string>
#include <vector>

#include "filesys.h"
#include "pipeline.h"
#include "stream.h"

namespace dct {

class InputSplit {
 public:
  struct Blob {
    void* dptr = nullptr;
    size_t size = 0;
  };

  virtual ~InputSplit() = default;
  // restart this part from its beginning (re-shuffles shuffled variants)
  virtual void BeforeFirst() = 0;
  // Pin the permutation the NEXT BeforeFirst() samples: shuffled variants
  // derive their per-epoch order from (seed, epoch), so a checkpoint that
  // records the epoch can replay the exact visit order after a restart —
  // without this, a resumed skip-prefix walks a different permutation and
  // silently duplicates/drops rows (mid-epoch resume, device_iter.py).
  // Returns false when nothing in the split chain shuffles (ordering is
  // epoch-independent and resume is safe anyway).
  virtual bool SetShuffleEpoch(unsigned epoch) {
    (void)epoch;
    return false;
  }
  // next single record; false at end of part
  virtual bool NextRecord(Blob* out) = 0;
  // next raw chunk of whole records; false at end of part
  virtual bool NextChunk(Blob* out) = 0;
  virtual void HintChunkSize(size_t bytes) {}
  virtual size_t GetTotalSize() = 0;
  // re-point this object at another (rank, nsplit) partition
  virtual void ResetPartition(unsigned rank, unsigned nsplit) = 0;

  // Factory (reference src/io.cc:81-130). type is "text" | "recordio" |
  // "indexed_recordio" (requires index_uri; honors shuffle/seed/batch_size).
  // uri may be ';'-separated and may name directories or trailing-'*'
  // globs. Composition order: base split -> CachedSplit (when cache_file)
  // -> PrefetchSplit (threaded) -> ShuffleSplit (when shuffle_parts > 1).
  static InputSplit* Create(const std::string& uri, unsigned part,
                            unsigned nsplit, const std::string& type,
                            const std::string& index_uri = "",
                            bool shuffle = false, int seed = 0,
                            size_t batch_size = 256,
                            bool recurse_directories = false,
                            bool threaded = true,
                            const std::string& cache_file = "",
                            unsigned shuffle_parts = 0);
};

// ---------------------------------------------------------------------------
// Chunk-producer interface consumed by the prefetch/cache wrappers: fills a
// caller buffer with whole records and extracts records from such buffers.
class RecordChunkSource {
 public:
  virtual ~RecordChunkSource() = default;
  virtual bool FillChunkBuffer(std::vector<char>* buf) = 0;
  // Extraction must only touch extraction state (concurrent with filling).
  virtual bool ExtractRecordAt(char* data, size_t valid, size_t* cursor,
                               InputSplit::Blob* out) = 0;
  virtual void SourceBeforeFirst() = 0;
};

// Expand a ';'-separated uri list (directories, trailing-'*' globs) into an
// ordered file list (reference input_split_base.cc:96-147).
std::vector<FileInfo> ExpandFileList(const std::string& uri,
                                     bool recurse_directories);

// ---------------------------------------------------------------------------
// Base byte-range splitter over an expanded file list.
class ByteSplit : public InputSplit, public RecordChunkSource {
 public:
  ByteSplit(const std::string& uri, unsigned align_bytes, bool is_text,
            bool recurse_directories);

  void BeforeFirst() override;
  bool NextRecord(Blob* out) override;
  bool NextChunk(Blob* out) override;
  void HintChunkSize(size_t bytes) override {
    chunk_size_ = std::max(bytes, size_t(64));
  }
  size_t GetTotalSize() override { return total_size_; }
  void ResetPartition(unsigned rank, unsigned nsplit) override;

 public:
  // --- format hooks ---
  // Advance `s` (positioned inside a record) to the next record head; return
  // bytes consumed. `file_size` is the size of the current file.
  virtual size_t SeekRecordHead(SeekStream* s, size_t local_pos,
                                size_t file_size) = 0;
  // Last record-head offset in [begin, end) strictly after `begin`, given
  // that `begin` is a record head; bytes from there on are carried to the
  // next chunk. Return 0 when no boundary found (chunk must grow).
  virtual size_t FindLastRecordHead(const char* begin, const char* end) = 0;

  // RecordChunkSource: fill `*buf` with whole records (overflow carry
  // preserved across calls); false at end of partition.
  bool FillChunkBuffer(std::vector<char>* buf) override;
  void SourceBeforeFirst() override { BeforeFirst(); }

 protected:
  // chunk data for unwrapped record iteration
  std::vector<char> chunk_;
  size_t cursor_ = 0;  // record-extraction position in chunk_

 private:
  size_t GlobalBoundaryFixup(size_t ofs);
  void SeekToGlobal(size_t ofs);
  // Read up to `want` bytes from the partition byte range, crossing file
  // boundaries, injecting '\n' between text files lacking trailing newlines
  // (the NOEOL rule, reference input_split_base.cc:195-199). Returns bytes
  // written into buf.
  size_t ReadSpan(char* buf, size_t want);

  std::vector<FileInfo> files_;
  std::vector<size_t> file_start_;  // cumulative start offset of each file
  size_t total_size_ = 0;

  size_t begin_ = 0, end_ = 0;  // adjusted partition range (global bytes)
  unsigned rank_ = 0, nsplit_ = 1;

  // read cursor
  size_t file_idx_ = 0;
  size_t local_pos_ = 0;  // position within current file
  std::unique_ptr<SeekStream> cur_stream_;
  char prev_byte_ = '\n';  // last byte read from current file
  bool pending_newline_ = false;

  std::vector<char> overflow_;  // partial trailing record from last chunk
  size_t chunk_size_;
  bool exhausted_ = false;

 protected:
  unsigned align_bytes_;
  bool is_text_;
};

// Sequential line split over one non-seekable stream — the stdin / single
// local FILE fallback (reference src/io/single_file_split.h:32-179, selected
// at src/io.cc:94-96 when uri=="stdin"). Partitioning is not possible on a
// pipe, so part must be 0 of 1.
class SingleFileSplit : public InputSplit {
 public:
  explicit SingleFileSplit(const std::string& uri);

  void BeforeFirst() override;
  bool NextRecord(Blob* out) override;
  bool NextChunk(Blob* out) override;
  void HintChunkSize(size_t bytes) override {
    chunk_size_ = std::max(bytes, size_t(64));
  }
  size_t GetTotalSize() override;
  void ResetPartition(unsigned rank, unsigned nsplit) override;

 private:
  // read chunk_size_ bytes + extend to the next '\n' (or EOF)
  bool FillChunk();

  std::string uri_;
  std::unique_ptr<Stream> stream_;
  std::vector<char> chunk_;
  size_t valid_ = 0;   // bytes of chunk_ holding whole records
  size_t cursor_ = 0;  // record-extraction position
  size_t chunk_size_ = 16 << 20;
  bool exhausted_ = false;
};

// Text records delimited by '\n' (reference src/io/line_split.cc).
class LineSplit : public ByteSplit {
 public:
  LineSplit(const std::string& uri, unsigned part, unsigned nsplit,
            bool recurse_directories = false);

 public:
  size_t SeekRecordHead(SeekStream* s, size_t local_pos,
                        size_t file_size) override;
  size_t FindLastRecordHead(const char* begin, const char* end) override;
  bool ExtractRecordAt(char* data, size_t valid, size_t* cursor,
                       Blob* out) override;
};

// Binary recordio records (reference src/io/recordio_split.cc): resync by
// scanning for an aligned magic word whose following header has cflag 0|1.
class RecordIOSplit : public ByteSplit {
 public:
  RecordIOSplit(const std::string& uri, unsigned part, unsigned nsplit,
                bool recurse_directories = false);

 public:
  size_t SeekRecordHead(SeekStream* s, size_t local_pos,
                        size_t file_size) override;
  size_t FindLastRecordHead(const char* begin, const char* end) override;
  bool ExtractRecordAt(char* data, size_t valid, size_t* cursor,
                       Blob* out) override;

 private:
  std::string assembled_;
};

// ---------------------------------------------------------------------------
// Record-exact partitioned split over an external index file of
// `record_index byte_offset` text pairs (reference src/io/
// indexed_recordio_split.{h,cc}): partitions BY RECORD COUNT, batches
// batch_size records per chunk, optionally visiting records in a freshly
// shuffled order each epoch (kRandMagic + seed mt19937, reshuffled in
// BeforeFirst — reference :221-233).
class IndexedRecordIOSplit : public InputSplit, public RecordChunkSource {
 public:
  IndexedRecordIOSplit(const std::string& uri, const std::string& index_uri,
                       unsigned part, unsigned nsplit, size_t batch_size,
                       bool shuffle, int seed, bool recurse_directories);

  void BeforeFirst() override;
  bool NextRecord(Blob* out) override;
  bool NextChunk(Blob* out) override;
  size_t GetTotalSize() override { return total_size_; }
  void ResetPartition(unsigned rank, unsigned nsplit) override;
  bool SetShuffleEpoch(unsigned epoch) override {
    epoch_.store(epoch, std::memory_order_relaxed);
    return shuffle_;
  }

  bool FillChunkBuffer(std::vector<char>* buf) override;
  bool ExtractRecordAt(char* data, size_t valid, size_t* cursor,
                       Blob* out) override;
  void SourceBeforeFirst() override { BeforeFirst(); }

 private:
  void ReadSpanAt(size_t global_ofs, char* dst, size_t size);

  std::vector<FileInfo> files_;
  std::vector<size_t> file_start_;
  size_t total_size_ = 0;
  // (global byte offset, byte size) of every record, in file order
  std::vector<std::pair<size_t, size_t>> index_;
  size_t lo_ = 0, hi_ = 0;     // record range of this partition
  std::vector<size_t> order_;  // visit order within [lo_, hi_)
  size_t next_rec_ = 0;
  size_t batch_size_;
  bool shuffle_;
  int seed_;
  // written by SetShuffleEpoch on the control thread, read/bumped inside
  // BeforeFirst on the prefetch producer thread (the pipe's mutex orders
  // the two; atomic removes the formal data race)
  std::atomic<unsigned> epoch_{0};
  std::vector<char> chunk_;
  size_t cursor_ = 0;
  std::string assembled_;
  std::unique_ptr<SeekStream> open_stream_;  // reused across records
  size_t open_file_ = size_t(-1);
};

// ---------------------------------------------------------------------------
// Write-through chunk cache (reference src/io/cached_input_split.h): the
// first epoch streams [u64 size][bytes] frames of every chunk to a local
// cache file while serving them; later epochs replay from the cache,
// skipping the original (possibly remote) filesystem entirely.
class CachedSplit : public InputSplit, public RecordChunkSource {
 public:
  // takes ownership of base (which must also be the extraction source).
  // `fingerprint` identifies (uri, part, nsplit, type); a pre-existing cache
  // written under a different fingerprint is ignored and rebuilt.
  CachedSplit(InputSplit* base, RecordChunkSource* base_src,
              const std::string& cache_file, const std::string& fingerprint);
  ~CachedSplit() override;

  void BeforeFirst() override;
  bool NextRecord(Blob* out) override;
  bool NextChunk(Blob* out) override;
  void HintChunkSize(size_t bytes) override { base_->HintChunkSize(bytes); }
  size_t GetTotalSize() override { return base_->GetTotalSize(); }
  void ResetPartition(unsigned rank, unsigned nsplit) override;
  bool SetShuffleEpoch(unsigned epoch) override {
    return base_->SetShuffleEpoch(epoch);
  }

  bool FillChunkBuffer(std::vector<char>* buf) override;
  bool ExtractRecordAt(char* data, size_t valid, size_t* cursor,
                       Blob* out) override;
  void SourceBeforeFirst() override { BeforeFirst(); }

 private:
  void FinalizeCache();

  std::unique_ptr<InputSplit> base_;
  RecordChunkSource* base_src_;  // borrowed view of base_
  std::string cache_file_;
  uint64_t fingerprint_ = 0;
  std::unique_ptr<Stream> cache_writer_;
  std::unique_ptr<SeekStream> cache_reader_;
  bool replaying_ = false;
  bool write_complete_ = false;
  std::vector<char> chunk_;
  size_t cursor_ = 0;
};

// ---------------------------------------------------------------------------
// Coarse-grained global shuffle (reference include/dmlc/
// input_split_shuffle.h): multiplies the partition count by
// num_shuffle_parts and visits this part's sub-parts in a freshly shuffled
// order each epoch.
class ShuffleSplit : public InputSplit {
 public:
  ShuffleSplit(InputSplit* base, unsigned part, unsigned nsplit,
               unsigned num_shuffle_parts, int seed);

  void BeforeFirst() override;
  bool NextRecord(Blob* out) override;
  bool NextChunk(Blob* out) override;
  void HintChunkSize(size_t bytes) override { base_->HintChunkSize(bytes); }
  size_t GetTotalSize() override { return base_->GetTotalSize(); }
  void ResetPartition(unsigned rank, unsigned nsplit) override;
  bool SetShuffleEpoch(unsigned epoch) override {
    epoch_.store(epoch, std::memory_order_relaxed);
    return true;
  }

 private:
  bool AdvanceSubPart();

  std::unique_ptr<InputSplit> base_;
  unsigned part_, nsplit_, num_shuffle_parts_;
  int seed_;
  std::atomic<unsigned> epoch_{0};  // see IndexedRecordIOSplit::epoch_
  std::vector<unsigned> order_;
  size_t cur_ = 0;
};

// ---------------------------------------------------------------------------
// Background prefetch wrapper (reference src/io/threaded_input_split.h):
// a PipelineIter of chunk cells produced by the wrapped source.
class PrefetchSplit : public InputSplit {
 public:
  // takes ownership of base; src must be the same object's chunk interface
  PrefetchSplit(InputSplit* base, RecordChunkSource* src,
                size_t capacity = 2);
  ~PrefetchSplit() override;

  void BeforeFirst() override;
  bool NextRecord(Blob* out) override;
  bool NextChunk(Blob* out) override;
  void HintChunkSize(size_t bytes) override { base_->HintChunkSize(bytes); }
  size_t GetTotalSize() override { return base_->GetTotalSize(); }
  void ResetPartition(unsigned rank, unsigned nsplit) override;
  bool SetShuffleEpoch(unsigned epoch) override {
    return base_->SetShuffleEpoch(epoch);
  }

 private:
  struct Cell {
    std::vector<char> data;
    size_t cursor = 0;
  };
  std::unique_ptr<InputSplit> base_;
  RecordChunkSource* src_;  // borrowed view of base_
  PipelineIter<Cell> pipe_;
  Cell* current_ = nullptr;
  bool started_ = false;
  void EnsureStarted();
};

}  // namespace dct

#endif  // DCT_INPUT_SPLIT_H_
