// Dense binary ingest: RecordIO records carrying row MATRICES in device
// layout — the zero-parse lane of the TPU pipeline.
//
// The CSR "rec" lane (parser.h RecParser) still pays deserialize + batcher
// accumulation + dense scatter per row. For dense datasets (HIGGS-like
// low-dimensional tabular data, the BASELINE.md north-star workload) the
// device batch is a [rows, F] matrix; storing exactly that on disk —
// bf16-capable, so the bytes on disk ARE the bytes the TPU wants — reduces
// ingest to record framing + one memcpy per batch row-range. This is the
// logical continuation of the reference's pre-baked .rec datasets
// (reference test/README.md ilsvrc12 val.rec), re-designed for the MXU's
// preferred layout instead of opaque image payloads.
//
// Record layout (little-endian on disk; written by
// dmlc_core_tpu/io/convert.py rows_to_dense_recordio):
//   [u32 'DRD1'][u32 flags: bit0 x is bf16, bit1 weights present]
//   [u32 n_rows][u32 n_features]
//   label   f32[n_rows]
//   weight  f32[n_rows]                  (only when flags bit1)
//   x       dtype[n_rows * n_features]   row-major
//
// Byte-range partitioning, shuffling, caching and prefetch all come from
// the RecordIO InputSplit machinery (input_split.h), so this lane keeps
// the full distributed-read contract.
#ifndef DCT_DENSE_REC_H_
#define DCT_DENSE_REC_H_

#include <cstdint>
#include <memory>
#include <string>

#include "input_split.h"
#include "serializer.h"

namespace dct {

constexpr uint32_t kDenseRecMagic = 0x44524431;  // 'DRD1'

// Decode helper with an explicit host_is_le switch so the big-endian
// branch is testable on an LE host (recordio.h LoadWordAs rationale; the
// shared 32-bit copy lives in recordio.h CopyWords32LE).
namespace denserec_detail {
void CopyX(void* dst, int out_dtype, const char* src, int disk_dtype,
           uint64_t count, bool host_is_le = serial::NativeIsLE());
}  // namespace denserec_detail

class DenseRecBatcher {
 public:
  // batch_rows must divide by num_shards (device-axis reshape contract,
  // same as PaddedBatcher).
  DenseRecBatcher(const std::string& uri, unsigned part, unsigned npart,
                  uint64_t batch_rows, uint32_t num_shards);

  // Static shape discovered from the first record (valid before any Fill):
  // x_dtype 0 = float32, 1 = bfloat16; has_weight 1 when records carry
  // per-row weights.
  void Meta(uint64_t* num_features, int* x_dtype, int* has_weight);

  // Fill one batch into caller buffers: x is [batch_rows, x_features] in
  // out_dtype (0 = float32, 1 = bfloat16; converted from the disk dtype
  // when they differ, memcpy when equal), label/weight are [batch_rows]
  // f32 (weight 1.0 when the file has none), nrows is [num_shards].
  // x_features must equal the file's feature width (checked — the fill
  // writes x_features elements per row, so a mismatch would corrupt the
  // caller's heap). The tail of a final partial batch is zero-padded with
  // weight 0. Returns the true row count (<= batch_rows); 0 at end.
  uint64_t Fill(void* x, int out_dtype, uint64_t x_features, float* label,
                float* weight, int32_t* nrows);

  // Fused shard-major fill: x exactly as Fill ([batch_rows, F] row-major
  // IS [num_shards, R, F], already shard-major); label/weight/nrows fused
  // into aux [num_shards, ka, R] int32 (label bits, weight bits, nrows
  // plane — ka must be 3, the dense rec format carries no qid). Returns
  // the true row count; 0 at end.
  uint64_t FillPacked(void* x, int out_dtype, uint64_t x_features,
                      int32_t* aux, int32_t ka, int32_t* nrows);

  void BeforeFirst();
  size_t BytesRead() const { return bytes_read_; }
  // Pin the shuffle permutation the next BeforeFirst samples (mid-epoch
  // resume; InputSplit::SetShuffleEpoch). False when nothing shuffles.
  bool SetShuffleEpoch(unsigned epoch) {
    return split_->SetShuffleEpoch(epoch);
  }

 private:
  bool AdvanceRecord();  // load + validate the next record; false at end
  void Peek();           // ensure the first record's header is parsed

  std::unique_ptr<InputSplit> split_;
  const uint64_t batch_rows_;
  const uint32_t num_shards_;

  // current record view (valid until the next NextRecord on split_)
  const char* labels_ = nullptr;
  const char* weights_ = nullptr;
  const char* x_ = nullptr;
  uint64_t rec_rows_ = 0;
  uint64_t row_in_rec_ = 0;

  // pinned static shape (first record wins; later mismatches throw)
  uint64_t num_features_ = 0;
  int x_dtype_ = -1;
  int has_weight_ = -1;

  bool eof_ = false;
  bool have_record_ = false;
  size_t bytes_read_ = 0;
};

}  // namespace dct

#endif  // DCT_DENSE_REC_H_
