// Local-filesystem durability layer: deterministic fault injection +
// syscall wrappers shared by every LOCAL write path (the network twin is
// retry.h's DMLC_IO_FAULT_PLAN).
//
// The reference's local I/O story (src/io/local_filesys.cc) assumes the
// disk is reliable: EIO/ENOSPC/failed fsync either fire a hard CHECK or
// are silently ignored (fread's error flag was never looked at — a mid-
// file EIO read as EOF, i.e. silent truncation). This layer gives the
// local plane the same two properties the remote plane got in PR 2:
//
//   1. Every failure is OBSERVABLE and STRUCTURED: the wrappers keep the
//      raw syscall contract (-1 + errno / nullptr / MAP_FAILED) so call
//      sites keep one error path, and the throwing helpers raise FsError
//      (op + errno + path) instead of a bare CHECK string.
//   2. Every failure is INJECTABLE below every mock: DMLC_FS_FAULT_PLAN /
//      dct_fs_set_fault_plan installs a deterministic plan evaluated
//      inside the wrappers themselves, so the chaos suites prove the real
//      degradation machinery (quarantine, text-lane stand-down, atomic
//      checkpoint cleanup), not a test harness.
//
// Plan grammar (';'-separated rules, checked parse — a typo errors, the
// retry.h CheckedEnvInt rule):
//
//   <op>:fault=<kind>,(every=N | p=<prob>)
//
//   op:    open | read | write | fsync | rename | mmap
//   kind:  eio          (fail with EIO — any op)
//          enospc       (fail with ENOSPC — open/write/fsync)
//          short_write  (write REALLY writes half, then fails ENOSPC —
//                        the torn-bytes disk-full artifact; write only)
//          fsync_fail   (fsync returns EIO — fsync only)
//          torn_rename  (destination receives a TRUNCATED half-copy, the
//                        source is gone, the call fails EIO — the crash-
//                        mid-publish artifact a non-atomic filesystem
//                        could expose; rename only)
//
// Selectors mirror retry.cc: every=N keeps a per-rule atomic counter of
// the ops it OBSERVES (ops of its own kind only) and fires on every Nth;
// p= draws from one RNG seeded by DMLC_FS_FAULT_SEED (default 1) so runs
// replay. Each firing bumps fs_fault_injected_total{op=} (telemetry.h).
#ifndef DCT_FS_FAULT_H_
#define DCT_FS_FAULT_H_

#include <cstdio>
#include <string>

#include "base.h"

namespace dct {
namespace fsio {

enum class FsOp { kOpen = 0, kRead, kWrite, kFsync, kRename, kMmap };
const char* FsOpName(FsOp op);

// Structured local-filesystem error: what failed, on which path, with
// which errno — so a full disk surfaces as "write failed (No space left
// on device)" instead of a context-free check string.
class FsError : public Error {
 public:
  FsError(FsOp op, const std::string& path, int err);
  FsOp op() const { return op_; }
  int error_number() const { return err_; }

 private:
  FsOp op_;
  int err_;
};

// Install/replace the plan ("" clears; explicit set — even clear — beats
// the env, same rule as io::SetFaultPlan). Throws Error on bad grammar or
// an op/fault combination that cannot happen (read:fault=torn_rename).
void SetFsFaultPlan(const std::string& plan);

// Lazily installs DMLC_FS_FAULT_PLAN from the env on first wrapper use.
void EnsureFsFaultPlanFromEnv();

// ------------------------------------------------------------- wrappers --
// Syscall-compatible: injected faults return the call's failure value
// with errno set, exactly like the real failure would, so every call
// site keeps ONE error path. The short_write/torn_rename kinds perform
// their real partial side effect first.
int Open(const char* path, int flags, unsigned mode = 0644);
long Write(int fd, const void* buf, size_t n);                // ssize_t
long Pwrite(int fd, const void* buf, size_t n, long long off);
int Fsync(int fd);
int Rename(const char* from, const char* to);
void* Mmap(size_t len, int prot, int flags, int fd);          // MAP_FAILED

// Write all of `data` through Write(); throws FsError naming `path` on
// any failure (EINTR retried). The shared loop the shard cache and any
// future local writer ride, so the partial-write handling cannot drift.
void WriteAllFd(int fd, const void* data, size_t size,
                const std::string& path);

// Best-effort fsync of the directory containing `path` so a rename into
// it survives a crash (some filesystems reject directory fsync; that is
// not an error). The one deliberate unchecked-fsync site.
void FsyncDirOf(const std::string& path);

// Read a whole local file; false on ANY failure (absent, injected or
// real open/read fault) — the validation-miss shape: replay validators
// must fall back to the text lane, never throw.
bool ReadFileToString(const std::string& path, std::string* out);

// ------------------------------------------------------ stdio helpers ----
// For FILE*-backed streams (filesys.cc StdFileStream), where the failure
// contract is throwing: evaluate the plan for `op` and throw FsError on a
// fired simple fault (eio/enospc/fsync_fail). short_write against a
// FILE* is handled by InjectStdioWrite, which really fwrites half before
// throwing. Call BEFORE the real stdio op.
void InjectThrow(FsOp op, const std::string& path);
void InjectStdioWrite(std::FILE* fp, const void* p, size_t n,
                      const std::string& path);

// True (with errno set) when an injected open fault fired — lets
// allow_null open sites treat injection exactly like a failed fopen.
bool InjectOpenFail(const std::string& path);

}  // namespace fsio
}  // namespace dct

#endif  // DCT_FS_FAULT_H_
