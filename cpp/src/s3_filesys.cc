// S3 filesystem implementation (see s3_filesys.h for provenance).
#include "s3_filesys.h"

#include <unistd.h>

#include <algorithm>
#include <cstdlib>
#include <ctime>
#include <map>
#include <memory>
#include <sstream>

#include "http.h"
#include "http_stream.h"
#include "listing.h"
#include "range_reader.h"
#include "sha256.h"

namespace dct {
namespace s3 {

std::string UriEncode(const std::string& s, bool keep_slash) {
  static const char* hex = "0123456789ABCDEF";
  std::string out;
  for (unsigned char c : s) {
    if (isalnum(c) || c == '-' || c == '_' || c == '.' || c == '~' ||
        (keep_slash && c == '/')) {
      out.push_back(static_cast<char>(c));
    } else {
      out.push_back('%');
      out.push_back(hex[c >> 4]);
      out.push_back(hex[c & 0xF]);
    }
  }
  return out;
}

std::string AmzDateNow() {
  std::time_t now = std::time(nullptr);
  std::tm tm_utc;
  gmtime_r(&now, &tm_utc);
  char buf[32];
  std::strftime(buf, sizeof(buf), "%Y%m%dT%H%M%SZ", &tm_utc);
  return buf;
}

// AWS Signature V4 (reference s3_filesys.cc:231-319; algorithm per the
// public AWS sigv4 spec).
std::string BuildAuthorization(
    const S3Config& cfg, const SignedRequest& req,
    std::map<std::string, std::string>* extra_headers) {
  std::string date = req.amz_date.substr(0, 8);

  // canonical query: sorted, uri-encoded keys and values
  std::vector<std::pair<std::string, std::string>> q;
  for (const auto& kv : req.query) {
    q.emplace_back(UriEncode(kv.first, false), UriEncode(kv.second, false));
  }
  std::sort(q.begin(), q.end());
  std::string canonical_query;
  for (size_t i = 0; i < q.size(); ++i) {
    if (i) canonical_query += '&';
    canonical_query += q[i].first + "=" + q[i].second;
  }

  // canonical headers: host, x-amz-content-sha256, x-amz-date (+ token)
  std::map<std::string, std::string> signed_hdrs = {
      {"host", req.host_header},
      {"x-amz-content-sha256", req.payload_hash},
      {"x-amz-date", req.amz_date},
  };
  if (!cfg.session_token.empty()) {
    signed_hdrs["x-amz-security-token"] = cfg.session_token;
  }
  std::string canonical_headers, signed_header_names;
  for (const auto& kv : signed_hdrs) {
    canonical_headers += kv.first + ":" + kv.second + "\n";
    if (!signed_header_names.empty()) signed_header_names += ';';
    signed_header_names += kv.first;
  }

  std::string canonical_request =
      req.method + "\n" + UriEncode(req.canonical_path, true) + "\n" +
      canonical_query + "\n" + canonical_headers + "\n" +
      signed_header_names + "\n" + req.payload_hash;

  std::string scope = date + "/" + cfg.region + "/s3/aws4_request";
  std::string string_to_sign = "AWS4-HMAC-SHA256\n" + req.amz_date + "\n" +
                               scope + "\n" +
                               crypto::Sha256Hex(canonical_request);

  std::string k_date = crypto::HmacSha256("AWS4" + cfg.secret_key, date);
  std::string k_region = crypto::HmacSha256(k_date, cfg.region);
  std::string k_service = crypto::HmacSha256(k_region, "s3");
  std::string k_signing = crypto::HmacSha256(k_service, "aws4_request");
  std::string signature =
      crypto::HexEncode(crypto::HmacSha256(k_signing, string_to_sign));

  for (const auto& kv : signed_hdrs) {
    if (kv.first != "host") (*extra_headers)[kv.first] = kv.second;
  }
  return "AWS4-HMAC-SHA256 Credential=" + cfg.access_key + "/" + scope +
         ", SignedHeaders=" + signed_header_names +
         ", Signature=" + signature;
}

std::string XmlUnescape(const std::string& s) {
  std::string out;
  out.reserve(s.size());
  size_t i = 0;
  while (i < s.size()) {
    if (s[i] != '&') {
      out.push_back(s[i++]);
      continue;
    }
    size_t semi = s.find(';', i);
    if (semi == std::string::npos || semi - i > 10) {
      out.push_back(s[i++]);
      continue;
    }
    std::string ent = s.substr(i + 1, semi - i - 1);
    if (ent == "amp") out.push_back('&');
    else if (ent == "lt") out.push_back('<');
    else if (ent == "gt") out.push_back('>');
    else if (ent == "quot") out.push_back('"');
    else if (ent == "apos") out.push_back('\'');
    else if (ent.size() > 1 && ent[0] == '#') {
      char* end = nullptr;
      long code = ent[1] == 'x' || ent[1] == 'X'
                      ? std::strtol(ent.c_str() + 2, &end, 16)
                      : std::strtol(ent.c_str() + 1, &end, 10);
      if (end == nullptr || *end != '\0' || code <= 0 || code > 0x10FFFF ||
          (code >= 0xD800 && code <= 0xDFFF)) {  // UTF-16 surrogates
        out.append(s, i, semi - i + 1);  // malformed/out-of-range: keep literal
      } else if (code < 128) {
        out.push_back(static_cast<char>(code));
      } else if (code < 0x800) {
        out.push_back(static_cast<char>(0xC0 | (code >> 6)));
        out.push_back(static_cast<char>(0x80 | (code & 0x3F)));
      } else if (code < 0x10000) {
        out.push_back(static_cast<char>(0xE0 | (code >> 12)));
        out.push_back(static_cast<char>(0x80 | ((code >> 6) & 0x3F)));
        out.push_back(static_cast<char>(0x80 | (code & 0x3F)));
      } else {  // supplementary plane needs 4 bytes
        out.push_back(static_cast<char>(0xF0 | (code >> 18)));
        out.push_back(static_cast<char>(0x80 | ((code >> 12) & 0x3F)));
        out.push_back(static_cast<char>(0x80 | ((code >> 6) & 0x3F)));
        out.push_back(static_cast<char>(0x80 | (code & 0x3F)));
      }
    } else {
      out.append(s, i, semi - i + 1);  // unknown entity: keep literally
    }
    i = semi + 1;
  }
  return out;
}

bool XmlNextField(const std::string& xml, size_t* pos, const std::string& tag,
                  std::string* out) {
  std::string open = "<" + tag + ">";
  std::string close = "</" + tag + ">";
  size_t b = xml.find(open, *pos);
  if (b == std::string::npos) return false;
  b += open.size();
  size_t e = xml.find(close, b);
  if (e == std::string::npos) return false;
  *out = xml.substr(b, e - b);
  *pos = e + close.size();
  return true;
}

namespace {

constexpr const char* kUnsigned = "UNSIGNED-PAYLOAD";

struct Target {
  std::string host;        // connect + Host header
  int port;
  std::string base_path;   // "" or "/<bucket>" for path-style
};

Target ResolveTarget(const S3Config& cfg, const std::string& bucket) {
  Target t;
  if (!cfg.endpoint_host.empty()) {
    t.host = cfg.endpoint_host;
    t.port = cfg.endpoint_port;
    t.base_path = cfg.path_style ? "/" + bucket : "";
    if (!cfg.path_style) t.host = bucket + "." + t.host;
  } else {
    // real AWS is TLS-only: default to https (reached via DCT_TLS_PROXY)
    t.host = bucket + ".s3." + cfg.region + ".amazonaws.com";
    t.port = cfg.scheme == "https" ? 443 : 80;
    t.base_path = "";
  }
  return t;
}

// Socket route for a resolved target (via the TLS helper for https).
HttpRoute RouteOf(const S3Config& cfg, const Target& t) {
  return ResolveHttpRoute(cfg.scheme, t.host, t.port, "s3");
}

std::map<std::string, std::string> SignedHeaders(
    const S3Config& cfg, const Target& t, const std::string& method,
    const std::string& path,
    const std::vector<std::pair<std::string, std::string>>& query,
    const std::string& payload_hash) {
  s3::SignedRequest req;
  req.method = method;
  req.canonical_path = path;
  req.query = query;
  // MUST match the wire Host (ResolveHttpRoute) or SIG4 verification fails
  req.host_header = DefaultHostHeader(cfg.scheme, t.host, t.port);
  req.payload_hash = payload_hash;
  req.amz_date = s3::AmzDateNow();
  std::map<std::string, std::string> headers;
  headers["Authorization"] = s3::BuildAuthorization(cfg, req, &headers);
  headers["Host"] = req.host_header;
  return headers;
}

std::string QueryString(
    const std::vector<std::pair<std::string, std::string>>& query) {
  std::string out;
  for (size_t i = 0; i < query.size(); ++i) {
    out += i == 0 ? "?" : "&";
    out += s3::UriEncode(query[i].first, false) + "=" +
           s3::UriEncode(query[i].second, false);
  }
  return out;
}

// Split URI -> (bucket, object key with leading '/')
void SplitBucketKey(const URI& uri, std::string* bucket, std::string* key) {
  *bucket = uri.host;
  DCT_CHECK(!bucket->empty()) << "s3 uri missing bucket: " << uri.Str();
  *key = uri.path.empty() ? "/" : uri.path;
}

// ---------------------------------------------------------------- reading --
class S3ReadStream : public RetryingHttpReadStream {
 public:
  S3ReadStream(const S3Config& cfg, const URI& uri, size_t file_size,
               const io::RetryPolicy& policy, int timeout_ms)
      : RetryingHttpReadStream("s3", file_size, policy, timeout_ms),
        cfg_(cfg), uri_(uri) {
    SplitBucketKey(uri, &bucket_, &key_);
    target_ = ResolveTarget(cfg_, bucket_);
  }

 private:
  void Connect() override {
    std::string path = target_.base_path + key_;
    auto headers = SignedHeaders(cfg_, target_, "GET", path, {}, kUnsigned);
    headers["Range"] = "bytes=" + std::to_string(pos_) + "-";
    conn_.reset(new HttpConnection(RouteOf(cfg_, target_)));
    // the wire path must be the same percent-encoded form that was signed
    conn_->SendRequest("GET", s3::UriEncode(path, true), headers, "");
    HttpResponse head;
    conn_->ReadResponseHead(&head);
    if (head.status != 200 && head.status != 206) {
      conn_->ReadFullBody(&head);
      int status = head.status;
      conn_.reset();
      throw HttpStatusError("s3 GET " + uri_.Str() +
                                " failed with status " +
                                std::to_string(status) + ": " + head.body,
                            status);
    }
    if (head.status == 206) {
      // misaligned Content-Range must retry, never splice silently
      CheckContentRangeStart(head, pos_, "s3", uri_.Str());
    }
  }

  S3Config cfg_;
  URI uri_;
  std::string bucket_, key_;
  Target target_;
};

// One idempotent bounded ranged GET per call (range_reader.h): each fetch
// signs its own request (fresh SIG4 headers + fresh connection), asks for
// `Range: bytes=a-b`, and verifies the 206's Content-Range offset. A 200
// means the endpoint ignored Range — degrade to the sequential lane.
class S3RangeFetcher : public io::RangeFetcher {
 public:
  S3RangeFetcher(const S3Config& cfg, const URI& uri) : cfg_(cfg), uri_(uri) {
    SplitBucketKey(uri, &bucket_, &key_);
    target_ = ResolveTarget(cfg_, bucket_);
  }

  io::FetchStatus Fetch(size_t off, size_t len, char* buf,
                        size_t* progress) override {
    std::string path = target_.base_path + key_;
    auto headers = SignedHeaders(cfg_, target_, "GET", path, {}, kUnsigned);
    headers["Range"] = RangeHeader(off, len);
    HttpConnection conn(RouteOf(cfg_, target_));
    conn.SendRequest("GET", s3::UriEncode(path, true), headers, "");
    HttpResponse head;
    conn.ReadResponseHead(&head);
    if (head.status == 200) return io::FetchStatus::kDegraded;
    if (head.status != 206) {
      conn.ReadFullBody(&head);
      throw HttpStatusError("s3 ranged GET " + uri_.Str() +
                                " failed with status " +
                                std::to_string(head.status) + ": " +
                                head.body,
                            head.status);
    }
    CheckContentRangeStart(head, off, "s3", uri_.Str());
    ReadRangeBody(&conn, buf, len, "s3", uri_.Str(), progress);
    return io::FetchStatus::kOk;
  }

 private:
  S3Config cfg_;
  URI uri_;
  std::string bucket_, key_;
  Target target_;
};

// ---------------------------------------------------------------- writing --
class S3WriteStream : public Stream {
 public:
  static constexpr size_t kPartSize = 5 << 20;  // S3 minimum part size

  S3WriteStream(const S3Config& cfg, const URI& uri) : cfg_(cfg), uri_(uri) {
    SplitBucketKey(uri, &bucket_, &key_);
    target_ = ResolveTarget(cfg_, bucket_);
  }

  ~S3WriteStream() override {
    try {
      Finish();
    } catch (...) {
      // destructor must not throw; errors surface on explicit Finish
    }
  }

  size_t Read(void*, size_t) override {
    throw Error("S3WriteStream is write-only");
  }

  size_t Write(const void* ptr, size_t size) override {
    buffer_.append(static_cast<const char*>(ptr), size);
    while (buffer_.size() >= kPartSize) {
      UploadBufferedPart(kPartSize);
    }
    return size;
  }

  void Finish() override {
    if (finished_) return;
    finished_ = true;
    if (upload_id_.empty()) {
      // small object: single PUT (reference small-file path)
      std::string path = target_.base_path + key_;
      auto headers = SignedHeaders(cfg_, target_, "PUT", path, {},
                                   crypto::Sha256Hex(buffer_));
      HttpResponse resp = DoRequest("PUT", path, {}, headers, buffer_);
      DCT_CHECK(resp.status == 200) << "s3 PUT failed: " << resp.status
                                    << " " << resp.body;
      return;
    }
    if (!buffer_.empty()) UploadBufferedPart(buffer_.size());
    // CompleteMultipartUpload (reference s3_filesys.cc:978-1016)
    std::ostringstream xml;
    xml << "<CompleteMultipartUpload>";
    for (size_t i = 0; i < etags_.size(); ++i) {
      xml << "<Part><PartNumber>" << i + 1 << "</PartNumber><ETag>"
          << etags_[i] << "</ETag></Part>";
    }
    xml << "</CompleteMultipartUpload>";
    std::string body = xml.str();
    std::string path = target_.base_path + key_;
    std::vector<std::pair<std::string, std::string>> q = {
        {"uploadId", upload_id_}};
    auto headers =
        SignedHeaders(cfg_, target_, "POST", path, q, crypto::Sha256Hex(body));
    HttpResponse resp = DoRequest("POST", path, q, headers, body);
    DCT_CHECK(resp.status == 200)
        << "s3 CompleteMultipartUpload failed: " << resp.status << " "
        << resp.body;
  }

 private:
  HttpResponse DoRequest(
      const std::string& method, const std::string& path,
      const std::vector<std::pair<std::string, std::string>>& query,
      std::map<std::string, std::string> headers, const std::string& body) {
    // write-side retry: 5xx/429 and transport drops are retried like the
    // read path; request signing is deterministic, so a resend is
    // byte-identical and parts are idempotent by partNumber
    return RetryingHttpRequest(
        RouteOf(cfg_, target_), method,
        s3::UriEncode(path, true) + QueryString(query), headers, body,
        cfg_.retry);
  }

  void StartMultipart() {
    std::string path = target_.base_path + key_;
    std::vector<std::pair<std::string, std::string>> q = {{"uploads", ""}};
    auto headers =
        SignedHeaders(cfg_, target_, "POST", path, q, crypto::Sha256Hex(""));
    HttpResponse resp = DoRequest("POST", path, q, headers, "");
    DCT_CHECK(resp.status == 200)
        << "s3 CreateMultipartUpload failed: " << resp.status << " "
        << resp.body;
    size_t pos = 0;
    DCT_CHECK(s3::XmlNextField(resp.body, &pos, "UploadId", &upload_id_))
        << "no UploadId in response: " << resp.body;
  }

  void UploadBufferedPart(size_t size) {
    if (upload_id_.empty()) StartMultipart();
    std::string part = buffer_.substr(0, size);
    buffer_.erase(0, size);
    int part_number = static_cast<int>(etags_.size()) + 1;
    std::string path = target_.base_path + key_;
    std::vector<std::pair<std::string, std::string>> q = {
        {"partNumber", std::to_string(part_number)},
        {"uploadId", upload_id_}};
    auto headers =
        SignedHeaders(cfg_, target_, "PUT", path, q, crypto::Sha256Hex(part));
    HttpResponse resp = DoRequest("PUT", path, q, headers, part);
    DCT_CHECK(resp.status == 200) << "s3 UploadPart failed: " << resp.status
                                  << " " << resp.body;
    auto it = resp.headers.find("etag");
    DCT_CHECK(it != resp.headers.end()) << "UploadPart response missing ETag";
    etags_.push_back(it->second);
  }

  S3Config cfg_;
  URI uri_;
  std::string bucket_, key_;
  Target target_;
  std::string buffer_;
  std::string upload_id_;
  std::vector<std::string> etags_;
  bool finished_ = false;
};

}  // namespace

}  // namespace s3

// ---------------------------------------------------------------- listing --
S3Config S3Config::FromEnv() {
  auto get = [](const char* a, const char* b) -> std::string {
    const char* v = std::getenv(a);
    if (v == nullptr || *v == '\0') v = std::getenv(b);
    return v == nullptr ? "" : v;
  };
  S3Config cfg;
  cfg.access_key = get("S3_ACCESS_KEY_ID", "AWS_ACCESS_KEY_ID");
  cfg.secret_key = get("S3_SECRET_ACCESS_KEY", "AWS_SECRET_ACCESS_KEY");
  cfg.session_token = get("S3_SESSION_TOKEN", "AWS_SESSION_TOKEN");
  std::string region = get("S3_REGION", "AWS_REGION");
  if (!region.empty()) cfg.region = region;
  std::string endpoint = get("S3_ENDPOINT", "AWS_ENDPOINT");
  if (!endpoint.empty()) {
    // scheme picks the transport: http direct, https via the TLS helper
    std::string scheme = StripUrlScheme(&endpoint);
    if (!scheme.empty()) cfg.scheme = scheme;
    if (cfg.scheme == "https") cfg.endpoint_port = 443;
    SplitHostPort(endpoint, &cfg.endpoint_host, &cfg.endpoint_port,
                  cfg.endpoint_port);
    cfg.path_style = true;  // custom endpoints default to path-style
  } else {
    cfg.scheme = "https";  // real AWS endpoints are TLS-only
  }
  // checked parse: a typo'd S3_PATH_STYLE raises instead of silently
  // selecting virtual-hosted addressing
  cfg.path_style =
      io::CheckedEnvInt("S3_PATH_STYLE", cfg.path_style ? 1 : 0, 0, 1) != 0;
  // fault-tolerance knobs: DMLC_IO_* layered under the legacy S3_* names,
  // all through the checked parser (a typo'd S3_MAX_RETRY used to atoi()
  // to a silent 0-retry config; now it throws)
  cfg.retry = io::RetryPolicy::FromEnv("S3");
  return cfg;
}

S3FileSystem* S3FileSystem::GetInstance() {
  static S3FileSystem inst(S3Config::FromEnv());
  return &inst;
}

void S3FileSystem::ListDirectory(const URI& path, std::vector<FileInfo>* out) {
  std::string bucket, key;
  s3::SplitBucketKey(path, &bucket, &key);
  s3::Target t = s3::ResolveTarget(config_, bucket);
  std::string prefix = key.substr(1);  // drop leading '/'
  if (!prefix.empty() && prefix.back() != '/') prefix += '/';
  std::string marker;
  while (true) {
    std::vector<std::pair<std::string, std::string>> q = {
        {"delimiter", "/"}, {"prefix", prefix}};
    if (!marker.empty()) q.emplace_back("marker", marker);
    std::sort(q.begin(), q.end());
    std::string base = t.base_path.empty() ? "/" : t.base_path;
    auto headers = s3::SignedHeaders(config_, t, "GET", base, q,
                                     crypto::Sha256Hex(""));
    // metadata requests ride the same resilience policy as data reads
    // (idempotent GET: RetryingHttpRequest)
    HttpResponse resp =
        RetryingHttpRequest(s3::RouteOf(config_, t), "GET",
                            s3::UriEncode(base, true) + s3::QueryString(q),
                            headers, "", config_.retry);
    DCT_CHECK(resp.status == 200)
        << "s3 ListObjects failed: " << resp.status << " " << resp.body;
    // scan <Contents><Key>..</Key><Size>..</Size></Contents> and
    // <CommonPrefixes><Prefix>..</Prefix>
    size_t pos = 0;
    std::string chunk;
    while (s3::XmlNextField(resp.body, &pos, "Contents", &chunk)) {
      size_t cp = 0;
      std::string k, sz;
      if (!s3::XmlNextField(chunk, &cp, "Key", &k)) continue;
      s3::XmlNextField(chunk, &cp, "Size", &sz);
      k = s3::XmlUnescape(k);
      if (k == prefix) continue;  // the directory placeholder itself
      FileInfo info;
      info.path = URI("s3://" + bucket + "/" + k);
      // env-ok: service XML listing size, not a config knob; an absent
      // field deliberately degrades to size 0
      info.size = static_cast<size_t>(std::atoll(sz.c_str()));
      info.type = FileType::kFile;
      out->push_back(info);
      marker = k;
    }
    pos = 0;
    while (s3::XmlNextField(resp.body, &pos, "CommonPrefixes", &chunk)) {
      size_t cp = 0;
      std::string p;
      if (!s3::XmlNextField(chunk, &cp, "Prefix", &p)) continue;
      FileInfo info;
      std::string dir = s3::XmlUnescape(p);
      if (!dir.empty() && dir.back() == '/') dir.pop_back();
      info.path = URI("s3://" + bucket + "/" + dir);
      info.size = 0;
      info.type = FileType::kDirectory;
      out->push_back(info);
    }
    pos = 0;
    while (s3::XmlNextField(resp.body, &pos, "CommonPrefixes", &chunk)) {
      size_t cp = 0;
      std::string p;
      if (s3::XmlNextField(chunk, &cp, "Prefix", &p) &&
          s3::XmlUnescape(p) > marker) {
        marker = s3::XmlUnescape(p);  // prefixes also advance the marker
      }
    }
    std::string next_marker;
    pos = 0;
    if (s3::XmlNextField(resp.body, &pos, "NextMarker", &next_marker) &&
        !next_marker.empty()) {
      marker = s3::XmlUnescape(next_marker);  // authoritative when present
    }
    std::string truncated;
    pos = 0;
    s3::XmlNextField(resp.body, &pos, "IsTruncated", &truncated);
    if (truncated != "true") break;
    DCT_CHECK(!marker.empty())
        << "s3 ListObjects: truncated page without any marker";
  }
}

FileInfo S3FileSystem::GetPathInfo(const URI& path) {
  return PathInfoUnderPolicy(path, config_.retry);
}

FileInfo S3FileSystem::PathInfoUnderPolicy(const URI& path,
                                           const io::RetryPolicy& policy) {
  // TryGetPathInfo via ListObjects with the exact key as prefix
  // (reference s3_filesys.cc:1221-1239); file-vs-directory resolution is
  // the shared ProbePathInfo (listing.h)
  std::string bucket, key;
  s3::SplitBucketKey(path, &bucket, &key);
  s3::Target t = s3::ResolveTarget(config_, bucket);
  std::string base = t.base_path.empty() ? "/" : t.base_path;
  auto list_page = [&](const std::string& pfx) {
    std::vector<std::pair<std::string, std::string>> q = {
        {"delimiter", "/"}, {"prefix", pfx}};
    auto headers =
        s3::SignedHeaders(config_, t, "GET", base, q, crypto::Sha256Hex(""));
    HttpResponse resp =
        RetryingHttpRequest(s3::RouteOf(config_, t), "GET",
                            s3::UriEncode(base, true) + s3::QueryString(q),
                            headers, "", policy);
    DCT_CHECK(resp.status == 200)
        << "s3 ListObjects failed: " << resp.status << " " << resp.body;
    ListedPage page;
    size_t pos = 0;
    std::string chunk;
    while (s3::XmlNextField(resp.body, &pos, "Contents", &chunk)) {
      size_t cp = 0;
      std::string k, sz;
      if (!s3::XmlNextField(chunk, &cp, "Key", &k)) continue;
      s3::XmlNextField(chunk, &cp, "Size", &sz);
      // env-ok: service XML listing size, not a config knob
      const size_t obj_size = static_cast<size_t>(std::atoll(sz.c_str()));
      page.objects.push_back({s3::XmlUnescape(k), obj_size});
    }
    pos = 0;
    while (s3::XmlNextField(resp.body, &pos, "CommonPrefixes", &chunk)) {
      size_t cp = 0;
      std::string p;
      if (s3::XmlNextField(chunk, &cp, "Prefix", &p)) {
        page.prefixes.push_back(s3::XmlUnescape(p));
      }
    }
    return page;
  };
  return ProbePathInfo(path, key.substr(1), list_page, "s3");
}

SeekStream* S3FileSystem::OpenForRead(const URI& path, bool allow_null) {
  // per-open resilience overrides ride `?io_*=` query args (retry.h); the
  // stripped path is the real object key
  URI clean = path;
  io::RetryPolicy policy = config_.retry;
  io::RangeConfig rcfg = io::RangeConfig::FromEnv();
  int timeout_ms = 0;
  io::ExtractUriIoArgs(&clean.path, &policy, &timeout_ms, &rcfg);
  // the per-open socket-timeout override must bind the open-time metadata
  // probe too, or a stalled endpoint holds `open` for the global 60 s
  // despite the URI asking for less
  io::ScopedIoTimeout scoped_timeout(timeout_ms);
  try {
    FileInfo info = PathInfoUnderPolicy(clean, policy);
    DCT_CHECK(info.type == FileType::kFile)
        << "cannot open s3 directory for read: " << clean.Str();
    const S3Config cfg = config_;
    const size_t size = info.size;
    return io::NewRangedOrSequential(
        "s3", size, std::make_unique<s3::S3RangeFetcher>(cfg, clean),
        [cfg, clean, size, policy, timeout_ms]() -> SeekStream* {
          return new s3::S3ReadStream(cfg, clean, size, policy, timeout_ms);
        },
        rcfg, policy, timeout_ms);
  } catch (const Error&) {
    if (allow_null) return nullptr;
    throw;
  }
}

Stream* S3FileSystem::Open(const URI& path, const char* mode,
                           bool allow_null) {
  std::string m = mode;
  if (m.find('r') != std::string::npos) return OpenForRead(path, allow_null);
  DCT_CHECK(m.find('w') != std::string::npos)
      << "s3 supports modes r|w, got " << mode;
  return new s3::S3WriteStream(config_, path);
}

namespace {
// register s3:// at load time (reference src/io.cc:53-59 dispatch)
struct S3Registrar {
  S3Registrar() {
    FileSystem::RegisterScheme(
        "s3", [](const URI&) -> FileSystem* {
          return S3FileSystem::GetInstance();
        });
  }
} s3_registrar;
}  // namespace

}  // namespace dct
