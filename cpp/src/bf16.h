// bfloat16 <-> float32 storage conversion (the XLA/MXU convention):
// round-to-nearest-even on narrowing, NaN quieted with sign preserved.
// Shared by the padded batcher's dense fill (batcher.cc) and the dense
// RecordIO ingest lane (dense_rec.cc).
#ifndef DCT_BF16_H_
#define DCT_BF16_H_

#include <cstdint>
#include <cstring>

namespace dct {

inline uint16_t Bf16FromFloat(float f) {
  uint32_t u;
  std::memcpy(&u, &f, sizeof(u));
  if ((u & 0x7fffffffu) > 0x7f800000u) {
    return static_cast<uint16_t>((u >> 16) | 0x0040u);
  }
  u += 0x7fffu + ((u >> 16) & 1u);
  return static_cast<uint16_t>(u >> 16);
}

inline float Bf16ToFloat(uint16_t b) {
  uint32_t u = static_cast<uint32_t>(b) << 16;
  float f;
  std::memcpy(&f, &u, sizeof(f));
  return f;
}

}  // namespace dct

#endif  // DCT_BF16_H_
