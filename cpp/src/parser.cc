// Parser implementations. Parse-rule provenance is cited per function; the
// threading/fan-out structure is original (see parser.h).
#include "parser.h"

#include <algorithm>
#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <exception>
#include <limits>
#include <thread>

#include "fs_fault.h"
#include "numparse.h"
#include "parameter.h"
#include "recordio.h"
#include "registry.h"
#include "shard_cache.h"
#include "telemetry.h"

namespace dct {

namespace {

// Process-wide pipeline telemetry (telemetry.h): totals across every
// PipelinedParser instance plus per-stage latency histograms. The
// per-handle ParsePipelineStats struct stays the per-parser view; these
// are what dct_telemetry_snapshot / /metrics serve. Pointers resolved
// once (registry lookup), then every touch is one relaxed atomic op.
struct PipeTelemetry {
  telemetry::Counter* chunks_read;
  telemetry::Counter* blocks_delivered;
  telemetry::Counter* reader_waits;
  telemetry::Counter* worker_waits;
  telemetry::Counter* consumer_waits;
  telemetry::Hist* fill_us;             // ReadChunk (source -> owned bytes)
  telemetry::Hist* scan_us;             // TileCuts slice pre-tiling
  telemetry::Hist* parse_us;            // one worker slice decode
  telemetry::Hist* reassemble_wait_us;  // consumer head-of-line wait
};

const PipeTelemetry& PipeTel() {
  static const PipeTelemetry t = {
      telemetry::GetCounter("parse_chunks_read_total"),
      telemetry::GetCounter("parse_blocks_delivered_total"),
      telemetry::GetCounter("parse_reader_waits_total"),
      telemetry::GetCounter("parse_worker_waits_total"),
      telemetry::GetCounter("parse_consumer_waits_total"),
      telemetry::GetHist("parse_stage_fill_us"),
      telemetry::GetHist("parse_stage_scan_us"),
      telemetry::GetHist("parse_stage_parse_us"),
      telemetry::GetHist("parse_stage_reassemble_wait_us"),
  };
  return t;
}

// Skip blanks; a '#' means the rest of the line is a comment
// (reference libsvm_parser.h IgnoreCommentAndBlank).
inline const char* SkipBlankOrComment(const char* p, const char* end) {
  while (p != end && IsBlankChar(*p)) ++p;
  if (p != end && *p == '#') return end;
  return p;
}

// Advance past one line; *line_end receives the end of the current line
// (excluding the terminator); returns the start of the next line. Both
// '\n' and bare '\r' terminate a line (reference text_parser.h semantics);
// memchr keeps the scans vectorized. "\r\n" and blank lines yield empty
// lines which every parser skips.
inline const char* LineSpan(const char* p, const char* end,
                            const char** line_end) {
  const char* nl =
      static_cast<const char*>(memchr(p, '\n', static_cast<size_t>(end - p)));
  const char* limit = nl == nullptr ? end : nl;
  const char* cr =
      static_cast<const char*>(memchr(p, '\r', static_cast<size_t>(limit - p)));
  const char* term = cr == nullptr ? limit : cr;
  *line_end = term;
  return term == end ? end : term + 1;
}

inline const char* SkipUTF8BOM(const char* p, const char* end) {
  if (end - p >= 3 && static_cast<unsigned char>(p[0]) == 0xEF &&
      static_cast<unsigned char>(p[1]) == 0xBB &&
      static_cast<unsigned char>(p[2]) == 0xBF) {
    return p + 3;
  }
  return p;
}

int DefaultThreads(int requested) {
  // The reference caps workers at max(nprocs/2 - 4, 1)
  // (text_parser.h:28) — a fudge tuned for 2010s many-core Xeons that
  // throttles to 1 thread on the small hosts fronting TPU slices. Here the
  // default uses every available core (the parse workers are the ingest
  // bottleneck and XLA compute runs on the TPU, not these cores), and an
  // explicit request is honored up to a 4x oversubscription bound so
  // I/O-stalled workers can still overlap.
  int hw = std::max(static_cast<int>(std::thread::hardware_concurrency()), 1);
  if (requested <= 0) return hw;
  return std::min(requested, std::max(4 * hw, 8));
}

std::string GetArg(const std::map<std::string, std::string>& args,
                   const std::string& key, const std::string& dflt) {
  auto it = args.find(key);
  return it == args.end() ? dflt : it->second;
}

}  // namespace

// -- parser parameters (reflection structs, reference LibSVMParserParam
//    libsvm_parser.h:24-39 / CSVParserParam csv_parser.h:24-55 /
//    LibFMParserParam libfm_parser.h:24-40) --------------------------------
struct LibSVMParserParam : public Parameter<LibSVMParserParam> {
  std::string format;
  int indexing_mode;
  DCT_DECLARE_PARAMETER(LibSVMParserParam) {
    DCT_DECLARE_FIELD(format).set_default("libsvm");
    DCT_DECLARE_FIELD(indexing_mode)
        .set_default(0)
        .add_enum("auto", -1)
        .add_enum("zero_based", 0)
        .add_enum("one_based", 1)
        .describe("0: indices start at 0; 1: start at 1 (converted); "
                  "-1: heuristic (sklearn-compatible, reference "
                  "libsvm_parser.h:24-39)");
  }
};

struct CSVParserParam : public Parameter<CSVParserParam> {
  std::string format;
  int label_column;
  int weight_column;
  std::string delimiter;
  int dtype;
  DCT_DECLARE_PARAMETER(CSVParserParam) {
    DCT_DECLARE_FIELD(format).set_default("csv");
    DCT_DECLARE_FIELD(label_column)
        .set_default(-1)
        .set_lower_bound(-1)
        .describe("column holding the label; -1: no label column");
    DCT_DECLARE_FIELD(weight_column)
        .set_default(-1)
        .set_lower_bound(-1)
        .describe("column holding the row weight; -1: none");
    DCT_DECLARE_FIELD(delimiter)
        .set_default(",")
        .describe("single-character field delimiter");
    DCT_DECLARE_FIELD(dtype)
        .set_default(0)
        .set_range(0, 2)
        .add_enum("float32", 0)
        .add_enum("int32", 1)
        .add_enum("int64", 2)
        .describe("value dtype (reference csv_parser.h DType)");
  }
};

struct LibFMParserParam : public Parameter<LibFMParserParam> {
  std::string format;
  int indexing_mode;
  DCT_DECLARE_PARAMETER(LibFMParserParam) {
    DCT_DECLARE_FIELD(format).set_default("libfm");
    DCT_DECLARE_FIELD(indexing_mode)
        .set_default(0)
        .add_enum("auto", -1)
        .add_enum("zero_based", 0)
        .add_enum("one_based", 1)
        .describe("indexing heuristic over field and feature ids "
                  "(reference libfm_parser.h:24-40)");
  }
};

// --------------------------------------------------------------------------
template <typename IndexType>
TextParserBase<IndexType>::TextParserBase(InputSplit* source, int nthread)
    : source_(source),
      nthread_(DefaultThreads(nthread)),
      // per-construction resolve (not a process-global): the differential
      // lanes flip DMLC_PARSE_SIMD between parser constructions to compare
      // SIMD and scalar output in one process
      simd_tier_(ResolveSimdTier()) {}

template <typename IndexType>
TextParserBase<IndexType>::~TextParserBase() {
  {
    std::lock_guard<std::mutex> lk(pool_mu_);
    pool_stop_ = true;
  }
  pool_cv_.notify_all();
  for (auto& t : pool_) t.join();
}

template <typename IndexType>
void TextParserBase<IndexType>::EnsurePool(int workers) {
  while (static_cast<int>(pool_.size()) < workers) {
    int i = static_cast<int>(pool_.size());
    pool_.emplace_back([this, i] { WorkerLoop(i); });
  }
}

template <typename IndexType>
void TextParserBase<IndexType>::BeforeFirst() {
  source_->BeforeFirst();
  blocks_.clear();
  block_idx_ = block_count_ = 0;
}

namespace {
// Optional per-row arrays must be absent or full-length: the C ABI exposes
// them as dense parallel arrays, so ragged input (e.g. libsvm rows mixing
// `idx:val` and bare `idx` features) must fail loudly, not misalign.
// The offset checks guard the binary rec lane: LoadAppend validates vector
// LENGTHS against the stream, but a bit-flipped record can carry
// non-monotone or inflated offset VALUES that would underflow
// offset[r+1]-offset[r] in the batcher fills and index out of bounds —
// they must die here, not in a memcpy.
template <typename IndexType>
void ValidateBlock(const RowBlockContainer<IndexType>& b) {
  DCT_CHECK(b.offset.size() == b.label.size() + 1 && b.offset.front() == 0)
      << "corrupt row block: " << b.offset.size() << " offsets for "
      << b.label.size() << " rows";
  DCT_CHECK(b.offset.back() == b.index.size())
      << "corrupt row block: final offset " << b.offset.back()
      << " does not match " << b.index.size() << " features";
  for (size_t i = 1; i < b.offset.size(); ++i) {
    DCT_CHECK(b.offset[i - 1] <= b.offset[i])
        << "corrupt row block: offsets decrease at row " << (i - 1);
  }
  DCT_CHECK(b.ValueCount() == 0 || b.ValueCount() == b.index.size())
      << "inconsistent input: some features have explicit values and some "
         "do not (" << b.ValueCount() << " values for " << b.index.size()
      << " features)";
  DCT_CHECK(b.weight.empty() || b.weight.size() == b.label.size())
      << "inconsistent input: only " << b.weight.size() << " of "
      << b.label.size() << " rows carry a label:weight";
  DCT_CHECK(b.qid.empty() || b.qid.size() == b.label.size())
      << "inconsistent input: only " << b.qid.size() << " of "
      << b.label.size() << " rows carry qid:";
  DCT_CHECK(b.field.empty() || b.field.size() == b.index.size())
      << "inconsistent libfm input: field count mismatch";
}
}  // namespace

template <typename IndexType>
void TextParserBase<IndexType>::WorkerLoop(int i) {
  uint64_t seen = 0;
  for (;;) {
    std::unique_lock<std::mutex> lk(pool_mu_);
    pool_cv_.wait(lk, [&] {
      return pool_stop_ ||
             (pool_generation_ != seen && i < pool_active_);
    });
    if (pool_stop_) return;
    seen = pool_generation_;
    // worker i owns slice i+1 (slice 0 runs on the calling thread)
    const char* b = (*round_cuts_)[i + 1];
    const char* e = (*round_cuts_)[i + 2];
    auto* out = &(*round_blocks_)[i + 1];
    auto* err = &(*round_errors_)[i + 1];
    lk.unlock();
    try {
      this->ParseBlock(b, e, out);
      ValidateBlock(*out);
    } catch (...) {
      *err = std::current_exception();
    }
    lk.lock();
    if (++pool_done_ == pool_active_) done_cv_.notify_one();
  }
}

template <typename IndexType>
bool TextParserBase<IndexType>::ReadChunk(std::vector<char>* buf) {
  // Fast lane: when the split chain's top exposes the chunk-producer
  // interface (ByteSplit / IndexedRecordIOSplit — the pipelined Create
  // skips the PrefetchSplit wrapper precisely so it does), fill the task
  // buffer straight from the stream: zero extra copies.
  if (!chunk_source_probed_) {
    chunk_source_ = dynamic_cast<RecordChunkSource*>(source_.get());
    chunk_source_probed_ = true;
  }
  if (chunk_source_ != nullptr) {
    if (!chunk_source_->FillChunkBuffer(buf)) return false;
    bytes_read_.fetch_add(buf->size(), std::memory_order_relaxed);
    return true;
  }
  // wrapped chains (ShuffleSplit, PrefetchSplit): the Blob aliases the
  // split's internal buffer (invalid after the next NextChunk), so an
  // in-flight chunk needs its own copy — a memcpy at memory bandwidth
  // against parsing at ~1% of it
  InputSplit::Blob chunk;
  if (!source_->NextChunk(&chunk)) return false;
  bytes_read_.fetch_add(chunk.size, std::memory_order_relaxed);
  buf->assign(static_cast<const char*>(chunk.dptr),
              static_cast<const char*>(chunk.dptr) + chunk.size);
  return true;
}

template <typename IndexType>
void TextParserBase<IndexType>::TileCuts(const char* begin, const char* end,
                                         int nslice,
                                         std::vector<const char*>* cuts) {
  // Tile the chunk into unit-aligned slices: cut i starts at the first
  // parse-unit head at/after i*size/n — line heads for text formats,
  // RecordIO magics for binary (FindUnitBoundary; the reference tiles text
  // backward via BackFindEndLine — forward tiling yields the same cover).
  const size_t size = static_cast<size_t>(end - begin);
  cuts->resize(nslice + 1);
  (*cuts)[0] = begin;
  (*cuts)[nslice] = end;
  for (int i = 1; i < nslice; ++i) {
    (*cuts)[i] = FindUnitBoundary(begin, begin + size * i / nslice, end);
  }
  for (int i = 1; i < nslice; ++i) {
    if ((*cuts)[i] < (*cuts)[i - 1]) (*cuts)[i] = (*cuts)[i - 1];
  }
}

template <typename IndexType>
bool TextParserBase<IndexType>::FillBlocks(
    std::vector<RowBlockContainer<IndexType>>* blocks) {
  InputSplit::Blob chunk;
  if (!source_->NextChunk(&chunk)) return false;
  bytes_read_.fetch_add(chunk.size, std::memory_order_relaxed);
  const char* begin = static_cast<const char*>(chunk.dptr);
  const char* end = begin + chunk.size;
  const int nworker = SlicesFor(chunk.size);
  blocks->resize(nworker);
  if (nworker == 1) {
    ParseBlock(begin, end, &(*blocks)[0]);
    ValidateBlock((*blocks)[0]);
    return true;
  }
  std::vector<const char*> cuts;
  TileCuts(begin, end, nworker, &cuts);
  // fan out slices 1..n-1 to the persistent pool; slice 0 parses on this
  // thread (spawning fresh threads per chunk would tax every chunk ~100 us
  // per worker — the pool signals instead)
  std::vector<std::exception_ptr> errors(nworker);
  EnsurePool(nworker - 1);
  {
    std::lock_guard<std::mutex> lk(pool_mu_);
    round_cuts_ = &cuts;
    round_blocks_ = blocks;
    round_errors_ = &errors;
    pool_done_ = 0;
    pool_active_ = nworker - 1;
    ++pool_generation_;
  }
  pool_cv_.notify_all();
  std::exception_ptr my_error;
  try {
    ParseBlock(cuts[0], cuts[1], &(*blocks)[0]);
    ValidateBlock((*blocks)[0]);
  } catch (...) {
    my_error = std::current_exception();
  }
  {
    std::unique_lock<std::mutex> lk(pool_mu_);
    done_cv_.wait(lk, [&] { return pool_done_ == pool_active_; });
  }
  if (my_error != nullptr) std::rethrow_exception(my_error);
  for (auto& e : errors) {
    if (e != nullptr) std::rethrow_exception(e);  // reference OMPException
  }
  return true;
}

template <typename IndexType>
const char* TextParserBase<IndexType>::FindUnitBoundary(const char* base,
                                                        const char* hint,
                                                        const char* end) {
  (void)base;
  const char* nl = static_cast<const char*>(
      memchr(hint, '\n', static_cast<size_t>(end - hint)));
  return nl == nullptr ? end : nl + 1;
}

template <typename IndexType>
const RowBlockContainer<IndexType>* TextParserBase<IndexType>::NextBlock() {
  while (true) {
    while (block_idx_ < block_count_) {
      const RowBlockContainer<IndexType>* b = &blocks_[block_idx_++];
      if (b->Size() != 0) return b;
    }
    if (!FillBlocks(&blocks_)) return nullptr;
    block_count_ = blocks_.size();
    block_idx_ = 0;
  }
}

template <typename IndexType>
bool TextParserBase<IndexType>::NextBlockMove(
    RowBlockContainer<IndexType>* out) {
  // swap hand-off: the consumer gets the parsed buffers, the worker slot
  // keeps out's old capacity for the next chunk
  const RowBlockContainer<IndexType>* b = NextBlock();
  if (b == nullptr) return false;
  std::swap(*out, blocks_[block_idx_ - 1]);
  return true;
}

// --------------------------------------------------------------------------
template <typename IndexType>
LibSVMParser<IndexType>::LibSVMParser(
    InputSplit* source, const std::map<std::string, std::string>& args,
    int nthread)
    : TextParserBase<IndexType>(source, nthread) {
  LibSVMParserParam param;
  param.Init(args, ParamInitOption::kAllowUnknown);
  DCT_CHECK_EQ(param.format, std::string("libsvm")) << "format mismatch";
  indexing_mode_ = param.indexing_mode;
}

namespace {
// Advance past the current line: to just after the next '\n'/'\r', or end.
inline const char* SkipToEol(const char* p, const char* end) {
  const char* nl =
      static_cast<const char*>(memchr(p, '\n', static_cast<size_t>(end - p)));
  const char* limit = nl == nullptr ? end : nl;
  const char* cr =
      static_cast<const char*>(memchr(p, '\r', static_cast<size_t>(limit - p)));
  const char* term = cr == nullptr ? limit : cr;
  return term == end ? end : term + 1;
}

inline bool IsEolChar(char c) { return c == '\n' || c == '\r'; }
}  // namespace

namespace {
// One libsvm row starting at p (a non-blank, non-EOL char); returns the
// cursor past the row's line terminator (or end). This IS the scalar
// tokenizer (reference src/data/libsvm_parser.h:87-169 semantics:
// comment/garbage lines discard, label[:weight], qid:, bare-index
// features, ':'-garbage discards the line tail). kFused=false compiles
// to exactly the scalar byte loops; kFused=true swaps the numeric
// primitives for the fused SWAR field decoders (simd_scan.h), which
// accept only shapes whose value AND consumption provably equal the
// scalar ops' — so both instantiations are byte-identical by
// construction.
//
// `dec` (0 or 1) is subtracted from every feature id as it is written:
// the decode-path hoist of the old O(nnz) 1-based post-pass for forced
// indexing_mode=1. *min_feat tracks the RAW (pre-decrement) ids for the
// indexing_mode=auto heuristic, which still needs one deferred pass (the
// minimum over the block is only known once the block ends).
template <typename IndexType, bool kFused>
const char* ParseLibSVMRow(const char* p, const char* end,
                           RowBlockContainer<IndexType>* out,
                           IndexType* min_feat, IndexType dec) {
  // feature ids below 10 digits accumulate in a u64 without overflow; wider
  // tokens delegate to ParseNum for exact from_chars overflow semantics
  constexpr int kFastIdxDigits = sizeof(IndexType) == 8 ? 19 : 9;
  if (*p == '#') return SkipToEol(p, end);  // comment-only line
  // label[:weight] — the parse stops at any non-numeric char, so the
  // chunk end doubles as the line bound here
  float label;
  if (!ParseNumF<kFused, float>(p, end, &p, &label)) {
    return SkipToEol(p, end);  // garbage line: discard (ParsePair contract)
  }
  if (p != end && *p == ':') {
    float weight;
    const char* wp;
    if (ParseNumF<kFused, float>(p + 1, end, &wp, &weight)) {
      out->weight.push_back(weight);
      p = wp;
    }
    // ":garbage" leaves p at ':' — the token loop below then discards
    // the rest of the line, matching the line-oriented behavior
  }
  out->label.push_back(label);
  // optional qid:n (space-separated, reference libsvm_parser.h:116-126)
  while (p != end && *p == ' ') ++p;
  if (end - p > 4 && std::memcmp(p, "qid:", 4) == 0) {
    uint64_t qid = 0;
    const char* qp;
    if (ParseNumF<kFused, uint64_t>(p + 4, end, &qp, &qid)) {
      out->qid.push_back(qid);
      p = qp;
    }
  }
  // index[:value] tokens until end of line
  while (true) {
    while (p != end && IsBlankChar(*p)) ++p;
    if (p == end) break;
    const char c = *p;
    if (IsEolChar(c)) {
      ++p;
      break;
    }
    if (c == '#') {
      p = SkipToEol(p, end);
      break;
    }
    // feature id: fused digit-run scan (one or two 8-byte loads) or the
    // inline digit loop — identical consumption and value either way
    uint64_t idx = 0;
    int nd = 0;
    const char* tok = p;
    if constexpr (kFused) {
      const int il = FusedDigitScan(p, end, &idx);
      if (il >= 1 && il <= kFastIdxDigits) {
        nd = il;
        p += il;
      } else if (il != 0) {
        // overflow-length run or too close to the chunk end: force the
        // exact ParseNum delegate below (same as the scalar lane's
        // kFastIdxDigits+1 bail-out)
        nd = kFastIdxDigits + 1;
      }
    } else {
      while (p != end && IsDigitChar(*p)) {
        idx = idx * 10 + static_cast<uint64_t>(*p - '0');
        ++p;
        if (++nd > kFastIdxDigits) break;
      }
    }
    IndexType idx_t;
    if (nd == 0 || nd > kFastIdxDigits) {
      // '+'-prefixed, overflowing, or non-numeric token: exact fallback
      if (!ParseNum<IndexType>(tok, end, &p, &idx_t)) {
        p = SkipToEol(tok, end);  // discard rest of line
        break;
      }
    } else {
      idx_t = static_cast<IndexType>(idx);
    }
    const IndexType written = static_cast<IndexType>(idx_t - dec);
    out->index.push_back(written);
    // inline max tracking replaces the old post-parse UpdateMax pass (an
    // O(nnz) re-walk of the index array per block)
    out->max_index = std::max<uint64_t>(out->max_index, written);
    *min_feat = std::min(*min_feat, idx_t);
    if (p != end && *p == ':') {
      float value;
      const char* vp;
      if (ParseNumF<kFused, float>(p + 1, end, &vp, &value)) {
        out->value.push_back(value);
        p = vp;
      }
      // ":garbage": p stays at ':' and the next iteration discards the
      // line, matching ParsePair's r==1-then-fail sequence
    }
  }
  out->offset.push_back(out->index.size());
  return p;
}

// reference src/data/libsvm_parser.h:87-169. Single-pass tokenizer: rows
// and tokens are recognized in the same scan (newlines terminate the token
// loop directly), instead of pre-scanning each line for its end and then
// re-walking it. Semantics (comment/blank lines, label[:weight], qid:,
// bare-index features, discard-line-on-garbage, CRLF/CR/NOEOL) match the
// line-oriented form; tests/test_native_parser.py pins them and
// tests/test_parse_simd.py pins kFused=true == kFused=false.
template <bool kFused, typename IndexType>
void ParseLibSVMBlockImpl(const char* begin, const char* end,
                          int indexing_mode,
                          RowBlockContainer<IndexType>* out) {
  IndexType min_feat = std::numeric_limits<IndexType>::max();
  const IndexType dec = indexing_mode > 0 ? 1 : 0;
  const char* p = SkipUTF8BOM(begin, end);
  while (p != end) {
    // between rows: swallow blanks and empty lines in one skip
    while (p != end && (IsBlankChar(*p) || IsEolChar(*p))) ++p;
    if (p == end) break;
    p = ParseLibSVMRow<IndexType, kFused>(p, end, out, &min_feat, dec);
  }
  DCT_CHECK_EQ(out->label.size() + 1, out->offset.size());
  // 0/1-based auto heuristic (sklearn-compatible, reference
  // libsvm_parser.h:155-168); the forced >0 mode decrements at decode time
  // (dec above), so only auto-detect still re-walks the index array
  if (indexing_mode < 0 && !out->index.empty() && min_feat > 0) {
    for (IndexType& e : out->index) --e;
    --out->max_index;  // min_feat > 0 keeps the decrement wrap-free
  }
}
}  // namespace

template <typename IndexType>
void LibSVMParser<IndexType>::ParseBlock(const char* begin, const char* end,
                                         RowBlockContainer<IndexType>* out) {
  if (this->simd_tier_ != kSimdScalar) {
    ParseBlockSimd(begin, end, out);
  } else {
    ParseBlockScalar(begin, end, out);
  }
}

template <typename IndexType>
void LibSVMParser<IndexType>::ParseBlockScalar(
    const char* begin, const char* end, RowBlockContainer<IndexType>* out) {
  out->Clear();
  ParseLibSVMBlockImpl<false>(begin, end, indexing_mode_, out);
}

// The SIMD lane: stage 1 runs the tier kernels over the chunk for the
// reserve hints (every valued feature owns one ':', every row one EOL),
// stage 2 is the SAME tokenizer instantiated with the fused SWAR field
// decoders (see simd_scan.h for why fused decode beats per-token tape
// walking on real corpora).
template <typename IndexType>
void LibSVMParser<IndexType>::ParseBlockSimd(
    const char* begin, const char* end, RowBlockContainer<IndexType>* out) {
  out->Clear();
  size_t n_sep = 0, n_eol = 0;
  CountSepEol(begin, end, ':',
              static_cast<SimdTier>(this->simd_tier_), &n_sep, &n_eol);
  out->index.reserve(n_sep);
  out->value.reserve(n_sep);
  out->label.reserve(n_eol + 1);
  out->offset.reserve(n_eol + 2);
  ParseLibSVMBlockImpl<true>(begin, end, indexing_mode_, out);
}

// --------------------------------------------------------------------------
template <typename IndexType>
CSVParser<IndexType>::CSVParser(InputSplit* source,
                                const std::map<std::string, std::string>& args,
                                int nthread)
    : TextParserBase<IndexType>(source, nthread) {
  CSVParserParam param;
  param.Init(args, ParamInitOption::kAllowUnknown);
  DCT_CHECK_EQ(param.format, std::string("csv")) << "format mismatch";
  label_column_ = param.label_column;
  weight_column_ = param.weight_column;
  DCT_CHECK_EQ(param.delimiter.size(), size_t(1))
      << "delimiter must be a single char";
  delimiter_ = param.delimiter[0];
  // the single-pass cell parse relies on the delimiter terminating a
  // number scan; a numeric-looking delimiter would let values run across
  // cells (reference csv_parser.h has the same implicit assumption via
  // strtof stopping at it)
  DCT_CHECK(!IsDigitChar(delimiter_) && delimiter_ != '.' &&
            delimiter_ != '-' && delimiter_ != '+' && delimiter_ != 'e' &&
            delimiter_ != 'E')
      << "csv delimiter '" << delimiter_
      << "' is a numeric character; values could not be delimited";
  DCT_CHECK(label_column_ != weight_column_ || label_column_ < 0)
      << "label and weight columns must differ";
  // typed values (reference csv_parser.h:24-147 DType float32/int32/int64);
  // the enum mapping (string -> code) happens in CSVParserParam::Init
  value_dtype_ = param.dtype;
}

namespace {
// value-cell sink per csv dtype: parses a number at vp into `values` and
// advances *out past it (the caller then skips any cell residue).
// kFused selects the fused numeric primitives (simd_scan.h) — identical
// values and consumption, fewer per-character loops.
template <bool kFused, typename VT>
bool ParseCellF(const char* vp, const char* end, const char** out,
                std::vector<VT>* values) {
  VT v;
  const char* after;
  if (!ParseNumF<kFused, VT>(vp, end, &after, &v)) return false;
  *out = after;
  values->push_back(v);
  return true;
}

// reference src/data/csv_parser.h:76-147. Single-pass tokenizer: cells
// are parsed where the cursor stands and EOL characters double as cell
// terminators. Semantics (missing values keep their column index,
// label/weight columns, blank-only lines emit empty rows, delimiter
// presence check) match the line-oriented form; tests pin them, and
// tests/test_parse_simd.py pins kFused=true == kFused=false.
template <bool kFused, typename IndexType>
void ParseCSVBlockImpl(const char* begin, const char* end, int label_column,
                       int weight_column, char delimiter, int value_dtype,
                       RowBlockContainer<IndexType>* out) {
  out->value_dtype = value_dtype;
  const char* p = SkipUTF8BOM(begin, end);
  while (p != end) {
    if (IsEolChar(*p)) {  // empty line (also the LF of a CRLF pair)
      ++p;
      continue;
    }
    p = SkipUTF8BOM(p, end);
    int column = 0;
    IndexType idx = 0;
    float label = 0.0f;
    float weight = std::numeric_limits<float>::quiet_NaN();
    bool any_delim = false;
    bool line_done = false;
    while (!line_done) {
      // leading blanks of the cell — but never across a blank DELIMITER
      // (tab-separated files: '\t' both blank and delimiter)
      while (p != end && IsBlankChar(*p) && *p != delimiter) ++p;
      if (column == label_column || column == weight_column) {
        float v;
        const char* after;
        if (ParseNumF<kFused, float>(p, end, &after, &v)) {
          (column == label_column ? label : weight) = v;
          p = after;
        }
      } else {
        bool parsed =
            value_dtype == 0
                ? ParseCellF<kFused>(p, end, &p, &out->value)
            : value_dtype == 1
                ? ParseCellF<kFused>(p, end, &p, &out->value_i32)
                : ParseCellF<kFused>(p, end, &p, &out->value_i64);
        if (parsed) {
          out->index.push_back(idx);
          // inline max tracking replaces the old UpdateMax pass
          out->max_index = std::max<uint64_t>(out->max_index, idx);
          ++idx;
        } else {
          ++idx;  // missing value: skip but keep the column index
        }
      }
      // cell residue (trailing garbage/blanks) up to the next delimiter
      // or end of line
      while (p != end && *p != delimiter && !IsEolChar(*p)) ++p;
      ++column;
      if (p == end) {
        line_done = true;  // NOEOL final line
      } else if (*p == delimiter) {
        any_delim = true;
        ++p;
      } else {
        ++p;  // consume the EOL character
        line_done = true;
      }
    }
    DCT_CHECK(any_delim || column <= 1 || idx > 0)
        << "delimiter '" << delimiter << "' not found in csv line";
    out->label.push_back(label);
    if (!std::isnan(weight)) out->weight.push_back(weight);
    out->offset.push_back(out->index.size());
  }
  DCT_CHECK_EQ(out->label.size() + 1, out->offset.size());
  DCT_CHECK(out->weight.empty() || out->weight.size() == out->label.size())
      << "weight_column missing on some csv rows";
}
}  // namespace

template <typename IndexType>
void CSVParser<IndexType>::ParseBlock(const char* begin, const char* end,
                                      RowBlockContainer<IndexType>* out) {
  if (this->simd_tier_ != kSimdScalar) {
    ParseBlockSimd(begin, end, out);
  } else {
    ParseBlockScalar(begin, end, out);
  }
}

template <typename IndexType>
void CSVParser<IndexType>::ParseBlockScalar(
    const char* begin, const char* end, RowBlockContainer<IndexType>* out) {
  out->Clear();
  ParseCSVBlockImpl<false>(begin, end, label_column_, weight_column_,
                           delimiter_, value_dtype_, out);
}

template <typename IndexType>
void CSVParser<IndexType>::ParseBlockSimd(
    const char* begin, const char* end, RowBlockContainer<IndexType>* out) {
  out->Clear();
  size_t n_sep = 0, n_eol = 0;
  CountSepEol(begin, end, delimiter_,
              static_cast<SimdTier>(this->simd_tier_), &n_sep, &n_eol);
  // cells <= delimiters + rows; every row owns one EOL (+1 NOEOL tail)
  const size_t cells_hint = n_sep + n_eol + 1;
  out->index.reserve(cells_hint);
  if (value_dtype_ == 1) {
    out->value_i32.reserve(cells_hint);
  } else if (value_dtype_ == 2) {
    out->value_i64.reserve(cells_hint);
  } else {
    out->value.reserve(cells_hint);
  }
  out->label.reserve(n_eol + 1);
  out->offset.reserve(n_eol + 2);
  ParseCSVBlockImpl<true>(begin, end, label_column_, weight_column_,
                          delimiter_, value_dtype_, out);
}

// --------------------------------------------------------------------------
template <typename IndexType>
LibFMParser<IndexType>::LibFMParser(
    InputSplit* source, const std::map<std::string, std::string>& args,
    int nthread)
    : TextParserBase<IndexType>(source, nthread) {
  LibFMParserParam param;
  param.Init(args, ParamInitOption::kAllowUnknown);
  DCT_CHECK_EQ(param.format, std::string("libfm")) << "format mismatch";
  indexing_mode_ = param.indexing_mode;
}

namespace {
// One libfm row starting at p (a non-blank, non-EOL char); same
// fused/scalar contract as ParseLibSVMRow above. `dec`/`dec_field` hoist
// the forced 1-based decrement into the decode path; mins track RAW ids
// for the auto heuristic.
template <typename IndexType, bool kFused>
const char* ParseLibFMRow(const char* p, const char* end,
                          RowBlockContainer<IndexType>* out,
                          uint32_t* min_field, IndexType* min_feat,
                          IndexType dec) {
  const uint32_t dec_field = static_cast<uint32_t>(dec);
  if (*p == '#') return SkipToEol(p, end);  // comment-only line
  float label;
  if (!ParseNumF<kFused, float>(p, end, &p, &label)) {
    return SkipToEol(p, end);  // garbage line: discard (ParsePair contract)
  }
  if (p != end && *p == ':') {
    float weight;
    const char* wp;
    if (ParseNumF<kFused, float>(p + 1, end, &wp, &weight)) {
      out->weight.push_back(weight);
      p = wp;
    }
  }
  out->label.push_back(label);
  // field:feature[:value] triples until end of line
  while (true) {
    while (p != end && IsBlankChar(*p)) ++p;
    if (p == end) break;
    const char c = *p;
    if (IsEolChar(c)) {
      ++p;
      break;
    }
    if (c == '#') {
      p = SkipToEol(p, end);
      break;
    }
    uint32_t field;
    IndexType feat;
    float value;
    const char* after;
    // a triple shares the pair grammar; ParseTriple's rr<=1 cases
    // (bare number, no second ':') keep the line-oriented semantics
    int rr = ParseTripleF<kFused, uint32_t, IndexType, float>(
        p, end, &after, &field, &feat, &value);
    if (rr == 0) {
      p = SkipToEol(p, end);  // non-numeric token: discard the line
      break;
    }
    p = after;
    if (rr == 1) continue;  // bare number token: skipped (reference)
    const uint32_t wfield = field - dec_field;
    const IndexType wfeat = static_cast<IndexType>(feat - dec);
    out->field.push_back(wfield);
    out->index.push_back(wfeat);
    // inline max tracking replaces the old post-parse UpdateMax pass
    out->max_field = std::max(out->max_field, wfield);
    out->max_index = std::max<uint64_t>(out->max_index, wfeat);
    *min_field = std::min(*min_field, field);
    *min_feat = std::min(*min_feat, feat);
    if (rr == 3) out->value.push_back(value);
  }
  out->offset.push_back(out->index.size());
  return p;
}

// reference src/data/libfm_parser.h:67-144. Single-pass tokenizer (same
// structure as the libsvm impl: rows and `field:feature[:value]` triples
// recognized in one scan, newlines terminate the token loop).
template <bool kFused, typename IndexType>
void ParseLibFMBlockImpl(const char* begin, const char* end,
                         int indexing_mode,
                         RowBlockContainer<IndexType>* out) {
  uint32_t min_field = std::numeric_limits<uint32_t>::max();
  IndexType min_feat = std::numeric_limits<IndexType>::max();
  const IndexType dec = indexing_mode > 0 ? 1 : 0;
  const char* p = SkipUTF8BOM(begin, end);
  while (p != end) {
    while (p != end && (IsBlankChar(*p) || IsEolChar(*p))) ++p;
    if (p == end) break;
    p = ParseLibFMRow<IndexType, kFused>(p, end, out, &min_field,
                                         &min_feat, dec);
  }
  DCT_CHECK_EQ(out->field.size(), out->index.size());
  DCT_CHECK_EQ(out->label.size() + 1, out->offset.size());
  // 1-based auto detection requires BOTH field and feature ids to exceed 0
  // (reference libfm_parser.h:130-143); forced >0 mode decrements at
  // decode time (dec above)
  if (indexing_mode < 0 && !out->index.empty() && min_feat > 0 &&
      !out->field.empty() && min_field > 0) {
    for (IndexType& e : out->index) --e;
    for (uint32_t& e : out->field) --e;
    --out->max_index;  // both mins > 0 keep the decrements wrap-free
    --out->max_field;
  }
}
}  // namespace

template <typename IndexType>
void LibFMParser<IndexType>::ParseBlock(const char* begin, const char* end,
                                        RowBlockContainer<IndexType>* out) {
  if (this->simd_tier_ != kSimdScalar) {
    ParseBlockSimd(begin, end, out);
  } else {
    ParseBlockScalar(begin, end, out);
  }
}

template <typename IndexType>
void LibFMParser<IndexType>::ParseBlockScalar(
    const char* begin, const char* end, RowBlockContainer<IndexType>* out) {
  out->Clear();
  ParseLibFMBlockImpl<false>(begin, end, indexing_mode_, out);
}

template <typename IndexType>
void LibFMParser<IndexType>::ParseBlockSimd(
    const char* begin, const char* end, RowBlockContainer<IndexType>* out) {
  out->Clear();
  size_t n_sep = 0, n_eol = 0;
  CountSepEol(begin, end, ':',
              static_cast<SimdTier>(this->simd_tier_), &n_sep, &n_eol);
  // every full triple owns two ':'
  const size_t nnz_hint = n_sep / 2 + 1;
  out->index.reserve(nnz_hint);
  out->field.reserve(nnz_hint);
  out->value.reserve(nnz_hint);
  out->label.reserve(n_eol + 1);
  out->offset.reserve(n_eol + 2);
  ParseLibFMBlockImpl<true>(begin, end, indexing_mode_, out);
}

// --------------------------------------------------------------------------
// rec: binary RecordIO-framed row blocks (parser.h RecParser). Each record
// is [magic 'DRB1' u32le][flags u32le: bit0 = uint64 indices] followed by
// the rowblock.h wire format; deserialization is bulk memcpy.
namespace {
constexpr uint32_t kRecRowBlockMagic = 0x44524231;  // 'DRB1' (LE word '1BRD')
}  // namespace

template <typename IndexType>
RecParser<IndexType>::RecParser(InputSplit* source,
                                const std::map<std::string, std::string>& args,
                                int nthread)
    : TextParserBase<IndexType>(source, nthread) {
  (void)args;
}

template <typename IndexType>
const char* RecParser<IndexType>::FindUnitBoundary(const char* base,
                                                   const char* hint,
                                                   const char* end) {
  return FindRecordHead(base, hint, end);
}

template <typename IndexType>
void RecParser<IndexType>::ParseBlock(const char* begin, const char* end,
                                      RowBlockContainer<IndexType>* out) {
  out->Clear();
  RecordIOChunkReader reader(begin, end, 0, 1);
  RecordIOChunkReader::Blob rec;
  while (reader.NextRecord(&rec)) {
    DCT_CHECK(rec.size >= 8) << "rec record too short for a row-block header";
    const char* p = static_cast<const char*>(rec.dptr);
    DCT_CHECK(recordio::LoadWordLE(p) == kRecRowBlockMagic)
        << "not a row-block record (bad payload magic); rec files are "
           "written by rows_to_recordio (dmlc_core_tpu/io/convert.py)";
    const bool is64 = (recordio::LoadWordLE(p + 4) & 1u) != 0;
    DCT_CHECK(is64 == (sizeof(IndexType) == 8))
        << "rec index width mismatch: payload has "
        << (is64 ? "uint64" : "uint32") << " feature ids but the parser "
        << "was created with index64=" << (sizeof(IndexType) == 8);
    MemoryFixedSizeStream ms(const_cast<char*>(p) + 8, rec.size - 8);
    // append-deserialize straight into the output container: one memcpy
    // per array from the mapped chunk, no intermediate container
    DCT_CHECK(out->LoadAppend(&ms)) << "truncated row-block record";
  }
}

// --------------------------------------------------------------------------
namespace {
// "DCTRBL2" — bumped when the RowBlockContainer wire format changes (v2
// added typed csv value arrays); a stale v1 cache fails the magic check and
// is rebuilt transparently
constexpr uint64_t kRowCacheMagic = 0x44435452424c32;

uint64_t FingerprintHash64(const std::string& s) {
  uint64_t h = 1469598103934665603ull;  // FNV-1a
  for (unsigned char c : s) {
    h ^= c;
    h *= 1099511628211ull;
  }
  return h;
}
}  // namespace

template <typename IndexType>
DiskCacheParser<IndexType>::DiskCacheParser(Parser<IndexType>* base,
                                            const std::string& cache_file,
                                            const std::string& fingerprint)
    : base_(base),
      cache_file_(cache_file),
      fingerprint_(FingerprintHash64(fingerprint)) {
  replaying_ = TryOpenCache();
}

template <typename IndexType>
DiskCacheParser<IndexType>::~DiskCacheParser() {
  if (replay_cell_ != nullptr) replay_pipe_.Recycle(&replay_cell_);
  replay_pipe_.Shutdown();
}

template <typename IndexType>
bool DiskCacheParser<IndexType>::TryOpenCache() {
  std::unique_ptr<SeekStream> probe(
      SeekStream::CreateForRead(cache_file_, /*allow_null=*/true));
  if (probe == nullptr) return false;
  uint64_t magic = 0, fp = 0;
  if (probe->Read(&magic, 8) != 8 || probe->Read(&fp, 8) != 8) {
    return false;
  }
  if (!serial::NativeIsLE()) {
    magic = serial::ByteSwap(magic);
    fp = serial::ByteSwap(fp);
  }
  if (magic != kRowCacheMagic || fp != fingerprint_) {
    std::remove(cache_file_.c_str());  // stale/foreign cache: rebuild
    return false;
  }
  reader_ = std::move(probe);
  return true;
}

template <typename IndexType>
void DiskCacheParser<IndexType>::StartReplayPipeline() {
  if (replay_started_) return;
  replay_pipe_.Init(
      [this](RowBlockContainer<IndexType>** cell) {
        if (*cell == nullptr) *cell = new RowBlockContainer<IndexType>();
        return (*cell)->Load(reader_.get());
      },
      [this] {
        // rewind past the header
        reader_->Seek(16);
      });
  replay_started_ = true;
}

template <typename IndexType>
void DiskCacheParser<IndexType>::FinalizeCache() {
  // publish ONLY a complete pass (a partial .tmp would silently truncate
  // the dataset forever)
  if (writer_ == nullptr) return;
  writer_.reset();
  std::string tmp = cache_file_ + ".tmp";
  if (!write_complete_) {
    std::remove(tmp.c_str());
    return;
  }
  // injectable publish (fs_fault.h): a failed/torn rename surfaces as a
  // structured error with errno instead of a bare check string. The
  // DESTINATION is removed first: a torn half-copy keeps the magic+
  // fingerprint probe valid, so leaving it would wedge every later
  // epoch/process mid-replay — deleting it makes the failure a clean
  // first-pass re-parse instead (the shard cache gets this from
  // manifest-last publishing; this single-file format has no manifest).
  if (fsio::Rename(tmp.c_str(), cache_file_.c_str()) != 0) {
    const int err = errno != 0 ? errno : EIO;
    std::remove(cache_file_.c_str());
    std::remove(tmp.c_str());
    throw fsio::FsError(fsio::FsOp::kRename, cache_file_, err);
  }
}

template <typename IndexType>
void DiskCacheParser<IndexType>::EnsureWriter() {
  if (writer_ != nullptr) return;
  writer_.reset(Stream::Create(cache_file_ + ".tmp", "w"));
  uint64_t magic = kRowCacheMagic, fp = fingerprint_;
  if (!serial::NativeIsLE()) {
    magic = serial::ByteSwap(magic);
    fp = serial::ByteSwap(fp);
  }
  writer_->Write(&magic, 8);
  writer_->Write(&fp, 8);
}

template <typename IndexType>
const RowBlockContainer<IndexType>* DiskCacheParser<IndexType>::NextBlock() {
  if (replaying_) {
    StartReplayPipeline();
    if (replay_cell_ != nullptr) replay_pipe_.Recycle(&replay_cell_);
    if (!replay_pipe_.Next(&replay_cell_)) return nullptr;
    return replay_cell_;
  }
  const RowBlockContainer<IndexType>* b = base_->NextBlock();
  if (b == nullptr) {
    write_complete_ = true;
    FinalizeCache();
    return nullptr;
  }
  EnsureWriter();
  b->Save(writer_.get());
  return b;
}

template <typename IndexType>
bool DiskCacheParser<IndexType>::NextBlockMove(
    RowBlockContainer<IndexType>* out) {
  if (replaying_) {
    StartReplayPipeline();
    if (replay_cell_ != nullptr) replay_pipe_.Recycle(&replay_cell_);
    if (!replay_pipe_.Next(&replay_cell_)) return false;
    // swap hand-off: the recycled replay cell keeps out's old capacity
    std::swap(*out, *replay_cell_);
    replay_cell_->Clear();
    return true;
  }
  // write-through epoch: move from base, then append to the cache
  if (!base_->NextBlockMove(out)) {
    write_complete_ = true;
    FinalizeCache();
    return false;
  }
  EnsureWriter();
  out->Save(writer_.get());
  return true;
}

template <typename IndexType>
void DiskCacheParser<IndexType>::BeforeFirst() {
  FinalizeCache();  // publishes only when the pass completed
  write_complete_ = false;
  if (replay_started_) {
    if (replay_cell_ != nullptr) replay_pipe_.Recycle(&replay_cell_);
    replay_pipe_.Shutdown();
    replay_started_ = false;
  }
  if (TryOpenCache()) {
    replaying_ = true;
  } else {
    replaying_ = false;
    base_->BeforeFirst();
  }
}

// --------------------------------------------------------------------------
// PipelinedParser: reader -> (chunk, slice) work queue -> worker pool ->
// ordered head-of-line reassembly. See parser.h for the stage diagram.
namespace {
// Default in-flight chunk bound: enough outstanding slices to ride over a
// straggler slice plus one chunk being read and one being consumed, capped
// so host RSS stays bounded (each task holds ~chunk bytes raw + ~chunk
// bytes parsed).
size_t DefaultChunksInFlight(int workers) {
  return static_cast<size_t>(
      std::max(3, std::min(workers + 2, 10)));
}
}  // namespace

template <typename IndexType>
PipelinedParser<IndexType>::PipelinedParser(TextParserBase<IndexType>* base,
                                            int chunks_in_flight)
    : base_(base),
      capacity_(chunks_in_flight > 0
                    ? static_cast<size_t>(chunks_in_flight)
                    : DefaultChunksInFlight(base->num_threads())),
      nworker_(base->num_threads()) {
  if (capacity_ < 2) capacity_ = 2;  // 1 would re-serialize read vs parse
}

template <typename IndexType>
PipelinedParser<IndexType>::~PipelinedParser() {
  StopThreads();
  if (current_ != nullptr) delete current_;
  // lock-ok: StopThreads joined every stage thread; dtor is sole owner
  for (ChunkTask* t : free_) delete t;
}

template <typename IndexType>
void PipelinedParser<IndexType>::Start() {
  if (started_) return;
  // lock-ok: no stage thread exists yet (started_ false, all joined)
  stop_ = false;
  eof_ = false;  // lock-ok: pre-spawn init, single-threaded
  reader_ = std::thread([this] { ReaderLoop(); });
  workers_.reserve(nworker_);
  for (int i = 0; i < nworker_; ++i) {
    workers_.emplace_back([this] { WorkerLoop(); });
  }
  started_ = true;
}

template <typename IndexType>
void PipelinedParser<IndexType>::StopThreads() {
  if (!started_) return;
  {
    std::lock_guard<std::mutex> lk(mu_);
    stop_ = true;
  }
  space_cv_.notify_all();
  work_cv_.notify_all();
  done_cv_.notify_all();
  reader_.join();
  for (auto& w : workers_) w.join();
  workers_.clear();
  started_ = false;
  stop_ = false;  // lock-ok: every stage thread joined above
  // reclaim in-flight tasks (buffers kept for the next epoch); claim_ holds
  // aliases of inflight_ entries, never owned tasks.
  // lock-ok: single-threaded after the joins above
  for (ChunkTask* t : inflight_) free_.push_back(t);
  inflight_.clear();  // lock-ok: single-threaded after the joins above
  claim_.clear();  // lock-ok: single-threaded after the joins above
  // an unconsumed reader error dies with the round it belongs to: the
  // consumer either already rethrew it (failed_ set, restart forbidden) or
  // abandoned the epoch — a stale pointer here would poison the NEXT
  // epoch's first NextBlock
  reader_error_ = nullptr;  // lock-ok: single-threaded after the joins
}

template <typename IndexType>
void PipelinedParser<IndexType>::ReaderLoop() {
  try {
    for (;;) {
      ChunkTask* t = nullptr;
      {
        std::unique_lock<std::mutex> lk(mu_);
        if (inflight_.size() >= capacity_) {
          reader_waits_.fetch_add(1, std::memory_order_relaxed);
          PipeTel().reader_waits->Add(1);
          space_cv_.wait(lk, [&] {
            return stop_ || inflight_.size() < capacity_;
          });
        }
        if (stop_) return;
        if (!free_.empty()) {
          t = free_.back();
          free_.pop_back();
        }
      }
      if (t == nullptr) t = new ChunkTask();
      bool more;
      try {
        {
          telemetry::ScopedTimerUs fill_span(PipeTel().fill_us);
          telemetry::TraceSpan trace("parse.fill");
          more = base_->ReadChunk(&t->data);
          trace.set_arg(t->data.size());
        }
        if (more) {
          const int nslice = base_->SlicesFor(t->data.size());
          t->nslice = nslice;
          // lock-ok: task not yet published to inflight_/claim_ — the
          // reader is its sole owner until the push under mu_ below
          t->next_slice = 0;
          t->remaining = nslice;  // lock-ok: reader-owned until publish
          t->next_serve = 0;
          // keep blocks at their high-water count so a small final chunk
          // does not free the recycled capacity of unused slices
          if (static_cast<int>(t->blocks.size()) < nslice) {
            t->blocks.resize(nslice);
          }
          t->errors.assign(nslice, nullptr);
          telemetry::ScopedTimerUs scan_span(PipeTel().scan_us);
          telemetry::TraceSpan trace("parse.scan");
          base_->TileCuts(t->data.data(), t->data.data() + t->data.size(),
                          nslice, &t->cuts);
        }
      } catch (...) {
        // reclaim the in-flight task (read OR slice-prep may have thrown)
        // so the destructor's free-list sweep still owns it
        std::lock_guard<std::mutex> lk(mu_);
        free_.push_back(t);
        throw;
      }
      if (!more) {
        std::lock_guard<std::mutex> lk(mu_);
        free_.push_back(t);
        eof_ = true;
        done_cv_.notify_all();
        return;
      }
      {
        std::lock_guard<std::mutex> lk(mu_);
        if (stop_) {
          free_.push_back(t);
          return;
        }
        inflight_.push_back(t);
        claim_.push_back(t);
        chunks_read_.fetch_add(1, std::memory_order_relaxed);
        PipeTel().chunks_read->Add(1);
        inflight_sum_.fetch_add(inflight_.size(),
                                std::memory_order_relaxed);
        // single writer (this thread, under mu_); atomic only for the
        // lock-free stats read
        if (inflight_.size() >
            inflight_peak_.load(std::memory_order_relaxed)) {
          inflight_peak_.store(inflight_.size(), std::memory_order_relaxed);
        }
      }
      work_cv_.notify_all();
    }
  } catch (...) {
    std::lock_guard<std::mutex> lk(mu_);
    reader_error_ = std::current_exception();
    done_cv_.notify_all();
  }
}

template <typename IndexType>
void PipelinedParser<IndexType>::WorkerLoop() {
  for (;;) {
    ChunkTask* t;
    int slice;
    {
      std::unique_lock<std::mutex> lk(mu_);
      if (claim_.empty() && !stop_) {
        worker_waits_.fetch_add(1, std::memory_order_relaxed);
        PipeTel().worker_waits->Add(1);
        work_cv_.wait(lk, [&] { return stop_ || !claim_.empty(); });
      }
      if (stop_) return;
      // oldest chunk first: finishing the head chunk unblocks the ordered
      // consumer soonest, and chunks complete roughly in input order
      t = claim_.front();
      slice = t->next_slice++;
      if (t->next_slice == t->nslice) claim_.pop_front();
    }
    try {
      telemetry::ScopedTimerUs parse_span(PipeTel().parse_us);
      telemetry::TraceSpan trace("parse.slice");
      RowBlockContainer<IndexType>* out = &t->blocks[slice];
      base_->ParseBlock(t->cuts[slice], t->cuts[slice + 1], out);
      ValidateBlock(*out);
      trace.set_arg(out->Size());
    } catch (...) {
      t->errors[slice] = std::current_exception();
    }
    {
      std::lock_guard<std::mutex> lk(mu_);
      if (--t->remaining == 0 && !inflight_.empty() &&
          inflight_.front() == t) {
        done_cv_.notify_all();
      }
    }
  }
}

template <typename IndexType>
void PipelinedParser<IndexType>::RecycleCurrent() {
  {
    std::lock_guard<std::mutex> lk(mu_);
    free_.push_back(current_);
    current_ = nullptr;
  }
  space_cv_.notify_one();
}

template <typename IndexType>
RowBlockContainer<IndexType>* PipelinedParser<IndexType>::NextMutable() {
  if (failed_) {
    throw Error("parse pipeline is in a failed state after an earlier error");
  }
  Start();
  while (true) {
    if (current_ != nullptr) {
      while (current_->next_serve < static_cast<size_t>(current_->nslice)) {
        const size_t i = current_->next_serve++;
        if (current_->errors[i] != nullptr) {
          // input-order rethrow: everything before this slice was already
          // served, matching where a serial parse would have died
          std::exception_ptr e = current_->errors[i];
          failed_ = true;
          StopThreads();
          std::rethrow_exception(e);
        }
        RowBlockContainer<IndexType>* b = &current_->blocks[i];
        if (b->Size() != 0) {
          blocks_delivered_.fetch_add(1, std::memory_order_relaxed);
          PipeTel().blocks_delivered->Add(1);
          return b;
        }
      }
      RecycleCurrent();
    }
    {
      std::unique_lock<std::mutex> lk(mu_);
      bool waited = false;
      const uint64_t wait_from =
          telemetry::Enabled() ? telemetry::NowUs() : 0;
      done_cv_.wait(lk, [&] {
        if (stop_) return true;
        if (!inflight_.empty()) {
          if (inflight_.front()->remaining == 0) return true;
          waited = true;
          return false;
        }
        if (eof_ || reader_error_ != nullptr) return true;
        waited = true;
        return false;
      });
      if (waited) {
        consumer_waits_.fetch_add(1, std::memory_order_relaxed);
        PipeTel().consumer_waits->Add(1);
        if (wait_from != 0) {
          const uint64_t waited_us = telemetry::NowUs() - wait_from;
          PipeTel().reassemble_wait_us->Observe(waited_us);
          telemetry::EmitSpan("parse.wait", wait_from, waited_us);
        }
      }
      if (!inflight_.empty() && inflight_.front()->remaining == 0) {
        current_ = inflight_.front();
        inflight_.pop_front();
      } else if (reader_error_ != nullptr) {
        // all chunks admitted before the failure were drained above — the
        // error surfaces exactly where the serial read would have died
        std::exception_ptr e = reader_error_;
        lk.unlock();
        failed_ = true;
        StopThreads();
        std::rethrow_exception(e);
      } else {
        return nullptr;  // eof (or stop)
      }
    }
    space_cv_.notify_one();  // popping the head freed an in-flight slot
  }
}

template <typename IndexType>
const RowBlockContainer<IndexType>* PipelinedParser<IndexType>::NextBlock() {
  return NextMutable();
}

template <typename IndexType>
bool PipelinedParser<IndexType>::NextBlockMove(
    RowBlockContainer<IndexType>* out) {
  RowBlockContainer<IndexType>* b = NextMutable();
  if (b == nullptr) return false;
  // swap hand-off: the recycled task slot keeps out's old buffer capacity
  std::swap(*out, *b);
  b->Clear();
  return true;
}

template <typename IndexType>
void PipelinedParser<IndexType>::BeforeFirst() {
  DCT_CHECK(!failed_)
      << "cannot restart a parse pipeline after a parse error";
  StopThreads();
  if (current_ != nullptr) {
    std::lock_guard<std::mutex> lk(mu_);
    free_.push_back(current_);
    current_ = nullptr;
  }
  eof_ = false;  // lock-ok: StopThreads joined every stage thread
  // the rewind reaches the split chain synchronously (shuffled splits
  // resample their permutation in BeforeFirst — see
  // PrefetchSplit::BeforeFirst for the same rule); threads respawn lazily
  // on the next NextBlock
  base_->BeforeFirst();
}

template <typename IndexType>
bool PipelinedParser<IndexType>::GetPipelineStats(
    ParsePipelineStats* out) const {
  out->chunks_read = chunks_read_.load(std::memory_order_relaxed);
  out->blocks_delivered = blocks_delivered_.load(std::memory_order_relaxed);
  out->reader_waits = reader_waits_.load(std::memory_order_relaxed);
  out->worker_waits = worker_waits_.load(std::memory_order_relaxed);
  out->consumer_waits = consumer_waits_.load(std::memory_order_relaxed);
  {
    std::lock_guard<std::mutex> lk(mu_);
    out->inflight_now = inflight_.size();
  }
  out->inflight_peak = inflight_peak_.load(std::memory_order_relaxed);
  out->inflight_sum = inflight_sum_.load(std::memory_order_relaxed);
  out->capacity = capacity_;
  out->workers = static_cast<uint64_t>(nworker_);
  out->simd_tier = static_cast<uint64_t>(base_->simd_tier());
  return true;
}

// --------------------------------------------------------------------------
template <typename IndexType>
Parser<IndexType>* Parser<IndexType>::Create(const std::string& uri,
                                             unsigned part, unsigned npart,
                                             const std::string& format,
                                             int nthread, bool threaded,
                                             int chunks_in_flight,
                                             const std::string& cache_dir,
                                             const std::string& cache_mode) {
  URISpec spec(uri, part, npart);
  std::string fmt = format;
  if (fmt == "auto" || fmt.empty()) {
    auto it = spec.args.find("format");
    if (it != spec.args.end()) {
      fmt = it->second;
    } else if (spec.uri.size() >= 4 &&
               spec.uri.compare(spec.uri.size() - 4, 4, ".rec") == 0) {
      fmt = "rec";  // binary row-block files are self-identifying by suffix
    } else {
      fmt = "libsvm";
    }
  }
  std::map<std::string, std::string> args = spec.args;
  args["format"] = fmt;
  // `?io_*=` resilience overrides (retry.h) apply to DIRECT filesystem
  // opens (streams, OpenForRead); the parser lane strips the query into
  // parser args before the filesystem ever sees it, so the knobs would be
  // silent no-ops here — and URI sugar a lane does not implement must
  // error, not no-op (stream.h RejectUnknownArgs rationale). Configure
  // parser-lane resilience through the DMLC_IO_* / per-backend env.
  for (const auto& kv : args) {
    if (kv.first.compare(0, 3, "io_") == 0) {
      throw Error("the parser lane does not support per-open `?" + kv.first +
                  "=` resilience overrides (they reach only direct stream "
                  "opens); set DMLC_IO_* / per-backend env knobs instead");
    }
  }
  // NOTE: the chunk-level CachedSplit is NOT layered here — the row-block
  // DiskCacheParser below caches the *parsed* data, and double-caching
  // would write the dataset to disk twice (reference disk_row_iter caches
  // only row blocks too)
  ParserFactoryReg<IndexType>* entry =
      Registry<ParserFactoryReg<IndexType>>::Get()->Find(fmt);
  if (entry == nullptr) {
    throw Error("unknown data format: " + fmt);
  }
  // binary row-block files partition on RecordIO magics, text on newlines
  const char* split_type = fmt == "rec" ? "recordio" : "text";
  // epoch shuffling rides URI sugar like #cachefile does
  // (reference input_split_shuffle.h exposes the same knob through
  // InputSplit::Create): `?shuffle_parts=K[&shuffle_seed=S]` subdivides
  // this part into K byte ranges visited in a freshly shuffled order each
  // epoch — the coarse-grained training shuffle
  // strict numeric parse: garbage must error, not silently disable the
  // shuffle; negative/huge values must not wrap into multi-GB state
  auto parse_uarg = [&](const char* key, long lo, long hi,
                        long dflt) -> long {
    auto it = spec.args.find(key);
    if (it == spec.args.end()) return dflt;
    const char* s = it->second.c_str();
    char* end = nullptr;
    const long v = std::strtol(s, &end, 10);
    DCT_CHECK(end != s && *end == '\0' && v >= lo && v <= hi)
        << "bad URI arg " << key << "=" << it->second << " (expected an "
        << "integer in [" << lo << ", " << hi << "])";
    return v;
  };
  const unsigned shuffle_parts = static_cast<unsigned>(
      parse_uarg("shuffle_parts", 0, 65536, 0));
  const int shuffle_seed = static_cast<int>(
      parse_uarg("shuffle_seed", 0, 1 << 30, 0));
  // a row-block cache replays the first epoch's PARSED order, which
  // would freeze (and fingerprint-ignore) the shuffle — same rule as
  // the split layer's own guard
  DCT_CHECK(shuffle_parts == 0 || spec.cache_file.empty())
      << "shuffle_parts cannot combine with #cachefile: the cache "
         "replays epoch 1's order and would silently disable the "
         "per-epoch reshuffle";
  // shard cache (shard_cache.h, doc/caching.md): explicit args > URI
  // sugar (#cachefile=<dir>, ?cache=) > env (DMLC_DATA_CACHE_DIR,
  // DMLC_DATA_CACHE)
  ShardCacheConfig ccfg = ShardCacheConfig::Resolve(
      spec.cache_dir, GetArg(spec.args, "cache", ""), cache_dir, cache_mode);
  if (ccfg.enabled() && !spec.cache_file.empty()) {
    // same env-vs-explicit rule as the shuffle_parts guard below: an
    // explicit double opt-in is a contradiction and must error, but a
    // process-wide DMLC_DATA_CACHE_DIR must not break a job already
    // using the legacy cache — the legacy cache wins for this parser
    DCT_CHECK(!ccfg.explicit_opt_in)
        << "pass either the legacy `#<path>` row-block cache or the "
           "`#cachefile=<dir>` shard cache, not both";
    ccfg.dir.clear();
  }
  if (ccfg.enabled() && shuffle_parts != 0) {
    // the shard cache replays epoch 1's parsed order, like the legacy
    // cache above. An explicit opt-in conflicting with shuffling must
    // error (URI sugar never silently no-ops); a process-wide
    // DMLC_DATA_CACHE_DIR, though, must not break unrelated shuffled
    // lanes — shuffling wins and the cache stands down for this parser.
    DCT_CHECK(!ccfg.explicit_opt_in)
        << "?shuffle_parts= cannot combine with the shard cache: the "
           "cache replays epoch 1's order and would silently disable "
           "the per-epoch reshuffle";
    ccfg.dir.clear();
  }

  // `?index=1` (the conventional <uri>.idx) or `?index=<path>` switches a
  // rec stream onto the indexed_recordio splitter: record-count
  // partitioning plus EXACT per-epoch record shuffling with `?shuffle=1`
  // (reference indexed_recordio_split.h; index written by
  // build_recordio_index, dmlc_core_tpu/io/convert.py)
  std::string index_uri;
  {
    auto it = spec.args.find("index");
    if (it != spec.args.end()) {
      DCT_CHECK(fmt == "rec")
          << "?index= applies to the rec binary format only";
      DCT_CHECK(shuffle_parts == 0)
          << "?index= (exact record shuffle) and ?shuffle_parts= (coarse "
             "byte-range shuffle) are alternatives; pass one";
      DCT_CHECK(spec.cache_file.empty())
          << "?index= cannot combine with #cachefile (the cache replays "
             "epoch 1's order)";
      if (ccfg.enabled()) {
        // same env-vs-explicit rule as the shuffle_parts guard above
        DCT_CHECK(!ccfg.explicit_opt_in)
            << "?index= cannot combine with the shard cache (the cache "
               "replays epoch 1's order)";
        ccfg.dir.clear();
      }
      index_uri = it->second == "1" ? spec.uri + ".idx" : it->second;
    }
  }
  // pipeline depth knob rides the same URI sugar so batcher/device lanes
  // (which reach Create through their own C-ABI entry points) can tune it
  // without a signature change
  const int uri_cif = static_cast<int>(
      parse_uarg("chunks_in_flight", 0, 1024, 0));
  if (uri_cif > 0) chunks_in_flight = uri_cif;
  const bool rec_shuffle = parse_uarg("shuffle", 0, 1, 0) != 0;
  DCT_CHECK(!rec_shuffle || !index_uri.empty())
      << "?shuffle=1 needs ?index= (exact shuffling walks the record "
         "index); for index-less streams use ?shuffle_parts=";
  DCT_CHECK(spec.args.count("shuffle_batch") == 0 || !index_uri.empty())
      << "?shuffle_batch= applies to indexed streams only (pass ?index=); "
         "it would otherwise be silently ignored";
  const size_t shuffle_batch = static_cast<size_t>(
      parse_uarg("shuffle_batch", 1, 1 << 20, 256));

  // The pipelined parser's reader thread IS the prefetch stage, so layering
  // PrefetchSplit under it would only add a second copy of every chunk and
  // a thread hop (ReadChunk then fills task buffers directly through the
  // RecordChunkSource fast lane). The synchronous parser keeps the
  // prefetch wrapper — it is its only read/parse overlap.
  //
  // The base chain is a FACTORY so the shard-cache wrapper can defer it:
  // on a cache hit the whole epoch is an mmap replay and the source —
  // including any remote filesystem open — is never touched.
  const bool split_threaded = !threaded;
  const std::string base_uri = spec.uri;
  auto build_base = [base_uri, part, npart, split_type, index_uri,
                     rec_shuffle, shuffle_seed, shuffle_batch,
                     split_threaded, shuffle_parts, entry, args, nthread,
                     threaded, chunks_in_flight]() -> Parser<IndexType>* {
    InputSplit* split =
        index_uri.empty()
            ? InputSplit::Create(base_uri, part, npart, split_type, "",
                                 false, shuffle_seed, 256, false,
                                 split_threaded, "", shuffle_parts)
            : InputSplit::Create(base_uri, part, npart, "indexed_recordio",
                                 index_uri, rec_shuffle, shuffle_seed,
                                 shuffle_batch, false, split_threaded, "");
    // ownership of split passes into the parser's base immediately; a
    // throwing constructor body unwinds through the already-built base,
    // which frees it
    TextParserBase<IndexType>* parser = entry->body(split, args, nthread);
    return threaded ? static_cast<Parser<IndexType>*>(
                          new PipelinedParser<IndexType>(parser,
                                                         chunks_in_flight))
                    : parser;
  };
  if (ccfg.enabled()) {
    const std::string key = ShardCacheKeyText(
        spec.uri, part, npart, fmt, sizeof(IndexType) == 8, spec.args);
    return new ShardCacheParser<IndexType>(
        build_base, ccfg, ShardCacheStem(ccfg.dir, key, part, npart), key);
  }
  Parser<IndexType>* out = build_base();
  if (!spec.cache_file.empty()) {
    std::string fingerprint = spec.uri + "|" + std::to_string(part) + "|" +
                              std::to_string(npart) + "|" + fmt + "|dtype=" +
                              GetArg(spec.args, "dtype", "float32");
    out = new DiskCacheParser<IndexType>(out, spec.cache_file + ".rowblock",
                                         fingerprint);
  }
  return out;
}

// explicit instantiations (reference data.cc:224-256 registers
// {uint32, uint64} index types)
template class TextParserBase<uint32_t>;
template class TextParserBase<uint64_t>;
template class LibSVMParser<uint32_t>;
template class LibSVMParser<uint64_t>;
template class CSVParser<uint32_t>;
template class CSVParser<uint64_t>;
template class LibFMParser<uint32_t>;
template class LibFMParser<uint64_t>;
template class RecParser<uint32_t>;
template class RecParser<uint64_t>;
template class PipelinedParser<uint32_t>;
template class PipelinedParser<uint64_t>;
template class DiskCacheParser<uint32_t>;
template class DiskCacheParser<uint64_t>;
template class Parser<uint32_t>;
template class Parser<uint64_t>;

// -- format registrations (reference DMLC_REGISTER_DATA_PARSER instantiated
//    for both index widths, data.cc:224-256) ------------------------------
namespace {

template <typename IndexType>
void RegisterBuiltinParsers() {
  using Map = std::map<std::string, std::string>;
  auto* reg = Registry<ParserFactoryReg<IndexType>>::Get();
  reg->__REGISTER__("libsvm")
      .describe("sparse `label[:weight] [qid:n] index[:value]...` text rows")
      .add_arguments(LibSVMParserParam::__FIELDS__())
      .set_body([](InputSplit* s, const Map& args, int nthread) {
        return new LibSVMParser<IndexType>(s, args, nthread);
      });
  reg->__REGISTER__("csv")
      .describe("dense delimited rows; label/weight columns, typed values")
      .add_arguments(CSVParserParam::__FIELDS__())
      .set_body([](InputSplit* s, const Map& args, int nthread) {
        return new CSVParser<IndexType>(s, args, nthread);
      });
  reg->__REGISTER__("libfm")
      .describe("`label[:weight] field:feature:value...` factorization rows")
      .add_arguments(LibFMParserParam::__FIELDS__())
      .set_body([](InputSplit* s, const Map& args, int nthread) {
        return new LibFMParser<IndexType>(s, args, nthread);
      });
  reg->__REGISTER__("rec")
      .describe("binary RecordIO-framed row blocks (rows_to_recordio)")
      .set_body([](InputSplit* s, const Map& args, int nthread) {
        return new RecParser<IndexType>(s, args, nthread);
      });
}

struct BuiltinParserRegistrar {
  BuiltinParserRegistrar() {
    RegisterBuiltinParsers<uint32_t>();
    RegisterBuiltinParsers<uint64_t>();
  }
} builtin_parser_registrar;

}  // namespace

}  // namespace dct
