// HTTP client implementation (see http.h).
#include "http.h"

#include <fcntl.h>
#include <netdb.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <cctype>
#include <cerrno>
#include <cstdlib>
#include <cstring>
#include <mutex>

#include "retry.h"
#include "telemetry.h"

namespace dct {

namespace {
std::string Lower(std::string s) {
  for (char& c : s) c = static_cast<char>(tolower(c));
  return s;
}

// StatusThrower for the fault-injection hook: retry.h stays independent of
// http.h, so the 5xx fault kind throws through this adapter.
[[noreturn]] void ThrowHttpStatus(const std::string& what, int status) {
  throw HttpStatusError(what, status);
}

// Block until fd is ready for `events` or the per-attempt I/O timeout
// (retry.h IoTimeoutMs) expires — the expiry surfaces as a retryable
// TimeoutError instead of the unbounded block a hung peer used to cause.
void WaitFdReady(int fd, short events, const char* what) {
  struct pollfd pfd;
  pfd.fd = fd;
  pfd.events = events;
  pfd.revents = 0;
  const int timeout_ms = io::IoTimeoutMs();
  int rc;
  do {
    rc = poll(&pfd, 1, timeout_ms);
  } while (rc < 0 && errno == EINTR);
  if (rc == 0) {
    io::GlobalIoStats().timeouts.fetch_add(1, std::memory_order_relaxed);
    throw TimeoutError(std::string("http ") + what + " timed out after " +
                       std::to_string(timeout_ms) + " ms");
  }
  DCT_CHECK(rc > 0) << "poll failed during http " << what << ": "
                    << std::strerror(errno);
}

// Non-blocking connect bounded by the I/O timeout; restores the fd to
// blocking mode on success. Sets *timed_out when the bound expired.
bool ConnectWithTimeout(int fd, const struct sockaddr* addr, socklen_t len,
                        bool* timed_out) {
  int flags = fcntl(fd, F_GETFL, 0);
  if (flags < 0 || fcntl(fd, F_SETFL, flags | O_NONBLOCK) != 0) return false;
  bool ok = false;
  if (connect(fd, addr, len) == 0) {
    ok = true;
  } else if (errno == EINPROGRESS) {
    struct pollfd pfd;
    pfd.fd = fd;
    pfd.events = POLLOUT;
    pfd.revents = 0;
    int rc;
    do {
      rc = poll(&pfd, 1, io::IoTimeoutMs());
    } while (rc < 0 && errno == EINTR);
    if (rc == 0) {
      *timed_out = true;
    } else if (rc > 0) {
      int err = 0;
      socklen_t elen = sizeof(err);
      ok = getsockopt(fd, SOL_SOCKET, SO_ERROR, &err, &elen) == 0 &&
           err == 0;
    }
  }
  if (ok && fcntl(fd, F_SETFL, flags) != 0) ok = false;
  return ok;
}

int ConnectSocket(const std::string& host, int port) {
  struct addrinfo hints;
  std::memset(&hints, 0, sizeof(hints));
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  struct addrinfo* res = nullptr;
  std::string port_str = std::to_string(port);
  int rc = getaddrinfo(host.c_str(), port_str.c_str(), &hints, &res);
  if (rc != 0) {
    const std::string what = "cannot resolve host " + host + ": " +
                             gai_strerror(rc);
    // EAI_AGAIN is a transient resolver hiccup worth retrying; anything
    // else (NXDOMAIN from a typo'd endpoint) is permanent — fail fast
    // instead of burning the whole backoff budget per request
    if (rc == EAI_AGAIN) throw Error(what);
    throw PermanentNetworkError(what);
  }
  int fd = -1;
  bool timed_out = false;
  for (struct addrinfo* ai = res; ai != nullptr; ai = ai->ai_next) {
    fd = socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
    if (fd < 0) continue;
    if (ConnectWithTimeout(fd, ai->ai_addr, ai->ai_addrlen, &timed_out)) {
      break;
    }
    close(fd);
    fd = -1;
  }
  freeaddrinfo(res);
  if (fd < 0 && timed_out) {
    io::GlobalIoStats().timeouts.fetch_add(1, std::memory_order_relaxed);
    throw TimeoutError("http connect to " + host + ":" +
                       std::to_string(port) + " timed out after " +
                       std::to_string(io::IoTimeoutMs()) + " ms");
  }
  DCT_CHECK(fd >= 0) << "cannot connect to " << host << ":" << port;
  return fd;
}
}  // namespace

HttpConnection::HttpConnection(const std::string& host, int port)
    : default_host_header_(port == 80 ? host
                                      : host + ":" + std::to_string(port)),
      io_hists_(telemetry::IoHistsFor("http")) {
  telemetry::ScopedTimerUs t(io_hists_->connect_us);
  fd_ = ConnectSocket(host, port);
}

HttpConnection::HttpConnection(const HttpRoute& route)
    : default_host_header_(route.host_header),
      path_prefix_(route.path_prefix),
      io_hists_(telemetry::IoHistsFor(route.backend)) {
  telemetry::ScopedTimerUs t(io_hists_->connect_us);
  fd_ = ConnectSocket(route.connect_host, route.connect_port);
}

HttpConnection::~HttpConnection() {
  if (fd_ >= 0) close(fd_);
}

void HttpConnection::SendRequest(
    const std::string& method, const std::string& path,
    const std::map<std::string, std::string>& headers,
    const std::string& body) {
  std::string req = method + " " + path_prefix_ + path + " HTTP/1.1\r\n";
  for (const auto& kv : headers) {
    req += kv.first + ": " + kv.second + "\r\n";
  }
  // HTTP/1.1 requires Host (RFC 7230); inject it when the caller did not
  // set one explicitly (signed clients like S3 pass their own).
  if (headers.find("Host") == headers.end() &&
      headers.find("host") == headers.end()) {
    req += "Host: " + default_host_header_ + "\r\n";
  }
  if (headers.find("content-length") == headers.end() &&
      headers.find("Content-Length") == headers.end() &&
      (!body.empty() || method == "PUT" || method == "POST")) {
    req += "Content-Length: " + std::to_string(body.size()) + "\r\n";
  }
  req += "Connection: close\r\n\r\n";
  req += body;
  // fault-injection hook: evaluated per outgoing request, below every mock
  // and every backend (retry.h DMLC_IO_FAULT_PLAN / dct_io_set_fault_plan)
  io::MaybeInjectFault(&ThrowHttpStatus);
  size_t sent = 0;
  while (sent < req.size()) {
    WaitFdReady(fd_, POLLOUT, "send");
    ssize_t n = send(fd_, req.data() + sent, req.size() - sent, 0);
    DCT_CHECK(n > 0) << "http send failed";
    sent += static_cast<size_t>(n);
  }
  // anchor for the time-to-first-header-byte span (ReadResponseHead)
  if (telemetry::Enabled()) {
    request_sent_us_ = telemetry::NowUs();
    ttfb_observed_ = false;
  }
}

size_t HttpConnection::RawRead(void* buf, size_t size) {
  if (rpos_ < rbuf_.size()) {
    size_t n = std::min(size, rbuf_.size() - rpos_);
    std::memcpy(buf, rbuf_.data() + rpos_, n);
    rpos_ += n;
    return n;
  }
  WaitFdReady(fd_, POLLIN, "recv");
  ssize_t n = recv(fd_, buf, size, 0);
  DCT_CHECK(n >= 0) << "http recv failed";
  return static_cast<size_t>(n);
}

bool HttpConnection::ReadLine(std::string* line) {
  line->clear();
  char c;
  while (true) {
    size_t n = RawRead(&c, 1);
    if (n == 0) return !line->empty();
    if (c == '\n') {
      if (!line->empty() && line->back() == '\r') line->pop_back();
      return true;
    }
    line->push_back(c);
  }
}

void HttpConnection::ReadResponseHead(HttpResponse* out) {
  std::string line;
  DCT_CHECK(ReadLine(&line)) << "empty http response";
  // first response bytes are in: observe time-to-first-byte once per request
  if (!ttfb_observed_ && request_sent_us_ != 0 && telemetry::Enabled()) {
    ttfb_observed_ = true;
    io_hists_->ttfb_us->Observe(telemetry::NowUs() - request_sent_us_);
  }
  // "HTTP/1.1 200 OK" — checked parse (analyze.py env rule): a garbled
  // status line is a transport error the retry layer should see, not a
  // silent status 0
  size_t sp = line.find(' ');
  DCT_CHECK(sp != std::string::npos) << "bad http status line: " << line;
  char* status_end = nullptr;
  long status = std::strtol(line.c_str() + sp + 1, &status_end, 10);
  DCT_CHECK(status_end != line.c_str() + sp + 1 && status >= 100 &&
            status <= 599)
      << "bad http status line: " << line;
  out->status = static_cast<int>(status);
  while (ReadLine(&line) && !line.empty()) {
    size_t colon = line.find(':');
    if (colon == std::string::npos) continue;
    std::string key = Lower(line.substr(0, colon));
    size_t vstart = line.find_first_not_of(' ', colon + 1);
    out->headers[key] =
        vstart == std::string::npos ? "" : line.substr(vstart);
  }
  auto it = out->headers.find("content-length");
  if (it != out->headers.end()) {
    char* cl_end = nullptr;
    errno = 0;  // strtoll reports overflow via ERANGE + LLONG_MAX,
                // which would otherwise pass the >= 0 check below
    body_remaining_ = std::strtoll(it->second.c_str(), &cl_end, 10);
    DCT_CHECK(cl_end != it->second.c_str() && errno != ERANGE &&
              body_remaining_ >= 0)
        << "bad content-length: " << it->second;
  }
  auto te = out->headers.find("transfer-encoding");
  chunked_ = te != out->headers.end() &&
             Lower(te->second).find("chunked") != std::string::npos;
}

size_t HttpConnection::ReadBody(void* buf, size_t size) {
  if (body_done_) return 0;
  // one span per body pull (~16-64 KB granularity — two clock reads per
  // call, never per byte); both branches below RawRead inside it
  telemetry::ScopedTimerUs recv_span(io_hists_->recv_us);
  if (chunked_) {
    if (chunk_remaining_ == 0) {
      std::string line;
      DCT_CHECK(ReadLine(&line)) << "truncated chunked body";
      chunk_remaining_ = std::strtoll(line.c_str(), nullptr, 16);
      if (chunk_remaining_ == 0) {
        ReadLine(&line);  // trailing CRLF / trailers
        body_done_ = true;
        return 0;
      }
    }
    size_t want = std::min<size_t>(size, chunk_remaining_);
    size_t n = RawRead(buf, want);
    DCT_CHECK(n > 0) << "truncated chunk";
    chunk_remaining_ -= n;
    if (chunk_remaining_ == 0) {
      std::string line;
      ReadLine(&line);  // chunk-terminating CRLF
    }
    return n;
  }
  if (body_remaining_ == 0) {
    body_done_ = true;
    return 0;
  }
  size_t want = size;
  if (body_remaining_ > 0) {
    want = std::min<size_t>(size, body_remaining_);
  }
  size_t n = RawRead(buf, want);
  if (body_remaining_ > 0) {
    body_remaining_ -= n;
    if (n == 0) {
      throw Error("http body shorter than content-length");
    }
  } else if (n == 0) {
    body_done_ = true;  // read-to-close
  }
  return n;
}

void HttpConnection::ReadFullBody(HttpResponse* out) {
  char buf[16384];
  while (true) {
    size_t n = ReadBody(buf, sizeof(buf));
    if (n == 0) break;
    out->body.append(buf, n);
  }
}

namespace {

int ParsePortOrDie(const std::string& where, const std::string& text) {
  DCT_CHECK(!text.empty() && text.size() <= 5)
      << "invalid port '" << text << "' in '" << where << "'";
  long v = 0;
  for (char c : text) {
    DCT_CHECK(isdigit(static_cast<unsigned char>(c)))
        << "invalid port '" << text << "' in '" << where << "'";
    v = v * 10 + (c - '0');
  }
  DCT_CHECK(v >= 1 && v <= 65535)
      << "port " << v << " out of range (1-65535) in '" << where << "'";
  return static_cast<int>(v);
}

}  // namespace

void SplitHostPort(const std::string& s, std::string* host, int* port,
                   int default_port) {
  *host = s;
  *port = default_port;
  if (!s.empty() && s.front() == '[') {
    size_t close = s.find(']');
    DCT_CHECK(close != std::string::npos) << "unterminated [v6] host: " << s;
    *host = s.substr(1, close - 1);
    if (close + 1 < s.size()) {
      DCT_CHECK(s[close + 1] == ':')
          << "unexpected trailing text after [v6] host: " << s;
      *port = ParsePortOrDie(s, s.substr(close + 2));
    }
    return;
  }
  size_t colon = s.find(':');
  if (colon == std::string::npos || s.rfind(':') != colon) {
    return;  // no port, or bare IPv6 literal
  }
  *host = s.substr(0, colon);
  *port = ParsePortOrDie(s, s.substr(colon + 1));
}

std::string DefaultHostHeader(const std::string& scheme,
                              const std::string& host, int port) {
  bool is_default = scheme == "https" ? port == 443 : port == 80;
  return is_default ? host : host + ":" + std::to_string(port);
}

std::string StripUrlScheme(std::string* s) {
  size_t pos = s->find("://");
  if (pos == std::string::npos) return "";
  std::string scheme = s->substr(0, pos);
  DCT_CHECK(scheme == "http" || scheme == "https")
      << "endpoint scheme must be http or https, got " << *s;
  s->erase(0, pos + 3);
  return scheme;
}

// Explicitly published TLS-helper address (dct_set_tls_proxy). Reading the
// DCT_TLS_PROXY env per request raced the Python side's setenv (glibc
// getenv/setenv are not thread-safe against each other; request threads
// crashed mid-scan when the io facade auto-started its helper), so the
// binding now pushes the address through this mutex-guarded global and the
// env is only the operator-configured fallback, set before any native
// thread exists.
namespace {
std::mutex g_tls_proxy_mu;
std::string g_tls_proxy_override DMLC_GUARDED_BY(g_tls_proxy_mu);
}  // namespace

void SetTlsProxyOverride(const std::string& addr) {
  std::lock_guard<std::mutex> lk(g_tls_proxy_mu);
  g_tls_proxy_override = addr;
}

std::string TlsProxyAddress() {
  {
    std::lock_guard<std::mutex> lk(g_tls_proxy_mu);
    if (!g_tls_proxy_override.empty()) return g_tls_proxy_override;
  }
  const char* proxy = std::getenv("DCT_TLS_PROXY");
  return proxy == nullptr ? "" : proxy;
}

HttpRoute ResolveHttpRoute(const std::string& scheme, const std::string& host,
                           int port, const std::string& backend) {
  HttpRoute r;
  r.backend = backend;
  r.host_header = DefaultHostHeader(scheme, host, port);
  if (scheme != "https") {
    r.connect_host = host;
    r.connect_port = port;
    return r;
  }
  const std::string proxy = TlsProxyAddress();
  if (proxy.empty()) {
    throw Error(
        "https origin but the built-in client is plain-HTTP and "
        "DCT_TLS_PROXY is unset. Start the TLS-terminating helper "
        "(python -m dmlc_core_tpu.io.tls_proxy) and export "
        "DCT_TLS_PROXY=host:port, or route the object through http:// / "
        "an S3-compatible endpoint: https://" + r.host_header);
  }
  SplitHostPort(proxy, &r.connect_host, &r.connect_port, 3128);
  r.path_prefix = "https://" + r.host_header;
  return r;
}

HttpResponse HttpRequest(const std::string& host, int port,
                         const std::string& method, const std::string& path,
                         const std::map<std::string, std::string>& headers,
                         const std::string& body) {
  HttpConnection conn(host, port);
  conn.SendRequest(method, path, headers, body);
  HttpResponse resp;
  conn.ReadResponseHead(&resp);
  conn.ReadFullBody(&resp);
  return resp;
}

HttpResponse HttpRequest(const HttpRoute& route, const std::string& method,
                         const std::string& path,
                         const std::map<std::string, std::string>& headers,
                         const std::string& body) {
  HttpConnection conn(route);
  conn.SendRequest(method, path, headers, body);
  HttpResponse resp;
  conn.ReadResponseHead(&resp);
  conn.ReadFullBody(&resp);
  return resp;
}

}  // namespace dct
