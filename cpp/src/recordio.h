// RecordIO: splittable binary record format.
//
// On-disk format is byte-compatible with reference include/dmlc/recordio.h:
//   [magic:u32le = 0xced7230a][lrecord:u32le][payload][pad to 4]
//   lrecord = (cflag << 29) | payload_len,  cflag: 0=whole record,
//   1=first part, 2=middle part, 3=last part.
// A payload containing the 4-byte magic pattern at a 4-aligned offset is
// split there into parts; the magic itself is elided on disk and re-inserted
// between parts on read (reference src/recordio.cc:11-51 escape scheme).
// This keeps every on-disk aligned magic word an unambiguous resync point,
// which is what lets byte-range splitters start mid-file.
//
// Implementation is original: a part-iterator (NextPartBoundary) drives the
// writer, and both readers share ReadParts.
#ifndef DCT_RECORDIO_H_
#define DCT_RECORDIO_H_

#include <cstring>
#include <string>

#include "serializer.h"
#include "stream.h"

namespace dct {

namespace recordio {
constexpr uint32_t kMagic = 0xced7230a;
// note (reference recordio.h:44): kMagic's top 3 bits decode to cflag > 3,
// so an lrecord word can never equal kMagic.

constexpr uint32_t EncodeHeader(uint32_t cflag, uint32_t len) {
  return (cflag << 29) | len;
}
constexpr uint32_t HeaderFlag(uint32_t lrec) { return (lrec >> 29) & 7u; }
constexpr uint32_t HeaderLen(uint32_t lrec) { return lrec & ((1u << 29) - 1); }
constexpr size_t AlignUp4(size_t n) { return (n + 3) & ~size_t(3); }

// host_is_le parameterization (defaulting to the real host) lets the
// big-endian decode branch run under test on an LE host — the QEMU-free
// equivalent of the reference's s390x lane (scripts/test_script.sh:60-65),
// same discipline as serial::ToDisk/FromDisk.
inline uint32_t LoadWordAs(const char* p, bool host_is_le) {
  uint32_t w;
  std::memcpy(&w, p, 4);
  if (!host_is_le) w = serial::ByteSwap(w);
  return w;
}

inline uint32_t LoadWordLE(const char* p) {
  return LoadWordAs(p, serial::NativeIsLE());
}

// Bulk little-endian 32-bit-word copy shared by the binary record lanes
// (dense_rec labels/weights, csr_rec planes): memcpy, then elementwise
// swap on big-endian hosts.
inline void CopyWords32LE(void* dst, const void* src, uint64_t n,
                          bool host_is_le = serial::NativeIsLE()) {
  std::memcpy(dst, src, n * 4);
  if (!host_is_le) {
    uint32_t u;
    char* d = static_cast<char*>(dst);
    for (uint64_t i = 0; i < n; ++i) {
      std::memcpy(&u, d + i * 4, 4);
      u = serial::ByteSwap(u);
      std::memcpy(d + i * 4, &u, 4);
    }
  }
}

inline uint64_t LoadU64As(const char* p, bool host_is_le) {
  uint64_t v;
  std::memcpy(&v, p, 8);
  if (!host_is_le) v = serial::ByteSwap(v);
  return v;
}

inline uint64_t LoadU64LE(const char* p) {
  return LoadU64As(p, serial::NativeIsLE());
}

// True when [p, p+8) looks like a record head (magic + cflag 0|1) — the
// resync predicate of reference src/recordio.cc FindNextRecordIOHead.
inline bool IsRecordHead(const char* p) {
  if (LoadWordLE(p) != kMagic) return false;
  uint32_t flag = HeaderFlag(LoadWordLE(p + 4));
  return flag == 0 || flag == 1;
}
}  // namespace recordio

class RecordIOWriter {
 public:
  explicit RecordIOWriter(Stream* stream) : stream_(stream) {}

  // Write one record (< 2^29 bytes), escaping embedded aligned magics.
  void WriteRecord(const void* buf, size_t size);
  void WriteRecord(const std::string& s) { WriteRecord(s.data(), s.size()); }

  // number of embedded-magic escapes performed (reference except_counter)
  size_t escape_count() const { return escape_count_; }

 private:
  Stream* stream_;
  size_t escape_count_ = 0;
};

class RecordIOReader {
 public:
  explicit RecordIOReader(Stream* stream) : stream_(stream) {}
  // Read the next record into *out; false at end of stream. A truncated
  // or corrupt frame throws a structured Error naming the record index
  // and byte offset (never a silent short record) — local-disk EIO below
  // this surfaces as fsio::FsError from the stream itself (filesys.cc).
  bool NextRecord(std::string* out);

 private:
  Stream* stream_;
  bool eof_ = false;
  uint64_t records_ = 0;     // completed records (error context)
  uint64_t bytes_in_ = 0;    // bytes consumed (error context)
};

// Sub-partitions an in-memory chunk of recordio bytes for multithreaded
// parsing (reference recordio.h:166 RecordIOChunkReader): part boundaries are
// byte ranges resynced forward to the next record head.
class RecordIOChunkReader {
 public:
  struct Blob {
    const void* dptr;
    size_t size;
  };
  RecordIOChunkReader(const char* begin, const char* end, unsigned part_index,
                      unsigned num_parts);
  // out points into the chunk for single-part records, or into an internal
  // buffer for reassembled multi-part records.
  bool NextRecord(Blob* out);

 private:
  const char* cur_;
  const char* end_;
  std::string assembled_;
};

// Scan [begin, end) for the first record head at/after begin (4-aligned
// offsets relative to `base`, which must be record-aligned).
const char* FindRecordHead(const char* base, const char* begin,
                           const char* end);

}  // namespace dct

#endif  // DCT_RECORDIO_H_
