// Shared object-store listing structures and the file-vs-directory probe.
//
// S3 and Azure both expose flat key namespaces where "directories" are an
// illusion over a delimiter; deciding whether a path is a file, a virtual
// directory, or absent requires the same careful probe in both (exact-key
// match, then children strictly under "<name>/" — a key that merely shares
// the string prefix must not count — with a second scoped list when the
// first page may have been truncated by sibling keys). The algorithm lives
// here once, parameterized on the backend's one-page list call.
#ifndef DCT_LISTING_H_
#define DCT_LISTING_H_

#include <functional>
#include <string>
#include <vector>

#include "filesys.h"

namespace dct {

struct ListedObject {
  std::string name;  // full key/blob name, XML-unescaped
  size_t size = 0;
};

struct ListedPage {
  std::vector<ListedObject> objects;   // delimiter-terminal entries
  std::vector<std::string> prefixes;   // common prefixes (with trailing '/')
};

// One delimiter="/" list request scoped to `prefix` (first page only).
using ListPageFn = std::function<ListedPage(const std::string& prefix)>;

// Resolve `path` (whose key/blob name is `name`, no leading '/') to a
// FileInfo via the backend's list call; throws Error("<backend> path does
// not exist: ...") when neither a file nor a virtual directory.
inline FileInfo ProbePathInfo(const URI& path, const std::string& name,
                              const ListPageFn& list_page,
                              const char* backend) {
  ListedPage page = list_page(name);
  // empty name = container/bucket root: any content makes it a directory
  std::string dir_prefix =
      (name.empty() || name.back() == '/') ? name : name + "/";
  bool is_dir = false;
  for (const ListedObject& obj : page.objects) {
    if (obj.name == name) {
      FileInfo info;
      info.path = path;
      info.size = obj.size;
      info.type = FileType::kFile;
      return info;
    }
    if (obj.name.compare(0, dir_prefix.size(), dir_prefix) == 0) {
      is_dir = true;
    }
  }
  for (const std::string& p : page.prefixes) {
    if (p == dir_prefix) is_dir = true;
  }
  if (!is_dir && dir_prefix != name) {
    // The first page was scoped to `name` and may have been truncated by
    // sibling keys sorting before '/' (e.g. 1000+ "data-*" keys hiding
    // "data/..."). Probe under "<name>/" directly — any result means the
    // directory exists.
    ListedPage deep = list_page(dir_prefix);
    is_dir = !deep.objects.empty() || !deep.prefixes.empty();
  }
  if (is_dir) {
    FileInfo info;
    info.path = path;
    info.size = 0;
    info.type = FileType::kDirectory;
    return info;
  }
  throw Error(std::string(backend) + " path does not exist: " + path.Str());
}

}  // namespace dct

#endif  // DCT_LISTING_H_
