// SIMD text-ingest engine (doc/parsing.md).
//
// Two stages, after simdjson's design (Langdale & Lemire, "Parsing
// Gigabytes of JSON per Second") adapted to what actually measures faster
// on ML text formats:
//
// STAGE 1 — structural scan. Kernels classify 64-byte blocks — two
// 32-byte AVX2 compares, four SSE2 compares, or eight 64-bit SWAR loads —
// into bitmask planes:
//   eol    '\n' | '\r'            (row boundaries)
//   sep    ':' or the csv delimiter (token/cell boundaries)
//   blank  ' ' | '\t'             (token separators; disabled for csv)
//   digit  '0'..'9'               (digit-run extents)
// The production parsers run the count-only form (CountSepEol) per chunk:
// popcount(sep) bounds nnz and popcount(eol)+1 bounds rows, so every
// RowBlockContainer vector reserves once instead of realloc-churning. The
// full tape (ScanTape + StructCursor + DigitRunAt) is the same kernels
// with the masks materialized — the structural index a tape-walking
// stage 2 would consume, kept as the engine's API and cross-checked
// against scalar classification by test_core --parse on every tier.
//
// STAGE 2 — fused field decode (the primitives further down). Measured on
// the bench host, walking the bit tape per TOKEN loses: the scalar
// parsers' byte loops are branch-predictable and already fuse
// tokenization into decoding, so a separate positional walk pays twice.
// What wins is fusing the DECODE — classifying and folding whole fields
// from one or two 8-byte loads (DigitRunLen8/DigitRunValue8) instead of
// per-character loops. parser.cc instantiates ONE tokenizer per format
// twice: kFused=false IS the scalar lane, kFused=true swaps in these
// primitives, which only accept shapes whose value AND consumption
// provably equal the scalar ops' — byte-identical lanes by construction
// (tests/test_parse_simd.py and test_core --parse pin it).
//
// Tier selection is runtime: CPUID picks AVX2 > SSE2 on x86, the 64-bit
// SWAR kernels cover everything little-endian, and big-endian hosts (or
// DMLC_PARSE_SIMD=0, the kill switch) keep the scalar parsers.
#ifndef DCT_SIMD_SCAN_H_
#define DCT_SIMD_SCAN_H_

#include <cstdint>
#include <cstring>
#include <vector>

#include "numparse.h"

namespace dct {

// Dispatch tiers, ordered by preference. The numeric values are stable:
// they ride the C ABI (dct_parse_pipeline_stats_t.simd_tier) and the
// DMLC_PARSE_SIMD override env understood by bench/CI lanes.
enum SimdTier {
  kSimdScalar = 0,  // byte-at-a-time parsers, no tape
  kSimdSWAR = 1,    // 64-bit SWAR blocks (any little-endian CPU)
  kSimdSSE2 = 2,    // 16-byte blocks (x86-64 baseline)
  kSimdAVX2 = 3,    // 32-byte blocks (runtime CPUID)
};

// Best tier this CPU supports (CPUID probed once, cached).
SimdTier BestSupportedSimdTier();

// Tier for a parser constructed NOW: DMLC_PARSE_SIMD env, clamped to
// hardware support. "0"/"off"/"scalar" force the scalar lane; "swar",
// "sse2", "avx2" pin a tier (clamped down if unsupported); unset/""/"1"/
// "auto" pick BestSupportedSimdTier(). Read per call (not cached) so a
// process can flip lanes between parser constructions — the differential
// tests rely on that.
SimdTier ResolveSimdTier();

const char* SimdTierName(int tier);

// --------------------------------------------------------------------------
// The structural index tape: four bitmask planes, bit i of word w
// classifying byte base[w*64 + i]. Planes:
//   all_    any structural (blank | sep | eol) — the token-end scan plane
//   sep_    ':' (libsvm/libfm) or the csv delimiter
//   eol_    '\n' | '\r'
//   digit_  '0'..'9'
// blank is implied: all_ & ~sep_ & ~eol_.
class ScanTape {
 public:
  // Classify [begin, end). blank0/blank1 are the blank-class chars (pass
  // '\0' for both to disable the class — csv), sep is the single separator
  // char. tier must be > kSimdScalar.
  void Build(const char* begin, const char* end, char blank0, char blank1,
             char sep, SimdTier tier);

  size_t size() const { return size_; }
  // reserve hints
  size_t sep_count() const { return n_sep_; }
  size_t eol_count() const { return n_eol_; }

  // kinds returned by the structural scans below
  enum Kind : uint32_t { kBlank = 0, kSep = 1, kEol = 2, kNone = 3 };

  // First structural position >= pos, or size() when none. *kind receives
  // the class of the found byte (kNone at end).
  size_t NextStructural(size_t pos, Kind* kind) const {
    size_t w = pos >> 6;
    const size_t nw = words_;
    if (w >= nw) {
      *kind = kNone;
      return size_;
    }
    uint64_t m = all_[w] & (~0ull << (pos & 63));
    while (m == 0) {
      if (++w >= nw) {
        *kind = kNone;
        return size_;
      }
      m = all_[w];
    }
    const size_t hit = (w << 6) + static_cast<size_t>(__builtin_ctzll(m));
    *kind = KindAt(hit, w);
    return hit;
  }

  // Class of the structural byte at pos (caller guarantees the all_ bit).
  Kind KindAt(size_t pos, size_t w) const {
    const uint64_t bit = 1ull << (pos & 63);
    if (eol_[w] & bit) return kEol;
    if (sep_[w] & bit) return kSep;
    return kBlank;
  }
  Kind KindOf(size_t pos) const { return KindAt(pos, pos >> 6); }
  size_t words() const { return words_; }
  const uint64_t* all_words() const { return all_.data(); }
  const uint64_t* sep_words() const { return sep_.data(); }
  const uint64_t* eol_words() const { return eol_.data(); }
  bool IsStructural(size_t pos) const {
    return (all_[pos >> 6] >> (pos & 63)) & 1;
  }
  bool IsEol(size_t pos) const { return (eol_[pos >> 6] >> (pos & 63)) & 1; }
  bool IsSep(size_t pos) const { return (sep_[pos >> 6] >> (pos & 63)) & 1; }
  bool IsBlankKind(size_t pos) const {
    const size_t w = pos >> 6;
    const uint64_t bit = 1ull << (pos & 63);
    return (all_[w] & bit) && !((sep_[w] | eol_[w]) & bit);
  }

  // First EOL position >= pos, or size() (comment-line skipping).
  size_t NextEol(size_t pos) const {
    size_t w = pos >> 6;
    if (w >= words_) return size_;
    uint64_t m = eol_[w] & (~0ull << (pos & 63));
    while (m == 0) {
      if (++w >= words_) return size_;
      m = eol_[w];
    }
    return (w << 6) + static_cast<size_t>(__builtin_ctzll(m));
  }

  // Length of the digit run starting at pos, capped at `cap` (<= 64 - the
  // window the two-word load covers; token decoders need <= 20).
  int DigitRunAt(size_t pos, int cap) const {
    if (pos >= size_) return 0;
    const size_t w = pos >> 6;
    const unsigned o = pos & 63;
    uint64_t run = digit_[w] >> o;
    if (o != 0 && w + 1 < words_) run |= digit_[w + 1] << (64 - o);
    // trailing-ones count: first zero bit bounds the run
    const int len = run == ~0ull ? 64
                                 : static_cast<int>(__builtin_ctzll(~run));
    return len < cap ? len : cap;
  }

  // one block's classification lands here from whichever kernel ran
  // (public for the kernel functions in simd_scan.cc only)
  void PushBlock(uint64_t blank, uint64_t sep, uint64_t eol, uint64_t digit,
                 size_t w) {
    all_[w] = blank | sep | eol;
    sep_[w] = sep;
    eol_[w] = eol;
    digit_[w] = digit;
    n_sep_ += static_cast<size_t>(__builtin_popcountll(sep));
    n_eol_ += static_cast<size_t>(__builtin_popcountll(eol));
  }

 private:
  std::vector<uint64_t> all_, sep_, eol_, digit_;
  size_t size_ = 0;
  size_t words_ = 0;
  size_t n_sep_ = 0, n_eol_ = 0;
};

// --------------------------------------------------------------------------
// Streaming cursor over the structural bit stream: the current word's
// masks stay in registers, so advancing to the next structural is one
// ctz + clear-lowest-bit (plus a word refill every 64 bytes) instead of
// re-deriving word/bit state from a byte position per probe. The stage-2
// walkers are written against this: every structural byte is visited
// exactly once, in order, with its class.
class StructCursor {
 public:
  explicit StructCursor(const ScanTape& t)
      : all_(t.all_words()),
        sep_(t.sep_words()),
        eol_(t.eol_words()),
        nwords_(t.words()),
        size_(t.size()) {
    SeekTo(0);
  }

  size_t pos;          // position of the current structural; size() at end
  ScanTape::Kind kind; // its class; kNone at end

  // step past the current structural
  void Advance() {
    bits_ &= bits_ - 1;
    Settle();
  }

  // resync to the first structural >= p (fallback-row re-entry)
  void SeekTo(size_t p) {
    w_ = p >> 6;
    bits_ = w_ < nwords_ ? all_[w_] & (~0ull << (p & 63)) : 0;
    Settle();
  }

 private:
  void Settle() {
    while (bits_ == 0) {
      if (++w_ >= nwords_) {
        pos = size_;
        kind = ScanTape::kNone;
        return;
      }
      bits_ = all_[w_];
    }
    pos = (w_ << 6) + static_cast<size_t>(__builtin_ctzll(bits_));
    const uint64_t bit = bits_ & (~bits_ + 1);
    kind = (eol_[w_] & bit) ? ScanTape::kEol
           : (sep_[w_] & bit) ? ScanTape::kSep
                              : ScanTape::kBlank;
  }

  const uint64_t* all_;
  const uint64_t* sep_;
  const uint64_t* eol_;
  size_t nwords_, size_;
  size_t w_ = 0;
  uint64_t bits_ = 0;
};

// --------------------------------------------------------------------------
// Stage 2: fused SWAR field decoders.
//
// Measured on the bench host, walking the bit tape per TOKEN (a cursor
// advance per structural plus mask probes) costs more than it saves: the
// scalar parsers' byte loops are branch-predictable and fuse tokenization
// into decoding, so a separate walk pays twice. What does win is fusing
// the DECODE itself: one or two 8-byte loads classify and fold a whole
// field ([-]d+[.d+] or a feature id) with DigitRunLen8/DigitRunValue8
// instead of per-character loops. These primitives are drop-in
// replacements for the exact scalar ops (ParseNum / the inline digit
// loop) AT THE SAME CURSOR POSITION: whenever a fused primitive accepts,
// its value and consumption provably equal the scalar op's; whenever a
// shape is outside its envelope it declines and the caller runs the
// scalar op — so the fused and scalar parse lanes are byte-identical by
// construction, with no row re-parsing or rollback needed. The tape
// (ScanTape/StructCursor above) remains the structural engine: the
// production lane uses its counting kernels for reserve hints
// (CountSepEol), and the differential suites walk the full tape to
// cross-check every kernel tier.

// Count separator and newline/CR bytes in [begin, end) — the reserve-hint
// scan. Same classification kernels as ScanTape::Build, but pure popcount
// accumulation (no mask stores): sep bounds nnz, eol+1 bounds rows.
void CountSepEol(const char* begin, const char* end, char sep,
                 SimdTier tier, size_t* n_sep, size_t* n_eol);

// Scan a digit run starting at p: up to 15 digits via two guarded 8-byte
// loads, verified and folded in one pass. Returns the run length with the
// value in *v, 0 when p is not a digit (*v untouched), or kFusedOverflow
// when the run may extend past 15 digits or sits too close to load_end to
// load — the caller then delegates to its exact path (ParseNum /
// from_chars), which re-derives everything from p.
inline constexpr int kFusedOverflow = 99;

inline int FusedDigitScan(const char* p, const char* load_end, uint64_t* v) {
  // 1-2 digit ids dominate sparse ML data: settle them from byte probes
  // before any SWAR setup (two compares beat a load+classify there)
  const ptrdiff_t avail = load_end - p;
  if (avail <= 0 || !IsDigitChar(p[0])) return avail <= 0 ? kFusedOverflow
                                                          : 0;
  if (avail == 1 || !IsDigitChar(p[1])) {
    *v = static_cast<uint64_t>(p[0] - '0');
    return 1;
  }
  if (avail == 2 || !IsDigitChar(p[2])) {
    *v = static_cast<uint64_t>(p[0] - '0') * 10u +
         static_cast<uint64_t>(p[1] - '0');
    return 2;
  }
  if (!detail::kSwarLE || avail < 8) return kFusedOverflow;
  uint64_t c0;
  std::memcpy(&c0, p, 8);
  const int il = detail::DigitRunLen8(c0);
  if (il < 8) {
    *v = detail::DigitRunValue8(c0, il);  // il >= 3 here
    return il;
  }
  if (avail < 16) return kFusedOverflow;
  uint64_t c1;
  std::memcpy(&c1, p + 8, 8);
  const int fl = detail::DigitRunLen8(c1);
  if (fl >= 8) return kFusedOverflow;  // 16+ digits: exact path decides
  *v = fl != 0 ? detail::DigitRunValue8(c0, 8) * detail::kPow10U64[fl] +
                     detail::DigitRunValue8(c1, fl)
               : detail::DigitRunValue8(c0, 8);
  return 8 + fl;
}

// Fused float decode starting at p: finds its own end from the loaded
// words (like the scalar ParseFloatFast does from bytes) and covers the
// dominant ML shapes [-+]?D{1,7}(.D{1,7})? — sign, integer run, '.',
// fraction run, all measured by DigitRunLen8 on two 8-byte loads. Returns
// the first unconsumed byte, or nullptr for every other shape (exponents,
// 8+ digit runs, inf/nan/garbage, tokens too close to load_end): the
// caller then runs ParseNum from the SAME position. Acceptance is
// envelope-safe by construction (<= 14 digits, exponent >= -7, all inside
// ParseFloatFast's exact range) and the arithmetic below IS
// ParseFloatFast's — same mant, same exp10, same double ops — so fused
// and scalar decodes agree bit-for-bit (the differential suites pin it).
template <typename T>
inline const char* DecodeFloatAuto(const char* p, const char* load_end,
                                   T* v) {
  // caller guarantees p != load_end
  const bool neg = *p == '-';
  const char* s = p + (neg || *p == '+' ? 1 : 0);
  // room for the 2-digit byte probes plus the fraction's 8-byte load;
  // tokens closer to the chunk end than this take the exact path
  if (!detail::kSwarLE || load_end - s < 11) return nullptr;
  // integer part: byte probes for the dominant 0-2 digit case, one SWAR
  // gulp for longer runs
  uint64_t ipart;
  int il;
  if (!IsDigitChar(s[0])) {
    if (s[0] != '.') return nullptr;  // inf/nan/garbage: exact path
    il = 0;
    ipart = 0;
  } else if (!IsDigitChar(s[1])) {
    il = 1;
    ipart = static_cast<uint64_t>(s[0] - '0');
  } else if (!IsDigitChar(s[2])) {
    il = 2;
    ipart = static_cast<uint64_t>(s[0] - '0') * 10u +
            static_cast<uint64_t>(s[1] - '0');
  } else {
    uint64_t c0;
    std::memcpy(&c0, s, 8);
    il = detail::DigitRunLen8(c0);  // >= 3 here
    if (il >= 8) return nullptr;    // long integer part: exact path
    ipart = detail::DigitRunValue8(c0, il);
  }
  uint64_t mant;
  int fl = 0;
  const char* after;
  const char ci = s[il];
  if (ci == '.') {
    const char* f = s + il + 1;
    if (load_end - f < 8) return nullptr;
    uint64_t c1;
    std::memcpy(&c1, f, 8);
    fl = detail::DigitRunLen8(c1);
    if (fl == 0 || fl >= 8) return nullptr;  // "5." / long fraction
    const char ce = f[fl];
    if (ce == 'e' || ce == 'E') return nullptr;
    mant = ipart * detail::kPow10U64[fl] + detail::DigitRunValue8(c1, fl);
    after = f + fl;
  } else if (il == 0) {
    return nullptr;  // bare '.' — exact path decides consumption
  } else {
    if (ci == 'e' || ci == 'E') return nullptr;  // exponent: exact path
    mant = ipart;
    after = s + il;
  }
  double d = static_cast<double>(mant);
  if (fl != 0) d = d / detail::kPow10[fl];
  *v = static_cast<T>(neg ? -d : d);
  return after;
}

// ParseNum with the fused fast lane in front (compile-time selected):
// the scalar parse lanes instantiate kFused=false and get exactly the old
// ParseNum; the SIMD lanes instantiate kFused=true.
template <bool kFused, typename T>
inline bool ParseNumF(const char* p, const char* end, const char** out,
                      T* v) {
  if constexpr (kFused) {
    if (p != end) {
      if constexpr (std::is_floating_point_v<T>) {
        const char* after = DecodeFloatAuto(p, end, v);
        if (after != nullptr) {
          *out = after;
          return true;
        }
      } else {
        // integral ids/cells (qid, libfm fields, csv int dtypes): digit
        // budgets that can never overflow T (9 digits < 2^31, 15 < 2^50);
        // longer runs, '+' signs, and chunk-end tails take the exact path
        const bool sneg = std::is_signed_v<T> && *p == '-';
        const char* q = p + (sneg ? 1 : 0);
        if (q != end && IsDigitChar(*q)) {
          constexpr int kSafe = sizeof(T) == 8 ? 15 : 9;
          uint64_t val;
          const int il = FusedDigitScan(q, end, &val);
          if (il >= 1 && il <= kSafe) {
            const int64_t sv =
                sneg ? -static_cast<int64_t>(val) : static_cast<int64_t>(val);
            *v = static_cast<T>(sv);
            *out = q + il;
            return true;
          }
        }
      }
    }
  }
  return ParseNum<T>(p, end, out, v);
}

// ParsePair / ParseTriple over ParseNumF — same contracts as the
// numparse.h originals (which the kFused=false instantiation reproduces
// op for op).
template <bool kFused, typename TA, typename TB>
inline int ParsePairF(const char* p, const char* end, const char** out,
                      TA* a, TB* b) {
  while (p != end && IsBlankChar(*p)) ++p;
  if (p == end) {
    *out = end;
    return 0;
  }
  const char* q;
  if (!ParseNumF<kFused>(p, end, &q, a)) {
    *out = end;
    return 0;
  }
  if (q == end || *q != ':') {
    *out = q;
    return 1;
  }
  const char* r;
  if (!ParseNumF<kFused>(q + 1, end, &r, b)) {
    *out = q;
    return 1;
  }
  *out = r;
  return 2;
}

template <bool kFused, typename TA, typename TB, typename TC>
inline int ParseTripleF(const char* p, const char* end, const char** out,
                        TA* a, TB* b, TC* c) {
  TA ta;
  TB tb;
  int n = ParsePairF<kFused, TA, TB>(p, end, out, &ta, &tb);
  if (n >= 1) *a = ta;
  if (n >= 2) *b = tb;
  if (n < 2) return n;
  const char* q = *out;
  if (q == end || *q != ':') return 2;
  const char* r;
  if (!ParseNumF<kFused>(q + 1, end, &r, c)) return 2;
  *out = r;
  return 3;
}

}  // namespace dct

#endif  // DCT_SIMD_SCAN_H_
