#include "config.h"

#include <cctype>
#include <sstream>

#include "base.h"

namespace dct {
namespace {

// Unescape the body of a quoted value: \" \\ \n \t (reference config.cc's
// TransformTokenToReal).
std::string Unescape(const std::string& s) {
  std::string out;
  out.reserve(s.size());
  for (size_t i = 0; i < s.size(); ++i) {
    if (s[i] == '\\' && i + 1 < s.size()) {
      ++i;
      switch (s[i]) {
        case 'n': out += '\n'; break;
        case 't': out += '\t'; break;
        case '"': out += '"'; break;
        case '\\': out += '\\'; break;
        default: out += '\\'; out += s[i];
      }
    } else {
      out += s[i];
    }
  }
  return out;
}

std::string Escape(const std::string& s) {
  std::string out;
  out.reserve(s.size() + 2);
  for (char c : s) {
    switch (c) {
      case '\n': out += "\\n"; break;
      case '\t': out += "\\t"; break;
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      default: out += c;
    }
  }
  return out;
}

std::string Strip(const std::string& s) {
  size_t b = 0;
  size_t e = s.size();
  while (b < e && std::isspace(static_cast<unsigned char>(s[b]))) ++b;
  while (e > b && std::isspace(static_cast<unsigned char>(s[e - 1]))) --e;
  return s.substr(b, e - b);
}

}  // namespace

Config::Config(bool multi_value) : multi_value_(multi_value) {}

Config::Config(std::istream& is, bool multi_value) : multi_value_(multi_value) {
  LoadFromStream(is);
}

void Config::Clear() {
  order_.clear();
  index_.clear();
  is_string_.clear();
  entry_is_string_.clear();
}

void Config::LoadFromText(const std::string& text) {
  std::istringstream is(text);
  LoadFromStream(is);
}

void Config::LoadFromStream(std::istream& is) {
  std::string line;
  size_t lineno = 0;
  while (std::getline(is, line)) {
    ++lineno;
    // strip comments outside quotes; a backslash escapes exactly the next
    // char inside quotes (so \\" is a literal backslash + closing quote)
    bool in_quote = false;
    bool esc = false;
    for (size_t i = 0; i < line.size(); ++i) {
      if (esc) {
        esc = false;
      } else if (in_quote && line[i] == '\\') {
        esc = true;
      } else if (line[i] == '"') {
        in_quote = !in_quote;
      } else if (line[i] == '#' && !in_quote) {
        line.resize(i);
        break;
      }
    }
    std::string t = Strip(line);
    if (t.empty()) continue;
    size_t eq = std::string::npos;
    in_quote = false;
    esc = false;
    for (size_t i = 0; i < t.size(); ++i) {
      if (esc) esc = false;
      else if (in_quote && t[i] == '\\') esc = true;
      else if (t[i] == '"') in_quote = !in_quote;
      else if (t[i] == '=' && !in_quote) { eq = i; break; }
    }
    DCT_CHECK(eq != std::string::npos)
        << "config line " << lineno << ": expected `key = value`, got: " << t;
    std::string key = Strip(t.substr(0, eq));
    std::string val = Strip(t.substr(eq + 1));
    DCT_CHECK(!key.empty()) << "config line " << lineno << ": empty key";
    bool is_str = false;
    if (val.size() >= 2 && val.front() == '"' && val.back() == '"') {
      val = Unescape(val.substr(1, val.size() - 2));
      is_str = true;
    }
    Insert(key, val, is_str);
  }
}

void Config::SetParam(const std::string& key, const std::string& value,
                      bool is_string) {
  Insert(key, value, is_string);
}

void Config::Insert(const std::string& key, const std::string& value,
                    bool is_string) {
  auto it = index_.find(key);
  if (it != index_.end() && !multi_value_) {
    size_t slot = it->second.back();
    order_[slot].second = value;  // later wins
    entry_is_string_[slot] = is_string;
    is_string_[key] = is_string;
    return;
  }
  index_[key].push_back(order_.size());
  order_.emplace_back(key, value);
  entry_is_string_.push_back(is_string);
  is_string_[key] = is_string;
}

const std::string& Config::GetParam(const std::string& key) const {
  auto it = index_.find(key);
  DCT_CHECK(it != index_.end()) << "config: key " << key << " not found";
  return order_[it->second.back()].second;
}

bool Config::Contains(const std::string& key) const {
  return index_.count(key) != 0;
}

std::vector<std::string> Config::GetAll(const std::string& key) const {
  std::vector<std::string> out;
  auto it = index_.find(key);
  if (it == index_.end()) return out;
  for (size_t slot : it->second) out.push_back(order_[slot].second);
  return out;
}

bool Config::IsString(const std::string& key) const {
  auto it = is_string_.find(key);
  return it != is_string_.end() && it->second;
}

std::string Config::ToProtoString() const {
  std::ostringstream os;
  for (size_t i = 0; i < order_.size(); ++i) {
    os << order_[i].first << " : ";
    if (entry_is_string_[i]) {  // per-occurrence, not per-key
      os << '"' << Escape(order_[i].second) << '"';
    } else {
      os << order_[i].second;
    }
    os << '\n';
  }
  return os.str();
}

}  // namespace dct
