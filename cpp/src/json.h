// Streaming JSON reader/writer with declarative struct binding.
//
// Counterpart of reference include/dmlc/json.h: JSONReader (:44) /
// JSONWriter (:190) event-style API (BeginObject/NextObjectItem,
// BeginArray/NextArrayItem, Read/Write of scalars and STL containers) and
// JSONObjectReadHelper (:312) declarative field binding with
// required/optional fields. Redesigned on C++17: templates + if constexpr
// replace the reference's Handler<T> trait hierarchy; input is any
// std::istream (pair with iostream_bridge.h to parse straight off a
// dct::Stream, the way the reference layers json.h over dmlc::istream).
#ifndef DCT_JSON_H_
#define DCT_JSON_H_

#include <cctype>
#include <cmath>
#include <istream>
#include <map>
#include <ostream>
#include <sstream>
#include <string>
#include <type_traits>
#include <utility>
#include <vector>

#include "base.h"

namespace dct {

class JSONReader;
class JSONWriter;

namespace json_detail {
template <typename T, typename = void>
struct IsMapLike : std::false_type {};
template <typename T>
struct IsMapLike<T, std::void_t<typename T::key_type, typename T::mapped_type>>
    : std::true_type {};
template <typename T, typename = void>
struct IsVectorLike : std::false_type {};
template <typename T>
struct IsVectorLike<
    T, std::void_t<typename T::value_type,
                   decltype(std::declval<T>().push_back(
                       std::declval<typename T::value_type>()))>>
    : std::true_type {};
}  // namespace json_detail

// Event-pull JSON parser (reference json.h:44-188).
class JSONReader {
 public:
  explicit JSONReader(std::istream* is) : is_(is) {}

  void BeginObject() { Expect('{'); scope_counter_.push_back(0); }
  void BeginArray() { Expect('['); scope_counter_.push_back(0); }

  // Advance to the next "key": value member; false at object end.
  bool NextObjectItem(std::string* out_key) {
    if (!NextScopeItem('}')) return false;
    ReadString(out_key);
    Expect(':');
    return true;
  }
  // Advance to the next array element; false at array end.
  bool NextArrayItem() { return NextScopeItem(']'); }

  void ReadString(std::string* out) {
    Expect('"');
    out->clear();
    while (true) {
      int c = is_->get();
      DCT_CHECK(c != EOF) << "json: unterminated string" << Where();
      if (c == '"') return;
      if (c == '\\') {
        int e = is_->get();
        switch (e) {
          case '"': out->push_back('"'); break;
          case '\\': out->push_back('\\'); break;
          case '/': out->push_back('/'); break;
          case 'b': out->push_back('\b'); break;
          case 'f': out->push_back('\f'); break;
          case 'n': out->push_back('\n'); break;
          case 'r': out->push_back('\r'); break;
          case 't': out->push_back('\t'); break;
          case 'u': {  // \uXXXX -> UTF-8 (BMP only, like the reference)
            unsigned code = 0;
            for (int i = 0; i < 4; ++i) {
              int h = is_->get();
              DCT_CHECK(std::isxdigit(h)) << "json: bad \\u escape" << Where();
              code = code * 16 +
                     (std::isdigit(h) ? h - '0' : std::tolower(h) - 'a' + 10);
            }
            if (code < 0x80) {
              out->push_back(static_cast<char>(code));
            } else if (code < 0x800) {
              out->push_back(static_cast<char>(0xC0 | (code >> 6)));
              out->push_back(static_cast<char>(0x80 | (code & 0x3F)));
            } else {
              out->push_back(static_cast<char>(0xE0 | (code >> 12)));
              out->push_back(static_cast<char>(0x80 | ((code >> 6) & 0x3F)));
              out->push_back(static_cast<char>(0x80 | (code & 0x3F)));
            }
            break;
          }
          default:
            throw Error("json: unknown escape" + Where());
        }
      } else {
        out->push_back(static_cast<char>(c));
        if (c == '\n') ++line_;
      }
    }
  }

  template <typename T>
  void ReadNumber(T* out) {
    static_assert(std::is_arithmetic_v<T>);
    SkipSpace();
    // parse via the widest type then narrow — matches reference behavior of
    // istream >> extraction per numeric type
    if constexpr (std::is_floating_point_v<T>) {
      double v;
      DCT_CHECK(static_cast<bool>(*is_ >> v)) << "json: bad number" << Where();
      *out = static_cast<T>(v);
    } else if constexpr (std::is_signed_v<T>) {
      long long v;  // NOLINT(runtime/int)
      DCT_CHECK(static_cast<bool>(*is_ >> v)) << "json: bad number" << Where();
      *out = static_cast<T>(v);
    } else {
      unsigned long long v;  // NOLINT(runtime/int)
      DCT_CHECK(static_cast<bool>(*is_ >> v)) << "json: bad number" << Where();
      *out = static_cast<T>(v);
    }
  }

  void ReadBool(bool* out) {
    SkipSpace();
    std::string word;
    while (std::isalpha(is_->peek())) word.push_back(is_->get());
    if (word == "true") { *out = true; return; }
    if (word == "false") { *out = false; return; }
    throw Error("json: expected true/false, got `" + word + "`" + Where());
  }

  // Generic dispatch: scalars, strings, vector-likes, map-likes, pairs, and
  // classes exposing Load(JSONReader*).
  template <typename T>
  void Read(T* out) {
    if constexpr (std::is_same_v<T, std::string>) {
      ReadString(out);
    } else if constexpr (std::is_same_v<T, bool>) {
      ReadBool(out);
    } else if constexpr (std::is_arithmetic_v<T>) {
      ReadNumber(out);
    } else if constexpr (json_detail::IsMapLike<T>::value) {
      static_assert(
          std::is_same_v<typename T::key_type, std::string>,
          "json object keys must be strings");
      out->clear();
      BeginObject();
      std::string key;
      while (NextObjectItem(&key)) {
        typename T::mapped_type v{};
        Read(&v);
        out->emplace(key, std::move(v));
      }
    } else if constexpr (json_detail::IsVectorLike<T>::value) {
      out->clear();
      BeginArray();
      while (NextArrayItem()) {
        typename T::value_type v{};
        Read(&v);
        out->push_back(std::move(v));
      }
    } else {
      out->Load(this);
    }
  }
  template <typename A, typename B>
  void Read(std::pair<A, B>* out) {
    BeginArray();
    DCT_CHECK(NextArrayItem()) << "json: pair needs 2 elements" << Where();
    Read(&out->first);
    DCT_CHECK(NextArrayItem()) << "json: pair needs 2 elements" << Where();
    Read(&out->second);
    DCT_CHECK(!NextArrayItem()) << "json: pair has >2 elements" << Where();
  }

  // Skip one complete value of any type (for ignoring unknown keys).
  void SkipValue() {
    SkipSpace();
    int c = is_->peek();
    if (c == '{') {
      BeginObject();
      std::string k;
      while (NextObjectItem(&k)) SkipValue();
    } else if (c == '[') {
      BeginArray();
      while (NextArrayItem()) SkipValue();
    } else if (c == '"') {
      std::string s;
      ReadString(&s);
    } else {
      while (c != EOF && c != ',' && c != '}' && c != ']' &&
             !std::isspace(c)) {
        is_->get();
        c = is_->peek();
      }
    }
  }

 private:
  void SkipSpace() {
    while (std::isspace(is_->peek())) {
      if (is_->get() == '\n') ++line_;
    }
  }
  void Expect(char want) {
    SkipSpace();
    int c = is_->get();
    DCT_CHECK(c == want) << "json: expected `" << want << "` got `"
                         << static_cast<char>(c) << "`" << Where();
  }
  bool NextScopeItem(char closer) {
    DCT_CHECK(!scope_counter_.empty()) << "json: Next*Item outside scope";
    SkipSpace();
    if (scope_counter_.back() != 0) {
      int c = is_->get();
      if (c == closer) { scope_counter_.pop_back(); return false; }
      DCT_CHECK(c == ',') << "json: expected `,`" << Where();
      SkipSpace();
    } else if (is_->peek() == closer) {
      is_->get();
      scope_counter_.pop_back();
      return false;
    }
    ++scope_counter_.back();
    return true;
  }
  std::string Where() const { return " at line " + std::to_string(line_); }

  std::istream* is_;
  std::vector<size_t> scope_counter_;
  size_t line_ = 1;
};

// Event-push JSON emitter (reference json.h:190-310).
class JSONWriter {
 public:
  explicit JSONWriter(std::ostream* os) : os_(os) {}

  void BeginObject(bool multi_line = true) {
    *os_ << '{';
    scope_counter_.push_back(0);
    scope_multi_line_.push_back(multi_line);
  }
  void EndObject() { CloseScope('}'); }
  void BeginArray(bool multi_line = false) {
    *os_ << '[';
    scope_counter_.push_back(0);
    scope_multi_line_.push_back(multi_line);
  }
  void EndArray() { CloseScope(']'); }

  template <typename T>
  void WriteObjectKeyValue(const std::string& key, const T& value) {
    Separator(scope_counter_.back()++ != 0);
    WriteString(key);
    *os_ << ": ";
    Write(value);
  }
  template <typename T>
  void WriteArrayItem(const T& value) {
    Separator(scope_counter_.back()++ != 0);
    Write(value);
  }

  void WriteString(const std::string& s) {
    *os_ << '"';
    for (char ch : s) {
      switch (ch) {
        case '"': *os_ << "\\\""; break;
        case '\\': *os_ << "\\\\"; break;
        case '\b': *os_ << "\\b"; break;
        case '\f': *os_ << "\\f"; break;
        case '\n': *os_ << "\\n"; break;
        case '\r': *os_ << "\\r"; break;
        case '\t': *os_ << "\\t"; break;
        default: *os_ << ch;
      }
    }
    *os_ << '"';
  }

  template <typename T>
  void Write(const T& value) {
    if constexpr (std::is_same_v<T, std::string>) {
      WriteString(value);
    } else if constexpr (std::is_same_v<T, bool>) {
      *os_ << (value ? "true" : "false");
    } else if constexpr (std::is_floating_point_v<T>) {
      // round-trip precision (reference uses max_digits10 too)
      auto old = os_->precision(std::numeric_limits<T>::max_digits10);
      DCT_CHECK(std::isfinite(value)) << "json cannot encode non-finite";
      *os_ << value;
      os_->precision(old);
    } else if constexpr (std::is_arithmetic_v<T>) {
      *os_ << +value;  // promote char-sized ints to numbers
    } else if constexpr (json_detail::IsMapLike<T>::value) {
      BeginObject(false);
      for (const auto& [k, v] : value) WriteObjectKeyValue(k, v);
      EndObject();
    } else if constexpr (json_detail::IsVectorLike<T>::value) {
      BeginArray(false);
      for (const auto& v : value) WriteArrayItem(v);
      EndArray();
    } else {
      value.Save(this);
    }
  }
  template <typename A, typename B>
  void Write(const std::pair<A, B>& value) {
    BeginArray(false);
    WriteArrayItem(value.first);
    WriteArrayItem(value.second);
    EndArray();
  }
  void Write(const char* value) { WriteString(value); }

 private:
  void Separator(bool need_comma) {
    if (need_comma) *os_ << ", ";
    if (scope_multi_line_.back()) {
      *os_ << '\n' << std::string(scope_counter_.size() * 2, ' ');
    }
  }
  void CloseScope(char closer) {
    bool multi = scope_multi_line_.back();
    bool had_items = scope_counter_.back() != 0;
    scope_counter_.pop_back();
    scope_multi_line_.pop_back();
    if (multi && had_items) {
      *os_ << '\n' << std::string(scope_counter_.size() * 2, ' ');
    }
    *os_ << closer;
  }

  std::ostream* os_;
  std::vector<size_t> scope_counter_;
  std::vector<bool> scope_multi_line_;
};

// Declarative object binding (reference json.h:312-370): declare typed
// fields once, then ReadAllFields enforces required fields and (optionally)
// rejects unknown keys.
class JSONObjectReadHelper {
 public:
  template <typename T>
  void DeclareField(const std::string& key, T* addr) {
    Declare(key, addr, /*optional=*/false);
  }
  template <typename T>
  void DeclareOptionalField(const std::string& key, T* addr) {
    Declare(key, addr, /*optional=*/true);
  }

  void ReadAllFields(JSONReader* reader, bool allow_unknown = false) {
    for (auto& [key, entry] : fields_) entry.seen = false;
    reader->BeginObject();
    std::string key;
    while (reader->NextObjectItem(&key)) {
      auto it = fields_.find(key);
      if (it == fields_.end()) {
        DCT_CHECK(allow_unknown) << "json: unknown field `" << key << "`";
        reader->SkipValue();
        continue;
      }
      it->second.read(reader);
      it->second.seen = true;
    }
    for (auto& [k, entry] : fields_) {
      DCT_CHECK(entry.seen || entry.optional)
          << "json: required field `" << k << "` missing";
    }
  }

 private:
  template <typename T>
  void Declare(const std::string& key, T* addr, bool optional) {
    DCT_CHECK(fields_.count(key) == 0)
        << "json: field `" << key << "` declared twice";
    fields_[key] = {[addr](JSONReader* r) { r->Read(addr); }, optional,
                    false};
  }
  struct Entry {
    std::function<void(JSONReader*)> read;
    bool optional = false;
    bool seen = false;
  };
  std::map<std::string, Entry> fields_;
};

// Convenience round-trips.
template <typename T>
std::string ToJSONString(const T& value) {
  std::ostringstream os;
  JSONWriter writer(&os);
  writer.Write(value);
  return os.str();
}

template <typename T>
void FromJSONString(const std::string& text, T* out) {
  std::istringstream is(text);
  JSONReader reader(&is);
  reader.Read(out);
}

}  // namespace dct

#endif  // DCT_JSON_H_
