// Full native-pipeline benchmark: file -> InputSplit(prefetch) ->
// ThreadedParser -> consumed blocks, all in C++ — the stage between the
// ParseBlock microbench (bench_parse.cc) and the Python e2e number
// (bench.py --parse-only). The spread between the three locates the
// pipeline overhead: IO+split+threading here, ctypes/Python above.
// Build: make -C cpp benchpipeline
// Run:   ./dmlc_core_tpu/_native/bench_pipeline FILE [nthread] [reps]
#include <chrono>
#include <cstdio>
#include <cstdlib>
#include <string>

#include "../src/parser.h"
#include "../src/recordio.h"
#include "../src/retry.h"

namespace {

// `bench_pipeline rt N PAYLOAD PATH`: native RecordIO write+read
// round-trip — the BASELINE.md parity row measured engine-to-engine
// (the Python-facade probe in bench.py pays one ctypes call per record,
// which measures the binding, not the format).
int RoundTrip(int n, int payload, const char* path) {
  using Clock = std::chrono::steady_clock;
  std::string blob(payload, 'x');
  for (int i = 0; i < payload; ++i) blob[i] = static_cast<char>(i & 0xff);
  auto t0 = Clock::now();
  {
    std::unique_ptr<dct::Stream> fo(dct::Stream::Create(path, "w"));
    dct::RecordIOWriter w(fo.get());
    for (int i = 0; i < n; ++i) w.WriteRecord(blob.data(), blob.size());
  }
  double t_write = std::chrono::duration<double>(Clock::now() - t0).count();
  t0 = Clock::now();
  size_t got = 0;
  {
    std::unique_ptr<dct::Stream> fi(dct::Stream::Create(path, "r"));
    dct::RecordIOReader r(fi.get());
    std::string rec;
    while (r.NextRecord(&rec)) ++got;
  }
  double t_read = std::chrono::duration<double>(Clock::now() - t0).count();
  printf("recordio_rt %9.0f rec/s  (write %.0f, read %.0f, %zu recs, "
         "payload %d)\n", got / (t_write + t_read), n / t_write,
         got / t_read, got, payload);
  return got == static_cast<size_t>(n) ? 0 : 1;
}

}  // namespace

int main(int argc, char** argv) {
  if (argc < 2) {
    fprintf(stderr, "usage: %s FILE [nthread] [reps] | %s rt N PAYLOAD "
            "PATH\n", argv[0], argv[0]);
    return 2;
  }
  if (std::string(argv[1]) == "rt") {
    if (argc < 5) {
      fprintf(stderr, "usage: %s rt N PAYLOAD PATH\n", argv[0]);
      return 2;
    }
    return RoundTrip(
        static_cast<int>(dct::io::CheckedInt("N", argv[2], 1, 1 << 28)),
        static_cast<int>(dct::io::CheckedInt("PAYLOAD", argv[3], 1,
                                             1 << 28)),
        argv[4]);
  }
  const char* path = argv[1];
  // checked CLI parses (analyze.py env rule): garbage args error loudly
  int nthread = argc > 2 ? static_cast<int>(
      dct::io::CheckedInt("nthread", argv[2], 1, 1024)) : 1;
  int reps = argc > 3 ? static_cast<int>(
      dct::io::CheckedInt("reps", argv[3], 1, 1 << 20)) : 5;
  using Clock = std::chrono::steady_clock;
  double best = 1e30;
  size_t rows = 0, bytes = 0;
  for (int i = 0; i < reps; ++i) {
    auto t0 = Clock::now();
    auto parser = std::unique_ptr<dct::Parser<uint32_t>>(
        dct::Parser<uint32_t>::Create(path, 0, 1, "libsvm", nthread,
                                      /*threaded=*/true));
    rows = 0;
    while (const auto* b = parser->NextBlock()) {
      rows += b->Size();
    }
    bytes = parser->BytesRead();
    double dt = std::chrono::duration<double>(Clock::now() - t0).count();
    if (dt < best) best = dt;
  }
  printf("pipeline  %7.1f MB/s  %9.0f rows/s  (%zu rows, %.1f MB, "
         "nthread=%d, best of %d)\n",
         bytes / best / 1e6, rows / best, rows, bytes / 1e6, nthread, reps);
  return 0;
}
