// Full native-pipeline benchmark: file -> InputSplit(prefetch) ->
// ThreadedParser -> consumed blocks, all in C++ — the stage between the
// ParseBlock microbench (bench_parse.cc) and the Python e2e number
// (bench.py --parse-only). The spread between the three locates the
// pipeline overhead: IO+split+threading here, ctypes/Python above.
// Build: make -C cpp benchpipeline
// Run:   ./dmlc_core_tpu/_native/bench_pipeline FILE [nthread] [reps]
#include <chrono>
#include <cstdio>
#include <cstdlib>

#include "../src/parser.h"

int main(int argc, char** argv) {
  if (argc < 2) {
    fprintf(stderr, "usage: %s FILE [nthread] [reps]\n", argv[0]);
    return 2;
  }
  const char* path = argv[1];
  int nthread = argc > 2 ? atoi(argv[2]) : 1;
  int reps = argc > 3 ? atoi(argv[3]) : 5;
  using Clock = std::chrono::steady_clock;
  double best = 1e30;
  size_t rows = 0, bytes = 0;
  for (int i = 0; i < reps; ++i) {
    auto t0 = Clock::now();
    auto parser = std::unique_ptr<dct::Parser<uint32_t>>(
        dct::Parser<uint32_t>::Create(path, 0, 1, "libsvm", nthread,
                                      /*threaded=*/true));
    rows = 0;
    while (const auto* b = parser->NextBlock()) {
      rows += b->Size();
    }
    bytes = parser->BytesRead();
    double dt = std::chrono::duration<double>(Clock::now() - t0).count();
    if (dt < best) best = dt;
  }
  printf("pipeline  %7.1f MB/s  %9.0f rows/s  (%zu rows, %.1f MB, "
         "nthread=%d, best of %d)\n",
         bytes / best / 1e6, rows / best, rows, bytes / 1e6, nthread, reps);
  return 0;
}
