// Native-level unit tests for C++-only surfaces that the ctypes C API does
// not expose: the std::iostream bridge, memory streams, TemporaryDirectory,
// and SingleFileSplit. Mirrors the reference's gtest suite role
// (test/unittest/*.cc) with a dependency-free assert harness; run by
// tests/test_native_core.py via subprocess.
#include <cassert>
#include <cstdio>
#include <cstring>
#include <fstream>
#include <sstream>
#include <string>
#include <sys/stat.h>

#include "../src/filesys.h"
#include "../src/input_split.h"
#include "../src/iostream_bridge.h"
#include "../src/serializer.h"
#include "../src/stream.h"

namespace {

int g_failures = 0;

#define EXPECT(cond)                                                     \
  do {                                                                   \
    if (!(cond)) {                                                       \
      std::fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__,       \
                   #cond);                                               \
      ++g_failures;                                                      \
    }                                                                    \
  } while (0)

void TestMemoryStreams() {
  dct::MemoryStream ms;
  ms.Write("hello ", 6);
  ms.Write("world", 5);
  ms.Seek(0);
  char buf[16] = {0};
  EXPECT(ms.Read(buf, sizeof buf) == 11);
  EXPECT(std::string(buf, 11) == "hello world");

  char fixed[8];
  dct::MemoryFixedSizeStream fs(fixed, sizeof fixed);
  fs.Write("abcd", 4);
  EXPECT(fs.Tell() == 4);
  bool threw = false;
  try {
    fs.Write("0123456789", 10);  // exceeds capacity
  } catch (const dct::Error&) {
    threw = true;
  }
  EXPECT(threw);
  fs.Seek(0);
  char rd[4];
  EXPECT(fs.Read(rd, 4) == 4);
  EXPECT(std::memcmp(rd, "abcd", 4) == 0);
}

void TestIostreamBridge() {
  // ostream formatting → Stream, then istream parsing back, with counters
  // (reference io.h:318-442 usage pattern: dmlc::ostream os(stream.get())).
  dct::MemoryStream ms;
  {
    dct::ostream os(&ms, /*buffer_size=*/8);  // tiny buffer forces overflow()
    os << "pi=" << 314 << " e=" << 271 << "\n";
    os.flush();
    EXPECT(os.bytes_written() == ms.data().size());
  }
  ms.Seek(0);
  {
    dct::istream is(&ms, /*buffer_size=*/8);
    std::string tok;
    int x = 0;
    is >> tok;
    EXPECT(tok == "pi=314");
    is >> tok;
    EXPECT(tok == "e=271");
    EXPECT(!(is >> x));  // EOF
    EXPECT(is.bytes_read() == ms.data().size());
  }
  // set_stream re-pointing
  dct::MemoryStream a(std::string("1 2")), b(std::string("3 4"));
  dct::istream is(&a);
  int v = 0;
  is >> v;
  EXPECT(v == 1);
  is.set_stream(&b);
  is >> v;
  EXPECT(v == 3);
}

void TestTemporaryDirectory() {
  std::string kept;
  {
    dct::TemporaryDirectory tmp;
    kept = tmp.path();
    struct stat sb;
    EXPECT(stat(kept.c_str(), &sb) == 0 && S_ISDIR(sb.st_mode));
    // nested content must be removed recursively
    std::string sub = kept + "/nested";
    EXPECT(mkdir(sub.c_str(), 0700) == 0);
    std::ofstream(sub + "/f.txt") << "x";
  }
  struct stat sb;
  EXPECT(stat(kept.c_str(), &sb) != 0);  // gone
}

void TestSingleFileSplit() {
  dct::TemporaryDirectory tmp;
  std::string path = tmp.path() + "/lines.txt";
  std::ofstream(path) << "alpha\nbeta\r\ngamma";  // CRLF + NOEOL tail
  dct::SingleFileSplit split(path);
  dct::InputSplit::Blob blob;
  EXPECT(split.NextRecord(&blob));
  EXPECT(std::string(static_cast<char*>(blob.dptr), blob.size) == "alpha");
  EXPECT(split.NextRecord(&blob));
  EXPECT(std::string(static_cast<char*>(blob.dptr), blob.size) == "beta");
  EXPECT(split.NextRecord(&blob));
  EXPECT(std::string(static_cast<char*>(blob.dptr), blob.size) == "gamma");
  EXPECT(!split.NextRecord(&blob));
  // rewind works on a real file (not stdin)
  split.BeforeFirst();
  EXPECT(split.NextRecord(&blob));
  EXPECT(std::string(static_cast<char*>(blob.dptr), blob.size) == "alpha");
  EXPECT(split.GetTotalSize() > 0);
  // via factory with uri="stdin" the type must be text / unpartitioned
  bool threw = false;
  try {
    delete dct::InputSplit::Create("stdin", 1, 2, "text");
  } catch (const dct::Error&) {
    threw = true;
  }
  EXPECT(threw);
}

void TestStdinSplit() {
  // only run when the harness pipes data in (argv gate in main)
  dct::SingleFileSplit split("stdin");
  dct::InputSplit::Blob blob;
  std::string all;
  while (split.NextRecord(&blob)) {
    all.append(static_cast<char*>(blob.dptr), blob.size);
    all.push_back('|');
  }
  std::printf("STDIN:%s\n", all.c_str());
}

}  // namespace

int main(int argc, char** argv) {
  if (argc > 1 && std::string(argv[1]) == "--stdin") {
    TestStdinSplit();
    return 0;
  }
  TestMemoryStreams();
  TestIostreamBridge();
  TestTemporaryDirectory();
  TestSingleFileSplit();
  if (g_failures == 0) {
    std::printf("OK\n");
    return 0;
  }
  std::fprintf(stderr, "%d failure(s)\n", g_failures);
  return 1;
}
