// Native-level unit tests for C++-only surfaces that the ctypes C API does
// not expose: the std::iostream bridge, memory streams, TemporaryDirectory,
// and SingleFileSplit. Mirrors the reference's gtest suite role
// (test/unittest/*.cc) with a dependency-free assert harness; run by
// tests/test_native_core.py via subprocess.
#include <arpa/inet.h>
#include <netinet/in.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <cassert>
#include <chrono>
#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <memory>
#include <random>
#include <fstream>
#include <sstream>
#include <string>
#include <sys/stat.h>
#include <utime.h>

#include <atomic>
#include <list>
#include <map>
#include <thread>
#include <vector>

#include "../src/concurrency.h"
#include "../src/config.h"
#include "../src/csr_rec.h"
#include "../src/dense_rec.h"
#include "../src/lockfree.h"
#include "../src/memory.h"
#include "../src/pipeline.h"
#include "../src/filesys.h"
#include "../src/fs_fault.h"
#include "../src/input_split.h"
#include "../src/iostream_bridge.h"
#include "../src/json.h"
#include "../src/parameter.h"
#include "../src/parser.h"
#include "../src/recordio.h"
#include "../src/http.h"
#include "../src/http_stream.h"
#include "../src/range_reader.h"
#include "../src/registry.h"
#include "../src/retry.h"
#include "../src/s3_filesys.h"
#include "../src/serializer.h"
#include "../src/shard_cache.h"
#include "../src/stream.h"
#include "../src/telemetry.h"

namespace {

int g_failures = 0;

#define EXPECT(cond)                                                     \
  do {                                                                   \
    if (!(cond)) {                                                       \
      std::fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__,       \
                   #cond);                                               \
      ++g_failures;                                                      \
    }                                                                    \
  } while (0)

void TestMemoryStreams() {
  dct::MemoryStream ms;
  ms.Write("hello ", 6);
  ms.Write("world", 5);
  ms.Seek(0);
  char buf[16] = {0};
  EXPECT(ms.Read(buf, sizeof buf) == 11);
  EXPECT(std::string(buf, 11) == "hello world");

  char fixed[8];
  dct::MemoryFixedSizeStream fs(fixed, sizeof fixed);
  fs.Write("abcd", 4);
  EXPECT(fs.Tell() == 4);
  bool threw = false;
  try {
    fs.Write("0123456789", 10);  // exceeds capacity
  } catch (const dct::Error&) {
    threw = true;
  }
  EXPECT(threw);
  fs.Seek(0);
  char rd[4];
  EXPECT(fs.Read(rd, 4) == 4);
  EXPECT(std::memcmp(rd, "abcd", 4) == 0);
}

void TestIostreamBridge() {
  // ostream formatting → Stream, then istream parsing back, with counters
  // (reference io.h:318-442 usage pattern: dmlc::ostream os(stream.get())).
  dct::MemoryStream ms;
  {
    dct::ostream os(&ms, /*buffer_size=*/8);  // tiny buffer forces overflow()
    os << "pi=" << 314 << " e=" << 271 << "\n";
    os.flush();
    EXPECT(os.bytes_written() == ms.data().size());
  }
  ms.Seek(0);
  {
    dct::istream is(&ms, /*buffer_size=*/8);
    std::string tok;
    int x = 0;
    is >> tok;
    EXPECT(tok == "pi=314");
    is >> tok;
    EXPECT(tok == "e=271");
    EXPECT(!(is >> x));  // EOF
    EXPECT(is.bytes_read() == ms.data().size());
  }
  // set_stream re-pointing
  dct::MemoryStream a(std::string("1 2")), b(std::string("3 4"));
  dct::istream is(&a);
  int v = 0;
  is >> v;
  EXPECT(v == 1);
  is.set_stream(&b);
  is >> v;
  EXPECT(v == 3);
}

void TestTemporaryDirectory() {
  std::string kept;
  {
    dct::TemporaryDirectory tmp;
    kept = tmp.path();
    struct stat sb;
    EXPECT(stat(kept.c_str(), &sb) == 0 && S_ISDIR(sb.st_mode));
    // nested content must be removed recursively
    std::string sub = kept + "/nested";
    EXPECT(mkdir(sub.c_str(), 0700) == 0);
    std::ofstream(sub + "/f.txt") << "x";
  }
  struct stat sb;
  EXPECT(stat(kept.c_str(), &sb) != 0);  // gone
}

void TestSingleFileSplit() {
  dct::TemporaryDirectory tmp;
  std::string path = tmp.path() + "/lines.txt";
  std::ofstream(path) << "alpha\nbeta\r\ngamma";  // CRLF + NOEOL tail
  dct::SingleFileSplit split(path);
  dct::InputSplit::Blob blob;
  EXPECT(split.NextRecord(&blob));
  EXPECT(std::string(static_cast<char*>(blob.dptr), blob.size) == "alpha");
  EXPECT(split.NextRecord(&blob));
  EXPECT(std::string(static_cast<char*>(blob.dptr), blob.size) == "beta");
  EXPECT(split.NextRecord(&blob));
  EXPECT(std::string(static_cast<char*>(blob.dptr), blob.size) == "gamma");
  EXPECT(!split.NextRecord(&blob));
  // rewind works on a real file (not stdin)
  split.BeforeFirst();
  EXPECT(split.NextRecord(&blob));
  EXPECT(std::string(static_cast<char*>(blob.dptr), blob.size) == "alpha");
  EXPECT(split.GetTotalSize() > 0);
  // via factory with uri="stdin" the type must be text / unpartitioned
  bool threw = false;
  try {
    delete dct::InputSplit::Create("stdin", 1, 2, "text");
  } catch (const dct::Error&) {
    threw = true;
  }
  EXPECT(threw);
}

struct JPoint {
  int x = 0;
  std::vector<double> ys;
  void Save(dct::JSONWriter* w) const {
    w->BeginObject(false);
    w->WriteObjectKeyValue("x", x);
    w->WriteObjectKeyValue("ys", ys);
    w->EndObject();
  }
  void Load(dct::JSONReader* r) {
    dct::JSONObjectReadHelper helper;
    helper.DeclareField("x", &x);
    helper.DeclareOptionalField("ys", &ys);
    helper.ReadAllFields(r);
  }
};

void TestJSON() {
  // scalar / container round-trips (reference unittest_json.cc coverage)
  std::map<std::string, std::vector<int>> m{{"a", {1, 2}}, {"b", {}}};
  std::string text = dct::ToJSONString(m);
  std::map<std::string, std::vector<int>> back;
  dct::FromJSONString(text, &back);
  EXPECT(back == m);

  std::vector<std::pair<std::string, double>> pairs{{"pi", 3.25}};
  std::vector<std::pair<std::string, double>> pback;
  dct::FromJSONString(dct::ToJSONString(pairs), &pback);
  EXPECT(pback == pairs);

  // struct Save/Load with helper: unknown key rejected unless allowed,
  // missing required field throws, escapes survive
  JPoint p;
  p.x = -7;
  p.ys = {0.5, 1.5};
  JPoint q;
  dct::FromJSONString(dct::ToJSONString(p), &q);
  EXPECT(q.x == -7 && q.ys == p.ys);

  JPoint r;
  bool threw = false;
  try {
    dct::FromJSONString("{\"ys\": []}", &r);  // x required
  } catch (const dct::Error&) {
    threw = true;
  }
  EXPECT(threw);

  std::string esc;
  dct::FromJSONString("\"a\\n\\\"b\\u0041\"", &esc);
  EXPECT(esc == "a\n\"bA");

  bool flag = false;
  dct::FromJSONString(" true ", &flag);
  EXPECT(flag);
}

void TestConcurrentQueue() {
  // FIFO: N producers push, consumers drain, kill unblocks
  dct::ConcurrentBlockingQueue<int> q;
  std::atomic<long> sum{0};
  std::vector<std::thread> producers, consumers;
  for (int p = 0; p < 4; ++p) {
    producers.emplace_back([&q, p] {
      for (int i = 0; i < 1000; ++i) q.Push(p * 1000 + i);
    });
  }
  for (int c = 0; c < 4; ++c) {
    consumers.emplace_back([&q, &sum] {
      int v;
      while (q.Pop(&v)) sum += v;
    });
  }
  for (auto& t : producers) t.join();
  q.SignalForKill();
  for (auto& t : consumers) t.join();
  long expect = 0;
  for (int p = 0; p < 4; ++p)
    for (int i = 0; i < 1000; ++i) expect += p * 1000 + i;
  EXPECT(sum == expect);

  // priority mode: highest priority first, FIFO among equals
  dct::ConcurrentBlockingQueue<std::string, dct::QueueType::kPriority> pq;
  pq.Push("low", 1);
  pq.Push("hi-a", 9);
  pq.Push("hi-b", 9);
  std::string s;
  EXPECT(pq.Pop(&s) && s == "hi-a");
  EXPECT(pq.Pop(&s) && s == "hi-b");
  EXPECT(pq.Pop(&s) && s == "low");
}

void TestMemoryPool() {
  // sequential carve, free-list reuse, page rollover
  dct::MemoryPool<64, 8> pool;
  void* a = pool.allocate();
  void* b = pool.allocate();
  EXPECT(a != b);
  pool.deallocate(a);
  EXPECT(pool.allocate() == a);  // LIFO free-list reuse
  // churn past one 4 MB page (65536 objects of 64 B)
  std::vector<void*> ptrs;
  for (int i = 0; i < 70000; ++i) ptrs.push_back(pool.allocate());
  for (void* p : ptrs) pool.deallocate(p);

  // STL container on the thread-local allocator; per-thread singletons
  std::vector<std::thread> ts;
  std::atomic<int> ok{0};
  for (int t = 0; t < 4; ++t) {
    ts.emplace_back([&ok] {
      std::list<int, dct::ThreadlocalAllocator<int>> l;
      for (int i = 0; i < 1000; ++i) l.push_back(i);
      long sum = 0;
      for (int v : l) sum += v;
      if (sum == 999 * 1000 / 2) ++ok;
    });
  }
  for (auto& t : ts) t.join();
  EXPECT(ok == 4);

  // ThreadLocalStore yields distinct instances per thread
  int* main_inst = dct::ThreadLocalStore<int>::Get();
  int* other_inst = nullptr;
  std::thread([&other_inst] {
    other_inst = dct::ThreadLocalStore<int>::Get();
  }).join();
  EXPECT(main_inst != other_inst);
}

void TestLockFreeQueue() {
  // single-threaded semantics: FIFO, full/empty edges, power-of-two cap
  dct::LockFreeQueue<int> small(3);
  EXPECT(small.capacity() == 4);
  int v = -1;
  EXPECT(!small.TryPop(&v));
  for (int i = 0; i < 4; ++i) EXPECT(small.TryPush(i));
  EXPECT(!small.TryPush(99));  // full
  for (int i = 0; i < 4; ++i) {
    EXPECT(small.TryPop(&v) && v == i);
  }
  EXPECT(!small.TryPop(&v));  // empty again
  // wrap-around across several laps
  for (int lap = 0; lap < 10; ++lap) {
    EXPECT(small.TryPush(lap));
    EXPECT(small.TryPop(&v) && v == lap);
  }

  // MPMC stress (counterpart of reference unittest_lockfree.cc): 4
  // producers x 4 consumers, spin on full/empty, checksum must balance
  dct::LockFreeQueue<long> q(256);
  constexpr int kProducers = 4, kConsumers = 4, kPerProducer = 20000;
  std::atomic<long> sum{0};
  std::atomic<int> done_producers{0};
  std::vector<std::thread> threads;
  for (int p = 0; p < kProducers; ++p) {
    threads.emplace_back([&q, &done_producers, p] {
      for (int i = 0; i < kPerProducer; ++i) {
        long item = static_cast<long>(p) * kPerProducer + i;
        while (!q.TryPush(item)) std::this_thread::yield();
      }
      ++done_producers;
    });
  }
  for (int c = 0; c < kConsumers; ++c) {
    threads.emplace_back([&q, &sum, &done_producers] {
      long item;
      while (true) {
        if (q.TryPop(&item)) {
          sum += item;
        } else if (done_producers.load() == kProducers) {
          if (!q.TryPop(&item)) break;  // drained after producers finished
          sum += item;
        } else {
          std::this_thread::yield();
        }
      }
    });
  }
  for (auto& t : threads) t.join();
  long expect = 0;
  for (int p = 0; p < kProducers; ++p)
    for (int i = 0; i < kPerProducer; ++i)
      expect += static_cast<long>(p) * kPerProducer + i;
  EXPECT(sum == expect);

  // move-only payloads
  dct::LockFreeQueue<std::unique_ptr<int>> mq(8);
  EXPECT(mq.TryPush(std::unique_ptr<int>(new int(42))));
  std::unique_ptr<int> got;
  EXPECT(mq.TryPop(&got) && got != nullptr && *got == 42);
}

void TestThreadGroup() {
  dct::ThreadGroup group;
  std::atomic<int> ticks{0};
  std::atomic<bool> worker_saw_shutdown{false};
  group.StartTimer("timer", std::chrono::milliseconds(5),
                   [&ticks] { ++ticks; });
  group.Start("worker", [&worker_saw_shutdown](dct::ThreadGroup::Thread* t) {
    while (!t->wait_shutdown_for(std::chrono::milliseconds(5))) {
    }
    worker_saw_shutdown = true;
  });
  EXPECT(group.size() == 2);
  EXPECT(group.Get("worker") != nullptr);
  EXPECT(group.Get("nope") == nullptr);
  std::this_thread::sleep_for(std::chrono::milliseconds(40));
  group.JoinAll();
  EXPECT(ticks.load() >= 2);
  EXPECT(worker_saw_shutdown.load());
  EXPECT(group.size() == 0);

  // spinlock under contention
  dct::Spinlock lock;
  int counter = 0;
  std::vector<std::thread> ts;
  for (int i = 0; i < 4; ++i) {
    ts.emplace_back([&lock, &counter] {
      for (int j = 0; j < 10000; ++j) {
        std::lock_guard<dct::Spinlock> g(lock);
        ++counter;
      }
    });
  }
  for (auto& t : ts) t.join();
  EXPECT(counter == 40000);
}

void TestPipelineExceptionPropagation() {
  // producer-side exceptions must surface at the consumer (reference
  // unittest_threaditer_exc_handling.cc; threadediter.h state machine)
  dct::PipelineIter<int> pipe(2);
  int produced = 0;
  pipe.Init([&produced](int** cell) {
    if (*cell == nullptr) *cell = new int;
    if (produced == 3) throw dct::Error("producer boom");
    **cell = produced++;
    return true;
  });
  int sum = 0;
  bool threw = false;
  try {
    int* c = nullptr;
    while (pipe.Next(&c)) {
      sum += *c;
      pipe.Recycle(&c);
    }
  } catch (const dct::Error& e) {
    threw = std::string(e.what()).find("boom") != std::string::npos;
  }
  EXPECT(threw);
  // the error may overtake cells still in the queue (rethrow happens at the
  // top of Next, as in the reference), so the consumed prefix varies
  EXPECT(sum == 0 || sum == 1 || sum == 3);

  // BeforeFirst restart semantics survive normal (non-error) exhaustion
  dct::PipelineIter<int> pipe2(2);
  int epoch_val = 0;
  int emitted = 0;
  pipe2.Init(
      [&](int** cell) {
        if (*cell == nullptr) *cell = new int;
        if (emitted == 2) return false;
        **cell = epoch_val * 10 + emitted++;
        return true;
      },
      [&] { emitted = 0; ++epoch_val; });
  std::vector<int> got;
  int* c = nullptr;
  while (pipe2.Next(&c)) {
    got.push_back(*c);
    pipe2.Recycle(&c);
  }
  pipe2.BeforeFirst();
  while (pipe2.Next(&c)) {
    got.push_back(*c);
    pipe2.Recycle(&c);
  }
  EXPECT((got == std::vector<int>{0, 1, 10, 11}));
}

// -- parameter / registry / config (reference parameter.h, registry.h,
//    config.h; gtest counterparts unittest_param.cc, registry_test.cc,
//    unittest_config.cc) ----------------------------------------------------
struct TestParam : public dct::Parameter<TestParam> {
  int num_hidden;
  float learning_rate;
  std::string name;
  bool shuffle;
  int act;
  DCT_DECLARE_PARAMETER(TestParam) {
    DCT_DECLARE_FIELD(num_hidden).set_range(0, 1000)
        .describe("hidden units");
    DCT_DECLARE_FIELD(learning_rate).set_default(0.01f)
        .set_lower_bound(0.0f);
    DCT_DECLARE_FIELD(name).set_default("mlp");
    DCT_DECLARE_FIELD(shuffle).set_default(false);
    DCT_DECLARE_FIELD(act).set_default(0)
        .add_enum("relu", 0).add_enum("tanh", 1);
    DCT_DECLARE_ALIAS(num_hidden, nhid);
  }
};

void TestParameter() {
  TestParam p;
  // keyword init + defaults + alias
  auto rest = p.Init({{"nhid", "64"}, {"act", "tanh"}, {"extra", "x"}});
  EXPECT(p.num_hidden == 64);
  EXPECT(p.act == 1);
  EXPECT(p.learning_rate == 0.01f);
  EXPECT(p.name == "mlp");
  EXPECT(!p.shuffle);
  EXPECT(rest.size() == 1 && rest[0].first == "extra");
  // bools and enum render-back in __DICT__
  auto d = p.__DICT__();
  EXPECT(d.at("act") == "tanh");
  EXPECT(d.at("shuffle") == "false");
  EXPECT(d.at("num_hidden") == "64");
  // required missing
  bool threw = false;
  try {
    TestParam q;
    q.Init({});
  } catch (const dct::ParamError& e) {
    threw = std::string(e.what()).find("num_hidden") != std::string::npos;
  }
  EXPECT(threw);
  // range violation
  threw = false;
  try {
    TestParam q;
    q.Init({{"num_hidden", "5000"}});
  } catch (const dct::ParamError&) {
    threw = true;
  }
  EXPECT(threw);
  // bad enum string
  threw = false;
  try {
    TestParam q;
    q.Init({{"num_hidden", "1"}, {"act", "gelu"}});
  } catch (const dct::ParamError&) {
    threw = true;
  }
  EXPECT(threw);
  // kAllMatch rejects unknown keys
  threw = false;
  try {
    TestParam q;
    q.Init({{"num_hidden", "1"}, {"mystery", "1"}},
           dct::ParamInitOption::kAllMatch);
  } catch (const dct::ParamError&) {
    threw = true;
  }
  EXPECT(threw);
  // kAllowHidden: underscore keys pass, others throw
  TestParam h;
  h.Init({{"num_hidden", "1"}, {"_hidden", "1"}},
         dct::ParamInitOption::kAllowHidden);
  // docstring mentions fields and ranges
  std::string doc = TestParam::__DOC__();
  EXPECT(doc.find("num_hidden") != std::string::npos);
  EXPECT(doc.find("required") != std::string::npos);
  EXPECT(doc.find("'relu'") != std::string::npos);
  // JSON round trip
  std::ostringstream os;
  dct::JSONWriter w(&os);
  p.Save(&w);
  TestParam r;
  std::istringstream is(os.str());
  dct::JSONReader jr(&is);
  r.Load(&jr);
  EXPECT(r.num_hidden == 64 && r.act == 1 && r.name == "mlp");
  // GetEnv typed defaults
  ::setenv("DCT_TEST_ENV_INT", "42", 1);
  EXPECT(dct::GetEnv("DCT_TEST_ENV_INT", 7) == 42);
  EXPECT(dct::GetEnv("DCT_TEST_ENV_ABSENT", 7) == 7);
  EXPECT(dct::GetEnv<std::string>("DCT_TEST_ENV_ABSENT", "d") == "d");
}

struct ToyReg
    : public dct::FunctionRegEntryBase<ToyReg, std::function<int(int)>> {};

void TestRegistry() {
  auto* reg = dct::Registry<ToyReg>::Get();
  reg->__REGISTER__("double")
      .describe("doubles the input")
      .add_argument("x", "int", "the input")
      .set_body([](int x) { return 2 * x; });
  reg->__REGISTER_OR_GET__("double");  // no duplicate
  const ToyReg* e = reg->Find("double");
  EXPECT(e != nullptr);
  EXPECT(e->body(21) == 42);
  EXPECT(e->description == "doubles the input");
  EXPECT(e->arguments.size() == 1 && e->arguments[0].name == "x");
  EXPECT(reg->Find("absent") == nullptr);
  EXPECT(reg->ListAllNames().size() == 1);
  // the built-in parsers registered themselves (libsvm/csv/libfm)
  auto* preg = dct::Registry<dct::ParserFactoryReg<uint32_t>>::Get();
  EXPECT(preg->Find("libsvm") != nullptr);
  EXPECT(preg->Find("csv") != nullptr);
  EXPECT(preg->Find("libfm") != nullptr);
  EXPECT(!preg->Find("csv")->arguments.empty());
}

void TestConfig() {
  std::string text =
      "# a comment\n"
      "learning_rate = 0.1\n"
      "name = \"quoted # not comment\"\n"
      "layers = 2  # trailing comment\n"
      "layers = 3\n"
      "msg = \"line\\nbreak\\t\\\"q\\\"\"\n";
  dct::Config cfg;
  cfg.LoadFromText(text);
  EXPECT(cfg.GetParam("learning_rate") == "0.1");
  EXPECT(cfg.GetParam("name") == "quoted # not comment");
  EXPECT(cfg.GetParam("layers") == "3");  // later wins
  EXPECT(cfg.GetParam("msg") == "line\nbreak\t\"q\"");
  EXPECT(cfg.IsString("name"));
  EXPECT(!cfg.IsString("layers"));
  EXPECT(cfg.Contains("name") && !cfg.Contains("ghost"));
  bool threw = false;
  try {
    cfg.GetParam("ghost");
  } catch (const dct::Error&) {
    threw = true;
  }
  EXPECT(threw);
  // multi-value mode keeps duplicates in order
  dct::Config multi(true);
  multi.LoadFromText("k = 1\nk = 2\nother = x\n");
  auto all = multi.GetAll("k");
  EXPECT(all.size() == 2 && all[0] == "1" && all[1] == "2");
  EXPECT(multi.items().size() == 3);
  // proto rendering quotes strings and escapes
  std::string proto = cfg.ToProtoString();
  EXPECT(proto.find("learning_rate : 0.1") != std::string::npos);
  EXPECT(proto.find("name : \"quoted # not comment\"") != std::string::npos);
  EXPECT(proto.find("\\n") != std::string::npos);
  // round trip: proto-ish `key = value` reload
  dct::Config cfg2;
  cfg2.LoadFromText("a = 1\nb = \"two\"\n");
  EXPECT(cfg2.GetParam("b") == "two");
  // trailing literal backslash before the closing quote (\\") must close
  // the quote, and the comment after it must be stripped
  dct::Config cfg3;
  cfg3.LoadFromText("msg = \"a\\\\\" # comment\n");
  EXPECT(cfg3.GetParam("msg") == "a\\");
  EXPECT(cfg3.IsString("msg"));
  // multi-value proto rendering quotes per occurrence, not per key
  dct::Config multi2(true);
  multi2.LoadFromText("k = 1\nk = \"two\"\n");
  std::string p2 = multi2.ToProtoString();
  EXPECT(p2.find("k : 1\n") != std::string::npos);
  EXPECT(p2.find("k : \"two\"\n") != std::string::npos);
}

struct FloatParam : public dct::Parameter<FloatParam> {
  float lr;
  DCT_DECLARE_PARAMETER(FloatParam) { DCT_DECLARE_FIELD(lr); }
};

void TestParameterFloatRoundTrip() {
  FloatParam p;
  p.Init({{"lr", "1.0000001"}});
  FloatParam q;
  q.Init(p.__DICT__());
  EXPECT(q.lr == p.lr);  // full max_digits10 precision in __DICT__
}

void TestStdinSplit() {
  // only run when the harness pipes data in (argv gate in main)
  dct::SingleFileSplit split("stdin");
  dct::InputSplit::Blob blob;
  std::string all;
  while (split.NextRecord(&blob)) {
    all.append(static_cast<char*>(blob.dptr), blob.size);
    all.push_back('|');
  }
  std::printf("STDIN:%s\n", all.c_str());
}

void TestXmlUnescape() {
  using dct::s3::XmlUnescape;
  EXPECT(XmlUnescape("a&amp;b&lt;c&gt;d") == "a&b<c>d");
  EXPECT(XmlUnescape("&#65;&#x42;") == "AB");
  // 2- and 3-byte UTF-8
  EXPECT(XmlUnescape("&#233;") == "\xC3\xA9");          // é
  EXPECT(XmlUnescape("&#x20AC;") == "\xE2\x82\xAC");    // €
  // supplementary plane needs a 4-byte sequence (U+1F600)
  EXPECT(XmlUnescape("&#x1F600;") == "\xF0\x9F\x98\x80");
  EXPECT(XmlUnescape("&#128512;") == "\xF0\x9F\x98\x80");
  // malformed / out-of-range entities stay literal
  EXPECT(XmlUnescape("&#;") == "&#;");
  EXPECT(XmlUnescape("&#x;") == "&#x;");
  EXPECT(XmlUnescape("&#xZZ;") == "&#xZZ;");
  EXPECT(XmlUnescape("&#1114112;") == "&#1114112;");  // > U+10FFFF
  EXPECT(XmlUnescape("&#xD800;") == "&#xD800;");      // UTF-16 surrogate
  EXPECT(XmlUnescape("&#65a;") == "&#65a;");          // trailing junk
  EXPECT(XmlUnescape("&bogus;") == "&bogus;");
}

void TestSplitHostPort() {
  std::string host;
  int port = 0;
  dct::SplitHostPort("example.com:8443", &host, &port, 80);
  EXPECT(host == "example.com" && port == 8443);
  dct::SplitHostPort("example.com", &host, &port, 80);
  EXPECT(host == "example.com" && port == 80);
  dct::SplitHostPort("[::1]:9000", &host, &port, 80);
  EXPECT(host == "::1" && port == 9000);
  dct::SplitHostPort("::1", &host, &port, 80);  // bare v6: no port split
  EXPECT(host == "::1" && port == 80);
  // invalid port suffixes must fail loudly, not leak 'host:junk' to DNS
  const char* bad[] = {"host:", "host:80a", "host:0", "host:65536",
                       "host:123456", "[::1]:x"};
  for (const char* s : bad) {
    bool threw = false;
    try {
      dct::SplitHostPort(s, &host, &port, 80);
    } catch (const dct::Error&) {
      threw = true;
    }
    EXPECT(threw);
  }
}

void TestEndianGoldenBytes() {
  using dct::serial::ByteSwap;
  using dct::serial::FromDisk;
  using dct::serial::ToDisk;

  // ByteSwap round-trip + known values
  EXPECT(ByteSwap<uint32_t>(0x01020304u) == 0x04030201u);
  EXPECT(ByteSwap<uint16_t>(0xBEEF) == 0xEFBE);
  EXPECT(ByteSwap<uint64_t>(0x0102030405060708ull) == 0x0807060504030201ull);
  EXPECT(ByteSwap(ByteSwap<uint64_t>(0xDEADBEEFCAFEF00Dull)) ==
         0xDEADBEEFCAFEF00Dull);
  float f = 1.5f;
  EXPECT(ByteSwap(ByteSwap(f)) == f);

  // The on-disk format is LE regardless of host order. Simulate a BE host:
  // a BE machine holding value 0x01020304 has bytes {01,02,03,04} in
  // memory; ToDisk(v, /*host_is_le=*/false) must emit {04,03,02,01} — the
  // same bytes an LE host emits. Golden fixtures pin that down.
  struct Golden32 {
    uint32_t value;
    uint8_t le_bytes[4];
  };
  const Golden32 cases32[] = {
      {0x01020304u, {0x04, 0x03, 0x02, 0x01}},
      {0xDEADBEEFu, {0xEF, 0xBE, 0xAD, 0xDE}},
      {1u, {0x01, 0x00, 0x00, 0x00}},
  };
  for (const auto& c : cases32) {
    // BE-host write path: the in-memory representation on a BE machine is
    // the byte-reversed LE pattern, which ByteSwap produces here
    uint32_t be_mem = ByteSwap(c.value);           // BE memory image
    uint32_t disk = ToDisk(be_mem, false);         // BE-host serialize
    EXPECT(std::memcmp(&disk, c.le_bytes, 4) == 0 ||
           disk == c.value);  // numeric identity on this LE host
    uint8_t buf[4];
    std::memcpy(buf, &disk, 4);
    // after the swap branch, the numeric value equals the logical value,
    // whose LE byte image is the golden fixture
    EXPECT(std::memcmp(buf, c.le_bytes, 4) == 0);
    // BE-host read path: bytes from disk loaded raw, then FromDisk swaps
    uint32_t raw;
    std::memcpy(&raw, c.le_bytes, 4);              // raw LE bytes
    EXPECT(FromDisk(ByteSwap(raw), false) == ByteSwap(ByteSwap(c.value)));
    EXPECT(FromDisk(raw, true) == c.value);        // LE-host read
  }

  // float64 golden: 1.0 is 0x3FF0000000000000 -> LE bytes end with 0xF0 0x3F
  double one = 1.0;
  uint8_t dbuf[8];
  std::memcpy(dbuf, &one, 8);
  const uint8_t one_le[8] = {0, 0, 0, 0, 0, 0, 0xF0, 0x3F};
  EXPECT(std::memcmp(dbuf, one_le, 8) == 0);  // this host writes LE already
  double be_one = ByteSwap(one);              // BE memory image of 1.0
  double disk_one = dct::serial::ToDisk(be_one, false);
  std::memcpy(dbuf, &disk_one, 8);
  EXPECT(std::memcmp(dbuf, one_le, 8) == 0);  // BE branch emits same bytes

  // full-stream check: serialize on a simulated BE writer, read back on the
  // real (LE) reader — the wire must be host-order independent
  dct::MemoryStream ms;
  const uint64_t magic = 0x1122334455667788ull;
  uint64_t be_magic_mem = ByteSwap(magic);
  uint64_t wire = dct::serial::ToDisk(be_magic_mem, false);
  ms.Write(&wire, 8);
  ms.Seek(0);
  EXPECT(dct::serial::ReadPOD<uint64_t>(&ms) == magic);
}

// Threaded text-parse fan-out under the race detector: the ParseBlock
// worker tiling + PipelinedParser stage hand-off are the riskiest
// threaded code in the library (VERDICT r2 item 5b); this drive puts them
// under `make tsan-test`. Determinism contract: any worker count must
// produce the identical multiset of rows (verified via order-insensitive
// aggregates; reference proves the same with nthread sweeps,
// test/unittest/unittest_parser.cc).
struct ParseSummary {
  size_t rows = 0;
  size_t nnz = 0;
  double label_sum = 0;
  double value_sum = 0;
  double weighted_index = 0;  // order-insensitive content fingerprint
};

ParseSummary SummarizeParse(const std::string& uri, const char* fmt,
                            int nthread, bool threaded, int epochs) {
  std::unique_ptr<dct::Parser<uint32_t>> p(
      dct::Parser<uint32_t>::Create(uri, 0, 1, fmt, nthread, threaded));
  ParseSummary s;
  for (int e = 0; e < epochs; ++e) {
    const dct::RowBlockContainer<uint32_t>* b;
    while ((b = p->NextBlock()) != nullptr) {
      s.rows += b->Size();
      s.nnz += b->index.size();
      for (float l : b->label) s.label_sum += l;
      for (float v : b->value) s.value_sum += v;
      for (size_t k = 0; k < b->index.size(); ++k) {
        s.weighted_index += static_cast<double>(b->index[k]) *
                            static_cast<double>(b->value[k]);
      }
    }
    p->BeforeFirst();
  }
  return s;
}

void ExpectSummariesMatch(const ParseSummary& a, const ParseSummary& b) {
  EXPECT(a.rows == b.rows);
  EXPECT(a.nnz == b.nnz);
  EXPECT(std::abs(a.label_sum - b.label_sum) < 1e-3);
  EXPECT(std::abs(a.value_sum - b.value_sum) < 1e-3);
  EXPECT(std::abs(a.weighted_index - b.weighted_index) < 1e-2);
}

// Golden on-disk bytes for the binary framing + the BE decode branches —
// the QEMU-free equivalent of the reference's s390x lane
// (scripts/test_script.sh:60-65): every decode helper takes host_is_le, so
// the big-endian branch runs here on the LE host and must be the exact
// byte-swap of the LE branch.
void TestRecordIOGoldenBytes() {
  // frame of payload "hi!": magic 0xced7230a LE, lrec = len 3 cflag 0 LE,
  // payload, 1 pad byte to the 4-byte boundary (recordio.h format spec)
  const uint8_t golden[] = {0x0A, 0x23, 0xD7, 0xCE, 0x03, 0x00, 0x00, 0x00,
                            'h',  'i',  '!',  0x00};
  dct::MemoryStream ms;
  {
    dct::RecordIOWriter w(&ms);
    w.WriteRecord("hi!", 3);
  }
  EXPECT(ms.data().size() == sizeof(golden));
  EXPECT(std::memcmp(ms.data().data(), golden, sizeof(golden)) == 0);
  // reader over the golden bytes
  dct::MemoryFixedSizeStream in(const_cast<char*>(
      reinterpret_cast<const char*>(golden)), sizeof(golden));
  dct::RecordIOReader r(&in);
  std::string rec;
  EXPECT(r.NextRecord(&rec));
  EXPECT(rec == "hi!");
  EXPECT(!r.NextRecord(&rec));
  // BE decode branch: LoadWordAs(p, false) must equal the byte-swap of
  // the LE load — a BE host's memory image of the same disk bytes
  const char* gp = reinterpret_cast<const char*>(golden);
  EXPECT(dct::recordio::LoadWordAs(gp, true) == 0xCED7230Au);
  EXPECT(dct::recordio::LoadWordAs(gp, false) ==
         dct::serial::ByteSwap(0xCED7230Au));
}

void TestBinaryLaneBEDecodeBranches() {
  using dct::serial::ByteSwap;
  // shared CopyWords32LE: the BE branch output is elementwise ByteSwap of
  // the LE branch output over identical disk bytes
  const float src[3] = {1.5f, -2.25f, 0.0f};
  const char* sb = reinterpret_cast<const char*>(src);
  float le_out[3], be_out[3];
  dct::recordio::CopyWords32LE(le_out, sb, 3, true);
  dct::recordio::CopyWords32LE(be_out, sb, 3, false);
  for (int i = 0; i < 3; ++i) {
    uint32_t a, b;
    std::memcpy(&a, le_out + i, 4);
    std::memcpy(&b, be_out + i, 4);
    EXPECT(b == ByteSwap(a));
    EXPECT(le_out[i] == src[i]);
  }
  // dense_rec CopyX bf16 -> f32: bf16 of 1.5 is 0x3FC0; on a BE host the
  // memcpy'd halfword is pre-swap, so the branch must swap it back. Feed
  // the swapped image through the BE branch and expect the true value.
  const uint16_t le_h = 0x3FC0;                     // LE disk bytes C0 3F
  const uint16_t be_mem = ByteSwap(le_h);           // BE memory image
  float out_f;
  dct::denserec_detail::CopyX(&out_f, 0,
                              reinterpret_cast<const char*>(&be_mem), 1, 1,
                              false);
  EXPECT(out_f == 1.5f);
  // integer words through the same shared copy
  const uint32_t words[2] = {0x01020304u, 0xDEADBEEFu};
  uint32_t le_w[2], be_w[2];
  dct::recordio::CopyWords32LE(le_w, words, 2, true);
  dct::recordio::CopyWords32LE(be_w, words, 2, false);
  EXPECT(le_w[0] == 0x01020304u && be_w[0] == 0x04030201u);
  EXPECT(be_w[1] == ByteSwap(le_w[1]));
  // recordio LoadU64As (csr_rec header words ride through it)
  const uint64_t u = 0x1122334455667788ull;
  const char* up = reinterpret_cast<const char*>(&u);
  EXPECT(dct::recordio::LoadU64As(up, true) == u);
  EXPECT(dct::recordio::LoadU64As(up, false) == ByteSwap(u));
}

// Hand-crafted golden DRD1 + DRC1 records decoded by the real batchers:
// pins the on-disk layout independent of the Python encoder.
void TestGoldenBinaryRecordsDecode() {
  dct::TemporaryDirectory tmp;
  {  // DRD1: 2 rows x 2 features f32, no weights
    dct::MemoryStream payload;
    dct::serial::WritePOD<uint32_t>(&payload, 0x44524431u);  // 'DRD1'
    dct::serial::WritePOD<uint32_t>(&payload, 0u);  // f32, no weight
    dct::serial::WritePOD<uint32_t>(&payload, 2u);  // rows
    dct::serial::WritePOD<uint32_t>(&payload, 2u);  // F
    for (float v : {1.0f, 0.0f}) dct::serial::WritePOD(&payload, v);
    for (float v : {0.5f, -1.5f, 2.0f, 4.25f}) {
      dct::serial::WritePOD(&payload, v);
    }
    std::unique_ptr<dct::Stream> out(
        dct::Stream::Create(tmp.path() + "/g.drec", "w"));
    dct::RecordIOWriter w(out.get());
    w.WriteRecord(payload.data());
  }
  {
    dct::DenseRecBatcher b(tmp.path() + "/g.drec", 0, 1, 2, 1);
    float x[4], label[2], weight[2];
    int32_t nrows[1];
    EXPECT(b.Fill(x, 0, 2, label, weight, nrows) == 2);
    EXPECT(label[0] == 1.0f && label[1] == 0.0f);
    EXPECT(weight[0] == 1.0f && weight[1] == 1.0f);
    EXPECT(x[0] == 0.5f && x[1] == -1.5f && x[2] == 2.0f && x[3] == 4.25f);
    EXPECT(nrows[0] == 2);
  }
  {  // DRC1: 2 rows, nnz 3, row lens {1, 2}, no optional planes
    dct::MemoryStream payload;
    dct::serial::WritePOD<uint32_t>(&payload, 0x44524331u);  // 'DRC1'
    dct::serial::WritePOD<uint32_t>(&payload, 0u);           // flags
    dct::serial::WritePOD<uint32_t>(&payload, 2u);           // rows
    dct::serial::WritePOD<uint32_t>(&payload, 2u);           // nwin
    dct::serial::WritePOD<uint64_t>(&payload, 3u);           // nnz
    dct::serial::WritePOD<uint32_t>(&payload, 7u);           // max_col
    dct::serial::WritePOD<uint32_t>(&payload, 0u);           // reserved
    dct::serial::WritePOD<uint64_t>(&payload, 2u);  // win_max[0] (1 row)
    dct::serial::WritePOD<uint64_t>(&payload, 3u);  // win_max[1] (2 rows)
    dct::serial::WritePOD<uint32_t>(&payload, 1u);  // row_len[0]
    dct::serial::WritePOD<uint32_t>(&payload, 2u);  // row_len[1]
    for (float v : {1.0f, 0.0f}) dct::serial::WritePOD(&payload, v);
    for (uint32_t c : {3u, 5u, 7u}) dct::serial::WritePOD(&payload, c);
    for (float v : {0.25f, -0.5f, 1.75f}) {
      dct::serial::WritePOD(&payload, v);
    }
    std::unique_ptr<dct::Stream> out(
        dct::Stream::Create(tmp.path() + "/g.crec", "w"));
    dct::RecordIOWriter w(out.get());
    w.WriteRecord(payload.data());
  }
  {
    dct::CsrRecBatcher b(tmp.path() + "/g.crec", 0, 1, 2, 1, 4);
    uint64_t bucket = 0;
    int hw = -1, hq = -1, hf = -1;
    b.Meta(&bucket, &hw, &hq, &hf);
    EXPECT(bucket == 4 && hw == 0 && hq == 0 && hf == 0);
    std::vector<int32_t> row(bucket), col(bucket);
    std::vector<float> val(bucket);
    float label[2], weight[2];
    int32_t nrows[1];
    EXPECT(b.Fill(row.data(), col.data(), val.data(), nullptr, label,
                  weight, nullptr, nrows) == 2);
    EXPECT(label[0] == 1.0f && label[1] == 0.0f);
    EXPECT(row[0] == 0 && row[1] == 1 && row[2] == 1);
    EXPECT(row[3] == 2);  // padding points at the sacrificial segment R
    EXPECT(col[0] == 3 && col[1] == 5 && col[2] == 7 && col[3] == 0);
    EXPECT(val[0] == 0.25f && val[1] == -0.5f && val[2] == 1.75f);
    EXPECT(nrows[0] == 2);
  }
}

// -- multi-chunk parse pipeline (parser.h PipelinedParser): ordering,
//    restart, consumer abandonment, and worker/reader exception surfacing.
//    Chunks are shrunk via DCT_CHUNK_SIZE_KB so several are in flight even
//    on small fixtures. ----------------------------------------------------

// RAII chunk-size shrink: the env var is read at split construction, so it
// only needs to be set across Parser::Create.
struct SmallChunks {
  SmallChunks() { setenv("DCT_CHUNK_SIZE_KB", "64", 1); }
  ~SmallChunks() { unsetenv("DCT_CHUNK_SIZE_KB"); }
};

std::string WriteOrderedLibsvm(const std::string& dir, int rows) {
  std::string path = dir + "/ordered.libsvm";
  std::ofstream f(path);
  for (int i = 0; i < rows; ++i) {
    // the label encodes the line number (exact in float up to 2^24), so an
    // out-of-order or duplicated block shows up as a sequence mismatch,
    // not just a sum mismatch
    f << i << " 0:1 " << (i % 7) + 1 << ':' << (i % 13) * 0.25 << '\n';
  }
  return path;
}

std::vector<float> CollectLabels(const std::string& uri, int nthread,
                                 bool threaded, int chunks_in_flight = 0) {
  std::unique_ptr<dct::Parser<uint32_t>> p(dct::Parser<uint32_t>::Create(
      uri, 0, 1, "libsvm", nthread, threaded, chunks_in_flight));
  std::vector<float> labels;
  const dct::RowBlockContainer<uint32_t>* b;
  while ((b = p->NextBlock()) != nullptr) {
    labels.insert(labels.end(), b->label.begin(), b->label.end());
  }
  return labels;
}

void TestParsePipelineOrdered() {
  dct::TemporaryDirectory tmp;
  SmallChunks small;
  std::string path = WriteOrderedLibsvm(tmp.path(), 60000);
  std::vector<float> serial = CollectLabels(path, 1, false);
  EXPECT(serial.size() == 60000u);
  EXPECT(serial.front() == 0.0f && serial.back() == 59999.0f);
  // several worker counts and pipeline depths must all reproduce the
  // serial sequence exactly (ordered reassembly, not just coverage)
  for (int nt : {1, 3, 4}) {
    for (int cif : {0, 2, 6}) {
      EXPECT(CollectLabels(path, nt, true, cif) == serial);
    }
  }
}

void TestParsePipelineRestart() {
  dct::TemporaryDirectory tmp;
  SmallChunks small;
  std::string path = WriteOrderedLibsvm(tmp.path(), 30000);
  std::unique_ptr<dct::Parser<uint32_t>> p(
      dct::Parser<uint32_t>::Create(path, 0, 1, "libsvm", 4, true, 3));
  for (int epoch = 0; epoch < 3; ++epoch) {
    float next = 0.0f;
    const dct::RowBlockContainer<uint32_t>* b;
    while ((b = p->NextBlock()) != nullptr) {
      for (float l : b->label) EXPECT(l == next++);
    }
    EXPECT(next == 30000.0f);
    p->BeforeFirst();
  }
  // restart mid-stream: drain a prefix, rewind, and the full ordered
  // sequence must come back (in-flight chunks of the old epoch dropped)
  const dct::RowBlockContainer<uint32_t>* b = p->NextBlock();
  EXPECT(b != nullptr && b->label.front() == 0.0f);
  p->BeforeFirst();
  std::vector<float> again;
  while ((b = p->NextBlock()) != nullptr) {
    again.insert(again.end(), b->label.begin(), b->label.end());
  }
  EXPECT(again.size() == 30000u && again.front() == 0.0f &&
         again.back() == 29999.0f);
}

void TestParsePipelineAbandon() {
  // consumer walks away mid-stream with chunks in flight: the destructor
  // must stop the reader/worker stages without a hang or leak (run under
  // TSan via the tsan-test lane)
  dct::TemporaryDirectory tmp;
  SmallChunks small;
  std::string path = WriteOrderedLibsvm(tmp.path(), 60000);
  {
    std::unique_ptr<dct::Parser<uint32_t>> p(
        dct::Parser<uint32_t>::Create(path, 0, 1, "libsvm", 4, true, 4));
    EXPECT(p->NextBlock() != nullptr);  // pipeline running, queue filling
  }
  {
    // abandon before ANY read: stages never started (lazy Start)
    std::unique_ptr<dct::Parser<uint32_t>> p(
        dct::Parser<uint32_t>::Create(path, 0, 1, "libsvm", 4, true, 4));
  }
}

void TestParsePipelineWorkerThrow() {
  // a parse-worker exception (ragged libsvm row: explicit values on some
  // features only -> ValidateBlock) must surface at the consumer, poison
  // the pipeline, and forbid restart (reference OMPException semantics)
  dct::TemporaryDirectory tmp;
  SmallChunks small;
  std::string path = tmp.path() + "/bad.libsvm";
  {
    std::ofstream f(path);
    for (int i = 0; i < 40000; ++i) f << "1 0:1 1:2\n";
    f << "1 0:1 2\n";  // ragged row lands in a late chunk
  }
  std::unique_ptr<dct::Parser<uint32_t>> p(
      dct::Parser<uint32_t>::Create(path, 0, 1, "libsvm", 4, true, 3));
  size_t rows = 0;
  bool threw = false;
  try {
    const dct::RowBlockContainer<uint32_t>* b;
    while ((b = p->NextBlock()) != nullptr) rows += b->Size();
  } catch (const dct::Error& e) {
    threw = std::string(e.what()).find("inconsistent") != std::string::npos;
  }
  EXPECT(threw);
  EXPECT(rows < 40001u);  // the poisoned slice never reaches the consumer
  bool threw_again = false;
  try {
    p->NextBlock();
  } catch (const dct::Error&) {
    threw_again = true;
  }
  EXPECT(threw_again);
  bool restart_threw = false;
  try {
    p->BeforeFirst();
  } catch (const dct::Error&) {
    restart_threw = true;
  }
  EXPECT(restart_threw);
}

void TestParsePipelineReaderThrow() {
  // a reader-stage exception (second input file vanishes between listing
  // and read) surfaces at the consumer after the preceding chunks drain
  dct::TemporaryDirectory tmp;
  SmallChunks small;
  std::string a = WriteOrderedLibsvm(tmp.path(), 20000);
  std::string b_path = tmp.path() + "/gone.libsvm";
  {
    std::ofstream f(b_path);
    for (int i = 0; i < 20000; ++i) f << "1 0:1\n";
  }
  std::unique_ptr<dct::Parser<uint32_t>> p(dct::Parser<uint32_t>::Create(
      a + ";" + b_path, 0, 1, "libsvm", 2, true, 2));
  EXPECT(p->NextBlock() != nullptr);  // streams are open lazily per file
  std::remove(b_path.c_str());
  bool threw = false;
  size_t rows = 0;
  try {
    const dct::RowBlockContainer<uint32_t>* blk;
    while ((blk = p->NextBlock()) != nullptr) rows += blk->Size();
  } catch (const dct::Error&) {
    threw = true;
  }
  // either the split had already opened the second file (POSIX keeps an
  // unlinked open file readable) or the reader died and the error
  // surfaced; both must leave the pipeline shut down cleanly — no hang,
  // no crash on destruction
  EXPECT(threw || rows == 2u * 20000u);
}

void TestThreadedTextParse() {
  dct::TemporaryDirectory tmp;
  std::string path = tmp.path() + "/big.libsvm";
  {
    std::ofstream f(path);
    for (int i = 0; i < 60000; ++i) {
      f << (i % 2);
      for (int j = 0; j < 8; ++j) {
        f << ' ' << j << ':' << (((i * 31 + j) % 97) * 0.01);
      }
      f << '\n';
    }
  }
  ParseSummary serial = SummarizeParse(path, "libsvm", 1, false, 2);
  EXPECT(serial.rows == 2u * 60000);
  EXPECT(serial.nnz == 2u * 60000 * 8);
  ParseSummary fanout = SummarizeParse(path, "libsvm", 4, true, 2);
  ExpectSummariesMatch(serial, fanout);
}

void TestThreadedRecParse() {
  dct::TemporaryDirectory tmp;
  std::string path = tmp.path() + "/blocks.rec";
  size_t want_rows = 0, want_nnz = 0;
  {
    std::unique_ptr<dct::Stream> out(dct::Stream::Create(path, "w"));
    dct::RecordIOWriter w(out.get());
    for (int r = 0; r < 400; ++r) {
      dct::RowBlockContainer<uint32_t> c;
      for (int i = 0; i < 50; ++i) {
        c.label.push_back(static_cast<float>((r + i) % 3));
        for (uint32_t j = 0; j < 5; ++j) {
          c.index.push_back(j);
          c.value.push_back(0.5f * static_cast<float>(j + r % 7));
        }
        c.offset.push_back(c.index.size());
      }
      c.UpdateMax();
      want_rows += c.Size();
      want_nnz += c.index.size();
      dct::MemoryStream ms;
      dct::serial::WritePOD<uint32_t>(&ms, 0x44524231u);  // 'DRB1'
      dct::serial::WritePOD<uint32_t>(&ms, 0u);           // uint32 ids
      c.Save(&ms);
      w.WriteRecord(ms.data());
    }
  }
  ParseSummary serial = SummarizeParse(path, "rec", 1, false, 2);
  EXPECT(serial.rows == 2 * want_rows);
  EXPECT(serial.nnz == 2 * want_nnz);
  ParseSummary fanout = SummarizeParse(path, "rec", 4, true, 2);
  ExpectSummariesMatch(serial, fanout);
}

// ---- SIMD text-ingest engine (simd_scan.h) -- the `--parse` suite --------
// Run standalone (test_core --parse) by the cpp/Makefile asan-parse /
// tsan-parse lanes, with DMLC_PARSE_SIMD pinning each dispatch tier.

// save/restore the ambient DMLC_PARSE_SIMD pin around tests that set it
// (a caller running the whole binary pinned must keep its pin afterwards)
struct ScopedParseSimdEnv {
  ScopedParseSimdEnv() {
    const char* cur = ::getenv("DMLC_PARSE_SIMD");
    had_ = cur != nullptr;
    if (had_) saved_ = cur;
  }
  ~ScopedParseSimdEnv() {
    if (had_) {
      ::setenv("DMLC_PARSE_SIMD", saved_.c_str(), 1);
    } else {
      ::unsetenv("DMLC_PARSE_SIMD");
    }
  }
  bool had_ = false;
  std::string saved_;
};

std::vector<dct::SimdTier> SupportedTiers() {
  std::vector<dct::SimdTier> tiers{dct::kSimdSWAR};
  if (dct::BestSupportedSimdTier() >= dct::kSimdSSE2) {
    tiers.push_back(dct::kSimdSSE2);
  }
  if (dct::BestSupportedSimdTier() >= dct::kSimdAVX2) {
    tiers.push_back(dct::kSimdAVX2);
  }
  return tiers;
}

void TestScanTapeKernelsAgree() {
  // every kernel tier must classify byte-for-byte like a scalar oracle,
  // including block tails, runs crossing 64-byte boundaries, and bytes
  // >= 0x80 (signed-compare traps)
  std::mt19937 rng(41);
  const char pool[] = "0123456789 \t:\n\r#abcZ.-+\xEF\xBB\x80\xFF";
  for (int round = 0; round < 8; ++round) {
    const size_t n = 1 + static_cast<size_t>(rng() % 300);
    std::string buf(n, '\0');
    for (auto& c : buf) c = pool[rng() % (sizeof(pool) - 1)];
    for (dct::SimdTier tier : SupportedTiers()) {
      dct::ScanTape tape;
      tape.Build(buf.data(), buf.data() + n, ' ', '\t', ':', tier);
      size_t seps = 0, eols = 0;
      for (size_t i = 0; i < n; ++i) {
        const char c = buf[i];
        const bool sep = c == ':';
        const bool eol = c == '\n' || c == '\r';
        const bool blank = c == ' ' || c == '\t';
        const bool digit = c >= '0' && c <= '9';
        EXPECT(tape.IsStructural(i) == (sep || eol || blank));
        EXPECT(tape.IsSep(i) == sep);
        EXPECT(tape.IsEol(i) == eol);
        EXPECT(tape.IsBlankKind(i) == blank);
        EXPECT((tape.DigitRunAt(i, 1) == 1) == digit);
        seps += sep;
        eols += eol;
      }
      EXPECT(tape.sep_count() == seps);
      EXPECT(tape.eol_count() == eols);
      // digit-run extents across word boundaries
      for (size_t i = 0; i < n; ++i) {
        int want = 0;
        while (i + want < n && buf[i + want] >= '0' &&
               buf[i + want] <= '9' && want < 20) {
          ++want;
        }
        EXPECT(tape.DigitRunAt(i, 20) == want);
      }
      // the count-only scan matches the materialized tape
      size_t cn_sep = 0, cn_eol = 0;
      dct::CountSepEol(buf.data(), buf.data() + n, ':', tier, &cn_sep,
                       &cn_eol);
      EXPECT(cn_sep == seps && cn_eol == eols);
    }
  }
}

void TestStructCursorWalk() {
  std::mt19937 rng(43);
  const char pool[] = "01 :\n\raz";
  for (int round = 0; round < 6; ++round) {
    const size_t n = 1 + static_cast<size_t>(rng() % 200);
    std::string buf(n, '\0');
    for (auto& c : buf) c = pool[rng() % (sizeof(pool) - 1)];
    dct::ScanTape tape;
    tape.Build(buf.data(), buf.data() + n, ' ', '\t', ':',
               dct::BestSupportedSimdTier());
    // the cursor must enumerate exactly the structural bytes, in order,
    // with the right classes
    dct::StructCursor sc(tape);
    for (size_t i = 0; i < n; ++i) {
      if (!tape.IsStructural(i)) continue;
      EXPECT(sc.pos == i);
      EXPECT(sc.kind == tape.KindOf(i));
      sc.Advance();
    }
    EXPECT(sc.pos == n && sc.kind == dct::ScanTape::kNone);
    // SeekTo resyncs mid-stream
    const size_t mid = n / 2;
    dct::ScanTape::Kind k;
    const size_t want = tape.NextStructural(mid, &k);
    sc.SeekTo(mid);
    EXPECT(sc.pos == want && sc.kind == k);
  }
}

// fuzz corpus of numeric-ish tokens: whenever a fused primitive accepts,
// its value must be BIT-identical to ParseNum's and its consumption equal
std::vector<std::string> FusedFuzzTokens() {
  std::vector<std::string> toks = {
      "0",        "1",      "9",       "42",        "007",
      "123456",   "12345678901234567890",           "4294967296",
      "2.5",      "-2.5",   "+2.5",    "0.500000",  "-0.000001",
      ".5",       "5.",     ".",       "-",         "+",
      "1e4",      "1E-4",   "2.5e3",   "1e",        "1e+",
      "3.14159265358979",   "123456789.123456789",  "0x10",
      "nan",      "inf",    "-inf",    "NaN",       "abc",
      "12ab",     "1.2.3",  "--5",     "9999999999999999999999",
      "0.12345678",         "12345678.9",           "00000000000000001",
  };
  std::mt19937 rng(47);
  std::uniform_real_distribution<double> val(-1e6, 1e6);
  char buf[64];
  for (int i = 0; i < 400; ++i) {
    switch (rng() % 4) {
      case 0:
        snprintf(buf, sizeof buf, "%.*f", static_cast<int>(rng() % 12),
                 val(rng));
        break;
      case 1:
        snprintf(buf, sizeof buf, "%g", val(rng) * 1e-8);
        break;
      case 2:
        snprintf(buf, sizeof buf, "%llu",
                 static_cast<unsigned long long>(rng()) * rng());
        break;
      default:
        snprintf(buf, sizeof buf, "%d", static_cast<int>(rng()));
        break;
    }
    toks.push_back(buf);
  }
  return toks;
}

void TestFusedDecodersMatchScalar() {
  for (const std::string& tok : FusedFuzzTokens()) {
    for (const char* suffix : {"", " tail", ":3", "\n1 2:3", "…"}) {
      const std::string s = tok + suffix;
      const char* p = s.data();
      const char* end = p + s.size();
      // float: fused acceptance implies bit-identical value + consumption
      float fv = 0.0f;
      const char* fa = dct::DecodeFloatAuto(p, end, &fv);
      float sv = 0.0f;
      const char* sp = p;
      const bool sok = dct::ParseNum<float>(p, end, &sp, &sv);
      if (fa != nullptr) {
        EXPECT(sok);
        EXPECT(fa == sp);
        EXPECT(std::memcmp(&fv, &sv, sizeof fv) == 0);
      }
      // the composed wrapper must EQUAL ParseNum on every input
      float wv = 0.0f;
      const char* wp = p;
      const bool wok = dct::ParseNumF<true, float>(p, end, &wp, &wv);
      EXPECT(wok == sok);
      if (sok) {
        EXPECT(wp == sp);
        EXPECT(std::memcmp(&wv, &sv, sizeof wv) == 0);
      }
      // unsigned and signed integral wrappers likewise
      uint64_t u_f = 0, u_s = 0;
      const char *up_f = p, *up_s = p;
      const bool uok_f = dct::ParseNumF<true, uint64_t>(p, end, &up_f, &u_f);
      const bool uok_s = dct::ParseNum<uint64_t>(p, end, &up_s, &u_s);
      EXPECT(uok_f == uok_s);
      if (uok_s) EXPECT(up_f == up_s && u_f == u_s);
      int32_t i_f = 0, i_s = 0;
      const char *ip_f = p, *ip_s = p;
      const bool iok_f = dct::ParseNumF<true, int32_t>(p, end, &ip_f, &i_f);
      const bool iok_s = dct::ParseNum<int32_t>(p, end, &ip_s, &i_s);
      EXPECT(iok_f == iok_s);
      if (iok_s) EXPECT(ip_f == ip_s && i_f == i_s);
    }
  }
  // FusedDigitScan: verified digit runs with exact values at every length
  std::string digits = "12345678901234567890123";
  for (size_t len = 1; len <= digits.size(); ++len) {
    // trailing padding keeps the 8/16-byte load guards satisfied, so only
    // genuine 16+ digit runs may defer to the exact path
    std::string s = digits.substr(0, len) + ":" + std::string(16, ' ');
    uint64_t v = 0;
    const int il = dct::FusedDigitScan(s.data(), s.data() + s.size(), &v);
    if (il != dct::kFusedOverflow) {
      EXPECT(il == static_cast<int>(len));
      uint64_t want = 0;
      for (size_t i = 0; i < len; ++i) want = want * 10 + (digits[i] - '0');
      EXPECT(v == want);
    } else {
      EXPECT(len >= 16);  // only 16+ digit runs may defer to the exact path
    }
  }
}

// adversarial text corpora: every dispatch tier must produce containers
// byte-identical to the scalar lane, for every format and index width
const char* kAdversarialLibSVM =
    "\xEF\xBB\xBF"
    "1 0:2.5 3:-0.75 7:1e-4\r\n"
    "0\r"
    "# a comment line with 5:5 inside\n"
    "   \t \n"
    "2:0.5 3:9.25 11:3\n"
    "1:1.5 2 qid:7 4:4\n"
    "-1 qid:9 1:0.5 2:0.25\n"
    "3.5:2.25 1:1 2:2\n"
    "1 12345678901:3.5 2:2\n"
    "1 4294967296:1 1:1\n"
    "1 1:0.123456789012345678 2:2.5\n"
    "1 3:nan 4:inf 5:0x10\n"
    "1 +5:2.5 6:+0.5\n"
    "garbage line here\n"
    "1 2:3 trailing junk\n"
    "1 1:2.5e309 2:1\n"
    "0 1:.5 2:5. 3:.\n"
    "1 000000000000001:2 2:3\n"
    "1 7:1.25 # trailing comment\n"
    "1 8:";

const char* kAdversarialCSV =
    "\xEF\xBB\xBF"
    "1,2.5,,-0.75,1e-4\r\n"
    "\r"
    ",,,\n"
    "0, .5 ,5.,nan\n"
    "1,0x10,inf,-inf\n"
    "3,  2.25,junk,4.5trailing\n"
    "9,123456789012345678901,0.123456789012345,+7\n"
    "2,-3.5,1.25,";

const char* kAdversarialLibFM =
    "\xEF\xBB\xBF"
    "1 0:1:0.5 2:3:-0.25\r\n"
    "0\r"
    "# comment 1:2:3\n"
    "  \t\n"
    "1:0.5 2:3:1e-4 7\n"
    "-1 1:2 3:4:5.5 12345678901:2:3\n"
    "1 4294967296:1:1 1:1:1\n"
    "1 1:2:3:4 5:6:7\n"
    "garbage 1:2:3\n"
    "1 2:+3:0.5 4:5:+1.5\n"
    "0 1:.5:.25 2:5.:1\n"
    "1 3:4:";

template <typename IndexType, typename ParserT>
dct::RowBlockContainer<IndexType> ParseWithTier(
    ParserT* parser, const std::string& corpus) {
  dct::RowBlockContainer<IndexType> out;
  parser->ParseBlock(corpus.data(), corpus.data() + corpus.size(), &out);
  return out;
}

template <typename T>
bool VecBitsEqual(const std::vector<T>& a, const std::vector<T>& b) {
  // bitwise compare: float vectors may legitimately hold NaN
  return a.size() == b.size() &&
         (a.empty() ||
          std::memcmp(a.data(), b.data(), a.size() * sizeof(T)) == 0);
}

template <typename IndexType>
bool ContainersEqual(const dct::RowBlockContainer<IndexType>& a,
                     const dct::RowBlockContainer<IndexType>& b) {
  return a.offset == b.offset && VecBitsEqual(a.label, b.label) &&
         VecBitsEqual(a.weight, b.weight) && a.qid == b.qid &&
         a.field == b.field && a.index == b.index &&
         VecBitsEqual(a.value, b.value) && a.value_i32 == b.value_i32 &&
         a.value_i64 == b.value_i64 && a.value_dtype == b.value_dtype &&
         a.max_index == b.max_index && a.max_field == b.max_field;
}

template <typename IndexType>
void DifferentialOneWidth() {
  ScopedParseSimdEnv scoped_env;
  const std::map<std::string, std::string> no_args;
  for (int mode : {0, 1, -1}) {
    std::map<std::string, std::string> margs;
    margs["indexing_mode"] =
        mode == 0 ? "zero_based" : mode == 1 ? "one_based" : "auto";
    for (dct::SimdTier tier : SupportedTiers()) {
      ::setenv("DMLC_PARSE_SIMD", "0", 1);
      dct::LibSVMParser<IndexType> svm_s(nullptr, margs, 1);
      dct::LibFMParser<IndexType> fm_s(nullptr, margs, 1);
      ::setenv("DMLC_PARSE_SIMD", dct::SimdTierName(tier), 1);
      dct::LibSVMParser<IndexType> svm_v(nullptr, margs, 1);
      dct::LibFMParser<IndexType> fm_v(nullptr, margs, 1);
      ::unsetenv("DMLC_PARSE_SIMD");
      EXPECT(ContainersEqual(
          ParseWithTier<IndexType>(&svm_s, kAdversarialLibSVM),
          ParseWithTier<IndexType>(&svm_v, kAdversarialLibSVM)));
      EXPECT(ContainersEqual(
          ParseWithTier<IndexType>(&fm_s, kAdversarialLibFM),
          ParseWithTier<IndexType>(&fm_v, kAdversarialLibFM)));
    }
  }
  for (int dtype : {0, 1, 2}) {
    std::map<std::string, std::string> cargs;
    cargs["label_column"] = "0";
    cargs["dtype"] = dtype == 0 ? "float32" : dtype == 1 ? "int32" : "int64";
    for (dct::SimdTier tier : SupportedTiers()) {
      ::setenv("DMLC_PARSE_SIMD", "0", 1);
      dct::CSVParser<IndexType> csv_s(nullptr, cargs, 1);
      ::setenv("DMLC_PARSE_SIMD", dct::SimdTierName(tier), 1);
      dct::CSVParser<IndexType> csv_v(nullptr, cargs, 1);
      ::unsetenv("DMLC_PARSE_SIMD");
      EXPECT(ContainersEqual(
          ParseWithTier<IndexType>(&csv_s, kAdversarialCSV),
          ParseWithTier<IndexType>(&csv_v, kAdversarialCSV)));
    }
  }
  (void)no_args;
}

void TestParseSimdDifferential() {
  ScopedParseSimdEnv scoped_env;
  DifferentialOneWidth<uint32_t>();
  DifferentialOneWidth<uint64_t>();
  // randomized rows, truncated at every offset near the end so chunk
  // boundaries land mid-token (the tail token then crosses load guards)
  std::mt19937 rng(53);
  std::uniform_real_distribution<double> val(-100.0, 100.0);
  std::string corpus;
  char buf[96];
  for (int r = 0; r < 200; ++r) {
    corpus += std::to_string(r % 3);
    const int feats = static_cast<int>(rng() % 6);
    for (int f = 0; f < feats; ++f) {
      snprintf(buf, sizeof buf, " %u:%.*f",
               static_cast<unsigned>(rng() % 100000000),
               static_cast<int>(rng() % 10), val(rng));
      corpus += buf;
    }
    corpus += (rng() % 8) == 0 ? "\r\n" : "\n";
  }
  const std::map<std::string, std::string> args;
  ::setenv("DMLC_PARSE_SIMD", "0", 1);
  dct::LibSVMParser<uint32_t> scalar(nullptr, args, 1);
  ::unsetenv("DMLC_PARSE_SIMD");
  dct::LibSVMParser<uint32_t> simd(nullptr, args, 1);
  for (size_t cut = corpus.size() > 64 ? corpus.size() - 64 : 0;
       cut <= corpus.size(); ++cut) {
    const std::string part = corpus.substr(0, cut);
    EXPECT(ContainersEqual(ParseWithTier<uint32_t>(&scalar, part),
                           ParseWithTier<uint32_t>(&simd, part)));
  }
}

void TestSimdTierResolution() {
  ScopedParseSimdEnv scoped_env;
  // the kill switch and the tier overrides must resolve predictably
  ::setenv("DMLC_PARSE_SIMD", "0", 1);
  EXPECT(dct::ResolveSimdTier() == dct::kSimdScalar);
  ::setenv("DMLC_PARSE_SIMD", "off", 1);
  EXPECT(dct::ResolveSimdTier() == dct::kSimdScalar);
  ::setenv("DMLC_PARSE_SIMD", "swar", 1);
  EXPECT(dct::ResolveSimdTier() == dct::kSimdSWAR);
  ::setenv("DMLC_PARSE_SIMD", "avx2", 1);
  EXPECT(dct::ResolveSimdTier() <= dct::kSimdAVX2);  // clamped to support
  ::setenv("DMLC_PARSE_SIMD", "definitely-a-typo", 1);
  EXPECT(dct::ResolveSimdTier() == dct::BestSupportedSimdTier());
  ::unsetenv("DMLC_PARSE_SIMD");
  EXPECT(dct::ResolveSimdTier() == dct::BestSupportedSimdTier());
  // the pipeline reports the lane through its stats struct
  dct::TemporaryDirectory tmp;
  std::string path = tmp.path() + "/t.libsvm";
  {
    std::ofstream f(path);
    for (int i = 0; i < 1000; ++i) f << "1 0:1 1:2\n";
  }
  std::unique_ptr<dct::Parser<uint32_t>> p(
      dct::Parser<uint32_t>::Create(path, 0, 1, "libsvm", 2, true, 2));
  while (p->NextBlock() != nullptr) {
  }
  dct::ParsePipelineStats st;
  EXPECT(p->GetPipelineStats(&st));
  EXPECT(st.simd_tier ==
         static_cast<uint64_t>(dct::BestSupportedSimdTier()));
}

void RunParseSimdSuite() {
  TestScanTapeKernelsAgree();
  TestStructCursorWalk();
  TestFusedDecodersMatchScalar();
  TestParseSimdDifferential();
  TestSimdTierResolution();
}

// ---- remote-I/O resilience layer (retry.h) -- the `--io` / tsan-io suite --

void TestCheckedEnvParse() {
  ::setenv("DCT_TEST_IO_INT", "17", 1);
  EXPECT(dct::io::CheckedEnvInt("DCT_TEST_IO_INT", 3, 0, 100) == 17);
  EXPECT(dct::io::CheckedEnvInt("DCT_TEST_IO_ABSENT", 3, 0, 100) == 3);
  // clamped, not silently wrong
  EXPECT(dct::io::CheckedEnvInt("DCT_TEST_IO_INT", 3, 0, 10) == 10);
  ::setenv("DCT_TEST_IO_INT", "-5", 1);
  EXPECT(dct::io::CheckedEnvInt("DCT_TEST_IO_INT", 3, 0, 100) == 0);
  // non-numeric text throws instead of atoi()-ing to 0
  ::setenv("DCT_TEST_IO_INT", "fifty", 1);
  bool threw = false;
  try {
    dct::io::CheckedEnvInt("DCT_TEST_IO_INT", 3, 0, 100);
  } catch (const dct::Error&) {
    threw = true;
  }
  EXPECT(threw);
  ::setenv("DCT_TEST_IO_INT", "12x", 1);
  threw = false;
  try {
    dct::io::CheckedEnvInt("DCT_TEST_IO_INT", 3, 0, 100);
  } catch (const dct::Error&) {
    threw = true;
  }
  EXPECT(threw);
  ::unsetenv("DCT_TEST_IO_INT");
}

void TestRetryPolicyFromEnvLayering() {
  // global DMLC_IO_* layer, overridden by the backend prefix layer (the
  // legacy <P>_RETRY_SLEEP_MS name maps onto the backoff base)
  ::setenv("DMLC_IO_MAX_RETRY", "9", 1);
  ::setenv("DMLC_IO_BACKOFF_BASE_MS", "20", 1);
  ::setenv("DMLC_IO_DEADLINE_MS", "4000", 1);
  ::setenv("T9_MAX_RETRY", "4", 1);
  ::setenv("T9_RETRY_SLEEP_MS", "7", 1);
  dct::io::RetryPolicy p = dct::io::RetryPolicy::FromEnv("T9");
  EXPECT(p.max_retry == 4);
  EXPECT(p.backoff_base_ms == 7);
  EXPECT(p.deadline_ms == 4000);
  dct::io::RetryPolicy q = dct::io::RetryPolicy::FromEnv("T8");
  EXPECT(q.max_retry == 9);
  EXPECT(q.backoff_base_ms == 20);
  ::unsetenv("DMLC_IO_MAX_RETRY");
  ::unsetenv("DMLC_IO_BACKOFF_BASE_MS");
  ::unsetenv("DMLC_IO_DEADLINE_MS");
  ::unsetenv("T9_MAX_RETRY");
  ::unsetenv("T9_RETRY_SLEEP_MS");
}

void TestExtractUriRetryArgs() {
  dct::io::RetryPolicy p;
  int timeout_ms = 0;
  std::string path = "/bkt/key?io_max_retry=3&fmt=csv&io_deadline_ms=250"
                     "&io_timeout_ms=99";
  dct::io::ExtractUriRetryArgs(&path, &p, &timeout_ms);
  EXPECT(path == "/bkt/key?fmt=csv");  // foreign args survive
  EXPECT(p.max_retry == 3);
  EXPECT(p.deadline_ms == 250);
  EXPECT(timeout_ms == 99);
  // all-ours query drops the '?' entirely
  path = "/k?io_backoff_base_ms=2&io_backoff_cap_ms=8";
  dct::io::ExtractUriRetryArgs(&path, &p, &timeout_ms);
  EXPECT(path == "/k");
  EXPECT(p.backoff_base_ms == 2 && p.backoff_cap_ms == 8);
  // no query is a no-op; garbage values throw (checked parser)
  path = "/plain";
  dct::io::ExtractUriRetryArgs(&path, &p, &timeout_ms);
  EXPECT(path == "/plain");
  path = "/k?io_max_retry=banana";
  bool threw = false;
  try {
    dct::io::ExtractUriRetryArgs(&path, &p, &timeout_ms);
  } catch (const dct::Error&) {
    threw = true;
  }
  EXPECT(threw);
}

void TestRetryBackoffDeterministicAndBounded() {
  dct::io::ResetIoStats();
  dct::io::RetryPolicy p;
  p.max_retry = 6;
  p.backoff_base_ms = 1;
  p.backoff_cap_ms = 4;
  p.jitter_seed = 42;
  auto run = [&] {
    dct::io::RetryController ctl(p);
    int ok = 0;
    while (ctl.BackoffOrGiveUp()) ++ok;
    return ok;
  };
  uint64_t before = dct::io::GlobalIoStats().backoff_ms_total.load();
  int a = run();
  uint64_t mid = dct::io::GlobalIoStats().backoff_ms_total.load();
  int b = run();
  uint64_t after = dct::io::GlobalIoStats().backoff_ms_total.load();
  EXPECT(a == 6 && b == 6);  // exactly max_retry sleeps, then giveup
  // same seed -> identical jitter sequence; every sleep within [base, cap]
  EXPECT(mid - before == after - mid);
  EXPECT(mid - before >= 6u * 1u && mid - before <= 6u * 4u);
  EXPECT(dct::io::GlobalIoStats().retries.load() == 12u);
  EXPECT(dct::io::GlobalIoStats().giveups.load() == 2u);
}

void TestRetryDeadlineExhaustion() {
  dct::io::ResetIoStats();
  dct::io::RetryPolicy p;
  p.max_retry = 1000000;  // retries alone would run ~forever
  p.backoff_base_ms = 5;
  p.backoff_cap_ms = 10;
  p.deadline_ms = 60;
  p.jitter_seed = 1;
  dct::io::RetryController ctl(p);
  auto t0 = std::chrono::steady_clock::now();
  int loops = 0;
  while (ctl.BackoffOrGiveUp()) ++loops;
  auto elapsed = std::chrono::duration_cast<std::chrono::milliseconds>(
                     std::chrono::steady_clock::now() - t0)
                     .count();
  EXPECT(loops >= 1);
  EXPECT(elapsed >= 50 && elapsed < 2000);  // bounded by the budget
  EXPECT(dct::io::GlobalIoStats().deadline_exhausted.load() == 1u);
  EXPECT(dct::io::GlobalIoStats().giveups.load() == 1u);
}

void TestFaultPlanParseAndDeterministicTick() {
  dct::io::ResetIoStats();
  // bad grammar throws (out-of-range numerics merely clamp — the shared
  // checked parser's contract: reject garbage, clamp extremes)
  for (const char* bad :
       {"flood:every=3", "reset", "reset:every=x", "5xx:rate=2",
        "stall:ms=abc,every=2", "reset:p=1.5"}) {
    bool threw = false;
    try {
      dct::io::SetFaultPlan(bad);
    } catch (const dct::Error&) {
      threw = true;
    }
    EXPECT(threw);
  }
  auto thrower = [](const std::string& what, int status) {
    throw dct::HttpStatusError(what, status);
  };
  dct::io::SetFaultPlan("reset:every=4;5xx:every=6,status=599");
  int resets = 0, fivexx = 0, clean = 0;
  for (int i = 0; i < 24; ++i) {
    try {
      dct::io::MaybeInjectFault(thrower);
      ++clean;
    } catch (const dct::HttpStatusError& e) {
      EXPECT(e.status == 599);
      ++fivexx;
    } catch (const dct::Error&) {
      ++resets;
    }
  }
  // every 4th of 24 -> 6 resets; every 6th -> 4 hits for 5xx, of which
  // multiples of both (12, 24) fire as the first-listed rule (reset)
  EXPECT(resets == 6);
  EXPECT(fivexx == 2);
  EXPECT(clean == 16);
  EXPECT(dct::io::GlobalIoStats().faults_injected.load() == 8u);
  EXPECT(dct::io::GlobalIoStats().requests.load() == 24u);
  // stall fires as a TimeoutError after sleeping its ms
  dct::io::SetFaultPlan("stall:every=1,ms=1");
  bool timed = false;
  try {
    dct::io::MaybeInjectFault(thrower);
  } catch (const dct::TimeoutError&) {
    timed = true;
  }
  EXPECT(timed);
  dct::io::SetFaultPlan("");
  dct::io::MaybeInjectFault(thrower);  // cleared: no throw
}

void TestFaultPlanThreadSafety() {
  // shared mutable state under concurrent tick: rule counters are atomic,
  // so the TOTAL fault count is exact even when the firing thread races
  dct::io::ResetIoStats();
  auto thrower = [](const std::string& what, int status) {
    throw dct::HttpStatusError(what, status);
  };
  dct::io::SetFaultPlan("reset:every=5");
  constexpr int kThreads = 4, kPerThread = 250;
  std::atomic<int> faults{0};
  std::vector<std::thread> threads;
  for (int t = 0; t < kThreads; ++t) {
    threads.emplace_back([&] {
      for (int i = 0; i < kPerThread; ++i) {
        try {
          dct::io::MaybeInjectFault(thrower);
        } catch (const dct::Error&) {
          faults.fetch_add(1);
        }
      }
    });
  }
  for (auto& t : threads) t.join();
  EXPECT(faults.load() == kThreads * kPerThread / 5);
  EXPECT(dct::io::GlobalIoStats().faults_injected.load() ==
         static_cast<uint64_t>(kThreads * kPerThread / 5));
  dct::io::SetFaultPlan("");
}

void TestHttpRecvTimeoutOnStalledServer() {
  // a server that accepts and then goes silent must surface as a bounded
  // retryable TimeoutError, not an infinite block (the ISSUE's headline
  // failure mode)
  int listener = ::socket(AF_INET, SOCK_STREAM, 0);
  EXPECT(listener >= 0);
  struct sockaddr_in addr;
  std::memset(&addr, 0, sizeof(addr));
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  addr.sin_port = 0;
  EXPECT(::bind(listener, reinterpret_cast<struct sockaddr*>(&addr),
                sizeof(addr)) == 0);
  EXPECT(::listen(listener, 1) == 0);
  socklen_t alen = sizeof(addr);
  EXPECT(::getsockname(listener, reinterpret_cast<struct sockaddr*>(&addr),
                       &alen) == 0);
  int port = ntohs(addr.sin_port);
  std::atomic<int> conn_fd{-1};
  std::thread server([&] {
    int fd = ::accept(listener, nullptr, nullptr);
    conn_fd.store(fd);  // hold it open, never answer
  });
  dct::io::SetIoTimeoutMs(120);
  bool timed_out = false;
  auto t0 = std::chrono::steady_clock::now();
  try {
    dct::HttpConnection conn("127.0.0.1", port);
    conn.SendRequest("GET", "/stall", {}, "");
    dct::HttpResponse head;
    conn.ReadResponseHead(&head);
  } catch (const dct::TimeoutError&) {
    timed_out = true;
  }
  auto elapsed = std::chrono::duration_cast<std::chrono::milliseconds>(
                     std::chrono::steady_clock::now() - t0)
                     .count();
  dct::io::SetIoTimeoutMs(0);
  EXPECT(timed_out);
  EXPECT(elapsed >= 100 && elapsed < 5000);
  EXPECT(dct::io::GlobalIoStats().timeouts.load() >= 1u);
  server.join();
  if (conn_fd.load() >= 0) ::close(conn_fd.load());
  ::close(listener);
}

void TestScopedIoTimeoutIsThreadLocal() {
  dct::io::SetIoTimeoutMs(0);
  const int base = dct::io::IoTimeoutMs();
  {
    dct::io::ScopedIoTimeout scoped(123);
    EXPECT(dct::io::IoTimeoutMs() == 123);
    int other_thread_value = -1;
    std::thread peer(
        [&] { other_thread_value = dct::io::IoTimeoutMs(); });
    peer.join();
    EXPECT(other_thread_value == base);  // override is per-thread
    {
      dct::io::ScopedIoTimeout inner(0);  // <=0: no-op, keeps 123
      EXPECT(dct::io::IoTimeoutMs() == 123);
    }
  }
  EXPECT(dct::io::IoTimeoutMs() == base);
}

void RunIoResilienceSuite() {
  TestCheckedEnvParse();
  TestRetryPolicyFromEnvLayering();
  TestExtractUriRetryArgs();
  TestRetryBackoffDeterministicAndBounded();
  TestRetryDeadlineExhaustion();
  TestFaultPlanParseAndDeterministicTick();
  TestFaultPlanThreadSafety();
  TestHttpRecvTimeoutOnStalledServer();
  TestScopedIoTimeoutIsThreadLocal();
  dct::io::ResetIoStats();
}

// ---- telemetry registry (telemetry.h) -- the `--telemetry` suite ---------
// Run standalone (test_core --telemetry) by the cpp/Makefile
// tsan-telemetry lane: concurrent metric writers against snapshot/reset
// walkers is the registry's race surface.

void TestHistBucketBoundaries() {
  using dct::telemetry::Hist;
  using dct::telemetry::kHistBuckets;
  // bucket i holds v <= 2^i: exact powers stay in their own bucket,
  // power+1 spills into the next
  EXPECT(Hist::BucketOf(0) == 0);
  EXPECT(Hist::BucketOf(1) == 0);
  EXPECT(Hist::BucketOf(2) == 1);
  EXPECT(Hist::BucketOf(3) == 2);
  EXPECT(Hist::BucketOf(4) == 2);
  EXPECT(Hist::BucketOf(5) == 3);
  EXPECT(Hist::BucketOf(1024) == 10);
  EXPECT(Hist::BucketOf(1025) == 11);
  EXPECT(Hist::BucketOf(1ull << (kHistBuckets - 1)) == kHistBuckets - 1);
  EXPECT(Hist::BucketOf((1ull << (kHistBuckets - 1)) + 1) == kHistBuckets);
  EXPECT(Hist::BucketOf(~0ull) == kHistBuckets);  // overflow -> +Inf

  Hist h;
  h.Observe(1);
  h.Observe(3);
  h.Observe(1ull << 40);  // overflow bucket
  EXPECT(h.count() == 3);
  EXPECT(h.sum() == 1 + 3 + (1ull << 40));
  EXPECT(h.bucket(0) == 1);
  EXPECT(h.bucket(2) == 1);
  EXPECT(h.bucket(kHistBuckets) == 1);
  uint64_t total = 0;
  for (int i = 0; i <= kHistBuckets; ++i) total += h.bucket(i);
  EXPECT(total == h.count());  // every observation lands in one bucket
  h.Zero();
  EXPECT(h.count() == 0 && h.sum() == 0 && h.bucket(0) == 0);
}

void TestTelemetryRegistryAndSnapshot() {
  namespace tl = dct::telemetry;
  tl::Counter* c = tl::GetCounter("test_snapshot_counter_total");
  EXPECT(c == tl::GetCounter("test_snapshot_counter_total"));  // stable
  c->Add(7);
  tl::Gauge* g = tl::GetGauge("test_snapshot_gauge");
  g->Set(-3);
  tl::Hist* h = tl::GetHist("test_snapshot_us", {{"backend", "t\"est"}});
  h->Observe(5);
  static std::atomic<uint64_t> ext{41};
  tl::RegisterExternalCounter("test_snapshot_external_total", &ext);
  ext.fetch_add(1);

  const std::string s = tl::SnapshotJson();
  // the document must parse as JSON (the Python side consumes it raw)
  std::istringstream is(s);
  dct::JSONReader r(&is);
  std::map<std::string, int> seen;
  r.BeginObject();
  std::string key;
  int version = 0;
  while (r.NextObjectItem(&key)) {
    seen[key] = 1;
    if (key == "version") {
      r.Read(&version);
    } else if (key == "enabled") {
      bool b;
      r.Read(&b);
    } else {
      // counters/gauges/histograms arrays: skip through generically
      r.SkipValue();
    }
  }
  EXPECT(version == tl::kSnapshotVersion);
  EXPECT(seen.count("counters") == 1);
  EXPECT(seen.count("gauges") == 1);
  EXPECT(seen.count("histograms") == 1);
  EXPECT(s.find("\"test_snapshot_counter_total\"") != std::string::npos);
  EXPECT(s.find("\"value\":7") != std::string::npos);
  EXPECT(s.find("\"test_snapshot_gauge\"") != std::string::npos);
  EXPECT(s.find("\"value\":-3") != std::string::npos);
  EXPECT(s.find("\"test_snapshot_external_total\"") != std::string::npos);
  EXPECT(s.find("\"value\":42") != std::string::npos);
  // label values are JSON-escaped
  EXPECT(s.find("\"backend\":\"t\\\"est\"") != std::string::npos);

  tl::Reset();
  EXPECT(c->value() == 0);
  EXPECT(ext.load() == 0);  // external counters reset too
  EXPECT(h->count() == 0);
}

void TestTelemetryEnabledGate() {
  namespace tl = dct::telemetry;
  tl::Hist* h = tl::GetHist("test_gate_us");
  h->Zero();
  tl::SetEnabled(false);
  { tl::ScopedTimerUs t(h); }
  EXPECT(h->count() == 0);  // disabled: no clock read, no observation
  tl::SetEnabled(true);
  { tl::ScopedTimerUs t(h); }
  EXPECT(h->count() == 1);
}

void TestIoHistsPerBackend() {
  namespace tl = dct::telemetry;
  const tl::IoHists* s3 = tl::IoHistsFor("s3");
  EXPECT(s3 == tl::IoHistsFor("s3"));  // cached, pointer-stable
  const tl::IoHists* az = tl::IoHistsFor("azure");
  EXPECT(s3->connect_us != az->connect_us);  // distinct label sets
  s3->connect_us->Observe(9);
  const std::string s = tl::SnapshotJson();
  EXPECT(s.find("\"io_connect_us\"") != std::string::npos);
  EXPECT(s.find("\"backend\":\"s3\"") != std::string::npos);
  EXPECT(s.find("\"backend\":\"azure\"") != std::string::npos);
  tl::Reset();
}

void TestTelemetryConcurrentWritersAndSnapshot() {
  // the TSan target: writers ticking counters/hists + snapshotters walking
  // the registry + a resetter zeroing mid-flight must all be race-free
  namespace tl = dct::telemetry;
  tl::Counter* c = tl::GetCounter("test_conc_total");
  tl::Hist* h = tl::GetHist("test_conc_us");
  std::atomic<bool> stop{false};
  std::vector<std::thread> writers;
  for (int i = 0; i < 4; ++i) {
    writers.emplace_back([&] {
      for (int k = 0; k < 20000; ++k) {
        c->Add(1);
        h->Observe(static_cast<uint64_t>(k));
        // registration races registration: same names resolve to the
        // same objects from every thread
        tl::GetCounter("test_conc_total")->Add(0);
      }
    });
  }
  std::vector<std::thread> readers;
  for (int i = 0; i < 2; ++i) {
    readers.emplace_back([&] {
      while (!stop.load(std::memory_order_relaxed)) {
        const std::string s = tl::SnapshotJson();
        EXPECT(!s.empty());
      }
    });
  }
  std::thread resetter([&] {
    while (!stop.load(std::memory_order_relaxed)) {
      tl::Reset();
      std::this_thread::sleep_for(std::chrono::milliseconds(1));
    }
  });
  for (auto& w : writers) w.join();
  stop.store(true);
  for (auto& r : readers) r.join();
  resetter.join();
  // quiesced determinism: after a final reset + known adds, the snapshot
  // reflects exactly those adds
  tl::Reset();
  c->Add(5);
  EXPECT(c->value() == 5);
  const std::string s = tl::SnapshotJson();
  EXPECT(s.find("\"test_conc_total\"") != std::string::npos);
  tl::Reset();
}

void RunTelemetrySuite() {
  TestHistBucketBoundaries();
  TestTelemetryRegistryAndSnapshot();
  TestTelemetryEnabledGate();
  TestIoHistsPerBackend();
  TestTelemetryConcurrentWritersAndSnapshot();
}

// ---- span ring / distributed tracing (telemetry.h) -- `--trace` suite ----
// Run standalone (test_core --trace) by the cpp/Makefile tsan-trace lane:
// wait-free span writers racing TraceJson/TraceReset walkers is the
// ring's whole race surface.

// count occurrences of a substring (span records in a trace document)
int CountOccurrences(const std::string& s, const std::string& needle) {
  int n = 0;
  for (size_t at = s.find(needle); at != std::string::npos;
       at = s.find(needle, at + needle.size())) {
    ++n;
  }
  return n;
}

void TestTraceSpanBasicsAndParenting() {
  namespace tl = dct::telemetry;
  tl::TraceReset();
  tl::SetEnabled(true);
  {
    tl::TraceSpan outer("trace.outer");
    outer.set_arg(7);
    { tl::TraceSpan inner("trace.inner"); }
  }
  tl::EmitSpan("trace.manual", 100, 50, 9);
  const std::string s = tl::TraceJson();
  // the document must parse as JSON (Python consumes it raw)
  std::istringstream is(s);
  dct::JSONReader r(&is);
  r.BeginObject();
  std::string key;
  int version = 0;
  std::map<std::string, int> seen;
  while (r.NextObjectItem(&key)) {
    seen[key] = 1;
    if (key == "version") {
      r.Read(&version);
    } else {
      r.SkipValue();
    }
  }
  EXPECT(version == 1);
  EXPECT(seen.count("pid") == 1);
  EXPECT(seen.count("anchor") == 1);
  EXPECT(seen.count("spans") == 1);
  EXPECT(s.find("\"wall_us\":") != std::string::npos);
  EXPECT(s.find("\"steady_us\":") != std::string::npos);
  EXPECT(s.find("\"trace.outer\"") != std::string::npos);
  EXPECT(s.find("\"trace.inner\"") != std::string::npos);
  EXPECT(s.find("\"trace.manual\"") != std::string::npos);
  EXPECT(s.find("\"arg\":7") != std::string::npos);
  EXPECT(s.find("\"arg\":9") != std::string::npos);
  EXPECT(s.find("\"dropped\":0") != std::string::npos);
  // parenting: the inner span's parent is the outer span's id. Records
  // land inner-first (completion order); ids allocate outer-first.
  const size_t inner_at = s.find("\"trace.inner\"");
  const size_t outer_at = s.find("\"trace.outer\"");
  EXPECT(inner_at != std::string::npos && outer_at != std::string::npos);
  auto field_after = [&](size_t at, const char* field) -> long long {
    const size_t f = s.find(field, at);
    EXPECT(f != std::string::npos);
    // env-ok: parsing our own just-serialized test document, not env
    return std::atoll(s.c_str() + f + std::strlen(field));
  };
  const long long outer_id = field_after(outer_at, "\"id\":");
  EXPECT(field_after(inner_at, "\"parent\":") == outer_id);
  EXPECT(field_after(outer_at, "\"parent\":") == 0);
  // the manual emit outside any open TraceSpan carries no parent
  EXPECT(field_after(s.find("\"trace.manual\""), "\"parent\":") == 0);
  tl::TraceReset();
}

void TestTraceDisabledGate() {
  namespace tl = dct::telemetry;
  tl::TraceReset();
  tl::SetEnabled(false);
  {
    tl::TraceSpan gated("trace.gated");
    tl::EmitSpan("trace.gated_manual", 1, 1);
  }
  tl::SetEnabled(true);
  const std::string s = tl::TraceJson();
  EXPECT(s.find("\"emitted\":0") != std::string::npos);
  EXPECT(s.find("trace.gated") == std::string::npos);
  tl::TraceReset();
}

void TestTraceRingWraparound() {
  namespace tl = dct::telemetry;
  tl::TraceReset();
  tl::SetEnabled(true);
  const int extra = 100;
  const int total = static_cast<int>(tl::kSpanRingSize) + extra;
  for (int i = 0; i < total; ++i) {
    tl::EmitSpan("trace.wrap", static_cast<uint64_t>(i), 1,
                 static_cast<uint64_t>(i));
  }
  const std::string s = tl::TraceJson();
  EXPECT(s.find("\"emitted\":" + std::to_string(total)) !=
         std::string::npos);
  EXPECT(s.find("\"dropped\":" + std::to_string(extra)) !=
         std::string::npos);
  // the ring holds exactly the most recent kSpanRingSize spans: the
  // first surviving record is span number `extra` (ts == extra), and
  // the record count matches the capacity
  EXPECT(CountOccurrences(s, "\"trace.wrap\"") ==
         static_cast<int>(tl::kSpanRingSize));
  EXPECT(s.find("\"ts\":" + std::to_string(extra) + ",") !=
         std::string::npos);
  EXPECT(s.find("\"ts\":" + std::to_string(extra - 1) + ",") ==
         std::string::npos);
  tl::TraceReset();
}

void TestTraceConcurrentWritersVsSnapshot() {
  // the TSan target: wait-free writers claiming/publishing slots while
  // snapshotters walk the ring and a resetter clears it mid-flight
  namespace tl = dct::telemetry;
  tl::TraceReset();
  tl::SetEnabled(true);
  std::atomic<bool> stop{false};
  std::vector<std::thread> writers;
  for (int i = 0; i < 4; ++i) {
    writers.emplace_back([&, i] {
      for (int k = 0; k < 20000; ++k) {
        tl::TraceSpan span(i % 2 == 0 ? "trace.conc_a" : "trace.conc_b");
        span.set_arg(static_cast<uint64_t>(k));
      }
    });
  }
  std::vector<std::thread> readers;
  for (int i = 0; i < 2; ++i) {
    readers.emplace_back([&] {
      while (!stop.load(std::memory_order_relaxed)) {
        const std::string s = tl::TraceJson();
        EXPECT(!s.empty());
        // a torn record would corrupt the JSON structure; spot-check
        // the bracket balance of every concurrent snapshot
        EXPECT(CountOccurrences(s, "{") == CountOccurrences(s, "}"));
      }
    });
  }
  std::thread resetter([&] {
    while (!stop.load(std::memory_order_relaxed)) {
      tl::TraceReset();
      std::this_thread::sleep_for(std::chrono::milliseconds(1));
    }
  });
  for (auto& w : writers) w.join();
  stop.store(true);
  for (auto& r : readers) r.join();
  resetter.join();
  // quiesced determinism: after a final reset + known emits, the
  // document holds exactly those spans
  tl::TraceReset();
  tl::EmitSpan("trace.final", 1, 2, 3);
  const std::string s = tl::TraceJson();
  EXPECT(CountOccurrences(s, "\"trace.final\"") == 1);
  EXPECT(s.find("\"emitted\":1") != std::string::npos);
  tl::TraceReset();
}

void TestTraceAnchorTracksClocks() {
  namespace tl = dct::telemetry;
  // the anchor pair must be coherent with the clocks it claims to
  // anchor: steady_us within a breath of NowUs
  const std::string s = tl::TraceJson();
  const size_t at = s.find("\"steady_us\":");
  EXPECT(at != std::string::npos);
  // env-ok: parsing our own just-serialized test document, not env
  const long long steady = std::atoll(s.c_str() + at + 12);
  const long long now = static_cast<long long>(tl::NowUs());
  EXPECT(now >= steady && now - steady < 5 * 1000 * 1000);
}

void RunTraceSuite() {
  TestTraceSpanBasicsAndParenting();
  TestTraceDisabledGate();
  TestTraceRingWraparound();
  TestTraceConcurrentWritersVsSnapshot();
  TestTraceAnchorTracksClocks();
}

// ---- transcoding shard cache (shard_cache.h) -- the `--cache` suite ------
// Run standalone (test_core --cache) by the cpp/Makefile asan-cache /
// tsan-cache lanes: concurrent transcoders/readers over one cache unit,
// and the crash-recovery path (temp debris, missing manifest, corrupt
// payload) — the rename/mmap/validate machinery under sanitizers.

std::string WriteCacheCorpus(const std::string& dir, int rows) {
  std::string path = dir + "/corpus.libsvm";
  std::ofstream f(path);
  unsigned s = 12345;
  for (int i = 0; i < rows; ++i) {
    f << (i % 2) << ":" << 1.5 << " qid:" << (i / 8);
    for (int j = 0; j < 10; ++j) {
      s = s * 1664525u + 1013904223u;
      f << ' ' << (j + 1) << ':' << (s % 1000) / 250.0;
    }
    f << '\n';
  }
  return path;
}

// drain a parser into one flat container (the byte-identity probe)
dct::RowBlockContainer<uint32_t> DrainParser(dct::Parser<uint32_t>* p) {
  dct::RowBlockContainer<uint32_t> all;
  dct::RowBlockContainer<uint32_t> block;
  while (p->NextBlockMove(&block)) {
    all.Append(block);
  }
  return all;
}

bool SameBlocks(const dct::RowBlockContainer<uint32_t>& a,
                const dct::RowBlockContainer<uint32_t>& b) {
  return a.offset == b.offset && a.label == b.label &&
         a.weight == b.weight && a.qid == b.qid && a.field == b.field &&
         a.index == b.index && a.value == b.value &&
         a.value_i32 == b.value_i32 && a.value_i64 == b.value_i64 &&
         a.value_dtype == b.value_dtype;
}

dct::ShardCacheParser<uint32_t>* MakeCacheParser(
    const std::string& uri, const std::string& dir, dct::ShardCacheMode mode,
    bool explicit_opt_in = true) {
  dct::ShardCacheConfig cfg;
  cfg.dir = dir;
  cfg.mode = mode;
  cfg.explicit_opt_in = explicit_opt_in;
  const std::string key = dct::ShardCacheKeyText(uri, 0, 1, "libsvm",
                                                 false, {});
  return new dct::ShardCacheParser<uint32_t>(
      [uri]() {
        return dct::Parser<uint32_t>::Create(uri, 0, 1, "libsvm", 2, true);
      },
      cfg, dct::ShardCacheStem(dir, key, 0, 1), key);
}

void TestShardCacheTranscodeThenReplay() {
  dct::TemporaryDirectory tmp;
  const std::string uri = WriteCacheCorpus(tmp.path(), 4000);
  const std::string cdir = tmp.path() + "/cache";
  std::unique_ptr<dct::Parser<uint32_t>> plain(
      dct::Parser<uint32_t>::Create(uri, 0, 1, "libsvm", 2, true));
  auto text = DrainParser(plain.get());
  {
    std::unique_ptr<dct::ShardCacheParser<uint32_t>> p(
        MakeCacheParser(uri, cdir, dct::ShardCacheMode::kAuto));
    EXPECT(!p->replaying());
    EXPECT(SameBlocks(text, DrainParser(p.get())));
    // same handle: the completed pass published; epoch 2 replays
    p->BeforeFirst();
    EXPECT(p->replaying());
    EXPECT(SameBlocks(text, DrainParser(p.get())));
  }
  {
    // fresh handle: replay from construction, base never built
    std::unique_ptr<dct::ShardCacheParser<uint32_t>> p(
        MakeCacheParser(uri, cdir, dct::ShardCacheMode::kAuto));
    EXPECT(p->replaying());
    EXPECT(SameBlocks(text, DrainParser(p.get())));
    // the zero-copy view lane agrees with the container lane
    p->BeforeFirst();
    dct::RowBlockView<uint32_t> v;
    uint64_t rows = 0;
    while (p->NextBlockView(&v)) rows += v.num_rows;
    EXPECT(rows == text.Size());
  }
  {
    // refresh: forced re-transcode, then replay
    std::unique_ptr<dct::ShardCacheParser<uint32_t>> p(
        MakeCacheParser(uri, cdir, dct::ShardCacheMode::kRefresh));
    EXPECT(!p->replaying());
    EXPECT(SameBlocks(text, DrainParser(p.get())));
    p->BeforeFirst();
    EXPECT(p->replaying());
  }
}

void TestShardCacheConcurrentTranscodersAndReaders() {
  // N parsers over the SAME cache unit, started together: several
  // transcode to their own temp simultaneously (atomic rename, last
  // publish wins), stragglers may open the just-published shard — every
  // drain must be byte-identical regardless of which lane it rode
  dct::TemporaryDirectory tmp;
  const std::string uri = WriteCacheCorpus(tmp.path(), 2500);
  const std::string cdir = tmp.path() + "/cache";
  std::unique_ptr<dct::Parser<uint32_t>> plain(
      dct::Parser<uint32_t>::Create(uri, 0, 1, "libsvm", 2, true));
  auto text = DrainParser(plain.get());
  for (int round = 0; round < 2; ++round) {  // round 2: all replay
    constexpr int kWorkers = 4;
    std::vector<std::thread> threads;
    std::atomic<int> ok{0};
    for (int i = 0; i < kWorkers; ++i) {
      threads.emplace_back([&, i] {
        std::unique_ptr<dct::ShardCacheParser<uint32_t>> p(
            MakeCacheParser(uri, cdir, dct::ShardCacheMode::kAuto));
        auto got = DrainParser(p.get());
        // epoch 2 on the same handle flips to replay
        p->BeforeFirst();
        auto again = DrainParser(p.get());
        if (SameBlocks(text, got) && SameBlocks(text, again)) {
          ok.fetch_add(1);
        }
        (void)i;
      });
    }
    for (auto& t : threads) t.join();
    EXPECT(ok.load() == kWorkers);
  }
}

void TestShardCacheCrashRecoveryAndCorruption() {
  dct::TemporaryDirectory tmp;
  const std::string uri = WriteCacheCorpus(tmp.path(), 1500);
  const std::string cdir = tmp.path() + "/cache";
  const std::string key = dct::ShardCacheKeyText(uri, 0, 1, "libsvm",
                                                 false, {});
  const std::string stem = dct::ShardCacheStem(cdir, key, 0, 1);
  // owned probe: TryOpen hands out a new'd reader and a discarded
  // success would leak under the asan lane
  auto opens = [](const std::string& s, const std::string& k) {
    return std::unique_ptr<dct::MmapShardReader<uint32_t>>(
               dct::MmapShardReader<uint32_t>::TryOpen(s, k)) != nullptr;
  };
  std::unique_ptr<dct::Parser<uint32_t>> plain(
      dct::Parser<uint32_t>::Create(uri, 0, 1, "libsvm", 2, true));
  auto text = DrainParser(plain.get());
  // crash debris: a partial temp shard, NO manifest (the writer dies
  // before Finalize) — must be a miss, then a clean re-transcode
  {
    mkdir(cdir.c_str(), 0755);
    std::ofstream(stem + ".dshard.tmp.9999",
                  std::ios::binary) << "partial garbage";
    EXPECT(!opens(stem, key));
    std::unique_ptr<dct::ShardCacheParser<uint32_t>> p(
        MakeCacheParser(uri, cdir, dct::ShardCacheMode::kAuto));
    EXPECT(!p->replaying());
    EXPECT(SameBlocks(text, DrainParser(p.get())));
  }
  // a published, valid unit replays
  EXPECT(opens(stem, key));
  // corrupt payload byte (size unchanged): checksum miss
  {
    std::fstream f(stem + ".dshard",
                   std::ios::in | std::ios::out | std::ios::binary);
    f.seekp(300);
    f.put('\xff');
  }
  EXPECT(!opens(stem, key));
  // the next parser re-transcodes over it and republishes
  {
    std::unique_ptr<dct::ShardCacheParser<uint32_t>> p(
        MakeCacheParser(uri, cdir, dct::ShardCacheMode::kAuto));
    EXPECT(!p->replaying());
    EXPECT(SameBlocks(text, DrainParser(p.get())));
  }
  EXPECT(opens(stem, key));
  // a different key (changed parser args) never opens this unit
  const std::string other = dct::ShardCacheKeyText(
      uri, 0, 1, "libsvm", false, {{"indexing_mode", "one_based"}});
  EXPECT(other != key);
  EXPECT(!opens(stem, other));
  // truncation: recorded size mismatch
  truncate((stem + ".dshard").c_str(), 64);
  EXPECT(!opens(stem, key));
  // manifest gone: miss even with a shard present
  std::remove((stem + ".manifest").c_str());
  EXPECT(!opens(stem, key));
}

void TestShardCacheKeyText() {
  using dct::ShardCacheKeyText;
  const std::string a = ShardCacheKeyText("u", 0, 4, "libsvm", false, {});
  // part/npart/format/index width all key
  EXPECT(a != ShardCacheKeyText("u", 1, 4, "libsvm", false, {}));
  EXPECT(a != ShardCacheKeyText("u", 0, 2, "libsvm", false, {}));
  EXPECT(a != ShardCacheKeyText("u", 0, 4, "csv", false, {}));
  EXPECT(a != ShardCacheKeyText("u", 0, 4, "libsvm", true, {}));
  EXPECT(a != ShardCacheKeyText(
      "u", 0, 4, "libsvm", false, {{"indexing_mode", "one_based"}}));
  // cache-lane selectors and pipeline depth do NOT fragment the key
  EXPECT(a == ShardCacheKeyText("u", 0, 4, "libsvm", false,
                                {{"cache", "refresh"}}));
  EXPECT(a == ShardCacheKeyText("u", 0, 4, "libsvm", false,
                                {{"chunks_in_flight", "7"}}));
  // mode parsing: the checked-arg rule
  bool threw = false;
  try {
    dct::ParseShardCacheMode("?cache", "fresh", dct::ShardCacheMode::kAuto);
  } catch (const dct::Error&) {
    threw = true;
  }
  EXPECT(threw);
}

// ---- concurrent ranged-read engine (range_reader.h) -- `--range` suite ---
// Run standalone (test_core --range) by the cpp/Makefile asan-range /
// tsan-range lanes: N worker threads racing claims/deposits against the
// consumer (and its seeks) is exactly where ordering or shutdown bugs
// would hide. The fetcher here is in-memory — no sockets — so every case
// is deterministic; the live-backend coverage is tests/test_io_ranged.py.

std::string RangePseudoPayload(size_t n, uint32_t seed) {
  std::string s(n, '\0');
  uint64_t x = seed * 2654435761ULL + 1;
  for (size_t i = 0; i < n; ++i) {
    x = x * 6364136223846793005ULL + 1442695040888963407ULL;
    s[i] = static_cast<char>(x >> 56);
  }
  return s;
}

class ScriptedRangeFetcher : public dct::io::RangeFetcher {
 public:
  explicit ScriptedRangeFetcher(std::string payload)
      : payload_(std::move(payload)) {}

  std::atomic<int> fetches{0};
  // runs before the copy; may throw or return kDegraded
  std::function<dct::io::FetchStatus(size_t off, size_t len, int nth)> hook;

  dct::io::FetchStatus Fetch(size_t off, size_t len, char* buf,
                             size_t* progress) override {
    int nth = ++fetches;
    if (hook) {
      dct::io::FetchStatus st = hook(off, len, nth);
      if (st != dct::io::FetchStatus::kOk) return st;
    }
    EXPECT(off + len <= payload_.size());
    std::memcpy(buf, payload_.data() + off, len);
    *progress = len;
    return dct::io::FetchStatus::kOk;
  }

 private:
  std::string payload_;
};

dct::io::RetryPolicy RangeFastPolicy() {
  dct::io::RetryPolicy p;
  p.max_retry = 8;
  p.backoff_base_ms = 1;
  p.backoff_cap_ms = 2;
  p.deadline_ms = 0;
  p.jitter_seed = 7;
  return p;
}

dct::io::RangeConfig RangeSmallCfg() {
  dct::io::RangeConfig c;
  c.enabled = true;
  c.min_bytes = 8 << 10;
  c.max_bytes = 64 << 10;
  c.max_concurrency = 4;
  return c;
}

std::string RangeReadAll(dct::SeekStream* s, size_t chunk = 37 * 1024) {
  std::string out;
  std::vector<char> buf(chunk);
  while (true) {
    size_t n = s->Read(buf.data(), buf.size());
    if (n == 0) break;
    out.append(buf.data(), n);
  }
  return out;
}

dct::SeekStream* RangeNeverSequential() {
  // tests that must not degrade hand this factory in: calling it is a bug
  EXPECT(false);
  return new dct::MemoryStream(std::string());
}

void TestRangeConfigEnvAndUriArgs() {
  setenv("DMLC_IO_RANGE", "0", 1);
  setenv("DMLC_IO_RANGE_MIN_BYTES", "8192", 1);
  setenv("DMLC_IO_RANGE_MAX_BYTES", "4096", 1);  // < min: normalized up
  setenv("DMLC_IO_RANGE_CONCURRENCY", "3", 1);
  dct::io::RangeConfig c = dct::io::RangeConfig::FromEnv();
  EXPECT(!c.enabled);
  EXPECT(c.min_bytes == 8192);
  EXPECT(c.max_bytes == 8192);
  EXPECT(c.max_concurrency == 3);
  setenv("DMLC_IO_RANGE_MIN_BYTES", "banana", 1);
  bool threw = false;
  try {
    dct::io::RangeConfig::FromEnv();
  } catch (const dct::Error&) {
    threw = true;
  }
  EXPECT(threw);  // typo'd knob errors, never silently defaults
  unsetenv("DMLC_IO_RANGE");
  unsetenv("DMLC_IO_RANGE_MIN_BYTES");
  unsetenv("DMLC_IO_RANGE_MAX_BYTES");
  unsetenv("DMLC_IO_RANGE_CONCURRENCY");

  // per-open URI args: range family peeled, retry family still applied,
  // non-io args survive
  std::string path =
      "/obj?io_range=0&io_range_min_bytes=16384&foo=1&io_max_retry=2";
  dct::io::RetryPolicy p;
  dct::io::RangeConfig rc;
  int tmo = 0;
  dct::io::ExtractUriIoArgs(&path, &p, &tmo, &rc);
  EXPECT(path == "/obj?foo=1");
  EXPECT(!rc.enabled);
  EXPECT(rc.min_bytes == 16384);
  EXPECT(p.max_retry == 2);

  threw = false;
  try {
    std::string bad = "/o?io_range_concurrency=banana";
    dct::io::ExtractUriIoArgs(&bad, &p, &tmo, &rc);
  } catch (const dct::Error&) {
    threw = true;
  }
  EXPECT(threw);

  threw = false;
  try {
    std::string bad = "/o?io_rang=1";  // typo'd io_* arg: loud error
    dct::io::ExtractUriIoArgs(&bad, &p, &tmo, &rc);
  } catch (const dct::Error& e) {
    threw = std::string(e.what()).find("io_range") != std::string::npos;
  }
  EXPECT(threw);
}

void TestContentRangeHelpers() {
  EXPECT(dct::RangeHeader(0, 10) == "bytes=0-9");
  EXPECT(dct::RangeHeader(4096, 4096) == "bytes=4096-8191");
  dct::HttpResponse h;
  EXPECT(dct::ContentRangeStart(h) == -1);  // absent: tolerated
  h.headers["content-range"] = "bytes 100-199/500";
  EXPECT(dct::ContentRangeStart(h) == 100);
  dct::CheckContentRangeStart(h, 100, "http", "x");  // aligned: fine
  bool threw = false;
  try {
    dct::CheckContentRangeStart(h, 50, "http", "x");
  } catch (const dct::Error&) {
    threw = true;  // misaligned: retryable error, never a silent splice
  }
  EXPECT(threw);
}

void TestRangeReaderByteIdentical() {
  const std::string payload = RangePseudoPayload(1 << 20, 3);
  auto f = std::make_unique<ScriptedRangeFetcher>(payload);
  // stagger fetch latency by offset so completions land out of order —
  // head-of-line delivery must still be byte-identical
  f->hook = [](size_t off, size_t, int) {
    std::this_thread::sleep_for(
        std::chrono::milliseconds((off / (8 << 10)) % 3));
    return dct::io::FetchStatus::kOk;
  };
  dct::io::RangeReader r("rangetest", payload.size(), std::move(f),
                         &RangeNeverSequential, RangeSmallCfg(),
                         RangeFastPolicy(), 0);
  EXPECT(RangeReadAll(&r) == payload);
  dct::io::RangeReader::Stats st = r.stats();
  EXPECT(st.ranges_fetched >= 2);
  EXPECT(!st.degraded);
}

void TestRangeReaderPerRangeRetryIsolation() {
  const std::string payload = RangePseudoPayload(256 << 10, 4);
  auto f = std::make_unique<ScriptedRangeFetcher>(payload);
  ScriptedRangeFetcher* fp = f.get();
  std::atomic<int> faults{0};
  f->hook = [&faults](size_t off, size_t, int) -> dct::io::FetchStatus {
    if (off == (16 << 10) && faults.fetch_add(1) == 0) {
      throw dct::Error("injected mid-range fault");
    }
    return dct::io::FetchStatus::kOk;
  };
  dct::io::RangeConfig cfg;
  cfg.min_bytes = 16 << 10;
  cfg.max_bytes = 16 << 10;  // fixed 16K ranges: exactly 16 over 256K
  cfg.max_concurrency = 2;
  dct::io::RangeReader r("rangetest", payload.size(), std::move(f),
                         &RangeNeverSequential, cfg, RangeFastPolicy(), 0);
  EXPECT(RangeReadAll(&r) == payload);
  dct::io::RangeReader::Stats st = r.stats();
  EXPECT(st.range_retries == 1);   // only the faulted range retried
  EXPECT(fp->fetches.load() == 17);  // 16 ranges + 1 refetch, no restart
  EXPECT(!st.degraded);
}

void TestRangeReaderMidRangeTruncationResumes() {
  const std::string payload = RangePseudoPayload(128 << 10, 10);
  // every fetch delivers HALF of what was asked, then dies — the retry
  // must resume WITHIN the range (offset+progress); refetch-from-scratch
  // would never converge against this server shape
  class HalfFetcher : public dct::io::RangeFetcher {
   public:
    explicit HalfFetcher(const std::string& p) : p_(p) {}
    std::atomic<int> fetches{0};
    dct::io::FetchStatus Fetch(size_t off, size_t len, char* buf,
                               size_t* progress) override {
      ++fetches;
      if (len <= 512) {
        std::memcpy(buf, p_.data() + off, len);
        *progress = len;
        return dct::io::FetchStatus::kOk;
      }
      const size_t half = len / 2;
      std::memcpy(buf, p_.data() + off, half);
      *progress = half;
      throw dct::Error("mid-range truncation");
    }

   private:
    const std::string& p_;
  };
  auto f = std::make_unique<HalfFetcher>(payload);
  dct::io::RangeConfig cfg;
  cfg.min_bytes = 16 << 10;
  cfg.max_bytes = 16 << 10;
  cfg.max_concurrency = 2;
  dct::io::RangeReader r("rangetest", payload.size(), std::move(f),
                         &RangeNeverSequential, cfg, RangeFastPolicy(), 0);
  EXPECT(RangeReadAll(&r) == payload);
  dct::io::RangeReader::Stats st = r.stats();
  EXPECT(st.range_retries > 0);
  EXPECT(!st.degraded);
}

void TestRangeReaderDegradeTo200Fallback() {
  const std::string payload = RangePseudoPayload(200 << 10, 5);
  auto f = std::make_unique<ScriptedRangeFetcher>(payload);
  f->hook = [](size_t off, size_t, int) {
    // the origin answers 200 (ignores Range) for any non-zero offset
    return off > 0 ? dct::io::FetchStatus::kDegraded
                   : dct::io::FetchStatus::kOk;
  };
  // the fallback stands in for the backend's sequential stream (which
  // inherits the 200-resume budget rule by construction)
  dct::io::RangeReader r(
      "rangetest", payload.size(), std::move(f),
      [payload]() -> dct::SeekStream* {
        return new dct::MemoryStream(payload);
      },
      RangeSmallCfg(), RangeFastPolicy(), 0);
  EXPECT(RangeReadAll(&r) == payload);
  EXPECT(r.stats().degraded);
}

void TestRangeReaderSeekReset() {
  const std::string payload = RangePseudoPayload(512 << 10, 6);
  auto f = std::make_unique<ScriptedRangeFetcher>(payload);
  dct::io::RangeConfig cfg;
  cfg.min_bytes = 16 << 10;
  cfg.max_bytes = 32 << 10;
  cfg.max_concurrency = 3;
  dct::io::RangeReader r("rangetest", payload.size(), std::move(f),
                         &RangeNeverSequential, cfg, RangeFastPolicy(), 0);
  std::vector<char> buf(20000);
  size_t n = r.Read(buf.data(), 10000);
  EXPECT(n > 0);
  EXPECT(std::memcmp(buf.data(), payload.data(), n) == 0);
  r.Seek(300000);  // forward past the readahead window: plan restart
  EXPECT(r.Tell() == 300000);
  size_t m = r.Read(buf.data(), 5000);
  EXPECT(m > 0);
  EXPECT(std::memcmp(buf.data(), payload.data() + 300000, m) == 0);
  r.Seek(100);  // backward: plan restart again
  std::string tail = RangeReadAll(&r);
  EXPECT(tail == payload.substr(100));
  EXPECT(r.stats().discontinuities >= 1);
}

void TestRangeReaderBackwardSeekIntoLateLanding() {
  // regression: forward-seek past an IN-FLIGHT low range, read (trimming
  // the landed mids as waste), let the low range land late, then seek
  // BACKWARD into it. Treating that island as "within plan" would serve
  // its bytes and then hang forever at its end — the mid ranges were
  // trimmed and nobody re-carves them. A backward seek must restart.
  const std::string payload = RangePseudoPayload(512 << 10, 12);
  auto f = std::make_unique<ScriptedRangeFetcher>(payload);
  std::atomic<int> slow_hits{0};
  f->hook = [&slow_hits](size_t off, size_t, int) {
    if (off == (64 << 10) && slow_hits.fetch_add(1) == 0) {
      std::this_thread::sleep_for(std::chrono::milliseconds(300));
    }
    return dct::io::FetchStatus::kOk;
  };
  dct::io::RangeConfig cfg;
  cfg.min_bytes = 64 << 10;
  cfg.max_bytes = 64 << 10;
  cfg.max_concurrency = 4;
  dct::io::RangeReader r("rangetest", payload.size(), std::move(f),
                         &RangeNeverSequential, cfg, RangeFastPolicy(), 0);
  std::vector<char> buf(1024);
  EXPECT(r.Read(buf.data(), buf.size()) > 0);   // range [0,64K) serves
  r.Seek(200 << 10);  // forward past the slow in-flight [64K,128K) range
  EXPECT(r.Read(buf.data(), buf.size()) > 0);   // trims the landed mids
  std::this_thread::sleep_for(std::chrono::milliseconds(400));
  r.Seek(80 << 10);   // backward INTO the late-landed island
  std::string rest = RangeReadAll(&r);          // must not hang
  EXPECT(rest == payload.substr(80 << 10));
  // the backward seek restarted the plan (the forward one may or may not
  // have, depending on how far the carve frontier had run)
  EXPECT(r.stats().discontinuities >= 1);
}

void TestRangeReaderReadBoundLimitsCarve() {
  // a partitioned split reads only to its partition edge: with a
  // HintReadBound the engine must not prefetch a readahead window past
  // it (the boundary-waste shape), yet reads beyond must still work
  const std::string payload = RangePseudoPayload(1 << 20, 11);
  auto f = std::make_unique<ScriptedRangeFetcher>(payload);
  ScriptedRangeFetcher* fp = f.get();
  dct::io::RangeConfig cfg;
  cfg.min_bytes = 64 << 10;
  cfg.max_bytes = 64 << 10;  // fixed 64K ranges
  cfg.max_concurrency = 4;
  dct::io::RangeReader r("rangetest", payload.size(), std::move(f),
                         &RangeNeverSequential, cfg, RangeFastPolicy(), 0);
  const size_t bound = 256 << 10;  // "partition edge" at 256K = 4 ranges
  r.HintReadBound(bound);
  std::string got;
  std::vector<char> buf(32 << 10);
  while (got.size() < bound) {
    size_t n = r.Read(buf.data(),
                      std::min(buf.size(), bound - got.size()));
    EXPECT(n > 0);
    got.append(buf.data(), n);
  }
  EXPECT(got == payload.substr(0, bound));
  // give any (wrongly) carved extra range time to land, then check: only
  // the 4 in-bound ranges were ever fetched
  std::this_thread::sleep_for(std::chrono::milliseconds(50));
  EXPECT(fp->fetches.load() == 4);
  // reading past the hint clears it and carving resumes
  std::string rest = RangeReadAll(&r);
  EXPECT(rest == payload.substr(bound));
  EXPECT(fp->fetches.load() == 16);
}

void TestRangeReaderShutdownMidFlight() {
  const std::string payload = RangePseudoPayload(256 << 10, 7);
  auto f = std::make_unique<ScriptedRangeFetcher>(payload);
  f->hook = [](size_t, size_t, int) {
    std::this_thread::sleep_for(std::chrono::milliseconds(150));
    return dct::io::FetchStatus::kOk;
  };
  dct::io::RangeConfig cfg = RangeSmallCfg();
  auto* r = new dct::io::RangeReader("rangetest", payload.size(),
                                     std::move(f), &RangeNeverSequential,
                                     cfg, RangeFastPolicy(), 0);
  char b[1024];
  size_t n = r->Read(b, sizeof(b));  // starts workers, waits for the head
  EXPECT(n > 0);
  auto t0 = std::chrono::steady_clock::now();
  delete r;  // several fetches in flight: must join promptly, not hang
  auto dtor_ms = std::chrono::duration_cast<std::chrono::milliseconds>(
                     std::chrono::steady_clock::now() - t0)
                     .count();
  EXPECT(dtor_ms < 2000);
}

void TestRangeReaderShutdownInterruptsBackoff() {
  // a worker parked in a multi-second late-ladder backoff must notice
  // shutdown within the ~100 ms slice, not wait the sleep out — stream
  // teardown (parser close, next file) happens on the consumer's clock
  const std::string payload = RangePseudoPayload(256 << 10, 13);
  auto f = std::make_unique<ScriptedRangeFetcher>(payload);
  f->hook = [](size_t off, size_t, int) -> dct::io::FetchStatus {
    if (off >= (64 << 10)) throw dct::Error("always-failing tail");
    return dct::io::FetchStatus::kOk;
  };
  dct::io::RetryPolicy p = RangeFastPolicy();
  p.backoff_base_ms = 3000;  // workers park in 3-6 s sleeps
  p.backoff_cap_ms = 6000;
  p.max_retry = 50;
  dct::io::RangeConfig cfg;
  cfg.min_bytes = 64 << 10;
  cfg.max_bytes = 64 << 10;
  cfg.max_concurrency = 3;
  auto* r = new dct::io::RangeReader("rangetest", payload.size(),
                                     std::move(f), &RangeNeverSequential,
                                     cfg, p, 0);
  char b[1024];
  EXPECT(r->Read(b, sizeof(b)) > 0);  // head range fine; tail retrying
  std::this_thread::sleep_for(std::chrono::milliseconds(100));
  auto t0 = std::chrono::steady_clock::now();
  delete r;
  auto dtor_ms = std::chrono::duration_cast<std::chrono::milliseconds>(
                     std::chrono::steady_clock::now() - t0)
                     .count();
  EXPECT(dtor_ms < 1500);
}

void TestRangeReaderNonRetryableFails() {
  const std::string payload = RangePseudoPayload(64 << 10, 8);
  auto f = std::make_unique<ScriptedRangeFetcher>(payload);
  f->hook = [](size_t, size_t, int) -> dct::io::FetchStatus {
    throw dct::HttpStatusError("gone", 404);
  };
  dct::io::RangeConfig cfg;
  cfg.min_bytes = 8 << 10;
  cfg.max_bytes = 8 << 10;
  cfg.max_concurrency = 2;
  dct::io::RangeReader r("rangetest", payload.size(), std::move(f),
                         &RangeNeverSequential, cfg, RangeFastPolicy(), 0);
  bool threw = false;
  try {
    char b[1024];
    r.Read(b, sizeof(b));
  } catch (const dct::HttpStatusError& e) {
    threw = e.status == 404;
  }
  EXPECT(threw);  // definitive statuses fail fast, exactly like sequential
  EXPECT(r.stats().range_retries == 0);
}

void TestNewRangedOrSequentialGate() {
  const std::string payload = RangePseudoPayload(64 << 10, 9);
  dct::io::RangeConfig cfg;
  cfg.min_bytes = 64 << 10;  // file < 2 ranges: sequential wins
  cfg.max_bytes = 64 << 10;
  cfg.max_concurrency = 4;
  auto seq = [payload]() -> dct::SeekStream* {
    return new dct::MemoryStream(payload);
  };
  std::unique_ptr<dct::SeekStream> small(dct::io::NewRangedOrSequential(
      "rangetest", payload.size(),
      std::make_unique<ScriptedRangeFetcher>(payload), seq, cfg,
      RangeFastPolicy(), 0));
  EXPECT(dynamic_cast<dct::io::RangeReader*>(small.get()) == nullptr);
  EXPECT(RangeReadAll(small.get()) == payload);

  cfg.min_bytes = 8 << 10;  // big enough now, but the kill switch is off
  cfg.enabled = false;
  std::unique_ptr<dct::SeekStream> killed(dct::io::NewRangedOrSequential(
      "rangetest", payload.size(),
      std::make_unique<ScriptedRangeFetcher>(payload), seq, cfg,
      RangeFastPolicy(), 0));
  EXPECT(dynamic_cast<dct::io::RangeReader*>(killed.get()) == nullptr);

  cfg.enabled = true;
  std::unique_ptr<dct::SeekStream> ranged(dct::io::NewRangedOrSequential(
      "rangetest", payload.size(),
      std::make_unique<ScriptedRangeFetcher>(payload), seq, cfg,
      RangeFastPolicy(), 0));
  EXPECT(dynamic_cast<dct::io::RangeReader*>(ranged.get()) != nullptr);
  EXPECT(RangeReadAll(ranged.get()) == payload);
}

void RunRangeReaderSuite() {
  TestRangeConfigEnvAndUriArgs();
  TestContentRangeHelpers();
  TestRangeReaderByteIdentical();
  TestRangeReaderPerRangeRetryIsolation();
  TestRangeReaderMidRangeTruncationResumes();
  TestRangeReaderDegradeTo200Fallback();
  TestRangeReaderSeekReset();
  TestRangeReaderBackwardSeekIntoLateLanding();
  TestRangeReaderReadBoundLimitsCarve();
  TestRangeReaderShutdownMidFlight();
  TestRangeReaderShutdownInterruptsBackoff();
  TestRangeReaderNonRetryableFails();
  TestNewRangedOrSequentialGate();
}

// ---- deterministic shard-cache fuzz driver (--fuzz-shard) ----------------
// Seeded mutation of the published shard + manifest bytes: every mutated
// unit must either be rejected as a clean validation MISS or open into a
// reader whose every view walks strictly inside the mapping — never a
// crash, hang, or out-of-bounds read. Runs under the asan-cache and
// ubsan-test lanes (cpp/Makefile), where an OOB pointer aimed by a corrupt
// block length dies loudly instead of silently serving garbage.

std::string FuzzSlurp(const std::string& path) {
  std::ifstream f(path, std::ios::binary);
  std::ostringstream os;
  os << f.rdbuf();
  return os.str();
}

void FuzzSpew(const std::string& path, const std::string& bytes) {
  std::ofstream f(path, std::ios::binary | std::ios::trunc);
  f.write(bytes.data(), static_cast<std::streamsize>(bytes.size()));
}

// touch every byte a view exposes so ASan/UBSan observe the full walk
uint64_t FuzzWalkReader(dct::MmapShardReader<uint32_t>* r) {
  uint64_t acc = 0;
  dct::RowBlockView<uint32_t> v;
  while (r->NextView(&v)) {
    for (uint64_t i = 0; i <= v.num_rows; ++i) acc += v.offset[i];
    for (uint64_t i = 0; i < v.num_rows; ++i) {
      acc += static_cast<uint64_t>(v.label[i]);
      if (v.weight != nullptr) acc += static_cast<uint64_t>(v.weight[i]);
      if (v.qid != nullptr) acc += v.qid[i];
    }
    for (uint64_t i = 0; i < v.nnz; ++i) {
      acc += v.index[i];
      if (v.field != nullptr) acc += v.field[i];
      if (v.value != nullptr) acc += static_cast<uint64_t>(v.value[i]);
      if (v.value_i32 != nullptr) {
        acc += static_cast<uint64_t>(v.value_i32[i]);
      }
      if (v.value_i64 != nullptr) {
        acc += static_cast<uint64_t>(v.value_i64[i]);
      }
    }
  }
  return acc;
}

void FuzzShardCache(int iters) {
  dct::TemporaryDirectory tmp;
  const std::string uri = WriteCacheCorpus(tmp.path(), 600);
  const std::string cdir = tmp.path() + "/cache";
  const std::string key = dct::ShardCacheKeyText(uri, 0, 1, "libsvm",
                                                 false, {});
  const std::string stem = dct::ShardCacheStem(cdir, key, 0, 1);
  {
    // publish one valid unit to mutate
    std::unique_ptr<dct::ShardCacheParser<uint32_t>> p(
        MakeCacheParser(uri, cdir, dct::ShardCacheMode::kAuto));
    DrainParser(p.get());
  }
  const std::string shard0 = FuzzSlurp(stem + ".dshard");
  const std::string mani0 = FuzzSlurp(stem + ".manifest");
  EXPECT(shard0.size() > 128 && !mani0.empty());

  // fixed-seed splitmix-style generator: the run is fully deterministic
  uint64_t state = 0x9e3779b97f4a7c15ull;
  auto rnd = [&state]() {
    state = state * 6364136223846793005ull + 1442695040888963407ull;
    return state >> 11;
  };

  int opened = 0, missed = 0;
  for (int iter = 0; iter < iters; ++iter) {
    std::string shard = shard0;
    std::string mani = mani0;
    const uint64_t what = rnd() % 10;
    if (what < 5) {
      // shard byte flips — half biased into the first 512 B (file header
      // + first block header, where a corrupt length would aim pointers
      // past the mapping), half anywhere (checksum coverage)
      const int flips = 1 + static_cast<int>(rnd() % 4);
      for (int i = 0; i < flips; ++i) {
        const size_t zone =
            rnd() % 2 == 0 ? std::min<size_t>(shard.size(), 512)
                           : shard.size();
        const size_t off = rnd() % zone;
        shard[off] = static_cast<char>(
            shard[off] ^ static_cast<char>(1u << (rnd() % 8)));
      }
    } else if (what < 7) {
      // truncate or extend the shard (recorded-size mismatch + mappings
      // shorter than the headers claim)
      shard.resize(rnd() % (shard0.size() + 64),
                   static_cast<char>(rnd() % 256));
    } else if (what < 9) {
      // manifest mutations: flips or truncation of the k=v lines
      if (rnd() % 2 == 0 && !mani.empty()) {
        const int flips = 1 + static_cast<int>(rnd() % 3);
        for (int i = 0; i < flips; ++i) {
          const size_t off = rnd() % mani.size();
          mani[off] = static_cast<char>(
              mani[off] ^ static_cast<char>(1u << (rnd() % 8)));
        }
      } else {
        mani.resize(rnd() % (mani0.size() + 1));
      }
    } else {
      // cross-unit splice: a valid-looking header over garbage payload
      const size_t keep = 80 + rnd() % 64;
      shard = shard0.substr(0, std::min(keep, shard0.size()));
      shard.resize(shard0.size(), static_cast<char>(rnd() % 256));
    }
    FuzzSpew(stem + ".dshard", shard);
    FuzzSpew(stem + ".manifest", mani);
    std::unique_ptr<dct::MmapShardReader<uint32_t>> r(
        dct::MmapShardReader<uint32_t>::TryOpen(stem, key));
    if (r == nullptr) {
      ++missed;  // clean miss: the text lane would re-transcode
      continue;
    }
    // a survivor (mutation in don't-care bytes, or didn't change the
    // payload the checksum covers) must walk fully in bounds
    ++opened;
    (void)FuzzWalkReader(r.get());
    r->BeforeFirst();
    (void)FuzzWalkReader(r.get());
  }
  // pristine bytes restored: the unit must validate and replay again
  FuzzSpew(stem + ".dshard", shard0);
  FuzzSpew(stem + ".manifest", mani0);
  std::unique_ptr<dct::MmapShardReader<uint32_t>> r(
      dct::MmapShardReader<uint32_t>::TryOpen(stem, key));
  EXPECT(r != nullptr);
  EXPECT(FuzzWalkReader(r.get()) != 0);
  // the overwhelming majority of mutations must be rejected (every flip
  // of a checksummed byte); a run where most opened would mean validation
  // stopped looking at the payload
  EXPECT(missed > opened);
  std::printf("fuzz-shard: %d mutations, %d clean misses, %d replayed "
              "in-bounds\n", missed + opened, missed, opened);
}

void RunShardCacheSuite() {
  TestShardCacheKeyText();
  TestShardCacheTranscodeThenReplay();
  TestShardCacheConcurrentTranscodersAndReaders();
  TestShardCacheCrashRecoveryAndCorruption();
}

// ---- local-durability plane (fs_fault.h) -- the `--fsfault` suite --------
// Run standalone (test_core --fsfault) by the cpp/Makefile asan-fsfault
// lane: the DMLC_FS_FAULT_PLAN matrix across transcode / publish / replay
// / local streams, asserting every outcome is exactly one of {clean miss
// + re-transcode, byte-identical replay, structured loud error} — never
// corrupt bytes, never a wedged pass. Each case clears the plan on exit
// (an explicit clear beats the env forever).

// RAII plan guard: a failing EXPECT mid-case must not leak a plan into
// the next case.
struct ScopedFsPlan {
  explicit ScopedFsPlan(const std::string& plan) {
    dct::fsio::SetFsFaultPlan(plan);
  }
  ~ScopedFsPlan() { dct::fsio::SetFsFaultPlan(""); }
};

uint64_t FsFaultCount(const char* op) {
  return dct::telemetry::GetCounter("fs_fault_injected_total",
                                    {{"op", op}})->value();
}

uint64_t CacheWriteErrors() {
  return dct::telemetry::GetCounter("cache_write_errors_total")->value();
}

bool DirHas(const std::string& dir, const std::string& needle,
            bool suffix = false) {
  std::vector<dct::FileInfo> items;
  dct::FileSystem::GetInstance(dct::URI(dir.c_str()))
      ->ListDirectory(dct::URI(dir.c_str()), &items);
  for (const auto& fi : items) {
    const std::string& p = fi.path.path;
    if (suffix) {
      if (p.size() >= needle.size() &&
          p.compare(p.size() - needle.size(), needle.size(), needle) == 0) {
        return true;
      }
    } else if (p.find(needle) != std::string::npos) {
      return true;
    }
  }
  return false;
}

void TestFsFaultPlanGrammar() {
  const char* bad[] = {
      "write",                           // no params
      "write:every=2",                   // no fault
      "write:fault=eio",                 // no selector
      "write:fault=bogus,every=2",       // unknown fault
      "frobnicate:fault=eio,every=2",    // unknown op
      "read:fault=torn_rename,every=1",  // impossible combo
      "mmap:fault=short_write,every=1",  // impossible combo
      "write:fault=eio,every=0",         // every < 1
      "write:fault=eio,p=1.5",           // p out of range
      "write:fault=eio,garbage",         // malformed param
      "write:fault=eio,every=5,p=1.0",   // both selectors (ambiguous)
  };
  for (const char* plan : bad) {
    bool threw = false;
    try {
      dct::fsio::SetFsFaultPlan(plan);
    } catch (const dct::Error&) {
      threw = true;
    }
    EXPECT(threw);
  }
  // good plans parse (and clear cleanly)
  dct::fsio::SetFsFaultPlan(
      "write:fault=enospc,every=3;rename:fault=torn_rename,p=0.5;"
      "fsync:fault=fsync_fail,every=1;open:fault=eio,p=1.0;"
      "read:fault=eio,every=7;mmap:fault=eio,every=2");
  dct::fsio::SetFsFaultPlan("");
}

void TestFsFaultLocalStreamStructuredErrors() {
  dct::TemporaryDirectory tmp;
  const std::string path = tmp.path() + "/f.bin";
  // injected ENOSPC on write: FsError naming the path + errno text
  {
    ScopedFsPlan plan("write:fault=enospc,every=1");
    std::unique_ptr<dct::Stream> s(dct::Stream::Create(path.c_str(), "w"));
    bool threw = false;
    try {
      s->Write("abcdefgh", 8);
    } catch (const dct::fsio::FsError& e) {
      threw = true;
      EXPECT(std::string(e.what()).find(path) != std::string::npos);
      EXPECT(e.error_number() == ENOSPC);
    }
    EXPECT(threw);
    EXPECT(FsFaultCount("write") >= 1);
  }
  // short_write: HALF the bytes really land before the error — the torn
  // artifact crash-consistent callers must clean up
  {
    ScopedFsPlan plan("write:fault=short_write,every=2");
    std::unique_ptr<dct::Stream> s(dct::Stream::Create(path.c_str(), "w"));
    s->Write("12345678", 8);  // op 1: clean
    bool threw = false;
    try {
      s->Write("abcdefgh", 8);  // op 2: half lands, then ENOSPC
    } catch (const dct::fsio::FsError&) {
      threw = true;
    }
    EXPECT(threw);
    s->Finish();
  }
  {
    std::unique_ptr<dct::SeekStream> r(
        dct::SeekStream::CreateForRead(path.c_str()));
    char buf[32];
    size_t n = r->Read(buf, sizeof(buf));
    EXPECT(n == 12);  // 8 clean + 4 torn
    EXPECT(std::memcmp(buf, "12345678abcd", 12) == 0);
  }
  // injected EIO on read: structured throw, never a silent short read
  {
    ScopedFsPlan plan("read:fault=eio,every=1");
    std::unique_ptr<dct::SeekStream> r(
        dct::SeekStream::CreateForRead(path.c_str()));
    bool threw = false;
    char buf[8];
    try {
      r->Read(buf, sizeof(buf));
    } catch (const dct::fsio::FsError& e) {
      threw = true;
      EXPECT(e.op() == dct::fsio::FsOp::kRead);
    }
    EXPECT(threw);
  }
  // injected open fault honors allow_null (probe shape) and errors
  // loudly otherwise
  {
    ScopedFsPlan plan("open:fault=eio,p=1.0");
    EXPECT(dct::SeekStream::CreateForRead(path.c_str(), true) == nullptr);
    bool threw = false;
    try {
      delete dct::SeekStream::CreateForRead(path.c_str(), false);
    } catch (const dct::Error& e) {
      threw = true;
      EXPECT(std::string(e.what()).find("Input/output") !=
             std::string::npos);
    }
    EXPECT(threw);
  }
}

void TestFsFaultTranscodeDegradesEnvOnlyAndQuarantines() {
  dct::TemporaryDirectory tmp;
  const std::string uri = WriteCacheCorpus(tmp.path(), 3000);
  const std::string cdir = tmp.path() + "/cache";
  std::unique_ptr<dct::Parser<uint32_t>> plain(
      dct::Parser<uint32_t>::Create(uri, 0, 1, "libsvm", 2, true));
  auto text = DrainParser(plain.get());
  const uint64_t errs0 = CacheWriteErrors();
  {
    // ENOSPC mid-tee under an ENV-ONLY cache: the epoch completes on the
    // text lane byte-identically, the partial temp is QUARANTINED, and
    // nothing is published
    ScopedFsPlan plan("write:fault=enospc,every=2");
    std::unique_ptr<dct::ShardCacheParser<uint32_t>> p(MakeCacheParser(
        uri, cdir, dct::ShardCacheMode::kAuto, /*explicit_opt_in=*/false));
    EXPECT(!p->replaying());
    EXPECT(SameBlocks(text, DrainParser(p.get())));
  }
  EXPECT(CacheWriteErrors() > errs0);
  EXPECT(DirHas(cdir, ".quarantined", /*suffix=*/true));
  EXPECT(!DirHas(cdir, ".manifest", /*suffix=*/true));
  {
    // the SAME fault under an EXPLICIT opt-in errors loudly
    ScopedFsPlan plan("write:fault=enospc,every=2");
    std::unique_ptr<dct::ShardCacheParser<uint32_t>> p(MakeCacheParser(
        uri, cdir, dct::ShardCacheMode::kAuto, /*explicit_opt_in=*/true));
    bool threw = false;
    try {
      DrainParser(p.get());
    } catch (const dct::Error&) {
      threw = true;
    }
    EXPECT(threw);
  }
  // plan cleared: transcode publishes and replays byte-identical
  {
    std::unique_ptr<dct::ShardCacheParser<uint32_t>> p(
        MakeCacheParser(uri, cdir, dct::ShardCacheMode::kAuto));
    EXPECT(SameBlocks(text, DrainParser(p.get())));
    p->BeforeFirst();
    EXPECT(p->replaying());
    EXPECT(SameBlocks(text, DrainParser(p.get())));
  }
}

void TestFsFaultPublishFaultsNeverCorrupt() {
  dct::TemporaryDirectory tmp;
  const std::string uri = WriteCacheCorpus(tmp.path(), 2000);
  const std::string cdir = tmp.path() + "/cache";
  std::unique_ptr<dct::Parser<uint32_t>> plain(
      dct::Parser<uint32_t>::Create(uri, 0, 1, "libsvm", 2, true));
  auto text = DrainParser(plain.get());
  const char* publish_plans[] = {
      "fsync:fault=fsync_fail,every=1",   // durability cut at the fsync
      "rename:fault=torn_rename,every=1", // crash-mid-publish artifact
      "rename:fault=eio,every=1",         // plain rename failure
  };
  for (const char* text_plan : publish_plans) {
    // env-only: the pass degrades (text bytes already served), nothing
    // VALID is ever visible under the published names
    {
      ScopedFsPlan plan(text_plan);
      std::unique_ptr<dct::ShardCacheParser<uint32_t>> p(MakeCacheParser(
          uri, cdir, dct::ShardCacheMode::kAuto, /*explicit_opt_in=*/false));
      EXPECT(SameBlocks(text, DrainParser(p.get())));
    }
    // whatever debris the fault left (torn shard, temp, no manifest):
    // the next open is a clean miss that re-transcodes byte-identically,
    // then replays
    {
      std::unique_ptr<dct::ShardCacheParser<uint32_t>> p(
          MakeCacheParser(uri, cdir, dct::ShardCacheMode::kAuto));
      EXPECT(SameBlocks(text, DrainParser(p.get())));
      p->BeforeFirst();
      EXPECT(p->replaying());
      EXPECT(SameBlocks(text, DrainParser(p.get())));
    }
    // explicit opt-in on the same publish fault errors loudly (refresh
    // forces the re-transcode so the publish path actually runs)
    {
      ScopedFsPlan plan(text_plan);
      std::unique_ptr<dct::ShardCacheParser<uint32_t>> p(MakeCacheParser(
          uri, cdir, dct::ShardCacheMode::kRefresh,
          /*explicit_opt_in=*/true));
      bool threw = false;
      try {
        DrainParser(p.get());
      } catch (const dct::Error&) {
        threw = true;
      }
      EXPECT(threw);
    }
    // clean up for the next plan: re-publish a valid unit
    {
      std::unique_ptr<dct::ShardCacheParser<uint32_t>> p(MakeCacheParser(
          uri, cdir, dct::ShardCacheMode::kRefresh));
      EXPECT(SameBlocks(text, DrainParser(p.get())));
    }
  }
}

void TestFsFaultReplayReadFaultsMissCleanly() {
  dct::TemporaryDirectory tmp;
  const std::string uri = WriteCacheCorpus(tmp.path(), 2000);
  const std::string cdir = tmp.path() + "/cache";
  std::unique_ptr<dct::Parser<uint32_t>> plain(
      dct::Parser<uint32_t>::Create(uri, 0, 1, "libsvm", 2, true));
  auto text = DrainParser(plain.get());
  {
    // publish a valid unit
    std::unique_ptr<dct::ShardCacheParser<uint32_t>> p(
        MakeCacheParser(uri, cdir, dct::ShardCacheMode::kAuto));
    EXPECT(SameBlocks(text, DrainParser(p.get())));
  }
  const char* read_plans[] = {
      "mmap:fault=eio,every=1",
      "open:fault=eio,every=2",  // every=2: the text-source fopen draws
                                 // op 1, the shard open draws op 2
      "read:fault=eio,every=1",  // manifest read
  };
  for (const char* text_plan : read_plans) {
    ScopedFsPlan plan(text_plan);
    // validation must MISS (never throw) and the epoch must re-serve
    // correct bytes — from text, re-transcoding when the writes survive
    std::unique_ptr<dct::ShardCacheParser<uint32_t>> p(MakeCacheParser(
        uri, cdir, dct::ShardCacheMode::kAuto, /*explicit_opt_in=*/false));
    EXPECT(!p->replaying());
    bool served = false;
    try {
      served = SameBlocks(text, DrainParser(p.get()));
    } catch (const dct::Error&) {
      // read faults can also hit the text source itself (open/read
      // plans): a structured error is an allowed gauntlet outcome —
      // never corrupt bytes
      served = true;
    }
    EXPECT(served);
  }
  // plans cleared: the published (or re-published) unit still replays
  std::unique_ptr<dct::ShardCacheParser<uint32_t>> p(
      MakeCacheParser(uri, cdir, dct::ShardCacheMode::kAuto));
  EXPECT(p->replaying());
  EXPECT(SameBlocks(text, DrainParser(p.get())));
}

void TestFsFaultGcSweepsStaleTempsOnly() {
  dct::TemporaryDirectory tmp;
  const std::string uri = WriteCacheCorpus(tmp.path(), 600);
  const std::string cdir = tmp.path() + "/cache";
  mkdir(cdir.c_str(), 0755);
  // debris of three ages/shapes: an ancient temp (reap), an ancient
  // quarantined partial (reap), a FRESH temp — a live concurrent
  // transcoder's staging file (keep) — and a foreign user file (keep)
  const std::string old_tmp = cdir + "/deadbeef.p0.n1.dshard.tmp.1.0";
  const std::string old_q =
      cdir + "/deadbeef.p0.n1.dshard.tmp.2.0.quarantined";
  const std::string fresh_tmp = cdir + "/cafe.p0.n1.dshard.tmp.3.0";
  const std::string foreign = cdir + "/users-notes.txt";
  for (const std::string& p : {old_tmp, old_q, fresh_tmp, foreign}) {
    std::ofstream(p) << "x";
  }
  struct utimbuf ancient;
  ancient.actime = ancient.modtime = time(nullptr) - 3 * 86400;
  EXPECT(utime(old_tmp.c_str(), &ancient) == 0);
  EXPECT(utime(old_q.c_str(), &ancient) == 0);
  {
    // writer construction sweeps (the transcode is incidental)
    std::unique_ptr<dct::ShardCacheParser<uint32_t>> p(
        MakeCacheParser(uri, cdir, dct::ShardCacheMode::kAuto));
    DrainParser(p.get());
  }
  EXPECT(!DirHas(cdir, "dshard.tmp.1.0", /*suffix=*/true));
  EXPECT(!DirHas(cdir, ".quarantined", /*suffix=*/true));
  EXPECT(DirHas(cdir, "cafe.p0.n1.dshard.tmp.3.0", /*suffix=*/true));
  EXPECT(DirHas(cdir, "users-notes.txt", /*suffix=*/true));
}

void TestFsFaultRecordIOStructuredTruncation() {
  dct::TemporaryDirectory tmp;
  const std::string path = tmp.path() + "/r.rec";
  {
    std::unique_ptr<dct::Stream> s(dct::Stream::Create(path.c_str(), "w"));
    dct::RecordIOWriter w(s.get());
    for (int i = 0; i < 8; ++i) {
      std::string rec(64 + i, static_cast<char>('a' + i));
      w.WriteRecord(rec.data(), rec.size());
    }
    s->Finish();
  }
  // cut mid-record: the reader must name WHERE the stream broke
  struct stat st;
  EXPECT(stat(path.c_str(), &st) == 0);
  EXPECT(truncate(path.c_str(), st.st_size - 30) == 0);
  {
    std::unique_ptr<dct::SeekStream> s(
        dct::SeekStream::CreateForRead(path.c_str()));
    dct::RecordIOReader r(s.get());
    std::string rec;
    bool threw = false;
    int got = 0;
    try {
      while (r.NextRecord(&rec)) ++got;
    } catch (const dct::Error& e) {
      threw = true;
      EXPECT(std::string(e.what()).find("record 7") != std::string::npos ||
             std::string(e.what()).find("truncated") != std::string::npos);
    }
    EXPECT(threw);
    EXPECT(got == 7);  // every complete record before the tear survives
  }
  // injected EIO below the reader surfaces as a structured FsError
  {
    ScopedFsPlan plan("read:fault=eio,every=2");
    std::unique_ptr<dct::SeekStream> s(
        dct::SeekStream::CreateForRead(path.c_str()));
    dct::RecordIOReader r(s.get());
    std::string rec;
    bool threw = false;
    try {
      while (r.NextRecord(&rec)) {
      }
    } catch (const dct::fsio::FsError& e) {
      threw = true;
      EXPECT(e.op() == dct::fsio::FsOp::kRead);
    }
    EXPECT(threw);
  }
}

void TestFsFaultEveryNDeterminism() {
  dct::TemporaryDirectory tmp;
  const std::string path = tmp.path() + "/n.bin";
  const uint64_t fired0 = FsFaultCount("write");
  ScopedFsPlan plan("write:fault=eio,every=3");
  std::unique_ptr<dct::Stream> s(dct::Stream::Create(path.c_str(), "w"));
  int threw = 0;
  for (int i = 0; i < 12; ++i) {
    try {
      s->Write("x", 1);
    } catch (const dct::fsio::FsError&) {
      ++threw;
    }
  }
  EXPECT(threw == 4);  // ops 3, 6, 9, 12 — exact, not approximate
  EXPECT(FsFaultCount("write") - fired0 == 4);
}

void RunFsFaultSuite() {
  TestFsFaultPlanGrammar();
  TestFsFaultLocalStreamStructuredErrors();
  TestFsFaultTranscodeDegradesEnvOnlyAndQuarantines();
  TestFsFaultPublishFaultsNeverCorrupt();
  TestFsFaultReplayReadFaultsMissCleanly();
  TestFsFaultGcSweepsStaleTempsOnly();
  TestFsFaultRecordIOStructuredTruncation();
  TestFsFaultEveryNDeterminism();
}

}  // namespace

int main(int argc, char** argv) {
  if (argc > 1 && std::string(argv[1]) == "--stdin") {
    TestStdinSplit();
    return 0;
  }
  if (argc > 1 && std::string(argv[1]) == "--telemetry") {
    // the telemetry-registry suite alone — the cpp/Makefile tsan-telemetry
    // lane runs exactly this under ThreadSanitizer (concurrent writers +
    // snapshot/reset walkers)
    RunTelemetrySuite();
    if (g_failures == 0) {
      std::printf("OK\n");
      return 0;
    }
    std::fprintf(stderr, "%d failure(s)\n", g_failures);
    return 1;
  }
  if (argc > 1 && std::string(argv[1]) == "--trace") {
    // the span-ring tracing suite alone — the cpp/Makefile tsan-trace
    // lane runs exactly this under ThreadSanitizer (wait-free span
    // writers racing TraceJson/TraceReset walkers)
    RunTraceSuite();
    if (g_failures == 0) {
      std::printf("OK\n");
      return 0;
    }
    std::fprintf(stderr, "%d failure(s)\n", g_failures);
    return 1;
  }
  if (argc > 1 && std::string(argv[1]) == "--io") {
    // the remote-I/O resilience suite alone — the cpp/Makefile tsan-io
    // lane runs exactly this under ThreadSanitizer (the fault hook and
    // io-retry counters are shared mutable state)
    RunIoResilienceSuite();
    if (g_failures == 0) {
      std::printf("OK\n");
      return 0;
    }
    std::fprintf(stderr, "%d failure(s)\n", g_failures);
    return 1;
  }
  if (argc > 1 && std::string(argv[1]) == "--range") {
    // the concurrent ranged-read suite alone — the cpp/Makefile
    // asan-range / tsan-range lanes run exactly this under sanitizers
    // (worker claims/deposits racing the consumer and its seeks)
    RunRangeReaderSuite();
    if (g_failures == 0) {
      std::printf("OK\n");
      return 0;
    }
    std::fprintf(stderr, "%d failure(s)\n", g_failures);
    return 1;
  }
  if (argc > 1 && std::string(argv[1]) == "--parse") {
    // the SIMD text-ingest suite alone — the cpp/Makefile asan-parse /
    // tsan-parse lanes run exactly this under sanitizers, with
    // DMLC_PARSE_SIMD pinning each dispatch tier
    RunParseSimdSuite();
    if (g_failures == 0) {
      std::printf("OK\n");
      return 0;
    }
    std::fprintf(stderr, "%d failure(s)\n", g_failures);
    return 1;
  }
  if (argc > 1 && std::string(argv[1]) == "--fuzz-shard") {
    // deterministic shard/manifest mutation driver — the asan-cache and
    // ubsan-test lanes run exactly this (validation must yield a clean
    // miss or an in-bounds replay, never a crash/OOB)
    FuzzShardCache(argc > 2 ? std::atoi(argv[2]) : 400);  // env-ok: test CLI
    if (g_failures == 0) {
      std::printf("OK\n");
      return 0;
    }
    std::fprintf(stderr, "%d failure(s)\n", g_failures);
    return 1;
  }
  if (argc > 1 && std::string(argv[1]) == "--fsfault") {
    // the local-durability suite alone — the cpp/Makefile asan-fsfault
    // lane runs exactly this under AddressSanitizer (the quarantine/
    // degrade paths walk mmap pointers and partial buffers)
    RunFsFaultSuite();
    if (g_failures == 0) {
      std::printf("OK\n");
      return 0;
    }
    std::fprintf(stderr, "%d failure(s)\n", g_failures);
    return 1;
  }
  if (argc > 1 && std::string(argv[1]) == "--cache") {
    // the shard-cache suite alone — the cpp/Makefile asan-cache /
    // tsan-cache lanes run exactly this under sanitizers (concurrent
    // transcoders + readers over one unit, crash-recovery validation)
    RunShardCacheSuite();
    if (g_failures == 0) {
      std::printf("OK\n");
      return 0;
    }
    std::fprintf(stderr, "%d failure(s)\n", g_failures);
    return 1;
  }
  if (argc > 1 && std::string(argv[1]) == "--pipeline") {
    // the parse-pipeline concurrency suite alone — the cpp/Makefile
    // tsan-pipeline lane runs exactly this under ThreadSanitizer
    TestParsePipelineOrdered();
    TestParsePipelineRestart();
    TestParsePipelineAbandon();
    TestParsePipelineWorkerThrow();
    TestParsePipelineReaderThrow();
    TestThreadedTextParse();
    TestThreadedRecParse();
    if (g_failures == 0) {
      std::printf("OK\n");
      return 0;
    }
    std::fprintf(stderr, "%d failure(s)\n", g_failures);
    return 1;
  }
  TestMemoryStreams();
  TestIostreamBridge();
  TestTemporaryDirectory();
  TestSingleFileSplit();
  TestJSON();
  TestConcurrentQueue();
  TestMemoryPool();
  TestLockFreeQueue();
  TestThreadGroup();
  TestPipelineExceptionPropagation();
  TestParameter();
  TestParameterFloatRoundTrip();
  TestRegistry();
  TestConfig();
  TestXmlUnescape();
  TestSplitHostPort();
  TestEndianGoldenBytes();
  TestRecordIOGoldenBytes();
  TestBinaryLaneBEDecodeBranches();
  TestGoldenBinaryRecordsDecode();
  TestParsePipelineOrdered();
  TestParsePipelineRestart();
  TestParsePipelineAbandon();
  TestParsePipelineWorkerThrow();
  TestParsePipelineReaderThrow();
  TestThreadedTextParse();
  TestThreadedRecParse();
  RunParseSimdSuite();
  RunIoResilienceSuite();
  RunRangeReaderSuite();
  RunTelemetrySuite();
  RunTraceSuite();
  RunShardCacheSuite();
  RunFsFaultSuite();
  if (g_failures == 0) {
    std::printf("OK\n");
    return 0;
  }
  std::fprintf(stderr, "%d failure(s)\n", g_failures);
  return 1;
}
