// Host-parse microbenchmark: times ParseBlock on synthetic corpora shaped
// like the bench.py datasets (HIGGS-ish libsvm, dense csv, libfm triples).
// Build:  make -C cpp benchparse   Run: ./dmlc_core_tpu/_native/bench_parse
// This is the fast inner loop for parser optimization work — it isolates
// the single-core ParseBlock cost from the split/pipeline/device stages
// (reference keeps equivalent manual probes in test/, e.g.
// test/split_read_test.cc:27-33 printing MB/s).
#include <chrono>
#include <cstdio>
#include <cstring>
#include <random>
#include <string>
#include <vector>

#include "../src/parser.h"
#include "../src/retry.h"

namespace {

using Clock = std::chrono::steady_clock;

double Secs(Clock::time_point a, Clock::time_point b) {
  return std::chrono::duration<double>(b - a).count();
}

std::string MakeLibsvm(int rows, int feats, uint32_t seed) {
  std::mt19937 rng(seed);
  std::uniform_real_distribution<double> val(-3.0, 3.0);
  std::string out;
  out.reserve(static_cast<size_t>(rows) * (feats * 11 + 3));
  char buf[64];
  for (int r = 0; r < rows; ++r) {
    out += (rng() & 1) ? '1' : '0';
    for (int f = 0; f < feats; ++f) {
      snprintf(buf, sizeof(buf), " %d:%.6f", f, val(rng));
      out += buf;
    }
    out += '\n';
  }
  return out;
}

std::string MakeCSV(int rows, int cols, uint32_t seed) {
  std::mt19937 rng(seed);
  std::uniform_real_distribution<double> val(-3.0, 3.0);
  std::string out;
  out.reserve(static_cast<size_t>(rows) * (cols * 10 + 3));
  char buf[64];
  for (int r = 0; r < rows; ++r) {
    out += (rng() & 1) ? '1' : '0';
    for (int c = 0; c < cols; ++c) {
      snprintf(buf, sizeof(buf), ",%.6f", val(rng));
      out += buf;
    }
    out += '\n';
  }
  return out;
}

std::string MakeLibfm(int rows, int feats, uint32_t seed) {
  std::mt19937 rng(seed);
  std::uniform_real_distribution<double> val(-3.0, 3.0);
  std::string out;
  out.reserve(static_cast<size_t>(rows) * (feats * 14 + 3));
  char buf[64];
  for (int r = 0; r < rows; ++r) {
    out += (rng() & 1) ? '1' : '0';
    for (int f = 0; f < feats; ++f) {
      snprintf(buf, sizeof(buf), " %d:%d:%.6f", f % 7, f, val(rng));
      out += buf;
    }
    out += '\n';
  }
  return out;
}

template <typename ParserT>
void BenchFormat(const char* name, const std::string& corpus,
                 const std::map<std::string, std::string>& args, int reps) {
  ParserT parser(nullptr, args, 1);
  dct::RowBlockContainer<uint32_t> out;
  // warm
  parser.ParseBlock(corpus.data(), corpus.data() + corpus.size(), &out);
  const size_t rows = out.Size();
  double best = 1e30;
  for (int i = 0; i < reps; ++i) {
    auto t0 = Clock::now();
    parser.ParseBlock(corpus.data(), corpus.data() + corpus.size(), &out);
    auto t1 = Clock::now();
    double dt = Secs(t0, t1);
    if (dt < best) best = dt;
  }
  printf("%-8s %7.1f MB/s  %9.0f rows/s  (%zu rows, %.1f MB, best of %d, "
         "%s lane)\n",
         name, corpus.size() / best / 1e6, rows / best, rows,
         corpus.size() / 1e6, reps, dct::SimdTierName(parser.simd_tier()));
}

// --check: correctness-mode smoke (make -C cpp ci): the SIMD decode lane
// must reproduce the scalar lane's containers on every format corpus, for
// every supported dispatch tier. No timing asserts — the throughput floor
// lives in tests/test_parse_scaling.py where noise is budgeted for.
template <typename ParserT>
int CheckFormat(const char* name, const std::string& corpus,
                const std::map<std::string, std::string>& args) {
  // save/restore any ambient tier pin instead of erasing it
  const char* ambient = ::getenv("DMLC_PARSE_SIMD");
  const std::string saved = ambient != nullptr ? ambient : "";
  const bool had = ambient != nullptr;
  auto restore = [&] {
    if (had) {
      ::setenv("DMLC_PARSE_SIMD", saved.c_str(), 1);
    } else {
      ::unsetenv("DMLC_PARSE_SIMD");
    }
  };
  ::setenv("DMLC_PARSE_SIMD", "0", 1);
  ParserT scalar(nullptr, args, 1);
  restore();
  dct::RowBlockContainer<uint32_t> want;
  scalar.ParseBlock(corpus.data(), corpus.data() + corpus.size(), &want);
  int failures = 0;
  for (int t = dct::kSimdSWAR; t <= dct::BestSupportedSimdTier(); ++t) {
    ::setenv("DMLC_PARSE_SIMD", dct::SimdTierName(t), 1);
    ParserT simd(nullptr, args, 1);
    restore();
    dct::RowBlockContainer<uint32_t> got;
    simd.ParseBlock(corpus.data(), corpus.data() + corpus.size(), &got);
    const bool same =
        want.offset == got.offset && want.label == got.label &&
        want.weight == got.weight && want.qid == got.qid &&
        want.field == got.field && want.index == got.index &&
        want.value == got.value && want.max_index == got.max_index &&
        want.max_field == got.max_field;
    if (!same) {
      fprintf(stderr, "MISMATCH: %s lane %s != scalar\n", name,
              dct::SimdTierName(t));
      ++failures;
    }
  }
  printf("%-8s ok (%zu rows, scalar == swar..%s)\n", name, want.Size(),
         dct::SimdTierName(dct::BestSupportedSimdTier()));
  return failures;
}

}  // namespace

int main(int argc, char** argv) {
  if (argc > 1 && std::string(argv[1]) == "--check") {
    const int rows = argc > 2
        ? static_cast<int>(dct::io::CheckedInt("rows", argv[2], 1,
                                               1 << 28))
        : 20000;
    int failures = 0;
    {
      std::string c = MakeLibsvm(rows, 28, 7);
      failures += CheckFormat<dct::LibSVMParser<uint32_t>>("libsvm", c, {});
    }
    {
      std::string c = MakeCSV(rows, 28, 7);
      failures += CheckFormat<dct::CSVParser<uint32_t>>("csv", c, {});
    }
    {
      std::string c = MakeLibfm(rows, 28, 7);
      failures += CheckFormat<dct::LibFMParser<uint32_t>>("libfm", c, {});
    }
    if (failures != 0) {
      fprintf(stderr, "%d lane mismatch(es)\n", failures);
      return 1;
    }
    printf("OK\n");
    return 0;
  }
  // checked CLI parses (analyze.py env rule): garbage args error loudly
  int rows = argc > 1 ? static_cast<int>(
      dct::io::CheckedInt("rows", argv[1], 1, 1 << 28)) : 100000;
  int reps = argc > 2 ? static_cast<int>(
      dct::io::CheckedInt("reps", argv[2], 1, 1 << 20)) : 7;
  {
    std::string c = MakeLibsvm(rows, 28, 7);
    BenchFormat<dct::LibSVMParser<uint32_t>>("libsvm", c, {}, reps);
  }
  {
    std::string c = MakeCSV(rows, 28, 7);
    BenchFormat<dct::CSVParser<uint32_t>>("csv", c, {}, reps);
  }
  {
    std::string c = MakeLibfm(rows, 28, 7);
    BenchFormat<dct::LibFMParser<uint32_t>>("libfm", c, {}, reps);
  }
  return 0;
}
