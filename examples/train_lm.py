#!/usr/bin/env python3
"""Language-model training example: byte-level next-token prediction over
the framework's parallelism lanes.

Two flagship configurations, both driven from one script:

  # DP x SP — ring attention for long sequences (seq sharded over "seq")
  python examples/train_lm.py corpus.txt --mesh data=2,seq=4 --seq 2048

  # DP x TP(+MoE) — Megatron splits + top-1 experts via GSPMD
  python examples/train_lm.py corpus.txt --model tp --mesh data=2,model=4

The corpus is any text/binary file; tokens are raw bytes (vocab 256), so
there is no external tokenizer. Windows are sampled deterministically:
each step's GLOBAL batch is seeded by (seed, step) over the whole corpus
and every host takes its contiguous row slice (process_part), so the
global batch stream is identical no matter when the run was resumed —
the elastic data-plane determinism rule (doc/robustness.md), applied to
the example's sampler.

Smoke-testable on CPU:  JAX_PLATFORMS=cpu \
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python examples/train_lm.py README.md --mesh data=2,seq=4 --seq 256 \
      --steps 3 --embed 32 --layers 1
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# same site-config workaround as examples/train.py: JAX_PLATFORMS must be
# applied through jax.config to outrank platform-pinning site plugins
if os.environ.get("JAX_PLATFORMS"):
    import jax  # noqa: E402

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import numpy as np  # noqa: E402


def parse_mesh(spec: str):
    """"data=2,seq=4" -> (("data", 2), ("seq", 4))."""
    out = []
    for part in spec.split(","):
        name, _, n = part.partition("=")
        out.append((name.strip(), int(n)))
    return tuple(out)


def load_corpus(path: str, seq: int) -> np.ndarray:
    """The whole corpus, memory-mapped (each host reads only the window
    bytes it samples — no per-host byte-slice copy)."""
    if os.path.getsize(path) < seq + 1:
        raise SystemExit(f"corpus has {os.path.getsize(path)} bytes; "
                         f"need at least seq+1={seq + 1}")
    return np.memmap(path, np.uint8, mode="r")


def byte_windows(data: np.ndarray, seq: int, batch: int, seed: int,
                 step: int, part: int = 0, npart: int = 1) -> np.ndarray:
    """[batch, seq+1] int32 windows for THIS host at `step`.

    The GLOBAL stream of npart*batch windows per step is seeded by
    (seed, step) alone and sampled over the whole corpus — never by which
    host draws it (the elastic data-plane determinism rule,
    doc/robustness.md): a resumed run continues the identical stream from
    any step with no sampler replay, and every host slices its contiguous
    rows out of the same global batch."""
    rng = np.random.default_rng([seed, step])
    starts = rng.integers(0, data.size - seq, size=npart * batch)
    mine = starts[part * batch:(part + 1) * batch]
    return np.stack([np.asarray(data[s:s + seq + 1])
                     for s in mine]).astype(np.int32)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("corpus", help="any file; bytes are the tokens")
    ap.add_argument("--model", default="lm", choices=("lm", "tp"),
                    help="lm: DP x SP ring attention; tp: DP x TP + MoE")
    ap.add_argument("--mesh", default="data=1,seq=1",
                    help='axis spec, e.g. "data=2,seq=4" (lm) or '
                         '"data=2,model=4" (tp)')
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--batch", type=int, default=0,
                    help="rows per step (0 = one per data-axis slice)")
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--embed", type=int, default=128)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--experts", type=int, default=0,
                    help="tp only: MoE experts (0 = 2 per model slice)")
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--checkpoint", default="",
                    help="URI to write params + step each --ckpt-every "
                         "steps (any stream scheme: file/s3/hdfs/azure). "
                         "jax.distributed worlds write a TWO-PHASE job "
                         "checkpoint (per-host parts + rank-0 commit "
                         "marker; torn step sets are unresumable); other "
                         "multi-host runs write one file per host "
                         "(.partK suffix appended). Saving params whose "
                         "model axis spans HOSTS is out of this "
                         "example's scope (shards must be addressable)")
    ap.add_argument("--ckpt-every", type=int, default=10)
    ap.add_argument("--resume", default="",
                    help="checkpoint URI (same base as --checkpoint) to "
                         "restore before training")
    args = ap.parse_args()
    if args.checkpoint and args.ckpt_every <= 0:
        raise SystemExit("--ckpt-every must be positive")

    import jax
    from jax.sharding import Mesh
    from dmlc_core_tpu.parallel import init_from_env
    from dmlc_core_tpu.tpu.sharding import process_part

    init_from_env()  # multi-host: jax.distributed under dmlc-submit

    # elastic-mesh check-in (doc/robustness.md "Elastic mesh training"):
    # under dmlc-submit the worker joins the tracker rendezvous, which
    # opens the heartbeat channel (env-gated) — the abort broadcast and
    # the step watchdog below are what turn a SIGKILL'd peer into a
    # structured between-steps abort instead of a hung collective
    client = assign = None
    if os.environ.get("DMLC_TRACKER_URI"):
        from dmlc_core_tpu.tracker.client import RendezvousClient
        from dmlc_core_tpu.tracker.wire import env_int
        client = RendezvousClient(os.environ["DMLC_TRACKER_URI"],
                                  env_int("DMLC_TRACKER_PORT", 9091))
        assign = client.start(heartbeat=None)

    nproc = jax.process_count()
    axes = parse_mesh(args.mesh)
    need = int(np.prod([n for _, n in axes]))
    # multi-process worlds step over this HOST's mesh and keep replicas
    # identical with a cross-host parameter mean (allreduce_tree below):
    # works on every backend — XLA's CPU floor cannot run multiprocess
    # computations at all (tpu/sharding.host_data_mesh), and on TPU the
    # reduction rides ICI/DCN through the same helper
    devs = jax.local_devices() if nproc > 1 else jax.devices()
    if len(devs) < need:
        raise SystemExit(f"mesh {args.mesh} needs {need} devices, "
                         f"have {len(devs)}")
    mesh = Mesh(np.array(devs[:need]).reshape([n for _, n in axes]),
                tuple(name for name, _ in axes))
    names = dict(axes)
    n_data = names.get("data", 1)
    batch = args.batch or n_data
    if batch % n_data:
        raise SystemExit(f"--batch {batch} must divide by data={n_data}")
    n_seq = names.get("seq", 1)
    if args.seq % n_seq:
        raise SystemExit(f"--seq {args.seq} must divide by seq={n_seq}")

    if args.model == "lm":
        from dmlc_core_tpu.models.transformer import (TransformerConfig,
                                                      TransformerLM)
        cfg = TransformerConfig(vocab=256, max_seq=args.seq,
                                embed=args.embed, heads=args.heads,
                                layers=args.layers)
        model = TransformerLM(cfg, mesh, learning_rate=args.lr)
    else:
        from dmlc_core_tpu.models.tp_transformer import (TPTransformerConfig,
                                                         TPTransformerLM)
        n_model = names.get("model", 1)
        # attention heads shard over the model axis: round up to the next
        # multiple so every (heads, mesh) combination is valid, and say
        # so. The rounded count must still divide --embed (head_dim =
        # embed // heads) — fail with guidance instead of a reshape error
        # deep inside jit.
        heads = -(-args.heads // n_model) * n_model
        if heads != args.heads:
            print(f"note: --heads {args.heads} rounded up to {heads} "
                  f"(must divide by model={n_model})")
        if args.embed % heads:
            raise SystemExit(
                f"--embed {args.embed} must divide by heads={heads} "
                f"(after rounding to the model axis); pick --embed as a "
                f"multiple of {heads}")
        cfg = TPTransformerConfig(
            vocab=256, max_seq=args.seq, embed=args.embed,
            heads=heads, layers=args.layers,
            moe_experts=args.experts or 2 * n_model)
        model = TPTransformerLM(cfg, mesh, learning_rate=args.lr)

    from dmlc_core_tpu.utils import restore_checkpoint, save_checkpoint

    params = model.init(seed=args.seed)
    part, npart = process_part()
    mesh_world = nproc > 1
    # one checkpoint file per host: concurrent writers to a shared URI
    # would clobber each other
    suffix = f".part{part}of{npart}" if npart > 1 else ""
    # the data stream's identity: a resume under a different one would
    # silently continue on different windows (same pattern as train.py)
    identity = {"model": args.model, "mesh": args.mesh,
                "seq": str(args.seq), "batch": str(batch),
                "seed": str(args.seed), "part": f"{part}/{npart}"}
    start = 0
    if args.resume and mesh_world:
        # two-phase job checkpoint: ONLY a committed marker is
        # resumable — a torn step set (some hosts saved step N, others
        # died first) is invisible, and restore falls back to whatever
        # the marker last named. A missing marker (relaunch before the
        # first commit) means a fresh start, which is exactly what a
        # supervised world-relaunch with the original command line
        # needs.
        from dmlc_core_tpu.utils import restore_job_checkpoint
        got = restore_job_checkpoint(args.resume, part, npart,
                                     like=params)
        if got is None:
            print("no committed job checkpoint yet; starting fresh",
                  flush=True)
        else:
            params, start, extra = got
            mismatch = {k: (extra.get(k), v) for k, v in identity.items()
                        if extra.get(k) != v}
            if mismatch:
                raise SystemExit(
                    f"checkpoint was written under a different run "
                    f"identity (stored vs now): {mismatch}")
            print(f"resumed from committed job checkpoint {args.resume} "
                  f"at step {start}", flush=True)
    elif args.resume:
        # restore onto the template's shardings (preemption recovery)
        params, start, extra = restore_checkpoint(args.resume + suffix,
                                                  like=params)
        mismatch = {k: (extra.get(k), v) for k, v in identity.items()
                    if extra.get(k) != v}
        if mismatch:
            raise SystemExit(
                f"checkpoint was written under a different run identity "
                f"(stored vs now): {mismatch}")
        print(f"resumed from {args.resume}{suffix} at step {start}")

    def save_ckpt(at_step):
        if mesh_world:
            from dmlc_core_tpu.parallel import barrier
            from dmlc_core_tpu.utils import (commit_job_checkpoint,
                                             save_job_checkpoint)
            save_job_checkpoint(args.checkpoint, params, at_step,
                                part, npart, extra=identity)
            # every host must have PUBLISHED its part before rank 0
            # names the set in the commit marker; a host that dies
            # before the barrier leaves step at_step torn and therefore
            # unresumable — by design
            barrier(f"ckpt-{at_step}")
            if part == 0:
                commit_job_checkpoint(args.checkpoint, at_step, npart)
        else:
            save_checkpoint(args.checkpoint + suffix, params,
                            step=at_step, extra=identity)

    data = load_corpus(args.corpus, args.seq)
    from dmlc_core_tpu.parallel import (STEP_ABORT_EXIT, StepWatchdog,
                                        allreduce, allreduce_tree,
                                        structured_abort)
    from dmlc_core_tpu.tracker.wire import TrackerAbortedError
    rank = assign.rank if assign is not None else part
    wd = step = None
    first = last = None
    try:
        if mesh_world or os.environ.get("DMLC_TRACKER_URI"):
            wd = StepWatchdog(rank=rank).start()
        for step in range(start, args.steps):
            if wd is not None:
                wd.step_begin(step)
            # per-step seeding: no sampler replay needed on resume —
            # step s draws the same global windows whether or not the
            # run restarted
            w = byte_windows(data, args.seq, batch, args.seed, step,
                             part, npart)
            params, loss = model.step(params, w[:, :-1], w[:, 1:])
            if mesh_world:
                # host-local step + cross-host parameter mean == the
                # global-batch update (equal per-host batches), and the
                # rank-ordered reduction makes every replica (and every
                # rerun of the same schedule) bit-identical
                params = allreduce_tree(params, "mean")
                loss = allreduce(np.asarray(loss, np.float32), "mean")
            if wd is not None:
                wd.step_end()
            last = float(loss)
            if first is None:
                first = last
            print(f"step {step}: loss {last:.4f}", flush=True)
            if args.checkpoint and (step + 1) % args.ckpt_every == 0:
                save_ckpt(step + 1)
        if (args.checkpoint and last is not None
                and args.steps % args.ckpt_every != 0):  # not saved yet
            save_ckpt(args.steps)
    except TrackerAbortedError as e:
        # a peer died: the tracker broadcast the abort and check()
        # surfaced it BETWEEN steps — drain, leave the postmortem
        # record, and exit with the structured code the supervisor maps
        # to "relaunch the world from the last committed checkpoint"
        if wd is not None:
            wd.drain()
        at = f" at step {step}" if step is not None else ""
        structured_abort(f"train_lm{at}: {e}", rank=rank)
        return STEP_ABORT_EXIT
    finally:
        if wd is not None:
            wd.stop()
    if client is not None:
        client.shutdown(rank)
    if last is None:
        print(f"nothing to do: resume step {start} >= --steps {args.steps}")
        return 0
    print(f"done: loss {first:.4f} -> {last:.4f} over steps "
          f"{start}..{args.steps - 1} (mesh {args.mesh}, seq {args.seq}, "
          f"part {part}/{npart})")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
