#!/usr/bin/env python3
"""End-to-end training example: any supported data URI -> HBM-resident
batches -> distributed linear learner -> checkpoint/resume.

Walks the full TPU-native pipeline surface in ~60 lines of user code:

  python examples/train.py data.libsvm --epochs 3
  python examples/train.py "data.libsvm?shuffle_parts=16" --objective pairwise
  python examples/train.py s3://bucket/train.drec --batch-rows 8192
  python examples/train.py data.rec --resume ckpt.bin   # after preemption

Under dmlc-submit the same script runs per-host with its own partition:

  bin/dmlc-submit --cluster=tpu-pod --host-file hosts.txt -- \
      python examples/train.py hdfs://nn/train.rec

(each worker calls init_from_env + process_part and reads a disjoint,
exactly-covering slice — the reference's distributed-read contract).
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Honor JAX_PLATFORMS even where a site config (e.g. an axon install) pins
# the platform before env vars are consulted: site plugins register through
# jax.config, so requesting the platform through jax.config outranks them.
# This is what lets the test suite run this example hermetically on CPU
# while production runs pick up the TPU default.
if os.environ.get("JAX_PLATFORMS"):
    import jax  # noqa: E402

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import numpy as np  # noqa: E402

from dmlc_core_tpu.models import FMLearner, LinearLearner  # noqa: E402
from dmlc_core_tpu.parallel import init_from_env  # noqa: E402
from dmlc_core_tpu.tpu import DeviceRowBlockIter, data_mesh  # noqa: E402
from dmlc_core_tpu.tpu.sharding import process_part  # noqa: E402
from dmlc_core_tpu.utils import (restore_checkpoint,  # noqa: E402
                                 save_checkpoint)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("uri", help="libsvm/csv/libfm/rec/drec data URI "
                               "(file://, s3://, hdfs://, azure://)")
    ap.add_argument("--num-features", type=int, default=0,
                    help="0 = discover from the first epoch's max index")
    ap.add_argument("--model", default="linear", choices=("linear", "fm"),
                    help="linear learner or second-order factorization "
                         "machine (the libfm lane's canonical consumer)")
    ap.add_argument("--fm-rank", type=int, default=8,
                    help="FM interaction-factor rank k")
    ap.add_argument("--objective", default="logistic",
                    choices=("logistic", "squared", "pairwise"))
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batch-rows", type=int, default=4096)
    ap.add_argument("--learning-rate", type=float, default=0.1)
    ap.add_argument("--checkpoint", default="",
                    help="URI to write the model + data position each epoch")
    ap.add_argument("--resume", default="",
                    help="checkpoint URI to resume from (mid-epoch exact)")
    args = ap.parse_args()

    init_from_env()  # multi-host: no-op single-process, rendezvous on pods
    part, npart = process_part()
    mesh = data_mesh()

    if args.num_features <= 0:
        # cheap discovery pass over this part only (a real deployment
        # passes --num-features; feature spaces are part-invariant)
        from dmlc_core_tpu.io import NativeParser
        mx = 0
        with NativeParser(args.uri, part=part, npart=npart) as p:
            for b in p:
                mx = max(mx, int(b.max_index))
        args.num_features = mx + 1

    if args.model == "fm":
        learner = FMLearner(num_features=args.num_features, mesh=mesh,
                            k=args.fm_rank, objective=args.objective,
                            learning_rate=args.learning_rate)
    else:
        learner = LinearLearner(num_features=args.num_features, mesh=mesh,
                                objective=args.objective,
                                learning_rate=args.learning_rate)
    params = learner.init()
    start_epoch = 0
    data_state = None
    if args.resume:
        params, step, extra = restore_checkpoint(args.resume, like=params)
        start_epoch = step
        if "batches_consumed" in extra:
            # the epoch-boundary checkpoint below records 0 batches; a
            # preemption-time checkpoint records the mid-epoch position.
            # Rebuild the state from the SAVED identity (not current CLI
            # args) so restore() can catch a mismatched resume — a batch
            # count under different batch_rows/uri/part is different data.
            data_state = {
                k: int(extra[k]) if k in ("batches_consumed", "batch_rows",
                                          "part", "npart", "epoch")
                else extra[k]
                for k in ("batches_consumed", "batch_rows", "part",
                          "npart", "uri", "fmt", "epoch") if k in extra}

    it = DeviceRowBlockIter(args.uri, part=part, npart=npart, mesh=mesh,
                            batch_rows=args.batch_rows, dense_dtype="bf16")
    try:
        for epoch in range(start_epoch, args.epochs):
            if data_state is not None:  # mid-epoch resume, once
                it.restore(data_state)
                data_state = None
            losses = []
            for batch in it:
                params, loss = learner.step(params, batch)
                losses.append(float(loss))
            summary = (f"mean loss {float(np.mean(losses)):.6f} over "
                       f"{len(losses)} batches" if losses
                       else "no batches in this part")
            print(f"epoch {epoch}: {summary}")
            it.before_first()
            if args.checkpoint:
                st = {str(k): str(v) for k, v in it.state().items()}
                save_checkpoint(args.checkpoint, params, step=epoch + 1,
                                extra=st)
    finally:
        it.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
