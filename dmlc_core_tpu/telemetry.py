"""Unified telemetry: one metrics plane across C++, Python, and the tracker.

Before this layer, observability lived in three disjoint side-channels —
``io_retry_stats()`` (native IoStats counters), per-parser
``pipeline_stats()`` structs, and the tracker's ad-hoc event list — with no
shared naming, units, or reset semantics. This module is the Python half of
the unified plane (the native half is ``cpp/src/telemetry.h``):

- a process-wide registry of counters / gauges / log2-bucket latency
  histograms (same bucket scheme as the native side: bucket *i* counts
  observations ``v <= 2**i``, plus one +Inf overflow bucket);
- :func:`snapshot` merges the Python registry with the native registry's
  versioned JSON document (``dct_telemetry_snapshot``) into ONE document —
  the same metric names and values are retrievable through the C ABI,
  through this function, and through a live tracker's HTTP ``GET /metrics``
  scrape;
- two export formats from that one snapshot: Prometheus text exposition
  (:func:`prometheus_text`) and the tracker's JSONL event schema
  (:func:`events_jsonl` — tracker events are just another telemetry
  stream, ring-buffered by :func:`emit_event`).

Metric catalog, units, and env knobs: ``doc/observability.md``. Hot-path
cost: Python metrics are touched at batch granularity (never per row), and
:func:`enabled` gates timed spans; ``DMLC_TELEMETRY=0`` disables spans in
both halves.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

__all__ = ["Counter", "Gauge", "Histogram", "HIST_BUCKETS",
           "SNAPSHOT_VERSION", "counter", "gauge", "histogram",
           "register_collector", "unregister_collector", "enabled",
           "enable", "reset", "emit_event", "events", "snapshot",
           "prometheus_text", "events_jsonl"]

SNAPSHOT_VERSION = 1
# must match cpp/src/telemetry.h kHistBuckets (le 2^0..2^27, then +Inf)
HIST_BUCKETS = 28

_lock = threading.Lock()
_counters: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], "Counter"] = {}
_gauges: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], "Gauge"] = {}
_hists: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], "Histogram"] = {}
_collectors: List[Callable[[], None]] = []
_events: List[dict] = []
_EVENTS_MAX = 4096
_enabled: Optional[bool] = None


def _labels_key(labels: Optional[Dict[str, str]]
                ) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((labels or {}).items()))


class Counter:
    """A monotonically increasing value (Prometheus ``counter``). Thread-safe
    under the GIL plus a per-instance lock for the read-modify-write."""

    __slots__ = ("name", "labels", "_v", "_mu")

    def __init__(self, name: str, labels: Dict[str, str]):
        self.name = name
        self.labels = dict(labels)
        self._v = 0
        self._mu = threading.Lock()

    def inc(self, n: int = 1) -> None:
        """Add ``n`` (default 1)."""
        with self._mu:
            self._v += n

    @property
    def value(self) -> int:
        """Current count."""
        return self._v

    def zero(self) -> None:
        """Reset to 0 (registry-wide :func:`reset` calls this)."""
        with self._mu:
            self._v = 0


class Gauge:
    """A point-in-time value that can go up or down (Prometheus
    ``gauge``)."""

    __slots__ = ("name", "labels", "_v")

    def __init__(self, name: str, labels: Dict[str, str]):
        self.name = name
        self.labels = dict(labels)
        self._v = 0.0

    def set(self, v: float) -> None:
        """Set the current value."""
        self._v = v

    @property
    def value(self) -> float:
        """Current value."""
        return self._v

    def zero(self) -> None:
        """Reset to 0 (registry-wide :func:`reset` calls this)."""
        self._v = 0.0


class Histogram:
    """Fixed-bucket log2 latency histogram, bucket-compatible with the
    native side (cpp/src/telemetry.h Hist): bucket ``i`` counts
    observations ``v <= 2**i`` for ``i < HIST_BUCKETS``, the last bucket is
    +Inf overflow. Observe integer microseconds for ``*_us`` metrics."""

    __slots__ = ("name", "labels", "count", "sum", "buckets", "_mu")

    def __init__(self, name: str, labels: Dict[str, str]):
        self.name = name
        self.labels = dict(labels)
        self.count = 0
        self.sum = 0
        self.buckets = [0] * (HIST_BUCKETS + 1)
        self._mu = threading.Lock()

    @staticmethod
    def bucket_of(v: int) -> int:
        """Index of the first bucket whose upper bound ``2**i`` holds
        ``v``; ``HIST_BUCKETS`` is the overflow bucket."""
        if v <= 1:
            return 0
        w = int(v - 1).bit_length()  # ceil(log2(v))
        return w if w < HIST_BUCKETS else HIST_BUCKETS

    def observe(self, v: float) -> None:
        """Record one observation (non-negative; fractions are truncated
        for the bucket choice, summed exactly — sub-unit observations must
        not read as zero-cost in sum/count means)."""
        if v < 0:
            v = 0
        with self._mu:
            self.count += 1
            self.sum += v
            self.buckets[self.bucket_of(int(v))] += 1

    def zero(self) -> None:
        """Reset all counts (registry-wide :func:`reset` calls this)."""
        with self._mu:
            self.count = 0
            self.sum = 0
            self.buckets = [0] * (HIST_BUCKETS + 1)


def counter(name: str, labels: Optional[Dict[str, str]] = None) -> Counter:
    """Resolve-or-register the counter ``(name, labels)``; the returned
    object is stable for the process lifetime — resolve once, keep it."""
    key = (name, _labels_key(labels))
    with _lock:
        c = _counters.get(key)
        if c is None:
            c = _counters[key] = Counter(name, dict(key[1]))
        return c


def gauge(name: str, labels: Optional[Dict[str, str]] = None) -> Gauge:
    """Resolve-or-register the gauge ``(name, labels)`` (see
    :func:`counter`)."""
    key = (name, _labels_key(labels))
    with _lock:
        g = _gauges.get(key)
        if g is None:
            g = _gauges[key] = Gauge(name, dict(key[1]))
        return g


def histogram(name: str, labels: Optional[Dict[str, str]] = None
              ) -> Histogram:
    """Resolve-or-register the histogram ``(name, labels)`` (see
    :func:`counter`)."""
    key = (name, _labels_key(labels))
    with _lock:
        h = _hists.get(key)
        if h is None:
            h = _hists[key] = Histogram(name, dict(key[1]))
        return h


def register_collector(fn: Callable[[], None]) -> None:
    """Register a callback run at every :func:`snapshot` before the
    registry is read — how components with derived state (the tracker's
    per-rank heartbeat ages) refresh their gauges lazily instead of on a
    timer. Collectors must be fast and must not raise (exceptions are
    swallowed so one broken collector cannot sink a scrape)."""
    with _lock:
        if fn not in _collectors:
            _collectors.append(fn)


def unregister_collector(fn: Callable[[], None]) -> None:
    """Remove a collector registered with :func:`register_collector`
    (no-op when absent) — call on component shutdown so a dead tracker
    does not keep publishing."""
    with _lock:
        if fn in _collectors:
            _collectors.remove(fn)


def enabled() -> bool:
    """Whether timed-span instrumentation is on: ``DMLC_TELEMETRY`` env at
    first use (default on), overridable via :func:`enable`. Counters keep
    counting either way."""
    global _enabled
    if _enabled is None:
        _enabled = os.environ.get("DMLC_TELEMETRY", "1") not in ("0", "off")
    return _enabled


def enable(on: bool) -> None:
    """Set the span gate for BOTH halves: the Python registry and — when
    the native library is already loaded — the native registry
    (``dct_telemetry_enable``)."""
    global _enabled
    _enabled = bool(on)
    lib = _native_lib_if_loaded()
    if lib is not None:
        lib.dct_telemetry_enable(1 if on else 0)


def reset(native: bool = True) -> None:
    """Zero every Python-registered metric and drop buffered events; with
    ``native=True`` (default) also zero the native registry when its
    library is loaded (``dct_telemetry_reset``)."""
    with _lock:
        for c in _counters.values():
            c.zero()
        for g in _gauges.values():
            g.zero()
        for h in _hists.values():
            h.zero()
        del _events[:]
    if native:
        lib = _native_lib_if_loaded()
        if lib is not None:
            lib.dct_telemetry_reset()


def emit_event(event: str, **fields) -> None:
    """Append one event to the telemetry event stream (the PR-4 tracker
    JSONL schema: ``{"ts": ..., "event": ..., **fields}``; pass ``ts=`` to
    preserve an already-stamped time). The stream is a ring buffer of the
    most recent ``4096`` events; exposition via :func:`events_jsonl`. Also
    bumps ``telemetry_events_total{event=...}``."""
    rec = {"ts": fields.pop("ts", None) or time.time(), "event": event}
    rec.update(fields)
    with _lock:
        _events.append(rec)
        if len(_events) > _EVENTS_MAX:
            del _events[: len(_events) - _EVENTS_MAX]
    counter("telemetry_events_total", {"event": event}).inc()


def events() -> List[dict]:
    """A copy of the buffered event stream (most recent ``4096``)."""
    with _lock:
        return list(_events)


def _native_lib_if_loaded():
    """The loaded ctypes library, or None. NEVER triggers the native
    build: a tracker-only process (or a scrape) must not block minutes on
    a C++ compile just to report its own metrics."""
    try:
        from dmlc_core_tpu.io import native as _native
    except Exception:  # jax/numpy missing in a minimal tracker venv
        return None
    return _native._lib


def _native_snapshot_dict(force: bool) -> Optional[dict]:
    if force:
        from dmlc_core_tpu.io import native as _native
        _native.lib()
    lib = _native_lib_if_loaded()
    if lib is None:
        return None
    import ctypes
    out = ctypes.c_char_p()
    if lib.dct_telemetry_snapshot(ctypes.byref(out)) != 0:
        return None
    try:
        doc = json.loads(ctypes.string_at(out).decode())
    finally:
        lib.dct_str_free(out)
    return doc


def snapshot(native: Optional[bool] = None) -> dict:
    """The merged telemetry document — the single source every surface
    serves (C ABI consumers read the native half directly; the tracker's
    ``GET /metrics`` renders this via :func:`prometheus_text`).

    ``native``: ``None`` (default) merges the native registry only when
    the library is ALREADY loaded (never triggers a build); ``True``
    forces loading/building it; ``False`` excludes it.

    Schema (version 1, append-only): ``{"version", "enabled", "native":
    bool, "counters": [{"name", "labels", "value"}], "gauges": [...],
    "histograms": [{"name", "labels", "count", "sum", "buckets":
    [HIST_BUCKETS+1 counts]}], "events": [...]}``."""
    with _lock:
        collectors = list(_collectors)
    for fn in collectors:
        try:
            fn()
        except Exception:
            pass  # a broken collector must not sink the scrape
    doc = {"version": SNAPSHOT_VERSION, "enabled": enabled(),
           "native": False, "counters": [], "gauges": [],
           "histograms": [], "events": []}
    if native is not False:
        nat = _native_snapshot_dict(force=bool(native))
        if nat is not None:
            doc["native"] = True
            doc["counters"] += nat.get("counters", [])
            doc["gauges"] += nat.get("gauges", [])
            doc["histograms"] += nat.get("histograms", [])
    with _lock:
        for c in _counters.values():
            doc["counters"].append({"name": c.name, "labels": c.labels,
                                    "value": c.value})
        for g in _gauges.values():
            doc["gauges"].append({"name": g.name, "labels": g.labels,
                                  "value": g.value})
        for h in _hists.values():
            doc["histograms"].append(
                {"name": h.name, "labels": h.labels, "count": h.count,
                 "sum": h.sum, "buckets": list(h.buckets)})
        doc["events"] = list(_events)
    return doc


def _escape_label(v: str) -> str:
    """Prometheus label-value escaping: backslash, double-quote, newline."""
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
                 .replace("\n", "\\n")


def _fmt_labels(labels: Dict[str, str], extra: str = "") -> str:
    parts = [f'{k}="{_escape_label(v)}"' for k, v in sorted(labels.items())]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def _fmt_value(v) -> str:
    if isinstance(v, float) and not v.is_integer():
        return repr(v)
    return str(int(v))


def prometheus_text(snap: Optional[dict] = None) -> str:
    """Render a snapshot (default: take one now) in the Prometheus text
    exposition format (version 0.0.4): one ``# TYPE`` line per metric
    name, label escaping, histograms as cumulative ``_bucket{le=...}``
    series ending in ``le="+Inf"`` plus ``_sum``/``_count``."""
    if snap is None:
        snap = snapshot()
    lines: List[str] = []
    typed: set = set()

    def type_line(name: str, kind: str) -> None:
        if name not in typed:
            typed.add(name)
            lines.append(f"# TYPE {name} {kind}")

    for c in snap["counters"]:
        type_line(c["name"], "counter")
        lines.append(f"{c['name']}{_fmt_labels(c['labels'])} "
                     f"{_fmt_value(c['value'])}")
    for g in snap["gauges"]:
        type_line(g["name"], "gauge")
        lines.append(f"{g['name']}{_fmt_labels(g['labels'])} "
                     f"{_fmt_value(g['value'])}")
    for h in snap["histograms"]:
        type_line(h["name"], "histogram")
        cum = 0
        for i, n in enumerate(h["buckets"]):
            cum += n
            le = "+Inf" if i == len(h["buckets"]) - 1 else str(1 << i)
            le_label = 'le="' + le + '"'
            labels = _fmt_labels(h["labels"], le_label)
            lines.append(f"{h['name']}_bucket{labels} {cum}")
        lines.append(f"{h['name']}_sum{_fmt_labels(h['labels'])} "
                     f"{_fmt_value(h['sum'])}")
        lines.append(f"{h['name']}_count{_fmt_labels(h['labels'])} "
                     f"{_fmt_value(h['count'])}")
    return "\n".join(lines) + "\n"


def events_jsonl(snap: Optional[dict] = None) -> str:
    """Render a snapshot's event stream (default: take one now) as JSONL —
    the PR-4 ``DMLC_TRACKER_EVENT_LOG`` schema, one ``{"ts", "event",
    ...}`` object per line."""
    if snap is None:
        snap = snapshot()
    return "".join(json.dumps(rec) + "\n" for rec in snap.get("events", []))
