"""Unified telemetry: one metrics plane across C++, Python, and the tracker.

Before this layer, observability lived in three disjoint side-channels —
``io_retry_stats()`` (native IoStats counters), per-parser
``pipeline_stats()`` structs, and the tracker's ad-hoc event list — with no
shared naming, units, or reset semantics. This module is the Python half of
the unified plane (the native half is ``cpp/src/telemetry.h``):

- a process-wide registry of counters / gauges / log2-bucket latency
  histograms (same bucket scheme as the native side: bucket *i* counts
  observations ``v <= 2**i``, plus one +Inf overflow bucket);
- :func:`snapshot` merges the Python registry with the native registry's
  versioned JSON document (``dct_telemetry_snapshot``) into ONE document —
  the same metric names and values are retrievable through the C ABI,
  through this function, and through a live tracker's HTTP ``GET /metrics``
  scrape;
- two export formats from that one snapshot: Prometheus text exposition
  (:func:`prometheus_text`) and the tracker's JSONL event schema
  (:func:`events_jsonl` — tracker events are just another telemetry
  stream, ring-buffered by :func:`emit_event`).

Metric catalog, units, and env knobs: ``doc/observability.md``. Hot-path
cost: Python metrics are touched at batch granularity (never per row), and
:func:`enabled` gates timed spans; ``DMLC_TELEMETRY=0`` disables spans in
both halves.
"""

from __future__ import annotations

import json
import math
import os
import re
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

__all__ = ["Counter", "Gauge", "Histogram", "HIST_BUCKETS",
           "SNAPSHOT_VERSION", "SPANS_MAX", "METRIC_HELP", "counter",
           "gauge", "histogram", "register_collector",
           "unregister_collector", "enabled", "enable", "reset",
           "emit_event", "events", "snapshot", "prometheus_text",
           "events_jsonl", "span", "emit_span", "new_span_id", "spans",
           "clock_anchor", "trace_snapshot", "trace_json", "rank_export",
           "cluster_prometheus_text", "cluster_trace_json",
           "stall_attribution", "straggler_attribution", "VERDICT_CODES",
           "flight_dump", "device_overlap_ratio", "quantile_from_buckets",
           "WindowedView", "SloMonitor", "start_windowed_view",
           "stop_windowed_view", "windowed_view", "slo_page_active",
           "HostResourceSampler"]

SNAPSHOT_VERSION = 1
# must match cpp/src/telemetry.h kHistBuckets (le 2^0..2^27, then +Inf)
HIST_BUCKETS = 28
# Python half of the span ring: most recent SPANS_MAX completed spans
# (the native ring is cpp/src/telemetry.h kSpanRingSize)
SPANS_MAX = 8192

_lock = threading.Lock()
_counters: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], "Counter"] = {}
_gauges: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], "Gauge"] = {}
_hists: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], "Histogram"] = {}
_collectors: List[Callable[[], None]] = []
_events: List[dict] = []
_EVENTS_MAX = 4096
_enabled: Optional[bool] = None

# span-ring state: completed spans (dicts) in emit order, a monotonically
# increasing span-id allocator, a small per-thread lane id map, and the
# per-thread currently-open span (the parent of the next nested one)
_spans: List[dict] = []
_spans_dropped = 0
_span_seq = 0
_tids: Dict[int, int] = {}
_tls = threading.local()


def _labels_key(labels: Optional[Dict[str, str]]
                ) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((labels or {}).items()))


class Counter:
    """A monotonically increasing value (Prometheus ``counter``). Thread-safe
    under the GIL plus a per-instance lock for the read-modify-write."""

    __slots__ = ("name", "labels", "_v", "_mu")

    def __init__(self, name: str, labels: Dict[str, str]):
        self.name = name
        self.labels = dict(labels)
        self._v = 0
        self._mu = threading.Lock()

    def inc(self, n: int = 1) -> None:
        """Add ``n`` (default 1)."""
        with self._mu:
            self._v += n

    @property
    def value(self) -> int:
        """Current count."""
        return self._v

    def zero(self) -> None:
        """Reset to 0 (registry-wide :func:`reset` calls this)."""
        with self._mu:
            self._v = 0


class Gauge:
    """A point-in-time value that can go up or down (Prometheus
    ``gauge``)."""

    __slots__ = ("name", "labels", "_v")

    def __init__(self, name: str, labels: Dict[str, str]):
        self.name = name
        self.labels = dict(labels)
        self._v = 0.0

    def set(self, v: float) -> None:
        """Set the current value."""
        self._v = v

    @property
    def value(self) -> float:
        """Current value."""
        return self._v

    def zero(self) -> None:
        """Reset to 0 (registry-wide :func:`reset` calls this)."""
        self._v = 0.0


class Histogram:
    """Fixed-bucket log2 latency histogram, bucket-compatible with the
    native side (cpp/src/telemetry.h Hist): bucket ``i`` counts
    observations ``v <= 2**i`` for ``i < HIST_BUCKETS``, the last bucket is
    +Inf overflow. Observe integer microseconds for ``*_us`` metrics."""

    __slots__ = ("name", "labels", "count", "sum", "buckets", "exemplars",
                 "_mu")

    def __init__(self, name: str, labels: Dict[str, str]):
        self.name = name
        self.labels = dict(labels)
        self.count = 0
        self.sum = 0
        self.buckets = [0] * (HIST_BUCKETS + 1)
        # bucket index -> trace id of the LAST sampled observation that
        # landed there (doc/observability.md "Per-request tracing"): the
        # breadcrumb from a latency bucket back to the span chain that
        # produced it. Lazy — stays None until the first exemplar, so
        # unsampled histograms pay nothing
        self.exemplars: Optional[Dict[int, int]] = None
        self._mu = threading.Lock()

    @staticmethod
    def bucket_of(v: int) -> int:
        """Index of the first bucket whose upper bound ``2**i`` holds
        ``v``; ``HIST_BUCKETS`` is the overflow bucket."""
        if v <= 1:
            return 0
        w = int(v - 1).bit_length()  # ceil(log2(v))
        return w if w < HIST_BUCKETS else HIST_BUCKETS

    def observe(self, v: float, trace_id: Optional[int] = None) -> None:
        """Record one observation (non-negative; fractions are truncated
        for the bucket choice, summed exactly — sub-unit observations must
        not read as zero-cost in sum/count means). ``trace_id`` (a span
        id from a sampled request chain) is kept as the bucket's exemplar
        — last writer wins, exported in the JSON snapshot only (the text
        exposition stays plain 0.0.4)."""
        if v < 0:
            v = 0
        with self._mu:
            self.count += 1
            self.sum += v
            b = self.bucket_of(int(v))
            self.buckets[b] += 1
            if trace_id:
                if self.exemplars is None:
                    self.exemplars = {}
                self.exemplars[b] = trace_id

    def zero(self) -> None:
        """Reset all counts (registry-wide :func:`reset` calls this)."""
        with self._mu:
            self.count = 0
            self.sum = 0
            self.buckets = [0] * (HIST_BUCKETS + 1)
            self.exemplars = None

    def quantile(self, q: float) -> float:
        """Upper-bound estimate of the ``q``-quantile (0 < q <= 1) from
        the log2 buckets: the bound ``2**i`` of the first bucket where
        the cumulative count reaches ``ceil(q * count)``. Factor-of-two
        resolution — exactly what an open-loop latency capture needs to
        tell a 1 ms p99 from a 200 ms one without storing samples."""
        with self._mu:
            return quantile_from_buckets(self.buckets, self.count, q)


def quantile_from_buckets(buckets, count: int, q: float) -> float:
    """Shared quantile-from-log2-buckets estimate (see
    :meth:`Histogram.quantile`); works on any snapshot's bucket list.
    Returns 0.0 on an empty histogram and ``inf`` when the quantile
    lands in the +Inf overflow bucket."""
    if not 0.0 < q <= 1.0:
        raise ValueError(f"quantile {q} outside (0, 1]")
    if count <= 0:
        return 0.0
    need = max(1, math.ceil(q * count))
    cum = 0
    for i, n in enumerate(buckets):
        cum += n
        if cum >= need:
            return float("inf") if i >= HIST_BUCKETS else float(1 << i)
    return float("inf")


def counter(name: str, labels: Optional[Dict[str, str]] = None) -> Counter:
    """Resolve-or-register the counter ``(name, labels)``; the returned
    object is stable for the process lifetime — resolve once, keep it."""
    key = (name, _labels_key(labels))
    with _lock:
        c = _counters.get(key)
        if c is None:
            c = _counters[key] = Counter(name, dict(key[1]))
        return c


def gauge(name: str, labels: Optional[Dict[str, str]] = None) -> Gauge:
    """Resolve-or-register the gauge ``(name, labels)`` (see
    :func:`counter`)."""
    key = (name, _labels_key(labels))
    with _lock:
        g = _gauges.get(key)
        if g is None:
            g = _gauges[key] = Gauge(name, dict(key[1]))
        return g


def histogram(name: str, labels: Optional[Dict[str, str]] = None
              ) -> Histogram:
    """Resolve-or-register the histogram ``(name, labels)`` (see
    :func:`counter`)."""
    key = (name, _labels_key(labels))
    with _lock:
        h = _hists.get(key)
        if h is None:
            h = _hists[key] = Histogram(name, dict(key[1]))
        return h


def register_collector(fn: Callable[[], None]) -> None:
    """Register a callback run at every :func:`snapshot` before the
    registry is read — how components with derived state (the tracker's
    per-rank heartbeat ages) refresh their gauges lazily instead of on a
    timer. Collectors must be fast and must not raise (exceptions are
    swallowed so one broken collector cannot sink a scrape)."""
    with _lock:
        if fn not in _collectors:
            _collectors.append(fn)


def unregister_collector(fn: Callable[[], None]) -> None:
    """Remove a collector registered with :func:`register_collector`
    (no-op when absent) — call on component shutdown so a dead tracker
    does not keep publishing."""
    with _lock:
        if fn in _collectors:
            _collectors.remove(fn)


def enabled() -> bool:
    """Whether timed-span instrumentation is on: ``DMLC_TELEMETRY`` env at
    first use (default on), overridable via :func:`enable`. Counters keep
    counting either way."""
    global _enabled
    if _enabled is None:
        _enabled = os.environ.get("DMLC_TELEMETRY", "1") not in ("0", "off")
    return _enabled


def enable(on: bool) -> None:
    """Set the span gate for BOTH halves: the Python registry and — when
    the native library is already loaded — the native registry
    (``dct_telemetry_enable``)."""
    global _enabled
    _enabled = bool(on)
    lib = _native_lib_if_loaded()
    if lib is not None:
        lib.dct_telemetry_enable(1 if on else 0)


def reset(native: bool = True) -> None:
    """Zero every Python-registered metric and drop buffered events; with
    ``native=True`` (default) also zero the native registry when its
    library is loaded (``dct_telemetry_reset``). Also force-stops the
    process :class:`WindowedView` (test isolation: a leaked ticker thread
    from one test must not publish windows into the next)."""
    global _spans_dropped
    stop_windowed_view(force=True)
    with _lock:
        for c in _counters.values():
            c.zero()
        for g in _gauges.values():
            g.zero()
        for h in _hists.values():
            h.zero()
        del _events[:]
        del _spans[:]
        _spans_dropped = 0
    if native:
        lib = _native_lib_if_loaded()
        if lib is not None:
            lib.dct_telemetry_reset()  # also drops the native span ring


def emit_event(event: str, **fields) -> None:
    """Append one event to the telemetry event stream (the PR-4 tracker
    JSONL schema: ``{"ts": ..., "event": ..., **fields}``; pass ``ts=`` to
    preserve an already-stamped time). The stream is a ring buffer of the
    most recent ``4096`` events; exposition via :func:`events_jsonl`. Also
    bumps ``telemetry_events_total{event=...}``."""
    rec = {"ts": fields.pop("ts", None) or time.time(), "event": event}
    rec.update(fields)
    with _lock:
        _events.append(rec)
        if len(_events) > _EVENTS_MAX:
            del _events[: len(_events) - _EVENTS_MAX]
    counter("telemetry_events_total", {"event": event}).inc()


def events() -> List[dict]:
    """A copy of the buffered event stream (most recent ``4096``)."""
    with _lock:
        return list(_events)


# -- distributed tracing (doc/observability.md "Distributed tracing") --------
def _thread_lane() -> int:
    """Small stable lane id for the calling thread (Chrome-trace tid)."""
    ident = threading.get_ident()
    with _lock:
        lane = _tids.get(ident)
        if lane is None:
            lane = _tids[ident] = len(_tids) + 1
        return lane


def _perf_us() -> float:
    return time.perf_counter() * 1e6


def clock_anchor() -> Dict[str, float]:
    """One (wall, monotonic) clock pair sampled back to back — the
    per-process anchor every snapshot/trace/dump carries, so timelines
    recorded on the monotonic clock (spans) merge with wall-clock streams
    (events) and with other processes' spans without drift. Keys:
    ``wall_us`` (``time.time()`` µs) and ``perf_us``
    (``time.perf_counter()`` µs)."""
    return {"wall_us": time.time() * 1e6, "perf_us": _perf_us()}


def _append_span(name: str, span_id: int, parent: int, start_us: float,
                 dur_us: float, args: Optional[dict]) -> None:
    """Append one completed record to the ring (the one shared writer:
    :func:`emit_span` and :class:`_Span` both land here)."""
    global _spans_dropped
    lane = _thread_lane()
    with _lock:
        rec = {"name": name, "id": span_id, "parent": parent, "tid": lane,
               "ts": int(start_us), "dur": int(dur_us)}
        if args:
            rec["args"] = args
        _spans.append(rec)
        if len(_spans) > SPANS_MAX:
            drop = len(_spans) - SPANS_MAX
            del _spans[:drop]
            _spans_dropped += drop


def new_span_id() -> int:
    """Allocate one span id from the process allocator WITHOUT emitting a
    span — the handle a sampled request carries across the worker-thread
    boundary so its child spans can name an explicit ``parent=`` and the
    root can be emitted later under ``span_id=`` (the ring's thread-local
    parent chain does not cross threads)."""
    global _span_seq
    with _lock:
        _span_seq += 1
        return _span_seq


def emit_span(name: str, start_us: float, dur_us: float,
              parent: Optional[int] = None, span_id: Optional[int] = None,
              **args) -> None:
    """Append one COMPLETED span to the process span ring: ``start_us``
    on the ``time.perf_counter()`` microsecond clock, ``dur_us`` its
    duration. Parents under the thread's currently open :func:`span`
    (matching the native ``EmitSpan``) unless an explicit ``parent=`` is
    given — the cross-thread handoff used by sampled request chains
    (pass ``parent=0`` for an explicit root). ``span_id=`` reuses an id
    from :func:`new_span_id` instead of allocating. Extra keyword args
    ride along as the span's ``args`` dict (keep them small — shard ids,
    byte counts). No-op when telemetry is disabled; the ring keeps the
    most recent :data:`SPANS_MAX` spans and counts what it overwrote."""
    if not enabled():
        return
    if span_id is None:
        span_id = new_span_id()
    if parent is None:
        parent = getattr(_tls, "open_span", 0)
    _append_span(name, span_id, parent, start_us, dur_us, args or None)


class _Span:
    """Context manager behind :func:`span`; exposes ``set_arg`` for the
    dominant dimension of the work (bytes, rows, shard id)."""

    __slots__ = ("name", "args", "_start", "_id", "_parent", "_active")

    def __init__(self, name: str, args: Optional[dict]):
        self.name = name
        self.args = args

    def set_arg(self, key: str, value) -> None:
        """Attach one key/value to the span's args."""
        if self.args is None:
            self.args = {}
        self.args[key] = value

    def __enter__(self) -> "_Span":
        self._active = enabled()
        if not self._active:
            return self
        global _span_seq
        with _lock:
            _span_seq += 1
            self._id = _span_seq
        self._parent = getattr(_tls, "open_span", 0)
        _tls.open_span = self._id
        self._start = _perf_us()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if not self._active:
            return
        dur = _perf_us() - self._start
        _tls.open_span = self._parent
        _append_span(self.name, self._id, self._parent, self._start, dur,
                     self.args)


def span(name: str, **args) -> _Span:
    """RAII trace span: ``with telemetry.span("rowblock.next"): ...``
    records one completed span (perf-counter clock, µs) into the process
    span ring at scope exit, parented under the thread's currently open
    span. Disabled (:func:`enabled` False) cost: one attribute read.
    Extra kwargs become the span's ``args``."""
    return _Span(name, args or None)


def spans() -> List[dict]:
    """A copy of the buffered Python span ring (most recent
    :data:`SPANS_MAX` completed spans, emit order)."""
    with _lock:
        return list(_spans)


def _native_trace_doc() -> Optional[dict]:
    """The native span-ring document (``dct_trace_snapshot``), or None
    when the library is not loaded. Never triggers a build."""
    lib = _native_lib_if_loaded()
    if lib is None:
        return None
    import ctypes
    out = ctypes.c_char_p()
    if lib.dct_trace_snapshot(ctypes.byref(out)) != 0:
        return None
    try:
        return json.loads(ctypes.string_at(out).decode())
    finally:
        lib.dct_str_free(out)


def trace_snapshot() -> dict:
    """The process trace document: Python spans (perf-counter clock) plus
    the native ring's document (steady clock) when the library is already
    loaded, each with its own (wall, monotonic) anchor pair. Schema:
    ``{"version", "pid", "anchor": {"wall_us", "perf_us"}, "spans": [...],
    "dropped", "native": <dct_trace_snapshot doc>|None}``. Use
    :func:`trace_json` for the merged wall-clock Chrome-trace render."""
    return {"version": 1, "pid": os.getpid(), "anchor": clock_anchor(),
            "spans": spans(), "dropped": _spans_dropped,
            "native": _native_trace_doc()}


def _wall_spans(snap: dict) -> List[dict]:
    """Flatten a :func:`trace_snapshot` doc into ONE list of spans on the
    wall-clock µs timeline: each half's spans are shifted by its own
    (wall, monotonic) anchor pair, so native (steady-clock) and Python
    (perf-counter) spans land on the same axis — and, across processes,
    on the same axis as every other rank's."""
    out = []
    a = snap.get("anchor") or {}
    shift = float(a.get("wall_us", 0)) - float(a.get("perf_us", 0))
    for s in snap.get("spans", ()):
        rec = dict(s)
        rec["ts"] = int(s["ts"] + shift)
        rec["cat"] = "python"
        out.append(rec)
    nat = snap.get("native")
    if nat:
        na = nat.get("anchor") or {}
        nshift = float(na.get("wall_us", 0)) - float(na.get("steady_us", 0))
        for s in nat.get("spans", ()):
            rec = {"name": s["name"], "id": s["id"], "parent": s["parent"],
                   # native lanes get their own tid namespace so a native
                   # worker thread never shares a lane with a Python one
                   "tid": 1000 + int(s["tid"]),
                   "ts": int(s["ts"] + nshift), "dur": int(s["dur"]),
                   "cat": "native"}
            if s.get("arg"):
                rec["args"] = {"arg": s["arg"]}
            out.append(rec)
    out.sort(key=lambda r: r["ts"])
    return out


def _chrome_events(wall_spans: List[dict], pid, label: str) -> List[dict]:
    """Chrome-trace/Perfetto events for one process lane: complete ("X")
    events plus the process_name metadata record."""
    evs = [{"ph": "M", "name": "process_name", "pid": pid, "tid": 0,
            "args": {"name": label}}]
    for s in wall_spans:
        ev = {"ph": "X", "name": s["name"], "pid": pid, "tid": s["tid"],
              "ts": s["ts"], "dur": max(int(s["dur"]), 0),
              "cat": s.get("cat", "python"),
              "args": dict(s.get("args") or {},
                           span_id=s["id"], parent=s["parent"])}
        evs.append(ev)
    return evs


def trace_json(snap: Optional[dict] = None) -> str:
    """Render the process trace (default: take :func:`trace_snapshot`
    now) as Chrome-trace JSON — loadable in Perfetto / ``chrome://
    tracing``. C++ and Python spans are merged onto ONE wall-clock µs
    timeline via each half's (wall, monotonic) anchor pair; native worker
    threads get their own ``tid`` lanes. For the job-wide merged view
    across ranks, scrape a live tracker's ``GET /trace``
    (:func:`cluster_trace_json`)."""
    if snap is None:
        snap = trace_snapshot()
    pid = snap.get("pid", 0)
    evs = _chrome_events(_wall_spans(snap), pid, f"pid {pid}")
    return json.dumps({"traceEvents": evs, "displayTimeUnit": "ms"})


# -- stall attribution (doc/observability.md "Stall attribution") ------------
# verdict -> stall_verdict_code gauge value
VERDICT_CODES = {"unknown": -1, "fill_bound": 0, "parse_bound": 1,
                 "consumer_bound": 2, "transfer_bound": 3,
                 "stage_bound": 4, "compile_bound": 5,
                 "straggler_bound": 6}

# the consumer counts as the binding stage when it spent less than this
# fraction of the pipeline's busy time waiting on the head-of-line chunk
# (the pipeline kept up; whatever is downstream of it did not)
_STARVED_WAIT_FRACTION = 0.05


def stall_attribution(snap: Optional[dict] = None) -> dict:
    """Per-stage occupancy plus a fill-bound / parse-bound /
    consumer-bound / transfer-bound / stage-bound / compile-bound
    verdict, derived from the span-backed stage histograms of one
    snapshot (default: take one now).

    The decision tree reads the batch path's own instrumentation, device
    lane first (doc/observability.md "Device lane"): XLA compilation time
    (``device_compile_us``, the jax.monitoring hook) dominating every
    other stage means shapes are churning (``compile_bound``); the NET
    host batch-assembly time — ``device_stage_us`` minus the fill/parse/
    pipeline-wait time nested inside it — dominating means the pad+bucket
    +pack stage binds (``stage_bound``); ``device_transfer_us``
    dominating both fill and parse means the host→HBM hop binds
    (``transfer_bound``). Host side, a small
    ``parse_stage_reassemble_wait_us`` relative to the pipeline's busy
    time means the pipeline kept up and the CONSUMER binds
    (``consumer_bound``); otherwise the consumer was starved by the
    pipeline, and the larger of the fill (source read + cache replay) and
    parse (scan + slice decode) sums names the stage. With no stage
    observations (spans disabled, nothing run) the verdict is
    ``unknown``. Returns ``{"verdict", "stage_us": {...}, "occupancy":
    {stage: fraction}}``; the same result rides every snapshot as the
    ``stall_stage_occupancy{stage=}`` / ``stall_verdict_code`` gauges."""
    if snap is None:
        snap = snapshot()
    sums: Dict[str, float] = {}
    for h in snap.get("histograms", ()):
        if not h.get("labels"):
            sums[h["name"]] = sums.get(h["name"], 0.0) + float(h["sum"])
    fill = sums.get("parse_stage_fill_us", 0.0) + \
        sums.get("cache_read_us", 0.0)
    parse = sums.get("parse_stage_parse_us", 0.0) + \
        sums.get("parse_stage_scan_us", 0.0)
    wait = sums.get("parse_stage_reassemble_wait_us", 0.0)
    transfer = sums.get("device_transfer_us", 0.0)
    # NET batch assembly: device_stage_us wraps batcher.next_batch(),
    # which nests the parse pipeline's fill/parse/head-of-line time —
    # subtracting those leaves the pad+bucket+pack cost this stage adds
    stage = max(sums.get("device_stage_us", 0.0) - fill - parse - wait,
                0.0)
    compile_t = sums.get("device_compile_us", 0.0)
    dev_wait = sums.get("device_wait_us", 0.0)
    busy = fill + parse
    stage_us = {"fill": fill, "parse": parse, "pipeline_wait": wait,
                "transfer": transfer, "stage": stage,
                "compile": compile_t, "device_wait": dev_wait}
    total = busy + transfer + stage + compile_t
    occupancy = {k: (stage_us[k] / total if total > 0 else 0.0)
                 for k in ("fill", "parse", "transfer", "stage",
                           "compile")}
    occupancy["pipeline_wait"] = wait / total if total > 0 else 0.0
    if total <= 0:
        verdict = "unknown"
    elif compile_t > max(transfer, stage, fill, parse):
        verdict = "compile_bound"
    elif stage > max(transfer, fill, parse):
        verdict = "stage_bound"
    elif transfer > max(fill, parse):
        verdict = "transfer_bound"
    elif wait <= _STARVED_WAIT_FRACTION * busy:
        verdict = "consumer_bound"
    elif fill > parse:
        verdict = "fill_bound"
    else:
        verdict = "parse_bound"
    return {"verdict": verdict, "stage_us": stage_us,
            "occupancy": occupancy}


def device_overlap_ratio(span_list: Optional[List[dict]] = None
                         ) -> Optional[float]:
    """Fraction of host→device transfer time hidden behind consumer
    compute, derived from the Python span ring (default: read it now):
    each ``device.put`` span's interval is intersected with the merged
    ``device.wait`` intervals — transfer time the consumer spent WAITING
    through is exposed, the rest ran while the consumer computed and is
    hidden. All spans share one ``perf_counter`` clock across threads, so
    the interval math needs no anchor shifting. Returns a value in
    [0, 1], or ``None`` when the ring holds no ``device.put`` span (the
    device lane never ran, or spans are disabled)."""
    if span_list is None:
        span_list = spans()
    xfer = [(s["ts"], s["ts"] + s["dur"]) for s in span_list
            if s["name"] == "device.put"]
    if not xfer:
        return None
    waits = sorted((s["ts"], s["ts"] + s["dur"]) for s in span_list
                   if s["name"] == "device.wait")
    merged: List[List[float]] = []
    for a, b in waits:
        if merged and a <= merged[-1][1]:
            merged[-1][1] = max(merged[-1][1], b)
        else:
            merged.append([a, b])
    total = exposed = 0.0
    for a, b in xfer:
        total += b - a
        for wa, wb in merged:
            if wa >= b:
                break
            lo, hi = max(a, wa), min(b, wb)
            if hi > lo:
                exposed += hi - lo
    if total <= 0:
        return None
    return min(max((total - exposed) / total, 0.0), 1.0)


def straggler_attribution(step_durs_by_rank: Dict[int, List[float]],
                          factor: float = 2.0,
                          min_steps: int = 3) -> dict:
    """Name the mesh straggler from per-rank recent step durations
    (doc/observability.md "Step timelines"): a rank is ``straggler_bound``
    when its median step over the window sustains above ``factor`` times
    the median of the OTHER ranks' medians — a sustained-ratio test, so
    one GC pause or one slow step cannot page. Ranks with fewer than
    ``min_steps`` observations abstain; fewer than two voting ranks (no
    peer baseline) is ``unknown``. Returns ``{"verdict", "rank",
    "ratio", "median_us": {rank: median}}`` — ``rank``/``ratio`` are
    ``None``/``0.0`` when no straggler is bound."""
    medians: Dict[int, float] = {}
    for rank, durs in step_durs_by_rank.items():
        if len(durs) >= max(1, int(min_steps)):
            s = sorted(durs)
            medians[rank] = float(s[len(s) // 2])
    out = {"verdict": "unknown", "rank": None, "ratio": 0.0,
           "median_us": medians}
    if len(medians) < 2:
        return out
    worst_rank, worst_ratio = None, 0.0
    for rank, med in medians.items():
        peers = sorted(m for r, m in medians.items() if r != rank)
        peer_med = peers[len(peers) // 2]
        if peer_med <= 0:
            continue
        ratio = med / peer_med
        if ratio > worst_ratio:
            worst_rank, worst_ratio = rank, ratio
    if worst_rank is not None and worst_ratio > factor:
        out["verdict"] = "straggler_bound"
        out["rank"] = worst_rank
        out["ratio"] = worst_ratio
    return out


# -- flight recorder (doc/observability.md "Flight recorder") ----------------
_flight_seq = 0


def flight_dump(reason: str, rank: Optional[int] = None) -> Optional[str]:
    """Write a postmortem — the span ring (both halves), the event ring,
    and a full metric snapshot, with this process's clock anchors — to
    ``$DMLC_TRACE_DUMP/flight_<pid>_<n>.json``. No-op (returns None) when
    ``DMLC_TRACE_DUMP`` is unset; every failure is swallowed, because a
    postmortem writer must never mask the failure it is recording.
    Called on abort broadcasts, tracker aborts, and dead-rank write-offs;
    the native half mirrors it for fault-plane quarantines."""
    out_dir = os.environ.get("DMLC_TRACE_DUMP")
    if not out_dir:
        return None
    global _flight_seq
    try:
        with _lock:
            _flight_seq += 1
            seq = _flight_seq
        doc = {"reason": reason, "rank": rank, "pid": os.getpid(),
               "wall_ts": time.time(), "anchor": clock_anchor(),
               "trace": trace_snapshot(), "metrics": snapshot()}
        os.makedirs(out_dir, exist_ok=True)
        path = os.path.join(out_dir,
                            f"flight_{os.getpid()}_{seq}.json")
        with open(path, "w") as f:
            json.dump(doc, f)
            f.write("\n")
        return path
    except Exception:
        return None


# -- cluster aggregation (the tracker's /metrics + /trace) -------------------
def rank_export(max_spans: int = 2048) -> dict:
    """The per-rank telemetry document a worker ships to the tracker in
    answer to a TELEMETRY_PULL frame (doc/observability.md "Cluster
    aggregation"): the merged metric snapshot plus the span ring
    flattened onto the WALL clock (each half shifted by its own anchor
    pair, so the tracker merges ranks without knowing their monotonic
    epochs). Spans are capped at the most recent ``max_spans`` to bound
    the frame."""
    snap = snapshot()
    wall = _wall_spans(trace_snapshot())
    if len(wall) > max_spans:
        wall = wall[-max_spans:]
    return {"pid": os.getpid(), "anchor": snap["anchor"],
            "metrics": {"counters": snap["counters"],
                        "gauges": snap["gauges"],
                        "histograms": snap["histograms"]},
            "spans": wall}


def _aggregate_ranks(per_rank: Dict[int, dict]) -> dict:
    """Element-wise job sums across rank metric docs: counters by (name,
    labels); histograms by (name, labels) with bucket-wise addition."""
    counters: Dict[tuple, float] = {}
    hists: Dict[tuple, dict] = {}
    for doc in per_rank.values():
        m = doc.get("metrics", {})
        for c in m.get("counters", ()):
            key = (c["name"], _labels_key(c.get("labels")))
            counters[key] = counters.get(key, 0) + c["value"]
        for h in m.get("histograms", ()):
            key = (h["name"], _labels_key(h.get("labels")))
            agg = hists.get(key)
            if agg is None:
                hists[key] = {"count": h["count"], "sum": h["sum"],
                              "buckets": list(h["buckets"])}
            else:
                agg["count"] += h["count"]
                agg["sum"] += h["sum"]
                agg["buckets"] = [a + b for a, b in
                                  zip(agg["buckets"], h["buckets"])]
    return {"counters": counters, "histograms": hists}


def cluster_prometheus_text(per_rank: Dict[int, dict],
                            local_snap: Optional[dict] = None) -> str:
    """The job-wide Prometheus exposition a live tracker serves at
    ``GET /metrics``: the tracker process's own merged snapshot
    (unchanged — back-compatible with single-process scrapes), every
    pulled rank's series re-labeled with ``rank="<r>"``, and job-level
    sums under the ``job:<name>`` aggregate-naming convention (counters
    summed value-wise, histograms bucket-wise) so job counters equal the
    per-rank sums counter-for-counter. One ``# HELP``/``# TYPE`` pair per
    metric name across the whole document."""
    if local_snap is None:
        local_snap = snapshot()
    # family-grouped: the tracker's own series and every rank's
    # rank="r"-labeled series of one metric land in ONE contiguous group
    # (the exposition format's grouping rule)
    fams: Dict[str, dict] = {}
    _collect_doc(fams, local_snap)
    for rank in sorted(per_rank):
        _collect_doc(fams, per_rank[rank].get("metrics", {}),
                     extra=f'rank="{rank}"')
    agg = _aggregate_ranks(per_rank)
    for (name, labels), value in sorted(agg["counters"].items()):
        f = fams.setdefault("job:" + name, {
            "kind": "counter",
            "help": f"job-wide sum of {name} across ranks", "lines": []})
        f["lines"].append(f"job:{name}{_fmt_labels(dict(labels))} "
                          f"{_fmt_value(value)}")
    for (name, labels), h in sorted(agg["histograms"].items()):
        f = fams.setdefault("job:" + name, {
            "kind": "histogram",
            "help": f"job-wide bucket-wise sum of {name} across ranks",
            "lines": []})
        _render_hist_series(f["lines"], "job:" + name, dict(labels), h)
    return _emit_families(fams)


def cluster_trace_json(per_rank: Dict[int, dict],
                       local_trace: Optional[dict] = None,
                       meta: Optional[dict] = None) -> str:
    """The merged job timeline a live tracker serves at ``GET /trace``:
    one Chrome-trace/Perfetto document with a process lane PER RANK (the
    event ``pid`` is the rank, the lane is labeled with the rank and its
    OS pid) plus the tracker's own lane. Every rank's spans arrive
    already wall-clock-shifted by that rank's anchor pair
    (:func:`rank_export`), so the lanes share one timeline. ``meta``
    (e.g. the tracker's :func:`straggler_attribution` verdict) rides as
    one metadata ("M") event on the tracker lane."""
    evs: List[dict] = []
    for rank in sorted(per_rank):
        doc = per_rank[rank]
        evs += _chrome_events(doc.get("spans", ()), rank,
                              f"rank {rank} (pid {doc.get('pid', '?')})")
    if local_trace is None:
        local_trace = trace_snapshot()
    evs += _chrome_events(_wall_spans(local_trace), 999999,
                          f"tracker (pid {local_trace.get('pid', '?')})")
    if meta:
        evs.append({"ph": "M", "name": "job_meta", "pid": 999999,
                    "tid": 0, "args": dict(meta)})
    return json.dumps({"traceEvents": evs, "displayTimeUnit": "ms"})


def _native_lib_if_loaded():
    """The loaded ctypes library, or None. NEVER triggers the native
    build: a tracker-only process (or a scrape) must not block minutes on
    a C++ compile just to report its own metrics."""
    try:
        from dmlc_core_tpu.io import native as _native
    except Exception:  # jax/numpy missing in a minimal tracker venv
        return None
    return _native._lib


def _native_snapshot_dict(force: bool) -> Optional[dict]:
    if force:
        from dmlc_core_tpu.io import native as _native
        _native.lib()
    lib = _native_lib_if_loaded()
    if lib is None:
        return None
    import ctypes
    out = ctypes.c_char_p()
    if lib.dct_telemetry_snapshot(ctypes.byref(out)) != 0:
        return None
    try:
        doc = json.loads(ctypes.string_at(out).decode())
    finally:
        lib.dct_str_free(out)
    return doc


def snapshot(native: Optional[bool] = None) -> dict:
    """The merged telemetry document — the single source every surface
    serves (C ABI consumers read the native half directly; the tracker's
    ``GET /metrics`` renders this via :func:`prometheus_text`).

    ``native``: ``None`` (default) merges the native registry only when
    the library is ALREADY loaded (never triggers a build); ``True``
    forces loading/building it; ``False`` excludes it.

    Schema (version 1, append-only): ``{"version", "enabled", "anchor":
    {"wall_us", "perf_us"}, "native": bool, "native_anchor": {...}|None,
    "counters": [{"name", "labels", "value"}], "gauges": [...],
    "histograms": [{"name", "labels", "count", "sum", "buckets":
    [HIST_BUCKETS+1 counts]}], "events": [...]}``. The anchor is this
    process's (wall, monotonic) clock pair; ``native_anchor`` the native
    half's (wall, steady) pair from the same snapshot. The gauge list
    ends with the derived stall-attribution gauges
    (``stall_stage_occupancy{stage=}`` + ``stall_verdict_code``,
    :func:`stall_attribution`)."""
    with _lock:
        collectors = list(_collectors)
    for fn in collectors:
        try:
            fn()
        except Exception:
            pass  # a broken collector must not sink the scrape
    doc = {"version": SNAPSHOT_VERSION, "enabled": enabled(),
           "anchor": clock_anchor(), "native": False,
           "native_anchor": None, "counters": [], "gauges": [],
           "histograms": [], "events": []}
    if native is not False:
        nat = _native_snapshot_dict(force=bool(native))
        if nat is not None:
            doc["native"] = True
            doc["native_anchor"] = nat.get("anchor")
            doc["counters"] += nat.get("counters", [])
            doc["gauges"] += nat.get("gauges", [])
            doc["histograms"] += nat.get("histograms", [])
    with _lock:
        for c in _counters.values():
            doc["counters"].append({"name": c.name, "labels": c.labels,
                                    "value": c.value})
        for g in _gauges.values():
            doc["gauges"].append({"name": g.name, "labels": g.labels,
                                  "value": g.value})
        for h in _hists.values():
            rec = {"name": h.name, "labels": h.labels, "count": h.count,
                   "sum": h.sum, "buckets": list(h.buckets)}
            if h.exemplars:
                # JSON-snapshot only (never the text exposition): the
                # bucket -> last-sampled-trace-id breadcrumbs
                rec["exemplars"] = dict(h.exemplars)
            doc["histograms"].append(rec)
        doc["events"] = list(_events)
        # the Python ring's overflow count, labeled so it can never
        # collide with the native half's spans_dropped_total sample
        doc["counters"].append({"name": "spans_dropped_total",
                                "labels": {"half": "python"},
                                "value": _spans_dropped})
    # derived stall-attribution gauges ride every snapshot (and therefore
    # every /metrics scrape) without a collector: they are computed FROM
    # the snapshot, so a collector would recurse
    att = stall_attribution(doc)
    for stage, frac in att["occupancy"].items():
        doc["gauges"].append({"name": "stall_stage_occupancy",
                              "labels": {"stage": stage}, "value": frac})
    doc["gauges"].append({"name": "stall_verdict_code", "labels": {},
                          "value": VERDICT_CODES[att["verdict"]]})
    # same derivation rule for the device lane's overlap ratio: computed
    # FROM the span ring at snapshot time (doc/observability.md "Device
    # lane"); -1 marks "no transfer observed yet", keeping 0 meaningful
    # (a lane that ran fully exposed)
    ratio = device_overlap_ratio()
    doc["gauges"].append({"name": "device_overlap_ratio", "labels": {},
                          "value": -1.0 if ratio is None else ratio})
    return doc


def _escape_label(v: str) -> str:
    """Prometheus label-value escaping: backslash, double-quote, newline."""
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
                 .replace("\n", "\\n")


def _fmt_labels(labels: Dict[str, str], extra: str = "") -> str:
    parts = [f'{k}="{_escape_label(v)}"' for k, v in sorted(labels.items())]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def _fmt_value(v) -> str:
    if isinstance(v, float) and not v.is_integer():
        return repr(v)
    return str(int(v))


# One-line HELP text per cataloged metric name, emitted as ``# HELP``
# exposition lines (doc/observability.md is the long-form catalog).
# Uncataloged names (tests, ad-hoc metrics) simply carry no HELP line.
# MACHINE-CHECKED (scripts/analyze.py Pass 4, doc/analysis.md): every
# metric registered in shipped code — either half — must have an entry
# here AND a doc/observability.md catalog row, and every entry here must
# match a live registration; `make analyze` fails on drift either way.
METRIC_HELP: Dict[str, str] = {
    "io_requests_total": "HTTP requests sent",
    "io_retries_total": "backoff sleeps taken",
    "io_backoff_ms_total": "total milliseconds slept in backoff",
    "io_timeouts_total": "per-attempt socket timeout expiries",
    "io_faults_injected_total": "DMLC_IO_FAULT_PLAN firings",
    "io_giveups_total": "retry loops that exhausted their budget",
    "io_deadline_exhausted_total": "giveups caused by the per-op deadline",
    "io_connect_us": "TCP connect latency per request (us)",
    "io_ttfb_us": "request-sent to first response byte (us)",
    "io_recv_us": "one response-body pull (us)",
    "io_range_issued_total": "range fetches issued",
    "io_range_retried_total": "per-range retry attempts",
    "io_range_degraded_200_total":
        "streams degraded to the sequential lane (origin ignored Range)",
    "io_range_bytes": "completed range sizes (bytes)",
    "io_range_wait_us": "consumer head-of-line wait (us)",
    "io_range_sched_bytes": "scheduler's current range size",
    "io_range_sched_concurrency": "scheduler's current worker credit",
    "parse_chunks_read_total": "chunks admitted by reader stages",
    "parse_blocks_delivered_total": "row blocks handed to consumers",
    "parse_reader_waits_total": "reader blocked on the in-flight bound",
    "parse_worker_waits_total": "worker slept with no claimable slice",
    "parse_consumer_waits_total":
        "consumer slept on the head-of-line chunk",
    "parse_stage_fill_us": "one ReadChunk, source to owned bytes (us)",
    "parse_stage_scan_us": "one TileCuts slice pre-tiling (us)",
    "parse_stage_parse_us": "one worker slice decode (us)",
    "parse_stage_reassemble_wait_us":
        "one consumer head-of-line wait (us)",
    "cache_hits_total": "epochs served from a validated binary shard",
    "cache_misses_total": "epochs served from the text lane",
    "cache_transcodes_total": "completed atomically-published transcodes",
    "cache_write_errors_total":
        "transcode passes lost to local-I/O failure (quarantined)",
    "cache_read_us": "one replay block hand-out (us)",
    "cache_write_us": "one transcoded block append (us)",
    "fs_fault_injected_total": "DMLC_FS_FAULT_PLAN firings per op",
    "ckpt_save_failures_total": "checkpoint saves that raised",
    "event_log_dropped_total":
        "tracker event-log lines dropped by a contained I/O failure",
    "rowblock_batch_us": "one RowBlockIter native block pull (us)",
    "rowblock_batches_total": "row blocks served",
    "rowblock_skipped_batches_total": "on_error=skip skips",
    "device_transfer_us": "one device_put, submit to arrays ready (us)",
    "device_put_submit_us": "the device_put dispatch alone (us)",
    "device_put_block_us": "dispatch-to-ready DMA wait (us)",
    "device_batches_total": "batches dispatched to the device",
    "device_transfer_bytes_total": "host bytes handed to device_put",
    "device_stage_us":
        "one host batch assembly (parse+pad+bucket+pack) on the staging "
        "thread (us)",
    "device_wait_us":
        "consumer head-of-line wait for the next device batch (us)",
    "device_put_failures_total": "device_put calls that raised",
    "device_host_q_depth": "staged host batches queued for transfer",
    "device_ready_q_depth": "device batches queued for the consumer",
    "device_compile_events_total":
        "first sight of a device batch shape (one XLA re-trace per "
        "jitted consumer)",
    "device_distinct_shapes": "distinct device batch shapes this process",
    "device_zero_copy_batches_total":
        "batches transferred by the zero-copy device_put path (staging "
        "buffers aliased/DMA'd in place, no host copy)",
    "device_zero_copy_fallbacks_total":
        "batches that fell back to the copying device_put path, by reason",
    "device_recycle_skipped":
        "aliased host staging buffers dropped from the deferred-recycle "
        "parking lot because the consumer held more batches than its "
        "depth (zero-copy backends)",
    "device_jit_compiles_total":
        "XLA compilations observed via the jax.monitoring hook",
    "device_compile_us": "one XLA compilation (us, jax.monitoring)",
    "device_overlap_ratio":
        "fraction of transfer time hidden behind consumer compute "
        "(-1 before any transfer)",
    "device_probe_attempts_total": "bench device-probe subprocess attempts",
    "device_probe_timeouts_total": "bench device-probe attempt timeouts",
    "device_probe_state":
        "bench device-probe verdict (0 unknown, 1 ok, 2 unavailable, "
        "3 cached unavailable)",
    "tracker_num_workers": "workers the tracker expects",
    "tracker_alive": "1 while the tracker thread is serving",
    "tracker_finished": "1 once every worker checked out",
    "tracker_aborted": "1 after the job was aborted",
    "tracker_rank_phase_code":
        "0 assigned, 1 alive, 2 dead, 3 shutdown, 4 lost",
    "tracker_rank_heartbeat_age_seconds":
        "seconds since the rank's last beat (-1 before the first)",
    "tracker_rank_restarts": "recover count per rank",
    "tracker_rank_attempts": "assignment handshakes served per rank",
    "telemetry_events_total": "events per kind",
    "tracker_lease_pool": "shards free for acquisition",
    "tracker_lease_held": "shards currently leased to a rank",
    "tracker_lease_done": "shards checked out exactly once",
    "tracker_lease_reassigned": "leases reclaimed this epoch",
    "tracker_lease_reassigned_total": "reclaim events across the job",
    "lease_renew_us": "tracker-side implicit lease renewal on a ping (us)",
    "lease_acquire_us": "worker-side acquire round trip (us)",
    # elastic mesh training (doc/robustness.md "Elastic mesh training")
    "tracker_world_relaunches_total":
        "whole-world relaunches after a mesh abort (run_job mesh mode)",
    "mesh_step_aborts_total":
        "structured step aborts on this rank (between-steps raise or "
        "step-deadline watchdog)",
    "device_abort_drains_total":
        "device-pipeline abort drains (staging/transfer stopped, parked "
        "buffers dropped)",
    "stall_stage_occupancy":
        "fraction of instrumented batch-path time in the stage",
    "stall_verdict_code":
        "-1 unknown, 0 fill, 1 parse, 2 consumer, 3 transfer, 4 stage, "
        "5 compile, 6 straggler bound",
    "spans_dropped_total":
        "span-ring records overwritten by wrap, per half",
    # SLO plane (WindowedView/SloMonitor, doc/observability.md "SLO plane")
    "window_rate":
        "per-second counter rate over the rolling window, summed across "
        "label sets",
    "window_quantile":
        "delta-histogram quantile over the rolling window (overflow "
        "clamped to the top bucket bound)",
    "slo_burn_rate":
        "error-budget burn multiple per objective and window",
    "slo_page": "1 while any SLO objective is paging (latched)",
    "slo_page_trips_total": "SLO page activations per objective",
    "tracker_straggler_rank":
        "rank bound as the mesh straggler (-1 when none)",
    # measurement rig (scripts/loadrig.py, doc/benchmarking.md)
    "rig_requests_total": "open/closed-loop requests completed",
    "rig_errors_total": "load-generator requests that failed",
    "rig_shed_total":
        "open-loop arrivals shed past the lateness budget",
    "rig_intended_us":
        "request latency from the INTENDED start time (us; "
        "coordinated-omission-safe)",
    "rig_service_us":
        "request latency from the actual send time (us; hides queueing "
        "behind a stalled origin — kept for the divergence itself)",
    # host resource sampler (HostResourceSampler, doc/benchmarking.md)
    "host_cpu_busy_frac": "whole-host CPU busy fraction, last interval",
    "host_rss_bytes": "sampling process RSS, last sample",
    # online scoring plane (dmlc_core_tpu/serving/, doc/serving.md)
    "serve_requests_total": "HTTP requests parsed by the front end",
    "serve_admitted_total": "score requests admitted to the queue",
    "serve_scored_total": "score requests answered 200 with scores",
    "serve_shed_total":
        "requests shed by reason: queue_full, late (intended-time "
        "lateness budget), draining, breaker, slo_burn",
    "serve_rejects_total":
        "error responses by HTTP status code (sheds are additionally "
        "counted by reason in serve_shed_total)",
    "serve_errors_total": "5xx server-side failures (forward/internal)",
    "serve_queue_depth": "admission queue occupancy (bounded)",
    "serve_inflight": "admitted requests awaiting their response",
    "serve_batches_total": "micro-batches run through the forward",
    "serve_batch_rows": "real (pre-padding) rows per micro-batch",
    "serve_batch_fill":
        "percent of the padded rows bucket holding real rows",
    "serve_parse_us": "micro-batch native parse time (us)",
    "serve_forward_us": "padded-batch jitted forward time (us)",
    "serve_request_us":
        "admit-to-reply latency on the INTENDED-time clock (us; queue "
        "wait included, coordinated-omission-safe)",
    "serve_model_reloads_total": "model reloads that swapped params in",
    "serve_model_reload_failures_total":
        "failed reloads (last-good model kept serving)",
    "serve_breaker_state": "0 closed, 1 open, 2 half-open",
    "serve_draining": "1 while draining shutdown runs",
    "serve_distinct_shapes":
        "distinct padded (kind, rows, nnz) forward shapes this process",
    "serve_access_log_dropped_total":
        "access-log lines dropped by a contained I/O failure",
}


def _escape_help(text: str) -> str:
    """HELP-line escaping per the exposition spec: backslash and
    newline only (label-value escaping additionally covers quotes)."""
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _render_hist_series(lines: List[str], name: str, labels: Dict[str, str],
                        h: dict) -> None:
    """One histogram's cumulative ``_bucket{le=}`` / ``_sum`` /
    ``_count`` sample lines."""
    cum = 0
    for i, n in enumerate(h["buckets"]):
        cum += n
        le = "+Inf" if i == len(h["buckets"]) - 1 else str(1 << i)
        le_label = 'le="' + le + '"'
        lines.append(f"{name}_bucket{_fmt_labels(labels, le_label)} {cum}")
    lines.append(f"{name}_sum{_fmt_labels(labels)} "
                 f"{_fmt_value(h['sum'])}")
    lines.append(f"{name}_count{_fmt_labels(labels)} "
                 f"{_fmt_value(h['count'])}")


def _family(fams: Dict[str, dict], name: str, kind: str) -> List[str]:
    """The sample-line bucket for one metric family (first-seen order,
    first-seen kind)."""
    f = fams.get(name)
    if f is None:
        f = fams[name] = {"kind": kind, "lines": []}
    return f["lines"]


def _collect_doc(fams: Dict[str, dict], doc: dict, extra: str = "") -> None:
    """Bucket one metric document's counters/gauges/histograms by family,
    with an optional extra label (``rank="0"``) appended to every
    sample."""
    for c in doc.get("counters", ()):
        _family(fams, c["name"], "counter").append(
            f"{c['name']}{_fmt_labels(c['labels'], extra)} "
            f"{_fmt_value(c['value'])}")
    for g in doc.get("gauges", ()):
        _family(fams, g["name"], "gauge").append(
            f"{g['name']}{_fmt_labels(g['labels'], extra)} "
            f"{_fmt_value(g['value'])}")
    for h in doc.get("histograms", ()):
        labels = dict(h["labels"])
        if extra:
            k, v = extra.split("=", 1)
            labels[k] = v.strip('"')
        _render_hist_series(_family(fams, h["name"], "histogram"),
                            h["name"], labels, h)


def _emit_families(fams: Dict[str, dict]) -> str:
    """Render bucketed families as exposition text: every line of one
    metric family contiguous (the format's grouping rule — interleaved
    families are rejected by strict parsers), one ``# HELP`` (from the
    :data:`METRIC_HELP` catalog, spec escaping) + ``# TYPE`` pair first."""
    lines: List[str] = []
    for name, f in fams.items():
        help_text = f.get("help") or METRIC_HELP.get(name)
        if help_text:
            lines.append(f"# HELP {name} {_escape_help(help_text)}")
        lines.append(f"# TYPE {name} {f['kind']}")
        lines += f["lines"]
    return "\n".join(lines) + "\n"


def prometheus_text(snap: Optional[dict] = None) -> str:
    """Render a snapshot (default: take one now) in the Prometheus text
    exposition format (version 0.0.4): samples grouped per metric family
    behind one ``# HELP`` (from the :data:`METRIC_HELP` catalog) +
    ``# TYPE`` pair, label escaping per the spec, histograms as
    cumulative ``_bucket{le=...}`` series ending in ``le="+Inf"`` plus
    ``_sum``/``_count``."""
    if snap is None:
        snap = snapshot()
    fams: Dict[str, dict] = {}
    _collect_doc(fams, snap)
    return _emit_families(fams)


def events_jsonl(snap: Optional[dict] = None) -> str:
    """Render a snapshot's event stream (default: take one now) as JSONL —
    the PR-4 ``DMLC_TRACKER_EVENT_LOG`` schema, one ``{"ts", "event",
    ...}`` object per line."""
    if snap is None:
        snap = snapshot()
    return "".join(json.dumps(rec) + "\n" for rec in snap.get("events", []))


# ---------------------------------------------------------------------------
# Rolling windows + SLO plane (doc/observability.md "SLO plane"): every
# registry series is process-lifetime cumulative, which is the right
# substrate (resets are visible, sums are exact) but the wrong operator
# surface — "is NOW bad" needs rates and quantiles over the last minutes,
# not since boot.  The WindowedView snapshots the merged registry (native
# + Python — deltas over snapshots, so the C++ half needs zero hot-path
# changes) on a cadence and publishes per-window rate/quantile gauges;
# the SloMonitor turns two of those windows into multi-window burn rates
# against declared objectives and latches a page with hysteresis.
# ---------------------------------------------------------------------------

# cardinality ceiling on the compact per-(name, labels) state one tick
# keeps: a test registering thousands of ad-hoc series must degrade the
# window view (silently-partial windows over the FIRST _MAX_SERIES keys),
# never the process
_MAX_SERIES = 4096


def _compact_snapshot(snap: dict) -> Tuple[Dict[tuple, float],
                                           Dict[tuple, tuple]]:
    """Reduce one merged snapshot to the per-(name, labels) counter
    values and histogram (count, sum, buckets) tuples the window math
    needs — gauges are point-in-time and carry no delta meaning, so they
    are dropped (which is also what makes :meth:`WindowedView.tick` safe
    to run off :func:`snapshot`: the derived gauges it appends are
    ignored here)."""
    counters: Dict[tuple, float] = {}
    hists: Dict[tuple, tuple] = {}
    for c in snap.get("counters", ()):
        if len(counters) >= _MAX_SERIES:
            break
        key = (c["name"], _labels_key(c.get("labels")))
        counters[key] = counters.get(key, 0.0) + float(c["value"])
    for h in snap.get("histograms", ()):
        if len(hists) >= _MAX_SERIES:
            break
        key = (h["name"], _labels_key(h.get("labels")))
        prev = hists.get(key)
        if prev is None:
            hists[key] = (int(h["count"]), float(h["sum"]),
                          tuple(h["buckets"]))
        else:
            hists[key] = (prev[0] + int(h["count"]),
                          prev[1] + float(h["sum"]),
                          tuple(a + b for a, b in
                                zip(prev[2], h["buckets"])))
    return counters, hists


class SloMonitor:
    """Multi-window burn-rate monitor over a :class:`WindowedView`
    (doc/observability.md "SLO plane").

    Two declared objectives, both on the serving plane's own series:
    **availability** (fraction of non-error, non-shed answers,
    ``DMLC_SLO_AVAILABILITY_TARGET``) and **latency** (fraction of
    answers under ``DMLC_SLO_LATENCY_TARGET_MS`` on the intended-time
    clock, ``DMLC_SLO_LATENCY_TARGET``). Each objective's burn rate —
    (bad fraction over the window) / (error budget) — is published per
    window as ``slo_burn_rate{slo=,window=}``; a page latches when EVERY
    window burns at ``DMLC_SLO_FAST_BURN`` or above (the multi-window
    rule: the fast window proves it is happening NOW, the slow window
    proves it is not a blip) and clears with hysteresis when the fastest
    window drops under ``DMLC_SLO_CLEAR_BURN``. A page flips
    ``slo_page``, bumps ``slo_page_trips_total{slo=}``, and lands a
    flight dump naming the tripping windows and burn values.

    Sheds the admission gate took BECAUSE of the page (``reason=
    "slo_burn"``) are excluded from the bad count — otherwise the
    monitor's own load-shedding would hold the burn high forever and the
    page could never clear once the underlying fault lifted."""

    def __init__(self):
        from dmlc_core_tpu.tracker.wire import env_float, env_int
        self.availability_target = env_float(
            "DMLC_SLO_AVAILABILITY_TARGET", 0.999)
        self.latency_target_ms = env_int("DMLC_SLO_LATENCY_TARGET_MS", 250)
        self.latency_target = env_float("DMLC_SLO_LATENCY_TARGET", 0.99)
        self.fast_burn = env_float("DMLC_SLO_FAST_BURN", 14.4)
        self.slow_burn = env_float("DMLC_SLO_SLOW_BURN", 6.0)
        self.clear_burn = env_float("DMLC_SLO_CLEAR_BURN", 1.0)
        self._paging: set = set()
        self._page_gauge = gauge("slo_page")

    @property
    def paging(self) -> bool:
        """Whether any objective is currently paging (latched)."""
        return bool(self._paging)

    @staticmethod
    def _availability_burn(dcounters: Dict[tuple, float],
                           budget: float) -> float:
        good = bad = 0.0
        for (name, labels), v in dcounters.items():
            v = max(v, 0.0)
            if name == "serve_scored_total":
                good += v
            elif name == "serve_errors_total":
                bad += v
            elif name == "serve_shed_total":
                if dict(labels).get("reason") != "slo_burn":
                    bad += v
        total = good + bad
        if total <= 0:
            return 0.0
        return (bad / total) / budget

    def _latency_burn(self, dhists: Dict[tuple, tuple],
                      budget: float) -> float:
        count = 0
        buckets = [0] * (HIST_BUCKETS + 1)
        for (name, _labels), (dc, _ds, db) in dhists.items():
            if name != "serve_request_us":
                continue
            count += max(dc, 0)
            for i, n in enumerate(db):
                buckets[i] += max(n, 0)
        if count <= 0:
            return 0.0
        target_us = self.latency_target_ms * 1000
        good = sum(n for i, n in enumerate(buckets)
                   if i < HIST_BUCKETS and (1 << i) <= target_us)
        bad = max(count - good, 0)
        return (bad / count) / budget

    def evaluate(self, deltas: Dict[str, tuple]) -> None:
        """Evaluate both objectives over one tick's per-window deltas
        (``{window_label: (elapsed_s, dcounters, dhists)}`` from
        :meth:`WindowedView.deltas`), publish the burn gauges, and run
        the page/clear latch."""
        if not deltas:
            return
        burns: Dict[str, Dict[str, float]] = {"availability": {},
                                              "latency": {}}
        avail_budget = max(1.0 - self.availability_target, 1e-9)
        lat_budget = max(1.0 - self.latency_target, 1e-9)
        for label, (_elapsed, dcounters, dhists) in deltas.items():
            burns["availability"][label] = self._availability_burn(
                dcounters, avail_budget)
            burns["latency"][label] = self._latency_burn(
                dhists, lat_budget)
        # the hysteresis clear reads the most responsive window — the
        # one whose delta spans the least elapsed time
        fastest = min(deltas, key=lambda lb: deltas[lb][0])
        for slo, per_window in burns.items():
            for label, burn in per_window.items():
                labels = {"slo": slo, "window": label}
                gauge("slo_burn_rate", labels).set(round(burn, 4))
            if slo not in self._paging:
                if per_window and min(per_window.values()) >= \
                        self.fast_burn:
                    self._paging.add(slo)
                    counter("slo_page_trips_total", {"slo": slo}).inc()
                    detail = ", ".join(
                        f"{lb}={b:.1f}x" for lb, b in
                        sorted(per_window.items()))
                    emit_event("slo-page", slo=slo, burns=detail)
                    flight_dump(f"slo-page: {slo} burn [{detail}] >= "
                                f"{self.fast_burn}x budget")
            elif per_window.get(fastest, 0.0) < self.clear_burn:
                self._paging.discard(slo)
                emit_event("slo-page-clear", slo=slo)
        self._page_gauge.set(1.0 if self._paging else 0.0)


class WindowedView:
    """Rolling-window view over the cumulative registry
    (doc/observability.md "SLO plane").

    A daemon ticker (cadence ``DMLC_SLO_TICK_MS``) takes compact
    registry snapshots and keeps just enough of them to serve deltas for
    each configured window (default ``fast`` = ``DMLC_SLO_WINDOW_FAST_S``
    and ``slow`` = ``DMLC_SLO_WINDOW_SLOW_S``; knob-scaled down to
    sub-second in tests). Every tick publishes, per window:
    ``window_rate{name=,window=}`` (counter delta per second, summed
    across label sets) and ``window_quantile{name=,window=,q=}``
    (p50/p99 from the window's DELTA histogram buckets via
    :func:`quantile_from_buckets`, overflow clamped to the top bucket
    bound) — ordinary gauges, so every ``/metrics`` surface serves them
    with zero extra plumbing. An attached :class:`SloMonitor` (serving
    processes) is fed the same deltas.

    Use the module helpers :func:`start_windowed_view` /
    :func:`stop_windowed_view` (refcounted process singleton);
    :meth:`tick` is public so tests can drive the clock
    deterministically with ``now=``."""

    def __init__(self, windows: Optional[Dict[str, float]] = None):
        from dmlc_core_tpu.tracker.wire import env_int
        self.tick_s = max(env_int("DMLC_SLO_TICK_MS", 5000), 10) / 1000.0
        if windows is None:
            windows = {"fast": float(env_int("DMLC_SLO_WINDOW_FAST_S",
                                             300)),
                       "slow": float(env_int("DMLC_SLO_WINDOW_SLOW_S",
                                             3600))}
        self.windows = dict(windows)
        self.slo: Optional[SloMonitor] = None
        self._snaps: List[tuple] = []   # (t, counters, hists)
        self._mu = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- window math --------------------------------------------------------
    def tick(self, now: Optional[float] = None) -> None:
        """Take one compact snapshot at ``now`` (default: the monotonic
        clock), prune history past the longest window, publish the
        window gauges, and feed the SLO monitor."""
        if now is None:
            now = time.monotonic()
        counters, hists = _compact_snapshot(snapshot())
        horizon = max(self.windows.values()) + 2 * self.tick_s
        with self._mu:
            self._snaps.append((now, counters, hists))
            while len(self._snaps) > 2 and self._snaps[1][0] < \
                    now - horizon:
                self._snaps.pop(0)
            deltas = self._deltas_locked(now)
        self._publish(deltas)
        if self.slo is not None:
            self.slo.evaluate(deltas)

    def _baseline_locked(self, now: float, seconds: float):
        base = None
        for rec in self._snaps:
            if rec[0] <= now - seconds:
                base = rec           # newest snap at/before window start
            else:
                break
        return base or self._snaps[0]

    def _deltas_locked(self, now: float) -> Dict[str, tuple]:
        out: Dict[str, tuple] = {}
        if len(self._snaps) < 2:
            return out
        cur_t, cur_c, cur_h = self._snaps[-1]
        for label, seconds in self.windows.items():
            base_t, base_c, base_h = self._baseline_locked(now, seconds)
            elapsed = cur_t - base_t
            if elapsed <= 0:
                continue
            dcounters = {k: v - base_c.get(k, 0.0)
                         for k, v in cur_c.items()}
            dhists = {}
            for k, (c, s, b) in cur_h.items():
                bc, bs, bb = base_h.get(k, (0, 0.0, (0,) * len(b)))
                dhists[k] = (c - bc, s - bs,
                             tuple(x - y for x, y in zip(b, bb)))
            out[label] = (elapsed, dcounters, dhists)
        return out

    def deltas(self) -> Dict[str, tuple]:
        """This instant's per-window ``(elapsed_s, dcounters, dhists)``
        map (the same structure :meth:`tick` publishes from) — the raw
        material for tests and ad-hoc window math."""
        with self._mu:
            return self._deltas_locked(time.monotonic())

    def _publish(self, deltas: Dict[str, tuple]) -> None:
        top = float(1 << HIST_BUCKETS)  # overflow clamp: top bucket bound
        for label, (elapsed, dcounters, dhists) in deltas.items():
            rates: Dict[str, float] = {}
            for (name, _labels), v in dcounters.items():
                rates[name] = rates.get(name, 0.0) + max(v, 0.0)
            for name, total in rates.items():
                gauge("window_rate",
                      {"name": name, "window": label}).set(
                          round(total / elapsed, 4))
            per_name: Dict[str, tuple] = {}
            for (name, _labels), (dc, _ds, db) in dhists.items():
                pc, pb = per_name.get(
                    name, (0, (0,) * (HIST_BUCKETS + 1)))
                per_name[name] = (pc + max(dc, 0),
                                  tuple(x + max(y, 0)
                                        for x, y in zip(pb, db)))
            for name, (dc, db) in per_name.items():
                if dc <= 0:
                    continue
                for q in (0.5, 0.99):
                    val = quantile_from_buckets(list(db), dc, q)
                    gauge("window_quantile",
                          {"name": name, "window": label,
                           "q": str(q)}).set(min(val, top))

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "WindowedView":
        """Start the ticker thread (idempotent)."""
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="windowed-view")
            self._thread.start()
        return self

    def stop(self) -> None:
        """Stop the ticker thread (idempotent, joins briefly)."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.tick_s):
            try:
                self.tick()
            except Exception:
                pass  # a broken tick must not kill the view


_view_lock = threading.Lock()
_view: Optional[WindowedView] = None
_view_refs = 0


def start_windowed_view(slo: bool = False) -> WindowedView:
    """Start (or ref) the process :class:`WindowedView` singleton; with
    ``slo=True`` also attach the :class:`SloMonitor` (serving processes
    want the burn monitors, a tracker just wants the window series).
    Pair every call with :func:`stop_windowed_view`."""
    global _view, _view_refs
    with _view_lock:
        if _view is None:
            _view = WindowedView().start()
        if slo and _view.slo is None:
            _view.slo = SloMonitor()
        _view_refs += 1
        return _view


def stop_windowed_view(force: bool = False) -> None:
    """Drop one reference on the process view; the last drop (or
    ``force=True``, used by :func:`reset` for test isolation) stops the
    ticker and clears the singleton."""
    global _view, _view_refs
    with _view_lock:
        if _view is None:
            _view_refs = 0
            return
        _view_refs = 0 if force else max(_view_refs - 1, 0)
        if _view_refs == 0:
            v, _view = _view, None
        else:
            return
    v.stop()


def windowed_view() -> Optional[WindowedView]:
    """The live process :class:`WindowedView`, or None when no component
    has started one."""
    return _view


def slo_page_active() -> bool:
    """Whether the process SLO monitor is currently paging — the burn
    signal the serving admission gate and ``/readyz`` read."""
    v = _view
    return v is not None and v.slo is not None and v.slo.paging


# ---------------------------------------------------------------------------
# Host resource sampler (doc/benchmarking.md): the evidence half of every
# harness-bound verdict.  "host swings +/-40%" stops being folklore when
# every bench lane carries the CPU/RSS/page-cache/net/disk envelope it ran
# under — extras.host_resources in bench.py, per-lane via section().
# ---------------------------------------------------------------------------
class HostResourceSampler:
    """Lightweight /proc-based host sampler for bench lanes.

    A daemon thread samples per-core CPU jiffies (``/proc/stat``), this
    process's RSS (``/proc/self/statm``), the host page cache
    (``/proc/meminfo`` Cached), and cumulative network/disk bytes
    (``/proc/net/dev``, ``/proc/diskstats``) every ``interval_s``.
    :meth:`summary` reduces any time window to an envelope — mean/max
    per-core busy fraction, peak RSS, byte deltas — and
    :meth:`section` names a window after the lane that ran inside it,
    so a remote-lane verdict can say *which* cores were saturated
    (client parse vs origin serve) instead of guessing.

    Degrades to ``{"unavailable": reason}`` summaries on hosts without
    /proc.  Overhead: one thread, a handful of small file reads per
    tick — nothing on the measured path.
    """

    def __init__(self, interval_s: float = 0.25):
        self.interval_s = max(0.05, float(interval_s))
        self.samples: List[dict] = []   # append-only; GIL-safe reads
        self.sections: Dict[str, dict] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._page = os.sysconf("SC_PAGESIZE") if hasattr(
            os, "sysconf") else 4096
        self._tick = os.sysconf("SC_CLK_TCK") if hasattr(
            os, "sysconf") else 100
        self._err: Optional[str] = None
        # label -> [pids]: per-process CPU attribution (sandboxed /proc
        # implementations zero the aggregate per-core jiffies while
        # per-pid clocks still tick — and the remote-lane verdict needs
        # the client-vs-origin CPU split either way)
        self._watch: Dict[str, List[int]] = {"self": [os.getpid()]}
        self._pid_last: Dict[int, int] = {}

    def watch(self, label: str, *pids: int) -> None:
        """Attribute the CPU of ``pids`` (e.g. a rig origin's workers, a
        client subprocess) to ``label`` in every later summary."""
        self._watch.setdefault(label, []).extend(int(p) for p in pids)

    # -- raw readers (each guarded: a missing file disables, not crashes) --
    def _read_cpu(self):
        out = []
        with open("/proc/stat") as f:
            for line in f:
                if not line.startswith("cpu") or line.startswith("cpu "):
                    continue
                parts = line.split()
                vals = [int(x) for x in parts[1:11]]
                idle = vals[3] + (vals[4] if len(vals) > 4 else 0)
                out.append((sum(vals) - idle, sum(vals)))
        return out

    def _read_rss(self):
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * self._page

    def _read_cached(self):
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("Cached:"):
                    return int(line.split()[1]) * 1024
        return 0

    def _read_net(self):
        total = 0
        with open("/proc/net/dev") as f:
            for line in f.readlines()[2:]:
                _, _, rest = line.partition(":")
                if not rest:
                    continue
                v = rest.split()
                total += int(v[0]) + int(v[8])  # rx + tx bytes
        return total

    # whole PHYSICAL devices only: partitions (sda1, nvme0n1p1) would
    # double-count their disk, and stacked devices (dm-*, md*) would
    # double-count their member disks
    _DISK_RE = re.compile(
        r"^(?:sd[a-z]+|vd[a-z]+|xvd[a-z]+|hd[a-z]+|nvme\d+n\d+"
        r"|mmcblk\d+)$")

    def _read_disk(self):
        total = 0
        with open("/proc/diskstats") as f:
            for line in f:
                v = line.split()
                if len(v) < 14:
                    continue
                if not self._DISK_RE.match(v[2]):
                    continue
                total += (int(v[5]) + int(v[9])) * 512  # sectors r+w
        return total

    def _read_pid_cpu(self, pid: int) -> int:
        # utime+stime jiffies; field 2 (comm) may contain spaces — split
        # after the closing paren
        with open(f"/proc/{pid}/stat") as f:
            rest = f.read().rsplit(")", 1)[1].split()
        return int(rest[11]) + int(rest[12])

    def _sample(self) -> dict:
        s = {"t": time.monotonic()}
        s["cpu"] = self._read_cpu()
        s["rss"] = self._read_rss()
        s["cached"] = self._read_cached()
        pids = {}
        for label, plist in list(self._watch.items()):
            total = 0
            for p in plist:
                try:
                    v = self._read_pid_cpu(p)
                    self._pid_last[p] = v
                except (OSError, IndexError, ValueError):
                    # pid exited: charge its last-seen cumulative so the
                    # label's total never drops mid-window
                    v = self._pid_last.get(p, 0)
                total += v
            pids[label] = total
        s["pids"] = pids
        try:
            s["net"] = self._read_net()
        except OSError:
            s["net"] = 0
        try:
            s["disk"] = self._read_disk()
        except OSError:
            s["disk"] = 0
        return s

    def _loop(self):
        cpu_gauge = gauge("host_cpu_busy_frac")
        rss_gauge = gauge("host_rss_bytes")
        prev = None
        while not self._stop.is_set():
            try:
                s = self._sample()
            except OSError as e:  # /proc went away: disable, don't spin
                self._err = str(e)
                return
            self.samples.append(s)
            if prev is not None:
                db = sum(b for b, _ in s["cpu"]) - sum(
                    b for b, _ in prev["cpu"])
                dt = sum(t for _, t in s["cpu"]) - sum(
                    t for _, t in prev["cpu"])
                if dt > 0:
                    cpu_gauge.set(round(db / dt, 4))
            rss_gauge.set(s["rss"])
            prev = s
            self._stop.wait(self.interval_s)

    def start(self) -> "HostResourceSampler":
        """Take a first sample and start the sampling thread (no-op off
        Linux: the first failed /proc read records the reason and every
        summary reports ``unavailable``)."""
        try:
            self.samples.append(self._sample())
        except OSError as e:
            self._err = str(e)
            return self
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="host-resource-sampler")
        self._thread.start()
        return self

    def stop(self) -> dict:
        """Stop sampling (one final sample) and return the whole-run
        summary."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            try:
                self.samples.append(self._sample())
            except OSError:
                pass
        return self.summary()

    def summary(self, t0: Optional[float] = None,
                t1: Optional[float] = None) -> dict:
        """Reduce the samples in ``[t0, t1]`` (monotonic; default: all)
        to the envelope dict bench lanes record."""
        if self._err is not None:
            return {"unavailable": self._err}
        window = [s for s in list(self.samples)
                  if (t0 is None or s["t"] >= t0)
                  and (t1 is None or s["t"] <= t1)]
        if len(window) < 2:
            return {"unavailable": "fewer than 2 samples in window"}
        a, b = window[0], window[-1]
        wall = b["t"] - a["t"]
        ncores = max(len(a["cpu"]), 1)
        per_core = []
        for (b0, t0_), (b1, t1_) in zip(a["cpu"], b["cpu"]):
            dt = t1_ - t0_
            per_core.append(round((b1 - b0) / dt, 4) if dt > 0 else 0.0)
        # peak = busiest consecutive interval (overall, all cores)
        peak = 0.0
        for p, s in zip(window, window[1:]):
            db = sum(x for x, _ in s["cpu"]) - sum(x for x, _ in p["cpu"])
            dt = sum(x for _, x in s["cpu"]) - sum(
                x for _, x in p["cpu"])
            if dt > 0:
                peak = max(peak, db / dt)
        # watched-process CPU seconds over the window
        proc_cpu = {}
        for label in b.get("pids", {}):
            d = b["pids"].get(label, 0) - a.get("pids", {}).get(label, 0)
            proc_cpu[label] = round(max(d, 0) / self._tick, 3)
        out = {
            "wall_s": round(wall, 3),
            "samples": len(window),
            "cpu_source": "stat",
            "cpu_busy_frac": round(sum(per_core) / max(len(per_core), 1),
                                   4),
            "cpu_busy_frac_peak": round(peak, 4),
            "cpu_per_core": per_core,
            "ncores": ncores,
            "proc_cpu_s": proc_cpu,
            "rss_max_bytes": max(s["rss"] for s in window),
            "page_cache_delta_bytes": b["cached"] - a["cached"],
            "net_bytes": b["net"] - a["net"],
            "net_bytes_per_sec": round((b["net"] - a["net"]) / wall, 1)
            if wall > 0 else 0.0,
            "disk_bytes": b["disk"] - a["disk"],
        }
        total_jiffies = (sum(t for _, t in b["cpu"])
                         - sum(t for _, t in a["cpu"]))
        if total_jiffies <= 0 and wall > 0:
            # sandboxed /proc: the aggregate per-core clocks are zeroed
            # while per-pid clocks tick — derive the busy fraction from
            # the watched processes instead of reporting a false idle
            out["cpu_source"] = "pids"
            out["cpu_busy_frac"] = round(
                min(sum(proc_cpu.values()) / (wall * ncores), 1.0), 4)
            out.pop("cpu_per_core")
            out.pop("cpu_busy_frac_peak")
        return out

    def section(self, name: str):
        """Context manager: summarize the samples taken while the body
        ran and stash the envelope under ``sections[name]``."""
        sampler = self

        class _Section:
            def __enter__(self):
                self.t0 = time.monotonic()
                return sampler

            def __exit__(self, *exc):
                sampler.sections[name] = sampler.summary(
                    self.t0, time.monotonic())
                return False

        return _Section()
