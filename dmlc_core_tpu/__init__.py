"""dmlc_core_tpu — a TPU-native rebuild of the dmlc-core backbone library.

The reference (tpboudreau/dmlc-core) is the common backbone of the DMLC
ecosystem: a URI-dispatched stream/filesystem layer, distributed record-aligned
input splitting, sparse text parsers (libsvm/csv/libfm) + RecordIO, threaded
prefetch pipelines, and parameter/registry/config/serialization infrastructure,
plus a Python distributed-job tracker (see /root/reference and SURVEY.md).

This package re-designs those capabilities TPU-first:

- A **C++ native core** (``cpp/``) implements the hot host-side path — streams,
  filesystems, record-aligned InputSplit partitioning, RecordIO, and the
  multithreaded libsvm/csv/libfm parsers — exposed through a C ABI bound with
  ctypes (``dmlc_core_tpu.io.native``).
- The **device bridge** (``dmlc_core_tpu.tpu``) lands parsed row blocks in HBM
  as sharded ``jax.Array``s with static bucketed shapes, double-buffering
  host parsing against XLA compute (the ThreadedIter contract of
  reference ``include/dmlc/threadediter.h`` carried across the GIL).
- The **parallel layer** (``dmlc_core_tpu.parallel``) replaces the socket-based
  Rabit tree/ring allreduce brokering (reference ``tracker/dmlc_tracker/
  tracker.py:185-252``) with XLA collectives over ICI/DCN under
  ``jax.sharding.Mesh``; the rendezvous role maps to
  ``jax.distributed.initialize``.
- The **tracker** (``dmlc_core_tpu.tracker``) keeps the ``dmlc-submit``
  launcher surface (local/ssh/mpi/sge/slurm cluster backends and the
  rabit-compatible rendezvous wire protocol) and adds ``cluster=tpu-pod``.
"""

__version__ = "0.1.0"

from dmlc_core_tpu.base import DMLCError, check, check_eq, get_env, set_env
from dmlc_core_tpu.params import Parameter, field, ParamError
from dmlc_core_tpu.registry import Registry

__all__ = [
    "DMLCError",
    "check",
    "check_eq",
    "get_env",
    "set_env",
    "Parameter",
    "field",
    "ParamError",
    "Registry",
    "__version__",
]
