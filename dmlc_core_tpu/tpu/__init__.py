"""Device bridge: HBM-resident sharded batches + mesh/sharding helpers."""

from dmlc_core_tpu.tpu.device_iter import (DenseBatch,  # noqa: F401
                                           DenseRecHostBatcher,
                                           DeviceRowBlockIter,
                                           ElasticDeviceRowBlockIter,
                                           HostBatcher,
                                           NativeHostBatcher, PaddedBatch)
from dmlc_core_tpu.tpu.sharding import (batch_sharding,  # noqa: F401
                                        data_mesh, host_data_mesh,
                                        local_device_count,
                                        process_part, replicated_sharding)
