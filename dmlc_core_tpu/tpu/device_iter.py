"""Device-resident row-block iteration: the heart of the TPU-native design.

The reference pipeline ends at host CSR views (RowBlockIter, data.h:267);
consumers then copy into their own matrices. Here the pipeline *ends in HBM*:

  native parse threads → PaddedBatch (static shapes, numpy, pinned layout)
        → background staging thread (double buffer)
        → jax.device_put under a NamedSharding  → sharded jax.Array batch

Static-shape strategy (XLA compiles one program per shape — SURVEY §7 hard
part 1 "ragged → device"):
- rows per batch is fixed (`batch_rows`); the final partial batch is padded
  with zero-weight rows, so row count never varies.
- nnz is bucketed to the next power of two of the batch's true nnz (floor
  `min_nnz_bucket`), so the number of distinct compiled shapes is
  O(log max_nnz).
- CSR offsets become per-nonzero `row` segment ids (int32, TPU-friendly);
  padding nonzeros point at row == rows_per_shard, a sacrificial segment
  sliced off by the ops in dmlc_core_tpu.ops.sparse.

Sharding strategy: arrays carry a leading device axis [D, ...] sharded over
the mesh "data" axis; shard d holds rows [d*R, (d+1)*R) of the batch with
*local* row ids — so segment ops never cross shard boundaries and DP
gradients reduce with one psum (SURVEY §2.5).

The double buffer is the ThreadedIter contract (threadediter.h:77-279)
carried across the GIL: ctypes releases the GIL during native parsing, so the
staging thread overlaps parse+pad with XLA compute on the main thread.
"""

from __future__ import annotations

import contextlib
import json
import os
import queue
import re
import threading
import time
import weakref
from dataclasses import dataclass
from typing import Any, Dict, Iterator, Optional

import numpy as np

import jax
import jax.numpy as jnp

from dmlc_core_tpu import telemetry
from dmlc_core_tpu.base import DMLCError
from dmlc_core_tpu.io.native import (NativeBatcher, NativeCsrRecBatcher,
                                     NativeDenseRecBatcher, NativeParser,
                                     _bf16_dtype)
from dmlc_core_tpu.tpu.sharding import batch_sharding
from dmlc_core_tpu.tracker.wire import TrackerAbortedError, env_int

# device-lane metric objects resolved ONCE (the registry contract:
# resolve, keep the pointer — per-batch re-resolution would take the
# registry lock on the staging/transfer threads); lazy so importing this
# module registers nothing
_lane_metrics = None


def _get_lane_metrics():
    global _lane_metrics
    if _lane_metrics is None:
        _lane_metrics = {
            "transfer_us": telemetry.histogram("device_transfer_us"),
            "submit_us": telemetry.histogram("device_put_submit_us"),
            "block_us": telemetry.histogram("device_put_block_us"),
            "stage_us": telemetry.histogram("device_stage_us"),
            "wait_us": telemetry.histogram("device_wait_us"),
            "batches": telemetry.counter("device_batches_total"),
            "bytes": telemetry.counter("device_transfer_bytes_total"),
            "failures": telemetry.counter("device_put_failures_total"),
            "host_q": telemetry.gauge("device_host_q_depth"),
            "ready_q": telemetry.gauge("device_ready_q_depth"),
            "shapes": telemetry.gauge("device_distinct_shapes"),
            "zc_batches": telemetry.counter("device_zero_copy_batches_total"),
            "recycle_skip": telemetry.gauge("device_recycle_skipped"),
        }
    return _lane_metrics


# -- compile-churn telemetry -------------------------------------------------
# Process-wide shape census: the jit cache is keyed by the batch tree's
# structure + leaf shapes/dtypes, so the FIRST sight of a key here is the
# batch that makes every jitted consumer re-trace. Bucket-policy
# regressions (min_nnz_bucket too small, a layout flip mid-run) surface
# as a growing device_compile_events_total{shape=} trail instead of
# silent re-tracing.
_shape_lock = threading.Lock()
_shapes_seen: set = set()


def _batch_shape_key(batch) -> str:
    """Deterministic census key for one host/device batch: every leaf's
    name + shape (+ the dense dtype, which changes the compiled program).
    Matches jit-cache granularity for the batch input."""
    parts = [f"{k}{tuple(v.shape)}" for k, v in sorted(batch.tree().items())]
    if isinstance(batch, DenseBatch):
        parts.append(f"x:{np.dtype(batch.x.dtype).name}")
    return ",".join(parts)


# the labeled compile-event trail stops growing the registry past this
# many distinct shapes (further firsts fold into shape="other"): the
# pathological churn this metric exists to DETECT would otherwise mint a
# full leaf-names+shapes label per batch forever, bloating every
# snapshot, rank_export frame, and /metrics scrape. The
# device_distinct_shapes gauge stays exact regardless.
_SHAPE_LABEL_CAP = 64


def _note_shape(batch) -> None:
    key = _batch_shape_key(batch)
    with _shape_lock:
        new = key not in _shapes_seen
        if new:
            _shapes_seen.add(key)
        n = len(_shapes_seen)
    if new:
        label = key if n <= _SHAPE_LABEL_CAP else "other"
        telemetry.counter("device_compile_events_total",
                          {"shape": label}).inc()
        telemetry.emit_event("device-shape", shape=label, distinct=n)
    _get_lane_metrics()["shapes"].set(n)


def _reset_shape_census() -> None:
    """Forget every seen shape (tests; the real census is process-wide
    like the jit cache it mirrors)."""
    with _shape_lock:
        _shapes_seen.clear()


_monitor_installed = False


def _install_compile_monitor() -> None:
    """Best-effort jax.monitoring hook: XLA compilation events land in
    the telemetry plane (device_jit_compiles_total / device_compile_us)
    when this jax exposes duration listeners; the shape census above is
    the portable fallback either way. Installed once per process, never
    raises — observability must not sink the lane."""
    global _monitor_installed
    if _monitor_installed:
        return
    _monitor_installed = True
    try:
        from jax import monitoring as _mon
        compiles = telemetry.counter("device_jit_compiles_total")
        compile_us = telemetry.histogram("device_compile_us")

        def _on_duration(event, duration, **_kw):
            # jax emits several phases per compilation (jaxpr trace,
            # mlir lower, backend compile) — every phase's duration
            # lands in the histogram, but only the backend_compile
            # event counts as ONE compilation
            if "compil" in event:
                compile_us.observe(duration * 1e6)
                if "backend_compile" in event:
                    compiles.inc()

        _mon.register_event_duration_secs_listener(_on_duration)
    except Exception:
        pass


@contextlib.contextmanager
def jax_profiler_capture():
    """Optional XLA-timeline capture, wall-clock-anchored to our
    Chrome-trace export: with ``DMLC_JAX_PROFILE=<dir>`` set, wraps the
    body in ``jax.profiler.start_trace/stop_trace`` and writes
    ``<dir>/dmlc_anchor_<pid>.json`` holding this process's (wall,
    monotonic) clock-anchor pairs at start and stop — the same anchors
    ``telemetry.trace_json()`` shifts by, so the XLA timeline and the
    ``/trace`` span timeline line up on one wall clock. Yields True when
    a capture is running, False otherwise (env unset, or the profiler
    refused — profiling must never sink the lane; every failure is
    swallowed)."""
    out_dir = os.environ.get("DMLC_JAX_PROFILE")
    if not out_dir:
        yield False
        return
    anchors = {"pid": os.getpid(), "start": telemetry.clock_anchor()}
    started = False
    try:
        os.makedirs(out_dir, exist_ok=True)
        jax.profiler.start_trace(out_dir)
        started = True
    except Exception:
        pass
    try:
        yield started
    finally:
        if started:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
        anchors["stop"] = telemetry.clock_anchor()
        try:
            path = os.path.join(out_dir,
                                f"dmlc_anchor_{os.getpid()}.json")
            with open(path, "w") as f:
                json.dump(anchors, f)
            telemetry.emit_event("jax-profile", dir=out_dir,
                                 started=started)
        except Exception:
            pass


def _dense_dtype_of(d) -> np.dtype:
    """Normalize the dense x dtype: float32 or bfloat16 (the MXU dtypes the
    native FillDense can emit; batcher.h x_dtype)."""
    if isinstance(d, str) and d in ("bf16", "bfloat16"):
        return _bf16_dtype()
    dt = np.dtype(d)
    if dt != np.dtype(np.float32) and dt != _bf16_dtype():
        raise DMLCError(
            f"dense_dtype must be float32 or bfloat16, got {dt}")
    return dt

__all__ = ["PaddedBatch", "DenseBatch", "DeviceRowBlockIter", "HostBatcher",
           "NativeHostBatcher", "DenseRecHostBatcher", "CsrRecHostBatcher",
           "unpack_tree", "unpack_shard", "match_placement_rules",
           "jax_profiler_capture"]


@dataclass
class PaddedBatch:
    """Static-shape CSR batch; named arrays lead with the device axis D.

    row/col/val: [D, NNZ]  per-nonzero segment id (local), column, value
    label/weight: [D, R]   weight 0 marks padding rows
    nrows: [D]             true row count per shard
    qid: [D, R] int32      optional query/group ids (ranking); -1 on padding
                           rows and rows from qid-less blocks (sentinel —
                           cannot collide with a real qid:0)
    field: [D, NNZ] int32  optional per-nonzero field ids (FM/FFM), 0 on pad

    qid/field continue the reference RowBlock's optional columns
    (data.h:174-236) into the device layout.

    Packed transfer layout (native batchers): `big` [D, Kb, NNZ] int32
    stacks row/col/val(f32 bits)[/field] per shard and `aux` [D, K, R]
    int32 stacks label(f32 bits)/weight(f32 bits)[/qid]/nrows-plane per
    shard, so a batch crosses host->HBM in TWO transfers instead of one
    RPC per leaf — on high-latency links the per-transfer dispatch, not
    bandwidth, was the recd/rec-lane ceiling (BENCH_r03). The packs are
    SHARD-MAJOR (device axis leads): under a NamedSharding every shard's
    bytes are one contiguous leading-axis slice of the host buffer, which
    is what lets the zero-copy device_put path hand each device its slab
    without a host gather. With ``csr_val_dtype="bf16"`` values travel as
    a separate bfloat16 ``val16`` leaf [D, NNZ] (half the value bytes;
    the int32 pack drops its val plane). Host-side the named fields are
    zero-copy views into the packs; device-side batches carry only the
    packs and consumers unpack INSIDE jit (unpack_shard/unpack_tree)."""
    row: Any = None
    col: Any = None
    val: Any = None
    label: Any = None
    weight: Any = None
    nrows: Any = None
    # host-side true row count (not part of the device tree; avoids a
    # device->host sync when consumers just need progress accounting)
    total_rows: int = 0
    qid: Any = None
    field: Any = None
    big: Any = None  # [D, Kb, NNZ] packed row/col[/val][/field]
    aux: Any = None  # [D, K, R] packed label/weight[/qid]/nrows
    val16: Any = None  # [D, NNZ] bfloat16 values (csr_val_dtype="bf16")

    @property
    def rows_per_shard(self) -> int:
        return self.aux.shape[2] if self.label is None else \
            self.label.shape[1]

    @property
    def nnz_bucket(self) -> int:
        return self.big.shape[2] if self.row is None else self.row.shape[1]

    def tree(self) -> Dict[str, Any]:
        """The batch as a flat dict pytree (the device_put / jit input):
        the packed leaves when packed, the named leaves otherwise."""
        if self.aux is not None:
            t = {"big": self.big, "aux": self.aux}
            if self.val16 is not None:
                t["val"] = self.val16
            return t
        t = {"row": self.row, "col": self.col, "val": self.val,
             "label": self.label, "weight": self.weight,
             "nrows": self.nrows}
        if self.qid is not None:
            t["qid"] = self.qid
        if self.field is not None:
            t["field"] = self.field
        return t


@dataclass
class DenseBatch:
    """Dense device layout for low-dimensional data (auto-chosen when
    max_index is small): x is [D, R, F] — downstream matmuls tile straight
    onto the MXU, and host->HBM transfer drops from 12 B/nnz (CSR triple) to
    4 B/value (or 2 with bfloat16). Missing entries are 0 (the reference's
    CSR semantics for absent features in a linear model).

    `aux` packs label/weight[/qid]/nrows as in PaddedBatch: a batch is TWO
    host->HBM transfers (x + aux) instead of 4-5."""
    x: Any = None
    label: Any = None
    weight: Any = None
    nrows: Any = None
    total_rows: int = 0
    qid: Any = None  # [D, R] int32 group ids (field has no dense layout)
    aux: Any = None  # [D, K, R] packed label/weight[/qid]/nrows

    @property
    def rows_per_shard(self) -> int:
        return self.aux.shape[2] if self.label is None else \
            self.label.shape[1]

    @property
    def num_features(self) -> int:
        return self.x.shape[2]

    def tree(self) -> Dict[str, Any]:
        """The batch as a flat dict pytree (the device_put / jit input):
        the two packed leaves when packed, the named leaves otherwise."""
        if self.aux is not None:
            return {"x": self.x, "aux": self.aux}
        t = {"x": self.x, "label": self.label, "weight": self.weight,
             "nrows": self.nrows}
        if self.qid is not None:
            t["qid"] = self.qid
        return t


# -- packed-batch helpers ----------------------------------------------------
# Shard-major packs (device axis LEADS): aux [D, K, R], big [D, Kb, NNZ].
# Per-shard plane order, aux: 0=label (f32 bits), 1=weight (f32 bits),
# [2=qid], last=nrows plane (entry [d, -1, 0] holds shard d's true row
# count). big: 0=row, 1=col, [2=val (f32 bits) unless a separate bf16
# `val` leaf travels], [last=field]. Both are int32 containers; float
# planes travel as raw bits and are bitcast back on device (a
# dtype-preserving reinterpretation, not a cast). Shard-major means shard
# d's bytes are the contiguous slice pack[d] — the layout the zero-copy
# sharded device_put path requires.

def _aligned_empty(shape, dtype, align: int = 64) -> np.ndarray:
    """C-contiguous uninitialised array whose base address is `align`-byte
    aligned. np.empty only guarantees 16; XLA:CPU aliases (rather than
    copies) a host buffer on device_put only at 64-byte alignment, so
    every staging buffer the zero-copy path may hand to device_put is
    allocated through here."""
    dtype = np.dtype(dtype)
    nbytes = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
    raw = np.empty(nbytes + align, np.uint8)
    off = (-raw.ctypes.data) % align
    return raw[off:off + nbytes].view(dtype).reshape(shape)


def _view_aux(aux: np.ndarray):
    """Named [D, R] views over a shard-major [D, K, R] aux pack (the
    native fills write the pack directly; these are zero-copy strided
    float32/int32 reinterpretations for host-side consumers)."""
    D, K, R = aux.shape
    label = aux[:, 0].view(np.float32)
    weight = aux[:, 1].view(np.float32)
    qid = aux[:, 2] if K == 4 else None
    return aux, label, weight, qid


def _view_big(big: np.ndarray, has_val: bool = True):
    """Named [D, NNZ] row/col[/val][/field] views over a shard-major
    [D, Kb, NNZ] pack (val viewed float32; pass has_val=False when values
    travel as a separate bf16 leaf and the pack carries no val plane)."""
    D, Kb, bucket = big.shape
    row = big[:, 0]
    col = big[:, 1]
    if has_val:
        val = big[:, 2].view(np.float32)
        field = big[:, 3] if Kb == 4 else None
    else:
        val = None
        field = big[:, 2] if Kb == 3 else None
    return row, col, val, field


def _finish_aux(aux, nrows) -> None:
    """Mirror the [D] nrows vector into the aux nrows plane ([d, -1, 0])."""
    aux[:, -1] = 0
    aux[:, -1, 0] = nrows


def _pack_aux(label, weight, qid, nrows, D: int, R: int, emit_qid: bool,
              aux=None):
    """Assemble an aux pack from already-built flat row arrays (the
    python-batcher path; the native batchers fill their aux views
    in-place instead). Reuses `aux` when its shape fits. Returns
    (aux, label_view, weight_view, qid_view) with views shaped [D, R]."""
    K = 4 if emit_qid else 3
    if aux is None or aux.shape != (D, K, R):
        aux = _aligned_empty((D, K, R), np.int32)
    _, label_v, weight_v, qid_v = _view_aux(aux)
    label_v[:] = np.asarray(label).reshape(D, R)
    weight_v[:] = np.asarray(weight).reshape(D, R)
    if qid_v is not None:
        qid_v[:] = np.asarray(qid).reshape(D, R)
    _finish_aux(aux, nrows)
    return aux, label_v, weight_v, qid_v


def _unpack(tree: Dict[str, Any], sel, nrows_of) -> Dict[str, Any]:
    """Shared aux/big plane decoding; `sel(pack, i)` slices plane i of a
    shard-major pack ([:, i] on full trees, [i] inside a shard_map body)
    and `nrows_of` extracts the nrows vector from the last aux plane."""
    if "aux" not in tree:
        return tree
    aux = tree["aux"]
    out = {}
    if "x" in tree:
        out["x"] = tree["x"]
    if "big" in tree:
        big = tree["big"]
        kb = big.shape[-2]
        out["row"] = sel(big, 0)
        out["col"] = sel(big, 1)
        if "val" in tree:  # separate bf16 value leaf; pack has no val plane
            out["val"] = tree["val"]
            if kb == 3:
                out["field"] = sel(big, 2)
        else:
            out["val"] = _bitcast_f32(sel(big, 2))
            if kb == 4:
                out["field"] = sel(big, 3)
    out["label"] = _bitcast_f32(sel(aux, 0))
    out["weight"] = _bitcast_f32(sel(aux, 1))
    if aux.shape[-2] == 4:
        out["qid"] = sel(aux, 2)
    out["nrows"] = nrows_of(sel(aux, aux.shape[-2] - 1))
    return out


def unpack_tree(tree: Dict[str, Any]) -> Dict[str, Any]:
    """Named leaves from a packed batch tree (device-axis-ful shapes:
    label/weight/qid [D, R], row/col/val/field [D, NNZ], nrows [D]).
    Identity for already-named trees. Usable under jit (bitcasts and
    slices only) and on host numpy."""
    return _unpack(tree, lambda a, i: a[:, i], lambda plane: plane[:, 0])


def unpack_shard(tree: Dict[str, Any]) -> Dict[str, Any]:
    """Named leaves from one shard of a packed tree (device axis already
    dropped: aux [K, R], big [Kb, NNZ], x [R, F]; nrows becomes a 0-d
    scalar — the SAME rank the named-tree lane yields after its v[0]
    device-axis slice, so _shard_loss implementations see one shape
    regardless of how the batch arrived). Identity for already-named
    trees. For use inside shard_map bodies."""
    return _unpack(tree, lambda a, i: a[i], lambda plane: plane[0])


def _bitcast_f32(a):
    if isinstance(a, np.ndarray):
        return a.view(np.float32)
    return jax.lax.bitcast_convert_type(a, jnp.float32)


def _next_pow2(n: int, floor: int) -> int:
    b = floor
    while b < n:
        b <<= 1
    return b


# -- spec-driven placement ---------------------------------------------------
# Leaf-name -> PartitionSpec rules, first match wins (the
# match_partition_rules idiom from t5x-style partitioning): the transfer
# thread derives every leaf's NamedSharding from this table instead of
# hard-coding per-leaf cases. Every batch leaf today is shard-major
# (device axis leads), so one catch-all leading-axis rule suffices; the
# table is the extension point for future non-leading layouts (add the
# specific rule ABOVE the catch-all).
_PLACEMENT_RULES = (
    (r".*", lambda axis: jax.sharding.PartitionSpec(axis)),
)


def match_placement_rules(mesh, keys, axis_name: str = "data"):
    """Per-leaf NamedSharding dict for batch-tree `keys`: each key takes
    the spec of the first _PLACEMENT_RULES regex that fully matches it."""
    out = {}
    for k in keys:
        for pat, spec_fn in _PLACEMENT_RULES:
            if re.fullmatch(pat, k):
                out[k] = jax.sharding.NamedSharding(mesh, spec_fn(axis_name))
                break
    return out


class _ZeroCopyIneligible(Exception):
    """A batch (or backend state) the zero-copy device_put path cannot
    serve; carries the fallback-counter reason label."""

    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason


def _tree_aliases_host(host_tree: Dict[str, Any],
                       dev_tree: Dict[str, Any]) -> bool:
    """Whether any device leaf's buffer lives inside its host leaf's
    memory span — i.e. device_put aliased instead of copied, so recycling
    the host buffer would corrupt live device data. Probes the actual
    buffer addresses (unsafe_buffer_pointer) instead of trusting backend
    names; anything unprobeable is treated as aliasing (recycling is an
    optimization, correctness must not depend on it)."""
    try:
        for k, h in host_tree.items():
            if not isinstance(h, np.ndarray):
                continue
            lo = h.ctypes.data
            hi = lo + h.nbytes
            d = dev_tree.get(k)
            for s in getattr(d, "addressable_shards", ()):
                p = s.data.unsafe_buffer_pointer()
                if lo <= p < hi:
                    return True
        return False
    except Exception:
        return True


class _HostBufferPool:
    """Shape-keyed free-list of host batch buffers shared by the batcher
    implementations: avoids per-batch allocate + page-fault churn on the
    staging thread. Buffers enter via put() only after the host->device
    copy has completed and only when device arrays cannot alias host
    memory (DeviceRowBlockIter's transfer-thread contract); bounded per
    key so idle memory stays small."""

    CAP = 4  # per shape key; covers the prefetch depth

    def __init__(self):
        self._pool: Dict[Any, list] = {}
        self._lock = threading.Lock()

    def pop(self, key):
        with self._lock:
            lst = self._pool.get(key)
            return lst.pop() if lst else None

    def put(self, key, arrs) -> None:
        with self._lock:
            lst = self._pool.setdefault(key, [])
            if len(lst) < self.CAP:
                lst.append(arrs)


class HostBatcher:
    """Accumulates native RowBlocks into fixed-row-count numpy batches.

    Splitting/merging is needed because native blocks have arbitrary sizes
    (one per parser worker per chunk) while the device wants `batch_rows`
    exactly."""

    def __init__(self, parser: NativeParser, batch_rows: int,
                 num_shards: int, min_nnz_bucket: int = 4096,
                 index64: bool = False, layout: str = "auto",
                 dense_max_features: int = 512, dense_dtype=np.float32):
        if batch_rows % num_shards != 0:
            raise DMLCError(
                f"batch_rows={batch_rows} must divide by shards={num_shards}")
        if layout not in ("auto", "csr", "dense"):
            raise DMLCError(f"unknown layout {layout!r}")
        self.parser = parser
        self.batch_rows = batch_rows
        self.num_shards = num_shards
        self.min_nnz_bucket = min_nnz_bucket
        self.layout = layout
        self.dense_max_features = dense_max_features
        self.dense_dtype = _dense_dtype_of(dense_dtype)
        self._num_features: Optional[int] = None  # fixed once dense chosen
        # leftover rows from the previous native block (numpy copies)
        self._pending: list = []  # (label, weight, lens, col, val, qid, fld)
        self._pending_rows = 0
        self._done = False
        self._has_qid = False    # sticky, like the layout choice
        self._has_field = False
        # plane presence pins on the first batch (static pytree structure
        # for jitted consumers; same contract as NativeHostBatcher)
        self._emit_qid: Optional[bool] = None
        self._emit_field: Optional[bool] = None
        # recycled big/aux packs (see _HostBufferPool contract)
        self._pool = _HostBufferPool()

    def recycle(self, batch) -> None:
        """Return a consumed host batch's packed buffers for reuse (same
        contract as NativeHostBatcher.recycle: only after the host->device
        copy has finished and only when device arrays cannot alias host
        memory). Every plane is fully rewritten on reuse, so dirty packs
        are safe."""
        if not isinstance(getattr(batch, "aux", None), np.ndarray):
            return
        if isinstance(batch, DenseBatch):
            if batch.x.dtype != self.dense_dtype:
                return
            self._pool.put(("dense", batch.x.shape[-1]),
                           (batch.x.reshape(self.batch_rows, -1),
                            batch.aux))
        else:
            self._pool.put(("csr", batch.big.shape[-1]),
                           (batch.big, batch.aux))

    def _block_to_parts(self, b) -> tuple:
        lens = np.diff(b.offset).astype(np.int32)
        # the device layout is int32; a feature id >= 2^31 would wrap
        # negative in the astype below and scatter to a wrong column —
        # refuse loudly instead (same contract as qid below; the native
        # batcher enforces this in PaddedBatcher::Accumulate). Reference
        # data.h:26-32 makes index width a first-class contract.
        if b.nnz:
            mx = int(getattr(b, "max_index", 0)) or int(b.index.max())
            if mx > np.iinfo(np.int32).max:
                raise DMLCError(
                    f"feature index {mx} exceeds the int32 device layout "
                    f"(max {np.iinfo(np.int32).max}); remap feature ids "
                    f"below 2^31 for the TPU batch layout")
        col = b.index.astype(np.int32, copy=True)
        val = (b.value.astype(np.float32, copy=True) if b.value is not None
               else np.ones(b.nnz, dtype=np.float32))
        label = b.label.astype(np.float32, copy=True)
        weight = (b.weight.astype(np.float32, copy=True)
                  if b.weight is not None
                  else np.ones(b.num_rows, dtype=np.float32))
        # qid/field stay None for blocks without them (no sentinel traffic
        # on the common qid/field-free path); sentinels materialize at batch
        # assembly only when the stream carries the column somewhere
        qid = fld = None
        if b.qid is not None:
            self._has_qid = True
            if b.qid.max(initial=0) > np.iinfo(np.int32).max:
                raise DMLCError(
                    f"qid {int(b.qid.max())} exceeds the int32 device "
                    f"layout")  # native path enforces the same (batcher.cc)
            qid = b.qid.astype(np.int32)
        if b.field is not None:
            self._has_field = True
            fld = b.field.astype(np.int32)
        return label, weight, lens, col, val, qid, fld

    def next_batch(self) -> Optional[PaddedBatch]:
        """Produce the next PaddedBatch of numpy arrays (None at end)."""
        while self._pending_rows < self.batch_rows and not self._done:
            b = self.parser.next_block()
            if b is None:
                self._done = True
                break
            self._pending.append(self._block_to_parts(b))
            self._pending_rows += len(self._pending[-1][0])
        if self._pending_rows == 0:
            return None

        if self._emit_qid is None:
            self._emit_qid, self._emit_field = self._has_qid, self._has_field
        elif (self._has_qid and not self._emit_qid) or (
                self._has_field and not self._emit_field):
            raise DMLCError(
                "qid/field column appeared mid-stream after the batch "
                "structure was pinned without it; order the inputs so the "
                "first batch carries the column")

        take = min(self.batch_rows, self._pending_rows)
        parts = []  # per-piece tuples, same layout as _pending entries
        got = 0

        def sl(arr, stop=None, start=None):
            if arr is None:
                return None
            return arr[start:] if start is not None else arr[:stop]

        while got < take:
            label, weight, lens, col, val, qid, fld = self._pending[0]
            n = len(label)
            if got + n <= take:
                self._pending.pop(0)
                parts.append((label, weight, lens, col, val, qid, fld))
                got += n
            else:
                keep = take - got
                nnz_keep = int(lens[:keep].sum())
                parts.append((label[:keep], weight[:keep], lens[:keep],
                              col[:nnz_keep], val[:nnz_keep], sl(qid, keep),
                              sl(fld, nnz_keep)))
                self._pending[0] = (label[keep:], weight[keep:], lens[keep:],
                                    col[nnz_keep:], val[nnz_keep:],
                                    sl(qid, start=keep),
                                    sl(fld, start=nnz_keep))
                got = take
        self._pending_rows -= take

        label, weight, lens, col, val = (
            np.concatenate([p[i] for p in parts]) for i in range(5))
        # sentinel backfill only when the stream carries the column at all
        qid = (np.concatenate(
            [p[5] if p[5] is not None else np.full(len(p[0]), -1, np.int32)
             for p in parts]) if self._emit_qid
            else np.empty(0, np.int32))
        fld = (np.concatenate(
            [p[6] if p[6] is not None else np.zeros(len(p[3]), np.int32)
             for p in parts]) if self._emit_field
            else np.empty(0, np.int32))

        D = self.num_shards
        R = self.batch_rows // D
        # pad rows to full batch (weight 0 ⇒ no gradient contribution)
        if take < self.batch_rows:
            pad = self.batch_rows - take
            label = np.concatenate([label, np.zeros(pad, np.float32)])
            weight = np.concatenate([weight, np.zeros(pad, np.float32)])
            lens = np.concatenate([lens, np.zeros(pad, np.int32)])
            if self._emit_qid:
                qid = np.concatenate([qid, np.full(pad, -1, np.int32)])

        if self.layout == "auto":
            # decide once, on the first batch: dense when the feature space
            # is small (the MXU path); sticky so device shapes stay static.
            # field-aware data always stays CSR (no dense field plane)
            max_idx = int(col.max()) if len(col) else 0
            self.layout = ("dense" if not self._emit_field
                           and max_idx + 1 <= self.dense_max_features
                           else "csr")
        if self.layout == "dense":
            if self._emit_field:
                raise DMLCError(
                    "field ids have no dense layout; pass layout='csr' for "
                    "field-aware (libfm) data")
            return self._emit_dense(take, label, weight, lens, col, val, qid)

        # split nnz by shard; bucket to the max shard nnz
        row_of = np.repeat(np.arange(self.batch_rows, dtype=np.int32), lens)
        shard_starts = np.concatenate(
            [[0], np.cumsum(lens.reshape(D, R).sum(axis=1))]).astype(np.int64)
        shard_nnz = np.diff(shard_starts)
        bucket = _next_pow2(int(shard_nnz.max()) if take else 1,
                            self.min_nnz_bucket)

        # assemble straight into the packed two-leaf layout (the same
        # big/aux contract the native batchers emit, so index64 batches
        # also cross host->HBM in two transfers); pooled packs are fully
        # rewritten below, so reuse needs no clearing beyond the fills
        Kb = 4 if self._emit_field else 3
        big = aux_buf = None
        pooled = self._pool.pop(("csr", bucket))
        if pooled is not None:
            big, aux_buf = pooled
            if big.shape[1] != Kb:
                big = None
        if big is None:
            big = _aligned_empty((D, Kb, bucket), np.int32)
        row, colp, valp, fldp = _view_big(big)
        row[:] = R  # R = padding segment
        colp[:] = 0
        valp[:] = 0.0
        if fldp is not None:
            fldp[:] = 0
        for d in range(D):
            lo, hi = shard_starts[d], shard_starts[d + 1]
            n = hi - lo
            row[d, :n] = row_of[lo:hi] - d * R  # local row ids
            colp[d, :n] = col[lo:hi]
            valp[d, :n] = val[lo:hi]
            if fldp is not None:
                fldp[d, :n] = fld[lo:hi]

        nrows = np.minimum(
            np.maximum(take - np.arange(D) * R, 0), R).astype(np.int32)
        aux, label_v, weight_v, qid_v = _pack_aux(
            label, weight, qid, nrows, D, R, self._emit_qid, aux=aux_buf)
        return PaddedBatch(
            row=row, col=colp, val=valp,
            label=label_v, weight=weight_v,
            nrows=nrows, total_rows=int(take),
            qid=qid_v, field=fldp, big=big, aux=aux)

    def _emit_dense(self, take, label, weight, lens, col, val, qid):
        D = self.num_shards
        R = self.batch_rows // D
        if self._num_features is None:
            self._num_features = int(col.max()) + 1 if len(col) else 1
        F = self._num_features
        mx = int(col.max()) + 1 if len(col) else 1
        if mx > F:
            raise DMLCError(
                f"dense layout fixed at {F} features but saw index {mx - 1}; "
                f"pass layout='csr' or a larger dense_max_features")
        x = aux_buf = None
        pooled = self._pool.pop(("dense", F))
        if pooled is not None:
            x, aux_buf = pooled
            x.fill(0)  # the scatter below only touches present entries
        if x is None:
            x = _aligned_empty((self.batch_rows, F), self.dense_dtype)
            x.fill(0)
        row_of = np.repeat(np.arange(self.batch_rows, dtype=np.int64), lens)
        x[row_of, col] = val
        nrows = np.minimum(
            np.maximum(take - np.arange(D) * R, 0), R).astype(np.int32)
        aux, label_v, weight_v, qid_v = _pack_aux(
            label, weight, qid, nrows, D, R, self._emit_qid, aux=aux_buf)
        return DenseBatch(
            x=x.reshape(D, R, F),
            label=label_v, weight=weight_v,
            nrows=nrows, total_rows=int(take),
            qid=qid_v, aux=aux)

    def reset(self) -> None:
        """Restart batching from the first row (new epoch)."""
        self.parser.before_first()
        self._pending.clear()
        self._pending_rows = 0
        self._done = False

    def set_epoch(self, epoch: int) -> bool:
        """Pin the shuffle permutation the next reset() samples (mid-epoch
        resume). False when the underlying split chain does not shuffle."""
        return self.parser.set_epoch(epoch)


class NativeHostBatcher:
    """HostBatcher drop-in backed by the C++ PaddedBatcher (cpp/src/batcher.h).

    The splitting/merging/padding that HostBatcher does with per-block numpy
    concatenation happens natively in one pass per batch: next_meta() stages
    a batch and reports its static shape, Python allocates the numpy arrays,
    and fill_* writes them with the GIL released. On a single host core this
    roughly halves the non-parse overhead of the ingest pipeline."""

    def __init__(self, uri: str, part: int = 0, npart: int = 1,
                 fmt: str = "auto", nthread: int = 0,
                 batch_rows: int = 65536, num_shards: int = 1,
                 min_nnz_bucket: int = 4096, layout: str = "auto",
                 dense_max_features: int = 512, dense_dtype=np.float32,
                 csr_val_dtype: str = "f32"):
        if batch_rows % num_shards != 0:
            raise DMLCError(
                f"batch_rows={batch_rows} must divide by shards={num_shards}")
        if layout not in ("auto", "csr", "dense"):
            raise DMLCError(f"unknown layout {layout!r}")
        if csr_val_dtype not in ("f32", "bf16"):
            raise DMLCError(f"unknown csr_val_dtype {csr_val_dtype!r} "
                            f"(expected 'f32' or 'bf16')")
        self._b = NativeBatcher(uri, part=part, npart=npart, fmt=fmt,
                                nthread=nthread, batch_rows=batch_rows,
                                num_shards=num_shards,
                                min_nnz_bucket=min_nnz_bucket)
        self.batch_rows = batch_rows
        self.num_shards = num_shards
        self.layout = layout
        self.dense_max_features = dense_max_features
        self.dense_dtype = _dense_dtype_of(dense_dtype)
        self._num_features: Optional[int] = None
        # bf16 CSR values travel as a separate [D, NNZ] bfloat16 leaf and
        # the int32 pack drops its val plane (the native fill converts
        # f32->bf16 round-to-nearest-even in the same pass — cpp/src/bf16.h)
        self._csr_bf16 = csr_val_dtype == "bf16"
        # plane presence pins on the first batch so the emitted pytree
        # structure (and therefore jitted consumers' traces) stays static
        self._emit_qid: Optional[bool] = None
        self._emit_field: Optional[bool] = None
        # recycled host buffers (see _HostBufferPool contract)
        self._pool = _HostBufferPool()

    def next_batch(self):
        """Produce the next static-shape batch of host numpy arrays (None at
        end); buffers come from the recycle pool when available."""
        meta = self._b.next_meta()
        if meta is None:
            return None
        take, bucket, max_index, has_qid, has_field = meta
        if self._emit_qid is None:
            self._emit_qid, self._emit_field = has_qid, has_field
        elif (has_qid and not self._emit_qid) or (
                has_field and not self._emit_field):
            raise DMLCError(
                "qid/field column appeared mid-stream after the batch "
                "structure was pinned without it; order the inputs so the "
                "first batch carries the column")
        has_qid, has_field = self._emit_qid, self._emit_field
        D = self.num_shards
        R = self.batch_rows // D
        if self.layout == "auto":
            # decide once, on the first batch; sticky so shapes stay static.
            # field ids have no dense representation, so field-aware data
            # always takes the CSR layout (batcher.h contract)
            self.layout = ("dense"
                           if not has_field
                           and max_index + 1 <= self.dense_max_features
                           else "csr")
        elif self.layout == "dense" and has_field:
            raise DMLCError(
                "field ids have no dense layout; pass layout='csr' for "
                "field-aware (libfm) data")
        if self.layout == "dense":
            if self._num_features is None:
                self._num_features = max(int(max_index) + 1, 1)
            F = self._num_features
            pooled = self._pool_pop(("dense", F))
            if pooled is not None:
                x, aux, nrows = pooled
            else:
                # the native fill writes float32 or bf16 storage directly
                # (batcher.h x_dtype) — no astype copy on this thread
                x = _aligned_empty((self.batch_rows, F), self.dense_dtype)
                aux = None
                nrows = np.empty(D, np.int32)
            if aux is None or aux.shape[1] != (4 if has_qid else 3):
                aux = _aligned_empty((D, 4 if has_qid else 3, R), np.int32)
            # one fused native pass writes x AND the aux pack (label/weight
            # [/qid]/nrows planes) — no per-plane fills or _finish_aux here
            self._b.fill_dense_packed(x, aux, nrows)
            _, label, weight, qid = _view_aux(aux)
            return DenseBatch(x=x.reshape(D, R, F),
                              label=label, weight=weight,
                              nrows=nrows, total_rows=int(take),
                              qid=qid, aux=aux)
        sep_val = self._csr_bf16
        Kb = 2 + (0 if sep_val else 1) + (1 if has_field else 0)
        pooled = self._pool_pop(("csr", bucket))
        if pooled is not None:
            big, val16, aux, nrows = pooled
        else:
            big, val16, aux = None, None, None
            nrows = np.empty(D, np.int32)
        if big is None or big.shape[1] != Kb:
            big = _aligned_empty((D, Kb, bucket), np.int32)
        if sep_val and (val16 is None or val16.shape != (D, bucket)):
            val16 = _aligned_empty((D, bucket), _bf16_dtype())
        if aux is None or aux.shape[1] != (4 if has_qid else 3):
            aux = _aligned_empty((D, 4 if has_qid else 3, R), np.int32)
        # one fused native pass assembles the whole shard-major batch
        self._b.fill_packed(big, aux, nrows, val=val16 if sep_val else None)
        row, col, val, field = _view_big(big, has_val=not sep_val)
        _, label, weight, qid = _view_aux(aux)
        return PaddedBatch(row=row, col=col,
                           val=val16 if sep_val else val,
                           label=label, weight=weight,
                           nrows=nrows, total_rows=int(take),
                           qid=qid, field=field, big=big, aux=aux,
                           val16=val16)

    # -- host-buffer recycling ---------------------------------------------
    def _pool_pop(self, key):
        return self._pool.pop(key)

    def recycle(self, batch) -> None:
        """Return a consumed host batch's buffers for reuse.

        Callers must guarantee the host->device copy has finished (e.g.
        block_until_ready on the device arrays) and that the device arrays
        no longer alias host memory. DeviceRowBlockIter enforces the
        latter by probing the first transferred batch's device buffer
        addresses against the host buffers (it no longer assumes which
        backends alias) and, when they overlap, DEFERRING the recycle
        behind weakrefs until the consumer drops the device batch
        (parking-lot overflow drops are counted in
        device_recycle_skipped)."""
        if getattr(batch, "aux", None) is None or \
                not isinstance(batch.aux, np.ndarray):
            return  # foreign/device batch; nothing to pool
        if isinstance(batch, DenseBatch):
            if batch.x.dtype != self.dense_dtype:
                return  # foreign buffer set; drop it
            key = ("dense", batch.x.shape[-1])
            arrs = (batch.x.reshape(self.batch_rows, -1), batch.aux,
                    batch.nrows)
        else:
            key = ("csr", batch.big.shape[-1])
            arrs = (batch.big, batch.val16, batch.aux, batch.nrows)
        self._pool.put(key, arrs)

    def reset(self) -> None:
        """Restart batching from the first row (new epoch); the recycle pool
        survives."""
        self._b.before_first()

    def set_epoch(self, epoch: int) -> bool:
        """Pin the shuffle permutation the next reset() samples (mid-epoch
        resume). False when the underlying split chain does not shuffle."""
        return self._b.set_epoch(epoch)

    def bytes_read(self) -> int:
        """Bytes consumed from the underlying source so far."""
        return self._b.bytes_read()

    def close(self) -> None:
        """Free the native batcher handle (idempotent)."""
        self._b.close()


class CsrRecHostBatcher:
    """Host batcher over the zero-rearrangement CSR lane (cpp/src/
    csr_rec.h): records store col/val/row-length planes in device layout,
    so next_batch() is bulk memcpy + row-id expansion straight into the
    packed big/aux buffers. The per-shard nnz bucket is STATIC for the
    epoch (the file's window table bounds it), so every batch compiles to
    one device shape. Emits the same PaddedBatch as the CSR text path."""

    def __init__(self, uri: str, part: int = 0, npart: int = 1,
                 batch_rows: int = 65536, num_shards: int = 1,
                 min_nnz_bucket: int = 4096):
        if batch_rows % num_shards != 0:
            raise DMLCError(
                f"batch_rows={batch_rows} must divide by shards="
                f"{num_shards}")
        self._b = NativeCsrRecBatcher(uri, part=part, npart=npart,
                                      batch_rows=batch_rows,
                                      num_shards=num_shards,
                                      min_nnz_bucket=min_nnz_bucket)
        self.batch_rows = batch_rows
        self.num_shards = num_shards
        self._meta = None
        self._pool = _HostBufferPool()

    def recycle(self, batch) -> None:
        """Return a consumed host batch's buffers for reuse (same contract
        as NativeHostBatcher.recycle)."""
        if not isinstance(batch, PaddedBatch) or \
                not isinstance(getattr(batch, "aux", None), np.ndarray):
            return
        self._pool.put(("crec", batch.big.shape[-1]),
                       (batch.big, batch.aux, batch.nrows))

    def next_batch(self) -> Optional[PaddedBatch]:
        """Next static-shape PaddedBatch of host numpy arrays (None at
        end); the fill is one GIL-released native pass."""
        if self._meta is None:
            self._meta = self._b.meta()
        bucket, _, has_qid, has_field = self._meta
        D = self.num_shards
        R = self.batch_rows // D
        pooled = self._pool.pop(("crec", bucket))
        if pooled is not None:
            big, aux, nrows = pooled
        else:
            big = _aligned_empty((D, 4 if has_field else 3, bucket),
                                 np.int32)
            aux = _aligned_empty((D, 4 if has_qid else 3, R), np.int32)
            nrows = np.empty(D, np.int32)
        # one fused native pass writes both shard-major packs
        take = self._b.fill_packed(big, aux, nrows)
        if take == 0:
            return None
        row, col, val, field = _view_big(big)
        _, label, weight, qid = _view_aux(aux)
        return PaddedBatch(row=row, col=col, val=val,
                           label=label, weight=weight,
                           nrows=nrows, total_rows=int(take),
                           qid=qid, field=field, big=big, aux=aux)

    def reset(self) -> None:
        """Restart from the first record (new epoch); the pool survives."""
        self._b.before_first()

    def set_epoch(self, epoch: int) -> bool:
        """Pin the shuffle permutation the next reset() samples."""
        return self._b.set_epoch(epoch)

    def bytes_read(self) -> int:
        """Record bytes consumed from the source so far."""
        return self._b.bytes_read()

    def close(self) -> None:
        """Free the native handle (idempotent)."""
        self._b.close()


class DenseRecHostBatcher:
    """Host batcher over the zero-parse dense lane (cpp/src/dense_rec.h):
    records store [rows, F] matrices in device layout, so next_batch() is
    record framing + bulk memcpy into (pooled) numpy buffers. Emits the
    same DenseBatch the dense text path produces — downstream consumers
    cannot tell the lanes apart."""

    def __init__(self, uri: str, part: int = 0, npart: int = 1,
                 batch_rows: int = 65536, num_shards: int = 1,
                 dense_dtype=np.float32):
        if batch_rows % num_shards != 0:
            raise DMLCError(
                f"batch_rows={batch_rows} must divide by shards="
                f"{num_shards}")
        self._b = NativeDenseRecBatcher(uri, part=part, npart=npart,
                                        batch_rows=batch_rows,
                                        num_shards=num_shards)
        self.batch_rows = batch_rows
        self.num_shards = num_shards
        self.dense_dtype = _dense_dtype_of(dense_dtype)
        self._F: Optional[int] = None
        self._pool = _HostBufferPool()

    def recycle(self, batch) -> None:
        """Return a consumed host batch's buffers for reuse (same contract
        as NativeHostBatcher.recycle: only after the host->device copy has
        finished and only when device arrays cannot alias host memory)."""
        if not isinstance(batch, DenseBatch) or \
                not isinstance(getattr(batch, "aux", None), np.ndarray) or \
                batch.x.dtype != self.dense_dtype:
            return
        self._pool.put(("drec", batch.x.shape[-1]),
                       (batch.x.reshape(self.batch_rows, -1), batch.aux,
                        batch.nrows))

    def next_batch(self) -> Optional[DenseBatch]:
        """Next static-shape DenseBatch of host numpy arrays (None at
        end); the fill is one GIL-released native pass."""
        if self._F is None:
            self._F, _, _ = self._b.meta()
            self._F = max(int(self._F), 1)
        F = self._F
        D = self.num_shards
        R = self.batch_rows // D
        pooled = self._pool.pop(("drec", F))
        if pooled is not None:
            x, aux, nrows = pooled
        else:
            x = _aligned_empty((self.batch_rows, F), self.dense_dtype)
            aux = _aligned_empty((D, 3, R), np.int32)
            nrows = np.empty(D, np.int32)
        # one fused native pass writes x and the aux pack
        take = self._b.fill_packed(x, aux, nrows)
        if take == 0:
            return None
        _, label, weight, _ = _view_aux(aux)
        return DenseBatch(x=x.reshape(D, R, F),
                          label=label, weight=weight,
                          nrows=nrows, total_rows=int(take), aux=aux)

    def reset(self) -> None:
        """Restart from the first record (new epoch); the pool survives."""
        self._b.before_first()

    def set_epoch(self, epoch: int) -> bool:
        """Pin the shuffle permutation the next reset() samples. Always
        False today: the dense-rec split does not shuffle."""
        return self._b.set_epoch(epoch)

    def bytes_read(self) -> int:
        """Record bytes consumed from the source so far."""
        return self._b.bytes_read()

    def close(self) -> None:
        """Free the native handle (idempotent)."""
        self._b.close()


class DeviceRowBlockIter:
    """HBM-resident row-block iterator (the TPU-native RowBlockIter).

    reference RowBlockIter<I,D>::Create (data.h:267) parity surface: iterate
    batches, before_first(), bytes_read(); plus device placement. A staging
    thread runs parse+pad (double buffer, capacity `prefetch`); the consumer
    thread issues device_put — by the time XLA finishes step k, batch k+1 is
    staged or already on device.

    ``prefetch=0`` runs the whole path synchronously on the caller's
    thread — no pipeline threads, no queues. The right mode when there is
    nothing to overlap with (single-core hosts, or calibration benches
    measuring the ingest path itself): each double-buffer handoff is a
    thread wakeup that buys nothing there and can cost more than the
    fused fill it hands over.
    """

    def __init__(self, uri: str, part: int = 0, npart: int = 1,
                 fmt: str = "auto", batch_rows: int = 65536,
                 mesh=None, min_nnz_bucket: int = 4096,
                 index64: bool = False, nthread: int = 0,
                 prefetch: int = 2, to_device: bool = True,
                 layout: str = "auto", dense_max_features: int = 512,
                 dense_dtype=np.float32, csr_val_dtype: str = "f32"):
        self.mesh = mesh
        self.to_device = to_device
        self.batch_rows = batch_rows
        num_shards = 1 if mesh is None else int(mesh.devices.size)
        path_part = uri.split("?", 1)[0].split("#", 1)[0]
        if fmt == "auto" and path_part.endswith(".drec"):
            fmt = "recd"  # dense row-matrix records are self-identifying
        elif fmt == "auto" and path_part.endswith(".crec"):
            fmt = "crec"  # CSR device-plane records (csr_rec.h)
        elif fmt == "auto" and path_part.endswith(".rec"):
            fmt = "rec"  # mirror the native suffix rule (parser.cc Create)
        # determinism keys for mid-epoch resume: the batch count is only a
        # position within THIS stream slicing (state()/restore()). Stored
        # AFTER suffix resolution so a checkpoint taken under fmt="auto"
        # restores into an iterator built with the explicit format.
        self._identity = {"uri": uri, "part": part, "npart": npart,
                          "fmt": fmt, "batch_rows": batch_rows}
        if csr_val_dtype != "f32" and (fmt in ("recd", "crec") or index64):
            raise DMLCError(
                "csr_val_dtype='bf16' is a native text/rec-lane feature "
                "(the fused fill converts values in-pass); the crec/drec "
                "binary lanes and the index64 python batcher keep f32")
        if fmt == "recd":
            # zero-parse dense lane: records already hold device-layout
            # matrices (dense_rec.h); CSR options don't apply
            self.parser = None
            self.batcher = DenseRecHostBatcher(
                uri, part=part, npart=npart, batch_rows=batch_rows,
                num_shards=num_shards, dense_dtype=dense_dtype)
        elif fmt == "crec":
            # zero-rearrangement CSR lane: records hold device-layout
            # col/val/row-length planes (csr_rec.h)
            self.parser = None
            self.batcher = CsrRecHostBatcher(
                uri, part=part, npart=npart, batch_rows=batch_rows,
                num_shards=num_shards, min_nnz_bucket=min_nnz_bucket)
        elif index64:
            # 64-bit parse width; the int32 device layout is still the hard
            # contract — the numpy batcher raises on any id >= 2^31
            # (_block_to_parts guard) instead of wrapping silently
            self.parser = NativeParser(uri, part=part, npart=npart, fmt=fmt,
                                       nthread=nthread, index64=True)
            self.batcher = HostBatcher(self.parser, batch_rows, num_shards,
                                       min_nnz_bucket, index64, layout=layout,
                                       dense_max_features=dense_max_features,
                                       dense_dtype=dense_dtype)
        else:
            self.parser = None
            self.batcher = NativeHostBatcher(
                uri, part=part, npart=npart, fmt=fmt, nthread=nthread,
                batch_rows=batch_rows, num_shards=num_shards,
                min_nnz_bucket=min_nnz_bucket, layout=layout,
                dense_max_features=dense_max_features,
                dense_dtype=dense_dtype, csr_val_dtype=csr_val_dtype)
        # per-leaf sharding derived from _PLACEMENT_RULES (every leaf is
        # shard-major, so all take the leading device axis); materialized
        # lazily from the first batch's tree structure — exposed for
        # bench probes
        self.sharding = None
        self._leading_sharding = (None if mesh is None
                                  else batch_sharding(mesh))
        # zero-copy transfer state: DMLC_DEVICE_ZERO_COPY=0 forces the
        # copying device_put path. _placements caches, per (leaf, shape),
        # each device's contiguous leading-axis slice of the host buffer
        # (None when the derived sharding is not leading-axis slicing).
        # _recycle_aliases latches whether this backend's device arrays
        # alias the staging buffers (probed on the first transfer — see
        # _tree_aliases_host).
        self._zero_copy = to_device and env_int(
            "DMLC_DEVICE_ZERO_COPY", 1) != 0
        self._placements: Dict[Any, Any] = {}
        self._recycle_aliases: Optional[bool] = None
        self._recycle_skipped = 0
        # deferred recycling under aliasing (zero-copy backends): host
        # buffers whose device arrays read them in place are parked here
        # behind weakrefs and recycled once the consumer drops the device
        # batch; overflow drops the oldest entry for real (counted in
        # device_recycle_skipped). Touched only by the transfer thread OR
        # the prefetch=0 sync generator, never both.
        self._deferred: list = []
        self._deferred_cap = max(4, prefetch * 2)
        self._prefetch = prefetch
        # two-stage pipeline: parse+pad thread -> _host_q -> transfer thread
        # -> _queue -> consumer. Parsing of batch k+1 overlaps the host->HBM
        # transfer of batch k, which overlaps XLA compute of batch k-1.
        self._host_q: "queue.Queue" = queue.Queue(maxsize=prefetch)
        self._queue: "queue.Queue" = queue.Queue(maxsize=prefetch)
        self._thread: Optional[threading.Thread] = None
        self._xfer_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # mid-epoch resume position (state()/restore())
        self.batches_consumed = 0
        self._skip_batches = 0
        # epoch ordinal: selects the shuffle permutation for shuffled URIs
        # (?shuffle_parts= / ?index=&shuffle=1). The split samples epoch 0's
        # permutation at construction; before_first() advances it. state()
        # records it so restore() can replay the exact visit order — a
        # batch prefix under a different permutation is different data.
        self._epoch = 0
        # compile-churn observability: best-effort jax.monitoring
        # listener (the shape census in _note_shape is the portable
        # fallback); once per process, never raises
        _install_compile_monitor()

    # -- staging threads -----------------------------------------------------
    # Queue ops are stop-aware: a blocking put/get could otherwise race the
    # close-time drain in _join_threads (the drain can steal the very item
    # that would unblock a peer, leaving it waiting forever on an empty
    # queue — the ThreadedIter shutdown hazard, pipeline.h Shutdown).
    _SHUTDOWN = object()

    def _put_stop(self, q: "queue.Queue", item) -> bool:
        """Put unless the iterator is stopping; False when dropped."""
        while True:
            try:
                q.put(item, timeout=0.05)
                return True
            except queue.Full:
                if self._stop.is_set():
                    return False

    def _get_stop(self, q: "queue.Queue"):
        """Get, or _SHUTDOWN once the iterator is stopping and the queue
        has drained."""
        while True:
            try:
                return q.get(timeout=0.05)
            except queue.Empty:
                if self._stop.is_set():
                    return self._SHUTDOWN

    def _parse_loop(self) -> None:
        try:
            # mid-epoch resume: burn the recorded prefix on this thread —
            # parsed and discarded, never transferred (restore())
            skip, self._skip_batches = self._skip_batches, 0
            for i in range(skip):
                if self._stop.is_set():  # close() must not wait out a
                    return               # potentially huge resume prefix
                batch = self.batcher.next_batch()
                if batch is None:
                    raise DMLCError(
                        f"restore: resume point ({skip} batches) is past "
                        f"end-of-data (got {i}); the checkpoint and the "
                        f"data stream disagree")
                if hasattr(self.batcher, "recycle"):
                    # discarded host batches never touched the device, so
                    # immediate recycling is safe on any backend
                    self.batcher.recycle(batch)
            m = _get_lane_metrics()
            while not self._stop.is_set():
                # device.stage: one host batch assembly (parse+pad+bucket
                # +pinned pack) on the staging thread — perf_counter like
                # every span clock; gated so DMLC_TELEMETRY=0 costs one
                # branch here
                if telemetry.enabled():
                    t0 = time.perf_counter()
                    batch = self.batcher.next_batch()
                    dur_us = (time.perf_counter() - t0) * 1e6
                    if batch is not None:
                        m["stage_us"].observe(dur_us)
                        telemetry.emit_span("device.stage", t0 * 1e6,
                                            dur_us,
                                            rows=batch.total_rows)
                else:
                    batch = self.batcher.next_batch()
                if batch is not None:
                    # compile-churn census: a new shape key here is the
                    # batch that re-traces every jitted consumer
                    _note_shape(batch)
                if not self._put_stop(self._host_q, batch):  # None terminates
                    return
                m["host_q"].set(self._host_q.qsize())
                if batch is None:
                    return
        except BaseException as e:  # propagate through the transfer stage
            self._put_stop(self._host_q, e)

    def _transfer_loop(self) -> None:
        try:
            recycle_ok = self.to_device and hasattr(self.batcher, "recycle")
            m = _get_lane_metrics()
            while not self._stop.is_set():
                item = self._get_stop(self._host_q)
                if item is self._SHUTDOWN:
                    return
                if isinstance(item, BaseException) or item is None:
                    self._put_stop(self._queue, item)
                    return
                host = item
                item = self._device_put(host)
                if not self._put_stop(self._queue, item):
                    return
                # double-buffer occupancy, both stages (scrape-time view
                # of where batches pile up)
                m["ready_q"].set(self._queue.qsize())
                m["host_q"].set(self._host_q.qsize())
                if recycle_ok and item is not host:
                    # _device_put blocked until the DMA landed, so the
                    # host buffers are free the moment the device batch
                    # is queued — UNLESS the device arrays alias the
                    # staging memory (zero-copy device_put, any backend
                    # where host and device share an address space). That
                    # is probed from the actual buffer addresses of the
                    # first transferred batch, not assumed from the
                    # backend name; aliased batches defer recycling
                    # until the consumer drops the device arrays.
                    self._recycle_or_defer(host, item, m)
        except BaseException as e:
            self._put_stop(self._queue, e)

    def _recycle_or_defer(self, host, item, m) -> None:
        """Return `host`'s staging buffers to the batcher pool — directly
        when the device arrays are independent copies, or DEFERRED when
        they alias the staging memory (zero-copy backends): the buffers
        are parked behind weakrefs to the device arrays and recycled on a
        later sweep, once the consumer has dropped the device batch.
        Without this, aliasing backends would allocate fresh staging for
        every batch forever — page-fault and allocator churn that can
        cost more than the fill itself. Overflowing the parking lot
        drops the oldest entry for real, counted in
        device_recycle_skipped."""
        if self._recycle_aliases is None:
            self._recycle_aliases = _tree_aliases_host(
                host.tree(), item.tree())
        if not self._recycle_aliases:
            self.batcher.recycle(host)
            return
        self._sweep_deferred()
        refs = tuple(weakref.ref(v) for v in item.tree().values())
        self._deferred.append((host, refs))
        if len(self._deferred) > self._deferred_cap:
            self._deferred.pop(0)
            self._recycle_skipped += 1
            m["recycle_skip"].set(self._recycle_skipped)

    def _sweep_deferred(self) -> None:
        """Recycle parked host batches whose aliasing device arrays have
        all been dropped by the consumer."""
        keep = []
        for host, refs in self._deferred:
            if all(r() is None for r in refs):
                self.batcher.recycle(host)
            else:
                keep.append((host, refs))
        self._deferred = keep

    def _ensure_started(self) -> None:
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(target=self._parse_loop,
                                            daemon=True)
            self._xfer_thread = threading.Thread(target=self._transfer_loop,
                                                 daemon=True)
            self._thread.start()
            self._xfer_thread.start()

    def _device_put(self, batch: PaddedBatch) -> PaddedBatch:
        if not self.to_device:
            return batch
        tree = batch.tree()
        m = _get_lane_metrics()
        nbytes = sum(int(v.nbytes) for v in tree.values())
        # host->HBM transfer, measured in its two halves for the unified
        # telemetry plane (doc/observability.md "Device lane"): SUBMIT
        # (the device_put dispatch) then BLOCK (dispatch to arrays
        # ready). Blocking here — not in the consumer — means the queue
        # hands over READY batches, so device.wait cleanly reads
        # "staging/transfer behind" and host-buffer recycling is
        # deterministic; the DMA for batch k still overlaps the
        # consumer's compute of batch k-1 (the double buffer), and
        # back-to-back dispatches bought nothing — transfers serialize
        # on the one host->device stream anyway. Timed spans are gated;
        # the block itself is unconditional (semantics must not depend
        # on DMLC_TELEMETRY).
        tel = telemetry.enabled()
        try:
            # the parent span is OPENED (telemetry.span), not emitted
            # post-hoc, so the submit/block children below genuinely
            # parent under its id in the ring — offline consumers of the
            # `parent` field see the nesting, not just Perfetto's
            # timestamp containment
            with telemetry.span("device.put", bytes=nbytes):
                t0 = time.perf_counter() if tel else None
                self._ensure_sharding(tree)
                if self._zero_copy:
                    try:
                        tree = self._zero_copy_put(tree)
                        m["zc_batches"].inc()
                    except _ZeroCopyIneligible as e:
                        telemetry.counter(
                            "device_zero_copy_fallbacks_total",
                            {"reason": e.reason}).inc()
                        tree = self._copy_put(tree)
                else:
                    tree = self._copy_put(tree)
                t1 = time.perf_counter() if tel else None
                jax.block_until_ready(list(tree.values()))
                if t0 is not None:
                    t2 = time.perf_counter()
                    m["transfer_us"].observe((t2 - t0) * 1e6)
                    m["submit_us"].observe((t1 - t0) * 1e6)
                    m["block_us"].observe((t2 - t1) * 1e6)
                    # same measurement, second surface: the span ring
                    # (doc/observability.md "Distributed tracing")
                    telemetry.emit_span("device.put.submit", t0 * 1e6,
                                        (t1 - t0) * 1e6)
                    telemetry.emit_span("device.put.block", t1 * 1e6,
                                        (t2 - t1) * 1e6)
        except BaseException:
            # counted + flight-dumped like host-side aborts (the
            # postmortem carries the span ring that shows which batch,
            # how far through the stream, and on what shape it died)
            m["failures"].inc()
            telemetry.flight_dump("device-put-failure")
            raise
        m["batches"].inc()
        m["bytes"].inc(nbytes)
        cls = type(batch)
        kwargs = dict(tree)
        if "val" in kwargs and "aux" in kwargs:
            # the packed tree's separate bf16 value leaf rides the val16
            # field so the device batch's tree() re-emits it
            kwargs["val16"] = kwargs.pop("val")
        return cls(total_rows=batch.total_rows, **kwargs)

    # -- zero-copy transfer --------------------------------------------------
    def _ensure_sharding(self, tree) -> None:
        if self._leading_sharding is None:
            return
        if self.sharding is None or set(self.sharding) != set(tree):
            self.sharding = match_placement_rules(self.mesh, tree)

    def _copy_put(self, tree):
        """The plain (copying) transfer: one device_put over the tree."""
        if self.sharding is not None:
            return jax.device_put(tree, self.sharding)
        return jax.device_put(tree)

    def _placement_table(self, key, shape, ns):
        """Per-device (device, lo, hi) leading-axis slices of leaf `key`
        under NamedSharding `ns`, derived from devices_indices_map and
        cached per (key, shape). None when the sharding does not slice
        the leading axis contiguously (zero-copy ineligible)."""
        ck = (key, shape)
        if ck in self._placements:
            return self._placements[ck]
        entries, ok = [], True
        try:
            imap = ns.devices_indices_map(shape)
        except Exception:
            imap, ok = None, False
        if ok:
            for dev, idx in imap.items():
                lead = idx[0] if idx else slice(None)
                rest = idx[1:] if idx else ()
                full_rest = all(
                    s.start in (None, 0) and s.step in (None, 1)
                    and s.stop in (None, shape[j + 1])
                    for j, s in enumerate(rest))
                if not idx or lead.step not in (None, 1) or not full_rest:
                    ok = False
                    break
                lo = 0 if lead.start is None else int(lead.start)
                hi = shape[0] if lead.stop is None else int(lead.stop)
                entries.append((dev, lo, hi))
        table = tuple(entries) if ok else None
        self._placements[ck] = table
        return table

    def _zero_copy_put(self, tree):
        """Transfer the batch without copying host memory: device_put of a
        64-byte-aligned C-contiguous numpy buffer lets the runtime alias
        it (DMA reads the staging memory in place), and under a mesh each
        device gets its own contiguous shard-major slab via the placement
        table + make_array_from_single_device_arrays — no host gather, no
        repack. Raises _ZeroCopyIneligible (counted, then the copying
        path runs) rather than silently degrading."""
        for v in tree.values():
            if not isinstance(v, np.ndarray) or \
                    not v.flags["C_CONTIGUOUS"]:
                raise _ZeroCopyIneligible("non_contiguous_host")
        if self.sharding is None:
            for v in tree.values():
                if v.ctypes.data % 64:
                    raise _ZeroCopyIneligible("unaligned")
            # one dispatch for the whole tree: each aligned leaf is
            # aliased individually; the single call just saves the
            # per-leaf Python round trip
            return jax.device_put(tree)
        out = {}
        for k, v in tree.items():
            ns = self.sharding[k]
            table = self._placement_table(k, v.shape, ns)
            if table is None:
                raise _ZeroCopyIneligible("non_leading_partition")
            shards = []
            for dev, lo, hi in table:
                piece = v[lo:hi]
                if piece.ctypes.data % 64:
                    # shard slab sizes that are not 64-byte multiples
                    # misalign every shard after the first
                    raise _ZeroCopyIneligible("unaligned")
                shards.append(jax.device_put(piece, dev))
            out[k] = jax.make_array_from_single_device_arrays(
                v.shape, ns, shards)
        return out

    def _iter_sync(self) -> Iterator[PaddedBatch]:
        """prefetch=0: parse+fill, device_put, and consumption inline on
        the caller's thread (see the class docstring). Same semantics as
        the threaded path — stage spans, shape census, resume-prefix
        burning, alias-probed recycling — minus the queues."""
        m = _get_lane_metrics()
        recycle_ok = self.to_device and hasattr(self.batcher, "recycle")
        skip, self._skip_batches = self._skip_batches, 0
        for i in range(skip):
            batch = self.batcher.next_batch()
            if batch is None:
                raise DMLCError(
                    f"restore: resume point ({skip} batches) is past "
                    f"end-of-data (got {i}); the checkpoint and the "
                    f"data stream disagree")
            if hasattr(self.batcher, "recycle"):
                self.batcher.recycle(batch)
        while True:
            if telemetry.enabled():
                t0 = time.perf_counter()
                host = self.batcher.next_batch()
                dur_us = (time.perf_counter() - t0) * 1e6
                if host is not None:
                    m["stage_us"].observe(dur_us)
                    telemetry.emit_span("device.stage", t0 * 1e6, dur_us,
                                        rows=host.total_rows)
            else:
                host = self.batcher.next_batch()
            if host is None:
                return
            _note_shape(host)
            item = self._device_put(host)
            self.batches_consumed += 1
            if recycle_ok and item is not host:
                # same alias-probed direct-or-deferred recycling as the
                # transfer thread: _device_put blocked until the DMA
                # landed, so the host buffers are refillable unless the
                # device arrays alias them — in which case they are
                # parked and reclaimed once the consumer drops `item`
                self._recycle_or_defer(host, item, m)
            yield item

    def __iter__(self) -> Iterator[PaddedBatch]:
        if self._prefetch == 0:
            yield from self._iter_sync()
            return
        self._ensure_started()
        m = _get_lane_metrics()
        while True:
            # device.wait: consumer head-of-line — the time this thread
            # stood idle because staging/transfer had not delivered the
            # next READY batch. The complement of these intervals is the
            # consumer's compute time, which is what the overlap ratio
            # (telemetry.device_overlap_ratio) intersects device.put
            # spans against.
            if telemetry.enabled():
                t0 = time.perf_counter()
                item = self._queue.get()
                dur_us = (time.perf_counter() - t0) * 1e6
                m["wait_us"].observe(dur_us)
                telemetry.emit_span("device.wait", t0 * 1e6, dur_us)
            else:
                item = self._queue.get()
            m["ready_q"].set(self._queue.qsize())
            if item is None:
                self._thread = None
                self._xfer_thread = None
                return
            if isinstance(item, BaseException):
                self._thread = None
                self._xfer_thread = None
                raise item
            self.batches_consumed += 1
            yield item

    # -- mid-epoch checkpoint/resume ----------------------------------------
    def state(self) -> Dict[str, Any]:
        """Resume point for mid-epoch checkpointing: the number of batches
        yielded this epoch plus the determinism keys (uri/part/npart/fmt/
        batch_rows) that make the count a position. Save it next to the
        model checkpoint (utils/checkpoint.py) and hand it to restore()
        after a preemption — the TPU-pod recovery story."""
        return dict(self._identity, batches_consumed=self.batches_consumed,
                    epoch=self._epoch)

    def restore(self, state: Dict[str, Any]) -> None:
        """Rewind to the epoch start, then skip `state['batches_consumed']`
        batches HOST-SIDE on the staging thread (parsed/filled and
        discarded — never transferred to the device), so iteration resumes
        exactly where state() was captured. Raises if any recorded
        determinism key (batch_rows/part/npart/uri/fmt) disagrees with
        this iterator — batch k of a different stream slicing is different
        data, and resuming there would silently skip and duplicate rows —
        or, at iteration time, if the resume point lies past end-of-data."""
        for key, ours in self._identity.items():
            theirs = state.get(key, ours)
            if theirs != ours:
                raise DMLCError(
                    f"restore: checkpoint was taken with {key}={theirs!r} "
                    f"but this iterator uses {ours!r}; resuming a batch "
                    f"count across a different stream slicing would read "
                    f"the wrong rows")
        # replay the checkpoint's epoch so shuffled URIs rewind into the
        # SAME permutation the prefix was counted under (split-level
        # SetShuffleEpoch; no-op for unshuffled streams, where ordering is
        # epoch-independent)
        self._epoch = int(state.get("epoch", 0))
        self._reset_stream()
        self._skip_batches = int(state.get("batches_consumed", 0))
        self.batches_consumed = self._skip_batches

    def _join_threads(self) -> None:
        self._stop.set()
        for th, q in ((self._thread, self._host_q),
                      (self._xfer_thread, self._queue)):
            if th is None:
                continue
            while th.is_alive():
                try:  # drain so a blocked put can finish
                    q.get_nowait()
                except queue.Empty:
                    pass
                th.join(timeout=0.02)
        self._thread = None
        self._xfer_thread = None
        for q in (self._host_q, self._queue):
            while True:
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
        # reclaim what the consumer has released; drop the rest (their
        # device arrays may still alias the staging memory)
        self._sweep_deferred()
        self._deferred = []
        self._stop.clear()

    def before_first(self) -> None:
        """Restart iteration as the next epoch (reference
        DataIter::BeforeFirst; shuffled URIs resample their permutation)."""
        self._epoch += 1
        self._reset_stream()

    def _reset_stream(self) -> None:
        """Rewind to the start of epoch ``self._epoch``."""
        self._join_threads()
        if hasattr(self.batcher, "set_epoch"):
            # pin the permutation deterministically to the epoch ordinal
            # (instead of the split's own BeforeFirst counter, which a
            # process restart would silently reset to 0)
            self.batcher.set_epoch(self._epoch)
        self.batcher.reset()
        self.batches_consumed = 0
        self._skip_batches = 0

    def bytes_read(self) -> int:
        """Bytes consumed from the underlying source so far."""
        if self.parser is not None:
            return self.parser.bytes_read()
        return self.batcher.bytes_read()

    def close(self) -> None:
        """Stop staging threads and free native resources (idempotent)."""
        self._join_threads()
        if self.parser is not None:
            self.parser.close()
        else:
            self.batcher.close()

    def abort_drain(self, reason: str = "tracker-abort") -> None:
        """Abort-path teardown with a BOUNDED wall clock
        (``DMLC_DEVICE_ABORT_DRAIN_MS``, default 2000 ms), for the
        TrackerAbortedError path (doc/robustness.md "Elastic mesh
        training"): a survivor of a dead mesh peer must drain this
        pipeline and exit promptly, even if a staging/transfer thread is
        parked inside a device_put it cannot finish.

        Differs from the cooperative :meth:`_join_threads` in two ways —
        thread joins give up at the deadline (daemon threads; the
        process is about to exit anyway), and the zero-copy parking lot
        is force-dropped: parked staging buffers whose device arrays are
        still live are LEAKED to the allocator rather than recycled,
        because recycling memory a device array still aliases would
        corrupt whatever the abort handler reads from it. Counted in
        ``device_abort_drains_total``; idempotent, and close() stays
        safe to call after."""
        deadline = time.monotonic() + max(
            1, env_int("DMLC_DEVICE_ABORT_DRAIN_MS", 2000)) / 1000.0
        self._stop.set()
        joined = True
        for th, q in ((self._thread, self._host_q),
                      (self._xfer_thread, self._queue)):
            if th is None:
                continue
            while th.is_alive():
                if time.monotonic() > deadline:
                    joined = False
                    break
                try:  # drain so a blocked put can finish
                    q.get_nowait()
                except queue.Empty:
                    pass
                th.join(timeout=0.02)
        self._thread = None
        self._xfer_thread = None
        for q in (self._host_q, self._queue):
            while True:
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
        # reclaim what the consumer released; FORCE-DROP the rest — their
        # device arrays may still alias the staging memory, so the
        # buffers leak to the allocator instead of returning to the pool
        self._sweep_deferred()
        dropped = len(self._deferred)
        self._deferred = []
        if joined:
            # only a fully-stopped pipeline may rearm; a straggler thread
            # still sees _stop and exits on its own
            self._stop.clear()
        telemetry.counter("device_abort_drains_total").inc()
        telemetry.flight_dump(
            f"device-abort-drain: {reason} (threads "
            f"{'joined' if joined else 'abandoned at deadline'}, "
            f"{dropped} parked buffer(s) dropped)")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class ElasticDeviceRowBlockIter:
    """Lease data-plane × device pipeline: the elastic-mesh input glue
    (doc/robustness.md "Elastic mesh training").

    Where :class:`~dmlc_core_tpu.data.ElasticRowBlockIter` feeds HOST
    consumers from tracker shard leases, this feeds the DEVICE: each
    granted shard becomes a :class:`DeviceRowBlockIter` over
    ``part=shard, npart=num_shards`` with the PR 16 spec-driven sharded
    placement, so per-mesh-axis data shards flow lease → batcher →
    device with no host gather. Yields ``(shard, device_batch)`` pairs;
    a shard's lease completes only after its last batch was yielded
    (the exactly-once checkout survives a consumer death mid-shard —
    the tracker reclaims and re-grants the shard).

    On TrackerAbortedError — from acquire, or surfaced by the monitor
    mid-shard — the live device pipeline is torn down through
    :meth:`DeviceRowBlockIter.abort_drain` (bounded wall clock, parking
    lot force-dropped) and the error propagates. ``abort_drain`` on this
    iterator is safe from another thread, so it slots directly into a
    :class:`~dmlc_core_tpu.parallel.elastic.StepWatchdog` drain list."""

    def __init__(self, uri: str, num_shards: Optional[int] = None,
                 monitor=None, epoch: int = 0,
                 acquire_timeout: Optional[float] = None,
                 **device_kwargs):
        from dmlc_core_tpu.tracker.client import current_monitor
        self.uri = uri
        self._monitor = monitor if monitor is not None else current_monitor()
        if self._monitor is None:
            raise DMLCError(
                "ElasticDeviceRowBlockIter needs a heartbeat channel "
                "(rendezvous with heartbeat=True under an elastic "
                "tracker) — without leases there is no shard source")
        self.num_shards = num_shards if num_shards is not None \
            else env_int("DMLC_TRACKER_NUM_SHARDS", 0)
        if self.num_shards <= 0:
            raise DMLCError(
                "ElasticDeviceRowBlockIter: num_shards must be > 0 (set "
                "DMLC_TRACKER_NUM_SHARDS or pass num_shards=)")
        self.epoch = epoch
        self._acquire_timeout = acquire_timeout
        self._device_kwargs = device_kwargs
        self._current: Optional[DeviceRowBlockIter] = None
        self._aborting = False

    def __iter__(self):
        while True:
            shard = self._monitor.acquire_lease(
                self.epoch, timeout=self._acquire_timeout)
            if shard is None:
                return  # epoch drained: every shard checked out
            it = DeviceRowBlockIter(self.uri, part=shard,
                                    npart=self.num_shards,
                                    **self._device_kwargs)
            self._current = it
            try:
                for batch in it:
                    yield shard, batch
                self._monitor.complete_lease(self.epoch, shard)
            except TrackerAbortedError:
                it.abort_drain("tracker-abort mid-shard")
                raise
            finally:
                self._current = None
                it.close()

    def abort_drain(self, reason: str = "tracker-abort") -> None:
        """Tear down the in-flight shard's device pipeline (bounded wall
        clock; see DeviceRowBlockIter.abort_drain). Thread-safe enough
        for a watchdog drain: _stop/queue ops are atomic, and a racing
        consumer raises out of its queue wait."""
        self._aborting = True
        it = self._current
        if it is not None:
            it.abort_drain(reason)
