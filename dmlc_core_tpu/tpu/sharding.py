"""Mesh and sharding helpers for the device-resident data path.

This is the TPU-native replacement for the reference's rank math: where
dmlc-core hands each worker a (part_index, num_parts) byte-range
(reference io.h:261 InputSplit::Create + input_split_base.cc:30-64) and the
Rabit tracker computes allreduce topologies over sockets
(tracker.py:185-252), here the topology is the `jax.sharding.Mesh` and the
collectives are XLA's (psum over ICI) — the tracker's tree/ring computation
disappears into hardware routing (SURVEY §2.5, §5).

Conventions:
- mesh axis "data" shards the batch (DP): each chip consumes distinct rows.
- the host-level shard is `jax.process_index()` of `jax.process_count()` —
  composing the byte-range InputSplit (process level) with the mesh
  (chip level) gives the full pod-slice sharding.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dmlc_core_tpu.tracker.wire import env_int_opt

__all__ = ["data_mesh", "host_data_mesh", "batch_sharding",
           "packed_batch_sharding", "replicated_sharding", "process_part",
           "local_device_count"]


def data_mesh(num_devices: Optional[int] = None,
              axis_name: str = "data") -> Mesh:
    """A 1-D mesh over (up to) all addressable devices for data parallelism."""
    devs = jax.devices()
    if num_devices is not None:
        devs = devs[:num_devices]
    return Mesh(np.array(devs), (axis_name,))


def host_data_mesh(num_devices: Optional[int] = None,
                   axis_name: str = "data") -> Mesh:
    """A 1-D mesh over this PROCESS's devices only.

    The compute mesh of the elastic-mesh CPU floor (doc/robustness.md
    "Elastic mesh training"): XLA's CPU backend cannot run multiprocess
    computations, so each host steps over its local mesh and the
    cross-host reduction rides the coordination-service collectives
    (parallel.allreduce_tree). On TPU, jit over the global
    :func:`data_mesh` is the native path; this helper keeps the CPU
    floor honest rather than silently global-meshing into a backend
    error."""
    devs = jax.local_devices()
    if num_devices is not None:
        devs = devs[:num_devices]
    return Mesh(np.array(devs), (axis_name,))


def batch_sharding(mesh: Mesh, axis_name: str = "data") -> NamedSharding:
    """Shard the leading (device) axis of a batch across the mesh."""
    return NamedSharding(mesh, P(axis_name))


def packed_batch_sharding(mesh: Mesh, axis_name: str = "data"
                          ) -> NamedSharding:
    """Sharding for the packed batch leaves (`aux` [D, K, R], `big`
    [D, Kb, NNZ] — device_iter packing). The packs are SHARD-MAJOR: the
    device axis LEADS, so each device's slice is one contiguous run of
    the host staging buffer — the precondition for the zero-copy
    device_put path. (Equal to batch_sharding since the shard-major
    migration; kept as a named concept and for callers that predate
    it.)"""
    return NamedSharding(mesh, P(axis_name))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Fully replicated placement (model parameters under pure DP)."""
    return NamedSharding(mesh, P())


def process_part(num_parts_per_process: int = 1) -> Tuple[int, int]:
    """(part_index, num_parts) for this host's InputSplit.

    The multi-host composition: every process opens the same URI with its
    own part of `process_count` parts — the exact-cover property of
    ByteSplit guarantees global coverage (the contract reference workers
    rely on, SURVEY §3.2).

    Launch regimes resolve the part in order (SURVEY §2.4 env protocol):
    - ``cluster=tpu-pod`` (or any `jax.distributed` job): the JAX process
      id/count — collectives and data sharding agree by construction.
    - task-id launchers (local/sge/kubernetes/yarn): the launcher's
      ``DMLC_TASK_ID`` / ``DMLC_NUM_WORKER`` assignment (the reference
      contract: InputSplit::Create(uri, rank, nworker)). Server/scheduler
      roles read the whole stream by convention (their task ids sit past
      the worker range).
    - mpi / slurm: the runtime's native rank vars
      (OMPI_COMM_WORLD_RANK / PMI_RANK / SLURM_PROCID). The slurm count
      comes from SLURM_STEP_NUM_TASKS — step-scoped, exported only inside
      an `srun` step — NOT from SLURM_NTASKS, which sbatch/salloc export
      for the whole allocation even when the script runs as ONE process
      (such a job must read the full dataset, not 1/N of it).
    - otherwise (ssh/mesos workers, whose rank is assigned dynamically at
      rendezvous): (0, 1) — pass part/npart explicitly from the
      rendezvous rank for those clusters.
    Without the fallbacks every single-process worker would silently
    train on the FULL dataset.
    """
    if jax.process_count() > 1:
        return jax.process_index(), jax.process_count()
    if os.environ.get("DMLC_ROLE", "worker") != "worker":
        return 0, 1  # servers/schedulers are not data consumers
    for rank_var, count_var in (
            ("DMLC_TASK_ID", "DMLC_NUM_WORKER"),
            ("OMPI_COMM_WORLD_RANK", "OMPI_COMM_WORLD_SIZE"),
            ("PMI_RANK", "PMI_SIZE"),
            ("SLURM_PROCID", "SLURM_STEP_NUM_TASKS")):
        # wire.env_int_opt behind a presence gate: a pair that is not
        # fully exported falls through to the next launcher WITHOUT
        # being parsed (garbage in an unused pair must not kill the
        # run), but a fully-exported pair with an empty/garbage/-1 rank
        # fails loudly instead of mis-sharding
        if rank_var not in os.environ or count_var not in os.environ:
            continue
        # count first: a single-task pair falls through WITHOUT parsing
        # its rank (a garbage rank in a pair this function would skip
        # anyway must not kill the run)
        npart = env_int_opt(count_var)
        if npart <= 1:
            continue
        part = env_int_opt(rank_var)
        if not 0 <= part < npart:
            raise ValueError(
                f"{rank_var}={part} out of range for "
                f"{count_var}={npart}")
        return part, npart
    return 0, 1


def local_device_count(mesh: Optional[Mesh] = None) -> int:
    """Devices visible to this process (or in `mesh` when given)."""
    if mesh is None:
        return jax.local_device_count()
    return mesh.devices.size
