"""Mesh and sharding helpers for the device-resident data path.

This is the TPU-native replacement for the reference's rank math: where
dmlc-core hands each worker a (part_index, num_parts) byte-range
(reference io.h:261 InputSplit::Create + input_split_base.cc:30-64) and the
Rabit tracker computes allreduce topologies over sockets
(tracker.py:185-252), here the topology is the `jax.sharding.Mesh` and the
collectives are XLA's (psum over ICI) — the tracker's tree/ring computation
disappears into hardware routing (SURVEY §2.5, §5).

Conventions:
- mesh axis "data" shards the batch (DP): each chip consumes distinct rows.
- the host-level shard is `jax.process_index()` of `jax.process_count()` —
  composing the byte-range InputSplit (process level) with the mesh
  (chip level) gives the full pod-slice sharding.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["data_mesh", "batch_sharding", "packed_batch_sharding",
           "replicated_sharding", "process_part", "local_device_count"]


def data_mesh(num_devices: Optional[int] = None,
              axis_name: str = "data") -> Mesh:
    """A 1-D mesh over (up to) all addressable devices for data parallelism."""
    devs = jax.devices()
    if num_devices is not None:
        devs = devs[:num_devices]
    return Mesh(np.array(devs), (axis_name,))


def batch_sharding(mesh: Mesh, axis_name: str = "data") -> NamedSharding:
    """Shard the leading (device) axis of a batch across the mesh."""
    return NamedSharding(mesh, P(axis_name))


def packed_batch_sharding(mesh: Mesh, axis_name: str = "data"
                          ) -> NamedSharding:
    """Shard the SECOND axis across the mesh: the packed batch leaves
    (`aux` [K, D, R], `big` [Kb, D, NNZ] — device_iter packing) carry the
    device axis at position 1 so each plane stays a contiguous native
    fill target."""
    return NamedSharding(mesh, P(None, axis_name))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Fully replicated placement (model parameters under pure DP)."""
    return NamedSharding(mesh, P())


def process_part(num_parts_per_process: int = 1) -> Tuple[int, int]:
    """(part_index, num_parts) for this host's InputSplit.

    The multi-host composition: every process opens the same URI with its own
    part of `process_count` parts — the exact-cover property of ByteSplit
    guarantees global coverage (the contract reference workers rely on,
    SURVEY §3.2)."""
    return jax.process_index(), max(jax.process_count(), 1)


def local_device_count(mesh: Optional[Mesh] = None) -> int:
    """Devices visible to this process (or in `mesh` when given)."""
    if mesh is None:
        return jax.local_device_count()
    return mesh.devices.size
