"""Rabit tracker wire protocol primitives.

Byte-compatible with the reference protocol (tracker/dmlc_tracker/
tracker.py:24-50 ExSocket + kMagic handshake) so legacy Rabit workers can
rendezvous against this tracker: native-endian 4-byte ints, length-prefixed
UTF-8 strings, magic 0xff99 exchanged on connect.
"""

from __future__ import annotations

import socket
import struct
from typing import List, Optional

MAGIC = 0xFF99

# -- liveness protocol constants ---------------------------------------------
# A worker that opts into liveness opens a SECOND tracker connection with
# cmd="heartbeat" after receiving its rank. The channel carries int32 pings
# (worker -> tracker, any non-negative value) on the interval the tracker
# announces right after the handshake; the only tracker -> worker frames are
# HEARTBEAT_ABORT followed by a length-prefixed reason string, broadcast
# when the job is being torn down so workers raise instead of hanging in
# peer links, and LEASE_GRANT (below). Legacy clients never send
# cmd="heartbeat", so the original start/recover/shutdown/print byte stream
# is untouched.
CMD_HEARTBEAT = "heartbeat"
HEARTBEAT_PING = 1
HEARTBEAT_BYE = 2   # graceful channel close: disarms liveness, not a death
HEARTBEAT_ABORT = -86

# -- elastic data-plane lease frames (doc/robustness.md "Elastic data-plane")
# Shard leases ride the EXISTING heartbeat channel — no second connection
# per renewal, and every lease frame doubles as a liveness proof. All
# command words are negative so they can never collide with a ping (any
# non-negative int32). Worker -> tracker frames:
#   [LEASE_ACQUIRE][epoch]          ask for one shard of `epoch`
#   [LEASE_RELEASE][epoch][shard]   return an unfinished shard to the pool
#   [LEASE_COMPLETE][epoch][shard]  mark the shard consumed (exactly-once)
# The tracker answers an acquire with [LEASE_GRANT][shard] where `shard`
# is a shard id >= 0, LEASE_EMPTY (nothing free NOW — held shards may
# return if their holder dies; retry), or LEASE_DRAINED (every shard of
# the epoch is complete: end of epoch). Renewal is implicit: every ping
# (and every lease frame) extends all leases the rank holds.
LEASE_ACQUIRE = -90
LEASE_RELEASE = -91
LEASE_COMPLETE = -92
LEASE_GRANT = -93
LEASE_EMPTY = -1
LEASE_DRAINED = -2

# -- cluster telemetry frames (doc/observability.md "Cluster aggregation") --
# Piggybacked on the SAME heartbeat channel, same negative-word rule.
# Tracker -> worker: [TELEMETRY_PULL] (no payload) asks the rank for its
# telemetry snapshot; sent to every live channel when the tracker's HTTP
# scrape surface serves /metrics or /trace. Worker -> tracker:
# [TELEMETRY_PUSH][len][<len> bytes of JSON] — the rank_export() document
# (metrics + wall-clock spans + the process clock anchor). A push doubles
# as a liveness proof; a worker that never answers (legacy client) simply
# times the pull out — the scrape degrades to the ranks that replied.
TELEMETRY_PULL = -95
TELEMETRY_PUSH = -96
# a push beyond this is a corrupt frame, not telemetry (the rank_export
# span cap keeps real documents far below it)
TELEMETRY_PUSH_MAX = 8 << 20

# -- the machine-checked channel word registry --------------------------------
# Every COMMAND word the heartbeat channel can carry must be negative (a
# ping is ANY non-negative int32, so a non-negative command word would be
# indistinguishable from a ping), and no two words — command or sentinel —
# may share a value. Nothing used to enforce that invariant; now
# `scripts/analyze.py` Pass 4 (doc/analysis.md) does, against this
# registry: it checks every entry names its constant, every registered
# word is negative and collision-free, and every negative module constant
# IS registered (a new word added without a registry entry is a finding —
# unregistered words would dodge the collision check).
#
# HEARTBEAT_PING / HEARTBEAT_BYE are deliberately absent: they live in the
# ping space (non-negative) by design and are classified by value range,
# not by word. Sentinels are answer-frame values in the shard-id position
# (shard ids are >= 0), so they share the negative space with commands
# and must not collide with them either.
CHANNEL_COMMAND_WORDS = {
    "HEARTBEAT_ABORT": HEARTBEAT_ABORT,
    "LEASE_ACQUIRE": LEASE_ACQUIRE,
    "LEASE_RELEASE": LEASE_RELEASE,
    "LEASE_COMPLETE": LEASE_COMPLETE,
    "LEASE_GRANT": LEASE_GRANT,
    "TELEMETRY_PULL": TELEMETRY_PULL,
    "TELEMETRY_PUSH": TELEMETRY_PUSH,
}
CHANNEL_SENTINELS = {
    "LEASE_EMPTY": LEASE_EMPTY,
    "LEASE_DRAINED": LEASE_DRAINED,
}


def env_float(name: str, default: float, env=None) -> float:
    """Checked float env parse (the env_int rule for float-valued knobs
    like DMLC_TRACKER_HANDSHAKE_TIMEOUT): garbage text raises instead of
    silently disabling a deadline."""
    import os
    raw = (os.environ if env is None else env).get(name)
    if raw is None or raw == "":
        return default
    try:
        return float(raw)
    except ValueError:
        raise RuntimeError(f"{name}={raw!r} is not a number")


def env_enum(name: str, choices, default: Optional[str] = None,
             env=None) -> Optional[str]:
    """Checked enum env parse: a set value outside `choices` raises with
    the allowed set named (a typo'd DMLC_JOB_CLUSTER must fail in the
    container bootstrap, not silently select a default backend)."""
    import os
    raw = (os.environ if env is None else env).get(name)
    if raw is None or raw == "":
        return default
    if raw not in choices:
        raise RuntimeError(
            f"{name}={raw!r} is not one of {sorted(choices)}")
    return raw


def env_int(name: str, default: int, env=None) -> int:
    """Checked env parse shared by tracker/client/bootstrap: garbage text
    raises instead of silently becoming a value that disables a liveness
    deadline (the retry.h CheckedEnvInt rule, applied to the control
    plane). `env` defaults to os.environ (bootstrap validates its own
    computed dict)."""
    import os
    raw = (os.environ if env is None else env).get(name)
    if raw is None or raw == "":
        return default
    try:
        return int(raw)
    except ValueError:
        raise RuntimeError(f"{name}={raw!r} is not an integer")


def env_str(name: str, default: Optional[str] = None,
            env=None) -> Optional[str]:
    """String env knob read through the same checked gate as the numeric
    parsers (one registry, one doc table): unset or empty returns the
    default — path-valued knobs like DMLC_SERVE_ACCESS_LOG treat "" as
    "off", matching the event-log convention."""
    import os
    raw = (os.environ if env is None else env).get(name)
    if raw is None or raw == "":
        return default
    return raw


def env_int_opt(name: str, env=None):
    """Presence-gated checked parse for launcher rank/count variables:
    None when the variable is UNSET (the caller falls through to its next
    source), but a SET-but-invalid value — empty text included — raises
    naming the variable. `env_int`'s \"\"→default convention is wrong
    here: a templating bug exporting RANK=\"\" must kill the job, not
    silently shard it wrong."""
    import os
    e = os.environ if env is None else env
    if name not in e:
        return None
    try:
        return int(e[name])
    except ValueError:
        raise RuntimeError(f"{name}={e[name]!r} is not an integer")


class TrackerAbortedError(RuntimeError):
    """The tracker gave up on the job (dead ranks past their deadline, a
    supervisor that exhausted its attempts, or an explicit abort()).

    Raised by ``RabitTracker.join()`` on the launcher side and by
    ``RendezvousClient`` operations unblocked by the abort broadcast on the
    worker side — the structured, loud end the liveness layer guarantees
    instead of an indefinite hang."""

    def __init__(self, reason: str, dead_ranks: Optional[List[int]] = None):
        self.reason = reason
        self.dead_ranks = sorted(dead_ranks or [])
        msg = reason
        if self.dead_ranks:
            msg = f"{reason} (dead ranks: {self.dead_ranks})"
        super().__init__(msg)


class WireSocket:
    """Length-prefixed int/str framing over a TCP socket."""

    def __init__(self, sock: socket.socket):
        self.sock = sock

    def recv_all(self, nbytes: int) -> bytes:
        """Receive exactly `nbytes` bytes (raises on EOF)."""
        chunks = []
        got = 0
        while got < nbytes:
            chunk = self.sock.recv(min(nbytes - got, 4096))
            if not chunk:
                raise ConnectionError("peer closed during recv")
            got += len(chunk)
            chunks.append(chunk)
        return b"".join(chunks)

    def recv_int(self) -> int:
        """Receive one int32 (Rabit wire byte order)."""
        return struct.unpack("@i", self.recv_all(4))[0]

    def send_int(self, v: int) -> None:
        """Send one int32 (Rabit wire byte order)."""
        self.sock.sendall(struct.pack("@i", v))

    # strings on this protocol are hostnames/job ids/log lines; a length
    # beyond this is a corrupt or adversarial frame, not data — without
    # the cap a bogus 2 GB prefix would balloon recv_all and stall the
    # tracker's accept loop
    MAX_STR = 1 << 20

    def recv_str(self) -> str:
        """Receive a length-prefixed string (Rabit wire format)."""
        n = self.recv_int()
        if n < 0 or n > self.MAX_STR:
            raise ConnectionError(
                f"invalid string length {n} on tracker wire")
        return self.recv_all(n).decode()

    def send_str(self, s: str) -> None:
        """Send a length-prefixed string (Rabit wire format)."""
        data = s.encode()
        self.send_int(len(data))  # byte count, not character count
        self.sock.sendall(data)

    def settimeout(self, timeout) -> None:
        """Bound every subsequent blocking op on the underlying socket."""
        self.sock.settimeout(timeout)

    def close(self) -> None:
        """Close the underlying socket (idempotent)."""
        self.sock.close()


def resolve_ip(host: str) -> str:
    """Resolve a hostname to the IP the workers should dial."""
    return socket.getaddrinfo(host, None)[0][4][0]


def addr_family(addr: str):
    """AF_INET or AF_INET6 for the given host string."""
    return socket.getaddrinfo(addr, None)[0][0]


def guess_host_ip(host_ip=None) -> str:
    """Best-effort routable IP (reference tracker.py get_host_ip)."""
    if host_ip not in (None, "auto", "ip", "dns"):
        return host_ip
    if host_ip == "dns":
        return socket.getfqdn()
    try:
        ip = socket.gethostbyname(socket.getfqdn())
    except socket.gaierror:
        ip = socket.gethostbyname(socket.gethostname())
    if ip.startswith("127."):
        probe = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            probe.connect(("10.255.255.255", 1))  # no traffic sent
            ip = probe.getsockname()[0]
        except OSError:
            ip = "127.0.0.1"
        finally:
            probe.close()
    return ip


def bind_free_port(host: str, port_start: int = 9091, port_end: int = 9999
                   ) -> socket.socket:
    """Bind a listening socket on the first free port in the scan range
    (reference tracker.py:141-153)."""
    sock = socket.socket(addr_family(host), socket.SOCK_STREAM)
    for port in range(port_start, port_end):
        try:
            sock.bind((host, port))
            return sock
        except OSError:
            continue
    raise OSError(f"no free port in [{port_start}, {port_end})")
