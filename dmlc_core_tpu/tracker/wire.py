"""Rabit tracker wire protocol primitives.

Byte-compatible with the reference protocol (tracker/dmlc_tracker/
tracker.py:24-50 ExSocket + kMagic handshake) so legacy Rabit workers can
rendezvous against this tracker: native-endian 4-byte ints, length-prefixed
UTF-8 strings, magic 0xff99 exchanged on connect.
"""

from __future__ import annotations

import socket
import struct

MAGIC = 0xFF99


class WireSocket:
    """Length-prefixed int/str framing over a TCP socket."""

    def __init__(self, sock: socket.socket):
        self.sock = sock

    def recv_all(self, nbytes: int) -> bytes:
        """Receive exactly `nbytes` bytes (raises on EOF)."""
        chunks = []
        got = 0
        while got < nbytes:
            chunk = self.sock.recv(min(nbytes - got, 4096))
            if not chunk:
                raise ConnectionError("peer closed during recv")
            got += len(chunk)
            chunks.append(chunk)
        return b"".join(chunks)

    def recv_int(self) -> int:
        """Receive one int32 (Rabit wire byte order)."""
        return struct.unpack("@i", self.recv_all(4))[0]

    def send_int(self, v: int) -> None:
        """Send one int32 (Rabit wire byte order)."""
        self.sock.sendall(struct.pack("@i", v))

    # strings on this protocol are hostnames/job ids/log lines; a length
    # beyond this is a corrupt or adversarial frame, not data — without
    # the cap a bogus 2 GB prefix would balloon recv_all and stall the
    # tracker's accept loop
    MAX_STR = 1 << 20

    def recv_str(self) -> str:
        """Receive a length-prefixed string (Rabit wire format)."""
        n = self.recv_int()
        if n < 0 or n > self.MAX_STR:
            raise ConnectionError(
                f"invalid string length {n} on tracker wire")
        return self.recv_all(n).decode()

    def send_str(self, s: str) -> None:
        """Send a length-prefixed string (Rabit wire format)."""
        data = s.encode()
        self.send_int(len(data))  # byte count, not character count
        self.sock.sendall(data)

    def close(self) -> None:
        """Close the underlying socket (idempotent)."""
        self.sock.close()


def resolve_ip(host: str) -> str:
    """Resolve a hostname to the IP the workers should dial."""
    return socket.getaddrinfo(host, None)[0][4][0]


def addr_family(addr: str):
    """AF_INET or AF_INET6 for the given host string."""
    return socket.getaddrinfo(addr, None)[0][0]


def guess_host_ip(host_ip=None) -> str:
    """Best-effort routable IP (reference tracker.py get_host_ip)."""
    if host_ip not in (None, "auto", "ip", "dns"):
        return host_ip
    if host_ip == "dns":
        return socket.getfqdn()
    try:
        ip = socket.gethostbyname(socket.getfqdn())
    except socket.gaierror:
        ip = socket.gethostbyname(socket.gethostname())
    if ip.startswith("127."):
        probe = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            probe.connect(("10.255.255.255", 1))  # no traffic sent
            ip = probe.getsockname()[0]
        except OSError:
            ip = "127.0.0.1"
        finally:
            probe.close()
    return ip


def bind_free_port(host: str, port_start: int = 9091, port_end: int = 9999
                   ) -> socket.socket:
    """Bind a listening socket on the first free port in the scan range
    (reference tracker.py:141-153)."""
    sock = socket.socket(addr_family(host), socket.SOCK_STREAM)
    for port in range(port_start, port_end):
        try:
            sock.bind((host, port))
            return sock
        except OSError:
            continue
    raise OSError(f"no free port in [{port_start}, {port_end})")
