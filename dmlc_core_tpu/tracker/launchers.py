"""Cluster launch backends for dmlc-submit.

Rebuild of reference tracker/dmlc_tracker/{local,ssh,mpi,sge,slurm}.py plus
the new TPU-native `tpu-pod` backend (SURVEY §7 step 6, BASELINE.md north
star). Every backend is split into a pure command-builder (unit-testable
without a cluster) and a `submit(args)` that wires it into the rendezvous
tracker via run_job.

Env-var protocol carried to every worker (the de-facto ABI, SURVEY §2.4):
DMLC_TRACKER_URI/PORT, DMLC_NUM_WORKER/SERVER, DMLC_ROLE, DMLC_TASK_ID,
DMLC_JOB_CLUSTER, DMLC_NUM_ATTEMPT, DMLC_PS_ROOT_URI/PORT, DMLC_NODE_HOST,
DMLC_INTERFACE. The tpu-pod backend adds the JAX distributed trio
(JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID) so workers
can `jax.distributed.initialize()` with no arguments.
"""

from __future__ import annotations

import logging
import os
import subprocess
import sys
import threading
from typing import Dict, List, Optional, Sequence, Tuple

from dmlc_core_tpu.tracker import rendezvous

logger = logging.getLogger("dmlc_core_tpu.tracker")

PASSTHROUGH_ENV_KEYS = [
    # reference ssh.py get_env keys
    "OMP_NUM_THREADS", "KMP_AFFINITY", "LD_LIBRARY_PATH",
    "AWS_ACCESS_KEY_ID", "AWS_SECRET_ACCESS_KEY", "DMLC_INTERFACE",
    # TPU additions
    "JAX_PLATFORMS", "TPU_WORKER_ID", "PYTHONPATH",
    # liveness knobs (doc/robustness.md): workers read these to open the
    # heartbeat channel; the tracker's worker_envs() also exports them,
    # but env-launched trackers (standalone/ssh) rely on pass-through
    "DMLC_TRACKER_HEARTBEAT_MS", "DMLC_TRACKER_DEAD_AFTER_MS",
    "DMLC_TRACKER_RECOVER_GRACE_MS", "DMLC_TRACKER_CLIENT_TIMEOUT",
]


def parse_host_file(path: str) -> List[Tuple[str, str]]:
    """Parse a host file into (host, ssh_port) pairs. Accepts `ip`,
    `ip:port`, and mpi-style `ip slots=N` lines (reference ssh.py:38-60)."""
    hosts: List[Tuple[str, str]] = []
    with open(path) as f:
        for raw in f:
            h = raw.strip()
            if not h or h.startswith("#"):
                continue
            i = h.find("slots=")
            if i != -1:
                h = h[:i].strip()
            port = "22"
            if ":" in h:
                h, port = h.rsplit(":", 1)
            hosts.append((h, port))
    if not hosts:
        raise ValueError(f"host file {path} contains no hosts")
    return hosts


def export_prefix(envs: Dict[str, object],
                  passthrough: Sequence[str] = PASSTHROUGH_ENV_KEYS) -> str:
    """`export K=V; ...` shell prefix (reference ssh.py get_env)."""
    parts = []
    for k in passthrough:
        v = os.getenv(k)
        if v is not None:
            parts.append(f"export {k}={v};")
    for k, v in envs.items():
        parts.append(f"export {k}={v};")
    return " ".join(parts)


def inline_env(envs: Dict[str, object]) -> str:
    """`K=V K=V` command prefix (reference slurm.py get_mpi_env)."""
    return " ".join(f"{k}={v}" for k, v in envs.items())


# -- local -------------------------------------------------------------------
def submit_local(args) -> None:
    """Local backend under WorkerSupervisor: worker exit is detected and
    the task relaunched under its old id (the restarted worker rejoins the
    tracker with cmd=recover) — AppMaster-style supervision instead of the
    reference's in-line retry loop (local.py:12-49). With liveness enabled
    the supervisor is wired to the tracker both ways: dead ranks trigger a
    proactive relaunch, exhausted attempts abort the job.

    ``--mesh`` switches supervision from per-task to per-WORLD
    (doc/robustness.md "Elastic mesh training"): a jax.distributed mesh
    cannot admit a single relaunched rank mid-flight, so any worker death
    aborts the tracker (max_attempts=0, no proactive relaunch) and
    run_job relaunches the whole world — fresh tracker + coordinator
    ports, every rank restarted together — resuming from the last
    committed job checkpoint."""
    from dmlc_core_tpu.tracker.supervisor import (WorkerSupervisor,
                                                  popen_start_fn)
    mesh = bool(getattr(args, "mesh", False))

    def launch(nworker: int, nserver: int, envs: Dict[str, object],
               tracker=None):
        sup = WorkerSupervisor(
            max_attempts=0 if mesh else args.num_attempt)
        for i in range(nworker + nserver):
            role = "worker" if i < nworker else "server"
            sup.add(i, role, popen_start_fn(args.command, role, i,
                                            dict(envs)))
        if tracker is not None:
            # mesh worlds never relaunch a single rank in place — the
            # supervisor's only job is fail-fast world teardown
            sup.attach_tracker(tracker,
                               proactive_relaunch=False if mesh else None)
        sup.launch()  # spawn errors raise here, in the submitting caller
        sup.watch_in_thread()
        # run_job invokes this stopper before a world relaunch so the old
        # attempt's surviving processes die before the new world binds
        return sup.stop

    rendezvous.run_job(args.num_workers, args.num_servers, launch,
                       host_ip=args.host_ip or "auto",
                       ps_cmd=" ".join(args.command),
                       mesh=mesh,
                       world_attempts=getattr(args, "world_attempts", None))


# -- ssh ---------------------------------------------------------------------
def build_ssh_commands(hosts: List[Tuple[str, str]], command: Sequence[str],
                       nworker: int, nserver: int, envs: Dict[str, object],
                       working_dir: str) -> List[str]:
    """One ssh command per host exporting the DMLC env before the worker
    command."""
    cmds = []
    for i in range(nworker + nserver):
        e = dict(envs)
        e["DMLC_ROLE"] = "server" if i < nserver else "worker"
        node, port = hosts[i % len(hosts)]
        e["DMLC_NODE_HOST"] = node
        inner = (export_prefix(e) + f" cd {working_dir}; " +
                 " ".join(command))
        cmds.append("ssh -o StrictHostKeyChecking=no " + node +
                    " -p " + port + " '" + inner + "'")
    return cmds


def submit_ssh(args) -> None:
    """cluster=ssh backend: spawn one ssh-launched worker per host-file entry."""
    hosts = parse_host_file(args.host_file)

    def launch(nworker: int, nserver: int, envs: Dict[str, object]) -> None:
        local_dir = os.getcwd() + "/"
        working_dir = local_dir
        if args.sync_dst_dir not in (None, "None"):
            working_dir = args.sync_dst_dir
            for node, port in hosts:  # rsync workdir (reference sync_dir)
                subprocess.check_call(
                    f'rsync -az --rsh="ssh -o StrictHostKeyChecking=no '
                    f'-p {port}" {local_dir} {node}:{working_dir}',
                    shell=True)
        for prog in build_ssh_commands(hosts, args.command, nworker, nserver,
                                       envs, working_dir):
            threading.Thread(
                target=lambda p=prog: subprocess.check_call(p, shell=True),
                daemon=True).start()

    rendezvous.run_job(args.num_workers, args.num_servers, launch,
                       host_ip=args.host_ip or "auto",
                       ps_cmd=" ".join(args.command))


# -- mpi ---------------------------------------------------------------------
def mpi_env_flags(envs: Dict[str, object], mpi_version_text: str) -> str:
    """-x K=V (OpenMPI) or -env K V (MPICH) flags (reference mpi.py:12-37)."""
    if "Open MPI" in mpi_version_text:
        return " ".join(f"-x {k}={v}" for k, v in envs.items())
    if "mpich" in mpi_version_text.lower():
        return " ".join(f"-env {k} {v}" for k, v in envs.items())
    raise RuntimeError("Unknown MPI version: " + mpi_version_text[:80])


def build_mpi_command(command: Sequence[str], n: int,
                      envs: Dict[str, object], mpi_version_text: str,
                      host_file: Optional[str] = None) -> str:
    """mpirun/mpiexec invocation carrying the DMLC env (OpenMPI -x / MPICH
    -env dialects)."""
    cmd = f"--hostfile {host_file} " if host_file else ""
    return (f"mpirun -n {n} {mpi_env_flags(envs, mpi_version_text)} "
            f"{cmd}{' '.join(command)}")


def submit_mpi(args) -> None:
    """cluster=mpi backend: run the job under mpirun against the rendezvous
    tracker."""
    out, _ = subprocess.Popen(["mpirun", "--version"],
                              stdout=subprocess.PIPE,
                              stderr=subprocess.PIPE).communicate()
    version = out.decode(errors="replace")

    def launch(nworker: int, nserver: int, envs: Dict[str, object]) -> None:
        envs = dict(envs, DMLC_JOB_CLUSTER="mpi")
        for role, n in (("worker", nworker), ("server", nserver)):
            if n == 0:
                continue
            prog = build_mpi_command(args.command, n,
                                     dict(envs, DMLC_ROLE=role), version,
                                     args.host_file)
            threading.Thread(
                target=lambda p=prog: subprocess.check_call(p, shell=True),
                daemon=True).start()

    rendezvous.run_job(args.num_workers, args.num_servers, launch,
                       host_ip=args.host_ip or "auto",
                       ps_cmd=" ".join(args.command))


# -- sge ---------------------------------------------------------------------
def build_sge_script() -> str:
    # the in-container bootstrap derives DMLC_ROLE from DMLC_TASK_ID for
    # array jobs (reference launcher.py:44-49) before exec'ing the command.
    # SGE_TASK_ID is 1-based (qsub -t 1-N); DMLC_TASK_ID is 0-based
    # everywhere else in this tracker, so shift here.
    """SGE array-job script body; $SGE_TASK_ID maps to DMLC_TASK_ID."""
    return ("source ~/.bashrc\n"
            "export DMLC_TASK_ID=$((SGE_TASK_ID - 1))\n"
            "export DMLC_JOB_CLUSTER=sge\n"
            'python3 -m dmlc_core_tpu.tracker.bootstrap "$@"\n')


def build_sge_command(args, ntask: int, envs: Dict[str, object],
                      runscript: str) -> str:
    """qsub invocation submitting the generated SGE array-job script."""
    env_arg = ",".join(f'{k}="{v}"' for k, v in envs.items())
    cmd = f"qsub -cwd -t 1-{ntask} -S /bin/bash"
    if args.queue != "default":
        cmd += f" -q {args.queue}"
    cmd += f" -N {args.jobname}"
    cmd += f" -e {args.log_dir} -o {args.log_dir}"
    cmd += f" -pe orte {args.vcores}"
    cmd += f" -v {env_arg},PATH=${{PATH}}:."
    cmd += f" {runscript} {' '.join(args.command)}"
    return cmd


def submit_sge(args) -> None:
    """cluster=sge backend: submit an array job per role via qsub."""
    if args.jobname is None:
        args.jobname = (f"dmlc{args.num_workers}." +
                        args.command[0].split("/")[-1])
    os.makedirs(args.log_dir, exist_ok=True)
    runscript = os.path.join(args.log_dir, "rundmlc.sh")
    with open(runscript, "w") as f:
        f.write(build_sge_script())

    def launch(nworker: int, nserver: int, envs: Dict[str, object]) -> None:
        cmd = build_sge_command(args, nworker + nserver, envs, runscript)
        logger.info("%s", cmd)
        subprocess.check_call(cmd, shell=True)

    rendezvous.run_job(args.num_workers, args.num_servers, launch,
                       host_ip=args.host_ip or "auto",
                       ps_cmd=" ".join(args.command))


# -- slurm -------------------------------------------------------------------
def build_slurm_command(command: Sequence[str], n: int, nodes: int,
                        envs: Dict[str, object]) -> str:
    """srun invocation carrying the DMLC env (one task per worker)."""
    return (f"{inline_env(envs)} srun --share --exclusive=user -N {nodes} "
            f"-n {n} {' '.join(command)}")


def submit_slurm(args) -> None:
    """cluster=slurm backend: srun workers against the rendezvous tracker."""
    def launch(nworker: int, nserver: int, envs: Dict[str, object]) -> None:
        envs = dict(envs, DMLC_JOB_CLUSTER="slurm")
        for role, n, nodes in (
                ("worker", nworker, args.slurm_worker_nodes or nworker),
                ("server", nserver, args.slurm_server_nodes or nserver)):
            if n == 0:
                continue
            prog = build_slurm_command(args.command, n, nodes,
                                       dict(envs, DMLC_ROLE=role))
            threading.Thread(
                target=lambda p=prog: subprocess.check_call(p, shell=True),
                daemon=True).start()

    rendezvous.run_job(args.num_workers, args.num_servers, launch,
                       host_ip=args.host_ip or "auto",
                       ps_cmd=" ".join(args.command))


# -- tpu-pod -----------------------------------------------------------------
def build_tpu_pod_env(host_index: int, hosts: List[Tuple[str, str]],
                      coordinator_port: int, envs: Dict[str, object]
                      ) -> Dict[str, object]:
    """Per-host env for a TPU pod slice: process_id = host index, coordinator
    = host 0. Workers call jax.distributed.initialize() with no args (or
    dmlc_core_tpu.parallel.init_from_env) and shard input with
    InputSplit(part=JAX_PROCESS_ID, nsplit=JAX_NUM_PROCESSES) — the
    TPU-native replacement for the Rabit socket rendezvous (SURVEY §5)."""
    e = dict(envs)
    e["DMLC_ROLE"] = "worker"
    e["DMLC_TASK_ID"] = host_index
    e["DMLC_JOB_CLUSTER"] = "tpu-pod"
    e["DMLC_NODE_HOST"] = hosts[host_index][0]
    e["JAX_COORDINATOR_ADDRESS"] = f"{hosts[0][0]}:{coordinator_port}"
    e["JAX_NUM_PROCESSES"] = len(hosts)
    e["JAX_PROCESS_ID"] = host_index
    return e


def build_tpu_pod_commands(hosts: List[Tuple[str, str]],
                           command: Sequence[str],
                           envs: Dict[str, object],
                           coordinator_port: int = 8476,
                           working_dir: str = ".") -> List[str]:
    """Per-host launch commands for a TPU pod slice (local exec or ssh), env
    from build_tpu_pod_env."""
    cmds = []
    for i, (node, port) in enumerate(hosts):
        e = build_tpu_pod_env(i, hosts, coordinator_port, envs)
        inner = (export_prefix(e) + f" cd {working_dir}; " +
                 " ".join(command))
        if node in ("localhost", "127.0.0.1") and port == "local":
            cmds.append(inner)
        else:
            cmds.append("ssh -o StrictHostKeyChecking=no " + node +
                        " -p " + port + " '" + inner + "'")
    return cmds


def submit_tpu_pod(args) -> None:
    """Launch one process per pod-slice host; no socket tracker is needed —
    JAX's coordination service (host 0) is the rendezvous."""
    if args.host_file:
        hosts = parse_host_file(args.host_file)
        if args.num_workers and args.num_workers != len(hosts):
            raise SystemExit(
                f"tpu-pod: --num-workers={args.num_workers} does not match "
                f"{len(hosts)} hosts in {args.host_file} (one process per "
                f"pod-slice host)")
    else:
        # single-host slice (or local simulation): spawn workers locally
        hosts = [("localhost", "local")] * args.num_workers
    working_dir = args.sync_dst_dir or os.getcwd()
    if args.sync_dst_dir not in (None, "None") and args.host_file:
        local_dir = os.getcwd() + "/"
        for node, port in hosts:  # ship the workdir like submit_ssh
            subprocess.check_call(
                f'rsync -az --rsh="ssh -o StrictHostKeyChecking=no '
                f'-p {port}" {local_dir} {node}:{working_dir}',
                shell=True)
    envs = {"DMLC_NUM_WORKER": len(hosts), "DMLC_NUM_SERVER": 0}
    cmds = build_tpu_pod_commands(hosts, args.command, envs,
                                  args.coordinator_port, working_dir)
    threads = []
    for i, prog in enumerate(cmds):
        # localhost simulation needs per-process env rather than ssh export
        t = threading.Thread(
            target=lambda p=prog: subprocess.check_call(
                p, shell=True, executable="/bin/bash"),
            daemon=True)
        t.start()
        threads.append(t)
    for t in threads:
        t.join()


# -- kubernetes --------------------------------------------------------------
def build_kube_manifest(args, role: str, count: int,
                        envs: Dict[str, object]) -> Dict[str, object]:
    """One indexed Job per role (reference kubernetes.py submits a
    manifest-template job per role). Emitted as a JSON-compatible dict —
    kubectl accepts JSON manifests, so no yaml dependency is needed. The
    DMLC_TASK_ID comes from the pod's completion index; TPU pods add
    google.com/tpu resources + the GKE tpu nodeSelector pair."""
    image = (args.kube_worker_image if role == "worker"
             else args.kube_server_image)
    mem = (args.worker_memory_mb if role == "worker"
           else args.server_memory_mb)
    cores = args.worker_cores if role == "worker" else args.server_cores
    env_list = [{"name": k, "value": str(v)} for k, v in envs.items()]
    env_list += [
        {"name": "DMLC_ROLE", "value": role},
        {"name": "DMLC_JOB_CLUSTER", "value": "kubernetes"},
        {"name": "DMLC_TASK_ID",
         "valueFrom": {"fieldRef": {
             "fieldPath":
                 "metadata.annotations['batch.kubernetes.io/job-completion-index']"}}},
    ]
    resources: Dict[str, object] = {
        "requests": {"memory": f"{mem}Mi", "cpu": str(cores)},
        "limits": {"memory": f"{mem}Mi"},
    }
    spec: Dict[str, object] = {
        "containers": [{
            "name": f"dmlc-{role}",
            "image": image,
            "command": list(args.command),
            "env": env_list,
            "resources": resources,
        }],
        "restartPolicy": "Never",
    }
    if args.kube_tpu_type:
        # chip count is independent of the cpu request: explicit flag, else
        # the product of the topology dims (2x4 -> 8)
        chips = args.kube_tpu_chips
        if chips is None and args.kube_tpu_topology:
            dims = args.kube_tpu_topology.lower().split("x")
            chips = 1
            for d in dims:
                chips *= int(d)
        if chips is None:
            raise SystemExit(
                "kubernetes: pass --kube-tpu-chips or --kube-tpu-topology "
                "with --kube-tpu-type")
        resources["limits"] = dict(resources["limits"],
                                   **{"google.com/tpu": str(chips)})
        resources["requests"] = dict(resources["requests"],
                                     **{"google.com/tpu": str(chips)})
        selector = {"cloud.google.com/gke-tpu-accelerator": args.kube_tpu_type}
        if args.kube_tpu_topology:
            selector["cloud.google.com/gke-tpu-topology"] = \
                args.kube_tpu_topology
        spec["nodeSelector"] = selector
    return {
        "apiVersion": "batch/v1",
        "kind": "Job",
        "metadata": {
            "name": f"{args.jobname}-{role}",
            "namespace": args.kube_namespace,
            "labels": {"app": "dmlc", "dmlc-job": args.jobname},
        },
        "spec": {
            "completions": count,
            "parallelism": count,
            "completionMode": "Indexed",
            "backoffLimit": max(int(args.num_attempt), 0) * count,
            "template": {
                "metadata": {"labels": {"app": "dmlc",
                                        "dmlc-job": args.jobname,
                                        "dmlc-role": role}},
                "spec": spec,
            },
        },
    }


def submit_kubernetes(args) -> None:
    """Reference tracker/dmlc_tracker/kubernetes.py: template a Job per role
    and submit; the rendezvous tracker runs here and pods dial back via
    DMLC_TRACKER_URI (which must be reachable from the cluster — pass
    --host-ip)."""
    import json

    if args.jobname is None:
        args.jobname = f"dmlc-{args.command[0].split('/')[-1]}"
    args.jobname = args.jobname.replace("_", "-").replace(".", "-").lower()

    def launch(nworker: int, nserver: int, envs: Dict[str, object]) -> None:
        manifests = []
        if nserver:
            manifests.append(build_kube_manifest(args, "server", nserver,
                                                 envs))
        if nworker:
            manifests.append(build_kube_manifest(args, "worker", nworker,
                                                 envs))
        payload = json.dumps({"apiVersion": "v1", "kind": "List",
                              "items": manifests}, indent=2)
        if args.kube_dry_run:
            print(payload)
            return
        # supervised submission (AppMaster parity): each role Job is a
        # CommandTask — failed Jobs are deleted + re-applied up to
        # --num-attempt times; restarted pods rejoin via cmd=recover
        from dmlc_core_tpu.tracker.supervisor import (CommandTask,
                                                      WorkerSupervisor)
        kubectl = getattr(args, "kubectl", None) or "kubectl"
        # CLI-polled supervision: each poll execs `kubectl get` against the
        # API server, and Job state changes on minute timescales — poll
        # seconds apart, not the local-Popen default
        sup = WorkerSupervisor(max_attempts=args.num_attempt,
                               poll_interval=5.0)
        for i, m in enumerate(manifests):
            name = m["metadata"]["name"]
            one = json.dumps(m, indent=2)
            # emit every condition as "Type=Status" — Complete/Failed may
            # not be conditions[0] (k8s appends SuccessCriteriaMet /
            # FailureTarget first on recent versions)
            status_path = ("jsonpath={range .status.conditions[*]}"
                           "{.type}={.status} {end}")

            def start(attempt, one=one, name=name):
                if attempt > 0:  # tear down the failed incarnation first
                    subprocess.run([kubectl, "delete", "job", name,
                                    "--ignore-not-found=true"],
                                   capture_output=True)
                return CommandTask(
                    submit_cmd=[kubectl, "apply", "-f", "-"],
                    submit_input=one,
                    status_cmd=[kubectl, "get", "job", name, "-o",
                                status_path],
                    succeeded_text="Complete=True",
                    failed_text="Failed=True",
                    delete_cmd=[kubectl, "delete", "job", name,
                                "--ignore-not-found=true"])

            role = m["spec"]["template"]["metadata"]["labels"]["dmlc-role"]
            sup.add(i, role, start)
        sup.launch()  # submission errors (RBAC, kubeconfig) raise here
        sup.watch_in_thread()

    if args.kube_dry_run:
        # no tracker: render manifests with placeholder rendezvous env and
        # return immediately (nothing listens, nothing leaks)
        launch(args.num_workers, args.num_servers, {
            "DMLC_TRACKER_URI": args.host_ip or "<tracker-host>",
            "DMLC_TRACKER_PORT": 9091,
            "DMLC_NUM_WORKER": args.num_workers,
            "DMLC_NUM_SERVER": args.num_servers,
        })
        return

    rendezvous.run_job(args.num_workers, args.num_servers, launch,
                       host_ip=args.host_ip or "auto",
                       ps_cmd=" ".join(args.command))


# -- yarn --------------------------------------------------------------------
def build_yarn_command(args, role: str, n: int,
                       envs: Dict[str, object],
                       attempt: int = 0) -> List[str]:
    """Reference yarn.py ships a Java AppMaster jar (tracker/yarn/) that
    allocates one container per task and restarts failed tasks. This build
    has no Java component; the same contract is expressed as one `yarn jar
    <distributed-shell>` submission *per role* (like the mpi/slurm backends)
    carrying the DMLC_* env protocol, with container count/memory/cores
    mapped onto -num_containers/-container_*. The attempt number is baked
    into -appname so supervision status for a relaunch never reads the
    previous incarnation's retained FINISHED/FAILED record (YARN keeps
    completed apps in `-list -appStates ALL`)."""
    e = dict(envs)
    e["DMLC_ROLE"] = role
    e["DMLC_JOB_CLUSTER"] = "yarn"
    e["DMLC_NUM_ATTEMPT"] = attempt
    if getattr(args, "archives", None):
        e["DMLC_JOB_ARCHIVES"] = ":".join(args.archives)
    shell_env = []
    for k, v in e.items():
        shell_env += ["-shell_env", f"{k}={v}"]
    mem = args.worker_memory_mb if role == "worker" else args.server_memory_mb
    cores = args.worker_cores if role == "worker" else args.server_cores
    jar = os.getenv("DMLC_YARN_SHELL_JAR",
                    "hadoop-yarn-applications-distributedshell.jar")
    cmd = [os.getenv("DMLC_YARN_BIN", "yarn"), "jar", jar,
           "-jar", jar,
           "-appname", f"{args.jobname or 'dmlc-job'}-{role}-a{attempt}",
           "-num_containers", str(n),
           "-container_memory", str(mem),
           "-container_vcores", str(cores)]
    cmd += shell_env
    # bootstrap extends LD_LIBRARY_PATH/CLASSPATH from HADOOP_HOME and
    # unpacks DMLC_JOB_ARCHIVES inside the container (reference launcher.py)
    cmd += ["-shell_command",
            "python3 -m dmlc_core_tpu.tracker.bootstrap " +
            " ".join(args.command)]
    return cmd


def submit_yarn(args) -> None:
    """Supervised submission (AppMaster parity, mirroring the kubernetes
    path): each role is a CommandTask — the distributedshell client runs
    async in the foreground, application state is polled from
    `yarn application -list` filtered to this app's name, and a FAILED
    final state kills + resubmits up to --num-attempt times."""
    from dmlc_core_tpu.tracker.supervisor import CommandTask, WorkerSupervisor

    ybin = os.getenv("DMLC_YARN_BIN", "yarn")

    def kill_cmd_for(name: str) -> List[str]:
        # real YARN kills by application id; resolve it from the list
        # output by app name (column 2) at kill time
        return ["bash", "-lc",
                f"id=$({ybin} application -list -appStates ALL 2>/dev/null"
                f" | awk -v n='{name}' '$2==n {{print $1; exit}}');"
                f" [ -n \"$id\" ] && {ybin} application -kill \"$id\""
                f" || true"]

    def launch(nworker: int, nserver: int, envs: Dict[str, object]) -> None:
        sup = WorkerSupervisor(max_attempts=args.num_attempt,
                               poll_interval=5.0)
        roles = [(r, n) for r, n in (("server", nserver),
                                     ("worker", nworker)) if n]
        base = args.jobname or "dmlc-job"
        for i, (role, n) in enumerate(roles):

            def start(attempt, role=role, n=n):
                # the per-attempt -appname means a relaunch polls ONLY its
                # own application; the failed incarnation was already torn
                # down by the supervisor's terminate() (delete_cmd below)
                name = f"{base}-{role}-a{attempt}"
                cmd = build_yarn_command(args, role, n, envs, attempt)
                logger.info("%s", " ".join(cmd))
                return CommandTask(
                    submit_cmd=cmd,
                    status_cmd=[ybin, "application", "-list",
                                "-appStates", "ALL"],
                    status_filter=name,
                    succeeded_text="SUCCEEDED", failed_text="FAILED",
                    delete_cmd=kill_cmd_for(name),
                    submit_async=True)

            sup.add(i, role, start)
        sup.launch()
        sup.watch_in_thread()

    rendezvous.run_job(args.num_workers, args.num_servers, launch,
                       host_ip=args.host_ip or "auto",
                       ps_cmd=" ".join(args.command))


# -- mesos -------------------------------------------------------------------
def build_mesos_command(args, role: str, n: int,
                        envs: Dict[str, object],
                        attempt: int = 0) -> List[str]:
    """Reference mesos.py registers a framework that launches one task per
    worker/server; expressed here as `mesos-execute` task groups against
    --mesos-master with the env protocol inlined. The attempt number is
    baked into the task name so each incarnation's status is observable
    independently in the master's /tasks feed (mesos task names are not
    unique; a relaunch must not read its predecessor's FAILED record)."""
    e = dict(envs)
    e["DMLC_ROLE"] = role
    e["DMLC_JOB_CLUSTER"] = "mesos"
    e["DMLC_NUM_ATTEMPT"] = attempt
    mem = args.worker_memory_mb if role == "worker" else args.server_memory_mb
    cores = args.worker_cores if role == "worker" else args.server_cores
    master = args.mesos_master or os.getenv("MESOS_MASTER")
    if not master:
        raise SystemExit("mesos: pass --mesos-master or set MESOS_MASTER")
    return [os.getenv("DMLC_MESOS_EXECUTE", "mesos-execute"),
            f"--master={master}",
            f"--name=dmlc-{role}-a{attempt}",
            f"--instances={n}",
            f"--resources=cpus:{cores};mem:{mem}",
            "--command=" + inline_env(e) + " " + " ".join(args.command)]


def submit_mesos(args) -> None:
    """Supervised submission: mesos-execute owns the framework and stays in
    the foreground, so it runs async under a CommandTask whose status is
    the master's /tasks REST feed (tracker/mesos_status.py), normalized to
    SUCCEEDED/FAILED; a failed incarnation's client is torn down and the
    group resubmitted under the next attempt's task name."""
    from dmlc_core_tpu.tracker.supervisor import CommandTask, WorkerSupervisor

    master = args.mesos_master or os.getenv("MESOS_MASTER")

    def launch(nworker: int, nserver: int, envs: Dict[str, object]) -> None:
        sup = WorkerSupervisor(max_attempts=args.num_attempt,
                               poll_interval=5.0)
        roles = [(r, n) for r, n in (("server", nserver),
                                     ("worker", nworker)) if n]
        for i, (role, n) in enumerate(roles):

            def start(attempt, role=role, n=n):
                cmd = build_mesos_command(args, role, n, envs, attempt)
                logger.info("%s", " ".join(cmd))
                return CommandTask(
                    submit_cmd=cmd,
                    status_cmd=[sys.executable, "-m",
                                "dmlc_core_tpu.tracker.mesos_status",
                                str(master), f"dmlc-{role}-a{attempt}"],
                    succeeded_text="SUCCEEDED", failed_text="FAILED",
                    submit_async=True)

            sup.add(i, role, start)
        sup.launch()
        sup.watch_in_thread()

    rendezvous.run_job(args.num_workers, args.num_servers, launch,
                       host_ip=args.host_ip or "auto",
                       ps_cmd=" ".join(args.command))


BACKENDS = {
    "local": submit_local,
    "ssh": submit_ssh,
    "mpi": submit_mpi,
    "sge": submit_sge,
    "slurm": submit_slurm,
    "tpu-pod": submit_tpu_pod,
    "kubernetes": submit_kubernetes,
    "yarn": submit_yarn,
    "mesos": submit_mesos,
}
