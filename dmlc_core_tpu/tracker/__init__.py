"""Distributed launch layer (reference tracker/dmlc_tracker).

dmlc-submit CLI + cluster backends (local/ssh/mpi/sge/slurm/tpu-pod), the
rabit-compatible rendezvous tracker, and a worker-side client.
"""

from dmlc_core_tpu.tracker.rendezvous import (PSTracker, RabitTracker,
                                              run_job,
                                              start_standalone_tracker)
from dmlc_core_tpu.tracker.client import RendezvousClient

__all__ = ["RabitTracker", "PSTracker", "run_job",
           "start_standalone_tracker", "RendezvousClient"]
