"""In-container bootstrap: set up the environment, then exec the user job.

Counterpart of reference tracker/dmlc_tracker/launcher.py:12-80 — the
script a cluster backend runs *inside* the allocated container before the
user command: derive the role on role-less schedulers (sge), extend
LD_LIBRARY_PATH/CLASSPATH for Hadoop-linked binaries, unzip shipped
archives, then exec. Extended for the TPU path: when the launcher exported
the JAX coordination trio (JAX_COORDINATOR_ADDRESS et al.) it is passed
through untouched so the job's `init_from_env` finds it.

Run as: python -m dmlc_core_tpu.tracker.bootstrap <cmd> [args...]
"""

from __future__ import annotations

import glob
import os
import subprocess
import sys
from typing import Dict, List


def unzip_archives(archives: List[str], env: Dict[str, str],
                   runner=subprocess.call) -> None:
    """Unpack .zip/.tar* files shipped with the job (launcher.py:12-19)."""
    for fname in archives:
        if not os.path.exists(fname):
            continue
        if fname.endswith(".zip"):
            runner(["unzip", "-o", fname], env=env)
        elif ".tar" in fname:
            runner(["tar", "-xf", fname], env=env)


def build_env(base: Dict[str, str],
              classpath_runner=None) -> Dict[str, str]:
    """Compute the job environment from the launcher's exports.

    Mirrors launcher.py: sge role derivation (:44-49), hadoop/java
    library+class paths (:51-63), LIBHDFS_OPTS default (:67-71),
    LD_LIBRARY_PATH extension (:73-74).
    """
    env = dict(base)
    from dmlc_core_tpu.tracker.wire import env_enum, env_int
    # a typo'd backend name must fail here too, not select nothing
    cluster = env_enum("DMLC_JOB_CLUSTER",
                       ("local", "ssh", "mpi", "sge", "slurm", "tpu-pod",
                        "kubernetes", "yarn", "mesos"), env=env)
    if cluster is None:
        raise RuntimeError("need DMLC_JOB_CLUSTER in the environment")

    # liveness + elastic data-plane knobs (doc/robustness.md) ride the
    # same env ABI; a typo'd value must fail HERE, in the container
    # bootstrap, not silently disable the heartbeat (or the lease TTL)
    # and let the job hang the old way
    for key in ("DMLC_TRACKER_HEARTBEAT_MS", "DMLC_TRACKER_DEAD_AFTER_MS",
                "DMLC_TRACKER_RECOVER_GRACE_MS", "DMLC_TRACKER_NUM_SHARDS",
                "DMLC_TRACKER_LEASE_TTL_MS", "DMLC_ELASTIC_SHARDS"):
        if env.get(key):
            env_int(key, 0, env=env)  # raises RuntimeError on garbage

    if cluster == "sge" and "DMLC_TASK_ID" in env:
        # array jobs carry no role: first num_worker tasks are workers
        num_worker = env_int("DMLC_NUM_WORKER", 0, env=env)
        task_id = env_int("DMLC_TASK_ID", 0, env=env)
        env["DMLC_ROLE"] = "worker" if task_id < num_worker else "server"

    hadoop_home = env.get("HADOOP_HOME") or env.get("HADOOP_PREFIX")
    hdfs_home = env.get("HADOOP_HDFS_HOME")
    java_home = env.get("JAVA_HOME")

    library_path = ["./"]
    class_path: List[str] = []
    if hdfs_home:
        library_path.append(f"{hdfs_home}/lib/native")
        library_path.append(f"{hdfs_home}/lib")
    if hadoop_home:
        # classpath expansion needs only the hadoop CLI (reference
        # launcher.py gates it on HADOOP_HOME alone)
        if classpath_runner is None:
            def classpath_runner(cmd):  # pragma: no cover - needs hadoop
                return subprocess.run(cmd, shell=True, capture_output=True,
                                      text=True).stdout
        raw = classpath_runner(f"{hadoop_home}/bin/hadoop classpath")
        for part in (raw or "").strip().split(":"):
            class_path += glob.glob(part) if part else []
    if java_home:
        library_path.append(f"{java_home}/jre/lib/amd64/server")

    if class_path:
        prev = env.get("CLASSPATH", "")
        env["CLASSPATH"] = (prev + ":" if prev else "") + ":".join(class_path)

    if "DMLC_HDFS_OPTS" in env:
        env["LIBHDFS_OPTS"] = env["DMLC_HDFS_OPTS"]
    elif "LIBHDFS_OPTS" not in env:
        env["LIBHDFS_OPTS"] = "--Xmx128m"

    prev_ld = env.get("LD_LIBRARY_PATH", "")
    env["LD_LIBRARY_PATH"] = ((prev_ld + ":") if prev_ld else "") + \
        ":".join(library_path)
    return env


def main(argv: List[str] = None) -> int:
    """CLI entry: prepare the container env (archives, lib paths) and exec the
    user command."""
    argv = sys.argv[1:] if argv is None else argv
    if not argv:
        # nonzero so a launcher that interpolated an empty user command
        # fails loudly instead of "succeeding" without running anything
        print("Usage: python -m dmlc_core_tpu.tracker.bootstrap <cmd...>",
              file=sys.stderr)
        return 1
    env = build_env(dict(os.environ))
    if "DMLC_JOB_ARCHIVES" in env:
        unzip_archives(env["DMLC_JOB_ARCHIVES"].split(":"), env)
    return subprocess.call(argv, env=env)


if __name__ == "__main__":
    sys.exit(main())
