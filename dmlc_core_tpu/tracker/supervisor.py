"""Worker supervision: detect worker exit, relaunch under the old rank.

reference parity (VERDICT r1 item 5): the YARN AppMaster restarts failed
containers (reference tracker/yarn/src/main/java/.../ApplicationMaster.java)
and the rabit tracker re-links a restarted worker that reconnects with
cmd=recover under its old rank (reference tracker/dmlc_tracker/
tracker.py:312-316). dmlc-core's other launchers only retried a locally
spawned process in-line (local.py:12-49); nothing watched remote workers.

Here supervision is backend-agnostic. A task is (task_id, role,
start(attempt) -> handle) where a handle speaks the tiny Popen-like
protocol `poll() -> Optional[int]` / `terminate()`:

- local: the handle IS a subprocess.Popen
- kubernetes / yarn: `CommandTask` wraps the backend CLI — submit command
  to (re)launch, status command polled for exit (kubectl/yarn CLIs), so
  the same loop supervises containers it cannot signal directly

The supervisor relaunches a failed task with an incremented attempt number
(exported as DMLC_NUM_ATTEMPT, the reference env ABI) up to max_attempts;
the restarted worker is expected to rejoin the rendezvous with
cmd=recover + its old rank (dmlc_core_tpu/tracker/client.py `start(
recover=True)`), which the tracker re-links without disturbing the rest of
the job (tested in tests/test_tracker.py).

Liveness integration (doc/robustness.md "Distributed job liveness") is
two-way via `attach_tracker`:

- tracker -> supervisor: the tracker's dead-rank notification triggers a
  PROACTIVE relaunch — a segfaulted container whose CLI status lags is
  restarted from the heartbeat signal, not the slow poll;
- supervisor -> tracker: a task that exhausts max_attempts tells the
  tracker to abort the job instead of leaving it waiting forever on a
  rank that will never return.
"""

from __future__ import annotations

import logging
import subprocess
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence

logger = logging.getLogger("dmlc_core_tpu.tracker")

__all__ = ["WorkerSupervisor", "CommandTask"]


@dataclass
class _TaskState:
    task_id: int
    role: str
    start: Callable[[int], object]  # attempt -> handle
    attempt: int = 0
    handle: object = None
    done: bool = False
    # monotonic launch time of the CURRENT incarnation — lets the
    # dead-rank callback tell "this incarnation is the dead one" from
    # "the dead one was already replaced" (see _on_rank_dead)
    started_at: Optional[float] = None


class WorkerSupervisor:
    """Watches worker handles; relaunches nonzero exits up to max_attempts.

    Usage::

        sup = WorkerSupervisor(max_attempts=2)
        sup.add(task_id=0, role="worker", start=make_start_fn(0))
        sup.add(task_id=1, role="worker", start=make_start_fn(1))
        sup.run()   # blocks; raises if any task exhausts its attempts
    """

    def __init__(self, max_attempts: int = 2, poll_interval: float = 0.05):
        self.max_attempts = max_attempts
        self.poll_interval = poll_interval
        self._tasks: List[_TaskState] = []
        self._stop = threading.Event()
        # (task_id, attempt, returncode) log of observed failures — lets
        # tests and callers audit the restart history (returncode is None
        # when the restart came from a tracker dead-rank signal whose CLI
        # status had not caught up yet)
        self.failures: List[tuple] = []
        # task mutation happens on the watch thread AND the tracker's
        # dead-rank notifier thread once attach_tracker is used
        self._lock = threading.Lock()
        self._tracker = None
        self._proactive_relaunch: Optional[bool] = None
        self._rank_to_task: Callable[[int], int] = lambda rank: rank

    def add(self, task_id: int, role: str,
            start: Callable[[int], object]) -> None:
        """Register a task: (task_id, role, start(attempt) -> handle)."""
        self._tasks.append(_TaskState(task_id, role, start))

    def attach_tracker(self, tracker,
                       rank_to_task: Optional[Callable[[int], int]] = None,
                       proactive_relaunch: Optional[bool] = None) -> None:
        """Wire liveness both ways with a RabitTracker: subscribe to its
        dead-rank notifications for proactive relaunch, and report
        attempt exhaustion back as a job abort.

        The dead rank is mapped to a task by, in order: the task id the
        worker itself reported on the wire (RendezvousClient defaults
        its jobid to "task<DMLC_TASK_ID>", carried in the notification
        as info["task_id"] — authoritative, since ranks are assigned by
        host-sorted arrival and need NOT equal task ids), then
        `rank_to_task` (default: identity) for legacy workers that
        report no jobid.

        `proactive_relaunch=None` (default) relaunches on a dead-rank
        signal UNLESS the tracker runs the elastic data-plane — there the
        dead rank's shard leases migrate to the survivors and the epoch
        completes without the replacement, so a relaunch is optional
        capacity restoration, not a liveness requirement. Pass True/False
        to override either way (the watch loop's relaunch of nonzero
        exits is unaffected)."""
        self._tracker = tracker
        self._proactive_relaunch = proactive_relaunch
        if rank_to_task is not None:
            self._rank_to_task = rank_to_task
        tracker.on_rank_dead(self._on_rank_dead)

    def _find(self, task_id: int) -> Optional[_TaskState]:
        for t in self._tasks:
            if t.task_id == task_id:
                return t
        return None

    def _abort_tracker(self, reason: str) -> None:
        if self._tracker is not None:
            try:
                self._tracker.abort(reason)
            except Exception:
                logger.exception("tracker abort failed")

    def _relaunch_locked(self, t: _TaskState, rc, why: str) -> bool:
        """Record the failure and relaunch `t` under the next attempt
        (caller holds self._lock). Returns False when max_attempts is
        exhausted: supervision stops and the tracker is told to abort
        instead of waiting forever on the rank. The single copy of the
        restart bookkeeping shared by the status-poll path (watch) and
        the dead-rank-signal path (_on_rank_dead)."""
        self.failures.append((t.task_id, t.attempt, rc))
        t.attempt += 1
        if t.attempt > self.max_attempts:
            self._stop_locked()
            self._abort_tracker(
                f"task {t.task_id} ({t.role}) exhausted {t.attempt} "
                f"attempts ({why})")
            return False
        # tear the failed incarnation down before resubmitting — remote
        # backends may still have live pieces (a surviving container of a
        # partially-failed group, a foreground mesos-execute client); a
        # dead local Popen ignores it
        try:
            t.handle.terminate()
        except Exception:
            pass
        logger.warning("task %d (%s) %s; relaunching (attempt %d)",
                       t.task_id, t.role, why, t.attempt)
        t.handle = t.start(t.attempt)
        t.started_at = time.monotonic()
        return True

    def _on_rank_dead(self, rank: int, info: Dict[str, object]) -> None:
        """Tracker liveness callback: relaunch the dead rank's task NOW —
        ahead of the (possibly minutes-slow) status poll."""
        proactive = self._proactive_relaunch
        if proactive is None:
            # elastic tracker: the dead rank's leases migrate after the
            # grace window — the job completes without the relaunch
            proactive = not getattr(self._tracker, "elastic", False)
        if not proactive:
            logger.info(
                "rank %d dead signal: proactive relaunch skipped (elastic "
                "data-plane — leases migrate to the survivors)", rank)
            return
        task_id = info.get("task_id")  # wire-reported: authoritative
        if not isinstance(task_id, int):
            try:
                task_id = self._rank_to_task(rank)
            except Exception:
                logger.exception("rank_to_task mapping failed for rank %d",
                                 rank)
                return
        with self._lock:
            t = self._find(task_id)
            if t is None or t.done or self._stop.is_set():
                return
            # If the current incarnation was launched AFTER the dead
            # rank's last heartbeat, the dead incarnation is already
            # replaced (the watch loop's poll won the race) — relaunching
            # again would kill the healthy replacement mid-recover. A
            # CommandTask whose CLI status lags keeps its old started_at,
            # so the genuinely-dead case still relaunches.
            last_beat = info.get("last_beat_monotonic")
            if isinstance(last_beat, float) and t.started_at is not None \
                    and t.started_at > last_beat:
                logger.info(
                    "rank %d dead signal ignored: task %d already "
                    "relaunched since its last heartbeat", rank, t.task_id)
                return
            handle = t.handle
        # poll outside the lock (same rule as watch(): on CLI backends
        # this execs a status command that can hang)
        rc = None
        try:
            rc = handle.poll() if handle is not None else None
        except Exception:
            pass
        with self._lock:
            if t.done or self._stop.is_set() or t.handle is not handle:
                return  # resolved or replaced while we were polling
            try:
                # lock-ok: the relaunch must be atomic with the attempt
                # bookkeeping — two racing observers (watch poll + this
                # signal) would double-launch over one incarnation. The
                # tracker serve loop never takes the supervisor lock
                # (notifications arrive on the dedicated notifier thread),
                # so a slow submit delays supervision only.
                self._relaunch_locked(t, rc, f"rank {rank} marked dead")
            except Exception:
                logger.exception("proactive relaunch of task %d failed",
                                 t.task_id)
                # lock-ok: terminal teardown; serve loop never holds this
                # lock and abort() only sets a flag + wakes the self-pipe
                self._stop_locked()
                # lock-ok: abort() is flag-set + selector wake, not I/O
                self._abort_tracker(
                    f"relaunch of task {t.task_id} failed")

    def stop(self) -> None:
        """Stop watching and terminate every live handle."""
        # lock-ok: teardown must be atomic against a racing relaunch (a
        # handle replaced mid-stop would survive); the tracker serve loop
        # never holds the supervisor lock, so terminate()'s CLI exec can
        # delay only supervision, never the rendezvous
        with self._lock:
            self._stop_locked()

    def _stop_locked(self) -> None:
        self._stop.set()
        for t in self._tasks:
            if t.handle is not None and not t.done:
                try:
                    t.handle.terminate()
                except Exception:
                    pass

    def launch(self) -> None:
        """Start every task once, synchronously — submission errors (bad
        kubeconfig, missing binary, RBAC) raise in the CALLER, not in a
        background watch thread."""
        for t in self._tasks:
            t.handle = t.start(t.attempt)
            t.started_at = time.monotonic()

    def watch(self) -> None:
        """Poll launched handles until all complete; relaunch failures."""
        while not self._stop.is_set():
            all_done = True
            for t in self._tasks:
                with self._lock:
                    if t.done or self._stop.is_set():
                        continue
                    handle = t.handle
                # poll OUTSIDE the lock: on CLI backends it execs a
                # status command that can block for seconds (a hung
                # kubectl) — holding the lock would serialize stop() and
                # the tracker's dead-rank callback behind exactly the
                # slow poll the proactive path exists to bypass
                rc = handle.poll()
                with self._lock:
                    if t.done or self._stop.is_set():
                        continue
                    if t.handle is not handle:
                        # replaced meanwhile by a proactive relaunch; the
                        # rc belongs to the dead incarnation it already
                        # accounted for
                        all_done = False
                        continue
                    if rc is None:
                        all_done = False
                        continue
                    if rc == 0:
                        t.done = True
                        continue
                    # failed: relaunch under the same task id — the worker
                    # rejoins with cmd=recover and keeps its old rank.
                    # lock-ok: atomic with the attempt bookkeeping (the
                    # dead-rank signal path races this poll); the serve
                    # loop never takes the supervisor lock
                    if not self._relaunch_locked(
                            t, rc, f"exited with code {rc}"):
                        raise RuntimeError(
                            f"task {t.task_id} ({t.role}) failed with code "
                            f"{rc} after {t.attempt} attempts")
                    all_done = False
            if all_done:
                return
            time.sleep(self.poll_interval)

    def run(self) -> None:
        """launch() + watch() in the calling thread."""
        self.launch()
        self.watch()

    def watch_in_thread(self) -> threading.Thread:
        """watch() on a daemon thread; failures are LOGGED loudly (the
        caller is typically blocked in tracker.join(), so an exception in
        the thread would otherwise vanish silently)."""
        def _watch():
            try:
                self.watch()
            except Exception:
                logger.exception(
                    "worker supervision failed; the tracker may now wait "
                    "on workers that will never finish")

        th = threading.Thread(target=_watch, daemon=True)
        th.start()
        return th


class CommandTask:
    """Poll-by-CLI handle for backends whose workers are remote containers
    (kubernetes/yarn/mesos): `submit_cmd` (re)creates the task, `status_cmd`
    is polled and must exit 0 while running/succeeded-with-`succeeded_text`,
    and its stdout is matched against `succeeded_text` / `failed_text` to
    decide completion (the AppMaster's container-status watch, expressed
    over the backend CLI).

    `submit_async=True` launches the submit command without waiting — for
    clients that stay in the foreground while the application runs (the
    yarn distributedshell client, mesos-execute); a nonzero exit of that
    client counts as a failure signal, exit 0 is ignored (status text
    decides). `status_filter` restricts matching to output lines containing
    the filter, so list-style status commands (`yarn application -list`)
    only see this task's application."""

    def __init__(self, submit_cmd: Sequence[str], status_cmd: Sequence[str],
                 succeeded_text: str = "Succeeded",
                 failed_text: str = "Failed",
                 delete_cmd: Optional[Sequence[str]] = None,
                 submit_input: Optional[str] = None,
                 status_errors_tolerated: int = 3,
                 submit_async: bool = False,
                 status_filter: Optional[str] = None):
        self.status_cmd = list(status_cmd)
        self.succeeded_text = succeeded_text
        self.failed_text = failed_text
        self.delete_cmd = list(delete_cmd) if delete_cmd else None
        self.status_errors_tolerated = status_errors_tolerated
        self.status_filter = status_filter
        self._status_errors = 0
        self._proc: Optional[subprocess.Popen] = None
        if submit_async:
            self._proc = subprocess.Popen(
                list(submit_cmd), stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL)
        else:
            out = subprocess.run(list(submit_cmd), capture_output=True,
                                 input=submit_input,
                                 text=True)
            if out.returncode != 0:
                raise RuntimeError(
                    f"submission failed ({' '.join(submit_cmd)}): "
                    f"{out.stderr or out.stdout}")

    def poll(self) -> Optional[int]:
        """Popen-protocol status: None while running, 0 success, nonzero
        failure (status text first, then the async client's exit)."""
        out = subprocess.run(self.status_cmd, capture_output=True, text=True)
        if out.returncode != 0:
            # a transient CLI/API error must not restart a healthy task;
            # only a persistent inability to observe it counts as failure
            self._status_errors += 1
            if self._status_errors > self.status_errors_tolerated:
                logger.warning("status command failing persistently: %s",
                               out.stderr or out.stdout)
                return 1
            return None
        self._status_errors = 0
        text = (out.stdout or "") + (out.stderr or "")
        if self.status_filter is not None:
            text = "\n".join(line for line in text.splitlines()
                             if self.status_filter in line)
        if self.failed_text in text:
            return 1
        if self.succeeded_text in text:
            return 0
        # no verdict from status output: a foreground client that died
        # nonzero is the only remaining failure signal (its exit 0 just
        # means "submission done" for detach-style clients)
        if self._proc is not None:
            rc = self._proc.poll()
            if rc is not None and rc != 0:
                return rc
        return None  # still running

    def terminate(self) -> None:
        """Tear the task down: run delete_cmd and stop the async submit
        client."""
        if self.delete_cmd is not None:
            subprocess.run(self.delete_cmd, capture_output=True)
        if self._proc is not None and self._proc.poll() is None:
            try:
                self._proc.terminate()
            except Exception:
                pass


def popen_start_fn(command: Sequence[str], role: str, task_id: int,
                   envs: Dict[str, object],
                   base_env: Optional[Dict[str, str]] = None
                   ) -> Callable[[int], subprocess.Popen]:
    """start(attempt) factory for local subprocess workers, exporting the
    reference env ABI (DMLC_TASK_ID / DMLC_ROLE / DMLC_NUM_ATTEMPT)."""
    import os

    cmd = list(command)
    # executables in the cwd but not on PATH still launch (the reference
    # local launcher's './' normalization, local.py)
    if "/" not in cmd[0] and os.path.exists(cmd[0]):
        cmd[0] = "./" + cmd[0]

    def start(attempt: int) -> subprocess.Popen:
        env = dict(base_env if base_env is not None else os.environ)
        for k, v in envs.items():
            env[k] = str(v)
        env["DMLC_TASK_ID"] = str(task_id)
        env["DMLC_ROLE"] = role
        env["DMLC_NUM_ATTEMPT"] = str(attempt)
        env.setdefault("DMLC_JOB_CLUSTER", "local")
        return subprocess.Popen(" ".join(cmd), shell=True,
                                executable="/bin/bash", env=env)

    return start
