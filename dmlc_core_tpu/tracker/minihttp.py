"""Shared mini-HTTP plumbing for content-sniffed sockets.

Two surfaces in this repo speak HTTP off a raw ``selectors`` loop: the
tracker's read-only scrape endpoints on the rendezvous port
(:mod:`dmlc_core_tpu.tracker.rendezvous`) and the online scoring front
end (:mod:`dmlc_core_tpu.serving.frontend`). Both sniff the first four
bytes of a connection to tell an HTTP request from a binary worker
frame, both need the same bounded request-head discipline (a loud 431
instead of a silent drop when headers overflow, a 405 instead of an
"invalid magic" reject when a known-but-unsupported method arrives),
and both render the same minimal HTTP/1.1 responses. This module is
that shared plumbing — pure byte-level helpers, no sockets, no loop.
"""

import os
import re
from typing import Dict, Optional, Tuple

# Hard ceiling on request line + headers (the terminating CRLFCRLF
# included). Small on purpose: both surfaces serve machine clients that
# send one short request; anything larger is a bug or abuse and gets a
# 431 so the sender can SEE why it was cut off.
MAX_REQUEST_HEAD = 8192

# First four bytes of every RFC 9110 method as it appears on the wire
# ("GET " and "PUT " include the mandatory space). A match means the
# peer is speaking HTTP — even if the surface doesn't serve that method,
# the polite answer is a 405, not a binary-protocol reject.
_METHOD_SNIFF: Dict[bytes, str] = {
    b"GET ": "GET",
    b"POST": "POST",
    b"PUT ": "PUT",
    b"HEAD": "HEAD",
    b"DELE": "DELETE",
    b"OPTI": "OPTIONS",
    b"PATC": "PATCH",
    b"TRAC": "TRACE",
    b"CONN": "CONNECT",
}

# Reason phrases for every status these mini-servers emit.
REASONS: Dict[int, str] = {
    200: "OK",
    204: "No Content",
    400: "Bad Request",
    404: "Not Found",
    405: "Method Not Allowed",
    411: "Length Required",
    413: "Content Too Large",
    429: "Too Many Requests",
    431: "Request Header Fields Too Large",
    500: "Internal Server Error",
    503: "Service Unavailable",
}


class HttpError(Exception):
    """A request that must be answered with an error status.

    Raised by :func:`parse_head` (and by callers' own validation) with
    the status to send; the message becomes the response body so the
    client sees WHY it was rejected instead of a bare reset.
    """

    def __init__(self, status: int, message: str,
                 headers: Optional[Dict[str, str]] = None):
        super().__init__(message)
        self.status = status
        #: extra response headers (e.g. ``Retry-After`` on a shed 429/503)
        self.headers = headers
        self.message = message


# accepted inbound X-Request-Id shape: anything else is replaced with a
# minted id (a request id lands in logs, traces, and response headers —
# it must never be a header-injection or log-forgery vector)
_REQUEST_ID_RE = re.compile(r"^[A-Za-z0-9._-]{1,64}$")


def request_id(incoming: Optional[str] = None) -> str:
    """The request id for one inbound request: the client's
    ``X-Request-Id`` when it is well-formed (``[A-Za-z0-9._-]{1,64}`` —
    propagation across hops), else a freshly minted 16-hex-char id.
    Pure sanitize-or-mint; the caller owns echoing it on the reply."""
    if incoming and _REQUEST_ID_RE.match(incoming):
        return incoming
    return os.urandom(8).hex()


def sniff_method(head: bytes) -> Optional[str]:
    """HTTP method name if ``head`` (the first 4 bytes of a connection)
    starts an HTTP request line, else ``None`` (binary frame)."""
    return _METHOD_SNIFF.get(bytes(head[:4]))


def parse_head(raw: bytes) -> Tuple[str, str, str, Dict[str, str]]:
    """Parse a full request head (through ``CRLFCRLF``) into
    ``(method, path, query, headers)``.

    Header names are lower-cased; duplicate headers keep the LAST value
    (none of the headers these surfaces read are list-valued). Raises
    :class:`HttpError` 400 on a malformed request line or header.
    """
    head = raw.split(b"\r\n\r\n", 1)[0]
    lines = head.split(b"\r\n")
    parts = lines[0].decode("latin-1", "replace").split()
    if len(parts) != 3 or not parts[2].startswith("HTTP/"):
        raise HttpError(400, "malformed request line")
    method = parts[0].upper()
    target = parts[1]
    path, _, query = target.partition("?")
    headers: Dict[str, str] = {}
    for ln in lines[1:]:
        if not ln:
            continue
        name, sep, value = ln.partition(b":")
        if not sep or not name.strip():
            raise HttpError(400, "malformed header line")
        headers[name.strip().decode("latin-1", "replace").lower()] = \
            value.strip().decode("latin-1", "replace")
    return method, path, query, headers


def body_length(method: str, headers: Dict[str, str],
                max_body: int) -> int:
    """Validated request-body length for a parsed head.

    Enforces the mini-server body discipline: bodies require an explicit
    ``Content-Length`` (411 when a body-bearing method omits it, since
    neither surface implements chunked framing), bounded by ``max_body``
    (413). GET/HEAD/DELETE with no ``Content-Length`` return 0.
    """
    raw = headers.get("content-length")
    if raw is None:
        if method in ("POST", "PUT", "PATCH"):
            raise HttpError(411, "Content-Length required")
        return 0
    try:
        n = int(raw)
    except ValueError:
        raise HttpError(400, f"bad Content-Length {raw!r}")
    if n < 0:
        raise HttpError(400, f"bad Content-Length {raw!r}")
    if n > max_body:
        raise HttpError(413,
                        f"body of {n} bytes exceeds limit {max_body}")
    return n


def render(status: int, body: bytes, ctype: str = "text/plain",
           *, keep_alive: bool = False,
           extra_headers: Optional[Dict[str, str]] = None) -> bytes:
    """Render one complete HTTP/1.1 response.

    Always carries ``Content-Length`` (so clients can detect a torn
    write — a killed server can never produce a short body that still
    parses as success) and an explicit ``Connection`` header.
    """
    reason = REASONS.get(status, "Unknown")
    head = [f"HTTP/1.1 {status} {reason}",
            f"Content-Type: {ctype}",
            f"Content-Length: {len(body)}",
            f"Connection: {'keep-alive' if keep_alive else 'close'}"]
    for name, value in (extra_headers or {}).items():
        head.append(f"{name}: {value}")
    return ("\r\n".join(head) + "\r\n\r\n").encode("latin-1") + body


def render_error(err: HttpError, *, keep_alive: bool = False) -> bytes:
    """Render an :class:`HttpError` as a structured JSON error response."""
    # hand-rolled JSON keeps this module stdlib-free of imports the
    # tracker hot path doesn't already pay for; messages are ASCII
    msg = err.message.replace("\\", "\\\\").replace('"', '\\"')
    body = ('{"error": "%s", "status": %d}\n' % (msg, err.status)).encode()
    return render(err.status, body, "application/json",
                  keep_alive=keep_alive, extra_headers=err.headers)
