"""Worker-side rendezvous client.

The reference ships only the tracker half (the worker half lives in the
separate Rabit C++ library). This client implements the worker side of the
same wire protocol so that (a) the tracker is testable in-process with N
fake workers — the single-process multi-"host" simulation strategy the
reference applies to InputSplit (SURVEY §4) — and (b) Python workers can
join a legacy Rabit rendezvous without the C++ library.

Liveness (doc/robustness.md "Distributed job liveness"): when the tracker
exports DMLC_TRACKER_HEARTBEAT_MS (or ``start(heartbeat=True)``), the
client opens a persistent heartbeat channel after learning its rank. The
HeartbeatMonitor pings on the announced interval and listens for the
tracker's abort broadcast; on abort it slams every guarded socket so a
worker blocked in a peer link raises TrackerAbortedError within the
deadline instead of hanging forever. Every client-side socket op also
carries a timeout — a hung tracker or a peer that never dials fails the
worker within DMLC_TRACKER_CLIENT_TIMEOUT seconds, not never.
"""

from __future__ import annotations

import json
import os
import queue
import socket
import struct
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from dmlc_core_tpu import telemetry
from dmlc_core_tpu.tracker.wire import (CMD_HEARTBEAT, HEARTBEAT_ABORT,
                                        HEARTBEAT_BYE, HEARTBEAT_PING,
                                        LEASE_ACQUIRE, LEASE_COMPLETE,
                                        LEASE_DRAINED, LEASE_EMPTY,
                                        LEASE_GRANT, LEASE_RELEASE, MAGIC,
                                        TELEMETRY_PULL, TELEMETRY_PUSH,
                                        TrackerAbortedError, WireSocket,
                                        env_float, env_int)


def _default_timeout() -> float:
    """Deadline for every client-side blocking socket op (seconds).
    `0` disables the deadline (the PR 2 convention) — returned as inf,
    which `_sock_timeout` maps back to blocking mode."""
    t = env_float("DMLC_TRACKER_CLIENT_TIMEOUT", 300.0)
    return float("inf") if t <= 0 else t


def _sock_timeout(timeout: float):
    """A socket-API timeout for our deadline value: None (block forever)
    when the deadline is disabled — settimeout(0) would mean NON-BLOCKING
    and fail every op instantly."""
    return None if timeout == float("inf") else timeout


def _default_jobid() -> str:
    """The reference tracker's jobid convention: workers report their
    launcher task id on the wire (tracker.py job_map), which (a) lets a
    restarted task reclaim its old rank and (b) lets the tracker tell
    the supervisor WHICH task a dead rank belongs to — ranks are
    assigned by host-sorted arrival, so rank != DMLC_TASK_ID in
    general."""
    task = os.environ.get("DMLC_TASK_ID")
    return f"task{task}" if task else "NULL"


# the process's active HeartbeatMonitor — the lease endpoint the elastic
# data layer (data.RowBlockIter.create with DMLC_ELASTIC_SHARDS=1) resolves
# without threading the monitor through every constructor
_active_monitor: Optional["HeartbeatMonitor"] = None


def current_monitor() -> Optional["HeartbeatMonitor"]:
    """The HeartbeatMonitor of this process's most recent rendezvous (set
    by RendezvousClient when it opens the heartbeat channel, cleared on
    shutdown), or None. The elastic data layer uses it as the default
    lease source."""
    return _active_monitor


def _set_active_monitor(mon: Optional["HeartbeatMonitor"]) -> None:
    global _active_monitor
    _active_monitor = mon


@dataclass
class TopologyAssignment:
    rank: int
    parent: int
    world_size: int
    tree_neighbors: List[int]
    ring_prev: int
    ring_next: int
    # rank -> connected peer socket (tree + ring links)
    links: Dict[int, WireSocket] = field(default_factory=dict)


class HeartbeatMonitor:
    """The worker half of the liveness protocol: one daemon thread that
    pings the tracker on the announced interval and listens for the abort
    broadcast on the same channel.

    Blocking sockets registered with :meth:`guard` are closed when an
    abort lands, so a worker stuck in a peer accept()/recv() raises
    immediately; the caller then turns that OSError into the structured
    TrackerAbortedError via :meth:`check`.

    The elastic data-plane's lease RPCs (doc/robustness.md "Elastic
    data-plane") ride THIS channel — :meth:`acquire_lease` /
    :meth:`complete_lease` / :meth:`release_lease` frame onto the same
    socket (writes serialized against the ping thread), and renewal is
    implicit in every ping, so no second connection is ever opened per
    renewal."""

    def __init__(self, tracker_host: str, tracker_port: int, rank: int,
                 jobid: str = "NULL", timeout: Optional[float] = None):
        self.rank = rank
        self.aborted: Optional[str] = None
        self._closing = False
        self._lock = threading.Lock()
        self._guarded: List[socket.socket] = []
        # lease plumbing: sends interleave with pings under _send_lock;
        # LEASE_GRANT payloads parsed by the monitor thread land here
        self._send_lock = threading.Lock()
        self._grants: "queue.Queue[int]" = queue.Queue()
        self._lease_lock = threading.Lock()  # one in-flight acquire
        # epoch of the last LEASE_ACQUIRE sent: a grant that lands after
        # its ask timed out is an orphan — it must be RELEASED on drain,
        # or the tracker keeps it held (and every ping renews it) forever
        self._inflight_epoch: Optional[int] = None
        timeout = _default_timeout() if timeout is None else timeout
        self.timeout = timeout
        sock = socket.create_connection((tracker_host, tracker_port),
                                        timeout=_sock_timeout(timeout))
        sock.settimeout(_sock_timeout(timeout))
        ws = WireSocket(sock)
        try:
            ws.send_int(MAGIC)
            got = ws.recv_int()
            if got != MAGIC:
                raise ConnectionError(f"bad tracker magic {got:#x}")
            ws.send_int(rank)
            ws.send_int(-1)
            ws.send_str(jobid)
            ws.send_str(CMD_HEARTBEAT)
            interval_ms = ws.recv_int()
            if interval_ms <= 0:
                raise ConnectionError(
                    f"tracker announced invalid heartbeat interval "
                    f"{interval_ms} ms")
        except BaseException:
            # no thread owns the socket yet: a failed handshake (tracker
            # rejecting the rank, bad magic) must not leak the fd —
            # retry loops would accumulate one per attempt up to EMFILE
            ws.close()
            raise
        self.interval = interval_ms / 1000.0
        self._ws = ws
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"heartbeat-rank{rank}")
        self._thread.start()

    def guard(self, sock: socket.socket) -> None:
        """Close `sock` if the job aborts (unblocks whoever is blocked on
        it). Already-aborted monitors close it immediately."""
        with self._lock:
            if self.aborted is not None:
                try:
                    sock.close()
                except OSError:
                    pass
                return
            self._guarded.append(sock)

    def unguard(self, sock: socket.socket) -> None:
        """Stop tracking `sock` (it outlived the risky blocking phase)."""
        with self._lock:
            if sock in self._guarded:
                self._guarded.remove(sock)

    def check(self) -> None:
        """Raise TrackerAbortedError if the tracker aborted the job —
        call this when a guarded socket op fails, and periodically from
        long compute loops."""
        if self.aborted is not None:
            raise TrackerAbortedError(self.aborted)

    def wait(self, timeout: Optional[float] = None) -> Optional[str]:
        """Block until the job aborts (or `timeout` elapses); returns the
        abort reason or None. Also returns when the channel closes."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while self.aborted is None and self._thread.is_alive():
            step = 0.05
            if deadline is not None:
                left = deadline - time.monotonic()
                if left <= 0:
                    break
                step = min(step, left)
            self._thread.join(step)
        return self.aborted

    def close(self, graceful: bool = True) -> None:
        """Stop pinging and close the channel — never the abort path.

        `graceful=True` (normal job end) says BYE first, so the tracker
        disarms liveness for this rank instead of logging a lost
        channel. `graceful=False` (this worker is dying abnormally)
        closes abruptly: the tracker's dead-after clock MUST keep
        running so the failure is detected and the job aborted — a BYE
        here would silently untrack the dying rank and hang the job."""
        self._closing = True
        if graceful:
            try:
                # lock-ok: BYE serializes with pings; bounded by the
                # channel timeout, and this lock is worker-side only —
                # the tracker serve loop never waits on it
                with self._send_lock:
                    self._ws.send_int(HEARTBEAT_BYE)
            except OSError:
                pass
        try:
            self._ws.close()
        except OSError:
            pass
        self._thread.join(timeout=2)

    # -- elastic data-plane lease RPCs (same socket as the pings) ------------
    def _send_words(self, *vals: int) -> None:
        # lock-ok: serializing frame writes on the one socket IS this
        # lock's job; the send is bounded by the channel timeout and the
        # lock is worker-side only (never held by the tracker serve loop)
        with self._send_lock:
            self._ws.sock.sendall(struct.pack(f"@{len(vals)}i", *vals))

    def acquire_lease(self, epoch: int,
                      timeout: Optional[float] = None) -> Optional[int]:
        """Request one shard lease for `epoch` from the tracker.

        Returns the granted shard id, or None when the epoch is drained
        (every shard complete — end of epoch). While the pool is merely
        empty (held shards may return if their holder dies), the request
        is retried until `timeout` (default: the monitor's deadline)
        elapses, then TimeoutError. Raises TrackerAbortedError when the
        job aborts mid-wait."""
        deadline = time.monotonic() + \
            (self.timeout if timeout is None else timeout)
        acquire_us = telemetry.histogram("lease_acquire_us")
        # lock-ok: the guarded operation IS waiting for a grant — one
        # in-flight acquire per monitor is this lock's contract; every
        # wait is deadline-bounded and abortable, and the lock is
        # worker-side only (the tracker serve loop never takes it)
        with self._lease_lock:
            while True:
                self.check()
                while True:  # drain grants a timed-out earlier ask orphaned
                    try:
                        orphan = self._grants.get_nowait()
                    except queue.Empty:
                        break
                    if orphan >= 0 and self._inflight_epoch is not None:
                        # a real shard granted to an ask we gave up on:
                        # hand it straight back or it stays held by this
                        # (live, pinging, renewing) rank and the epoch
                        # can never drain. Acquires are serialized under
                        # _lease_lock, so the orphan belongs to the LAST
                        # sent ask's epoch; a mismatch is ignored
                        # tracker-side as stale.
                        self._send_words(LEASE_RELEASE,
                                         self._inflight_epoch, orphan)
                t0 = time.perf_counter()
                self._inflight_epoch = epoch
                self._send_words(LEASE_ACQUIRE, epoch)
                left = deadline - time.monotonic()
                if left <= 0:
                    raise TimeoutError(
                        f"rank {self.rank}: no lease grant within the "
                        f"deadline")
                try:
                    grant = self._grants.get(timeout=left)
                except queue.Empty:
                    self.check()
                    raise TimeoutError(
                        f"rank {self.rank}: tracker answered no lease "
                        f"request within the deadline")
                acquire_us.observe((time.perf_counter() - t0) * 1e6)
                if grant >= 0:
                    return grant
                if grant == LEASE_DRAINED:
                    return None
                # LEASE_EMPTY: nothing free NOW — a held shard may return
                # if its holder dies; poll again shortly
                self.check()
                if time.monotonic() >= deadline:
                    raise TimeoutError(
                        f"rank {self.rank}: lease pool stayed empty past "
                        f"the deadline")
                time.sleep(min(0.05, max(deadline - time.monotonic(), 0)))

    def complete_lease(self, epoch: int, shard: int) -> None:
        """Mark a fully-consumed shard done (the exactly-once checkout)."""
        self.check()
        self._send_words(LEASE_COMPLETE, epoch, shard)

    def release_lease(self, epoch: int, shard: int) -> None:
        """Return an unfinished shard to the pool (this worker is bailing
        out of it; another worker will pick it up)."""
        self.check()
        self._send_words(LEASE_RELEASE, epoch, shard)

    def _answer_telemetry_pull(self) -> None:
        """Ship this rank's telemetry snapshot back on the channel
        ([TELEMETRY_PUSH][len][json]; doc/observability.md "Cluster
        aggregation"). Best effort: a broken export must cost the tracker
        one timed-out pull, never this channel or this worker."""
        try:
            doc = telemetry.rank_export()
            payload = json.dumps(doc, separators=(",", ":")).encode()
            # lock-ok: one bounded frame write serialized against pings,
            # worker-side lock only (the tracker serve loop never waits
            # on it)
            with self._send_lock:
                self._ws.sock.sendall(
                    struct.pack("@ii", TELEMETRY_PUSH, len(payload)) +
                    payload)
        except OSError:
            raise  # channel-level failures follow the ping error path
        except Exception:
            pass  # a snapshot/serialization bug degrades the scrape only

    def _trip(self, reason: str) -> None:
        with self._lock:
            if self.aborted is None:
                self.aborted = reason
            guarded, self._guarded = self._guarded, []
        # flight recorder (doc/observability.md): the abort broadcast is
        # the worker's last chance to ship a postmortem — the span ring,
        # event ring, and metric snapshot land in $DMLC_TRACE_DUMP
        telemetry.flight_dump(f"abort: {reason}", rank=self.rank)
        # wake a lease waiter parked on the grant queue: its next loop
        # round turns the sentinel into the structured abort via check()
        self._grants.put(LEASE_EMPTY)
        for s in guarded:
            # shutdown() first: close() alone does NOT unblock a thread
            # already parked in accept()/recv() on this fd (Linux keeps
            # the syscall waiting on the orphaned descriptor); shutdown
            # forces those to return immediately
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass

    def _run(self) -> None:
        sock = self._ws.sock
        try:
            sock.settimeout(self.interval)
        except OSError:
            return
        # partial frames survive across interval timeouts: recv_all would
        # DROP bytes it already buffered when the ping clock fires, and a
        # tracker abort word split across TCP segments would desync the
        # channel forever — exactly when the abort matters most. The same
        # buffering covers the LEASE_GRANT payload word: a grant split
        # across segments parks in `buf` while pings keep flowing (lease
        # renewal must not stall on a slow grant).
        buf = b""
        grant_pending = False  # next word is a LEASE_GRANT payload
        while not self._closing:
            try:
                chunk = sock.recv(4 - len(buf))
                if not chunk:
                    if not self._closing:
                        self._trip("heartbeat channel to the tracker lost")
                    return
                buf += chunk
                if len(buf) < 4:
                    continue
                val = struct.unpack("@i", buf)[0]
                buf = b""
                if grant_pending:
                    grant_pending = False
                    self._grants.put(val)
                    continue
                if val == HEARTBEAT_ABORT:
                    sock.settimeout(5.0)
                    reason = self._ws.recv_str()
                    self._trip(reason)
                    return
                if val == LEASE_GRANT:
                    grant_pending = True
                    continue
                if val == TELEMETRY_PULL:
                    # the tracker's scrape surface is asking for this
                    # rank's snapshot (doc/observability.md "Cluster
                    # aggregation"); channel errors surface like a ping's
                    try:
                        self._answer_telemetry_pull()
                    except OSError:
                        if not self._closing:
                            self._trip(
                                "heartbeat channel to the tracker lost")
                        return
                    continue
                # any other tracker->worker frame is unexpected; ignore
            except socket.timeout:
                # the quiet interval elapsed: time to ping (which also
                # renews every lease this rank holds, tracker-side)
                try:
                    # lock-ok: ping serialized against lease frames;
                    # timeout-bounded, worker-side lock only
                    with self._send_lock:
                        self._ws.send_int(HEARTBEAT_PING)
                except OSError:
                    if not self._closing:
                        self._trip("heartbeat channel to the tracker lost")
                    return
            except (OSError, ConnectionError):
                if not self._closing:
                    self._trip("heartbeat channel to the tracker lost")
                return


class RendezvousClient:
    """Speaks the tracker protocol end-to-end, including peer-link setup."""

    def __init__(self, tracker_host: str, tracker_port: int,
                 jobid: Optional[str] = None,
                 timeout: Optional[float] = None):
        self.tracker_host = tracker_host
        self.tracker_port = tracker_port
        # default jobid = the launcher's DMLC_TASK_ID (reference
        # convention): reclaims the old rank on restart and maps a dead
        # rank back to its supervised task
        self.jobid = _default_jobid() if jobid is None else jobid
        self.timeout = _default_timeout() if timeout is None else timeout
        self.heartbeat: Optional[HeartbeatMonitor] = None

    def _dial_tracker(self, cmd: str, rank: int = -1,
                      world_size: int = -1) -> WireSocket:
        sock = socket.create_connection(
            (self.tracker_host, self.tracker_port),
            timeout=_sock_timeout(self.timeout))
        # every subsequent op inherits the deadline: a tracker that
        # accepts and goes mute must fail this worker, not hang it
        sock.settimeout(_sock_timeout(self.timeout))
        ws = WireSocket(sock)
        ws.send_int(MAGIC)
        got = ws.recv_int()
        if got != MAGIC:
            # a real error, not an assert — `python -O` strips asserts and
            # would let a protocol mismatch continue on garbage
            ws.close()
            raise ConnectionError(f"bad tracker magic {got:#x}")
        ws.send_int(rank)
        ws.send_int(world_size)
        ws.send_str(self.jobid)
        ws.send_str(cmd)
        return ws

    def log(self, message: str) -> None:
        """Route a message through the tracker log (cmd=print,
        reference tracker.py:269-272)."""
        ws = self._dial_tracker("print")
        ws.send_str(message)
        ws.close()

    def shutdown(self, rank: int) -> None:
        """Send the shutdown handshake and close the tracker connection."""
        if self.heartbeat is not None:
            # stop the monitor first so the tracker-side channel EOF is
            # unambiguous teardown, never a liveness trip mid-shutdown
            self.heartbeat.close()
            if current_monitor() is self.heartbeat:
                _set_active_monitor(None)
            self.heartbeat = None
        ws = self._dial_tracker("shutdown", rank=rank)
        ws.close()

    def _maybe_start_heartbeat(self, rank: int,
                               heartbeat: Optional[bool]) -> None:
        if heartbeat is None:
            heartbeat = env_int("DMLC_TRACKER_HEARTBEAT_MS", 0) > 0
        if not heartbeat:
            return
        if self.heartbeat is not None:
            self.heartbeat.close()
        self.heartbeat = HeartbeatMonitor(
            self.tracker_host, self.tracker_port, rank, jobid=self.jobid,
            timeout=self.timeout)
        _set_active_monitor(self.heartbeat)

    def start(self, rank: int = -1, world_size: int = -1,
              recover: bool = False,
              heartbeat: Optional[bool] = None) -> TopologyAssignment:
        """Join the rendezvous: receive topology, open the heartbeat
        channel (env-gated, see module docstring), establish peer links.

        Raises TrackerAbortedError when the tracker aborts the job while
        this worker is mid-link, and ConnectionError/OSError within
        `timeout` when the tracker or a peer hangs."""
        ws = self._dial_tracker("recover" if recover else "start",
                                rank=rank, world_size=world_size)
        my_rank = ws.recv_int()
        parent = ws.recv_int()
        world = ws.recv_int()
        num_tree = ws.recv_int()
        tree_neighbors = [ws.recv_int() for _ in range(num_tree)]
        rprev = ws.recv_int()
        rnext = ws.recv_int()
        assign = TopologyAssignment(my_rank, parent, world, tree_neighbors,
                                    rprev, rnext)
        expected = set(tree_neighbors)
        if rprev != -1:
            expected.add(rprev)
        if rnext != -1:
            expected.add(rnext)

        # rank is known: liveness starts BEFORE the link dance, so a hang
        # anywhere below is abortable by the tracker's broadcast
        self._maybe_start_heartbeat(my_rank, heartbeat)
        monitor = self.heartbeat
        if monitor is not None:
            monitor.guard(ws.sock)

        # listen for peers that will dial us
        listener = socket.socket()
        listener.bind(("", 0))  # all interfaces: peers dial our tracker-seen IP
        listener.listen(16)
        if monitor is not None:
            monitor.guard(listener)

        good: Dict[int, WireSocket] = {}
        # one deadline spans the whole link dance: a peer that never
        # dials must fail this worker in bounded time, not hang the
        # previously-untimed accept loop
        deadline = time.monotonic() + self.timeout
        try:
            links = self._link_dance(
                ws, assign, expected, good, listener, monitor, deadline)
        except BaseException:
            # a failed rendezvous must not leave a zombie: stop
            # heartbeating (a never-linked worker reporting "alive"
            # forever would defeat the dead-rank deadline) and close the
            # half-built peer links and the dance socket
            for ps in good.values():
                try:
                    ps.close()
                except OSError:
                    pass
            try:
                ws.close()
            except OSError:
                pass
            if monitor is not None:
                # abrupt, NOT graceful: this worker failed rendezvous
                # and is about to die — the tracker's dead-after clock
                # must keep running so the job aborts instead of
                # waiting forever on a rank that never linked
                monitor.close(graceful=False)
                if current_monitor() is monitor:
                    _set_active_monitor(None)
                self.heartbeat = None
            raise
        # the rendezvous deadline must not outlive the rendezvous: a
        # healthy peer may legitimately stay quiet longer than the dance
        # timeout during compute — links block indefinitely (the abort
        # broadcast, not a timer, is what unblocks them on failure)
        for ps in links.values():
            try:
                ps.sock.settimeout(None)
            except OSError:
                pass
        assign.links = links
        if monitor is not None:
            monitor.unguard(ws.sock)
        ws.close()
        return assign

    def _link_dance(self, ws, assign, expected, good, listener, monitor,
                    deadline) -> Dict[int, WireSocket]:
        """The dial/accept rounds of the rendezvous (split from start()
        so its failure cleanup is one place)."""
        try:
            while True:
                # the dial rounds honor the same dance deadline as the
                # accept loop: a peer advertising a blackholed address
                # must not keep the worker in retry rounds forever
                if monitor is not None:
                    monitor.check()
                if time.monotonic() > deadline:
                    raise ConnectionError(
                        f"rank {assign.rank}: peer links not established "
                        f"within {self.timeout:.0f}s")
                ws.send_int(len(good))
                for r in good:
                    ws.send_int(r)
                num_dial = ws.recv_int()
                num_wait = ws.recv_int()
                errors = 0
                for _ in range(num_dial):
                    host = ws.recv_str()
                    port = ws.recv_int()
                    peer_rank = ws.recv_int()
                    try:
                        ps = WireSocket(socket.create_connection(
                            (host, port), timeout=10))
                        ps.send_int(assign.rank)  # identify ourselves
                        good[peer_rank] = ps
                        if monitor is not None:
                            monitor.guard(ps.sock)
                    except OSError:
                        errors += 1
                ws.send_int(errors)
                if errors:
                    continue
                ws.send_int(listener.getsockname()[1])  # our accept port
                break

            # accept the peers the tracker told to dial us. The accept
            # timeout is SHORT and looped: old kernels do not wake a
            # blocked accept() even on shutdown()/close() of the listener
            # fd (verified on 4.4 — only connected sockets wake), so the
            # abort broadcast is observed between attempts instead
            for _ in range(num_wait):
                while True:
                    if monitor is not None:
                        monitor.check()  # abort -> structured error
                    left = deadline - time.monotonic()
                    if left <= 0:
                        raise ConnectionError(
                            f"rank {assign.rank}: peers never dialed "
                            f"within {self.timeout:.0f}s")
                    listener.settimeout(min(0.1, left))
                    try:
                        fd, _ = listener.accept()
                        break
                    except socket.timeout:
                        continue
                fd.settimeout(_sock_timeout(self.timeout))
                ps = WireSocket(fd)
                peer_rank = ps.recv_int()
                good[peer_rank] = ps
                if monitor is not None:
                    monitor.guard(fd)
        except (OSError, ConnectionError):
            if monitor is not None:
                monitor.check()  # abort broadcast -> structured error
            raise
        finally:
            if monitor is not None:
                monitor.unguard(listener)
            listener.close()

        if set(good) != expected:
            raise ConnectionError(
                f"rank {assign.rank}: linked peers {sorted(good)} != "
                f"assigned {sorted(expected)}")
        return good
