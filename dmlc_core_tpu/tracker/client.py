"""Worker-side rendezvous client.

The reference ships only the tracker half (the worker half lives in the
separate Rabit C++ library). This client implements the worker side of the
same wire protocol so that (a) the tracker is testable in-process with N
fake workers — the single-process multi-"host" simulation strategy the
reference applies to InputSplit (SURVEY §4) — and (b) Python workers can
join a legacy Rabit rendezvous without the C++ library.
"""

from __future__ import annotations

import socket
from dataclasses import dataclass, field
from typing import Dict, List

from dmlc_core_tpu.tracker.wire import MAGIC, WireSocket


@dataclass
class TopologyAssignment:
    rank: int
    parent: int
    world_size: int
    tree_neighbors: List[int]
    ring_prev: int
    ring_next: int
    # rank -> connected peer socket (tree + ring links)
    links: Dict[int, WireSocket] = field(default_factory=dict)


class RendezvousClient:
    """Speaks the tracker protocol end-to-end, including peer-link setup."""

    def __init__(self, tracker_host: str, tracker_port: int,
                 jobid: str = "NULL"):
        self.tracker_host = tracker_host
        self.tracker_port = tracker_port
        self.jobid = jobid

    def _dial_tracker(self, cmd: str, rank: int = -1,
                      world_size: int = -1) -> WireSocket:
        sock = socket.create_connection(
            (self.tracker_host, self.tracker_port))
        ws = WireSocket(sock)
        ws.send_int(MAGIC)
        got = ws.recv_int()
        assert got == MAGIC, f"bad tracker magic {got:#x}"
        ws.send_int(rank)
        ws.send_int(world_size)
        ws.send_str(self.jobid)
        ws.send_str(cmd)
        return ws

    def log(self, message: str) -> None:
        """Route a message through the tracker log (cmd=print,
        reference tracker.py:269-272)."""
        ws = self._dial_tracker("print")
        ws.send_str(message)
        ws.close()

    def shutdown(self, rank: int) -> None:
        """Send the shutdown handshake and close the tracker connection."""
        ws = self._dial_tracker("shutdown", rank=rank)
        ws.close()

    def start(self, rank: int = -1, world_size: int = -1,
              recover: bool = False) -> TopologyAssignment:
        """Join the rendezvous: receive topology, establish peer links."""
        ws = self._dial_tracker("recover" if recover else "start",
                                rank=rank, world_size=world_size)
        my_rank = ws.recv_int()
        parent = ws.recv_int()
        world = ws.recv_int()
        num_tree = ws.recv_int()
        tree_neighbors = [ws.recv_int() for _ in range(num_tree)]
        rprev = ws.recv_int()
        rnext = ws.recv_int()
        assign = TopologyAssignment(my_rank, parent, world, tree_neighbors,
                                    rprev, rnext)
        expected = set(tree_neighbors)
        if rprev != -1:
            expected.add(rprev)
        if rnext != -1:
            expected.add(rnext)

        # listen for peers that will dial us
        listener = socket.socket()
        listener.bind(("", 0))  # all interfaces: peers dial our tracker-seen IP
        listener.listen(16)
        my_port = listener.getsockname()[1]

        good: Dict[int, WireSocket] = {}
        while True:
            ws.send_int(len(good))
            for r in good:
                ws.send_int(r)
            num_dial = ws.recv_int()
            num_wait = ws.recv_int()
            errors = 0
            for _ in range(num_dial):
                host = ws.recv_str()
                port = ws.recv_int()
                peer_rank = ws.recv_int()
                try:
                    ps = WireSocket(socket.create_connection((host, port),
                                                             timeout=10))
                    ps.send_int(assign.rank)  # identify ourselves
                    good[peer_rank] = ps
                except OSError:
                    errors += 1
            ws.send_int(errors)
            if errors:
                continue
            ws.send_int(my_port)
            break

        # accept the peers the tracker told to dial us
        for _ in range(num_wait):
            fd, _ = listener.accept()
            ps = WireSocket(fd)
            peer_rank = ps.recv_int()
            good[peer_rank] = ps
        listener.close()
        assert set(good) == expected, (set(good), expected)
        assign.links = good
        ws.close()
        return assign
