"""Allreduce topology computation: binomial tree + tree-sharing ring.

Replicates the reference's topology contract (tracker/dmlc_tracker/
tracker.py:164-252): a binary-heap tree over ranks (parent/children) for
reduce/broadcast, a DFS-derived ring that shares tree edges for bandwidth
recovery, and a relabeling so ring order is 0..n-1 (neighbors differ by 1 mod
n). On TPU this math is only needed for *legacy Rabit consumers* — JAX/XLA
collectives route over ICI in hardware and need no tracker-computed topology
(SURVEY §2.5) — but the tracker keeps serving it so existing workers run
unchanged.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

TreeMap = Dict[int, List[int]]
ParentMap = Dict[int, int]
RingMap = Dict[int, Tuple[int, int]]


def heap_neighbors(rank: int, n: int) -> List[int]:
    """Neighbors of `rank` in the 1-indexed binary heap over n ranks."""
    h = rank + 1
    out = []
    if h > 1:
        out.append(h // 2 - 1)
    if h * 2 - 1 < n:
        out.append(h * 2 - 1)
    if h * 2 < n:
        out.append(h * 2)
    return out


def build_tree(n: int) -> Tuple[TreeMap, ParentMap]:
    """Binomial reduction tree over `n` workers; returns (tree_map,
    parent_map) (reference tracker.py)."""
    tree: TreeMap = {}
    parent: ParentMap = {}
    for r in range(n):
        tree[r] = heap_neighbors(r, n)
        parent[r] = (r + 1) // 2 - 1
    return tree, parent


def _dfs_ring(tree: TreeMap, parent: ParentMap, r: int) -> List[int]:
    """DFS order visiting children, reversing the last subtree so the walk
    returns adjacent to the start (the reference's find_share_ring)."""
    children = [v for v in tree[r] if v != parent[r]]
    if not children:
        return [r]
    out = [r]
    for i, v in enumerate(children):
        sub = _dfs_ring(tree, parent, v)
        if i == len(children) - 1:
            sub.reverse()
        out += sub
    return out


def build_ring(tree: TreeMap, parent: ParentMap) -> RingMap:
    """Ring order over `n` workers rooted at `r` (reference tracker.py ring
    construction)."""
    order = _dfs_ring(tree, parent, 0)
    if len(order) != len(tree):
        # a real error, not an assert: `python -O` strips asserts, and a
        # malformed tree map must fail the rendezvous loudly
        raise RuntimeError(
            f"ring order covers {len(order)} of {len(tree)} workers")
    n = len(tree)
    ring: RingMap = {}
    for i in range(n):
        ring[order[i]] = (order[(i - 1) % n], order[(i + 1) % n])
    return ring


def build_link_maps(n: int) -> Tuple[TreeMap, ParentMap, RingMap]:
    """Tree/parent/ring maps relabeled so ring order is the identity
    (reference get_link_map): rank r's ring neighbors are r±1 mod n."""
    tree, parent = build_tree(n)
    ring = build_ring(tree, parent)
    relabel = {0: 0}
    cur = 0
    for i in range(n - 1):
        cur = ring[cur][1]
        relabel[cur] = i + 1
    tree2: TreeMap = {relabel[k]: sorted(relabel[x] for x in v)
                      for k, v in tree.items()}
    parent2: ParentMap = {relabel[k]: (relabel[v] if k != 0 else -1)
                          for k, v in parent.items()}
    ring2: RingMap = {relabel[k]: (relabel[v[0]], relabel[v[1]])
                      for k, v in ring.items()}
    return tree2, parent2, ring2
