"""Argument parsing for dmlc-submit (reference tracker/dmlc_tracker/opts.py).

Cluster choices mirror opts.py:71-143 with `tpu-pod` added; the
DMLC_SUBMIT_CLUSTER env default is preserved (opts.py:170-176).
"""

from __future__ import annotations

import argparse
import os
from typing import List, Optional


def build_parser() -> argparse.ArgumentParser:
    """The dmlc-submit argparse parser (reference dmlc_tracker/opts.py
    surface)."""
    p = argparse.ArgumentParser(
        prog="dmlc-submit",
        description="Submit a distributed dmlc_core_tpu job")
    default_cluster = os.getenv("DMLC_SUBMIT_CLUSTER")
    p.add_argument("--cluster", default=default_cluster,
                   choices=["local", "ssh", "mpi", "sge", "slurm", "tpu-pod",
                            "kubernetes", "yarn", "mesos"],
                   help="cluster backend (env default DMLC_SUBMIT_CLUSTER)")
    p.add_argument("--num-workers", required=True, type=int,
                   help="number of worker processes")
    p.add_argument("--num-servers", default=0, type=int,
                   help="number of parameter-server processes")
    p.add_argument("--host-ip", default=None, type=str,
                   help="tracker host IP override")
    p.add_argument("--host-file", default=None, type=str,
                   help="host list for ssh/mpi/tpu-pod backends")
    p.add_argument("--jobname", default=None, type=str)
    p.add_argument("--queue", default="default", type=str,
                   help="sge queue")
    p.add_argument("--vcores", default=1, type=int,
                   help="cores requested per task (sge)")
    p.add_argument("--log-dir", default="dmlc_logs", type=str)
    p.add_argument("--log-level", default="INFO",
                   choices=["INFO", "DEBUG"])
    p.add_argument("--sync-dst-dir", default=None, type=str,
                   help="remote working dir (ssh/tpu-pod rsync target)")
    p.add_argument("--num-attempt", default=0, type=int,
                   help="retry attempts per worker (local backend)")
    p.add_argument("--heartbeat-ms", default=None, type=int,
                   help="enable worker liveness: heartbeat interval in ms "
                        "(exported as DMLC_TRACKER_HEARTBEAT_MS; 0 keeps "
                        "the legacy wait-forever tracker)")
    p.add_argument("--dead-after-ms", default=None, type=int,
                   help="mark a rank dead after this many ms without a "
                        "heartbeat (DMLC_TRACKER_DEAD_AFTER_MS; default "
                        "4x --heartbeat-ms)")
    p.add_argument("--recover-grace-ms", default=None, type=int,
                   help="grace window for cmd=recover after a rank is "
                        "marked dead before the job aborts "
                        "(DMLC_TRACKER_RECOVER_GRACE_MS; default half of "
                        "--dead-after-ms)")
    p.add_argument("--num-shards", default=None, type=int,
                   help="enable the elastic data-plane: pre-split the "
                        "dataset into this many logical shard leases "
                        "(exported as DMLC_TRACKER_NUM_SHARDS + "
                        "DMLC_ELASTIC_SHARDS=1; pick S >> --num-workers; "
                        "unset keeps the static num_parts/part_index "
                        "contract)")
    p.add_argument("--lease-ttl-ms", default=None, type=int,
                   help="shard-lease time-to-live without a renewal "
                        "(DMLC_TRACKER_LEASE_TTL_MS; renewal piggybacks "
                        "on every heartbeat; default --dead-after-ms + "
                        "--recover-grace-ms)")
    p.add_argument("--mesh", action="store_true",
                   help="elastic-mesh world (local backend): workers get a "
                        "DMLC_COORDINATOR_ADDRESS for "
                        "jax.distributed.initialize, any rank death aborts "
                        "the world (no single-rank relaunch into a live "
                        "mesh), and the whole world is relaunched — fresh "
                        "tracker + coordinator ports — resuming from the "
                        "last committed job checkpoint")
    p.add_argument("--world-attempts", default=None, type=int,
                   help="whole-world relaunches after a mesh abort "
                        "(DMLC_TRACKER_WORLD_ATTEMPTS; default 2 with "
                        "--mesh, 0 otherwise)")
    p.add_argument("--archives", default=[], action="append",
                   help="archive (.zip/.tar*) the in-container bootstrap "
                        "unpacks before exec (reference opts.py archives); "
                        "repeatable")
    p.add_argument("--slurm-worker-nodes", default=None, type=int)
    p.add_argument("--slurm-server-nodes", default=None, type=int)
    p.add_argument("--worker-memory-mb", default=1024, type=int,
                   help="memory request per worker (yarn/mesos/kubernetes)")
    p.add_argument("--worker-cores", default=1, type=int,
                   help="cpu request per worker (yarn/mesos/kubernetes)")
    p.add_argument("--server-memory-mb", default=1024, type=int,
                   help="memory request per server (yarn/mesos/kubernetes)")
    p.add_argument("--server-cores", default=1, type=int,
                   help="cpu request per server (yarn/mesos/kubernetes)")
    p.add_argument("--kube-namespace", default="default", type=str,
                   help="kubernetes namespace for the job resources")
    p.add_argument("--kube-worker-image", default="dmlc/base", type=str,
                   help="container image for kubernetes workers")
    p.add_argument("--kube-server-image", default="dmlc/base", type=str,
                   help="container image for kubernetes servers")
    p.add_argument("--kube-tpu-type", default=None, type=str,
                   help="TPU accelerator selector (e.g. tpu-v5-lite-podslice);"
                        " adds google.com/tpu resources + nodeSelector")
    p.add_argument("--kube-tpu-topology", default=None, type=str,
                   help="TPU slice topology (e.g. 2x4) for the nodeSelector")
    p.add_argument("--kube-tpu-chips", default=None, type=int,
                   help="google.com/tpu chips per pod (defaults to the chip "
                        "count implied by --kube-tpu-topology, e.g. 2x4 -> 8)")
    p.add_argument("--kube-dry-run", action="store_true",
                   help="print the generated manifests instead of kubectl "
                        "apply")
    p.add_argument("--mesos-master", default=None, type=str,
                   help="mesos master address host:port")
    p.add_argument("--coordinator-port", default=8476, type=int,
                   help="JAX coordination service port (tpu-pod)")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="command to run on every worker")
    return p


def get_opts(argv: Optional[List[str]] = None) -> argparse.Namespace:
    """Parse dmlc-submit arguments; `--` splits launcher args from the user
    command."""
    args = build_parser().parse_args(argv)
    if args.cluster is None:
        raise SystemExit(
            "--cluster is required (or set DMLC_SUBMIT_CLUSTER)")
    if not args.command:
        raise SystemExit("no command given")
    while args.command and args.command[0] == "--":
        args.command = args.command[1:]
    return args
