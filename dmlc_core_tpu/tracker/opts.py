"""Argument parsing for dmlc-submit (reference tracker/dmlc_tracker/opts.py).

Cluster choices mirror opts.py:71-143 with `tpu-pod` added; the
DMLC_SUBMIT_CLUSTER env default is preserved (opts.py:170-176).
"""

from __future__ import annotations

import argparse
import os
from typing import List, Optional


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dmlc-submit",
        description="Submit a distributed dmlc_core_tpu job")
    default_cluster = os.getenv("DMLC_SUBMIT_CLUSTER")
    p.add_argument("--cluster", default=default_cluster,
                   choices=["local", "ssh", "mpi", "sge", "slurm", "tpu-pod"],
                   help="cluster backend (env default DMLC_SUBMIT_CLUSTER)")
    p.add_argument("--num-workers", required=True, type=int,
                   help="number of worker processes")
    p.add_argument("--num-servers", default=0, type=int,
                   help="number of parameter-server processes")
    p.add_argument("--host-ip", default=None, type=str,
                   help="tracker host IP override")
    p.add_argument("--host-file", default=None, type=str,
                   help="host list for ssh/mpi/tpu-pod backends")
    p.add_argument("--jobname", default=None, type=str)
    p.add_argument("--queue", default="default", type=str,
                   help="sge queue")
    p.add_argument("--vcores", default=1, type=int,
                   help="cores requested per task (sge)")
    p.add_argument("--log-dir", default="dmlc_logs", type=str)
    p.add_argument("--log-level", default="INFO",
                   choices=["INFO", "DEBUG"])
    p.add_argument("--sync-dst-dir", default=None, type=str,
                   help="remote working dir (ssh/tpu-pod rsync target)")
    p.add_argument("--num-attempt", default=0, type=int,
                   help="retry attempts per worker (local backend)")
    p.add_argument("--slurm-worker-nodes", default=None, type=int)
    p.add_argument("--slurm-server-nodes", default=None, type=int)
    p.add_argument("--coordinator-port", default=8476, type=int,
                   help="JAX coordination service port (tpu-pod)")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="command to run on every worker")
    return p


def get_opts(argv: Optional[List[str]] = None) -> argparse.Namespace:
    args = build_parser().parse_args(argv)
    if args.cluster is None:
        raise SystemExit(
            "--cluster is required (or set DMLC_SUBMIT_CLUSTER)")
    if not args.command:
        raise SystemExit("no command given")
    while args.command and args.command[0] == "--":
        args.command = args.command[1:]
    return args
